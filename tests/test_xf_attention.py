"""Fused MHA Pallas kernels (ops/xf_attention.py) vs the XLA oracle:
forward AND backward numerics, mask handling, and the encoder wiring
(VERDICT r3 item 4: use_pallas must actually reach the transformer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.ops.xf_attention import (fused_mha, mha_reference,
                                           _mha_fwd_pallas)


def _inputs(B=3, H=2, C=24, hd=16, dtype=jnp.float32, seed=0):
    r = np.random.default_rng(seed)
    q = jnp.asarray(r.normal(size=(B, H, C, hd)), dtype)
    k = jnp.asarray(r.normal(size=(B, H, C, hd)), dtype)
    v = jnp.asarray(r.normal(size=(B, H, C, hd)), dtype)
    mask = (r.random((B, C)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0  # at least one live key per row
    log_mask = jnp.asarray(np.log(np.maximum(mask, 1e-30)), jnp.float32)
    return q, k, v, log_mask


def test_fused_mha_forward_matches_reference():
    q, k, v, log_mask = _inputs()
    out = fused_mha(q, k, v, log_mask)
    ref = mha_reference(q, k, v, log_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_fused_mha_forward_bf16():
    q, k, v, log_mask = _inputs(dtype=jnp.bfloat16)
    out = fused_mha(q, k, v, log_mask)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(q, k, v, log_mask)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2)


def test_fused_mha_masked_keys_get_zero_weight():
    """Fully-masking all but key 0 must reduce to broadcasting v[:, :, 0]."""
    q, k, v, _ = _inputs(C=8)
    mask = np.zeros((q.shape[0], 8), np.float32)
    mask[:, 0] = 1.0
    log_mask = jnp.asarray(np.log(np.maximum(mask, 1e-30)), jnp.float32)
    out = fused_mha(q, k, v, log_mask)
    expect = jnp.broadcast_to(v[:, :, :1], v.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               atol=1e-5)


def test_fused_mha_backward_matches_reference():
    q, k, v, log_mask = _inputs(C=16)

    def loss_fused(q, k, v):
        return jnp.sum(jnp.square(fused_mha(q, k, v, log_mask)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(mha_reference(q, k, v, log_mask)))

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_fused, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_fused_mha_grouped_backward_matches_reference():
    """B=16 -> G=8: the grouped (batch-blocked) kernels' g-indexed
    unroll must match the reference in BOTH directions — the other
    backward test runs at G=1, which would miss a g-indexing bug in
    the unroll. Slightly looser tolerance: the grouped unroll changes
    f32 accumulation order marginally (measured ~1.4e-4 max delta)."""
    q, k, v, log_mask = _inputs(B=16, H=2, C=24, hd=16, seed=3)
    out = fused_mha(q, k, v, log_mask)
    ref = mha_reference(q, k, v, log_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    def loss_fused(q, k, v):
        return jnp.sum(jnp.square(fused_mha(q, k, v, log_mask)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(mha_reference(q, k, v, log_mask)))

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_fused, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch at G=8")


def test_fused_mha_odd_shapes():
    """C=200 / hd=96 — the real java-large transformer block shape
    (not lane-aligned; mosaic must pad internally)."""
    q, k, v, log_mask = _inputs(B=2, H=2, C=200, hd=96)
    out = fused_mha(q, k, v, log_mask)
    ref = mha_reference(q, k, v, log_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_encoder_pallas_path_matches_xla_path():
    """encode_transformer(use_pallas=True) must equal the XLA path —
    and actually take the kernel (spied)."""
    import code2vec_tpu.models.transformer_encoder as te
    from code2vec_tpu.models.encoder import ModelDims, init_params

    dims = ModelDims(token_vocab_size=64, path_vocab_size=48,
                     target_vocab_size=32, embeddings_size=16,
                     max_contexts=12, encoder_type="transformer",
                     xf_layers=2, xf_heads=2)
    params = init_params(jax.random.PRNGKey(0), dims)
    r = np.random.default_rng(1)
    B, C = 4, 12
    src = jnp.asarray(r.integers(0, 64, (B, C)), jnp.int32)
    pth = jnp.asarray(r.integers(0, 48, (B, C)), jnp.int32)
    dst = jnp.asarray(r.integers(0, 64, (B, C)), jnp.int32)
    mask = jnp.asarray((r.random((B, C)) > 0.2), jnp.float32)

    code_xla, attn_xla = te.encode_transformer(
        params, src, pth, dst, mask, dims=dims)
    code_pl, attn_pl = te.encode_transformer(
        params, src, pth, dst, mask, dims=dims, use_pallas=True)
    np.testing.assert_allclose(np.asarray(code_pl),
                               np.asarray(code_xla), atol=1e-4)
    np.testing.assert_allclose(np.asarray(attn_pl),
                               np.asarray(attn_xla), atol=1e-4)


def test_transformer_train_step_with_pallas_attention():
    """A full jitted train step through the fused kernels (fwd+bwd):
    loss finite, params move, and it matches the XLA-path step."""
    import optax

    from code2vec_tpu.models.encoder import ModelDims, init_params
    from code2vec_tpu.training.steps import make_train_step

    dims = ModelDims(token_vocab_size=64, path_vocab_size=48,
                     target_vocab_size=32, embeddings_size=16,
                     max_contexts=12, dropout_keep_rate=1.0,
                     encoder_type="transformer", xf_layers=1,
                     xf_heads=2)
    r = np.random.default_rng(2)
    B, C = 8, 12
    batch = (jnp.asarray(r.integers(0, 32, (B,)), jnp.int32),
             jnp.asarray(r.integers(0, 64, (B, C)), jnp.int32),
             jnp.asarray(r.integers(0, 48, (B, C)), jnp.int32),
             jnp.asarray(r.integers(0, 64, (B, C)), jnp.int32),
             jnp.ones((B, C), jnp.float32),
             jnp.ones((B,), jnp.float32))

    losses = {}
    moved = {}
    for use_pallas in (False, True):
        params = init_params(jax.random.PRNGKey(0), dims)
        qkv_before = np.asarray(params["xf"]["layers"][0]["qkv"]).copy()
        opt = optax.adam(1e-2)
        step = make_train_step(dims, opt, use_pallas=use_pallas)
        # the step donates params; qkv_before was snapshotted above
        p2, _s, loss = step(params, opt.init(params), batch,
                            jax.random.PRNGKey(1))
        losses[use_pallas] = float(loss)
        moved[use_pallas] = float(np.sum(np.abs(
            np.asarray(p2["xf"]["layers"][0]["qkv"]) - qkv_before)))
    assert np.isfinite(losses[True])
    assert moved[True] > 0
    assert losses[True] == pytest.approx(losses[False], abs=1e-4)
