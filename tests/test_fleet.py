"""Fleet plane (ISSUE 17): the cohort collector's policy layer under
fake clocks and injected fetch — offset estimation with asymmetric
round trips, restart re-handshake, straggler attribution, cross-host
divergence, summed throughput, the JSONL ring — plus the real-socket
seams (/clock commit -> manifest, /fleet 404 without a collector) and
the measured-offset trace merge that retires the clock_note caveat.

House rules under test: every policy case is sleep-free and
socket-free (clock/wall/fetch injectable); members are REAL memory
registries rendered through the REAL exposition renderer, so the
parse side exercises the same text a live member serves. The
2-process end-to-end (slow-marked, chaos-recipe style) drives the
acceptance path: an `infeed/produce` sleep fault on one member flips
the cohort_straggler ticket through the supervisor's alert engine,
a mid-train /fleet scrape shows the cohort, and the post-run
`trace_report --merge` aligns on COMMITTED offsets.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from code2vec_tpu import obs
from code2vec_tpu.obs.exposition import render_prometheus
from code2vec_tpu.obs.fleet import FleetCollector, fleet_alert_rules


# ---- fakes -----------------------------------------------------------

class FakeClock:
    """One mutable timebase standing in for the collector's monotonic
    AND wall clocks (tests only care about deltas and offsets)."""

    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    wall = __call__


class FakeCohort:
    """In-memory member endpoints behind an injectable fetch: real
    registries, the real /metrics renderer, fake member clocks, zero
    sockets. `legs` per member is a list of (request_s, response_s)
    pairs consumed by successive /clock reads — each leg advances the
    shared clock, so round-trip asymmetry is exact and deterministic."""

    def __init__(self, clock: FakeClock):
        self.clock = clock
        self.members = {}
        self.commits = []  # (endpoint, query) per commit round trip

    def add(self, endpoint, tele, *, run_id, process_index=0,
            offset_s=0.0, legs=None):
        self.members[endpoint] = {
            "tele": tele, "run_id": run_id,
            "process_index": process_index, "offset_s": offset_s,
            "legs": list(legs or [])}

    def fetch(self, url):
        endpoint, _, path = url.split("://", 1)[1].partition("/")
        m = self.members[endpoint]
        path, _, query = path.partition("?")
        if path == "clock":
            if "commit=1" in query:
                self.commits.append((endpoint, query))
                return json.dumps({"committed": True})
            a, b = (m["legs"].pop(0) if m["legs"] else (0.0, 0.0))
            self.clock.t += a  # request leg
            body = {"mono": 0.0,
                    "wall": self.clock.t + m["offset_s"],
                    "identity": {"run_id": m["run_id"],
                                 "process_index": m["process_index"]}}
            self.clock.t += b  # response leg
            return json.dumps(body)
        if path == "vars":
            return json.dumps({"identity": {
                "run_id": m["run_id"],
                "process_index": m["process_index"]}})
        if path == "metrics":
            return render_prometheus(m["tele"])
        raise ValueError(url)


def _collector(clock, cohort, endpoints, **kw):
    kw.setdefault("handshake_samples", 3)
    return FleetCollector(
        obs.Telemetry.memory("sup").make_threadsafe(),
        members=endpoints, clock=clock, wall=clock.wall,
        fetch=cohort.fetch, **kw)


def _member_tele(step_ms=None, **counts):
    t = obs.Telemetry.memory("member").make_threadsafe()
    for name, v in counts.items():
        t.count(f"train/{name}", v)
    if step_ms is not None:
        t.record_ms("train/step_ms", step_ms)
    return t


# ---- clock handshake -------------------------------------------------

def test_offset_median_survives_asymmetric_round_trips():
    """One slow request leg and one slow response leg bias their
    samples in OPPOSITE directions (+/- (a-b)/2); the median of three
    lands exactly on the true offset, where a mean would not."""
    clk = FakeClock()
    cohort = FakeCohort(clk)
    cohort.add("m0:1", _member_tele(steps=3), run_id="r1",
               offset_s=5.0,
               legs=[(0.001, 0.001), (0.010, 0.002), (0.002, 0.010)])
    fc = _collector(clk, cohort, ["m0:1"])
    agg = fc.sample()
    row = agg["hosts"][0]
    assert row["up"] and row["run_id"] == "r1"
    assert row["clock_offset_s"] == pytest.approx(5.0, abs=1e-12)
    assert row["clock_committed"] is True
    # the measurement went BACK to the member for manifest persistence
    assert len(cohort.commits) == 1
    ep, query = cohort.commits[0]
    assert ep == "m0:1"
    assert "offset_s=5.000000000" in query and "samples=3" in query


def test_restart_rehandshakes_and_resets_rates():
    """A changed run_id means a relaunched process: fresh clock
    measurement (a new process is a new clock relationship) and a
    rate-window reset, so counters restarting from zero never render
    as negative throughput."""
    clk = FakeClock()
    cohort = FakeCohort(clk)
    cohort.add("m0:1", _member_tele(steps=100, examples=3200),
               run_id="r1", offset_s=1.0)
    fc = _collector(clk, cohort, ["m0:1"])
    fc.sample()
    clk.t += 1.0
    fc.sample()  # second sweep: rates flow, no re-handshake
    assert len(cohort.commits) == 1
    assert fc.aggregate()["hosts"][0]["steps_s"] == pytest.approx(0.0)

    # relaunch: new run_id, counters back near zero, new clock skew
    cohort.members["m0:1"].update(
        tele=_member_tele(steps=2, examples=64), run_id="r2",
        offset_s=-3.0)
    clk.t += 1.0
    row = fc.sample()["hosts"][0]
    assert len(cohort.commits) == 2  # re-handshake committed
    assert row["run_id"] == "r2"
    assert row["clock_offset_s"] == pytest.approx(-3.0)
    # reset window: first post-restart sweep has no prior to rate from
    assert row["steps_s"] is None


def test_member_down_is_a_row_not_an_exception():
    def dead(_url):
        raise OSError("connection refused")

    clk = FakeClock()
    fc = FleetCollector(obs.Telemetry.memory("sup").make_threadsafe(),
                        members=["gone:9"], clock=clk, wall=clk.wall,
                        fetch=dead)
    agg = fc.sample()
    assert agg["hosts"][0] == {"endpoint": "gone:9", "up": False,
                               "error": "connection refused"}
    assert agg["cohort"]["hosts_up"] == 0
    assert agg["cohort"]["hosts_total"] == 1


# ---- straggler attribution ------------------------------------------

def test_straggler_score_attributes_worst_series():
    """Host 2 is 3x the cohort median on step_ms but 4x on the
    exposed-allreduce phase: the score takes the worst ratio and the
    attribution names the series — `phase_allreduce_exposed`, not a
    mystery step-time number."""
    clk = FakeClock()
    cohort = FakeCohort(clk)
    for i, (step, phase) in enumerate(((100.0, 10.0), (100.0, 10.0),
                                       (300.0, 40.0))):
        t = _member_tele(step_ms=step, steps=10)
        t.record_ms("train/phase_allreduce_exposed_ms", phase)
        cohort.add(f"m{i}:1", t, run_id=f"r{i}", process_index=i)
    fc = _collector(clk, cohort, ["m0:1", "m1:1", "m2:1"])
    engine = obs.AlertEngine.create(
        fc.telemetry, mode="warn", rules=fleet_alert_rules())
    fc.attach(alerts=engine)
    agg = fc.sample()
    c = agg["cohort"]
    assert c["straggler_host"] == "m2:1"
    assert c["straggler_score"] == pytest.approx(4.0)
    assert c["straggler_series"] == "phase_allreduce_exposed"
    assert c["step_p50_skew"] == pytest.approx(3.0)
    rows = [r for r in agg["hosts"] if r["endpoint"] != "m2:1"]
    assert all(r["straggler_score"] == pytest.approx(1.0)
               for r in rows)
    # the gauges landed in the hosting registry and the ticket fired
    # through the attached engine in the SAME sweep
    assert fc.telemetry.gauges["fleet/straggler_score"] == \
        pytest.approx(4.0)
    state = {r["rule"]: r["state"] for r in engine.status_table()}
    assert state["cohort_straggler"] == "firing"
    assert state["cohort_divergence"] != "firing"


def test_single_host_has_no_straggler():
    """Skew needs a cohort: one host never gets a score (a median of
    itself is a tautology, not a signal)."""
    clk = FakeClock()
    cohort = FakeCohort(clk)
    cohort.add("m0:1", _member_tele(step_ms=100.0, steps=1),
               run_id="r1")
    fc = _collector(clk, cohort, ["m0:1"])
    c = fc.sample()["cohort"]
    assert c["straggler_score"] is None
    assert c["step_p50_skew"] is None


# ---- divergence ------------------------------------------------------

def _loss_member(cohort, endpoint, run_id, step, loss, digest=None):
    t = _member_tele(steps=step)
    t.gauge("train/loss", loss, emit=False)
    t.gauge("train/loss_step", float(step), emit=False)
    if digest is not None:
        t.gauge("train/params_digest", digest, emit=False)
        t.gauge("train/params_digest_step", float(step), emit=False)
    if endpoint in cohort.members:
        cohort.members[endpoint]["tele"] = t
    else:
        cohort.add(endpoint, t, run_id=run_id)
    return t


def test_divergence_fires_on_matching_step_disagreement():
    clk = FakeClock()
    cohort = FakeCohort(clk)
    _loss_member(cohort, "m0:1", "r0", 10, 0.5)
    _loss_member(cohort, "m1:1", "r1", 10, 0.5)
    fc = _collector(clk, cohort, ["m0:1", "m1:1"])
    engine = obs.AlertEngine.create(
        fc.telemetry, mode="warn", rules=fleet_alert_rules())
    fc.attach(alerts=engine)
    c = fc.sample()["cohort"]
    assert c["divergence"] == 0
    assert c["loss_divergence_rel"] == pytest.approx(0.0)

    # same step, different loss: the SPMD contract broke at runtime
    clk.t += 1.0
    _loss_member(cohort, "m0:1", "r0", 20, 0.5)
    _loss_member(cohort, "m1:1", "r1", 20, 0.6)
    c = fc.sample()["cohort"]
    assert c["divergence"] == 1
    assert c["loss_divergence_step"] == 20
    assert c["loss_divergence_rel"] == pytest.approx(0.1 / 0.55,
                                                     rel=1e-6)
    state = {r["rule"]: r["state"] for r in engine.status_table()}
    assert state["cohort_divergence"] == "firing"


def test_divergence_params_digest_channel():
    """Loss can agree while weights drift (a buggy non-replicated
    optimizer state): the sampled params fingerprint is its own
    channel, matched at its own step labels."""
    clk = FakeClock()
    cohort = FakeCohort(clk)
    _loss_member(cohort, "m0:1", "r0", 10, 0.5, digest=1234.5)
    _loss_member(cohort, "m1:1", "r1", 10, 0.5, digest=1240.5)
    fc = _collector(clk, cohort, ["m0:1", "m1:1"])
    c = fc.sample()["cohort"]
    assert c["loss_divergence_rel"] == pytest.approx(0.0)
    assert c["params_digest_divergence_rel"] > 1e-4
    assert c["params_digest_divergence_step"] == 10
    assert c["divergence"] == 1


def test_disjoint_steps_never_compare():
    """Hosts scraped at different steps with no overlap: nothing to
    compare, no false alarm."""
    clk = FakeClock()
    cohort = FakeCohort(clk)
    _loss_member(cohort, "m0:1", "r0", 10, 0.5)
    _loss_member(cohort, "m1:1", "r1", 11, 0.9)
    fc = _collector(clk, cohort, ["m0:1", "m1:1"])
    c = fc.sample()["cohort"]
    assert c["divergence"] == 0
    assert c["loss_divergence_step"] is None


# ---- throughput, history, reads -------------------------------------

def test_cohort_throughput_sums_and_persists(tmp_path):
    clk = FakeClock()
    cohort = FakeCohort(clk)
    t0 = _member_tele(steps=10, examples=0)
    t0.gauge("train/max_contexts", 8, emit=False)
    t1 = _member_tele(steps=10, examples=0)
    t1.gauge("train/max_contexts", 8, emit=False)
    cohort.add("m0:1", t0, run_id="r0")
    cohort.add("m1:1", t1, run_id="r1")
    hist = str(tmp_path / "fleet.jsonl")
    fc = _collector(clk, cohort, ["m0:1", "m1:1"], history_path=hist)
    fc.sample()  # first sweep primes the rate windows
    clk.t += 2.0
    t0.count("train/examples", 64)
    t1.count("train/examples", 32)
    agg = fc.sample()
    c = agg["cohort"]
    assert c["ex_per_sec"] == pytest.approx(48.0)
    # pc/s = ex/s * max_contexts, summed over the cohort
    assert c["pc_per_sec"] == pytest.approx(384.0)
    assert [r["pc_s"] for r in agg["hosts"]] == \
        [pytest.approx(256.0), pytest.approx(128.0)]
    # ring + JSONL: the aggregate IS the durable record
    assert len(fc.history) == 2 and fc.aggregate() is agg
    brief = fc.brief()
    assert brief["sweeps"] == 2
    assert [h["endpoint"] for h in brief["hosts"]] == ["m0:1", "m1:1"]
    fc.stop()
    lines = [json.loads(ln) for ln in
             open(hist, encoding="utf-8").read().splitlines()]
    assert len(lines) == 2
    assert lines[1]["cohort"]["pc_per_sec"] == pytest.approx(384.0)
    # prometheus rendering: cohort totals bare, per-host labeled
    prom = fc.render_prometheus()
    assert "fleet_pc_per_sec 384.0" in prom
    assert 'fleet_host_pc_per_sec{host="m0:1"} 256.0' in prom


def test_set_members_keeps_surviving_state():
    """An elastic resize re-points the scrape set; survivors keep
    their handshake (no gratuitous re-measure), dropped members
    leave."""
    clk = FakeClock()
    cohort = FakeCohort(clk)
    cohort.add("m0:1", _member_tele(steps=1), run_id="r0",
               offset_s=2.0)
    cohort.add("m1:1", _member_tele(steps=1), run_id="r1")
    fc = _collector(clk, cohort, ["m0:1", "m1:1"])
    fc.sample()
    assert len(cohort.commits) == 2
    fc.set_members(["m0:1"])  # shrink to the survivor
    clk.t += 1.0
    agg = fc.sample()
    assert [r["endpoint"] for r in agg["hosts"]] == ["m0:1"]
    assert len(cohort.commits) == 2  # survivor NOT re-handshaked
    assert agg["hosts"][0]["clock_offset_s"] == pytest.approx(2.0)


def test_disabled_path_is_the_shared_singleton():
    off = FleetCollector.create(obs.Telemetry.memory("x"), members=())
    assert off is FleetCollector.disabled()
    assert FleetCollector.create(
        obs.Telemetry.disabled(), members=["m:1"]) is off
    assert FleetCollector.create(None, members=["m:1"]) is off
    before = threading.enumerate()
    assert off.start() is off
    assert off.sample() == {} and off.aggregate() == {}
    assert off.brief() == {}
    off.set_members(["m:1"])
    off.stop()
    assert threading.enumerate() == before


# ---- real-socket seams ----------------------------------------------

def test_fleet_endpoint_404_without_collector():
    t = obs.Telemetry.memory("m").make_threadsafe()
    srv = obs.MetricsServer(t, port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.bound_port}/fleet", timeout=5)
        assert e.value.code == 404
    finally:
        srv.stop()


def test_clock_commit_persists_manifest_block(tmp_path):
    """The member half of the handshake: a committed offset lands in
    the run manifest as the `clock` block trace_report --merge aligns
    with — fresh anchor pair, measured offset, sample count."""
    run = obs.Telemetry.create(str(tmp_path), component="train")
    srv = obs.MetricsServer(run, port=0,
                            identity={"run_id": run.run_id}).start()
    try:
        base = f"http://127.0.0.1:{srv.bound_port}"
        out = json.load(urllib.request.urlopen(
            base + "/clock?commit=1&offset_s=0.25&samples=5",
            timeout=5))
        assert out["committed"] is True
        manifest = json.load(
            open(os.path.join(run.run_dir, "manifest.json")))
        clock = manifest["clock"]
        assert clock["wall_offset_s"] == pytest.approx(0.25)
        assert clock["samples"] == 5
        assert isinstance(clock["mono"], float)
        assert isinstance(clock["wall"], float)
        # malformed commit: refused, manifest untouched
        bad = json.load(urllib.request.urlopen(
            base + "/clock?commit=1", timeout=5))
        assert bad["committed"] is False
    finally:
        srv.stop()
        run.close()


# ---- obs_top --fleet view -------------------------------------------

def test_obs_top_renders_fleet_aggregate():
    """`obs_top --fleet` renders the collector's aggregate — it never
    re-derives: cohort headline (summed pc/s, straggler + attributed
    series, converged/DIVERGED, clock spread) plus per-host rows with
    measured offsets and DOWN markers."""
    from tools import obs_top
    clk = FakeClock()
    cohort = FakeCohort(clk)
    for i, (step, phase) in enumerate(((100.0, 10.0), (300.0, 40.0))):
        t = _member_tele(step_ms=step, steps=7, examples=0)
        t.gauge("train/max_contexts", 8, emit=False)
        t.record_ms("train/phase_allreduce_exposed_ms", phase)
        cohort.add(f"m{i}:1", t, run_id=f"r{i}", offset_s=0.002 * i)
    fc = _collector(clk, cohort, ["m0:1", "m1:1"])
    fc.sample()
    clk.t += 1.0
    for m in cohort.members.values():
        m["tele"].count("train/examples", 32)
    agg = fc.sample()
    out = obs_top.render_fleet(agg)
    assert "2/2 hosts up" in out
    assert "pc/s (sum) 512.0" in out  # 2 hosts x 32 ex/s x C=8
    assert "(m1:1 via phase_allreduce_exposed)" in out
    assert "converged" in out and "DIVERGED" not in out
    assert "allreduce_exposed" in out  # phase table rides along
    # a dead member renders as a DOWN row, not a crash
    agg["hosts"][1] = {"endpoint": "m1:1", "up": False,
                       "error": "connection refused"}
    assert "DOWN: connection refused" in obs_top.render_fleet(agg)


def test_obs_top_fetch_fleet_normalizes_url():
    """fetch_fleet accepts host:port, a base URL, or the full /fleet
    URL — all land on the collector's endpoint."""
    from tools import obs_top
    t = obs.Telemetry.memory("sup").make_threadsafe()
    clk = FakeClock()
    cohort = FakeCohort(clk)
    cohort.add("m0:1", _member_tele(step_ms=50.0, steps=1),
               run_id="r0")
    fc = FleetCollector(t, members=["m0:1"], clock=clk, wall=clk.wall,
                        fetch=cohort.fetch, handshake_samples=1)
    fc.sample()
    srv = obs.MetricsServer(t, port=0, fleet=fc).start()
    try:
        for url in (f"127.0.0.1:{srv.bound_port}",
                    f"http://127.0.0.1:{srv.bound_port}/",
                    f"http://127.0.0.1:{srv.bound_port}/fleet"):
            agg = obs_top.fetch_fleet(url)
            assert agg["cohort"]["hosts_up"] == 1
    finally:
        srv.stop()


# ---- supervisor hosting ---------------------------------------------

def test_supervisor_hosts_collector_and_rules():
    from code2vec_tpu.training.supervisor import Supervisor
    sup = Supervisor(
        lambda *a: None, num_procs=1,
        telemetry=obs.Telemetry.memory("sup").make_threadsafe())
    # the cohort tickets ride the stock supervisor engine (quiet until
    # the fleet publishes: threshold rules on absent series never fire)
    rules = {r["rule"] for r in sup.alerts.status_table()}
    assert {"cohort_straggler", "cohort_divergence"} <= rules
    # null collector: attach is a no-op, topology stays fleet-free
    sup.attach_fleet(FleetCollector.disabled(), ["x:1"])
    assert sup.fleet is None
    assert "fleet" not in sup.cohort_topology()
    # live collector: cohort snapshot joins the stall-dump topology

    def dead(_url):
        raise OSError("down")

    clk = FakeClock()
    fc = FleetCollector(sup.telemetry, members=["m:1"], clock=clk,
                        wall=clk.wall, fetch=dead)
    sup.attach_fleet(fc, ["m:1"])
    assert sup.fleet is fc and fc._alerts is sup.alerts
    fc.sample()
    topo = sup.cohort_topology()
    assert topo["fleet"]["sweeps"] == 1
    assert topo["fleet"]["cohort"]["hosts_up"] == 0


# ---- measured-offset trace merge ------------------------------------

def _span(t0, name="train/step_cycle", trace="t", span="s"):
    return {"kind": "span", "trace": trace, "span": span,
            "name": name, "t0": t0, "dur_ms": 5.0, "tid": 1,
            "tname": "main", "attrs": {"step": 1}}


def _run_dir(d, pidx, created, spans, clock=None):
    manifest = {"run_id": f"run-p{pidx}", "component": "train",
                "process_index": pidx, "process_count": 2,
                "created_unix": created}
    if clock is not None:
        manifest["clock"] = clock
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    with open(os.path.join(d, "events.jsonl"), "w") as f:
        for e in spans:
            f.write(json.dumps(e) + "\n")
    return d


def test_merge_uses_committed_offsets_not_created_unix(tmp_path):
    """Two runs whose manifests carry handshake clock blocks: the
    merged timeline realigns each run's monotonic spans onto the
    collector's wall clock (`t0 - mono + wall - wall_offset_s`). The
    created_unix stamps are 2.5 s apart ON PURPOSE — the measured path
    must ignore them (true gap: 0.5 s) — and the clock_note caveat is
    retired."""
    from tools.trace_report import write_chrome_trace
    d0 = _run_dir(str(tmp_path / "r0"), 0, 1000.0,
                  [_span(100.0, trace="t0", span="s0")],
                  clock={"mono": 100.0, "wall": 1000.0,
                         "wall_offset_s": 0.0, "samples": 5})
    # p1's wall ran 2 s ahead; the handshake MEASURED that, so its
    # span (monotonic t0=50.5, 0.5 s after its anchor) lands 0.5 s
    # after p0's on the shared timeline
    d1 = _run_dir(str(tmp_path / "r1"), 1, 1002.5,
                  [_span(50.5, trace="t1", span="s1")],
                  clock={"mono": 50.0, "wall": 1002.0,
                         "wall_offset_s": 2.0, "samples": 5})
    out = str(tmp_path / "merged.json")
    write_chrome_trace([d0, d1], out, merge=True)
    trace = json.load(open(out))["traceEvents"]
    assert not [e for e in trace if e["name"] == "clock_note"]
    e0 = next(e for e in trace
              if e["name"] == "train/step_cycle" and e["pid"] == 0)
    e1 = next(e for e in trace
              if e["name"] == "train/step_cycle" and e["pid"] == 1)
    assert e1["ts"] - e0["ts"] == pytest.approx(0.5e6, abs=1.0)
    # process rows carry the measured offset for the reader
    names = {e["pid"]: e["args"] for e in trace
             if e["name"] == "process_name"}
    assert names[0]["clock_offset_s"] == pytest.approx(0.0)
    assert names[1]["clock_offset_s"] == pytest.approx(2.0)


def test_merge_half_measured_cohort_falls_back(tmp_path):
    """One run without a clock block poisons the measured path for the
    WHOLE merge (exact and sloppy timelines must not interleave as if
    comparable): created_unix fallback, clock_note caveat back on
    every process."""
    from tools.trace_report import write_chrome_trace
    d0 = _run_dir(str(tmp_path / "r0"), 0, 1000.0,
                  [_span(100.0, trace="t0", span="s0")],
                  clock={"mono": 100.0, "wall": 1000.0,
                         "wall_offset_s": 0.0})
    d1 = _run_dir(str(tmp_path / "r1"), 1, 1002.5,
                  [_span(50.5, trace="t1", span="s1")])
    out = str(tmp_path / "merged.json")
    write_chrome_trace([d0, d1], out, merge=True)
    trace = json.load(open(out))["traceEvents"]
    notes = [e for e in trace if e["name"] == "clock_note"]
    assert len(notes) == 2
    assert "fleet plane" in notes[0]["args"]["note"]
    e0 = next(e for e in trace
              if e["name"] == "train/step_cycle" and e["pid"] == 0)
    e1 = next(e for e in trace
              if e["name"] == "train/step_cycle" and e["pid"] == 1)
    assert e1["ts"] - e0["ts"] == pytest.approx(2.5e6, abs=1.0)


# ---- end to end: live 2-process cohort ------------------------------

@pytest.mark.slow
def test_live_cohort_straggler_ticket_and_merged_trace(tmp_path):
    """The ISSUE 17 acceptance path, 2-process Gloo cohort on CPU:
    an `infeed/produce` sleep fault on member 1 makes it the
    straggler; the supervisor-hosted collector measures it live, the
    cohort_straggler ticket flips through the supervisor's alert
    engine, a mid-train /fleet scrape shows per-host p50s + summed
    pc/s, and the post-run --merge trace aligns on the COMMITTED
    offsets (no clock_note)."""
    from code2vec_tpu.parallel.compat import free_port
    from code2vec_tpu.training.supervisor import (Supervisor,
                                                  build_cli_spawn)
    from tools import chaos
    from tools.telemetry_report import find_runs
    from tools.trace_report import write_chrome_trace

    prefix = chaos.build_dataset(str(tmp_path / "ds"))
    faults = str(tmp_path / "faults.json")
    chaos._write_faults(faults, {
        "infeed/produce": {"action": "sleep", "delay_ms": 150,
                           "times": -1, "process": 1}})
    members_dir = str(tmp_path / "members")
    # sync checkpointing: the loopback-Gloo transport race (the
    # parallel/compat docstring family) reproduces deterministically
    # when the async writer thread's device work interleaves with a
    # cohort this skewed — verified pre-existing with the fault alone,
    # no fleet plane attached
    cmd = chaos.train_cmd(prefix, str(tmp_path / "ckpt"),
                          epochs=6) + \
        ["--telemetry_dir", members_dir, "--trace",
         "--faults", faults, "--async_checkpoint", "off"]
    ports = [free_port(), free_port()]
    members = [f"127.0.0.1:{p}" for p in ports]

    sup_tele = obs.Telemetry.memory("supervisor").make_threadsafe()
    sup = Supervisor(
        build_cli_spawn(cmd, num_procs=2,
                        out_dir=str(tmp_path / "logs"),
                        cpu_devices=1, metrics_ports=ports),
        num_procs=2, max_restarts=1, telemetry=sup_tele,
        attempt_timeout_s=600.0, log=lambda _m: None)
    fc = FleetCollector.create(sup_tele, members=members,
                               interval_s=0.25, handshake_samples=3)
    sup.attach_fleet(fc, members)
    fsrv = obs.MetricsServer(sup_tele, port=0, fleet=fc).start()
    fleet_url = f"http://127.0.0.1:{fsrv.bound_port}/fleet"

    rc_box = {}
    th = threading.Thread(
        target=lambda: rc_box.update(rc=sup.run()), daemon=True)
    best = {}
    ticket_fired = False
    th.start()
    try:
        deadline = time.time() + 570.0
        while th.is_alive() and time.time() < deadline:
            time.sleep(0.5)
            try:
                agg = json.load(
                    urllib.request.urlopen(fleet_url, timeout=5))
            except (OSError, ValueError):
                continue
            c = agg.get("cohort") or {}
            up = [r for r in agg.get("hosts", ()) if r.get("up")]
            if (c.get("hosts_up") == 2 and c.get("pc_per_sec")
                    and all(r.get("step_p50") is not None
                            for r in up)
                    and (c.get("straggler_score") or 0) >
                    (best.get("cohort", {})
                     .get("straggler_score") or 0)):
                best = agg
            ticket_fired = ticket_fired or any(
                r["rule"] == "cohort_straggler"
                and r["state"] == "firing"
                for r in sup.alerts.status_table())
        th.join(timeout=60.0)
    finally:
        fsrv.stop()
    assert rc_box.get("rc") == 0, "supervised cohort run failed"

    # one mid-train /fleet scrape showed the whole cohort: both
    # hosts' step p50s, summed path-context throughput, and the
    # injected slow member as THE straggler past the ticket line
    assert best, "never saw a full 2-host /fleet snapshot mid-train"
    c = best["cohort"]
    assert c["pc_per_sec"] > 0
    assert c["straggler_score"] > 1.5
    assert c["straggler_host"] == members[1]
    by_ep = {r["endpoint"]: r for r in best["hosts"]}
    assert {r["process_index"] for r in best["hosts"]} == {0, 1}
    assert all(r["clock_committed"] for r in best["hosts"])
    assert by_ep[members[1]]["straggler_score"] == \
        pytest.approx(c["straggler_score"])
    assert ticket_fired, "cohort_straggler never flipped the engine"

    # the committed offsets make the merged trace MEASURED: pick the
    # final attempt's run per process, align, and the caveat is gone
    runs = {}
    for d in find_runs(members_dir):
        m = json.load(open(os.path.join(d, "manifest.json")))
        if m.get("component") != "train" or "clock" not in m:
            continue
        p = m.get("process_index")
        if p not in runs or m.get("created_unix", 0) > runs[p][0]:
            runs[p] = (m.get("created_unix", 0), d)
    assert set(runs) == {0, 1}, f"missing committed runs: {runs}"
    out = str(tmp_path / "merged.json")
    write_chrome_trace([d for _, d in runs.values()], out, merge=True)
    trace = json.load(open(out))["traceEvents"]
    assert not [e for e in trace if e["name"] == "clock_note"]
    spans = [e for e in trace if e.get("cat") == "span"]
    pids = {e["pid"] for e in spans}
    assert pids == {0, 1}
    # consistent interleaving: the two processes' step timelines
    # overlap on the shared clock (they trained concurrently)
    span_rng = {p: (min(e["ts"] for e in spans if e["pid"] == p),
                    max(e["ts"] for e in spans if e["pid"] == p))
                for p in pids}
    assert span_rng[0][0] < span_rng[1][1]
    assert span_rng[1][0] < span_rng[0][1]
