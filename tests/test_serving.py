"""Interactive predict REPL (SURVEY.md §4.4): scripted session over a
real Java file through the native extractor — prints top-k predictions
and attention-ranked contexts, exits on 'q'. Plus the ISSUE 3 REPL
satellites: EOF/Ctrl-C exit cleanly with a flushed telemetry summary,
and a missing/non-executable extractor binary fails up front with the
build_extractor.sh hint."""

import json
import os

import pytest

from code2vec_tpu.models.jax_model import Code2VecModel
from code2vec_tpu.serving.extractor import (Extractor, ExtractorError,
                                            ExtractorPool)
from code2vec_tpu.serving.interactive_predict import InteractivePredictor
from tests.helpers import build_tiny_dataset
from tests.test_model import tiny_config

BIN = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "code2vec_tpu", "extractor", "build",
    "c2v_extract")

JAVA_SRC = """class Demo {
  int count;
  int getCount(int base) {
    int result = base + count;
    if (result > base) { result -= 1; }
    return result;
  }
}
"""


@pytest.mark.skipif(not (os.path.exists(BIN)
                         or os.path.exists(BIN.replace(
                             "c2v_extract", "libc2v.so"))),
                    reason="native extractor not built")
def test_repl_scripted_session(tmp_path, monkeypatch, capsys):
    ds_dir = tmp_path / "ds"
    ds_dir.mkdir()
    prefix = build_tiny_dataset(str(ds_dir), n_train=128,
                                n_val=16, n_test=16, max_contexts=16)
    cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=2)
    model = Code2VecModel(cfg)
    model.train()

    input_file = str(tmp_path / "Input.java")
    with open(input_file, "w") as f:
        f.write(JAVA_SRC)

    # one prediction round, one attack round, then exit
    answers = iter(["", "attack", "q"])
    monkeypatch.setattr("builtins.input", lambda: next(answers))
    InteractivePredictor(cfg, model).predict(input_file=input_file)

    out = capsys.readouterr().out
    assert "Serving." in out
    assert "Original name:" in out
    assert "predicted:" in out
    assert "Attention:" in out
    assert "context:" in out
    # the REPL attack command printed an AttackResult (or a clean
    # attack error — never a traceback)
    assert "untargeted" in out or "Attack error:" in out
    assert "Exiting..." in out


# ---------------------------------------------------------------------
# ISSUE 3 satellites (no native binary required)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def repl_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("repl_ds")
    prefix = build_tiny_dataset(str(d), n_train=64, n_val=8, n_test=8,
                                max_contexts=16)
    cfg = tiny_config(prefix)
    return cfg, Code2VecModel(cfg)


def _one_shot_input(exc):
    calls = {"n": 0}

    def fake_input():
        calls["n"] += 1
        if calls["n"] == 1:
            raise exc
        raise AssertionError("REPL kept reading after exit condition")
    return fake_input


@pytest.mark.parametrize("exc", [EOFError, KeyboardInterrupt],
                         ids=["eof", "ctrl-c"])
def test_repl_eof_and_interrupt_exit_cleanly(repl_model, tmp_path,
                                             monkeypatch, capsys, exc):
    """Piped stdin EOF (and Ctrl-C) must exit the REPL cleanly AND
    flush the serve run's JSONL summary — before ISSUE 3 the EOFError
    escaped and `telemetry.close()` never ran."""
    cfg, model = repl_model
    cfg.TELEMETRY_DIR = str(tmp_path / "tele")
    try:
        monkeypatch.setattr("builtins.input", _one_shot_input(exc()))
        predictor = InteractivePredictor(cfg, model)
        predictor.predict(input_file=str(tmp_path / "Input.java"))
        out = capsys.readouterr().out
        assert "Exiting..." in out
        # the serve run's event log got its close()-time summary
        run_dir = predictor.telemetry.run_dir
        assert run_dir is not None
        with open(os.path.join(run_dir, "events.jsonl"),
                  encoding="utf-8") as f:
            kinds = [json.loads(ln)["kind"] for ln in f if ln.strip()]
        assert "summary" in kinds
    finally:
        cfg.TELEMETRY_DIR = None


def test_extractor_missing_binary_hint(tmp_path, monkeypatch):
    """Regression (satellite): a never-built binary raises ExtractorError
    with the build_extractor.sh hint up front, not an opaque subprocess
    error at first request."""
    from code2vec_tpu.config import Config
    monkeypatch.setattr("code2vec_tpu.serving.extractor.shutil.which",
                        lambda _name: None)
    cfg = Config(SERVE_EXTRACT_WORKERS=1)
    cfg.train_data_path = "unused"
    missing = str(tmp_path / "no_such" / "c2v_extract")
    ex = Extractor(cfg, extractor_path=missing, use_native=False)
    with pytest.raises(ExtractorError, match="build_extractor.sh"):
        ex.preflight()
    # the pool preflights at construction — server start fails early
    with pytest.raises(ExtractorError, match="build_extractor.sh"):
        ExtractorPool(cfg, extractor_path=missing, use_native=False)


def test_extractor_non_executable_binary_hint(tmp_path):
    from code2vec_tpu.config import Config
    cfg = Config()
    cfg.train_data_path = "unused"
    fake = tmp_path / "c2v_extract"
    fake.write_text("not a real binary")
    fake.chmod(0o644)  # exists but not executable
    ex = Extractor(cfg, extractor_path=str(fake), use_native=False)
    with pytest.raises(ExtractorError,
                       match="not .?executable.*build_extractor.sh"):
        ex.preflight()
