"""Interactive predict REPL (SURVEY.md §4.4): scripted session over a
real Java file through the native extractor — prints top-k predictions
and attention-ranked contexts, exits on 'q'."""

import os

import pytest

from code2vec_tpu.models.jax_model import Code2VecModel
from code2vec_tpu.serving.interactive_predict import InteractivePredictor
from tests.helpers import build_tiny_dataset
from tests.test_model import tiny_config

BIN = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "code2vec_tpu", "extractor", "build",
    "c2v_extract")

JAVA_SRC = """class Demo {
  int count;
  int getCount(int base) {
    int result = base + count;
    if (result > base) { result -= 1; }
    return result;
  }
}
"""


@pytest.mark.skipif(not (os.path.exists(BIN)
                         or os.path.exists(BIN.replace(
                             "c2v_extract", "libc2v.so"))),
                    reason="native extractor not built")
def test_repl_scripted_session(tmp_path, monkeypatch, capsys):
    ds_dir = tmp_path / "ds"
    ds_dir.mkdir()
    prefix = build_tiny_dataset(str(ds_dir), n_train=128,
                                n_val=16, n_test=16, max_contexts=16)
    cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=2)
    model = Code2VecModel(cfg)
    model.train()

    input_file = str(tmp_path / "Input.java")
    with open(input_file, "w") as f:
        f.write(JAVA_SRC)

    # one prediction round, one attack round, then exit
    answers = iter(["", "attack", "q"])
    monkeypatch.setattr("builtins.input", lambda: next(answers))
    InteractivePredictor(cfg, model).predict(input_file=input_file)

    out = capsys.readouterr().out
    assert "Serving." in out
    assert "Original name:" in out
    assert "predicted:" in out
    assert "Attention:" in out
    assert "context:" in out
    # the REPL attack command printed an AttackResult (or a clean
    # attack error — never a traceback)
    assert "untargeted" in out or "Attack error:" in out
    assert "Exiting..." in out
