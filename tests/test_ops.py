"""Masked-attention math vs a numpy oracle and sampled-vs-full softmax
agreement on tiny vocabs (SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu.ops.attention import attention_pool
from code2vec_tpu.ops.sampled_softmax import (log_uniform_sample,
                                              sampled_softmax_loss)


def _numpy_attention_oracle(contexts, transform, attention, mask):
    transformed = np.tanh(contexts @ transform)
    scores = transformed @ attention
    scores = np.where(mask > 0, scores, -1e9)
    e = np.exp(scores - scores.max(axis=-1, keepdims=True))
    attn = e / e.sum(axis=-1, keepdims=True)
    attn = np.where(mask.sum(-1, keepdims=True) > 0, attn, 0.0)
    code = np.einsum("bc,bcd->bd", attn, transformed)
    return code, attn


def test_attention_pool_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    B, C, D = 4, 6, 8
    contexts = rng.normal(size=(B, C, D)).astype(np.float32)
    transform = rng.normal(size=(D, D)).astype(np.float32) * 0.3
    attention = rng.normal(size=(D,)).astype(np.float32)
    mask = (rng.random((B, C)) > 0.3).astype(np.float32)
    mask[0] = 1.0   # fully valid row
    mask[1] = 0.0   # fully padded row
    code, attn = attention_pool(jnp.asarray(contexts), jnp.asarray(transform),
                                jnp.asarray(attention), jnp.asarray(mask))
    code_np, attn_np = _numpy_attention_oracle(contexts, transform,
                                               attention, mask)
    np.testing.assert_allclose(np.asarray(attn), attn_np, atol=1e-5)
    np.testing.assert_allclose(np.asarray(code), code_np, atol=1e-5)
    # attention is a distribution over valid positions
    np.testing.assert_allclose(np.asarray(attn).sum(-1)[0], 1.0, atol=1e-5)
    assert np.asarray(attn)[1].sum() == 0.0
    # padded positions get zero weight
    assert np.all(np.asarray(attn)[mask == 0] < 1e-6)


def test_log_uniform_sampler_distribution():
    """Candidates are unique per draw and their inclusion frequency
    matches the without-replacement expectation -expm1(S*log1p(-p))."""
    V, S, TRIALS = 100, 20, 2000
    counts = np.zeros(V)
    for seed in range(TRIALS):
        ids = np.asarray(log_uniform_sample(jax.random.PRNGKey(seed), S, V))
        assert ids.shape == (S,)
        assert ids.min() >= 0 and ids.max() < V
        assert len(np.unique(ids)) == S  # unique=True semantics
        counts[ids] += 1
    inclusion = counts / TRIALS
    from code2vec_tpu.ops.sampled_softmax import _effective_num_tries
    p = np.log((np.arange(V) + 2) / (np.arange(V) + 1)) / np.log(V + 1)
    T = _effective_num_tries(S, V)
    expected = -np.expm1(T * np.log1p(-p))
    # the bias-correction model should track the sampler's true inclusion
    # frequencies closely (it feeds log_expected_count)
    np.testing.assert_allclose(inclusion[:10], expected[:10], rtol=0.06)
    assert inclusion[0] > inclusion[10] > inclusion[50]


def test_sampled_softmax_close_to_full_softmax_on_tiny_vocab():
    """With S comparable to V, the corrected sampled loss should be close
    to the full-softmax CE (consistency of the estimator)."""
    rng = np.random.default_rng(1)
    V, D, B, S = 50, 16, 64, 40
    table = rng.normal(size=(V, D)).astype(np.float32) * 0.1
    code = rng.normal(size=(B, D)).astype(np.float32)
    labels = rng.integers(0, V, size=(B,)).astype(np.int32)

    logits = code @ table.T
    full_ce = float(np.mean(
        np.log(np.exp(logits).sum(-1)) - logits[np.arange(B), labels]))

    losses = []
    for seed in range(30):
        loss, _ = sampled_softmax_loss(
            jnp.asarray(table), jnp.asarray(code), jnp.asarray(labels),
            jax.random.PRNGKey(seed), S)
        losses.append(float(loss))
    mean_sampled = np.mean(losses)
    # estimator is biased low for small S; just require the right scale
    assert 0.5 * full_ce < mean_sampled < 1.5 * full_ce


def test_sampled_softmax_padded_examples_excluded():
    rng = np.random.default_rng(2)
    V, D, B, S = 20, 8, 8, 10
    table = rng.normal(size=(V, D)).astype(np.float32)
    code = rng.normal(size=(B, D)).astype(np.float32)
    labels = np.zeros((B,), dtype=np.int32)
    w_half = np.array([1, 1, 1, 1, 0, 0, 0, 0], dtype=np.float32)
    loss_half, _ = sampled_softmax_loss(
        jnp.asarray(table), jnp.asarray(code), jnp.asarray(labels),
        jax.random.PRNGKey(0), S, example_weights=jnp.asarray(w_half))
    # same valid examples, garbage rows changed -> loss unchanged
    code2 = code.copy()
    code2[4:] = 1e3
    loss_half2, _ = sampled_softmax_loss(
        jnp.asarray(table), jnp.asarray(code2), jnp.asarray(labels),
        jax.random.PRNGKey(0), S, example_weights=jnp.asarray(w_half))
    np.testing.assert_allclose(float(loss_half), float(loss_half2),
                               rtol=1e-5)


def test_make_lr_and_horizon_helpers():
    import optax

    from code2vec_tpu.training.optimizers import (make_lr,
                                                  resolve_checkpoint_schedule,
                                                  schedule_total_steps)
    assert make_lr(1e-3) == 1e-3
    sched = make_lr(1e-3, "cosine", 100)
    assert abs(float(sched(0)) - 1e-3) < 1e-9
    # decays to alpha=0.1 of peak at the horizon, clamps past it
    assert abs(float(sched(100)) - 1e-4) < 1e-9
    assert abs(float(sched(500)) - 1e-4) < 1e-9
    lin = make_lr(2e-3, "linear", 10)
    assert abs(float(lin(10)) - 2e-4) < 1e-9
    # warmup_cosine: 0 at step 0, peak at the end of warmup, 10% floor
    wc = make_lr(1e-3, "warmup_cosine", 100, warmup_steps=10)
    assert abs(float(wc(0))) < 1e-9
    assert abs(float(wc(10)) - 1e-3) < 1e-9
    assert abs(float(wc(100)) - 1e-4) < 1e-9
    # auto warmup = 5% of the horizon
    wc_auto = make_lr(1e-3, "warmup_cosine", 200)
    assert abs(float(wc_auto(10)) - 1e-3) < 1e-9
    assert float(wc_auto(5)) < 1e-3

    # horizon: per-host ceil-div batches times epochs, plus resume offset
    assert schedule_total_steps(100, 32, 2) == 8  # ceil(100/32)=4 *2
    assert schedule_total_steps(100, 32, 2, num_hosts=2) == 4
    assert schedule_total_steps(100, 32, 2, restored_step=7) == 15

    msgs = []
    assert resolve_checkpoint_schedule(
        "cosine", {"lr_schedule": "constant"}, msgs.append) == "constant"
    assert msgs and "ignored" in msgs[0]
    msgs.clear()
    assert resolve_checkpoint_schedule(
        "cosine", {"lr_schedule": "cosine"}, msgs.append) == "cosine"
    assert not msgs


def test_trust_ratio_rescales_per_array():
    """make_optimizer(trust_ratio=True): the LAMB-style rescale makes
    every per-array update land at lr * ||param|| / ||normalized
    update|| — so two arrays with very different norms get different
    effective step sizes, unlike plain adam whose normalized update
    magnitude is norm-independent."""
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.training.optimizers import make_optimizer

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {"token_emb": 10.0 * jax.random.normal(k1, (16, 8)),
              "transform": 0.1 * jax.random.normal(k2, (8, 8))}
    grads = jax.tree_util.tree_map(
        lambda p: jax.random.normal(jax.random.PRNGKey(7), p.shape), params)

    for eopt in ("adam", "adafactor"):
        opt_plain = make_optimizer(1e-3, eopt)
        opt_tr = make_optimizer(1e-3, eopt, trust_ratio=True)
        up_p, _ = opt_plain.update(grads, opt_plain.init(params), params)
        up_t, _ = opt_tr.update(grads, opt_tr.init(params), params)
        norm = lambda x: float(jnp.linalg.norm(x))
        # the big-norm table takes a LARGER step under trust ratio, the
        # small-norm matrix a smaller one: ratio ||p||/||u|| straddles 1
        assert norm(up_t["token_emb"]) > norm(up_p["token_emb"])
        assert norm(up_t["transform"]) < norm(up_p["transform"])
        # trust-ratio updates scale exactly with ||p|| per array
        ratio = norm(up_t["token_emb"]) / norm(params["token_emb"])
        ratio2 = norm(up_t["transform"]) / norm(params["transform"])
        assert abs(ratio - 1e-3) / 1e-3 < 0.05, (eopt, ratio)
        assert abs(ratio2 - 1e-3) / 1e-3 < 0.05, (eopt, ratio2)


def test_resolve_checkpoint_warmup():
    from code2vec_tpu.training.optimizers import resolve_checkpoint_warmup

    msgs = []
    # schedule pinned to a non-warmup one: warmup is zeroed with a log
    assert resolve_checkpoint_warmup("cosine", 50, {}, msgs.append) == 0
    assert msgs and "ignored" in msgs[0]
    msgs.clear()
    # checkpoint's effective warmup wins; a conflicting CLI value logs
    assert resolve_checkpoint_warmup(
        "warmup_cosine", 100, {"lr_warmup_steps": 3}, msgs.append) == 3
    assert msgs and "ignored" in msgs[0]
    msgs.clear()
    # pre-round-4 checkpoint (no key): CLI value passes through
    assert resolve_checkpoint_warmup("warmup_cosine", 50, {},
                                     msgs.append) == 50
    assert resolve_checkpoint_warmup("cosine", 0, {}, msgs.append) == 0
    assert not msgs
