"""Chaos smoke (ISSUE 10 satellite): the kill→auto-resume→parity
scenario gates tier-1 the way the graftlint / bench-regression suites
do — the recovery contract runs on every CI pass, not only in
postmortems. The scenarios drive REAL `code2vec.py` processes under
the REAL supervisor via tools/chaos.py. Tier-1 carries exactly the
ONE fast scenario the budget allows; the corrupt-checkpoint and
2-process Gloo scenarios are slow-marked (each spawns extra full
training subprocesses — their contracts stay tier-1-covered at the
unit level in tests/test_resilience.py)."""

import json
import os

import pytest

from tools import chaos


def _run(scenario, tmp_path, **kw):
    out = str(tmp_path / scenario)
    os.makedirs(out, exist_ok=True)
    result = chaos.SCENARIOS[scenario](out, **kw)
    assert result["ok"], json.dumps(result, indent=1, default=str)
    return result


def test_chaos_kill_resume_parity(tmp_path):
    """SIGKILL a 1-process training run mid-epoch (constant LR); the
    supervisor relaunches it with --auto_resume and the final
    checkpoint is BIT-IDENTICAL to an uninterrupted run's."""
    result = _run("kill_resume", tmp_path)
    assert result["kill_fired"]
    assert result["restarts"] == 1
    assert result["param_diffs"] == []
    assert result["oracle_step"] == result["chaos_step"]


@pytest.mark.slow
def test_chaos_corrupt_checkpoint_quarantine_and_alert(tmp_path):
    """A bit-flipped leaf blob in the latest committed step is detected
    before relaunch, quarantined, an `alert` event fires through the
    engine, and training resumes from the prior committed step."""
    result = _run("corrupt_checkpoint", tmp_path)
    assert result["quarantine_dir_exists"]
    assert result["alert_events"] == 1
    assert result["final_step"] > result["resumed_from_step"]


@pytest.mark.slow
def test_chaos_kill_resize_elastic_parity(tmp_path):
    """ISSUE 13 acceptance: SIGKILL one peer of a 2-process cohort
    mid-epoch; the supervisor re-forms the mesh at 1 process (a
    RESIZE — zero full-cohort relaunches), the checkpoint layer
    reshards the restore, and the final params are bit-identical to an
    uninterrupted 1-process run resumed from the same committed step
    (constant LR). The policy/reshard/resume contracts stay
    tier-1-covered at unit level in tests/test_resilience.py and
    tests/test_elastic.py."""
    result = _run("kill_resize", tmp_path)
    assert result["kill_fired"]
    assert result["restarts"] == 1
    assert result["resizes"] == [[2, 1]]
    assert result["full_relaunches"] == 0
    assert result["param_diffs"] == []
    assert result["oracle_step"] == result["chaos_step"]
    assert result["recovery_steps_lost"] >= 0
    assert result["recovery_seconds"] is None \
        or result["recovery_seconds"] > 0


@pytest.mark.slow
def test_chaos_kill_resume_2proc_parity(tmp_path):
    """The same parity contract through the 2-process Gloo cohort:
    worker 1 SIGKILLed mid-epoch, dead peer detected, cohort reaped
    and relaunched coherently on a fresh port, final params
    bit-identical to an uninterrupted 2-process run."""
    result = _run("kill_resume_2proc", tmp_path)
    assert result["kill_fired"]
    assert result["restarts"] >= 1
    assert result["param_diffs"] == []


def test_chaos_cli_list():
    assert chaos.main(["--list"]) == 0
