"""External serving plane (ISSUE 18): HTTP front-end, replica pool,
hot weight reload, SLO autoscaling — tier-1, on fake models (no jax on
the test path; the guard test at the bottom runs the whole plane in a
subprocess with jax IMPORT-BLOCKED). The real-model integration legs
live in tools/serving_bench.py and tools/chaos.py serve_swap_kill."""

import hashlib
import json
import os
import subprocess
import sys
import textwrap
import urllib.error
import urllib.request

import pytest

from code2vec_tpu.common import MethodPredictionResults
from code2vec_tpu.config import Config
from code2vec_tpu.obs import Telemetry
from code2vec_tpu.obs.alerts import AlertRule, serving_slo_rules
from code2vec_tpu.resilience import faults
from code2vec_tpu.resilience.retry import RetryPolicy
from code2vec_tpu.serving import (AutoScaler, PredictionCache,
                                  ReloadManager, ReplicaPool,
                                  ServerOverloaded, ServingFrontend)
from code2vec_tpu.serving.frontend import serialize_prediction
from code2vec_tpu.serving.reload import (committed_steps,
                                         verify_step_files)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- fakes: the model surface PredictionServer/ReplicaPool drive ----

class FakePrepared:
    """PreparedRows' surface: n / slice / concat over raw lines."""

    def __init__(self, lines):
        self.lines = list(lines)

    @property
    def n(self):
        return len(self.lines)

    def slice(self, a, b):
        return FakePrepared(self.lines[a:b])

    @classmethod
    def concat(cls, parts):
        out = []
        for p in parts:
            out.extend(p.lines)
        return cls(out)


class FakeModel:
    """Predicts `pred|<tag>` for every line, where tag lives in
    `params` — so a hot swap visibly changes the answers and a stale
    cache read is detectable."""

    def __init__(self, tag="v0"):
        self.params = {"tag": tag}
        self.warmups = 0

    def warmup_predict(self, max_batch):
        self.warmups += 1
        return [max_batch]

    def predict_compile_count(self):
        return 2  # flat after warmup: compile_delta must read 0

    def prepare_predict_rows(self, lines):
        for ln in lines:
            if ln.startswith("!"):
                raise ValueError(f"malformed line: {ln!r}")
        return FakePrepared(lines)

    def predict_device(self, prepared):
        return (list(prepared.lines),)

    def decode_predictions(self, chunk, result):
        out = []
        for ln in result[0]:
            res = MethodPredictionResults(ln.split(" ")[0])
            res.append_prediction("pred|" + self.params["tag"], 0.9)
            res.append_attention_path(0.5, "src", "1,2,3", "dst")
            out.append(res)
        return out


def fake_config(**kw):
    cfg = Config(SERVE_BATCH_MAX=8, SERVE_BATCH_TIMEOUT_MS=1.0,
                 SERVE_QUEUE_DEPTH=32, SERVE_DEADLINE_MS=0.0,
                 SERVE_CACHE_SIZE=64, SERVE_REPLICAS=2,
                 SERVE_MIN_REPLICAS=1, SERVE_MAX_REPLICAS=3)
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def make_pool(replicas=2, tag="v0", **cfg_kw):
    tele = Telemetry.memory("frontend-test").make_threadsafe()
    pool = ReplicaPool(fake_config(**cfg_kw), lambda: FakeModel(tag),
                       replicas=replicas, telemetry=tele)
    return pool.start(), tele


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _post(url, body: bytes):
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            raw = r.read().decode("utf-8")
            status = r.status
    except urllib.error.HTTPError as e:
        raw = e.read().decode("utf-8")
        status = e.code
    try:
        return status, json.loads(raw)
    except json.JSONDecodeError:
        return status, raw


# ---- HTTP round trip ----

def test_http_predict_healthz_metrics_pool_round_trip():
    pool, tele = make_pool()
    fe = ServingFrontend(pool, port=0, telemetry=tele).start()
    base = f"http://127.0.0.1:{fe.bound_port}"
    try:
        status, body = _post(base + "/predict", json.dumps(
            {"lines": ["methodA a,1,b", "methodB c,2,d"]}).encode())
        assert status == 200 and body["n"] == 2
        first = body["predictions"][0]
        assert first["original_name"] == "methodA"
        assert first["predictions"][0]["name"] == ["pred", "v0"]
        assert first["predictions"][0]["probability"] == 0.9
        assert first["attention_paths"][0]["source_token"] == "src"
        assert "code_vector" not in first  # stays out of the wire shape

        status, raw = _get(base + "/healthz")
        health = json.loads(raw)
        assert status == 200 and health["status"] == "ok"
        assert health["ready"] == 2

        status, raw = _get(base + "/pool")
        table = json.loads(raw)
        assert status == 200 and table["size"] == 2
        assert [r["state"] for r in table["replicas"]] == \
            ["ready", "ready"]

        status, raw = _get(base + "/metrics")
        assert status == 200
        assert b"serve_requests" in raw  # the shared exposition format

        assert _get(base + "/nope")[0] == 404
    finally:
        fe.stop()
        pool.close()


def test_http_error_mapping_400_429_500():
    class StubPool:
        telemetry = None

        def __init__(self, exc):
            self.exc = exc

        def predict_lines(self, lines, deadline_ms=None):
            raise self.exc

        def pool_table(self):
            return {"replicas": [], "size": 1, "ready": 1, "target": 1,
                    "generation": 0, "cache_entries": 0,
                    "cache_generation": 0}

    shed = ServingFrontend(StubPool(ServerOverloaded("queue full")),
                           port=0).start()
    bad = ServingFrontend(StubPool(ValueError("bad line")),
                          port=0).start()
    boom = ServingFrontend(StubPool(RuntimeError("device fell over")),
                           port=0).start()
    payload = json.dumps({"lines": ["m a,1,b"]}).encode()
    try:
        base = f"http://127.0.0.1:{shed.bound_port}"
        status, body = _post(base + "/predict", payload)
        assert status == 429 and body["shed"] is True

        # malformed request bodies 400 before touching the pool
        assert _post(base + "/predict", b"{not json")[0] == 400
        assert _post(base + "/predict",
                     json.dumps({"lines": "m a,1,b"}).encode())[0] == 400
        assert _post(base + "/predict", json.dumps(
            {"lines": ["m"], "deadline_ms": "soon"}).encode())[0] == 400
        assert _post(base + "/elsewhere", payload)[0] == 404

        status, body = _post(
            f"http://127.0.0.1:{bad.bound_port}/predict", payload)
        assert status == 400 and "bad line" in body["error"]

        status, body = _post(
            f"http://127.0.0.1:{boom.bound_port}/predict", payload)
        assert status == 500
    finally:
        shed.stop()
        bad.stop()
        boom.stop()


def test_healthz_gates_on_ready_and_page_alerts():
    class StubAlerts:
        enabled = True

        def __init__(self, rows):
            self.rows = rows

        def status_table(self):
            return self.rows

    pool, tele = make_pool(replicas=1)
    firing = StubAlerts([{"rule": "serving_p99_slo", "state": "firing",
                          "severity": "page"}])
    ticket = StubAlerts([{"rule": "reload_refused", "state": "firing",
                          "severity": "ticket"}])
    fe = ServingFrontend(pool, port=0, alerts=ticket).start()
    try:
        base = f"http://127.0.0.1:{fe.bound_port}"
        # a ticket-severity firing rule never fails readiness
        assert _get(base + "/healthz")[0] == 200
        fe.alerts = firing
        status, raw = _get(base + "/healthz")
        assert status == 503
        assert json.loads(raw)["alerts_firing"] == ["serving_p99_slo"]
        fe.alerts = None
        pool.shrink()  # no-op at min; kill readiness the hard way
        for rep in list(pool._replicas):
            pool._stop_replica(rep, state="stopped")
        assert _get(base + "/healthz")[0] == 503
    finally:
        fe.stop()
        pool.close()


def test_disabled_singletons_share_noop_paths():
    pool, _tele = make_pool(replicas=1)
    try:
        assert not ServingFrontend.create(None, port=9).enabled
        assert not ServingFrontend.create(pool, port=0).enabled
        assert ServingFrontend.create(pool, port=0).start().bound_port \
            is None
        assert not ReloadManager.create(None, pool, poll_s=1.0).enabled
        assert not ReloadManager.create("/tmp/x", pool,
                                        poll_s=0.0).enabled
        assert ReloadManager.disabled().check_now() is None
        assert not AutoScaler.create(pool, enabled=False).enabled
        assert AutoScaler.disabled().tick() is None
    finally:
        pool.close()


# ---- shared generation-scoped cache ----

def test_cache_generation_scoping_and_atomic_invalidate():
    cache = PredictionCache(4)
    cache.put("k", "old", generation=0)
    assert cache.get("k", generation=0) == "old"
    assert cache.get("k") == "old"  # None matches any generation
    cache.invalidate(7)
    assert len(cache) == 0 and cache.generation == 7
    # a replica still on the old generation is isolated BOTH ways
    assert cache.get("k", generation=0) is None
    cache.put("k", "stale-write", generation=0)
    assert len(cache) == 0
    cache.put("k", "new", generation=7)
    assert cache.get("k", generation=7) == "new"


def test_swap_invalidates_shared_cache_no_stale_reads():
    pool, tele = make_pool()
    try:
        line = "methodX a,1,b"
        first = pool.predict_lines([line])[0]
        assert first.predictions[0]["name"] == ["pred", "v0"]
        again = pool.predict_lines([line])[0]
        assert again is first  # served from the shared cache
        assert tele.counters.get("serve/cache_hit") == 1

        pool.swap_params({"tag": "v1"}, generation=1)
        table = pool.pool_table()
        assert table["generation"] == 1
        assert table["cache_generation"] == 1
        assert table["cache_entries"] == 0
        swapped = pool.predict_lines([line])[0]
        # the OLD cached result must not leak through the swap
        assert swapped.predictions[0]["name"] == ["pred", "v1"]
        assert tele.counters.get("serve/cache_hit") == 1  # no new hit
    finally:
        pool.close()


# ---- rolling swap / death / refill ----

def test_swap_rolls_one_replica_at_a_time_never_below_n_minus_1():
    pool, tele = make_pool(replicas=3)
    try:
        snaps = []
        orig = pool._publish

        def spy():
            orig()
            snaps.append(tele.gauges.get("serve/pool_ready"))

        pool._publish = spy
        pool.swap_params({"tag": "v2"}, generation=2)
        assert snaps and min(snaps) >= 2  # never below N-1 of 3
        table = pool.pool_table()
        assert table["ready"] == 3 and table["generation"] == 2
        assert all(r["generation"] == 2 and r["swaps"] == 1
                   for r in table["replicas"])
    finally:
        pool.close()


def test_replica_death_retries_request_and_refills():
    faults.install({"seed": 0, "sites": {
        "serve/kill": {"action": "raise", "at": 1}}},
        log=lambda _m: None)
    pool, tele = make_pool(replicas=2)
    try:
        # the first dispatch dies mid-request; the pool must answer
        # anyway (retry on the survivor) and refill in the background
        out = pool.predict_lines(["methodY a,1,b"])
        assert out[0].predictions[0]["name"] == ["pred", "v0"]
        assert tele.counters.get("serve/replica_dead") == 1
        assert pool.wait_ready(2, timeout_s=10)
        assert tele.counters.get("serve/replica_refill") == 1
        assert pool.compile_delta() == 0  # refill warmup is baseline
    finally:
        faults.clear()
        pool.close()


def test_replacement_gate_denial_leaves_pool_smaller():
    faults.install({"seed": 0, "sites": {
        "serve/kill": {"action": "raise", "at": 1}}},
        log=lambda _m: None)
    tele = Telemetry.memory("gate-test").make_threadsafe()
    pool = ReplicaPool(fake_config(), lambda: FakeModel(),
                       replicas=2, telemetry=tele,
                       replacement_fn=lambda: False).start()
    try:
        pool.predict_lines(["methodZ a,1,b"])
        assert pool.wait_ready(1, timeout_s=10)
        for t in list(pool._refill_threads):
            t.join(timeout=10)
        assert pool.size() == 1  # budget said no: smaller, not wedged
        assert tele.counters.get("serve/replica_refill") is None
    finally:
        faults.clear()
        pool.close()


# ---- hot reload: verify, swap, refuse ----

def _write_step(root, step, payload: bytes, checksums=True):
    state = root / f"step_{step}" / "state"
    state.mkdir(parents=True)
    (state / "params.bin").write_bytes(payload)
    if checksums:
        _write_checksums(root, step)


def _write_checksums(root, step):
    payload = (root / f"step_{step}" / "state"
               / "params.bin").read_bytes()
    (root / f"step_{step}" / "checksums.json").write_text(json.dumps(
        {"step": step, "files": {"state/params.bin": {
            "sha256": hashlib.sha256(payload).hexdigest(),
            "bytes": len(payload)}}}))


def test_reload_swaps_verified_and_refuses_corrupt(tmp_path):
    pool, tele = make_pool()
    rm = ReloadManager(str(tmp_path), pool,
                       load_fn=lambda step: {"tag": f"s{step}"},
                       telemetry=tele, poll_s=0.05)
    try:
        assert rm.check_now() is None  # empty dir: nothing to do

        _write_step(tmp_path, 1, b"good weights")
        assert rm.check_now() == 1
        assert pool.pool_table()["generation"] == 1
        out = pool.predict_lines(["methodR a,1,b"])
        assert out[0].predictions[0]["name"] == ["pred", "s1"]

        # bit-flip the committed blob AFTER its checksums were written:
        # exactly the corruption the manifest exists to catch
        _write_step(tmp_path, 2, b"soon to rot")
        blob = tmp_path / "step_2" / "state" / "params.bin"
        raw = bytearray(blob.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        blob.write_bytes(bytes(raw))
        assert verify_step_files(str(tmp_path), 2) is False
        assert rm.check_now() is None
        assert rm.refused == {2}
        assert tele.counters.get("serve/reload_refused") == 1
        # the pool keeps serving the weights it has
        assert pool.pool_table()["generation"] == 1
        # and the refusal does not log-spam: the step stays refused
        assert rm.check_now() is None
        assert tele.counters.get("serve/reload_refused") == 1

        # committed but no checksums yet (the rename->sidecar window):
        # no verdict, re-examined next sweep — never served unverified
        _write_step(tmp_path, 3, b"still committing", checksums=False)
        assert verify_step_files(str(tmp_path), 3) is None
        assert rm.check_now() is None
        assert 3 not in rm.refused
        _write_checksums(tmp_path, 3)
        assert rm.check_now() == 3
        assert pool.pool_table()["generation"] == 3
        assert rm.status()["last_step"] == 3

        assert [s for s, _ in committed_steps(str(tmp_path))] == \
            [1, 2, 3]
    finally:
        rm.stop()
        pool.close()


def test_reload_io_errors_retry_then_refuse(tmp_path):
    pool, tele = make_pool(replicas=1)
    calls = []

    def flaky_load(step):
        calls.append(step)
        raise OSError(5, "transient-looking but persistent")

    rm = ReloadManager(
        str(tmp_path), pool, load_fn=flaky_load, telemetry=tele,
        poll_s=0.05,
        retry=RetryPolicy("reload-io", max_attempts=2,
                          base_delay_s=0.0, max_delay_s=0.0,
                          retry_on=(OSError,)))
    try:
        _write_step(tmp_path, 1, b"verified but unreadable")
        assert rm.check_now() is None
        assert calls == [1, 1]  # the full retry budget was spent
        assert rm.refused == {1}
        assert tele.counters.get("serve/reload_refused") == 1
        assert pool.pool_table()["generation"] == 0
    finally:
        rm.stop()
        pool.close()


# ---- autoscaler: up on burn, down after quiet hold ----

def test_autoscale_up_on_page_rule_down_after_hold():
    pool, tele = make_pool(replicas=1)
    clk = [0.0]
    scaler = AutoScaler(
        pool, telemetry=tele,
        rules=[AlertRule("hot", metric="load", op=">", value=1.0,
                         severity="page"),
               AlertRule("note", metric="load", op=">", value=0.0,
                         severity="ticket")],
        hold_s=60.0, clock=lambda: clk[0])
    try:
        tele.gauge("load", 5.0, emit=False)
        assert scaler.tick() == "up" and pool.target == 2
        clk[0] = 1.0
        assert scaler.tick() == "up" and pool.target == 3
        clk[0] = 2.0
        assert scaler.tick() is None  # at SERVE_MAX_REPLICAS
        assert tele.counters.get("serve/scale_up") == 2

        # quiet (the page rule resolves; the TICKET rule still firing
        # must not block the shrink) — held for hold_s, then one down
        # per quiet window
        tele.gauge("load", 0.5, emit=False)
        clk[0] = 10.0
        assert scaler.tick() is None  # quiet timer arms
        clk[0] = 69.0
        assert scaler.tick() is None  # inside the hold
        clk[0] = 71.0
        assert scaler.tick() == "down" and pool.target == 2
        clk[0] = 72.0
        assert scaler.tick() is None  # window re-armed
        clk[0] = 135.0
        assert scaler.tick() == "down" and pool.target == 1
        clk[0] = 200.0
        assert scaler.tick() is None  # at SERVE_MIN_REPLICAS
        assert tele.counters.get("serve/scale_down") == 2
        assert pool.wait_ready(1, timeout_s=10)
    finally:
        scaler.stop()
        pool.close()


def test_serving_slo_rules_shape():
    rules = {r.name: r for r in serving_slo_rules(123.0)}
    assert rules["serving_p99_slo"].value == 123.0
    assert rules["serving_p99_slo"].severity == "page"
    assert rules["serving_shed_burn"].kind == "burn_rate"
    assert rules["reload_refused"].severity == "ticket"
    assert rules["replica_dead"].severity == "ticket"


# ---- the whole plane with jax import-BLOCKED ----

def test_serving_plane_runs_without_jax_or_tf(tmp_path):
    """The control plane's stdlib-only claim, enforced: pool + reload
    + autoscaler + HTTP front-end all import and RUN in a subprocess
    where `import jax` (and tensorflow) raises."""
    code = textwrap.dedent("""
        import hashlib, json, sys, urllib.request

        from code2vec_tpu.common import MethodPredictionResults
        from code2vec_tpu.config import Config
        from code2vec_tpu.obs import Telemetry
        from code2vec_tpu.obs.alerts import AlertRule
        from code2vec_tpu.serving import (AutoScaler, ReloadManager,
                                          ReplicaPool, ServingFrontend)

        class FakePrepared:
            def __init__(self, lines):
                self.lines = list(lines)
            @property
            def n(self):
                return len(self.lines)
            def slice(self, a, b):
                return FakePrepared(self.lines[a:b])
            @classmethod
            def concat(cls, parts):
                out = []
                for p in parts:
                    out.extend(p.lines)
                return cls(out)

        class FakeModel:
            def __init__(self):
                self.params = {"tag": "v0"}
            def warmup_predict(self, max_batch):
                return [max_batch]
            def predict_compile_count(self):
                return -1
            def prepare_predict_rows(self, lines):
                return FakePrepared(lines)
            def predict_device(self, prepared):
                return (list(prepared.lines),)
            def decode_predictions(self, chunk, result):
                out = []
                for ln in result[0]:
                    r = MethodPredictionResults(ln.split(" ")[0])
                    r.append_prediction("pred|" + self.params["tag"],
                                        0.9)
                    out.append(r)
                return out

        cfg = Config(SERVE_BATCH_MAX=8, SERVE_BATCH_TIMEOUT_MS=1.0,
                     SERVE_QUEUE_DEPTH=32, SERVE_DEADLINE_MS=0.0,
                     SERVE_CACHE_SIZE=16, SERVE_MAX_REPLICAS=3)
        tele = Telemetry.memory("guard").make_threadsafe()
        pool = ReplicaPool(cfg, FakeModel, replicas=2,
                           telemetry=tele).start()
        fe = ServingFrontend(pool, port=0, telemetry=tele).start()
        base = f"http://127.0.0.1:{fe.bound_port}"

        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"lines": ["m a,1,b"]}).encode(),
            method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            body = json.loads(r.read().decode())
        assert body["n"] == 1
        assert body["predictions"][0]["predictions"][0]["name"] == \\
            ["pred", "v0"]
        for path in ("/healthz", "/metrics", "/pool"):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                assert r.status == 200

        # hot reload: stdlib checksum verify + injected load_fn
        # (construct BEFORE the step lands: steps already on disk at
        # construction are the boot weights, not news)
        root = sys.argv[1]
        rm = ReloadManager(root, pool,
                           load_fn=lambda step: {"tag": "s1"},
                           telemetry=tele, poll_s=0.05)
        import os
        state = os.path.join(root, "step_1", "state")
        os.makedirs(state)
        blob = b"weights"
        with open(os.path.join(state, "params.bin"), "wb") as f:
            f.write(blob)
        with open(os.path.join(root, "step_1", "checksums.json"),
                  "w") as f:
            json.dump({"step": 1, "files": {"state/params.bin": {
                "sha256": hashlib.sha256(blob).hexdigest(),
                "bytes": len(blob)}}}, f)
        assert rm.check_now() == 1
        assert pool.pool_table()["generation"] == 1

        # autoscale: page rule fires -> grow
        tele.gauge("load", 9.0, emit=False)
        sc = AutoScaler(pool, telemetry=tele,
                        rules=[AlertRule("hot", metric="load", op=">",
                                         value=1.0, severity="page")],
                        clock=lambda: 0.0)
        assert sc.tick() == "up" and pool.target == 3

        fe.stop()
        pool.close()
        assert "jax" not in sys.modules
        assert "tensorflow" not in sys.modules
        print("FRONTEND-OK")
    """)
    from tests.test_obs_guard import _tf_blocked_env
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    r = subprocess.run(
        [sys.executable, "-c", code, str(ckpt)],
        env=_tf_blocked_env(tmp_path, block_jax=True), cwd=REPO,
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "FRONTEND-OK" in r.stdout


# ---- config flags ----

def test_serve_flag_bounds_verify():
    assert fake_config().SERVE_SLO_MS == 250.0  # the shipped default
    for kw in ({"SERVE_MIN_REPLICAS": 3, "SERVE_MAX_REPLICAS": 2},
               {"SERVE_REPLICAS": 5, "SERVE_MAX_REPLICAS": 4},
               {"SERVE_PORT": 70000},
               {"SERVE_SLO_MS": 0.0},
               {"SERVE_RELOAD_POLL_S": -1.0}):
        with pytest.raises(ValueError):
            fake_config(**kw).verify()


def test_serialize_prediction_shape():
    res = MethodPredictionResults("orig")
    res.append_prediction("do|thing", 0.75)
    res.append_attention_path(0.25, "a", "9,8,7", "b")
    res.code_vector = object()  # must never serialize
    d = serialize_prediction(res)
    assert d == {"original_name": "orig",
                 "predictions": [{"name": ["do", "thing"],
                                  "probability": 0.75}],
                 "attention_paths": [{"source_token": "a",
                                      "path": "9,8,7",
                                      "target_token": "b",
                                      "attention_score": 0.25}]}
