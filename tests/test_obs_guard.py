"""TF-free guard (ISSUE 2 satellite; extended for ISSUE 6): all of
`code2vec_tpu.obs` — telemetry, tracing, the stall watchdog — must
import and RUN (disabled + file-backed paths, span recording, a
fake-clock stall with its diagnostic dump) on an image with no
TensorFlow at all, and tier-1 test COLLECTION must never pull
TensorFlow in (TF is a tooling dependency, not a training one).

Both tests run subprocesses with a blocker module shadowing
`tensorflow` on PYTHONPATH, so any import attempt anywhere in the
chain fails loudly instead of silently using the locally-installed TF.
"""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tf_blocked_env(tmp_path, block_jax=False):
    blocker = tmp_path / "tfblock"
    blocker.mkdir(exist_ok=True)
    (blocker / "tensorflow.py").write_text(
        "raise ImportError('tensorflow blocked by test_obs_guard')\n")
    if block_jax:
        (blocker / "jax.py").write_text(
            "raise ImportError('jax blocked by test_obs_guard')\n")
    env = dict(os.environ)
    parts = [str(blocker), REPO]
    if env.get("PYTHONPATH"):
        parts.append(env["PYTHONPATH"])
    env["PYTHONPATH"] = os.pathsep.join(parts)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_obs_imports_and_runs_without_tensorflow(tmp_path):
    code = textwrap.dedent("""
        import json, os, sys, tempfile
        import code2vec_tpu.obs as obs

        # disabled path (the --telemetry_dir-unset production default)
        t = obs.Telemetry.disabled()
        assert not t.enabled
        t.count("x"); t.record_ms("a", 1.0); t.event("k"); t.close()
        rec = obs.TrainStepRecorder(t)
        infeed = [1]
        assert rec.wrap(infeed) is infeed

        # memory + file-backed paths
        m = obs.Telemetry.memory("guard")
        m.record_ms("a", 1.0)
        assert m.timer("a").count == 1
        d = tempfile.mkdtemp()
        run = obs.Telemetry.create(d, component="guard")
        run.event("step", step=1, step_ms=1.0, infeed_wait_ms=0.0,
                  loss=0.5)

        # tracing + watchdog (ISSUE 6) ride the same no-TF/no-JAX
        # constraint: spans record, the fake-clock watchdog fires and
        # dumps, and both disabled paths are shared no-op singletons
        tr_off = obs.Tracer.disabled()
        assert tr_off.start_trace("x") is tr_off.start_span("y")
        assert obs.Watchdog.disabled().register("z").beat() is None
        tr = obs.Tracer.create(run)
        root = tr.start_trace("guard/request")
        with tr.start_span("guard/phase", parent=root.context()):
            pass
        clock = [0.0]
        wd = obs.Watchdog(run, stall_s=5.0, tracer=tr,
                          clock=lambda: clock[0])
        hb = wd.register("guard_component")
        hb.beat()
        clock[0] = 6.0
        assert wd.check_now(), "fake-clock stall did not fire"
        assert [s["name"] for s in tr.live_spans()] == \
            ["guard/request"]
        root.end()
        run.close()
        assert os.path.exists(os.path.join(run.run_dir,
                                           "manifest.json"))
        with open(os.path.join(run.run_dir, "events.jsonl")) as f:
            kinds = [json.loads(ln)["kind"] for ln in f if ln.strip()]
        assert "span" in kinds and "stall" in kinds
        assert any(fn.startswith("stall_dump")
                   for fn in os.listdir(run.run_dir))

        # the ScalarWriter fallback rides the same no-TF constraint
        from code2vec_tpu.training.scalars import ScalarWriter
        w = ScalarWriter(d)   # TF blocked -> warn-once no-op
        assert w._writer is None
        w.write(1, {"a": 1.0}); w.close()

        assert "tensorflow" not in sys.modules
        print("GUARD-OK")
    """)
    r = subprocess.run([sys.executable, "-c", code],
                       env=_tf_blocked_env(tmp_path), cwd=REPO,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "GUARD-OK" in r.stdout


def test_live_plane_serves_and_evaluates_without_jax_or_tf(tmp_path):
    """ISSUE 7 extension of the blocked-import pattern: the live
    metrics plane — exposition server, health monitors, alert engine —
    must import AND run (HTTP round-trips included) with BOTH jax and
    tensorflow import-blocked. obs/ stays a pure-stdlib layer."""
    code = textwrap.dedent("""
        import json, sys, urllib.request
        import code2vec_tpu.obs as obs
        from code2vec_tpu.obs.alerts import AlertRule
        from code2vec_tpu.obs.health import (NonFiniteGauges,
                                             default_train_monitors)

        # registry + live plane, fully in memory (no jax manifest)
        t = obs.Telemetry.memory("guard").make_threadsafe()
        t.count("train/steps", 3)
        t.record_ms("train/step_ms", 5.0)
        t.gauge("train/loss", float("nan"), emit=False)
        clock = [0.0]
        wd = obs.Watchdog(t, stall_s=5.0, clock=lambda: clock[0])
        hb = wd.register("infeed_producer"); hb.beat()
        health = obs.HealthEngine.create(t)
        health.add(*default_train_monitors())
        alerts = obs.AlertEngine.create(
            t, mode="raise",
            rules=[AlertRule("nan", metric="health/loss_nonfinite",
                             op=">=", value=1.0)])
        health.add_listener(alerts.evaluate)
        wd.attach(health=health, alerts=alerts)
        health.check_now()  # evaluates monitors, fires the rule
        try:
            alerts.poll()
            raise SystemExit("sticky AlertError never surfaced")
        except obs.AlertError:
            pass

        srv = obs.MetricsServer(t, port=0, watchdog=wd,
                                health=health, alerts=alerts).start()
        base = f"http://127.0.0.1:{srv.bound_port}"
        text = urllib.request.urlopen(base + "/metrics",
                                      timeout=5).read().decode()
        assert "train_steps 3" in text
        assert 'alert_active{rule="nan"} 1' in text
        assert 'health_status{monitor="loss_nonfinite"} 1' in text
        assert "gauge_age_seconds" in text
        v = json.load(urllib.request.urlopen(base + "/vars",
                                             timeout=5))
        assert v["counters"]["train/steps"] == 3
        assert v["alerts"][0]["state"] == "firing"
        # healthz: firing page-severity alert -> 503
        import urllib.error
        try:
            urllib.request.urlopen(base + "/healthz", timeout=5)
            raise SystemExit("healthz should be 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        srv.stop()
        assert "jax" not in sys.modules
        assert "tensorflow" not in sys.modules
        print("LIVE-PLANE-OK")
    """)
    r = subprocess.run([sys.executable, "-c", code],
                       env=_tf_blocked_env(tmp_path, block_jax=True),
                       cwd=REPO, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "LIVE-PLANE-OK" in r.stdout


def test_fleet_collector_runs_without_jax_or_tf(tmp_path):
    """ISSUE 17 extension of the blocked-import pattern: the fleet
    plane — member exposition with /clock + identity, the cohort
    collector's handshake / scrape / straggler math — must import AND
    run (real HTTP round-trips included) with BOTH jax and tensorflow
    import-blocked. The collector runs on laptops and supervisors;
    obs/ stays a pure-stdlib layer."""
    code = textwrap.dedent("""
        import json, sys, threading, urllib.request
        import code2vec_tpu.obs as obs
        from code2vec_tpu.obs.fleet import FleetCollector

        # disabled path first: no members -> the shared no-op
        # singleton, and not one thread started
        before = len(threading.enumerate())
        off = FleetCollector.create(obs.Telemetry.memory("sup"),
                                    members=())
        off.start(); off.sample(); off.stop()
        assert not off.enabled and off.aggregate() == {}
        assert len(threading.enumerate()) == before

        # one real member endpoint (memory registry + exposition)
        m = obs.Telemetry.memory("member").make_threadsafe()
        m.count("train/steps", 4)
        m.count("train/examples", 128)
        m.gauge("train/max_contexts", 8, emit=False)
        m.record_ms("train/step_ms", 100.0)
        srv = obs.MetricsServer(
            m, port=0,
            identity={"run_id": "r-guard", "process_index": 0,
                      "process_count": 1}).start()
        ep = f"127.0.0.1:{srv.bound_port}"

        # /clock serves paired readings + identity
        c = json.load(urllib.request.urlopen(
            f"http://{ep}/clock", timeout=5))
        assert "mono" in c and "wall" in c
        assert c["identity"]["run_id"] == "r-guard"

        # supervisor-side collector: real handshake + scrape over HTTP
        sup = obs.Telemetry.memory("sup").make_threadsafe()
        fc = FleetCollector.create(sup, members=[ep],
                                   handshake_samples=3)
        agg = fc.sample()
        row = agg["hosts"][0]
        assert row["up"] and row["run_id"] == "r-guard"
        assert row["step_p50"] == 100.0
        assert row["clock_offset_s"] is not None
        assert agg["cohort"]["hosts_up"] == 1
        # /fleet serves the aggregate when a collector is attached
        fsrv = obs.MetricsServer(sup, port=0, fleet=fc).start()
        out = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{fsrv.bound_port}/fleet", timeout=5))
        assert out["cohort"]["hosts_up"] == 1
        prom = urllib.request.urlopen(
            f"http://127.0.0.1:{fsrv.bound_port}/fleet?format=prom",
            timeout=5).read().decode()
        assert "fleet_hosts_up 1.0" in prom
        fc.stop(); fsrv.stop(); srv.stop()

        assert "jax" not in sys.modules
        assert "tensorflow" not in sys.modules
        print("FLEET-GUARD-OK")
    """)
    r = subprocess.run([sys.executable, "-c", code],
                       env=_tf_blocked_env(tmp_path, block_jax=True),
                       cwd=REPO, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "FLEET-GUARD-OK" in r.stdout


def test_tier1_collection_is_tf_free(tmp_path):
    """`pytest --collect-only` over the tier-1 selection with TF
    blocked: any test module importing TensorFlow at module scope
    fails collection here before it can fail tier-1 on a TF-free
    image."""
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "--collect-only",
         "-q", "-m", "not slow", "-p", "no:cacheprovider"],
        env=_tf_blocked_env(tmp_path), cwd=REPO, capture_output=True,
        text=True, timeout=540)
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]
    assert "error" not in r.stdout.lower().splitlines()[-1]
