"""tools/sparse_update_sweep.py: the block-size x id-count x vocab
kernel-tuning sweep is `slow`-marked so tier-1 (`-m 'not slow'`,
ROADMAP.md) never pays for it; the marker-registration guard itself IS
tier-1 so an unregistered/typo'd marker cannot silently drop the
deselection (the requant_sweep pattern)."""

import importlib.util
import json
import os

import pytest


def _load_sweep():
    spec = importlib.util.spec_from_file_location(
        "sparse_update_sweep",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools",
            "sparse_update_sweep.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_slow_marker_registered(request):
    """The tier-1 command deselects with -m 'not slow'; that only
    reliably matches a REGISTERED marker (pytest.ini)."""
    markers = request.config.getini("markers")
    assert any(str(m).startswith("slow:") for m in markers), markers


@pytest.mark.slow
@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_sparse_update_sweep_tiny_grid(capsys, tmp_path, dtype):
    out = str(tmp_path / "sweep.jsonl")
    _load_sweep().main(["--vocabs", "64", "--blocks", "32", "--emb",
                        "8", "--ids", "128", "--dtype", dtype,
                        "--steps", "2", "--out", out])
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1
    row = json.loads(lines[0])
    for key in ("vocab", "n_ids", "block_rows", "dtype", "unique_rows",
                "fused_ms", "reference_ms", "update_bytes",
                "fused_gbps", "mode"):
        assert key in row, key
    assert row["vocab"] == 64 and row["block_rows"] == 32
    assert row["dtype"] == dtype
    assert 0 < row["unique_rows"] <= 64
    with open(out, encoding="utf-8") as f:
        assert json.loads(f.readline())["update_bytes"] \
            == row["update_bytes"]
