"""Worker process for the 2-process multi-host test.

Usage: python mp_worker.py <process_id> <port> <out_dir>

Each of the two processes provisions 4 local CPU devices, joins a
2-process distributed runtime (8 global devices), and runs ONE training
step over a global ('data','model') mesh with its own PROCESS-LOCAL half
of the batch — exactly the multi-host feed path of jax_model.train. It
writes the resulting loss and a parameter checksum for the parent test to
compare against a single-process oracle.
"""

import os
import sys

# 4 local CPU devices, pinned BEFORE the jax import: the env flag is the
# only provisioning knob every supported JAX reads (the
# `jax_num_cpu_devices` config key is newer-JAX-only —
# parallel/compat.cpu_worker_env documents the seam). The parent
# test strips XLA_FLAGS from the spawn env, so this append is authoritative.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4"
                           ).strip()


def main() -> None:
    pid, port, out_dir = int(sys.argv[1]), sys.argv[2], sys.argv[3]

    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # Bounded cohort bring-up (ISSUE 14 satellite): on 1-core boxes
    # the loopback-Gloo rendezvous intermittently wedges BOTH workers
    # during bring-up — inside jax.distributed.initialize (it blocks
    # for the peer connect) or at the first collective right after it
    # (PR 12 postscript — it used to eat the module's 300 s
    # communicate() wall per attempt and error 4 tests). The
    # watchdog's deadline covers init + a probe collective and
    # hard-exits this worker on a wedge; the parent fixture's
    # fresh-port transient_distributed retry re-forms the cohort.
    from code2vec_tpu.parallel.compat import (PhaseDeadline,
                                              first_collective_barrier)
    from code2vec_tpu.parallel.distributed import maybe_initialize
    _log = lambda m: print(m, flush=True)  # noqa: E731
    first_collective_barrier(
        timeout_s=90.0,
        setup_fn=lambda: maybe_initialize(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=2, process_id=pid),
        log=_log)
    # ...and the same protection for every phase AFTER bring-up: the
    # transport race can wedge a later collective too (observed mid-
    # workload on this box). Each beat re-arms a 120 s deadline —
    # ~4x the loaded per-phase cost — so a wedge anywhere surfaces as
    # a fast retryable death, never a burned communicate() wall.
    watchdog = PhaseDeadline(timeout_s=120.0, log=_log)
    # device placement (shard_params/shard_opt_state device_puts cross
    # the process boundary) is wedge-prone but compile-free: default
    # 120 s bound (observed: a real wedge here burned a 240 s phase)
    watchdog.beat("shard-state")

    import jax.numpy as jnp
    import numpy as np
    import optax

    from code2vec_tpu.models.encoder import ModelDims, init_params
    from code2vec_tpu.parallel.distributed import fetch_global
    from code2vec_tpu.parallel.mesh import make_mesh
    from code2vec_tpu.parallel.sharding import (shard_batch,
                                                shard_opt_state,
                                                shard_params)
    from code2vec_tpu.training.steps import make_eval_step, make_train_step
    from helpers import example_batch

    assert jax.process_count() == 2 and jax.device_count() == 8

    dims = ModelDims(token_vocab_size=64, path_vocab_size=48,
                     target_vocab_size=40, embeddings_size=16,
                     max_contexts=8, dropout_keep_rate=1.0,
                     vocab_pad_multiple=2)
    mesh = make_mesh(4, 2)

    params = init_params(jax.random.PRNGKey(0), dims)
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    params = shard_params(mesh, params)
    opt_state = shard_opt_state(mesh, opt_state, params)

    # --- train: process-local half-batch; global batch = 8 + 8 ---
    local = example_batch(seed=pid, dims=dims, batch=8)
    batch = shard_batch(mesh, local, process_local=True)
    assert batch[0].shape[0] == 16, batch[0].shape  # B scales with hosts

    # the step call carries the big XLA compiles: a loaded 1-core box
    # can legitimately take >100 s here (compat docstring), so this
    # phase gets extra headroom — still under the 300 s communicate
    # wall
    watchdog.beat("train-step", timeout_s=240.0)
    step = make_train_step(dims, optimizer, compute_dtype=jnp.float32)
    params, opt_state, loss = step(params, opt_state, batch,
                                   jax.random.PRNGKey(7))

    watchdog.beat("eval-step")
    # --- eval: identical batch on both hosts; global batch stays 8 ---
    eval_local = example_batch(seed=99, dims=dims, batch=8)
    eval_batch = shard_batch(mesh, eval_local, process_local=False)
    assert eval_batch[0].shape[0] == 8, eval_batch[0].shape
    eval_step = make_eval_step(dims, top_k=3, compute_dtype=jnp.float32)
    loss_sum, topk_ids, _ = eval_step(params, eval_batch)
    topk_host = fetch_global(topk_ids)

    watchdog.beat("checkpoint")
    # --- checkpoint save: orbax saves are collectives, every process
    # participates (jax_model.save does the same in train()) ---
    from code2vec_tpu.training import checkpoint as ckpt
    from code2vec_tpu.vocab.vocabularies import Code2VecVocabs, Vocab, \
        VocabType
    vocabs = Code2VecVocabs(
        Vocab(VocabType.Token, ["a", "b"]),
        Vocab(VocabType.Path, ["1"]),
        Vocab(VocabType.Target, ["t"]))
    ckpt_dir = os.path.join(out_dir, "ckpt")
    ckpt.save_checkpoint(ckpt_dir, {"params": params,
                                    "opt_state": opt_state, "step": 1},
                         1, vocabs, dims)
    restored = ckpt.load_checkpoint(ckpt_dir, {"params": params,
                                               "opt_state": opt_state,
                                               "step": 0})
    restored_checksum = float(sum(
        jnp.sum(fetch_global(v).astype(np.float64))
        for v in restored["params"].values()))

    watchdog.beat("async-checkpoint")
    # --- async checkpoint writer: the per-process call-order
    # discipline exercised with REAL processes (ISSUE 9 satellite).
    # Each process runs its OWN writer thread; orbax saves are
    # collectives, so commit requires both writers to issue the same
    # save sequence — two lockstep submits (the second blocks until
    # the first commits: one-in-flight), a wait() barrier, then a
    # crash-before-rename submit whose torn step dir must stay
    # invisible to latest_step on BOTH processes.
    async_dir = os.path.join(out_dir, "ckpt_async")
    writer = ckpt.AsyncCheckpointWriter()
    state = {"params": params, "opt_state": opt_state, "step": 2}
    writer.submit(async_dir, state, 2, vocabs, dims)
    state = {"params": params, "opt_state": opt_state, "step": 3}
    writer.submit(async_dir, state, 3, vocabs, dims)
    writer.wait()
    async_committed = ckpt.latest_step(async_dir)

    def killed_mid_save(ckpt_dir, state, step, vocabs, dims, **kw):
        # a preemption mid-orbax-write: temp content, no renamed state
        os.makedirs(os.path.join(ckpt_dir, f"step_{step}",
                                 "state.orbax-checkpoint-tmp"),
                    exist_ok=True)
        raise RuntimeError("writer killed before commit")

    crash_writer = ckpt.AsyncCheckpointWriter(save_fn=killed_mid_save)
    crash_writer.submit(async_dir, {"params": params,
                                    "opt_state": opt_state, "step": 4},
                        4, vocabs, dims)
    crash_sticky = 0
    try:
        crash_writer.wait()
    except RuntimeError:
        crash_sticky = 1
    crash_writer.close()
    async_latest = ckpt.latest_step(async_dir)
    # collective restore of the last committed async step, both procs
    restored_async = ckpt.load_checkpoint(
        async_dir, {"params": params, "opt_state": opt_state,
                    "step": 0})
    async_restored_step = int(np.asarray(restored_async["step"]))
    async_restored_checksum = float(sum(
        jnp.sum(fetch_global(v).astype(np.float64))
        for v in restored_async["params"].values()))

    checksum = float(sum(jnp.sum(fetch_global(v).astype(np.float64))
                         for v in params.values()))

    watchdog.beat("ring-attention")
    # --- ring attention across the REAL process boundary. Mesh layout
    # matters: jax.devices() reshapes to (dcn, data, ctx, model), and
    # process 0 owns devices 0-3 — with data>1 the ctx pairs would stay
    # intra-process. data=1, ctx=2, model=4 puts ctx shard 0 on process
    # 0's devices and shard 1 on process 1's, so every ppermute K/V hop
    # crosses the Gloo boundary; result must equal the dense oracle.
    from code2vec_tpu.ops.ring_attention import ring_attention
    from test_ring_attention import _inputs, dense_oracle
    q, kk, vv, rmask = _inputs(seed=5)
    ring_mesh = make_mesh(1, 4, 2)
    assert dict(ring_mesh.shape) == {"dcn": 1, "data": 1, "ctx": 2,
                                     "model": 4}
    ring_out = fetch_global(ring_attention(q, kk, vv, rmask, ring_mesh))
    ring_max_err = float(jnp.max(jnp.abs(
        ring_out - dense_oracle(q, kk, vv, rmask))))

    watchdog.beat("sharded-evaluate")
    # --- model-level SHARDED evaluate: each host parses a disjoint shard
    # of the eval file; metric partials allreduce at the end
    # (jax_model.evaluate multi-host path) ---
    from code2vec_tpu.models.jax_model import Code2VecModel
    from helpers import sharded_eval_setup
    ds_dir = os.path.join(out_dir, f"ds{pid}")
    os.makedirs(ds_dir, exist_ok=True)
    # deterministic build: both processes create identical content;
    # config shared with the single-process oracle via helpers
    cfg = sharded_eval_setup(ds_dir)
    model = Code2VecModel(cfg)
    eval_res = model.evaluate()

    watchdog.close()
    np.savez(os.path.join(out_dir, f"proc{pid}.npz"),
             loss=float(loss), checksum=checksum,
             restored_checksum=restored_checksum,
             async_committed=async_committed,
             async_latest=async_latest,
             async_crash_sticky=crash_sticky,
             async_restored_step=async_restored_step,
             async_restored_checksum=async_restored_checksum,
             eval_loss=float(loss_sum), topk=np.asarray(topk_host),
             m_eval_loss=eval_res.loss,
             m_eval_top1=eval_res.topk_acc[0],
             m_eval_f1=eval_res.subtoken_f1,
             ring_max_err=ring_max_err)


if __name__ == "__main__":
    main()
