"""Test env: force an 8-device virtual CPU platform BEFORE jax import so
multi-chip sharding tests run without TPU hardware (SURVEY.md §5
"multi-node without a cluster")."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's site customization (PYTHONPATH=/root/.axon_site) may
# have imported jax already with the axon TPU platform; force CPU via the
# config API too (env var alone is not enough in that case).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
