"""Test env: force an 8-device virtual CPU platform BEFORE jax import so
multi-chip sharding tests run without TPU hardware (SURVEY.md §5
"multi-node without a cluster")."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's site customization (PYTHONPATH=/root/.axon_site) may
# have imported jax already with the axon TPU platform; force CPU via the
# config API too (env var alone is not enough in that case).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import signal  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402


def _live_child_pids() -> set:
    """PIDs of this process's LIVE (non-zombie) direct children, via
    /proc. Zombies are excluded: a finished worker the Popen object
    hasn't reaped yet is not a leak, just bookkeeping."""
    me = os.getpid()
    out = set()
    try:
        pids = [int(p) for p in os.listdir("/proc") if p.isdigit()]
    except OSError:  # non-procfs platform: guard degrades to a no-op
        return out
    for pid in pids:
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                stat = f.read().decode("ascii", "replace")
        except OSError:
            continue
        # field 3 = state, field 4 = ppid (after the parenthesized comm,
        # which may itself contain spaces — split from the LAST ')')
        rest = stat.rsplit(")", 1)[-1].split()
        if len(rest) >= 2 and rest[0] != "Z" and int(rest[1]) == me:
            out.add(pid)
    return out


@pytest.fixture(autouse=True)
def no_leaked_subprocesses():
    """Multiprocess-test hygiene (ISSUE 9): no test may leak a worker
    subprocess past its teardown. The 2-process Gloo harnesses
    (tests/mp_worker.py, tools/multichip_bench.py) kill their workers
    in `finally`; this guard asserts the discipline repo-wide — an
    orphaned worker would otherwise hold the coordinator port and CPU
    for the rest of the suite. Leaked processes are SIGKILLed before
    the assertion so one failure can't cascade."""
    before = _live_child_pids()
    yield
    leaked = set()
    for _ in range(20):  # grace for children mid-exit
        leaked = _live_child_pids() - before
        if not leaked:
            return
        time.sleep(0.05)
    procs = []
    for pid in leaked:
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace").strip()
        except OSError:
            cmd = "?"
        procs.append(f"{pid}: {cmd}")
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
    pytest.fail("test leaked live subprocess(es) past teardown "
                f"(killed): {'; '.join(procs)}")
