"""Multi-host correctness, tested with REAL separate processes.

SURVEY.md §3.3 (comm-backend row) + §5 ("multi-node without a cluster"):
two OS processes, 4 CPU devices each, joined via
`jax.distributed.initialize` with Gloo collectives — the same code path a
multi-host TPU pod slice runs. Verifies:

- the train feed scales the GLOBAL batch with host count (each process
  contributes a disjoint local half via
  `jax.make_array_from_process_local_data` — the ADVICE round-1 fix),
- the 2-process step numerics equal a single-process 8-device step over
  the concatenated batch,
- the eval feed keeps the global batch un-scaled (identical data on all
  hosts) and `fetch_global` returns full outputs on every process,
- the host-shard readers partition the example space disjointly.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from helpers import example_batch

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "mp_worker.py")


@pytest.fixture(scope="module")
def two_process_results(tmp_path_factory):
    from code2vec_tpu.parallel.compat import free_port
    from code2vec_tpu.resilience import retry as retry_mod

    # Gloo over loopback TCP has a documented transient transport race
    # (compat docstring) — fresh-port retries keep the fixture from
    # turning a platform hiccup into 6 tier-1 errors. The retry IS the
    # shared resilience policy (ISSUE 10): the hand-rolled attempt
    # loop this fixture and tools/multichip_bench.py each carried
    # lives in code2vec_tpu/resilience/retry.py now. Round 18 (ISSUE
    # 14 satellite — the PR 12 postscript): the cohort bring-up in
    # mp_worker.py now runs a BOUNDED first-collective barrier
    # (compat.first_collective_barrier, 90 s watchdog ->
    # os._exit(BARRIER_TIMEOUT_EXIT)), so the wedge that used to
    # freeze BOTH workers at the first Gloo collective and silently
    # eat a full 300 s communicate() wall per attempt now surfaces as
    # a fast retryable worker death. That bound is what pays for the
    # third attempt below: hang attempts cost ~90 s instead of 300 s,
    # and `max_elapsed_s=330` refuses further retries once the
    # pathological POST-barrier-hang case (still backstopped by the
    # 300 s wall) has burned the budget — worst case stays at the old
    # two-wall ceiling while the common crash/wedge cases get one
    # more fresh port to recover on.
    def spawn_once():
        out_dir = str(tmp_path_factory.mktemp("mp"))
        port = free_port()
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # workers provision own devices
        procs = [subprocess.Popen(
            [sys.executable, WORKER, str(i), str(port), out_dir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for i in range(2)]
        try:
            outs = [p.communicate(timeout=300)[0] for p in procs]
        except subprocess.TimeoutExpired:
            outs = ["worker timed out"] * len(procs)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        if not all(p.returncode == 0 for p in procs):
            raise RuntimeError("worker failed:\n" + "\n".join(
                f"proc{i} rc={p.returncode}:\n{out}"
                for i, (p, out) in enumerate(zip(procs, outs))))
        return {i: np.load(os.path.join(out_dir, f"proc{i}.npz"))
                for i in range(2)}

    return retry_mod.transient_distributed(
        "two-process-fixture", max_attempts=3,
        base_delay_s=0.1, max_elapsed_s=330).call(spawn_once)


def test_two_processes_agree(two_process_results):
    r0, r1 = two_process_results[0], two_process_results[1]
    assert np.isfinite(r0["loss"])
    np.testing.assert_allclose(r0["loss"], r1["loss"], rtol=1e-6)
    np.testing.assert_allclose(r0["checksum"], r1["checksum"], rtol=1e-6)
    np.testing.assert_allclose(r0["eval_loss"], r1["eval_loss"], rtol=1e-6)
    np.testing.assert_array_equal(r0["topk"], r1["topk"])
    # cross-host orbax save -> restore round-trips the params
    np.testing.assert_allclose(r0["restored_checksum"], r0["checksum"],
                               rtol=1e-6)
    np.testing.assert_allclose(r1["restored_checksum"], r1["checksum"],
                               rtol=1e-6)


def test_subprocess_leak_guard_sees_live_children():
    """The conftest no_leaked_subprocesses guard's detector: a live
    child is visible, a reaped one is not (and a properly cleaned-up
    spawn — this very test — passes the autouse guard)."""
    from conftest import _live_child_pids

    p = subprocess.Popen([sys.executable, "-c",
                          "import time; time.sleep(30)"])
    try:
        assert p.pid in _live_child_pids()
    finally:
        p.kill()
        p.wait()
    assert p.pid not in _live_child_pids()


def test_two_process_async_writer_call_order_and_crash_safety(
        two_process_results):
    """ISSUE 9 satellite: each process runs its own
    AsyncCheckpointWriter thread, and the orbax save collective only
    commits when both issue the identical submit sequence — two
    lockstep async submits committed step 3 on BOTH processes, the
    injected crash-before-rename save surfaced as a sticky error on
    both, its torn step_4 stayed invisible to latest_step, and the
    collective restore of the last committed step round-tripped the
    trained params bit-for-bit on every process."""
    for pid in (0, 1):
        r = two_process_results[pid]
        assert int(r["async_committed"]) == 3, pid
        assert int(r["async_latest"]) == 3, pid
        assert int(r["async_crash_sticky"]) == 1, pid
        assert int(r["async_restored_step"]) == 3, pid
        np.testing.assert_allclose(r["async_restored_checksum"],
                                   r["checksum"], rtol=1e-6)


def test_two_process_step_matches_single_process_oracle(
        two_process_results):
    """Single-process 8-device mesh over the concatenated (proc0 ++ proc1)
    batch must produce the same loss and updated params: multi-host is a
    pure re-distribution, not a numerics change."""
    from code2vec_tpu.models.encoder import ModelDims, init_params
    from code2vec_tpu.parallel.mesh import make_mesh
    from code2vec_tpu.parallel.sharding import (shard_batch,
                                                shard_opt_state,
                                                shard_params)
    from code2vec_tpu.training.steps import make_eval_step, make_train_step

    dims = ModelDims(token_vocab_size=64, path_vocab_size=48,
                     target_vocab_size=40, embeddings_size=16,
                     max_contexts=8, dropout_keep_rate=1.0,
                     vocab_pad_multiple=2)
    mesh = make_mesh(4, 2)
    params = init_params(jax.random.PRNGKey(0), dims)
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    params = shard_params(mesh, params)
    opt_state = shard_opt_state(mesh, opt_state, params)

    halves = [example_batch(seed=i, dims=dims, batch=8) for i in range(2)]
    batch = shard_batch(mesh, tuple(
        np.concatenate([halves[0][k], halves[1][k]]) for k in range(6)))

    step = make_train_step(dims, optimizer, compute_dtype=jnp.float32)
    params, opt_state, loss = step(params, opt_state, batch,
                                   jax.random.PRNGKey(7))

    r0 = two_process_results[0]
    np.testing.assert_allclose(float(loss), r0["loss"], rtol=1e-5)
    checksum = float(sum(np.sum(np.asarray(v, dtype=np.float64))
                         for v in params.values()))
    np.testing.assert_allclose(checksum, r0["checksum"], rtol=1e-5)

    eval_batch = shard_batch(mesh, example_batch(seed=99, dims=dims,
                                                 batch=8))
    eval_step = make_eval_step(dims, top_k=3, compute_dtype=jnp.float32)
    loss_sum, topk_ids, _ = eval_step(params, eval_batch)
    np.testing.assert_allclose(float(loss_sum), r0["eval_loss"],
                               rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(topk_ids), r0["topk"])


def _unique_target_dataset(tmpdir: str, n: int):
    """A dataset whose n examples carry n UNIQUE target labels, so shard
    contents are identifiable per-example."""
    from code2vec_tpu.data import binarize as binarize_mod
    from code2vec_tpu.data import preprocess as preprocess_mod

    raw = os.path.join(tmpdir, "raw.txt")
    with open(raw, "w") as f:
        for i in range(n):
            f.write(f"m|{i} tok{i % 5},{1000 + i % 7},tok{i % 3}\n")
    prefix = os.path.join(tmpdir, "uniq")
    args = ["--train_data", raw, "--val_data", raw, "--test_data", raw,
            "--max_contexts", "4", "--word_vocab_size", "1000",
            "--path_vocab_size", "1000", "--target_vocab_size", "1000",
            "--output_name", prefix]
    preprocess_mod.main(args)
    binarize_mod.main(["--data", prefix, "--max_contexts", "4",
                       "--word_vocab_size", "1000",
                       "--path_vocab_size", "1000",
                       "--target_vocab_size", "1000"])
    return prefix


def test_host_shard_readers_partition_disjointly(tmp_path):
    """Each (host_shard, num_host_shards) reader must see a disjoint
    slice whose union is EXACTLY the full example set — text and binary
    paths, checked per-example via unique target labels."""
    from code2vec_tpu.data.reader import open_reader
    from code2vec_tpu.vocab.vocabularies import Code2VecVocabs

    N = 64
    prefix = _unique_target_dataset(str(tmp_path), N)
    vocabs = Code2VecVocabs.load_from_dict_file(
        prefix + ".dict.c2v", 1000, 1000, 1000)

    for use_binary in (True, False):
        shards = []
        for shard in range(3):
            reader = open_reader(
                prefix + ".train.c2v", vocabs, 4, batch_size=8,
                shuffle=False, keep_strings=not use_binary,
                host_shard=shard, num_host_shards=3)
            ids = set()
            for b in reader:
                nv = b.num_valid_examples
                if use_binary or not b.target_strings:
                    ids.update(int(i) for i in b.target_index[:nv])
                else:
                    ids.update(vocabs.target_vocab.lookup_index(s)
                               for s in b.target_strings[:nv])
            shards.append(ids)
        for a in range(3):
            for b in range(a + 1, 3):
                assert not (shards[a] & shards[b]), (use_binary, a, b)
        union = set().union(*shards)
        assert len(union) == N, (use_binary, len(union))


def test_host_shard_readers_emit_aligned_batch_counts(tmp_path):
    """With H hosts and a shard-size imbalance, every host must emit the
    SAME number of batches (short hosts pad with weight-zero batches) or
    the collective train step deadlocks."""
    from code2vec_tpu.data.reader import open_reader
    from code2vec_tpu.vocab.vocabularies import Code2VecVocabs

    # 17 examples, H=2, B=8: host 0 gets 9 (2 batches), host 1 gets 8
    # (1 batch) -> host 1 must pad to 2.
    prefix = _unique_target_dataset(str(tmp_path), 17)
    vocabs = Code2VecVocabs.load_from_dict_file(
        prefix + ".dict.c2v", 1000, 1000, 1000)

    for use_binary in (True, False):
        counts, valids = [], []
        for shard in range(2):
            reader = open_reader(
                prefix + ".train.c2v", vocabs, 4, batch_size=8,
                shuffle=False, keep_strings=not use_binary,
                host_shard=shard, num_host_shards=2)
            batches = list(reader)
            counts.append(len(batches))
            valids.append([b.num_valid_examples for b in batches])
        assert counts[0] == counts[1] == 2, (use_binary, counts)
        assert valids[0] == [8, 1], (use_binary, valids)
        assert valids[1] == [8, 0], (use_binary, valids)


def test_sharded_eval_matches_single_process(two_process_results,
                                             tmp_path):
    """evaluate() on 2 hosts shards the eval file per host and merges
    metric partials; the result must equal a single-process evaluate of
    the same model over the same data."""
    from code2vec_tpu.models.jax_model import Code2VecModel
    from helpers import sharded_eval_setup

    oracle = Code2VecModel(sharded_eval_setup(str(tmp_path))).evaluate()

    r0, r1 = two_process_results[0], two_process_results[1]
    # both hosts report the identical merged metrics
    for k in ("m_eval_loss", "m_eval_top1", "m_eval_f1"):
        np.testing.assert_allclose(r0[k], r1[k], rtol=1e-6, err_msg=k)
    # ring attention with the ctx ring spanning the process boundary
    # (K/V ppermute over Gloo) matched the dense oracle on both hosts
    assert float(r0["ring_max_err"]) < 1e-5
    assert float(r1["ring_max_err"]) < 1e-5
    np.testing.assert_allclose(r0["m_eval_loss"], oracle.loss, rtol=1e-4)
    np.testing.assert_allclose(r0["m_eval_top1"], oracle.topk_acc[0],
                               atol=1e-6)
    np.testing.assert_allclose(r0["m_eval_f1"], oracle.subtoken_f1,
                               atol=1e-6)
