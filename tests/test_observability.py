"""Existing observability corners (ISSUE 2 satellites): StepProfiler
when the run ends before the trace window opens, and ScalarWriter's
no-op + missing-TensorFlow fallback. All tier-1, CPU, TF-free."""

import logging
import sys

from code2vec_tpu.training.profiler import StepProfiler


def test_step_profiler_finish_before_window_start():
    msgs = []
    p = StepProfiler("/tmp/never-written", start_step=100, num_steps=5,
                     log=msgs.append)
    # a run shorter than PROFILE_START_STEP: tick never opens the trace
    p.tick(0, None)
    p.tick(1, None)
    assert not p._active
    p.finish(None)  # must not call jax.profiler.stop_trace / crash
    assert any("no trace written" in m for m in msgs)
    assert p._done
    p.finish(None)  # idempotent: says it once
    assert sum("no trace written" in m for m in msgs) == 1


def test_step_profiler_disabled_is_inert():
    p = StepProfiler(None, start_step=0, num_steps=5)
    p.tick(0, None)
    p.finish(None)  # no profile dir: never logs, never traces
    assert p._done and not p._active


def test_scalar_writer_none_dir_is_noop():
    from code2vec_tpu.training.scalars import ScalarWriter
    w = ScalarWriter(None)
    w.write(1, {"train/loss": 1.0})  # must not raise, must not need TF
    w.close()
    assert w._writer is None


def test_scalar_writer_missing_tf_degrades_to_warn_once(
        tmp_path, monkeypatch, caplog):
    import code2vec_tpu.training.scalars as scalars_mod

    # None in sys.modules makes `import tensorflow` raise ImportError
    # ("import halted") — the no-TF container image, simulated
    monkeypatch.setitem(sys.modules, "tensorflow", None)
    monkeypatch.setattr(scalars_mod, "_WARNED_MISSING_TF", False)
    with caplog.at_level(logging.WARNING, logger="code2vec-tpu"):
        w = scalars_mod.ScalarWriter(str(tmp_path))
        assert w._writer is None  # degraded, not raised
        w.write(1, {"train/loss": 1.0})
        w.close()
        w2 = scalars_mod.ScalarWriter(str(tmp_path))
        assert w2._writer is None
    warnings = [r for r in caplog.records
                if "TensorFlow" in r.getMessage()]
    assert len(warnings) == 1  # warn-once across constructions


def test_scalar_writer_warn_latch_suppresses_log_only():
    # the latch only suppresses repeat WARNINGs; construction still
    # attempts the TF import every time, so a later writer in an image
    # WITH TensorFlow works regardless of earlier failures
    import code2vec_tpu.training.scalars as scalars_mod
    w = scalars_mod.ScalarWriter(None)
    assert w._writer is None
