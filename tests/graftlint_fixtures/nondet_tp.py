"""nondeterminism TRUE POSITIVES: nondeterministic values reaching the
resume-parity surface. Every shape must flag."""

import glob
import os
import random
import time

import jax
import jax.numpy as jnp
import numpy as np


def clock_seeded_key():
    # wall clock -> rng seam: two processes (or a resumed run) draw
    # different streams
    seed = int(time.time())
    return jax.random.PRNGKey(seed)


def clock_fold_in(rng):
    # the ANTI-pattern of the sanctioned step-keyed fold_in
    return jax.random.fold_in(rng, int(time.time() * 1e3))


def global_rng_tensor(n):
    # the unseeded module-global stream into a tensor
    noise = [random.random() for _ in range(n)]
    return jnp.asarray(noise)


def set_order_tensor(ids, extra):
    tried = set(ids) | {extra}
    # list() materializes the set's ITERATION ORDER into the tensor
    return jnp.asarray(list(tried))


def listing_order_rows(d, load):
    names = os.listdir(d)  # unsorted: kernel-dependent order
    rows = [load(n) for n in names]
    return np.asarray(rows)


def glob_into_checkpoint(ckpt_dir, d, save_checkpoint, vocabs, dims):
    shards = glob.glob(os.path.join(d, "*.c2v"))
    # shard ORDER rides into checkpointed state -> resume reads a
    # different order than the run that wrote it
    save_checkpoint(ckpt_dir, {"shards": shards}, 0, vocabs, dims)


def save_checkpoint(ckpt_dir, state, step, vocabs, dims):
    """Stands in for the real seam (named checkpoint sink)."""


def loop_var_into_checkpoint(d, vocabs, dims):
    # the loop variable inherits the iterable's order-taint
    for shard in glob.glob(os.path.join(d, "*.c2v")):
        save_checkpoint("/ckpt", {"shard": shard}, 0, vocabs, dims)


def seed_kwarg_from_clock(open_reader, path):
    return open_reader(path, seed=int(time.monotonic()))


def _wall_clock_stamp():
    # no sink HERE — the hazard is in the caller, one hop away
    t = time.time()
    return t


def interprocedural_source(rng):
    # fires only through _wall_clock_stamp's summary (returns_nondet)
    return jax.random.fold_in(rng, int(_wall_clock_stamp()))


def object_identity_seed(obj):
    # id() differs per process/run even for equal values
    return jax.random.PRNGKey(id(obj) % (1 << 31))
