"""resource-leak TRUE POSITIVES: acquires whose release can be
skipped.

Parsed, never imported — tracer/threading here are fake.
"""

import threading


def leaked_span_on_error(tracer, req):
    """THE PR-6 shape: an exception in handle() leaks the request
    span into the live-span table forever."""
    root = tracer.start_trace("serve/request")
    result = handle(req)              # TP: can raise while root held
    root.end(n=len(result))
    return result


def telemetry_span_error_window(telemetry, batch):
    span = telemetry.span("serve/extract_ms")
    rows = parse(batch)               # TP: leaks span on a bad batch
    span.stop()
    return rows


def early_return_leaks(tracer, lines):
    sp = tracer.start_span("serve/parse")
    if not lines:
        return []                     # TP: sp never ended on this path
    out = decode(lines)
    sp.end()
    return out


def thread_never_joined(work):
    t = threading.Thread(target=work)
    t.start()
    wait_for_side_effect()
    return True                       # TP: started thread never joined


def submit_without_barrier(state, step):
    writer = FakeWriter()
    writer.submit(state, step)
    return state                      # TP: no wait/close — job may be
    #                                   in flight at interpreter exit


def acquire_without_release(lock):
    lock.acquire()
    if contended(lock):
        return False                  # TP: held lock leaks on return
    lock.release()
    return True


def handle(req):
    return []


def parse(b):
    return []


def decode(x):
    return x


def wait_for_side_effect():
    pass


def contended(lk):
    return False


class FakeWriter:
    def submit(self, state, step):
        pass
