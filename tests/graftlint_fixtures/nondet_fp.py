"""nondeterminism TRICKY FALSE POSITIVES: deterministic-by-
construction shapes that must stay quiet — the sanctioned seams and
the order-insensitive consumers."""

import os
import random
import time

import jax
import jax.numpy as jnp
import numpy as np


def step_keyed_fold_in(rng, step):
    # THE sanctioned resume-exact rng idiom (PR 10): a pure function of
    # (seed, step)
    return jax.random.fold_in(rng, step)


def seeded_streams(seed, n):
    # seeded generators are parity-safe: np.random.default_rng(seed)
    # and random.Random(seed) are not the global streams
    rng = np.random.default_rng(seed)
    r = random.Random(seed)
    return jnp.asarray(rng.normal(size=n)), r.random()


class JitteredRetry:
    """The seeded retry jitter (resilience/retry.py shape): an
    INSTANCE stream, injectable and seedable — not the global one."""

    def __init__(self, seed):
        self._rng = random.Random(seed)

    def delay_s(self, base):
        return base * (1.0 - 0.5 * self._rng.random())


def sorted_listing(d, load):
    # sorted() makes the listing order deterministic — the _step_dirs
    # idiom
    rows = [load(n) for n in sorted(os.listdir(d))]
    return np.asarray(rows)


def sorted_set_tensor(ids):
    # sorting a set kills the iteration-order hazard
    return jnp.asarray(sorted(set(ids)))


def set_membership(scores, tried):
    # membership/aggregation reads are order-insensitive: the
    # build_shortlist shape (inf-mask by set, then argpartition)
    for t in tried:
        scores[t] = np.inf
    return len(tried), np.argpartition(scores, 3)[:3]


def set_comparison(v):
    # `set(v) >= {...}` is a membership test — the is_quantized shape
    if set(v) >= {"q", "s"}:
        return jnp.zeros((2, 2))
    return None


def telemetry_timestamp(telemetry, loss):
    # timestamps belong in event logs: telemetry is not a parity sink
    telemetry.event("step", ts=round(time.time(), 6), loss=loss)


def throughput_window(examples):
    # wall clock feeding THROUGHPUT math, not tensors/rng/checkpoints
    t0 = time.time()
    dt = time.time() - t0
    return examples / max(dt, 1e-9)


def per_host_tag_rows(local_batch):
    # process identity into a tensor is the multihost row-tagging
    # MECHANISM (jax_model._my_global_rows), not nondeterminism
    return np.full((local_batch,), jax.process_index(), np.int32)


def dithered_requantize(x, idx, salt, dither_from_index):
    # the sanctioned deterministic counter-hash dither (ops/quant.py)
    return jnp.round(x + dither_from_index(idx, salt))
