"""test-marker-hygiene TRUE POSITIVES (parsed only, never collected —
the filename doesn't match pytest's test_*.py pattern)."""

import time

import pytest


@pytest.mark.slwo            # TP: typo'd marker — would RUN in tier-1
def test_requant_sweep_full_grid():
    pass


def test_long_soak():
    time.sleep(5.0)          # TP: >= 1 s sleep without @pytest.mark.slow


def test_duration_cli():
    # TP: long-run CLI mode without the slow marker
    return ["--mode", "compare", "--duration", "30"]


@pytest.mark.parametrize(
    "case", [pytest.param(1, marks=pytest.mark.sloow)])  # TP: typo
def test_param_typo(case):
    pass
