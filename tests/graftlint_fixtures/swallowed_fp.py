"""swallowed-error tricky FALSE positives: handlers that look like
swallows but either act on the error or sit on a sanctioned path."""
import logging
import queue

log = logging.getLogger(__name__)


def narrow_except_is_documentation(q):
    # naming the exception IS the handling: not broad, not flagged
    while True:
        try:
            return q.get_nowait()
        except queue.Empty:
            continue


def logged_swallow(fn):
    try:
        fn()
    except Exception as e:
        log.warning("best-effort probe failed: %s", e)


def fallback_assignment(fn):
    try:
        value = fn()
    except Exception:
        value = None  # explicit fallback: the error chose a value
    return value


def sticky_error_stash(fn, sink):
    # the AsyncCheckpointWriter pattern: the error is RECORDED, it
    # re-raises at the next barrier
    try:
        fn()
    except BaseException as e:
        sink.error = e


def reraise_after_cleanup(fn, tmp):
    try:
        fn()
    except Exception:
        tmp.unlink()
        raise


class Pool:
    def close(self):
        # sanctioned teardown: best-effort cleanup may swallow
        try:
            self._pool.shutdown()
        except Exception:
            pass

    def drain_quiet(self):
        try:
            self._pump()
        except Exception:
            pass


def finally_block_teardown(fn, conn):
    try:
        return fn()
    finally:
        try:
            conn.close()
        except Exception:
            pass  # teardown under finally: the real error is in flight
