"""Summary-layer RECURSION/CYCLE fixture (ISSUE 14 satellite): the
fixpoint must TERMINATE on self-recursion and mutual call cycles
(facts are monotone finite sets), and a clean cycle must stay quiet —
while an effect inside a cycle still propagates to every member."""

import jax


def clean_self_recursive(n):
    # self-recursion, no effects: summaries converge to empty
    if n <= 0:
        return 0
    return clean_self_recursive(n - 1) + 1


def ping(n):
    # mutual recursion, no effects
    if n <= 0:
        return 0
    return pong(n - 1)


def pong(n):
    if n <= 0:
        return 1
    return ping(n - 1)


def cyc_a(x, n):
    # a cycle CONTAINING a collective: both members' summaries carry it
    if n <= 0:
        return x
    return cyc_b(x, n - 1)


def cyc_b(x, n):
    x = jax.lax.psum(x, "data")
    return cyc_a(x, n)


def uniform_cycle_user(x, n):
    # uniform control calling into the effectful cycle: must stay quiet
    return cyc_a(x, n)
