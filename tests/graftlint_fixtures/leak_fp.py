"""resource-leak TRICKY FALSE POSITIVES: every release discipline the
rule must credit (try/finally, except-handler releases, context
managers, ownership transfer, daemon threads).

Parsed, never imported — tracer/threading here are fake.
"""

import threading


def finally_release(tracer, req):
    root = tracer.start_trace("serve/request")
    try:
        result = handle(req)
    finally:
        root.end()                    # dominates every exit
    return result


def handler_release_and_reraise(tracer, req):
    """The shape server.py actually ships (the PR-6 fix): the error
    path closes the trace and re-raises."""
    root = tracer.start_trace("serve/request")
    ex_span = tracer.start_span("serve/extract") \
        if root is not None else None
    try:
        lines = extract(req)
    except BaseException:
        if root is not None:
            ex_span.end()
            root.end(outcome="error")
        raise
    if ex_span is not None:
        ex_span.end()
    root.end(n=len(lines))
    return lines


def context_manager_span(tracer, req):
    with tracer.start_span("serve/decode"):
        return handle(req)


def with_as_span(telemetry, batch):
    with telemetry.span("serve/parse_ms") as sp:
        rows = parse(batch)
        sp.annotate(n=len(rows))
    return rows


def ownership_transfer(tracer, sink):
    sp = tracer.start_span("serve/request")
    sink.adopt(sp)                    # receiver owns the release now
    return True


def alias_transfer(telemetry):
    sp = telemetry.span("serve/x_ms")
    handle = sp                       # the alias owns the release now
    handle.stop()
    return True


def container_transfer(tracer, open_spans):
    sp = tracer.start_span("serve/request")
    open_spans = [sp]                 # whoever drains the list releases
    return open_spans


def yielded_resource(tracer, reqs):
    for req in reqs:
        sp = tracer.start_span("serve/request", req=req)
        yield sp                      # the consumer owns the release


def returned_resource(work):
    t = threading.Thread(target=work)
    t.start()
    return t                          # caller owns the join


def daemon_thread_sanctioned(work):
    t = threading.Thread(target=work, daemon=True)
    t.start()
    return t.name                     # daemons are never joined


def submit_then_barrier(state, step):
    writer = FakeWriter()
    writer.submit(state, step)
    writer.wait()
    writer.close()
    return state


def borrowed_writer_submit(get_writer, state, step):
    """A writer fetched from elsewhere is BORROWED — its lifecycle
    belongs to the owner, submit here needs no local barrier."""
    writer = get_writer()
    writer.submit(state, step)
    return state


def lock_with_statement(lock):
    with lock:
        return critical()


def conditional_release_is_credited(tracer, work, block):
    """Documented under-reach: a release under ANY branch counts —
    correlating the guard with the acquire (`if sp is not None:`
    vs `if block:`) is beyond static reach, and the guarded-release
    idiom is everywhere in the shipped serving layer."""
    t = threading.Thread(target=work)
    t.start()
    if block:
        t.join()
    return t.name


def match_span_is_not_a_resource(pattern, text):
    m = pattern.search(text)
    start, end = m.span()             # re.Match.span: just a tuple
    return text[start:end]


def handle(req):
    return []


def extract(req):
    return []


def parse(b):
    return []


def critical():
    return True


class FakeWriter:
    def submit(self, state, step):
        pass

    def wait(self):
        pass

    def close(self):
        pass
