"""test-marker-hygiene FALSE POSITIVES the rule must NOT flag."""

import time

import pytest


@pytest.mark.slow            # registered marker, correctly spelled
def test_long_soak_marked():
    time.sleep(5.0)          # fine: the test IS slow-marked


@pytest.mark.slow
def test_duration_cli_marked():
    return ["--mode", "compare", "--duration", "30"]


@pytest.mark.parametrize("n", [1, 2])
@pytest.mark.skipif(True, reason="builtin marks need no registration")
def test_builtin_marks(n):
    pass


def test_handoff_sleeps():
    # sub-second sleeps are thread-handoff timing, not a long run
    time.sleep(0.05)
    time.sleep(0.5)


def test_dynamic_sleep(request):
    # non-constant sleep durations are out of static reach — not flagged
    time.sleep(request.param if hasattr(request, "param") else 0.01)
