"""config-drift FALSE POSITIVES: a fully-reconciled mini config."""

import argparse
import dataclasses

CONFIG_CONSTANTS = frozenset({
    "DROPOUT",            # constant by design, registered
})


@dataclasses.dataclass
class Config:
    BATCH_SIZE: int = 1024
    DROPOUT: float = 0.75
    save_path: str = None    # lowercase CLI-surface fields are exempt

    @classmethod
    def arguments_parser(cls):
        p = argparse.ArgumentParser()
        p.add_argument("--batch_size", dest="batch_size", type=int)
        # dest derived from the flag spelling (no dest= kwarg)
        p.add_argument("--save")
        p.add_argument("-v", "--verbose", dest="verbose_mode", type=int)
        return p

    @classmethod
    def load_from_args(cls, args=None):
        ns = cls.arguments_parser().parse_args(args)
        cfg = cls()
        if ns.batch_size is not None:
            cfg.BATCH_SIZE = ns.batch_size
        cfg.save_path = ns.save
        if ns.verbose_mode:
            cfg.BATCH_SIZE = cfg.BATCH_SIZE  # touch so dest is consumed
        return cfg

    def verify(self):
        if self.BATCH_SIZE < 1 or not 0 < self.DROPOUT <= 1:
            raise ValueError("bad config")
