"""donation-safety TRICKY FALSE POSITIVES: every function here is the
sanctioned idiom — the rule must stay silent.

Parsed, never imported — jax here is fake.
"""

import jax

from fake_steps import make_train_step, snapshot_state  # noqa: F401


def rebind_kills_taint(dims, optimizer, batches, rng):
    """THE train-loop idiom: the statement that donates rebinds the
    names, killing the taint on the same line."""
    step = make_train_step(dims, optimizer)
    params, opt_state = init(dims)
    for batch in batches:
        params, opt_state, loss = step(params, opt_state, batch, rng)
        log_scalar(loss)          # loss is an output, not donated
    return params                 # rebound every iteration: clean


def snapshot_before_donation(step, params, opt_state, batch, rng):
    """The checkpoint idiom (PR 5): snapshot_state results are fresh
    buffers — the alias edge must NOT taint them."""
    jstep = jax.jit(step, donate_argnums=(0, 1))
    snap = snapshot_state({"params": params, "opt_state": opt_state})
    params, opt_state, loss = jstep(params, opt_state, batch, rng)
    submit_save(snap)             # FP-trap: snap is a sanctioned copy
    return params, opt_state


def explicit_copy_before_donation(step, params, batch, rng):
    jstep = jax.jit(step, donate_argnums=(0,))
    kept = jax.numpy.copy(params)
    params = jstep(params, batch, rng)
    return params, kept.mean()    # kept holds fresh buffers


def read_before_donation_is_fine(step, params, opt_state, batch, rng):
    jstep = jax.jit(step, donate_argnums=(0, 1))
    norm = compute_norm(params)   # read BEFORE the donating call
    params, opt_state, loss = jstep(params, opt_state, batch, rng)
    return params, opt_state, norm


def non_donating_eval_step(make_eval_step, dims, params, batches):
    """Eval steps don't donate — post-call reads of params are fine."""
    eval_step = make_eval_step(dims)      # unknown factory: no donation
    total = 0.0
    for batch in batches:
        loss, ids, probs = eval_step(params, batch)
        total += regularizer(params)      # params still alive
    return total


def jit_without_donation(step, params, batch, rng):
    jstep = jax.jit(step)                 # no donate_argnums
    out = jstep(params, batch, rng)
    return out, params                    # nothing was donated


def conditional_rebind_both_paths(step, params, batch, rng, fast):
    jstep = jax.jit(step, donate_argnums=(0,))
    if fast:
        params = jstep(params, batch, rng)
    else:
        params = jstep(params, batch, rng)
    return params                         # rebound on every path


def init(dims):
    return {}, {}


def log_scalar(x):
    pass


def submit_save(s):
    pass


def compute_norm(p):
    return 0.0


def regularizer(p):
    return 0.0
