"""config-drift TRUE POSITIVES: one of each drift class."""

import argparse
import dataclasses

CONFIG_CONSTANTS = frozenset({
    "REAL_CONSTANT",
    "WIRED_BUT_LISTED",   # TP: listed AND assigned in load_from_args
    "GHOST_CONSTANT",     # TP: names no dataclass field
})


@dataclasses.dataclass
class Config:
    BATCH_SIZE: int = 1024
    REAL_CONSTANT: int = 7
    WIRED_BUT_LISTED: int = 1
    ORPHAN_ATTR: int = 3          # TP: no flag, not in CONFIG_CONSTANTS

    @classmethod
    def arguments_parser(cls):
        p = argparse.ArgumentParser()
        p.add_argument("--batch_size", dest="batch_size", type=int)
        p.add_argument("--wired", dest="wired", type=int)
        p.add_argument("--dead_flag", dest="dead_flag", type=int)  # TP
        p.add_argument("--undocumented", dest="undocumented")      # TP
        return p

    @classmethod
    def load_from_args(cls, args=None):
        ns = cls.arguments_parser().parse_args(args)
        cfg = cls()
        cfg.BATCH_SIZE = ns.batch_size
        cfg.WIRED_BUT_LISTED = ns.wired
        if ns.undocumented is not None:
            cfg.BATCH_SIZE = ns.undocumented
        if ns.phantom is not None:   # TP: no add_argument for this
            cfg.BATCH_SIZE = ns.phantom
        return cfg

    def verify(self):
        if self.BATCH_SIZE < 1:
            raise ValueError("batch size")
        if self.BTACH_SIZE > 1 << 20:   # TP: typo'd attr guard
            raise ValueError("too big")
