"""lock-discipline FALSE POSITIVES the rule must NOT flag."""

import threading


class PlainStats:
    """No lock anywhere: a single-threaded accumulator mutating its own
    fields is not a race (obs.TimerStat's shape — its thread safety is
    the OWNING registry's lock)."""

    def __init__(self):
        self.count = 0
        self.total = 0.0

    def record(self, ms):
        self.count += 1
        self.total += ms


class Disciplined:
    def __init__(self):
        self._lock = threading.RLock()
        self._d = {}
        self._installed = False

    def _guard(self):
        return self._lock

    def put(self, k, v):
        with self._guard():        # lock acquired via a helper CALL
            self._d[k] = v

    def clear(self):
        with self._lock:
            self._d.clear()

    def install(self):
        # reassigning the LOCK attribute itself is setup, not a race
        self._lock = threading.RLock()
        with self._lock:
            self._installed = True

    def reader(self):
        # bare READS are deliberately out of scope (lock-free flag
        # reads are an idiom: MicroBatcher.running)
        return len(self._d), self._installed

    def suppressed_reset(self):
        # single-owner teardown, documented:
        # graftlint: disable=lock-discipline
        self._installed = False
