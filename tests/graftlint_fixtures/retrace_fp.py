"""retrace-hazard FALSE POSITIVES the rule must NOT flag."""

import functools

import jax


def make_train_step(dims):
    # the repo factory idiom: jit ONCE at build time, closure reused —
    # a def inside a caller's loop is fine, the jit call runs once
    @functools.partial(jax.jit, static_argnums=(2,))
    def step(params, batch, flag):
        return params @ batch if flag else batch

    return step


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def kernel(x, block_rows=128, interpret=False):
    # literal-tuple statics are exactly what the cache wants
    return x


class Model:
    def __init__(self, dims):
        self._predict_step = make_train_step(dims)

    def predict(self, params, batch):
        # bucketing WITHOUT branching on .shape at the call site: the
        # sanctioned pow-2 pad pattern (predict_bucket_size)
        padded = max(1, 1 << (batch.shape[0] - 1).bit_length())
        return self._predict_step(params, batch, padded > 0)

    def warm(self, params, buckets):
        for b in buckets:
            # calling an ALREADY-jitted step in a loop is the warmup
            # idiom, not a retrace storm
            self._predict_step(params, b, True)


def setup_elsewhere():
    f = jax.jit(lambda x: x)    # local binding, local scope
    return f


def unrelated_reuse():
    # the NAME f is plain abs here — a jit binding in another
    # function's scope must not leak onto this call site
    f = abs
    return f(2.0)
