"""host-sync-in-hot-path FALSE POSITIVES the rule must NOT flag:
shape math, sanctioned sync helpers, cold-path syncs, suppressions."""

import jax
import numpy as np


def device_sync(tree):
    # sanctioned by name: the obs explicit-sync helper shape
    return float(tree[0])


class _Span:
    def stop(self, sync=None):
        # sanctioned (class, name): span(...).stop(sync=...)
        if sync is not None:
            device_sync(sync)
        return 0.0


@jax.jit
def hot_step(params, batch):
    b = int(batch.shape[0])            # shape math, not a device sync
    scale = float(params["w"].shape[1] * 2)   # still shape math
    n = int(len(batch))                # len() is host bookkeeping
    k = float(1 << 8)                  # constant math
    span = _Span()
    span.stop(sync=params)             # sanctioned helper call
    suppressed = batch.item()  # graftlint: disable=host-sync-in-hot-path
    return b + scale + n + k + suppressed


def cold_report(results):
    # NOT reachable from any hot root: a report tool may sync freely
    arr = np.asarray(results)
    print("report:", float(arr.sum()), arr.item())
    return arr
