"""Summary-layer TWO-HOP true positives (ISSUE 14 satellite): the
hazardous effect sits TWO resolved calls below the divergent /
sinking site, so nothing intraprocedural — and no single-hop
special-case — can see it. Both rules must fire here purely through
the propagated summaries."""

import time

import jax


def _leaf_collective(x):
    return jax.lax.psum(x, "data")


def _middle(x):
    # hop 1: no effect of its own, inherits _leaf_collective's
    return _leaf_collective(x) + 1


def divergent_two_hops_up(x):
    # hop 2: the collective is invisible without summary propagation
    if jax.process_index() == 0:
        return _middle(x)
    return x


def _leaf_clock():
    return time.time()


def _stamp():
    # hop 1 for the nondeterminism rule: returns the leaf's wall clock
    return _leaf_clock()


def seeded_two_hops_up(rng):
    return jax.random.fold_in(rng, int(_stamp()))
