"""thread-handoff TRUE POSITIVES: objects mutated after crossing a
thread boundary, plus the raise-from-monitor-thread discipline.

Parsed, never imported — threading/queue here are fake.
"""

import threading


class RacyBatcher:
    """The PR-4 MicroBatcher shape: the request keeps being mutated
    after the consumer thread may already have dequeued it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = FakeQueue()

    def submit(self, req):
        self._queue.put(req)
        req.enqueued_at = now()       # TP: mutated after queue.put

    def submit_batch(self, reqs, req):
        self._queue.put(req)
        with self._lock:
            req.batch_id = 7          # locked: fine
        req.retries += 1              # TP: aug-mutation outside lock


def thread_args_mutation(state):
    worker = make_worker()
    t = threading.Thread(target=worker, args=(state,))
    t.start()
    state["phase"] = "running"        # TP: subscript store after handoff
    t.join()


def executor_submit_mutation(pool, job):
    fut = pool.submit(run_job, job)
    job.cancelled = False             # TP: worker may already read it
    return fut


def aug_extend_after_put(queue, rows):
    batch = list(rows)
    queue.put(batch)
    batch += ["tail"]                 # TP: in-place extend after handoff
    return batch


class SharedStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._current = None

    def publish(self, item):
        self._current = item          # escapes: other threads see self
        item.append("late")           # TP: mutator call after publish


def raising_monitor(deadline):
    def monitor_loop():
        while True:
            if overdue(deadline):
                raise RuntimeError("stalled")  # TP: kills the monitor

    t = threading.Thread(target=monitor_loop, name="stall-monitor")
    t.start()
    return t


class FakeQueue:
    def put(self, item):
        pass


def now():
    return 0.0


def make_worker():
    return lambda s: None


def run_job(job):
    pass


def overdue(d):
    return False
