"""host-sync-in-hot-path TRUE POSITIVES: syncs reachable from hot roots.

Parsed, never imported — the names only need to look real.
"""

import jax
import numpy as np


def fetch_helper(x):
    # reached transitively from the jitted root below
    return np.asarray(x)


def deeper(x):
    return fetch_helper(x).sum()


@jax.jit
def hot_step(params, batch):
    loss = params["w"] @ batch
    print("loss is", loss)          # TP: print in a jitted root
    lf = float(loss)                # TP: float() on a runtime value
    _ = loss.item()                 # TP: .item()
    jax.block_until_ready(loss)     # TP: bare block_until_ready
    deeper(loss)                    # TP lands in fetch_helper (2 hops)
    return lf


class MicroBatcher:
    def _run(self, batch):
        # TP: batcher-flush root reached by (class, name) pattern
        return jax.device_get(batch)


for _variant in range(1):
    @jax.jit
    def loop_defined_step(x):
        # TP: a jitted def hiding in a loop body must still be indexed
        # as a hot root (the indexer descends into For/While/except)
        return x.item()
