"""swallowed-error TRUE positives: broad excepts whose bodies erase the
failure with no log, re-raise, or fallback."""


def classic_pass(fn):
    try:
        return fn()
    except Exception:
        pass


def bound_but_unused(fn):
    try:
        return fn()
    except Exception as e:  # noqa: F841 — bound, then dropped
        pass


def bare_except_continue(items):
    out = []
    for it in items:
        try:
            out.append(it())
        except:  # noqa: E722
            continue
    return out


def base_exception_pass(fn):
    try:
        fn()
    except BaseException:
        pass


def broad_inside_tuple(fn):
    try:
        fn()
    except (ValueError, Exception):
        pass


def docstring_only_body(fn):
    try:
        fn()
    except Exception:
        """Intentionally ignored."""


def not_a_teardown_name(fn):
    # `closest` is not `close`: the sanction matches names, not prefixes
    def closest():
        try:
            fn()
        except Exception:
            pass
    return closest
