"""spmd-divergence TRICKY FALSE POSITIVES: every shape here is
process-UNIFORM (or an audited seam) and must stay quiet."""

import json
import os

import jax


def branch_on_process_count(x):
    # process_count is cohort-uniform: every process agrees, so every
    # process takes the same arm — the multi-host guard idiom
    if jax.process_count() > 1:
        return jax.lax.psum(x, "data")
    return x


def process_zero_sidecar(ckpt_dir, step):
    # the audited post-commit seam: process 0 diverges to write FILE
    # sidecars AFTER the collective completed — no collective inside
    if jax.process_index() == 0:
        with open(os.path.join(ckpt_dir, "checksums.json"), "w") as f:
            json.dump({"step": step}, f)


def rejoined_branch(x):
    # both arms rejoin before the collective: every process reaches it
    if jax.process_index() == 0:
        log_line = "coordinator"
    else:
        log_line = "worker"
    return jax.lax.psum(x, "data"), log_line


def reassigned_rank(x):
    rank = jax.process_index()
    rank = 0  # reassignment kills the per-host taint
    if rank == 0:
        return jax.lax.psum(x, "data")
    return x


def version_probe(f, mesh, x):
    # the compat seam: TypeError depends on the installed wheel, which
    # a homogeneous cohort shares — every process takes the same arm
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=None,
                             out_specs=None, check_vma=False)(x)
    except TypeError:
        return jax.shard_map(f, mesh=mesh, in_specs=None,
                             out_specs=None, check_rep=False)(x)


def sharded_reader_loop(open_reader, step, state):
    # a call result built FROM per-host identity is opaque: the reader
    # aligns batch counts across hosts by contract (the audited
    # pad-to-aligned-batches invariant) — iterating it is uniform
    reader = open_reader(host_shard=jax.process_index(),
                         num_host_shards=jax.process_count())
    for batch in reader:
        state = step(state, batch)
        _loss = jax.lax.psum(state, "data")
    return state


def per_host_scalar_writer(writer_cls, path):
    # per-host VALUES without collectives are fine — only process 0
    # gets a real tensorboard dir, the rest get None
    return writer_cls(path if jax.process_index() == 0 else None)


def lambda_defined_not_executed(x):
    # DEFINING a closure holding a collective executes nothing — the
    # per-branch reducer pattern; calling it (wherever that happens)
    # is a separate site in its own frame
    if jax.process_index() == 0:
        fn = lambda v: jax.lax.psum(v, "data")  # noqa: E731
    else:
        fn = lambda v: v  # noqa: E731
    return fn


def uniform_handler_telemetry(step, state, log):
    try:
        return step(state)
    except RuntimeError as e:
        # divergent handler, but no collective inside: record + re-raise
        log(f"step failed: {e}")
        raise
