"""lock-discipline TRUE POSITIVES: attrs mutated locked AND bare."""

import threading


class RacyQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []          # construction writes are exempt
        self._running = False

    def start(self):
        with self._lock:
            self._running = True   # locked here...

    def stop(self):
        self._running = False      # TP: ...bare here

    def push(self, x):
        with self._lock:
            self._items.append(x)  # locked mutator call...

    def drain(self):
        out = list(self._items)
        self._items.clear()        # TP: ...bare mutator call
        return out


class RacyCond:
    def __init__(self):
        self._cond = threading.Condition()
        self._depth = 0

    def inc(self):
        with self._cond:
            self._depth += 1       # locked AugAssign...

    def dec(self):
        self._depth -= 1           # TP: ...bare AugAssign


class RacyClassLock:
    # the class-attribute lock idiom — still taken as `with self._lock`
    _lock = threading.Lock()

    def grow(self):
        with self._lock:
            self._size = 1          # locked...

    def shrink(self):
        self._size = 0              # TP: ...bare


class RacyUnpack:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = None
        self._assembled = False    # 'sem' substring is NOT lock-ish

    def start(self):
        with self._lock:
            self._thread, self._assembled = object(), True

    def stop(self):
        # TP x2: tuple-unpacking mutations outside the lock (the exact
        # syntax of the batcher-lifecycle fix this rule guards)
        thread, self._thread = self._thread, None
        self._assembled = False
        return thread
