"""thread-handoff TRICKY FALSE POSITIVES: the sanctioned handoff
idioms — the rule must stay silent.

Parsed, never imported — threading/queue here are fake.
"""

import threading


class CleanBatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = FakeQueue()
        self.telemetry = None  # construction: single-threaded

    def submit(self, req):
        req.enqueued_at = now()       # mutate BEFORE the handoff
        self._queue.put(req)

    def submit_locked(self, req):
        self._queue.put(req)
        with self._lock:
            req.batch_id = 7          # mutation under the class lock

    def drain_and_reuse(self, items):
        for item in items:
            msg = wrap(item)
            self._queue.put(msg)
            msg = wrap(item)          # REBIND kills the escape
            msg.retries = 0           # fresh object, never handed off


def read_after_handoff_is_fine(state):
    t = threading.Thread(target=work, args=(state,))
    t.start()
    report(state["phase"])            # reads are out of scope
    t.join()
    return state


def local_then_publish(items):
    """Build-then-publish: all mutation happens before the escape."""
    batch = []
    for item in items:
        batch.append(item)            # still local
    OUT_QUEUE.put(batch)
    return len(items)


def recording_monitor(deadline, telemetry):
    """The watchdog discipline done right: the monitor thread records
    the stall, it never raises."""
    def monitor_loop():
        while True:
            try:
                if overdue(deadline):
                    raise RuntimeError("stalled")  # caught below
            except RuntimeError:
                telemetry_event(telemetry, "stall")

    t = threading.Thread(target=monitor_loop, name="stall-monitor")
    t.start()
    return t


def plain_worker_may_raise(path):
    """Only monitor/watchdog threads get the never-raise sub-check —
    an ordinary worker propagating into the excepthook is normal."""
    def loader():
        if missing(path):
            raise FileNotFoundError(path)

    t = threading.Thread(target=loader, name="shard-loader")
    t.start()
    return t


class FakeQueue:
    def put(self, item):
        pass


OUT_QUEUE = FakeQueue()


def now():
    return 0.0


def wrap(x):
    return x


def work(s):
    pass


def report(x):
    pass


def overdue(d):
    return False


def telemetry_event(t, name):
    pass


def missing(p):
    return False
