"""donation-safety TRUE POSITIVES: reads of donated buffers.

Parsed, never imported (fixtures README) — jax/optax here are fake.
"""

import functools

import jax

from fake_steps import make_train_step  # noqa: F401  (parse-only)


def read_after_factory_step_donation(dims, optimizer, batches, rng):
    """The acceptance shape: a make_train_step-style step's params are
    read after the donating call (the caller kept the OLD name)."""
    step = make_train_step(dims, optimizer)
    params, opt_state = init(dims)
    for batch in batches:
        new_params, new_opt, loss = step(params, opt_state, batch, rng)
        log_norm(params)          # TP: params was donated to step(...)
        params, opt_state = new_params, new_opt
    return params


def return_of_donated(step_fn, params, opt_state, batch, rng):
    step = jax.jit(step_fn, donate_argnums=(0,))
    new_params = step(params, opt_state, batch, rng)
    del new_params
    return params                 # TP: returning a deleted buffer


def aliased_container_read(step, params, opt_state, batch, rng):
    """The snapshot_state bug class: a dict built from params BEFORE
    the donating call still aliases the donated buffers."""
    jstep = jax.jit(step, donate_argnums=(0, 1))
    state = {"params": params, "opt_state": opt_state}
    params, opt_state, loss = jstep(params, opt_state, batch, rng)
    save(state)                   # TP: state aliases donated buffers
    return params, opt_state


def donate_argnames_read(loss_fn, params, batch):
    step = jax.jit(loss_fn, donate_argnames=("params",))
    out = step(batch, params=params)
    return out, params.mean()     # TP: attribute read of donated name


def closure_capture_after_donation(step_fn, params, batch, rng):
    step = functools.partial(jax.jit, donate_argnums=(0,))(step_fn)
    new_params = step(params, batch, rng)

    def report():
        return summarize(params)  # TP: closure reads deleted buffers

    return new_params, report


class ModelWithStep:
    def __init__(self, dims, optimizer):
        self._train_step = make_train_step(dims, optimizer)

    def train_one(self, params, opt_state, batch, rng):
        new_p, new_o, loss = self._train_step(params, opt_state,
                                              batch, rng)
        self.last_norm = norm(params)  # TP: class-attr donor seam
        return new_p, new_o, loss


def init(dims):
    return {}, {}


def log_norm(p):
    pass


def save(s):
    pass


def norm(p):
    return 0.0


def summarize(p):
    return 0.0
