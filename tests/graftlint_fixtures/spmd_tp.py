"""spmd-divergence TRUE POSITIVES: collective effects only some
processes execute. Every shape here must flag (tests/test_graftlint.py
asserts the exact symbol set)."""

import jax


def branch_on_process_index(x):
    # the textbook deadlock: only process 0 enters the collective
    if jax.process_index() == 0:
        return jax.lax.psum(x, "data")
    return x


def branch_on_assigned_rank(x, mesh):
    rank = jax.process_index()
    is_zero = rank == 0
    if is_zero:
        # taint survives assignment + comparison; shard_map bodies run
        # collectives, so entering one divergently deadlocks too
        return jax.shard_map(lambda a: a, mesh=mesh, in_specs=None,
                             out_specs=None)(x)
    return x


def divergent_early_exit(x):
    if jax.process_index() != 0:
        return x
    # only process 0 ever reaches this line
    return jax.lax.all_gather(x, "data")


def collective_in_exception_handler(step, state):
    try:
        return step(state)
    except RuntimeError:
        # only the host that raised re-issues the collective save —
        # its peers are not in the rendezvous (the distributed-
        # deadlock retry class)
        return save_checkpoint("/tmp/ckpt", state, 0, None, None)


def save_checkpoint(ckpt_dir, state, step, vocabs, dims):
    """Stands in for training/checkpoint.save_checkpoint (named seam +
    body effect for the summary layer)."""
    import orbax.checkpoint as ocp
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(ckpt_dir, state)


def _sync_helper(x):
    # no divergence HERE — the effect is inherited by divergent callers
    return jax.lax.psum(x, "data")


def interprocedural_reach(x):
    if jax.process_index() == 0:
        # the collective is one call away: only the summary layer
        # (ISSUE 14) can see it
        return _sync_helper(x)
    return x


def _my_rank():
    return int(jax.process_index())


def divergent_test_via_summary(x):
    # the TEST is per-host one call away: _my_rank()'s summary says it
    # returns process identity
    if _my_rank() == 0:
        return jax.lax.psum(x, "data")
    return x


def ternary_collective(x, flag):
    # divergence expressed as an IfExp arm
    out = jax.lax.pmean(x, "data") if jax.process_index() == 0 else x
    return out, flag


class RankedSaver:
    def __init__(self, writer):
        self._ckpt_writer = writer

    def maybe_submit(self, state):
        if jax.process_index() == 0:
            # the async writer's submit IS a collective save sequence:
            # every process must issue it
            self._ckpt_writer.submit("/tmp/ckpt", state, 1, None, None)


def loop_over_local_devices(x):
    for _d in jax.local_devices():
        # trip count differs on a heterogeneous pod slice
        x = jax.lax.psum(x, "data")
    return x
