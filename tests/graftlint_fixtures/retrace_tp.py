"""retrace-hazard TRUE POSITIVES."""

import functools

import jax

_step = jax.jit(lambda p, b: p @ b)


def storm(batches):
    for b in batches:
        f = jax.jit(lambda p: p @ b)      # TP: jit built per iteration
        yield f(b)


def one_shot(p, b):
    return jax.jit(lambda x: x + 1)(p)    # TP: jit(f)(args)


def bad_statics(fn, axes):
    g = jax.jit(fn, static_argnums=axes)          # TP: computed statics
    h = functools.partial(jax.jit,
                          static_argnames=[1, 2])  # TP: ints for names
    return g, h


def scalar_feed(params):
    return _step(params, 3.5)             # TP: Python scalar traced arg


def dict_feed(params):
    return _step(params, {"x": params})   # TP: dict literal traced arg


def shape_branchy(params, batch):
    if batch.shape[0] > 128:              # TP: shape-derived branch
        return _step(params, batch)
    return _step(params, batch)
