"""TF-checkpoint importer (tools/import_tf_checkpoint.py): a
reference-style tf.train.Saver checkpoint (the five variables of
SURVEY.md §3's tensorflow_model row) must import into a released
checkpoint this framework loads and serves, with the weights carried
over exactly."""

import os
import subprocess
import sys

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from code2vec_tpu.models.jax_model import Code2VecModel  # noqa: E402
from tests.helpers import build_tiny_dataset  # noqa: E402
from tests.test_model import tiny_config  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
IMPORTER = os.path.join(REPO, "tools", "import_tf_checkpoint.py")


def _write_reference_style_checkpoint(d, Vt, Vp, Vy, E):
    """A TF1-Saver checkpoint with the reference's variable names (plus
    Adam slots, which the importer must skip)."""
    import tensorflow.compat.v1 as tf1
    rng = np.random.default_rng(0)
    arrays = {
        "model/WORDS_VOCAB": rng.normal(size=(Vt, E)),
        "model/PATHS_VOCAB": rng.normal(size=(Vp, E)),
        "model/TARGET_WORDS_VOCAB": rng.normal(size=(Vy, 3 * E)),
        "model/TRANSFORM": rng.normal(size=(3 * E, 3 * E)),
        "model/ATTENTION": rng.normal(size=(3 * E, 1)),
    }
    g = tf1.Graph()
    with g.as_default():
        for name, arr in arrays.items():
            v = tf1.get_variable(name, shape=arr.shape,
                                 dtype=tf1.float32)
            # fake Adam slot vars the importer must NOT confuse with
            # the weights
            tf1.get_variable(name + "/Adam", shape=arr.shape,
                             dtype=tf1.float32)
        saver = tf1.train.Saver()
        with tf1.Session(graph=g) as s:
            s.run(tf1.global_variables_initializer())
            for name, arr in arrays.items():
                var = [v for v in tf1.global_variables()
                       if v.name == name + ":0"][0]
                s.run(var.assign(arr.astype(np.float32)))
            prefix = saver.save(s, os.path.join(d, "model"))
    return prefix, arrays


def test_import_reference_tf_checkpoint(tmp_path):
    # dataset supplies the .dict.c2v whose vocab sizes the TF tables
    # must match (vocab sizes INCLUDE the two special rows)
    (tmp_path / "ds").mkdir()
    prefix = build_tiny_dataset(str(tmp_path / "ds"), n_train=128,
                                n_val=16, n_test=16, max_contexts=16)
    cfg = tiny_config(prefix)
    probe = Code2VecModel(cfg)  # just to learn the vocab sizes
    Vt = probe.vocabs.token_vocab.size
    Vp = probe.vocabs.path_vocab.size
    Vy = probe.vocabs.target_vocab.size
    E = 16

    tf_prefix, arrays = _write_reference_style_checkpoint(
        str(tmp_path / "tfckpt"), Vt, Vp, Vy, E)
    out_dir = str(tmp_path / "imported")
    r = subprocess.run(
        [sys.executable, IMPORTER, "--tf_checkpoint", tf_prefix,
         "--dict", prefix + ".dict.c2v", "--save", out_dir,
         "--max_contexts", "16",
         "--word_vocab_size", "1000", "--path_vocab_size", "1000",
         "--target_vocab_size", "1000",
         "--verify_test", prefix + ".test.c2v", "--verify_rows", "16"],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr
    assert "imported TF checkpoint" in r.stdout
    # the semantic row-order check ran (ADVICE r3: a shape check alone
    # cannot catch row misalignment — this can). No warning asserted:
    # with a 10-word toy vocab, chance-level top1 (~1/8) sits above the
    # misalignment threshold that real 261K-vocab imports would trip.
    assert "verify_test (16 rows)" in r.stdout

    # the imported checkpoint loads as a released model and serves
    cfg2 = tiny_config(prefix)
    cfg2.train_data_path = None
    cfg2.load_path = out_dir
    cfg2.test_data_path = prefix + ".test.c2v"
    model = Code2VecModel(cfg2)
    # weights carried over exactly
    np.testing.assert_allclose(
        np.asarray(model.params["token_emb"], np.float32),
        arrays["model/WORDS_VOCAB"].astype(np.float32), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(model.params["attention"], np.float32),
        arrays["model/ATTENTION"][:, 0].astype(np.float32), atol=1e-6)
    results = model.evaluate()  # untrained weights — just must run
    assert results.subtoken_f1 >= 0.0


def test_import_rejects_shape_mismatch(tmp_path):
    (tmp_path / "ds").mkdir()
    prefix = build_tiny_dataset(str(tmp_path / "ds"), n_train=128,
                                n_val=16, n_test=16, max_contexts=16)
    tf_prefix, _ = _write_reference_style_checkpoint(
        str(tmp_path / "tfckpt"), 7, 5, 4, 16)  # wrong row counts
    r = subprocess.run(
        [sys.executable, IMPORTER, "--tf_checkpoint", tf_prefix,
         "--dict", prefix + ".dict.c2v", "--save",
         str(tmp_path / "out"), "--word_vocab_size", "1000",
         "--path_vocab_size", "1000", "--target_vocab_size", "1000"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode != 0
    assert "does not match" in r.stderr
