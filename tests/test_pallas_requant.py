"""Fused Pallas requantize row-pass tests (ops/pallas_requant.py).

Covers: interpret-mode parity against the multi-pass XLA reference
(q bit-exact under the shared counter-hash dither stream), the
dither-mean statistical property through the kernel, untouched-row
stability through the kernel, the requantize dispatch + config
resolution, and an int8 tiny-model train smoke that goes through the
fused path — all on the CPU interpreter (tier-1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.models.encoder import ModelDims, init_params
from code2vec_tpu.ops.pallas_requant import (requant_traffic_bytes,
                                             requantize_fused)
from code2vec_tpu.ops.quant import (dequantize_table, is_quantized,
                                    opt_param_view, quantize_table,
                                    requantize, requantize_reference,
                                    resolve_requant_mode)
from code2vec_tpu.training.optimizers import make_optimizer
from code2vec_tpu.training.steps import make_train_step

DIMS = ModelDims(token_vocab_size=64, path_vocab_size=32,
                 target_vocab_size=24, embeddings_size=8, max_contexts=6,
                 tables_dtype="int8")


def _case(V, E, upd_scale=0.005, upd_dtype=jnp.float32):
    r = np.random.default_rng(V)
    t = jnp.asarray(r.normal(size=(V, E)) * 0.3, jnp.float32)
    qt = quantize_table(t)
    upd = jnp.asarray(r.normal(size=(V, E)) * upd_scale, upd_dtype)
    return qt, upd


# shapes cover: multi-block, non-multiple-of-block V, single padded
# block, E > lane width, and a 1-row table
@pytest.mark.parametrize("V,E", [(64, 8), (40, 16), (300, 128), (5, 8),
                                 (1, 256)])
@pytest.mark.parametrize("upd_dtype", [jnp.float32, jnp.bfloat16])
def test_fused_matches_reference(V, E, upd_dtype):
    """The kernel IS the reference, restructured: same rng -> same salt
    -> same counter-hash dither stream -> q bit-exact. s agrees to
    <= 2 ulp (float-contraction/FMA ordering differs between the
    interpreted kernel and the fused XLA reference; q absorbs the last
    ulp in its integer rounding)."""
    qt, upd = _case(V, E, upd_dtype=upd_dtype)
    rng = jax.random.PRNGKey(9)
    ref = requantize_reference(qt, upd, rng)
    out = requantize_fused(qt, upd, rng, block_rows=32)
    assert out["q"].dtype == jnp.int8 and out["s"].shape == (V, 1)
    np.testing.assert_array_equal(np.asarray(ref["q"]),
                                  np.asarray(out["q"]))
    ulp = np.abs(np.asarray(ref["s"]).ravel().view(np.int32)
                 - np.asarray(out["s"]).ravel().view(np.int32))
    assert ulp.max() <= 2, ulp.max()


def test_fused_default_block_rows_and_jit():
    """The production call shape: default block size (table smaller
    than one block -> fully padded grid), invoked inside an outer jit
    like the train step does."""
    qt, upd = _case(48, 8)
    rng = jax.random.PRNGKey(2)
    # the one-shot outer-jit composition IS what this test exercises
    # graftlint: disable=retrace-hazard
    out = jax.jit(lambda q, u, r: requantize_fused(q, u, r))(qt, upd, rng)
    ref = requantize_reference(qt, upd, rng)
    np.testing.assert_array_equal(np.asarray(ref["q"]),
                                  np.asarray(out["q"]))


def test_requantize_dispatch_and_mode_resolution():
    """requantize() auto-selects the reference off-TPU, forces the
    kernel under fused=True; resolve_requant_mode maps the config
    strings onto exactly those arguments."""
    qt, upd = _case(32, 8)
    rng = jax.random.PRNGKey(4)
    auto = requantize(qt, upd, rng)  # CPU -> reference
    ref = requantize_reference(qt, upd, rng)
    np.testing.assert_array_equal(np.asarray(auto["q"]),
                                  np.asarray(ref["q"]))
    np.testing.assert_array_equal(np.asarray(auto["s"]),
                                  np.asarray(ref["s"]))
    forced = requantize(qt, upd, rng, fused=True)  # interpret kernel
    np.testing.assert_array_equal(np.asarray(forced["q"]),
                                  np.asarray(ref["q"]))
    assert resolve_requant_mode("auto") is None
    assert resolve_requant_mode("fused") is True
    assert resolve_requant_mode("reference") is False
    with pytest.raises(ValueError):
        resolve_requant_mode("bogus")


def test_requant_pallas_config_gate():
    from code2vec_tpu.config import Config

    cfg = Config(REQUANT_PALLAS="bogus")
    cfg.train_data_path = "x"
    with pytest.raises(ValueError):
        cfg.verify()


def test_fused_untouched_rows_stable():
    """Same property as test_quant.test_requantize_untouched_rows_stable,
    through the kernel: zero-update rows round-trip their scale to 1
    ulp, so q is stable up to the ~1e-5-probability dither tail."""
    r = np.random.default_rng(2)
    t = jnp.asarray(r.normal(size=(64, 8)) * 0.5, jnp.float32)
    qt = quantize_table(t)
    upd = np.zeros((64, 8), np.float32)
    upd[3] = 0.01  # one touched row
    out = requantize_fused(qt, jnp.asarray(upd), jax.random.PRNGKey(0),
                           block_rows=32)
    dq, dq_new = np.asarray(qt["q"]), np.asarray(out["q"])
    untouched = [i for i in range(64) if i != 3]
    assert (dq_new[untouched] != dq[untouched]).sum() <= 1
    assert (np.abs(dq_new[untouched].astype(int)
                   - dq[untouched].astype(int)) <= 1).all()
    row_f = np.asarray(dequantize_table(out))[3]
    target = np.asarray(dequantize_table(qt))[3] + upd[3]
    assert np.abs(row_f - target).max() <= np.asarray(out["s"])[3, 0]


def test_fused_stochastic_rounding_unbiased():
    """A 0.3-quantum update must survive in expectation through the
    kernel's dither (deterministic rounding would drop it entirely)."""
    r = np.random.default_rng(3)
    t = jnp.asarray(np.abs(r.normal(size=(1, 512))) * 0.1 + 0.01,
                    jnp.float32)
    qt = quantize_table(t)
    base = np.asarray(dequantize_table(qt)).mean()
    upd = jnp.full((1, 512), float(np.asarray(qt["s"])[0, 0]) * 0.3,
                   jnp.float32)
    deltas = [np.asarray(dequantize_table(requantize_fused(
        qt, upd, jax.random.PRNGKey(100 + k), block_rows=32))).mean()
        - base for k in range(8)]
    mean_delta = float(np.mean(deltas))
    expect = float(np.asarray(upd).mean())
    assert 0.5 * expect < mean_delta < 1.5 * expect, (mean_delta, expect)


def test_requant_traffic_bytes():
    qt, upd = _case(32, 8, upd_dtype=jnp.bfloat16)
    # q r+w (1 B) + s r+w (4 B) + update read (2 B)
    assert requant_traffic_bytes(qt, upd) == \
        32 * 8 * 1 * 2 + 32 * 4 * 2 + 32 * 8 * 2


def test_quantized_train_step_learns_through_fused_path():
    """int8 tiny-model train smoke THROUGH the kernel: the same loss
    trajectory contract as test_quant's reference-path version, with
    requant_fused=True (interpret mode on this CPU platform)."""
    params = init_params(jax.random.PRNGKey(3), DIMS)
    opt = make_optimizer(0.05)
    opt_state = opt.init(opt_param_view(params))
    step = make_train_step(DIMS, opt, use_sampled_softmax=False,
                           requant_fused=True)
    r = np.random.default_rng(7)
    batch = (jnp.asarray(r.integers(0, 24, 16), jnp.int32),
             jnp.asarray(r.integers(0, 64, (16, 6)), jnp.int32),
             jnp.asarray(r.integers(0, 32, (16, 6)), jnp.int32),
             jnp.asarray(r.integers(0, 64, (16, 6)), jnp.int32),
             jnp.ones((16, 6), jnp.float32),
             jnp.ones((16,), jnp.float32))
    losses = []
    rng = jax.random.PRNGKey(4)
    for _ in range(40):
        rng, k = jax.random.split(rng)
        params, opt_state, loss = step(params, opt_state, batch, k)
        losses.append(float(loss))
    assert is_quantized(params["token_emb"])  # structure preserved
    assert params["token_emb"]["q"].dtype == jnp.int8
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
