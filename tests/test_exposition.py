"""obs/exposition.py (ISSUE 7 tentpole): Prometheus text rendering,
the /metrics //healthz //vars HTTP server, staleness marking, the
disabled path, a live scrape DURING a CPU-mesh train run, and
tools/obs_top.py's parser/renderer against a real endpoint."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from code2vec_tpu.obs import (MetricsServer, Telemetry, Watchdog,
                              render_prometheus)
from code2vec_tpu.obs.health import HealthEngine, NonFiniteGauges
from code2vec_tpu.obs.alerts import AlertEngine, AlertRule
from tools.obs_top import labeled, parse_prometheus, scalar


def _get(port, path, timeout=5.0):
    """(status, body_text) — urllib raises on 4xx/5xx, which /healthz
    legitimately returns."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


@pytest.fixture
def populated():
    t = Telemetry.memory("expo").make_threadsafe()
    t.count("train/steps", 7)
    t.count("train/examples", 224)
    t.gauge("serve/queue_depth", 3, emit=False)
    t.gauge("train/loss", 1.25, emit=False)
    for ms in (1.0, 2.0, 3.0, 4.0, 5.0):
        t.record_ms("train/step_ms", ms)
    return t


# ---- rendering ----

def test_render_counters_gauges_summaries(populated):
    text = render_prometheus(populated)
    m = parse_prometheus(text)
    # names sanitized: train/step_ms -> train_step_ms
    assert scalar(m, "train_steps") == 7
    assert scalar(m, "serve_queue_depth") == 3
    # nearest-rank percentiles (TimerStat.summary's exact figures)
    assert labeled(m, "train_step_ms", quantile="0.5") == 3.0
    assert labeled(m, "train_step_ms", quantile="0.99") == 5.0
    assert scalar(m, "train_step_ms_sum") == 15.0
    assert scalar(m, "train_step_ms_count") == 5
    assert "# TYPE train_steps counter" in text
    assert "# TYPE serve_queue_depth gauge" in text
    assert "# TYPE train_step_ms summary" in text


def test_render_marks_gauge_age(populated):
    text = render_prometheus(populated)
    m = parse_prometheus(text)
    age = labeled(m, "gauge_age_seconds", gauge="serve_queue_depth")
    assert age is not None and 0.0 <= age < 60.0


def test_render_nan_gauge(populated):
    populated.gauge("train/loss", float("nan"), emit=False)
    m = parse_prometheus(render_prometheus(populated))
    v = scalar(m, "train_loss")
    assert v != v  # NaN round-trips through the text format


def test_render_watchdog_health_alert_families(populated):
    clock = [0.0]
    wd = Watchdog(populated, stall_s=5.0, clock=lambda: clock[0])
    hb = wd.register("infeed_producer")
    hb.beat()
    health = HealthEngine.create(populated)
    health.add(NonFiniteGauges(("train/loss",), name="loss_nonfinite"))
    alerts = AlertEngine.create(
        populated, mode="warn",
        rules=[AlertRule("loss_nonfinite",
                         metric="health/loss_nonfinite",
                         op=">=", value=1.0)])
    health.add_listener(alerts.evaluate)
    health.check_now()
    m = parse_prometheus(render_prometheus(populated, wd, health,
                                           alerts))
    assert labeled(m, "component_stalled",
                   component="infeed_producer") == 0
    assert labeled(m, "alert_active", rule="loss_nonfinite") == 0
    assert labeled(m, "health_status", monitor="loss_nonfinite") == 0
    # stall + NaN flip both families
    clock[0] = 10.0
    populated.gauge("train/loss", float("nan"), emit=False)
    health.check_now()
    m = parse_prometheus(render_prometheus(populated, wd, health,
                                           alerts))
    assert labeled(m, "component_stalled",
                   component="infeed_producer") == 1
    assert labeled(m, "alert_active", rule="loss_nonfinite") == 1
    assert labeled(m, "health_status", monitor="loss_nonfinite") == 1


# ---- the HTTP server ----

@pytest.fixture
def served(populated):
    clock = [0.0]
    wd = Watchdog(populated, stall_s=5.0, clock=lambda: clock[0])
    hb = wd.register("infeed_producer")
    hb.beat()
    srv = MetricsServer(populated, port=0, watchdog=wd).start()
    yield srv, populated, wd, hb, clock
    srv.stop()


def test_http_metrics_endpoint(served):
    srv, tele, *_ = served
    status, body = _get(srv.bound_port, "/metrics")
    assert status == 200
    assert scalar(parse_prometheus(body), "train_steps") == 7


def test_http_vars_endpoint(served):
    srv, *_ = served
    status, body = _get(srv.bound_port, "/vars")
    assert status == 200
    v = json.loads(body)
    assert v["counters"]["train/steps"] == 7
    assert "train/step_ms" in v["timers"]
    assert v["gauge_age_s"]["serve/queue_depth"] >= 0
    assert v["components"]["infeed_producer"]["stalled"] is False


def test_http_404(served):
    srv, *_ = served
    status, _ = _get(srv.bound_port, "/nope")
    assert status == 404


def test_healthz_flips_on_injected_infeed_stall(served):
    """The acceptance check: /healthz gates on the watchdog heartbeat
    table, recomputed at request time — an infeed producer that stops
    beating flips readiness to 503, and the next beat flips it back."""
    srv, _tele, _wd, hb, clock = served
    status, body = _get(srv.bound_port, "/healthz")
    assert status == 200 and json.loads(body)["status"] == "ok"
    clock[0] = 10.0  # 10s of silence vs a 5s deadline
    status, body = _get(srv.bound_port, "/healthz")
    v = json.loads(body)
    assert status == 503
    assert v["status"] == "unhealthy"
    assert v["stalled"] == ["infeed_producer"]
    hb.beat()  # progress resumes -> ready again, no operator reset
    status, _ = _get(srv.bound_port, "/healthz")
    assert status == 200


def test_healthz_gates_on_page_severity_alert(populated):
    alerts = AlertEngine.create(
        populated, mode="warn",
        rules=[AlertRule("bad", metric="g", op=">", value=0.0,
                         severity="page"),
               AlertRule("meh", metric="g", op=">", value=0.0,
                         severity="ticket")])
    srv = MetricsServer(populated, port=0, alerts=alerts).start()
    try:
        assert _get(srv.bound_port, "/healthz")[0] == 200
        populated.gauge("g", 1.0, emit=False)
        alerts.evaluate(now=time.monotonic())
        status, body = _get(srv.bound_port, "/healthz")
        assert status == 503
        # only the page-severity rule gates readiness
        assert json.loads(body)["alerts_firing"] == ["bad"]
    finally:
        srv.stop()


def test_disabled_paths_share_singleton():
    assert MetricsServer.create(None, port=9100) \
        is MetricsServer.disabled()
    assert MetricsServer.create(Telemetry.disabled(), port=9100) \
        is MetricsServer.disabled()
    t = Telemetry.memory("x")
    assert MetricsServer.create(t, port=0) is MetricsServer.disabled()
    off = MetricsServer.disabled()
    assert off.start() is off
    off.stop()  # no-op, no bind


def test_stop_releases_port(populated):
    srv = MetricsServer(populated, port=0).start()
    port = srv.bound_port
    srv.stop()
    with pytest.raises((urllib.error.URLError, OSError)):
        _get(port, "/metrics", timeout=0.5)


# ---- obs_top against a real endpoint ----

def test_obs_top_once_renders_live_table(populated, capsys):
    populated.gauge("train/max_contexts", 16, emit=False)
    # the health engine's derived gauge (round 13): obs_top surfaces
    # it as the per-host "opt eff" column
    populated.gauge("health/opt_efficiency", 0.913, emit=False)
    srv = MetricsServer(populated, port=0).start()

    # bump the counters between obs_top's two polls so rates are real
    def bump():
        time.sleep(0.15)
        populated.count("train/steps", 5)
        populated.count("train/examples", 160)
    t = threading.Thread(target=bump, daemon=True)
    t.start()
    try:
        from tools.obs_top import main as obs_top_main
        rc = obs_top_main([f"127.0.0.1:{srv.bound_port}", "--once",
                           "--interval", "0.4"])
        t.join()
        assert rc == 0
        out = capsys.readouterr().out
        assert f"127.0.0.1:{srv.bound_port}" in out
        assert "pc/s (sum)" in out
        assert "1/1 hosts up" in out
        # 160 ex over ~0.4s x 16 contexts: a positive live pc/s figure
        assert "| ok |" in out
        assert "opt eff" in out and "0.913" in out
    finally:
        srv.stop()


def test_obs_top_reports_down_host(capsys):
    from tools.obs_top import main as obs_top_main
    rc = obs_top_main(["127.0.0.1:1", "--once", "--interval", "0.05"])
    assert rc == 0
    assert "DOWN" in capsys.readouterr().out


# ---- acceptance: live scrape DURING a CPU-mesh train run ----

def test_scrape_during_train_run(tmp_path):
    """`--metrics_port` on a real (tiny) train run: /metrics serves
    live counters/gauges/timer summaries in Prometheus text format and
    /healthz answers while steps are still executing. The run is held
    open at step 5 by a gate in the train step so the scrape provably
    happens mid-run, not after."""
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.helpers import build_tiny_dataset
    from tests.test_model import tiny_config

    d = str(tmp_path / "ds")
    os.makedirs(d)
    prefix = build_tiny_dataset(d, n_train=96, n_val=8, n_test=8,
                                max_contexts=16)
    tdir = os.path.join(d, "tele")
    cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=4, TELEMETRY_DIR=tdir,
                      METRICS_PORT=0)
    # port 0 through config means "off"; bind ephemeral by letting the
    # server choose, so construct the config with a free-ish port: use
    # a socket probe
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cfg.METRICS_PORT = port
    model = Code2VecModel(cfg)

    orig_step = model._train_step
    gate = threading.Event()
    calls = []

    def gated_step(params, opt_state, batch, rng):
        calls.append(1)
        if len(calls) == 5:
            gate.wait(timeout=60)
        return orig_step(params, opt_state, batch, rng)

    model._train_step = gated_step
    err = []

    def run():
        try:
            model.train()
        except BaseException as e:  # surfaces in the main thread
            err.append(e)

    trainer = threading.Thread(target=run, daemon=True)
    trainer.start()
    try:
        deadline = time.time() + 120
        metrics = None
        while time.time() < deadline:
            try:
                status, body = _get(port, "/metrics", timeout=1.0)
            except (urllib.error.URLError, OSError):
                time.sleep(0.05)
                continue
            m = parse_prometheus(body)
            if (scalar(m, "train_steps") or 0) >= 4:
                metrics = m
                break
            time.sleep(0.05)
        assert metrics is not None, "never scraped a mid-run /metrics"
        # live counters, gauges and timer summaries, mid-run
        assert scalar(metrics, "train_steps") >= 4
        assert scalar(metrics, "train_examples") > 0
        assert scalar(metrics, "train_loss") is not None
        assert scalar(metrics, "train_max_contexts") == 16
        assert labeled(metrics, "train_step_ms",
                       quantile="0.5") is not None
        assert scalar(metrics, "train_step_ms_count") >= 4
        status, body = _get(port, "/healthz", timeout=2.0)
        assert status == 200 and json.loads(body)["status"] == "ok"
        status, body = _get(port, "/vars", timeout=2.0)
        assert json.loads(body)["counters"]["train/steps"] >= 4
    finally:
        gate.set()
        trainer.join(timeout=120)
    assert not err, f"train thread failed: {err}"
    assert not trainer.is_alive()
    # the run completed: server torn down with the loop
    with pytest.raises((urllib.error.URLError, OSError)):
        _get(port, "/metrics", timeout=0.5)
