"""Pallas fused attention-pool vs the XLA reference implementation
(interpret mode on the CPU test platform)."""

import jax.numpy as jnp
import numpy as np

from code2vec_tpu.ops.attention import attention_pool
from code2vec_tpu.ops.pallas_attention import attention_pool_pallas


def test_pallas_attention_matches_xla():
    rng = np.random.default_rng(0)
    B, C, D = 16, 12, 24
    contexts = rng.normal(size=(B, C, D)).astype(np.float32)
    transform = (rng.normal(size=(D, D)) * 0.2).astype(np.float32)
    attention = rng.normal(size=(D,)).astype(np.float32)
    mask = (rng.random((B, C)) > 0.3).astype(np.float32)
    mask[0] = 1.0
    mask[1] = 0.0  # fully padded example

    code_ref, attn_ref = attention_pool(
        jnp.asarray(contexts), jnp.asarray(transform),
        jnp.asarray(attention), jnp.asarray(mask))
    code_pl, attn_pl = attention_pool_pallas(
        jnp.asarray(contexts), jnp.asarray(transform),
        jnp.asarray(attention), jnp.asarray(mask), interpret=True)

    np.testing.assert_allclose(np.asarray(attn_pl), np.asarray(attn_ref),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(code_pl), np.asarray(code_ref),
                               atol=1e-5)
    assert np.asarray(attn_pl)[1].sum() == 0.0
