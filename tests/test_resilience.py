"""Resilience layer (ISSUE 10): deterministic fault injection, the
unified retry policy, checkpoint integrity (checksums / verify /
quarantine), the ENOSPC-mid-async-save contract, extractor-pool
restart-in-place, and the restart supervisor's policy logic (with
real—but trivial—child processes)."""

import errno
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.resilience import FaultInjected, RetryPolicy, faults
from code2vec_tpu.resilience import retry as retry_mod
from code2vec_tpu.training import checkpoint as ckpt
from code2vec_tpu.vocab.vocabularies import Code2VecVocabs, Vocab, \
    VocabType


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _tiny_vocabs():
    return Code2VecVocabs(Vocab(VocabType.Token, ["a", "b"]),
                          Vocab(VocabType.Path, ["1"]),
                          Vocab(VocabType.Target, ["t"]))


def _tiny_dims():
    return ModelDims(token_vocab_size=4, path_vocab_size=3,
                     target_vocab_size=3, embeddings_size=4,
                     max_contexts=4, dropout_keep_rate=1.0)


def _tiny_state(step=1, fill=0.0):
    return {"params": {"w": np.full((3, 4), fill, np.float32)},
            "step": step}


def _flip_byte(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


def _state_files(step_dir):
    out = []
    for base, _d, fs in os.walk(os.path.join(step_dir, "state")):
        out += [os.path.join(base, f) for f in fs]
    return out


# ---------------------------------------------------------------- faults

def test_fault_at_and_times_are_deterministic():
    faults.install({"seed": 0, "sites": {
        "s": {"action": "raise", "at": 3, "times": 2}}})
    fired = []
    for i in range(1, 7):
        try:
            faults.fire("s")
            fired.append(False)
        except FaultInjected:
            fired.append(True)
    # hits 3 and 4 fire (times=2), nothing before or after
    assert fired == [False, False, True, True, False, False]
    assert faults.stats()["s"] == {"hits": 6, "fired": 2}


def test_fault_prob_stream_is_seeded():
    def firing_hits(seed):
        faults.install({"seed": seed, "sites": {
            "p": {"action": "raise", "prob": 0.3, "times": -1}}},
            log=lambda _m: None)
        out = []
        for i in range(40):
            try:
                faults.fire("p")
            except FaultInjected:
                out.append(i)
        return out

    a, b, c = firing_hits(7), firing_hits(7), firing_hits(8)
    assert a == b               # same seed -> same failure schedule
    assert a != c               # different seed -> different schedule
    assert 2 < len(a) < 25      # ~30% of 40


def test_fault_marker_is_a_cross_restart_once_latch(tmp_path):
    marker = str(tmp_path / "once")
    spec = {"seed": 0, "sites": {
        "k": {"action": "raise", "at": 1, "marker": marker}}}
    faults.install(spec)
    with pytest.raises(FaultInjected):
        faults.fire("k")
    assert os.path.exists(marker)
    # a "restarted process" (fresh registry, same spec) stays disarmed
    faults.install(spec)
    for _ in range(3):
        faults.fire("k")
    assert faults.stats()["k"]["fired"] == 0


def test_fault_io_error_with_partial_leaves_torn_marker(tmp_path):
    faults.install({"seed": 0, "sites": {
        "ckpt/write": {"action": "io_error", "errno": "ENOSPC",
                       "partial": True}}})
    step_dir = str(tmp_path / "step_9")
    with pytest.raises(OSError) as ei:
        faults.fire("ckpt/write", path=step_dir)
    assert ei.value.errno == errno.ENOSPC
    # the torn orbax temp marker exists, the committed `state` does not
    assert os.path.isdir(os.path.join(step_dir,
                                      "state.orbax-checkpoint-tmp"))
    assert not os.path.exists(os.path.join(step_dir, "state"))


def test_disarmed_sites_are_null_handles():
    p = faults.point("train/kill")
    assert not p.armed
    p.fire()            # no-op
    assert not p.hit()
    faults.fire("anything")  # no registry: one None check
    # armed registry, unconfigured site -> still the null handle
    faults.install({"seed": 0, "sites": {"other": {"action": "raise"}}},
                   log=lambda _m: None)
    assert not faults.point("train/kill").armed


def test_fault_spec_validation():
    with pytest.raises(ValueError, match="action"):
        faults.install({"sites": {"s": {"action": "explode"}}})
    with pytest.raises(ValueError, match="unknown spec"):
        faults.install({"sites": {"s": {"action": "raise",
                                        "tyop": 1}}})
    with pytest.raises(ValueError, match="sites"):
        faults.install({"seed": 3})


# ----------------------------------------------------------------- retry

def test_retry_succeeds_within_budget_and_records():
    sleeps = []
    pol = RetryPolicy("t", max_attempts=3, base_delay_s=0.1, seed=0,
                      sleep=sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky) == "ok"
    assert calls["n"] == 3 and len(sleeps) == 2
    assert retry_mod.stats()["t"]["retries"] >= 2


def test_retry_exhausted_reraises_original():
    pol = RetryPolicy("x", max_attempts=2, base_delay_s=0,
                      sleep=lambda _s: None)
    with pytest.raises(ValueError, match="boom"):
        pol.call(lambda: (_ for _ in ()).throw(ValueError("boom")))
    assert retry_mod.stats()["x"]["exhausted"] >= 1


def test_retry_giveup_skips_backoff_entirely():
    sleeps = []
    pol = RetryPolicy("g", max_attempts=5, base_delay_s=1.0,
                      sleep=sleeps.append, retry_on=(OSError,),
                      giveup=lambda e: e.errno == errno.ENOSPC)
    with pytest.raises(OSError):
        pol.call(lambda: (_ for _ in ()).throw(
            OSError(errno.ENOSPC, "full")))
    assert sleeps == []


def test_retry_backoff_curve_is_jittered_exponential():
    pol = RetryPolicy("b", max_attempts=9, base_delay_s=0.1,
                      max_delay_s=1.0, multiplier=2.0, jitter=0.5,
                      seed=0)
    for attempt, ceiling in ((1, 0.1), (2, 0.2), (3, 0.4), (6, 1.0)):
        d = pol.delay_s(attempt)
        assert ceiling * 0.5 <= d <= ceiling, (attempt, d)
    # seeded stream is reproducible
    a = RetryPolicy("b2", seed=3).delay_s(2)
    b = RetryPolicy("b2", seed=3).delay_s(2)
    assert a == b


def test_retry_telemetry_counters_and_events():
    from code2vec_tpu.obs import Telemetry
    tele = Telemetry.memory("t")
    retry_mod.set_telemetry(tele)
    try:
        pol = RetryPolicy("tele", max_attempts=2, base_delay_s=0,
                          sleep=lambda _s: None)
        calls = {"n": 0}

        def once():
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("x")
            return 1

        assert pol.call(once) == 1
        assert tele.counters["resilience/retry"] == 1
    finally:
        retry_mod.set_telemetry(None)


# ----------------------------------- checkpoint integrity + quarantine

def test_checksums_written_and_verify_roundtrip(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, _tiny_state(1), 1, _tiny_vocabs(),
                         _tiny_dims())
    man = os.path.join(d, "step_1", ckpt.CHECKSUMS_NAME)
    assert os.path.exists(man)
    with open(man) as f:
        payload = json.load(f)
    assert payload["step"] == 1 and payload["files"]
    assert ckpt.verify_step(d, 1) is True
    # no-checksums step (pre-integrity checkpoint): None, not False
    os.remove(man)
    assert ckpt.verify_step(d, 1) is None


def test_bit_flip_detected_quarantined_and_fallback(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, _tiny_state(1, fill=1.0), 1,
                         _tiny_vocabs(), _tiny_dims())
    ckpt.save_checkpoint(d, _tiny_state(2, fill=2.0), 2,
                         _tiny_vocabs(), _tiny_dims())
    _flip_byte(max(_state_files(os.path.join(d, "step_2")),
                   key=os.path.getsize))
    assert ckpt.verify_step(d, 2) is False
    good, quarantined = ckpt.verify_and_resolve(d)
    assert good == 1 and len(quarantined) == 1
    assert os.path.isdir(os.path.join(d, "quarantine", "step_2"))
    assert ckpt.latest_step(d) == 1  # quarantine is invisible
    restored = ckpt.load_checkpoint(d, _tiny_state(0))
    assert int(np.asarray(restored["step"])) == 1
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.full((3, 4), 1.0, np.float32))


def test_load_checkpoint_quarantines_and_falls_back(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, _tiny_state(1, fill=3.0), 1,
                         _tiny_vocabs(), _tiny_dims())
    ckpt.save_checkpoint(d, _tiny_state(2, fill=4.0), 2,
                         _tiny_vocabs(), _tiny_dims())
    _flip_byte(max(_state_files(os.path.join(d, "step_2")),
                   key=os.path.getsize))
    restored = ckpt.load_checkpoint(d, _tiny_state(0))
    assert int(np.asarray(restored["step"])) == 1
    assert os.path.isdir(os.path.join(d, "quarantine", "step_2"))
    # an EXPLICITLY requested corrupt step raises instead of
    # substituting different bytes
    ckpt.save_checkpoint(d, _tiny_state(5, fill=5.0), 5,
                         _tiny_vocabs(), _tiny_dims())
    _flip_byte(max(_state_files(os.path.join(d, "step_5")),
                   key=os.path.getsize))
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.load_checkpoint(d, _tiny_state(0), step=5)


def test_transient_ckpt_io_error_is_retried(tmp_path):
    # EIO twice, then clean: the write succeeds through the policy
    faults.install({"seed": 0, "sites": {
        "ckpt/write": {"action": "io_error", "errno": "EIO",
                       "times": 2}}}, log=lambda _m: None)
    d = str(tmp_path)
    ckpt.save_checkpoint(d, _tiny_state(1), 1, _tiny_vocabs(),
                         _tiny_dims())
    assert ckpt.latest_step(d) == 1
    assert faults.stats()["ckpt/write"]["fired"] == 2


# ------------------------------------- ENOSPC mid-async-save satellite

def test_enospc_mid_async_save_sticky_then_recovers(tmp_path):
    """The satellite contract: ENOSPC during a background save (a)
    surfaces as a sticky error at the commit barrier, (b) leaves the
    partial step dir invisible to latest_step, and (c) the next save
    on a recovered disk succeeds."""
    d = str(tmp_path)
    ckpt.save_checkpoint(d, _tiny_state(1), 1, _tiny_vocabs(),
                         _tiny_dims())
    faults.install({"seed": 0, "sites": {
        "ckpt/write": {"action": "io_error", "errno": "ENOSPC",
                       "partial": True}}}, log=lambda _m: None)
    writer = ckpt.AsyncCheckpointWriter()
    writer.submit(d, _tiny_state(2), 2, _tiny_vocabs(), _tiny_dims())
    with pytest.raises(OSError) as ei:
        writer.wait()  # (a) sticky at the barrier
    assert ei.value.errno == errno.ENOSPC
    # (b) the torn step_2 exists but never counts
    assert os.path.isdir(os.path.join(d, "step_2"))
    assert ckpt.latest_step(d) == 1
    # (c) disk "recovers": the SAME writer's next save commits
    faults.clear()
    writer.submit(d, _tiny_state(3), 3, _tiny_vocabs(), _tiny_dims())
    writer.wait()
    writer.close()
    assert ckpt.latest_step(d) == 3
    assert ckpt.verify_step(d, 3) is True


# -------------------------------------------------- infeed failpoint

def test_infeed_produce_fault_surfaces_at_consumer():
    from code2vec_tpu.data.prefetch import build_train_infeed
    faults.install({"seed": 0, "sites": {
        "infeed/produce": {"action": "raise", "at": 3}}},
        log=lambda _m: None)
    infeed = build_train_infeed(
        [1, 2, 3, 4, 5], chunk=1, depth=2, mesh=None,
        host_arrays_fn=lambda b: (b,), device_batch_fn=lambda b: b,
        log=lambda _m: None)
    seen = []
    with pytest.raises(FaultInjected):
        for dev, host in infeed:
            seen.append(host)
    assert seen == [1, 2]  # batches before the injected failure landed


# ------------------------------------- extractor pool restart-in-place

@pytest.fixture
def py_source(tmp_path):
    p = tmp_path / "demo.py"
    p.write_text("def add_one(x):\n    y = x + 1\n    return y\n")
    return str(p)


def _pool(telemetry=None):
    from code2vec_tpu.config import Config
    from code2vec_tpu.serving.extractor import ExtractorPool
    cfg = Config(SERVE_EXTRACT_WORKERS=2)
    cfg.train_data_path = "unused"
    return ExtractorPool(cfg, telemetry=telemetry, language="python")


def test_extractor_pool_restarts_in_place_after_crash(py_source):
    """ISSUE 10 satellite: a worker crash restarts the pool instead of
    failing every subsequent request; requests racing the restart shed
    with ServerOverloaded; the next request succeeds."""
    from code2vec_tpu.obs import Telemetry
    from code2vec_tpu.serving.batcher import ServerOverloaded
    from code2vec_tpu.serving.extractor import Extractor
    tele = Telemetry.memory("serve").make_threadsafe()
    pool = _pool(telemetry=tele)
    names, lines = pool.extract_paths(py_source)
    assert names == ["add|one"]

    # hold the rebuild open so the shed window is observable: the
    # restart thread's preflight blocks until we release it
    import threading
    gate = threading.Event()
    real_preflight = Extractor.preflight

    def gated_preflight(self):
        gate.wait(timeout=10)
        return real_preflight(self)

    faults.install({"seed": 0, "sites": {
        "serve/extract": {"action": "raise", "at": 1}}},
        log=lambda _m: None)
    try:
        Extractor.preflight = gated_preflight
        with pytest.raises(FaultInjected):
            pool.extract_paths(py_source)  # the crash itself re-raises
        deadline = time.monotonic() + 5
        while not pool.restarting and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool.restarting
        with pytest.raises(ServerOverloaded):
            pool.extract_paths(py_source)  # shed while restarting
    finally:
        Extractor.preflight = real_preflight
        gate.set()
    deadline = time.monotonic() + 5
    while pool.restarting and time.monotonic() < deadline:
        time.sleep(0.01)
    names, _ = pool.extract_paths(py_source)  # restarted pool serves
    assert names == ["add|one"]
    assert tele.counters["serve/extractor_restart"] == 1
    assert tele.counters["serve/shed"] >= 1
    pool.close()


def test_extractor_pool_goes_dead_when_rebuild_exhausts(py_source,
                                                        monkeypatch):
    from code2vec_tpu.serving.extractor import Extractor, ExtractorError
    pool = _pool()
    # every rebuild preflight fails: the retry budget exhausts and the
    # pool goes dead with the terminal error, not a hang
    monkeypatch.setattr(
        Extractor, "preflight",
        lambda self: (_ for _ in ()).throw(
            ExtractorError("binary gone; build_extractor.sh")))
    faults.install({"seed": 0, "sites": {
        "serve/extract": {"action": "raise", "at": 1}}},
        log=lambda _m: None)
    with pytest.raises(FaultInjected):
        pool.extract_paths(py_source)
    deadline = time.monotonic() + 10
    while pool.restarting and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(ExtractorError, match="build_extractor.sh"):
        pool.extract_paths(py_source)
    pool.close()


def test_per_input_failure_does_not_restart_pool(tmp_path):
    from code2vec_tpu.serving.extractor import ExtractorError
    pool = _pool()
    bad = tmp_path / "empty.py"
    bad.write_text("# no functions here\n")
    with pytest.raises(ExtractorError, match="no methods"):
        pool.extract_paths(str(bad))
    assert not pool.restarting  # ExtractorError is per-input, no crash
    pool.close()


# ------------------------------------------------------- supervisor

def _sh_spawn(script_for_attempt, out_dir, record=None):
    """Spawn fn over trivial python children; script_for_attempt maps
    the attempt number to per-process python source. `record` (a list)
    captures every (attempt, proc_id, cohort_size) spawn — the resize
    tests assert the re-formed cohort's actual shape from it."""
    def spawn(attempt, proc_id, port, cohort_size=None):
        if record is not None:
            record.append((attempt, proc_id, cohort_size))
        return subprocess.Popen(
            [sys.executable, "-c",
             script_for_attempt(attempt, proc_id, port)])
    return spawn


def _supervisor(spawn, **kw):
    from code2vec_tpu.obs import Telemetry
    from code2vec_tpu.training.supervisor import Supervisor
    kw.setdefault("backoff", RetryPolicy("s", max_attempts=1,
                                         base_delay_s=0.01, seed=0))
    kw.setdefault("poll_s", 0.02)
    kw.setdefault("peer_grace_s", 0.3)
    kw.setdefault("telemetry", Telemetry.memory("supervisor"))
    return Supervisor(spawn, **kw)


def test_supervisor_restarts_until_success():
    sup = _supervisor(
        _sh_spawn(lambda a, p, port:
                  f"import sys; sys.exit(0 if {a} >= 2 else 1)", None),
        max_restarts=3)
    assert sup.run() == 0
    assert sup.restarts == 2
    assert sup.telemetry.gauges["supervisor/restarts"] == 2
    # the restart alert fired exactly once (edge-triggered)
    assert sup.telemetry.counters.get("alerts/fired", 0) == 1


def test_supervisor_budget_exhaustion_pages_and_raises():
    from code2vec_tpu.training.supervisor import RestartBudgetExceeded
    sup = _supervisor(
        _sh_spawn(lambda a, p, port: "import sys; sys.exit(1)", None),
        max_restarts=1)
    with pytest.raises(RestartBudgetExceeded):
        sup.run()
    assert sup.telemetry.gauges["supervisor/budget_exhausted"] == 1
    table = {r["rule"]: r["state"]
             for r in sup.alerts.status_table()}
    assert table["restart_budget_exhausted"] == "firing"


def test_supervisor_dead_peer_reaps_and_relaunches_cohort():
    """One member dies, the survivor would run 30s more: the grace
    window expires, the survivor is killed, and the NEXT attempt's
    whole cohort (exit 0) ends the run — coherent relaunch."""
    def script(attempt, proc_id, port):
        if attempt == 0 and proc_id == 1:
            return "import sys; sys.exit(9)"
        if attempt == 0:
            return "import time; time.sleep(30)"
        return f"import sys; sys.exit(0)  # port {port}"

    t0 = time.monotonic()
    sup = _supervisor(_sh_spawn(script, None), num_procs=2,
                      max_restarts=2)
    assert sup.run() == 0
    assert sup.restarts == 1
    assert time.monotonic() - t0 < 20  # never waited out the sleeper


def test_supervisor_shrink_reforms_cohort_at_n_minus_1():
    """ISSUE 13 tentpole (unit half of tools/chaos.py kill_resize):
    a dead peer under resize_policy='shrink' RE-FORMS the cohort at
    N−1 — the next attempt spawns one process, not two — recorded as a
    resize (never a full relaunch), counted, and escalated through the
    alert engine as the warn-tier `cohort_resized` ticket."""
    def script(attempt, proc_id, port):
        if attempt == 0 and proc_id == 1:
            return "import sys; sys.exit(9)"
        if attempt == 0:
            return "import time; time.sleep(30)"
        return "import sys; sys.exit(0)"

    spawns = []
    sup = _supervisor(_sh_spawn(script, None, record=spawns),
                      num_procs=2, max_restarts=2,
                      resize_policy="shrink")
    assert sup.run() == 0
    assert sup.restarts == 1
    assert sup.resizes == [(2, 1)]
    assert sup.full_relaunches == 0
    assert sup.cur_procs == 1
    # attempt 0 spawned a 2-cohort; attempt 1 re-formed at exactly one
    assert [(a, p, n) for a, p, n in spawns if a == 0] == \
        [(0, 0, 2), (0, 1, 2)]
    assert [(a, p, n) for a, p, n in spawns if a == 1] == [(1, 0, 1)]
    assert sup.telemetry.counters["resilience/resize"] == 1
    assert sup.telemetry.gauges["supervisor/cohort_size"] == 1
    assert sup.telemetry.gauges["supervisor/cohort_target"] == 2
    table = {r["rule"]: r for r in sup.alerts.status_table()}
    assert table["cohort_resized"]["state"] == "firing"
    assert table["cohort_resized"]["severity"] == "ticket"


def test_supervisor_shrink_floors_at_min_procs():
    """min_procs is the shrink floor: a cohort already at the floor
    relaunches at the same size after a peer death (a full relaunch,
    counted as such)."""
    def script(attempt, proc_id, port):
        if attempt == 0 and proc_id == 1:
            return "import sys; sys.exit(1)"
        if attempt == 0:
            return "import time; time.sleep(30)"
        return "import sys; sys.exit(0)"

    spawns = []
    sup = _supervisor(_sh_spawn(script, None, record=spawns),
                      num_procs=2, max_restarts=2,
                      resize_policy="shrink", min_procs=2)
    assert sup.run() == 0
    assert sup.resizes == []
    assert sup.full_relaunches == 1
    assert all(n == 2 for _a, _p, n in spawns)


def test_supervisor_grows_back_when_replacement_available():
    """Grow-back: once a replacement is configured and available, the
    next re-form returns toward the configured target size N."""
    replacements = [False, True]  # none at first death, one later

    def script(attempt, proc_id, port):
        if attempt == 0:  # one peer of the 2-cohort dies
            return ("import sys; sys.exit(1)" if proc_id == 1
                    else "import time; time.sleep(30)")
        if attempt == 1:  # the shrunk 1-cohort's only member dies
            return "import sys; sys.exit(1)"
        return "import sys; sys.exit(0)"

    spawns = []
    sup = _supervisor(
        _sh_spawn(script, None, record=spawns), num_procs=2,
        max_restarts=3, resize_policy="shrink",
        replacement_fn=lambda: replacements.pop(0)
        if replacements else False)
    assert sup.run() == 0
    # death at 2 -> shrink to 1 (no replacement); death at 1 -> floor
    # holds, replacement arrives -> grow back to 2; 2-cohort finishes
    assert sup.resizes == [(2, 1), (1, 2)]
    assert [n for _a, _p, n in spawns] == [2, 2, 1, 2, 2]


def test_supervisor_systemic_failure_keeps_full_size():
    """EVERY member of a multi-process cohort exiting nonzero together
    is systemic (the same bad flag killing all of them identically),
    not peer loss: shrink policy keeps the size — relaunching
    ever-smaller equally-doomed cohorts helps nobody."""
    spawns = []

    def spawn(attempt, proc_id, port, cohort_size=None):
        spawns.append((attempt, proc_id, cohort_size))
        p = subprocess.Popen(
            [sys.executable, "-c",
             "import sys; sys.exit(2)" if attempt == 0
             else "import sys; sys.exit(0)"])
        p.wait()  # already exited at the supervisor's FIRST poll —
        #           both deaths land in one sweep, deterministically
        return p

    sup = _supervisor(spawn, num_procs=2, max_restarts=2,
                      resize_policy="shrink")
    assert sup.run() == 0
    assert sup.resizes == []
    assert sup.full_relaunches == 1
    assert all(n == 2 for _a, _p, n in spawns)


def test_supervisor_timeout_relaunches_at_full_size():
    """A whole-cohort hang (attempt timeout) is NOT peer death: shrink
    policy keeps the size — every member wedging is no evidence any
    one of them is bad."""
    def script(attempt, proc_id, port):
        return ("import time; time.sleep(30)" if attempt == 0
                else "import sys; sys.exit(0)")

    spawns = []
    sup = _supervisor(_sh_spawn(script, None, record=spawns),
                      num_procs=2, max_restarts=2,
                      resize_policy="shrink", attempt_timeout_s=0.5)
    assert sup.run() == 0
    assert sup.resizes == []
    assert sup.full_relaunches == 1
    assert all(n == 2 for _a, _p, n in spawns)


def test_cohort_topology_joins_watchdog_stall_dump(tmp_path):
    """ISSUE 13 satellite: the supervisor's live cohort topology
    (process set + target size) rides the watchdog's stall dump via
    `attach(cohort=...)` — the wedged-cohort postmortem answers 'who
    was in the mesh'."""
    import json as json_mod

    from code2vec_tpu.obs import Telemetry
    from code2vec_tpu.obs.watchdog import Watchdog
    clk = {"t": 0.0}
    tele = Telemetry.create(str(tmp_path / "tele"), component="sup")
    wd = Watchdog(tele, stall_s=1.0, clock=lambda: clk["t"])
    # the Supervisor(watchdog=) wiring (what tools/train_supervisor.py
    # does behind --watchdog_stall_s): attaches cohort_topology and
    # registers the supervise-loop heartbeat
    sup = _supervisor(
        _sh_spawn(lambda a, p, port: "import sys; sys.exit(0)", None),
        num_procs=2, resize_policy="shrink", watchdog=wd)
    assert wd._cohort is not None
    assert "supervisor_loop" in wd.status()
    topo = sup.cohort_topology()
    assert topo["target_procs"] == 2 and topo["cohort_size"] == 2
    assert topo["resize_policy"] == "shrink"
    # a completed run leaves the supervise-loop heartbeat idle — the
    # deadline must not apply to a supervisor with nothing to watch
    assert sup.run() == 0
    assert wd.status()["supervisor_loop"]["active"] is False

    hb = wd.register("cohort")
    hb.busy()
    clk["t"] = 5.0
    stalls = wd.check_now()
    tele.close()
    assert len(stalls) == 1
    dumps = list((tmp_path / "tele").glob("*/stall_dump_*.json"))
    assert dumps, "stall dump missing"
    bundle = json_mod.loads(dumps[0].read_text())
    assert bundle["cohort"]["target_procs"] == 2
    assert bundle["cohort"]["cohort_size"] == 2
    assert "live_pids" in bundle["cohort"]


def test_supervisor_verifies_and_quarantines_before_launch(tmp_path):
    """The corrupt-checkpoint contract's fast half (the full
    subprocess scenario is tools/chaos.py corrupt_checkpoint,
    slow-marked): a bit-flipped latest step is detected BEFORE launch,
    quarantined, the run resumes from the prior committed step, and an
    edge-triggered `alert` JSONL event is emitted through the
    engine."""
    from code2vec_tpu.obs import Telemetry
    d = str(tmp_path / "ckpt")
    ckpt.save_checkpoint(d, _tiny_state(1, fill=1.0), 1,
                         _tiny_vocabs(), _tiny_dims())
    ckpt.save_checkpoint(d, _tiny_state(2, fill=2.0), 2,
                         _tiny_vocabs(), _tiny_dims())
    _flip_byte(max(_state_files(os.path.join(d, "step_2")),
                   key=os.path.getsize))
    tele = Telemetry.create(str(tmp_path / "tele"),
                            component="supervisor")
    sup = _supervisor(
        _sh_spawn(lambda a, p, port: "import sys; sys.exit(0)", None),
        max_restarts=0, ckpt_dir=d, telemetry=tele)
    assert sup.run() == 0
    tele.close()
    assert sup.resumed_from_step == 1
    assert len(sup.quarantined) == 1
    assert sup.telemetry.gauges["resilience/ckpt_quarantined"] == 1
    table = {r["rule"]: r["state"] for r in sup.alerts.status_table()}
    assert table["checkpoint_quarantined"] == "firing"
    with open(os.path.join(tele.run_dir, "events.jsonl"),
              encoding="utf-8") as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    alerts = [e for e in events if e["kind"] == "alert"
              and e["rule"] == "checkpoint_quarantined"]
    assert [a["transition"] for a in alerts] == ["firing"]
    assert any(e["kind"] == "ckpt_quarantine" for e in events)


def test_train_supervisor_cli_appends_auto_resume(tmp_path, capsys):
    """The CLI wrapper: a --save child gets --auto_resume appended, a
    trivially-succeeding child yields exit 0."""
    from tools.train_supervisor import main
    marker = tmp_path / "ran"
    rc = main(["--max_restarts", "0", "--backoff_base_s", "0.01",
               "--out_dir", str(tmp_path / "logs"), "--",
               sys.executable, "-c",
               f"import sys, pathlib; "
               f"pathlib.Path(r'{marker}').write_text("
               f"' '.join(sys.argv)); sys.exit(0)",
               "--save", str(tmp_path / "ckpt")])
    assert rc == 0
    assert "--auto_resume" in marker.read_text()
    out = capsys.readouterr().out
    assert "appending it" in out


# ----------------------------------------- resume math (epoch offset)

def test_steps_per_epoch_matches_reader_alignment():
    from code2vec_tpu.data.reader import steps_per_epoch
    assert steps_per_epoch(96, 32) == 3
    assert steps_per_epoch(97, 32) == 4
    # H=2, 17 examples, B=8: hosts align at 2 (test_multihost's case)
    assert steps_per_epoch(17, 8, 2) == 2


def test_auto_resume_replays_cosine_trajectory_exactly(tmp_path):
    """Auto-resume parity is SCHEDULE-agnostic: under --auto_resume
    the LR horizon stays the ORIGINAL epochs x steps-per-epoch (no
    `+ restored_step` extension — that is fine-tune semantics), so a
    run resumed from its own epoch-1 checkpoint finishes with params
    bit-identical to the uninterrupted run even under cosine decay
    (review finding: the horizon used to double-count and skew every
    resumed step's LR)."""
    import shutil

    import jax
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.helpers import build_tiny_dataset
    from tests.test_model import tiny_config
    ds = tmp_path / "ds"
    ds.mkdir()
    prefix = build_tiny_dataset(str(ds), n_train=96, n_val=8, n_test=8,
                                max_contexts=8)

    def run(cfg):
        model = Code2VecModel(cfg)
        model.train()
        model.close_session()
        return model

    full_dir = str(tmp_path / "full")
    cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=2, SAVE_EVERY_EPOCHS=1,
                      LR_SCHEDULE="cosine", save_path=full_dir,
                      MAX_CONTEXTS=8)
    cfg.test_data_path = None
    oracle = run(cfg)
    spe = oracle.step_num // 2

    # reconstruct "killed after epoch 1": the oracle's OWN epoch-1
    # checkpoint + sidecars in a fresh dir
    resume_dir = str(tmp_path / "resumed")
    os.makedirs(resume_dir)
    shutil.copytree(os.path.join(full_dir, f"step_{spe}"),
                    os.path.join(resume_dir, f"step_{spe}"))
    for sidecar in ("manifest.json", "vocab.pkl"):
        shutil.copy(os.path.join(full_dir, sidecar),
                    os.path.join(resume_dir, sidecar))
    cfg2 = tiny_config(prefix, NUM_TRAIN_EPOCHS=2, SAVE_EVERY_EPOCHS=1,
                       LR_SCHEDULE="cosine", save_path=resume_dir,
                       AUTO_RESUME=True, load_path=resume_dir,
                       MAX_CONTEXTS=8)
    cfg2.test_data_path = None
    resumed = run(cfg2)
    assert resumed.step_num == oracle.step_num
    for key in oracle.params:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(oracle.params[key])),
            np.asarray(jax.device_get(resumed.params[key])), err_msg=key)


def test_reader_epoch_offset_replays_the_interrupted_stream(tmp_path):
    from code2vec_tpu.data.reader import open_reader
    from tests.helpers import build_tiny_dataset, load_tiny_vocabs
    prefix = build_tiny_dataset(str(tmp_path), n_train=48, n_val=8,
                                n_test=8, max_contexts=8)
    vocabs = load_tiny_vocabs(prefix)

    def epoch_batches(reader):
        return [b.target_index.copy() for b in reader]

    cold = open_reader(prefix + ".train.c2v", vocabs, 8, 16,
                       shuffle=True, seed=5)
    first, second = epoch_batches(cold), epoch_batches(cold)
    resumed = open_reader(prefix + ".train.c2v", vocabs, 8, 16,
                          shuffle=True, seed=5, epoch_offset=1)
    replay = epoch_batches(resumed)
    for a, b in zip(second, replay):
        np.testing.assert_array_equal(a, b)
    assert any(not np.array_equal(a, b)
               for a, b in zip(first, replay))
