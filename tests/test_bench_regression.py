"""tools/bench_regression.py (ISSUE 7 satellite): the mechanical
BENCH-trajectory gate, exercised on checked-in fixtures under
tests/bench_fixtures/ (ok/ = latest inside the noise band, regress/ =
latest 20% below the median) and on the repo's own real BENCH_r*.json
trajectory."""

import json
import os
import subprocess
import sys

import pytest

from tools.bench_regression import (check_metric, load_rounds, render,
                                    run)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "bench_fixtures")


def test_load_rounds_sorted_and_both_shapes():
    rounds = load_rounds(os.path.join(FIXTURES, "ok"))
    assert [r for r, _ in rounds] == [1, 2, 3, 4]
    # r04 is bench.py's BARE result object (no "parsed" wrapper)
    assert rounds[-1][1]["value"] == 96000.0


def test_ok_trajectory_passes():
    rc, rows = run(os.path.join(FIXTURES, "ok"),
                   ["value", "transformer_pc_per_sec",
                    "int8_pc_per_sec"],
                   band=0.05, window=5, min_history=2, strict=False)
    assert rc == 0
    by = {r["metric"]: r for r in rows}
    assert by["value"]["status"] == "ok"
    # latest 96000 vs median(100000, 102000, 98000) = 100000
    assert by["value"]["baseline"] == 100000.0
    assert by["value"]["ratio"] == pytest.approx(0.96)
    # int8 appears in only ONE prior round -> not gated, never a pass
    # by omission that reads as a verdict
    assert by["int8_pc_per_sec"]["status"] == "skip"


def test_regression_fails_nonzero():
    rc, rows = run(os.path.join(FIXTURES, "regress"),
                   ["value", "transformer_pc_per_sec"],
                   band=0.05, window=5, min_history=2, strict=False)
    assert rc == 1
    by = {r["metric"]: r for r in rows}
    assert by["value"]["status"] == "REGRESSION"
    assert by["transformer_pc_per_sec"]["status"] == "ok"
    assert "REGRESSION" in render(rows)


def test_band_floor_widens_with_noisy_history():
    # history spread (MAD-based) wider than the flag floor must win:
    # a historically jittery metric should not page on normal jitter
    noisy = [(1, 100.0), (2, 140.0), (3, 60.0), (4, 95.0)]
    row = check_metric("m", noisy, 5, 70.0, band_floor=0.05,
                       min_history=2)
    assert row["band"] > 0.05
    assert row["status"] == "ok"  # inside the widened band
    tight = [(1, 100.0), (2, 101.0), (3, 99.0)]
    row = check_metric("m", tight, 4, 70.0, band_floor=0.05,
                       min_history=2)
    assert row["status"] == "REGRESSION"


def test_insufficient_history_skips_then_strict_errors():
    rc, rows = run(os.path.join(FIXTURES, "ok"), ["value"],
                   band=0.05, window=5, min_history=10, strict=False)
    assert rc == 0 and rows[0]["status"] == "skip"
    rc, _rows = run(os.path.join(FIXTURES, "ok"), ["value"],
                    band=0.05, window=5, min_history=10, strict=True)
    assert rc == 2


def test_empty_dir_is_usage_error(tmp_path):
    rc, rows = run(str(tmp_path), ["value"], band=0.05, window=5,
                   min_history=2, strict=False)
    assert rc == 2 and rows == []


def test_cli_exit_codes_and_json():
    r = subprocess.run(
        [sys.executable, "tools/bench_regression.py", "--dir",
         os.path.join(FIXTURES, "regress"), "--metrics", "value",
         "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout + r.stderr
    rows = json.loads(r.stdout)
    assert rows[0]["status"] == "REGRESSION"
    r = subprocess.run(
        [sys.executable, "tools/bench_regression.py", "--dir",
         os.path.join(FIXTURES, "ok"), "--metrics", "value"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr


def test_mixed_schema_history_gates_each_round_on_its_own_fields():
    """ISSUE 8 satellite: rounds predating the round-13 `sparse_*`
    fields must not crash the gate — they drop out of the sparse
    metric's history (gating there starts once 2+ rounds report it)
    while `value` stays gated across the whole trajectory; booleans
    and non-numeric placeholders never enter a series."""
    rc, rows = run(os.path.join(FIXTURES, "mixed"),
                   ["value", "sparse_pc_per_sec", "sparse_update_ms",
                    "sparse_update_fused"],
                   band=0.05, window=5, min_history=2, strict=False)
    assert rc == 0
    by = {r["metric"]: r for r in rows}
    # value: gated over ALL five rounds
    assert by["value"]["status"] == "ok"
    assert by["value"]["history_rounds"] == [1, 2, 3, 4]
    # sparse_pc_per_sec: only r03/r04 form history (r01/r02 predate it)
    assert by["sparse_pc_per_sec"]["status"] == "ok"
    assert by["sparse_pc_per_sec"]["history_rounds"] == [3, 4]
    # latest carries a non-numeric placeholder -> skip, not a crash,
    # and the note names the real cause (key present, value unusable)
    assert by["sparse_update_ms"]["status"] == "skip"
    assert by["sparse_update_ms"]["note"] == "non-numeric in latest round"
    # booleans are flags, not gauges -> never gated
    assert by["sparse_update_fused"]["status"] == "skip"


def test_mixed_schema_latest_predates_metric_skips():
    """A metric the LATEST round doesn't report is a skip even when
    old rounds had it (r05 lacks nothing here, so gate a phantom)."""
    rc, rows = run(os.path.join(FIXTURES, "mixed"),
                   ["sparse_update_unique_rows"],
                   band=0.05, window=5, min_history=2, strict=False)
    assert rc == 0
    assert rows[0]["status"] == "skip"
    assert rows[0]["note"] == "absent from latest round"


def test_default_metrics_include_sparse_gate():
    from tools.bench_regression import DEFAULT_METRICS
    assert "sparse_pc_per_sec" in DEFAULT_METRICS


def test_repo_trajectory_is_loadable():
    """The real BENCH_r*.json history stays parseable by the gate (the
    driver runs it against exactly these files)."""
    rounds = load_rounds(REPO)
    assert len(rounds) >= 2
    assert all("value" in res for _r, res in rounds)


# ---- --kind multichip: the MULTICHIP_r*.json trajectory (round 14)


def test_multichip_ok_trajectory_passes():
    """r01 is a seed-shaped failure record ({rc, ok, tail} — no
    metrics): skipped, never fatal; r02-r04 gate scaling_efficiency
    and multi_pc_per_sec with the latest inside the band."""
    rc, rows = run(os.path.join(FIXTURES, "multichip", "ok"),
                   ["scaling_efficiency", "multi_pc_per_sec"],
                   band=0.05, window=5, min_history=2, strict=False,
                   pattern="MULTICHIP_r*.json")
    assert rc == 0
    assert [r["status"] for r in rows] == ["ok", "ok"]
    # the failure-shape round contributed no history rows
    assert all(1 not in r["history_rounds"] for r in rows)


def test_multichip_efficiency_regression_fails():
    """A scaling-efficiency drop (0.87 -> 0.70) trips the gate even
    when absolute multi-leg throughput stays inside the band — the
    ratio is the pod-health headline."""
    rc, rows = run(os.path.join(FIXTURES, "multichip", "regress"),
                   ["scaling_efficiency", "multi_pc_per_sec"],
                   band=0.05, window=5, min_history=2, strict=False,
                   pattern="MULTICHIP_r*.json")
    assert rc == 1
    by = {r["metric"]: r for r in rows}
    assert by["scaling_efficiency"]["status"] == "REGRESSION"
    assert by["multi_pc_per_sec"]["status"] == "ok"


def test_multichip_cli_kind_selects_pattern_and_metrics():
    r = subprocess.run(
        [sys.executable, "tools/bench_regression.py", "--kind",
         "multichip", "--dir",
         os.path.join(FIXTURES, "multichip", "regress"), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout + r.stderr
    rows = json.loads(r.stdout)
    assert {row["metric"] for row in rows} == {
        "scaling_efficiency", "multi_pc_per_sec",
        "recovery_steps_lost", "recovery_seconds",
        "host_skew_ratio"}


def test_multichip_recovery_metrics_gate_lower_is_better():
    """ISSUE 13 satellite: the kill-mid-run recovery costs gate with
    the band flipped into a CEILING — ok/ fixtures keep the latest
    inside it, regress/ blows recovery_seconds past it while the
    steps-lost series stays flat."""
    rc, rows = run(os.path.join(FIXTURES, "multichip", "ok"),
                   ["recovery_steps_lost", "recovery_seconds"],
                   band=0.05, window=5, min_history=2, strict=False,
                   pattern="MULTICHIP_r*.json")
    assert rc == 0
    assert [r["status"] for r in rows] == ["ok", "ok"]
    assert all(r["lower_is_better"] for r in rows)

    rc, rows = run(os.path.join(FIXTURES, "multichip", "regress"),
                   ["recovery_steps_lost", "recovery_seconds"],
                   band=0.05, window=5, min_history=2, strict=False,
                   pattern="MULTICHIP_r*.json")
    assert rc == 1
    by = {r["metric"]: r for r in rows}
    assert by["recovery_seconds"]["status"] == "REGRESSION"
    assert by["recovery_steps_lost"]["status"] == "ok"


def test_lower_is_better_direction_flips_the_band():
    """check_metric's direction logic: a DROP in a lower-is-better
    metric is never a regression (it's the improvement), a rise past
    the banded ceiling is; the same values under a higher-is-better
    metric read the opposite way."""
    hist = [(1, 30.0), (2, 31.0)]
    worse = check_metric("recovery_seconds", hist, 3, 80.0,
                         band_floor=0.05, min_history=2)
    assert worse["status"] == "REGRESSION" and worse["lower_is_better"]
    assert worse["floor"] > worse["baseline"]  # a ceiling, not a floor
    better = check_metric("recovery_seconds", hist, 3, 5.0,
                          band_floor=0.05, min_history=2)
    assert better["status"] == "ok"
    # same numbers, throughput-style metric: the 5.0 IS the regression
    throughput = check_metric("multi_pc_per_sec", hist, 3, 5.0,
                              band_floor=0.05, min_history=2)
    assert throughput["status"] == "REGRESSION"
    assert not throughput["lower_is_better"]


def test_lower_is_better_zero_baseline_still_gates():
    """A perfect-recovery history (baseline 0) must keep gating a
    cost metric — 0 is the BEST possible baseline there, not broken
    data (the throughput-metric skip rule stays)."""
    hist = [(1, 0.0), (2, 0.0)]
    worse = check_metric("recovery_steps_lost", hist, 3, 50.0,
                         band_floor=0.05, min_history=2)
    assert worse["status"] == "REGRESSION"
    assert worse["ratio"] is None  # undefined over a 0 baseline
    assert "—" in render([worse])  # and renders without crashing
    perfect = check_metric("recovery_steps_lost", hist, 3, 0.0,
                           band_floor=0.05, min_history=2)
    assert perfect["status"] == "ok"
    # a zero-baseline THROUGHPUT series is still broken data -> skip
    thr = check_metric("multi_pc_per_sec", hist, 3, 50.0,
                       band_floor=0.05, min_history=2)
    assert thr["status"] == "skip"


def test_multichip_default_metrics_include_recovery_gate():
    from tools.bench_regression import MULTICHIP_METRICS
    assert "recovery_steps_lost" in MULTICHIP_METRICS
    assert "recovery_seconds" in MULTICHIP_METRICS
    assert "host_skew_ratio" in MULTICHIP_METRICS


def test_multichip_host_skew_gates_lower_is_better():
    """ISSUE 17 satellite: the cohort-evenness ratio (worst member
    step p50 / cohort median) gates with the band flipped into a
    ceiling — ok/ keeps the latest skew (1.05) inside it, regress/
    jumps to 1.42 (one straggler host taxing every lock-step
    all-reduce) and fails even though the recovery pair stays flat."""
    rc, rows = run(os.path.join(FIXTURES, "multichip", "ok"),
                   ["host_skew_ratio"],
                   band=0.05, window=5, min_history=2, strict=False,
                   pattern="MULTICHIP_r*.json")
    assert rc == 0
    assert rows[0]["status"] == "ok" and rows[0]["lower_is_better"]

    rc, rows = run(os.path.join(FIXTURES, "multichip", "regress"),
                   ["host_skew_ratio", "recovery_steps_lost"],
                   band=0.05, window=5, min_history=2, strict=False,
                   pattern="MULTICHIP_r*.json")
    assert rc == 1
    by = {r["metric"]: r for r in rows}
    assert by["host_skew_ratio"]["status"] == "REGRESSION"
    assert by["recovery_steps_lost"]["status"] == "ok"


def test_multichip_repo_trajectory_accepted():
    """The REAL repo-root MULTICHIP history must never crash the gate:
    the seed rounds are failure records; once multichip_bench captures
    a real round, it becomes the gated latest. Before that, rc=2 (no
    result-carrying rounds) — either way, no exception and no false
    REGRESSION."""
    rc, rows = run(REPO, ["scaling_efficiency"], band=0.05, window=5,
                   min_history=2, strict=False,
                   pattern="MULTICHIP_r*.json")
    assert rc in (0, 2)
    assert all(r["status"] != "REGRESSION" for r in rows)


def test_bench_r06_with_phase_breakdown_passes_real_trajectory(
        tmp_path):
    """ISSUE 15 satellite (the round-13 TODO that keeps the trajectory
    gate alive): a BENCH_r06 carrying the new phase_* breakdown must
    pass the DEFAULT gate against the repo's real BENCH_r01–r05 —
    the new keys have no history yet (skip, by the mixed-schema rule)
    and the headline metrics gate on-trajectory values. The driver's
    post-round bench capture is exactly this shape (bench.py now emits
    phase_* every round)."""
    import shutil

    from tools.bench_regression import DEFAULT_METRICS
    for n in range(1, 6):
        shutil.copy(os.path.join(REPO, f"BENCH_r0{n}.json"),
                    tmp_path / f"BENCH_r0{n}.json")
    r06 = {"metric": "path-contexts/sec/chip", "value": 6700000.0,
           "fwd_bwd_floor_pc_per_sec": 8500000.0,
           "int8_pc_per_sec": 5400000.0,
           "transformer_pc_per_sec": 2300000.0,
           "sparse_pc_per_sec": 8400000.0,
           "phase_embed_gather_ms": 4.1, "phase_concat_dense_ms": 3.0,
           "phase_forward_pool_ms": 5.2, "phase_backward_ms": 9.0,
           "phase_table_apply_ms": 6.4, "phase_sum_ms": 27.7}
    (tmp_path / "BENCH_r06.json").write_text(json.dumps(r06))
    rc, rows = run(str(tmp_path), list(DEFAULT_METRICS), band=0.05,
                   window=5, min_history=2, strict=False)
    assert rc == 0
    by = {r["metric"]: r for r in rows}
    assert by["value"]["status"] == "ok"
    # phase keys: no prior history -> skipped this round, gated from
    # the first round with 2+ phase-bearing predecessors
    assert by["phase_backward_ms"]["status"] == "skip"


# ---- --kind serving: the SERVING_r*.json trajectory (ISSUE 18)


def test_serving_ok_trajectory_passes():
    """serving_p99_ms gates as a CEILING (lower-is-better) and
    serving_req_per_sec as the usual floor; the ok/ trajectory keeps
    the latest round inside both bands."""
    rc, rows = run(os.path.join(FIXTURES, "serving", "ok"),
                   ["serving_p99_ms", "serving_req_per_sec"],
                   band=0.05, window=5, min_history=2, strict=False,
                   pattern="SERVING_r*.json")
    assert rc == 0
    by = {r["metric"]: r for r in rows}
    assert by["serving_p99_ms"]["status"] == "ok"
    assert by["serving_p99_ms"]["lower_is_better"]
    assert by["serving_req_per_sec"]["status"] == "ok"
    assert not by["serving_req_per_sec"]["lower_is_better"]


def test_serving_regression_fails_both_directions():
    """regress/ blows the p99 ceiling (19.5 vs a ~7.3 baseline) AND
    drops throughput below the floor — both read REGRESSION, each in
    its own direction."""
    rc, rows = run(os.path.join(FIXTURES, "serving", "regress"),
                   ["serving_p99_ms", "serving_req_per_sec"],
                   band=0.05, window=5, min_history=2, strict=False,
                   pattern="SERVING_r*.json")
    assert rc == 1
    by = {r["metric"]: r for r in rows}
    assert by["serving_p99_ms"]["status"] == "REGRESSION"
    assert by["serving_req_per_sec"]["status"] == "REGRESSION"


def test_serving_cli_kind_selects_pattern_and_metrics():
    r = subprocess.run(
        [sys.executable, "tools/bench_regression.py", "--kind",
         "serving", "--dir",
         os.path.join(FIXTURES, "serving", "regress"), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout + r.stderr
    rows = json.loads(r.stdout)
    assert {row["metric"] for row in rows} == {
        "serving_p99_ms", "serving_req_per_sec"}


def test_serving_repo_trajectory_accepted():
    """The repo-root SERVING history must never crash the gate: with
    a single captured round there is no baseline yet (skip / rc 0);
    as rounds accrue it becomes a real gate. No false REGRESSION
    either way."""
    rc, rows = run(REPO, ["serving_p99_ms", "serving_req_per_sec"],
                   band=0.05, window=5, min_history=2, strict=False,
                   pattern="SERVING_r*.json")
    assert rc in (0, 2)
    assert all(r["status"] != "REGRESSION" for r in rows)
