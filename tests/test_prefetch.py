"""Double-buffered infeed (data/prefetch.py; SURVEY.md §3.3 infeed row,
VERDICT r3 item 2): transfer of batch k+1 must overlap step k, without
changing order, results, or error behavior."""

import threading
import time

import numpy as np
import pytest

from code2vec_tpu.data.prefetch import (DevicePrefetcher, _SyncInfeed,
                                        prefetch_to_device)


def test_prefetcher_preserves_order_and_reiterates():
    batches = list(range(7))
    pf = prefetch_to_device(batches, lambda b: b * 10, depth=2)
    for _epoch in range(3):  # re-iterable across epochs
        out = list(pf)
        assert out == [(b * 10, b) for b in batches]


def test_depth_zero_is_synchronous_and_reiterable():
    calls = []
    pf = prefetch_to_device(list(range(3)), lambda b: calls.append(b),
                            depth=0)
    assert isinstance(pf, _SyncInfeed)
    it = iter(pf)
    assert calls == []          # nothing transferred ahead of the loop
    next(it)
    assert calls == [0]         # exactly one transfer per consumed item
    assert len(list(pf)) == 3   # fresh second epoch


def test_prefetcher_runs_ahead_of_consumer():
    """The overlap property itself: with a slow consumer, the producer
    thread transfers ahead — batch k+1's put_fn completes while the
    consumer is still holding batch k."""
    put_times = {}

    def put(b):
        put_times[b] = time.monotonic()
        return b

    pf = DevicePrefetcher(list(range(4)), put, depth=2)
    it = iter(pf)
    next(it)                      # consumer holds batch 0
    deadline = time.monotonic() + 5.0
    # batch 1 (and 2: queue slot + in-flight) get transferred without
    # the consumer asking for them
    while len(put_times) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(put_times) >= 3, (
        f"producer did not run ahead: only {sorted(put_times)} "
        "transferred while the consumer held batch 0")
    rest = list(it)
    assert [h for _d, h in rest] == [1, 2, 3]


def test_prefetcher_propagates_producer_exception_in_position():
    def put(b):
        if b == 2:
            raise RuntimeError("boom at batch 2")
        return b

    pf = DevicePrefetcher(list(range(5)), put, depth=2)
    seen = []
    with pytest.raises(RuntimeError, match="boom at batch 2"):
        for dev, _host in pf:
            seen.append(dev)
    assert seen == [0, 1]  # everything before the failure was delivered


def test_prefetcher_threads_do_not_leak():
    before = threading.active_count()
    pf = DevicePrefetcher(list(range(20)), lambda b: b, depth=2)
    for _ in range(5):
        list(pf)
    time.sleep(0.05)
    assert threading.active_count() <= before + 1


def test_train_and_eval_use_prefetched_infeed(tmp_path, monkeypatch):
    """The model loops actually take the overlap path (prefetch depth
    from config), and prefetched training is numerically identical to
    the synchronous round-3 loop."""
    import code2vec_tpu.data.prefetch as prefetch_mod
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.test_model import tiny_config
    from tests.helpers import build_tiny_dataset

    prefix = build_tiny_dataset(str(tmp_path), n_train=64, n_val=8,
                                n_test=8, max_contexts=16)

    used = []
    real = prefetch_mod.DevicePrefetcher

    class Recording(real):
        def __init__(self, *a, **k):
            used.append("prefetcher")
            super().__init__(*a, **k)

    monkeypatch.setattr(prefetch_mod, "DevicePrefetcher", Recording)

    def run(depth):
        cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=2,
                          INFEED_PREFETCH=depth)
        model = Code2VecModel(cfg)
        model.train()
        return model.evaluate()

    sync = run(0)
    assert used == []            # depth 0 -> synchronous path
    overlapped = run(2)
    assert used                  # train AND eval went through the thread
    assert overlapped.loss == pytest.approx(sync.loss, abs=1e-5)
    assert overlapped.topk_acc == pytest.approx(sync.topk_acc)
    np.testing.assert_allclose(overlapped.subtoken_f1, sync.subtoken_f1)


def test_abandoned_iteration_releases_producer_thread():
    """Breaking out of the consumer loop early (exception in the step)
    must stop the producer thread rather than leaving it blocked on a
    full queue for the process lifetime."""
    before = threading.active_count()
    pf = DevicePrefetcher(list(range(100)), lambda b: b, depth=2)
    for _t in range(4):
        it = iter(pf)
        next(it)
        it.close()  # abandon mid-epoch (what an exception does via GC)
    deadline = time.monotonic() + 5.0
    while threading.active_count() > before and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    assert threading.active_count() <= before, (
        "producer thread(s) leaked after abandoned iterations")


def test_chunked_prefetcher_amortizes_transfers():
    """ChunkedDevicePrefetcher: N batches with chunk G make ceil(N/G)
    transfers per field (incl. a partial tail chunk), yield order and
    values are preserved, and slices match the per-batch arrays."""
    from code2vec_tpu.data.prefetch import ChunkedDevicePrefetcher

    N, G = 10, 4
    batches = [np.full((2, 3), i, np.int32) for i in range(N)]
    transfers = []

    def transfer(stacked):
        transfers.append(stacked.shape)
        return stacked  # stay numpy: slicing semantics are identical

    pf = ChunkedDevicePrefetcher(
        batches, lambda b: (b, b * 10), chunk=G, transfer=transfer)
    out = list(pf)
    assert len(out) == N
    for i, (dev, host) in enumerate(out):
        assert host is batches[i]
        np.testing.assert_array_equal(dev[0], batches[i])
        np.testing.assert_array_equal(dev[1], batches[i] * 10)
    # ceil(10/4)=3 chunks x 2 fields; tail chunk is the partial one
    assert len(transfers) == 6
    assert transfers[0][0] == G and transfers[-1][0] == N % G

    # re-iterable (epochs) and exception propagation
    assert len(list(pf)) == N

    def boom(b):
        if int(b[0, 0]) == 5:
            raise RuntimeError("bad batch 5")
        return (b,)

    pf2 = ChunkedDevicePrefetcher(batches, boom, chunk=G,
                                  transfer=lambda s: s)
    with pytest.raises(RuntimeError, match="bad batch 5"):
        list(pf2)


def test_chunked_infeed_training_matches_per_batch(tmp_path,
                                                   monkeypatch):
    """A model trained through --infeed_chunk 4 is numerically identical
    to the per-batch infeed (same math, different transfer grouping).
    build_mesh is forced to None: chunked infeed is the single-device
    path (on the pytest virtual mesh it would silently fall back)."""
    import code2vec_tpu.models.setup as setup_mod
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.test_model import tiny_config
    from tests.helpers import build_tiny_dataset

    monkeypatch.setattr(setup_mod, "build_mesh", lambda cfg, **k: None)
    prefix = build_tiny_dataset(str(tmp_path), n_train=64, n_val=8,
                                n_test=8, max_contexts=16)

    def run(chunk):
        cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=2,
                          INFEED_CHUNK=chunk)
        model = Code2VecModel(cfg)
        assert model.mesh is None
        model.train()
        return model.evaluate()

    base = run(1)
    chunked = run(4)
    assert chunked.loss == pytest.approx(base.loss, abs=1e-5)
    assert chunked.topk_acc == pytest.approx(base.topk_acc)


def test_chunked_infeed_falls_back_on_mesh(tmp_path):
    """With a mesh active, --infeed_chunk logs and uses depth prefetch
    (the chunked stack is not mesh-sharded)."""
    from code2vec_tpu.data.prefetch import DevicePrefetcher
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.test_model import tiny_config
    from tests.helpers import build_tiny_dataset

    prefix = build_tiny_dataset(str(tmp_path), n_train=32, n_val=8,
                                n_test=8, max_contexts=16)
    cfg = tiny_config(prefix, INFEED_CHUNK=4)
    model = Code2VecModel(cfg)
    assert model.mesh is not None  # pytest virtual 8-device mesh
    infeed = model._train_infeed([])
    assert isinstance(infeed, DevicePrefetcher)
