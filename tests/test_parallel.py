"""Sharded == single-device numerics on the 8-device virtual CPU mesh
(SURVEY.md §5 '"Multi-node without a cluster"')."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from code2vec_tpu.models.encoder import ModelDims, full_logits, \
    init_params
from code2vec_tpu.parallel.mesh import make_mesh
from code2vec_tpu.parallel.sharding import (param_pspecs, shard_batch,
                                            shard_opt_state, shard_params)
from code2vec_tpu.training.steps import make_train_step

DIMS = ModelDims(token_vocab_size=32, path_vocab_size=24,
                 target_vocab_size=20, embeddings_size=8, max_contexts=6,
                 dropout_keep_rate=1.0, vocab_pad_multiple=2)


def _batch(rng, b=16):
    r = np.random.default_rng(rng)
    labels = r.integers(0, DIMS.target_vocab_size, size=(b,), dtype=np.int32)
    src = r.integers(0, DIMS.token_vocab_size, size=(b, 6), dtype=np.int32)
    pth = r.integers(0, DIMS.path_vocab_size, size=(b, 6), dtype=np.int32)
    dst = r.integers(0, DIMS.token_vocab_size, size=(b, 6), dtype=np.int32)
    mask = np.ones((b, 6), dtype=np.float32)
    weights = np.ones((b,), dtype=np.float32)
    return labels, src, pth, dst, mask, weights


def test_mesh_shapes():
    mesh = make_mesh(0, 2)
    assert mesh.shape == {"dcn": 1, "data": 4, "ctx": 1, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(3, 3)
    mesh2 = make_mesh(0, 2, dcn=2)
    assert mesh2.shape == {"dcn": 2, "data": 2, "ctx": 1, "model": 2}


@pytest.mark.parametrize("mesh_kwargs", [
    dict(),          # DP x TP: batch over 'data', tables over 'model'
    dict(dcn=2),     # multi-slice: batch over composite ('dcn','data')
], ids=["data-model", "dcn-data-model"])
def test_sharded_train_step_matches_single_device(mesh_kwargs):
    assert len(jax.devices()) == 8
    params = init_params(jax.random.PRNGKey(0), DIMS)
    opt = optax.adam(0.01)
    opt_state = opt.init(params)
    batch = _batch(0)
    rng = jax.random.PRNGKey(1)

    # single-device reference run
    step1 = make_train_step(DIMS, opt)
    p1, os1, loss1 = step1(
        jax.tree_util.tree_map(jnp.copy, params), opt.init(params),
        tuple(jnp.asarray(a) for a in batch), rng)

    # sharded run: numerics must be layout-invariant
    mesh = make_mesh(0, 2, **mesh_kwargs)
    sp = shard_params(mesh, params)
    so = shard_opt_state(mesh, opt_state, sp)
    sb = shard_batch(mesh, batch)
    step2 = make_train_step(DIMS, opt)
    p2, os2, loss2 = step2(sp, so, sb, rng)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   atol=1e-5, err_msg=k)
    # table sharding actually happened
    specs = param_pspecs()
    assert ("model" in str(p2["token_emb"].sharding)
            or p2["token_emb"].sharding.is_fully_replicated is False)


def test_sharded_sampled_softmax_matches_single_device():
    params = init_params(jax.random.PRNGKey(0), DIMS)
    opt = optax.adam(0.01)
    batch = _batch(1)
    rng = jax.random.PRNGKey(2)
    step = make_train_step(DIMS, opt, use_sampled_softmax=True,
                           num_sampled=8)
    _, _, loss1 = step(jax.tree_util.tree_map(jnp.copy, params),
                       opt.init(params),
                       tuple(jnp.asarray(a) for a in batch), rng)
    mesh = make_mesh(0, 2)
    sp = shard_params(mesh, params)
    so = shard_opt_state(mesh, opt.init(params), sp)
    sb = shard_batch(mesh, batch)
    step2 = make_train_step(DIMS, opt, use_sampled_softmax=True,
                            num_sampled=8)
    _, _, loss2 = step2(sp, so, sb, rng)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)


def test_vocab_row_padding_for_model_axis():
    params = init_params(jax.random.PRNGKey(0), DIMS)
    assert params["token_emb"].shape[0] % 2 == 0
    assert params["target_emb"].shape[0] % 2 == 0
    # padded logit rows are masked out of top-k
    code = jnp.ones((2, DIMS.code_vector_size))
    logits = full_logits(params, code, DIMS.target_vocab_size)
    assert np.all(np.asarray(logits)[:, DIMS.target_vocab_size:] < -1e8)


def test_vm_sharded_train_step_matches_single_device():
    """VarMisuse head on the (data x model) mesh == single device
    (VERDICT r4 item 7: vm_model.py shards params on a mesh no test
    constructed — this is that test, pointer head included)."""
    from code2vec_tpu.models.varmisuse import init_vm_params
    from code2vec_tpu.training.vm_steps import (make_vm_eval_step,
                                                make_vm_train_step)

    assert len(jax.devices()) == 8
    dims = ModelDims(token_vocab_size=32, path_vocab_size=24,
                     target_vocab_size=8, embeddings_size=8,
                     max_contexts=6, dropout_keep_rate=1.0,
                     vocab_pad_multiple=2)
    params = init_vm_params(jax.random.PRNGKey(0), dims)
    opt = optax.adam(0.01)
    K = 4
    r = np.random.default_rng(3)
    b = 16
    batch = (
        r.integers(0, K, size=(b,), dtype=np.int32),          # labels
        r.integers(0, 32, size=(b, 6), dtype=np.int32),       # src
        r.integers(0, 24, size=(b, 6), dtype=np.int32),       # pth
        r.integers(0, 32, size=(b, 6), dtype=np.int32),       # dst
        np.ones((b, 6), dtype=np.float32),                    # mask
        r.integers(0, 32, size=(b, K), dtype=np.int32),       # cand_ids
        np.ones((b, K), dtype=np.float32),                    # cand_mask
        np.ones((b,), dtype=np.float32))                      # weights
    rng = jax.random.PRNGKey(5)

    step1 = make_vm_train_step(dims, opt)
    p1, _, loss1 = step1(jax.tree_util.tree_map(jnp.copy, params),
                         opt.init(params),
                         tuple(jnp.asarray(a) for a in batch), rng)

    mesh = make_mesh(0, 2)
    sp = shard_params(mesh, params)
    so = shard_opt_state(mesh, opt.init(params), sp)
    sb = shard_batch(mesh, batch)
    step2 = make_vm_train_step(dims, opt)
    p2, _, loss2 = step2(sp, so, sb, rng)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   atol=1e-5, err_msg=k)
    # the vocab tables really are row-sharded over 'model'
    assert not p2["token_emb"].sharding.is_fully_replicated
    # and the eval step agrees on the sharded layout too
    ev1 = make_vm_eval_step(dims)(
        p1, tuple(jnp.asarray(a) for a in batch))
    ev2 = make_vm_eval_step(dims)(p2, sb)
    np.testing.assert_allclose(float(ev1[0]), float(ev2[0]), rtol=1e-5)
    np.testing.assert_allclose(float(ev1[1]), float(ev2[1]), rtol=1e-5)


# ---- bounded first-collective barrier (ISSUE 14 satellite) ----

def test_first_collective_barrier_single_process_skips_probe():
    """Nothing to rendezvous on one process: the probe is skipped and
    the watchdog thread is reaped before return (no timer left
    running). A generous deadline: the assertion is about thread
    hygiene, not timing."""
    import threading

    from code2vec_tpu.parallel.compat import first_collective_barrier

    before = threading.active_count()
    first_collective_barrier(timeout_s=30.0)
    assert threading.active_count() == before


def test_first_collective_barrier_deadline_covers_setup():
    """The watchdog deadline covers the INIT phase too —
    jax.distributed.initialize blocks for the peer connect, and a
    wedge there must trip the same fast exit (the round-18 probe run
    showed the hang striking before the probe collective)."""
    import threading

    from code2vec_tpu.parallel.compat import first_collective_barrier

    fired = threading.Event()
    first_collective_barrier(timeout_s=0.05,
                             setup_fn=lambda: fired.wait(5.0),
                             barrier_fn=lambda: None,
                             on_timeout=fired.set)
    assert fired.is_set()


def test_first_collective_barrier_fast_barrier_cancels_watchdog():
    """A completing probe must cancel the watchdog — on_timeout never
    fires even after the deadline would have passed."""
    import time

    from code2vec_tpu.parallel.compat import first_collective_barrier

    fired = []
    first_collective_barrier(timeout_s=0.05,
                             barrier_fn=lambda: None,
                             on_timeout=lambda: fired.append(1))
    time.sleep(0.15)
    assert fired == []


def test_first_collective_barrier_wedged_barrier_fires_watchdog():
    """A wedged probe trips on_timeout at the deadline (the injected
    stand-in for os._exit(BARRIER_TIMEOUT_EXIT)) — the shape that
    converts the PR 12 postscript module-eating hang into a fast
    retryable worker death."""
    import threading

    from code2vec_tpu.parallel.compat import (BARRIER_TIMEOUT_EXIT,
                                              first_collective_barrier)

    assert BARRIER_TIMEOUT_EXIT == 19  # the greppable contract
    fired = threading.Event()
    first_collective_barrier(timeout_s=0.05,
                             barrier_fn=lambda: fired.wait(5.0),
                             on_timeout=fired.set)
    assert fired.is_set()


def test_phase_deadline_beats_rearm_and_close_disarms():
    """PhaseDeadline: a beaten deadline never fires; close() disarms
    and reaps; a wedged phase fires with ITS label (the injected
    stand-in for os._exit)."""
    import threading
    import time

    from code2vec_tpu.parallel.compat import PhaseDeadline

    fired = []
    wd = PhaseDeadline(timeout_s=0.2, on_timeout=fired.append)
    for phase in ("a", "b", "c"):  # beats inside the deadline re-arm
        wd.beat(phase)
        time.sleep(0.05)
    wd.beat("compile-heavy", timeout_s=1.0)  # per-phase override
    time.sleep(0.3)  # past the default, inside the override
    wd.close()
    time.sleep(0.3)
    assert fired == []

    hung = threading.Event()
    wd2 = PhaseDeadline(timeout_s=0.05,
                        on_timeout=lambda ph: (fired.append(ph),
                                               hung.set()))
    wd2.beat("bring-up")
    wd2.beat("wedged-collective")
    assert hung.wait(5.0)
    assert fired == ["wedged-collective"]
    wd2.close()
