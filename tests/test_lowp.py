"""bf16 vocab tables + adafactor embedding optimizer (the measured perf
configuration, BASELINE.md): training still learns, checkpoints
round-trip preserving the storage dtype, and the dtype/optimizer pair is
recorded in the manifest so --load reconstructs the right model."""

import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.models.encoder import ModelDims, init_params
from code2vec_tpu.models.jax_model import Code2VecModel
from tests.helpers import build_tiny_dataset
from tests.test_model import tiny_config


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("data")
    return build_tiny_dataset(str(d), n_train=256, n_val=32, n_test=64,
                              max_contexts=16)


def test_init_params_tables_dtype():
    dims = ModelDims(token_vocab_size=16, path_vocab_size=16,
                     target_vocab_size=8, embeddings_size=4,
                     max_contexts=4, tables_dtype="bfloat16")
    import jax
    p = init_params(jax.random.PRNGKey(0), dims)
    assert p["token_emb"].dtype == jnp.bfloat16
    assert p["target_emb"].dtype == jnp.bfloat16
    # numerics-sensitive small params stay f32
    assert p["transform"].dtype == jnp.float32
    assert p["attention"].dtype == jnp.float32


def test_bf16_adafactor_trains_and_roundtrips(dataset, tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = tiny_config(dataset, TABLES_DTYPE="bfloat16",
                      EMBEDDING_OPTIMIZER="adafactor",
                      NUM_TRAIN_EPOCHS=6)
    cfg.save_path = ckpt_dir
    model = Code2VecModel(cfg)
    assert model.params["token_emb"].dtype == jnp.bfloat16
    before = model.evaluate()
    model.train()
    after = model.evaluate()
    assert after.loss < before.loss
    assert after.subtoken_f1 > 0.5
    model.save(ckpt_dir)

    cfg2 = tiny_config(dataset)  # dtype/optimizer come from the manifest
    cfg2.load_path = ckpt_dir
    model2 = Code2VecModel(cfg2)
    assert model2.params["token_emb"].dtype == jnp.bfloat16
    assert model2.config.EMBEDDING_OPTIMIZER == "adafactor"
    loaded = model2.evaluate()
    assert loaded.topk_acc == pytest.approx(after.topk_acc)


def test_sparse_updates_reject_lowp_config(dataset):
    cfg = tiny_config(dataset, SPARSE_EMBEDDING_UPDATES=True,
                      TABLES_DTYPE="bfloat16")
    with pytest.raises(ValueError):
        cfg.verify()


def test_bf16_numerics_close_to_f32_one_step(dataset):
    """One train step with bf16 tables stays close to the f32 step —
    the rounding shows up in the 3rd significant digit, not the 1st."""
    import jax
    import optax

    from code2vec_tpu.training.steps import make_train_step
    from tests.helpers import example_batch

    dims32 = ModelDims(token_vocab_size=64, path_vocab_size=48,
                       target_vocab_size=40, embeddings_size=16,
                       max_contexts=8, dropout_keep_rate=1.0)
    dims16 = ModelDims(token_vocab_size=64, path_vocab_size=48,
                       target_vocab_size=40, embeddings_size=16,
                       max_contexts=8, dropout_keep_rate=1.0,
                       tables_dtype="bfloat16")
    p32 = init_params(jax.random.PRNGKey(0), dims32)
    # the train step donates params, so both runs need their own copies
    p16 = {k: (v.astype(jnp.bfloat16)
               if k in ("token_emb", "path_emb", "target_emb")
               else jnp.copy(v))
           for k, v in p32.items()}
    batch = example_batch(seed=5, dims=dims32, batch=16)
    opt = optax.adam(1e-2)
    rng = jax.random.PRNGKey(3)
    s32 = make_train_step(dims32, opt)
    s16 = make_train_step(dims16, opt)
    _, _, l32 = s32(p32, opt.init(p32), batch, rng)
    _, _, l16 = s16(p16, opt.init(p16), batch, rng)
    np.testing.assert_allclose(float(l32), float(l16), rtol=2e-2)
