"""Vocab cut/lookup, preprocess round-trip, reader parsing edge cases
(SURVEY.md §5: "vocab cut/lookup, reader parsing of hand-written .c2v rows
incl. padding/mask edge cases: 0 contexts, >max contexts, OOV")."""

import numpy as np

from code2vec_tpu.data.reader import (BinaryShardReader, C2VTextReader,
                                      parse_c2v_rows)
from code2vec_tpu.vocab.vocabularies import (Code2VecVocabs, Vocab,
                                             VocabType)
from tests.helpers import build_tiny_dataset, load_tiny_vocabs


def test_vocab_specials_and_cut():
    v = Vocab.create_from_freq_dict(
        VocabType.Token, {"a": 5, "b": 3, "c": 10, "d": 1}, max_size=2)
    assert v.pad_index == 0 and v.oov_index == 1
    # top-2 by frequency: c, a
    assert v.lookup_index("c") == 2
    assert v.lookup_index("a") == 3
    assert v.lookup_index("b") == v.oov_index  # cut
    assert v.lookup_word(2) == "c"
    assert v.size == 4


def test_vocab_word_list_roundtrip():
    v = Vocab.create_from_freq_dict(VocabType.Target,
                                    {"get|x": 3, "set|x": 1}, 10)
    v2 = Vocab.from_word_list(VocabType.Target, v.to_word_list())
    assert v2.word_to_index == v.word_to_index


def test_preprocess_and_dict_roundtrip(tmp_path):
    prefix = build_tiny_dataset(str(tmp_path), n_train=50, n_val=8,
                                n_test=8, max_contexts=10)
    vocabs = load_tiny_vocabs(prefix)
    assert vocabs.num_training_examples == 50
    # every .c2v row has exactly 1 + max_contexts space-separated fields
    with open(prefix + ".train.c2v") as f:
        for line in f:
            assert len(line.rstrip("\n").split(" ")) == 11


def test_count_dict_readers_agree(tmp_path):
    """read_count_dicts and the token-only fast reader must expose the
    same .dict.c2v layout (attacks/detect.py depends on the latter)."""
    from code2vec_tpu.vocab.vocabularies import (read_count_dicts,
                                                 read_token_counts)
    prefix = build_tiny_dataset(str(tmp_path), n_train=50, n_val=8,
                                n_test=8, max_contexts=10)
    tok, pth, tgt, n = read_count_dicts(prefix + ".dict.c2v")
    assert read_token_counts(prefix + ".dict.c2v") == tok
    assert n == 50
    assert tok and pth and tgt
    assert all(isinstance(c, int) for c in tok.values())


def test_parse_c2v_rows_edge_cases():
    vocabs = Code2VecVocabs(
        Vocab(VocabType.Token, ["foo", "bar"]),
        Vocab(VocabType.Path, ["111", "222"]),
        Vocab(VocabType.Target, ["get|x"]))
    lines = [
        "get|x foo,111,bar bar,222,foo",          # 2 contexts
        "unknown|name ",                           # 0 contexts, OOV target
        "get|x oov_tok,999,foo",                   # OOV token+path
    ]
    labels, src, pth, dst, mask, _, _ = parse_c2v_rows(
        lines, vocabs, max_contexts=4)
    tv, pv = vocabs.token_vocab, vocabs.path_vocab
    assert labels[0] == vocabs.target_vocab.lookup_index("get|x")
    assert labels[1] == vocabs.target_vocab.oov_index
    assert mask[0].tolist() == [1.0, 1.0, 0.0, 0.0]
    assert mask[1].tolist() == [0.0, 0.0, 0.0, 0.0]
    assert src[0, 0] == tv.lookup_index("foo")
    assert pth[0, 1] == pv.lookup_index("222")
    assert src[2, 0] == tv.oov_index
    assert pth[2, 0] == pv.oov_index
    # padding positions hold PAD
    assert src[0, 2] == tv.pad_index and pth[1, 0] == pv.pad_index


def test_row_longer_than_max_contexts_truncates():
    vocabs = Code2VecVocabs(
        Vocab(VocabType.Token, ["a"]), Vocab(VocabType.Path, ["1"]),
        Vocab(VocabType.Target, ["t"]))
    line = "t " + " ".join(["a,1,a"] * 10)
    _, src, _, _, mask, _, _ = parse_c2v_rows([line], vocabs, max_contexts=4)
    assert mask.shape == (1, 4)
    assert mask.sum() == 4


def test_row_longer_than_max_contexts_samples_not_head():
    """Over-cap rows are downsampled (reference preprocess samples, not
    first-N), deterministically for a fixed seed."""
    vocabs = Code2VecVocabs(
        Vocab(VocabType.Token, [f"w{i}" for i in range(20)]),
        Vocab(VocabType.Path, ["1"]), Vocab(VocabType.Target, ["t"]))
    line = "t " + " ".join(f"w{i},1,w{i}" for i in range(20))
    _, src, _, _, mask, _, cstr = parse_c2v_rows(
        [line], vocabs, max_contexts=4, keep_strings=True)
    assert mask.sum() == 4
    picked = {int(w) for w in
              (c.split(",")[0][1:] for c in cstr[0])}
    # deterministic across calls
    _, src2, _, _, _, _, cstr2 = parse_c2v_rows(
        [line], vocabs, max_contexts=4, keep_strings=True)
    assert cstr2[0] == cstr[0]
    assert (src2 == src).all()
    # not simply the first four contexts (seeded sample spreads out)
    assert picked != {0, 1, 2, 3}
    # kept strings correspond to the sampled ids
    tv = vocabs.token_vocab
    assert [tv.lookup_word(int(i)) for i in src[0]] == \
        [c.split(",")[0] for c in cstr[0]]


def test_text_reader_batching_and_final_pad(tmp_path):
    prefix = build_tiny_dataset(str(tmp_path), n_train=10, n_val=2,
                                n_test=2, max_contexts=8)
    vocabs = load_tiny_vocabs(prefix)
    reader = C2VTextReader(prefix + ".train.c2v", vocabs, 8, batch_size=4)
    batches = list(reader)
    assert len(batches) == 3
    assert all(b.target_index.shape == (4,) for b in batches)
    assert batches[-1].num_valid_examples == 2
    # padded tail rows are masked out entirely
    assert batches[-1].context_valid_mask[2:].sum() == 0


def test_binary_reader_matches_text_reader(tmp_path):
    prefix = build_tiny_dataset(str(tmp_path), n_train=32, n_val=4,
                                n_test=4, max_contexts=8, binarize=True)
    vocabs = load_tiny_vocabs(prefix)
    text = list(C2VTextReader(prefix + ".train.c2v", vocabs, 8,
                              batch_size=8))
    binary = list(BinaryShardReader(prefix + ".train", batch_size=8))
    assert len(text) == len(binary)
    for tb, bb in zip(text, binary):
        np.testing.assert_array_equal(tb.target_index, bb.target_index)
        np.testing.assert_array_equal(tb.path_indices, bb.path_indices)
        np.testing.assert_array_equal(tb.path_source_token_indices,
                                      bb.path_source_token_indices)
        np.testing.assert_array_equal(tb.context_valid_mask,
                                      bb.context_valid_mask)


def test_binary_eval_fast_path_carries_target_strings(tmp_path):
    """evaluate()'s keep_strings path must ride the binary shards: the
    `.bin.targets` sidecar round-trips ORIGINAL names (incl. targets
    that are OOV in the vocab) in example order."""
    import os

    from code2vec_tpu.data.reader import open_reader

    prefix = build_tiny_dataset(str(tmp_path), n_train=32, n_val=4,
                                n_test=4, max_contexts=8, binarize=True)
    vocabs = load_tiny_vocabs(prefix)
    # inject an OOV-target row and re-binarize (string must survive)
    with open(prefix + ".train.c2v", "a") as f:
        f.write("totally|novel|name foo,123456,bar"
                + " " * 0 + "\n")
    from code2vec_tpu.data import binarize as binarize_mod
    binarize_mod.main(["--data", prefix, "--max_contexts", "8",
                       "--word_vocab_size", "1000",
                       "--path_vocab_size", "1000",
                       "--target_vocab_size", "1000"])
    assert os.path.exists(prefix + ".train.bin.targets")

    # with the sidecar present, open_reader picks binary for eval too
    binary = open_reader(prefix + ".train.c2v", vocabs, 8, 8,
                         keep_strings=True)
    assert isinstance(binary, BinaryShardReader)

    tb = list(C2VTextReader(prefix + ".train.c2v", vocabs, 8,
                            batch_size=8, keep_strings=True))
    bb = list(binary)
    assert len(tb) == len(bb)
    for t, b in zip(tb, bb):
        assert b.target_strings is not None
        assert t.target_strings[:t.num_valid_examples] \
            == b.target_strings[:b.num_valid_examples]
        np.testing.assert_array_equal(t.target_index, b.target_index)
    # the OOV name survived as a string in the last batch
    last = bb[-1]
    assert "totally|novel|name" in last.target_strings
    assert last.target_index[last.target_strings.index(
        "totally|novel|name")] == vocabs.target_vocab.oov_index


def test_reader_shuffle_is_seeded_and_complete(tmp_path):
    prefix = build_tiny_dataset(str(tmp_path), n_train=16, n_val=2,
                                n_test=2, max_contexts=8)
    vocabs = load_tiny_vocabs(prefix)
    r1 = C2VTextReader(prefix + ".train.c2v", vocabs, 8, batch_size=16,
                       shuffle=True, seed=7)
    r2 = C2VTextReader(prefix + ".train.c2v", vocabs, 8, batch_size=16,
                       shuffle=True, seed=7)
    b1, b2 = next(iter(r1)), next(iter(r2))
    np.testing.assert_array_equal(b1.target_index, b2.target_index)
    # same multiset of labels as unshuffled
    r3 = C2VTextReader(prefix + ".train.c2v", vocabs, 8, batch_size=16)
    b3 = next(iter(r3))
    assert sorted(b1.target_index.tolist()) == sorted(
        b3.target_index.tolist())


def test_over_cap_sampling_ignores_pad_fields():
    """A preprocessed row padded to a larger width than the run's
    max_contexts must keep ALL its real contexts (pads don't compete
    for slots) — regression for sampling across padding fields."""
    vocabs = Code2VecVocabs(
        Vocab(VocabType.Token, ["a", "b", "c"]),
        Vocab(VocabType.Path, ["1"]), Vocab(VocabType.Target, ["t"]))
    line = "t a,1,a b,1,b c,1,c " + " ".join([""] * 5)
    _, src, _, _, mask, _, _ = parse_c2v_rows([line], vocabs,
                                              max_contexts=4)
    assert mask.sum() == 3
    tv = vocabs.token_vocab
    assert {int(src[0, j]) for j in range(3)} == {
        tv.lookup_index("a"), tv.lookup_index("b"), tv.lookup_index("c")}
