"""Request-scoped tracing (ISSUE 6): trace-id propagation across the
serving queue/batcher threads under concurrent load, the Chrome
trace-event export schema, critical-path breakdowns, the train-loop
span tree (step <- infeed producer, save <- step, writer <- save), and
the disabled path's zero-allocation discipline. All CPU tier-1."""

import json
import os
import threading

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.obs import SpanChannel, Telemetry, Tracer
from code2vec_tpu.obs.trace import _NULL_SPAN


def _events(run_dir):
    out = []
    with open(os.path.join(run_dir, "events.jsonl"),
              encoding="utf-8") as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


def _spans(run_dir):
    return [e for e in _events(run_dir) if e["kind"] == "span"]


# ---------------------------------------------------------------------
# tracer unit behavior
# ---------------------------------------------------------------------

def test_span_tree_ids_and_thread_local_parenting(tmp_path):
    tele = Telemetry.create(str(tmp_path), component="t")
    tr = Tracer.create(tele)
    root = tr.start_trace("root", k=1)
    with tr.start_span("mid", parent=root.context()):
        leaf = tr.start_span("leaf")  # implicit: current span = mid
        leaf.end()
    root.end()
    tele.close()
    spans = {s["name"]: s for s in _spans(tele.run_dir)}
    assert spans["root"]["trace"] == spans["mid"]["trace"] == \
        spans["leaf"]["trace"]
    assert spans["mid"]["parent"] == spans["root"]["span"]
    assert spans["leaf"]["parent"] == spans["mid"]["span"]
    assert spans["root"].get("parent") is None
    assert spans["root"]["attrs"] == {"k": 1}
    # distinct ids throughout
    assert len({s["span"] for s in spans.values()}) == 3


def test_record_span_retroactive_and_live_span_table(tmp_path):
    tele = Telemetry.create(str(tmp_path), component="t")
    tr = Tracer.create(tele)
    open_span = tr.start_trace("in-flight")
    ctx = tr.record_span("retro", 10.0, 10.25,
                         parent=open_span.context(), track="a-queue")
    assert ctx.trace_id == open_span.trace_id
    live = tr.live_spans()
    assert [s["name"] for s in live] == ["in-flight"]
    open_span.end()
    assert tr.live_spans() == []
    tele.close()
    retro = next(s for s in _spans(tele.run_dir) if s["name"] == "retro")
    assert retro["dur_ms"] == pytest.approx(250.0)
    assert retro["tname"] == "a-queue"


def test_span_channel_fifo():
    ch = SpanChannel()
    assert ch.recv() is None
    ch.send("a")
    ch.send("b")
    assert ch.recv() == "a" and ch.recv() == "b" and ch.recv() is None


def test_disabled_tracer_is_shared_and_allocation_free(tmp_path):
    tr = Tracer.disabled()
    assert tr is Tracer.disabled()
    assert not tr.enabled
    # every span-producing call returns the ONE shared null span
    assert tr.start_trace("x") is _NULL_SPAN
    assert tr.start_span("y", parent=None) is _NULL_SPAN
    assert tr.record_span("z", 0.0, 1.0) is None
    with tr.start_trace("w") as s:
        assert s is _NULL_SPAN
    assert _NULL_SPAN.end() == 0.0 and _NULL_SPAN.context() is None
    assert tr.live_spans() == []
    # memory-mode telemetry (no sinks) gets the disabled singleton too
    assert Tracer.create(Telemetry.memory("m")) is tr
    assert Tracer.create(Telemetry.disabled()) is tr
    assert Tracer.create(None) is tr


def test_disabled_path_stays_out_of_recorder_and_server():
    """PR 2 discipline: with trace off, the recorder wraps nothing new
    and the null tracer is what models/servers hold by default."""
    from code2vec_tpu.obs import TrainStepRecorder
    rec = TrainStepRecorder(Telemetry.disabled())
    infeed = [1, 2]
    assert rec.wrap(infeed) is infeed
    assert rec._tracer is Tracer.disabled()


# ---------------------------------------------------------------------
# propagation across the queue/batcher threads under concurrent load
# (stub model: no device work, so thread interleaving is the test)
# ---------------------------------------------------------------------

class _StubModel:
    telemetry = Telemetry.disabled()
    tracer = Tracer.disabled()

    def prepare_predict_rows(self, lines):
        from code2vec_tpu.models.jax_model import PreparedRows
        n = len([ln for ln in lines if ln.strip()])
        z = np.zeros((n, 4), np.int32)
        return PreparedRows(np.zeros((n,), np.int32), z, z, z,
                            z.astype(np.float32), ["m"] * n,
                            [[] for _ in range(n)])

    def predict_device(self, prepared):
        n = prepared.n
        return (np.zeros((n, 1), np.int32),
                np.zeros((n, 1), np.float32),
                np.zeros((n, 4), np.float32),
                np.zeros((n, 4), np.float32))

    def decode_predictions(self, prepared, device_out):
        return ["res"] * prepared.n

    def warmup_predict(self, max_batch):
        return [1]

    def predict_compile_count(self):
        return 0


@pytest.fixture()
def traced_serving_run(tmp_path):
    """12 concurrent 2-method requests through the REAL server +
    batcher with tracing on; yields the run dir's span events."""
    from code2vec_tpu.serving.server import PredictionServer
    cfg = Config(SERVE_CACHE_SIZE=0, SERVE_BATCH_MAX=8,
                 SERVE_BATCH_TIMEOUT_MS=2.0, TRACE=True,
                 TELEMETRY_DIR=str(tmp_path))
    cfg.train_data_path = "unused"  # bypass verify's train-or-load rule
    tele = Telemetry.create(str(tmp_path), config=cfg,
                            component="serve").make_threadsafe()
    server = PredictionServer(cfg, _StubModel(), telemetry=tele)
    server.start()
    try:
        threads = [threading.Thread(
            target=lambda i=i: server.predict_lines(
                [f"m a,{i},b", f"m c,{i},d"])) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        server.close()
    tele.close()
    return _spans(tele.run_dir)


def test_trace_propagates_through_queue_and_batcher(traced_serving_run):
    spans = traced_serving_run
    roots = [s for s in spans if s["name"] == "serve/request"]
    assert len(roots) == 12
    flushes = [s for s in spans if s["name"] == "serve/batch_flush"]
    assert flushes, "no batch flush spans"
    # per request: parse + queue_wait + decode all carry ITS trace id
    for r in roots:
        mine = {s["name"] for s in spans if s["trace"] == r["trace"]}
        assert {"serve/parse", "serve/queue_wait",
                "serve/decode"} <= mine, (r["trace"], mine)
    # ACCEPTANCE: at least one request's queue -> batch chain shares a
    # single trace id end-to-end (the flush continues its trace)
    primary = {f["trace"] for f in flushes}
    assert primary & {r["trace"] for r in roots}
    # every other coalesced request is linked from some flush
    linked = {link[0] for f in flushes for link in (f.get("links") or ())}
    for r in roots:
        assert r["trace"] in primary or r["trace"] in linked
    # queue_wait is recorded retroactively on the virtual queue track,
    # parented to the request root (cross-thread handoff worked)
    by_span = {s["span"]: s for s in spans}
    for qw in (s for s in spans if s["name"] == "serve/queue_wait"):
        assert qw["tname"] == "serve-queue"
        assert by_span[qw["parent"]]["name"] == "serve/request"


def test_chrome_trace_schema_round_trip(traced_serving_run, tmp_path):
    from tools.trace_report import chrome_trace_events
    events = chrome_trace_events([({"process_index": 0},
                                   traced_serving_run)])
    # schema: every complete event has the required fields, metadata
    # names the threads, flows come in s/f pairs sharing an id
    assert {e["ph"] for e in events} >= {"X", "M", "s", "f"}
    for e in events:
        if e["ph"] == "X":
            assert {"name", "ts", "dur", "pid", "tid",
                    "args"} <= set(e)
            assert e["dur"] >= 1.0 and e["ts"] >= 0.0
            assert "trace" in e["args"] and "span" in e["args"]
    starts = {e["id"] for e in events if e["ph"] == "s"}
    finishes = {e["id"] for e in events if e["ph"] == "f"}
    assert starts and starts == finishes
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in events)
    # and it survives a JSON round trip through the file format
    out = tmp_path / "trace.json"
    from tools.trace_report import write_chrome_trace
    # write_chrome_trace reads run dirs; emulate via json dump/load of
    # the same event list instead
    out.write_text(json.dumps({"traceEvents": events,
                               "displayTimeUnit": "ms"}))
    doc = json.loads(out.read_text())
    assert len(doc["traceEvents"]) == len(events)


def test_request_critical_path_breakdown(traced_serving_run, capsys):
    from tools.trace_report import render, request_breakdowns
    rows = request_breakdowns(traced_serving_run)
    assert len(rows) == 12
    for r in rows:
        # every phase of the critical path is attributed — device and
        # encode come from the flush (by trace id or by link)
        for phase in ("queue_wait", "parse", "decode"):
            assert phase in r, (phase, r)
        assert r["total_ms"] > 0
    text = render([({"run_id": "r", "component": "serve"},
                    traced_serving_run)])
    assert "queue_wait" in text and "| Phase (all requests) |" in text


# ---------------------------------------------------------------------
# train-loop trace tree (real model, tiny CPU run)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def traced_train_run(tmp_path_factory):
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.helpers import build_tiny_dataset
    from tests.test_model import tiny_config
    d = str(tmp_path_factory.mktemp("trace_train"))
    prefix = build_tiny_dataset(d, n_train=64, n_val=8, n_test=8,
                                max_contexts=16)
    cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=2,
                      TELEMETRY_DIR=os.path.join(d, "tele"),
                      TRACE=True, SAVE_EVERY_EPOCHS=1)
    cfg.save_path = os.path.join(d, "ckpt")
    model = Code2VecModel(cfg)
    model.train()
    model.close_session()
    return _spans(model.telemetry.run_dir)


def test_step_spans_link_consumed_infeed_batches(traced_train_run):
    spans = traced_train_run
    steps = [s for s in spans if s["name"] == "train/step"]
    produces = {(s["trace"], s["span"])
                for s in spans if s["name"] == "infeed/produce"}
    assert steps and produces
    # FIFO alignment: every step links exactly the produce span of the
    # batch it consumed, and no two steps share one
    linked = [tuple(s["links"][0]) for s in steps if s.get("links")]
    assert len(linked) == len(steps), "a step lost its infeed handoff"
    assert len(set(linked)) == len(linked)
    assert set(linked) <= produces
    # the producer really ran on its own thread
    prod_threads = {s["tname"] for s in spans
                    if s["name"] == "infeed/produce"}
    step_threads = {s["tname"] for s in steps}
    assert prod_threads and prod_threads.isdisjoint(step_threads)


def test_save_spans_link_step_and_parent_writer(traced_train_run):
    spans = traced_train_run
    saves = [s for s in spans if s["name"] == "train/save_blocked"]
    writes = [s for s in spans if s["name"] == "train/save_write"]
    steps = {(s["trace"], s["span"]): s for s in spans
             if s["name"] == "train/step_cycle"}
    assert saves and writes
    for s in saves:
        assert s.get("links") and tuple(s["links"][0]) in steps, \
            "save did not link the step that triggered it"
    save_ids = {s["span"]: s for s in saves}
    for w in writes:
        # writer-thread span parented (cross-thread) to the loop's save
        assert w["parent"] in save_ids
        assert w["trace"] == save_ids[w["parent"]]["trace"]
        assert w["tname"] == "ckpt-writer"


def test_step_breakdown_tool(traced_train_run):
    from tools.trace_report import save_breakdowns, step_breakdowns
    rows = step_breakdowns(traced_train_run)
    assert rows and all("infeed_wait" in r and "step_ms" in r
                        for r in rows)
    assert {r["step"] for r in rows} == set(
        range(1, len(rows) + 1))
    srows = save_breakdowns(traced_train_run)
    assert srows and all(r["save_blocked_ms"] > 0 for r in srows)
    assert all(r["save_write_ms"] is not None for r in srows)


def test_breakdown_primary_and_linked_requests_agree():
    """Regression: the flush's encode/device children share the
    PRIMARY request's trace id — they must be attributed through the
    flush exactly once, so the primary and its coalesced (linked)
    siblings report identical device cost."""
    from tools.trace_report import request_breakdowns
    spans = [
        {"name": "serve/request", "trace": "tA", "span": "r1",
         "t0": 0.0, "dur_ms": 50.0, "tid": 1, "tname": "c1"},
        {"name": "serve/request", "trace": "tB", "span": "r2",
         "t0": 0.0, "dur_ms": 50.0, "tid": 2, "tname": "c2"},
        # flush continues tA, links tB's root
        {"name": "serve/batch_flush", "trace": "tA", "span": "f1",
         "parent": "r1", "links": [["tB", "r2"]],
         "t0": 1.0, "dur_ms": 40.0, "tid": 3, "tname": "batcher"},
        {"name": "serve/encode", "trace": "tA", "span": "e1",
         "parent": "f1", "t0": 1.0, "dur_ms": 10.0, "tid": 3,
         "tname": "batcher"},
        {"name": "serve/device", "trace": "tA", "span": "d1",
         "parent": "f1", "t0": 2.0, "dur_ms": 30.0, "tid": 3,
         "tname": "batcher"},
    ]
    rows = {r["trace"]: r for r in request_breakdowns(spans)}
    assert rows["tA"]["encode"] == rows["tB"]["encode"] == 10.0
    assert rows["tA"]["device"] == rows["tB"]["device"] == 30.0


def test_span_end_is_idempotent_and_error_paths_close_roots(tmp_path):
    """Regression: a failing parse must not leak the request root into
    the live-span table (a long-running traced server would grow it
    unboundedly and pollute every stall dump)."""
    from code2vec_tpu.serving.server import PredictionServer
    tele = Telemetry.create(str(tmp_path), component="t")
    tr = Tracer.create(tele)
    s = tr.start_trace("x")
    assert s.end() > 0.0 or True
    assert s.end() == 0.0          # second end: no-op, no re-emit
    assert tr.live_spans() == []
    tele.close()
    assert sum(1 for e in _events(tele.run_dir)
               if e["kind"] == "span") == 1

    class _BadParseModel(_StubModel):
        def prepare_predict_rows(self, lines):
            raise ValueError("malformed input")

    cfg = Config(SERVE_CACHE_SIZE=0, TRACE=True,
                 TELEMETRY_DIR=str(tmp_path))
    cfg.train_data_path = "unused"
    tele2 = Telemetry.create(str(tmp_path), config=cfg,
                             component="serve").make_threadsafe()
    server = PredictionServer(cfg, _BadParseModel(), telemetry=tele2)
    server.start()
    try:
        for _ in range(3):
            with pytest.raises(ValueError):
                server.predict_lines(["m a,1,b"])
        assert server.tracer.live_spans() == [], \
            "failed requests leaked live spans"
    finally:
        server.close()
    tele2.close()
