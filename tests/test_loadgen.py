"""tools/loadgen.py: quick closed/open-loop smoke stays tier-1; the
long-run (`--duration`) mode is `slow`-marked with the same
marker-registration guard pattern as test_requant_sweep.py, so tier-1
(`-m 'not slow'`) can never silently pay for it."""

import importlib.util
import json
import os

import pytest

from code2vec_tpu.models.jax_model import Code2VecModel
from code2vec_tpu.serving.server import PredictionServer
from tests.helpers import build_tiny_dataset, make_raw_lines
from tests.test_model import tiny_config


def _load_loadgen():
    spec = importlib.util.spec_from_file_location(
        "loadgen",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "loadgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_slow_marker_registered(request):
    """Tier-1 deselects with -m 'not slow'; that only reliably matches
    a REGISTERED marker (pytest.ini)."""
    markers = request.config.getini("markers")
    assert any(str(m).startswith("slow:") for m in markers), markers


def test_gen_corpus_shape_and_distinct():
    lg = _load_loadgen()
    corpus = lg.gen_corpus(8, methods_per_request=2, seed=3)
    assert len(corpus) == 8 and all(len(r) == 2 for r in corpus)
    # distinct salting: no two methods share a normalized bag, so an
    # LRU cache cannot turn a load test into a cache benchmark
    from code2vec_tpu.serving.server import normalize_bag
    bags = [normalize_bag(ln) for req in corpus for ln in req]
    assert len(set(bags)) == len(bags)


@pytest.fixture(scope="module")
def loadgen_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("lg_ds")
    prefix = build_tiny_dataset(str(d), n_train=64, n_val=8, n_test=8,
                                max_contexts=16)
    cfg = tiny_config(prefix)
    return cfg, Code2VecModel(cfg)


def test_open_loop_reports_offered_qps(loadgen_model):
    lg = _load_loadgen()
    cfg, model = loadgen_model
    server = PredictionServer(cfg, model)
    server.start()
    try:
        corpus = [make_raw_lines(1, seed=i) for i in range(8)]
        rep = lg.run_load(server, corpus, mode="open", concurrency=4,
                          qps=200.0)
        assert rep["mode"] == "open" and rep["offered_qps"] == 200.0
        assert rep["ok"] + rep["shed"] + rep["errors"] == 8
        assert rep["errors"] == 0
        assert rep["latency"]["count"] == rep["ok"]
    finally:
        server.close()


@pytest.mark.slow
def test_loadgen_long_run_cli(tmp_path, capsys):
    """Long-run CLI mode: --duration loops the corpus; compare mode
    reports the sequential-vs-batched speedup and the telemetry run
    renders a serving row."""
    lg = _load_loadgen()
    tdir = str(tmp_path / "tele")
    out = str(tmp_path / "report.json")
    rc = lg.main(["--mode", "compare", "--synthetic", "--requests", "32",
                  "--concurrency", "8", "--duration", "3",
                  "--telemetry_dir", tdir, "--out", out])
    assert rc == 0
    with open(out, encoding="utf-8") as f:
        report = json.load(f)
    assert len(report["reports"]) == 2
    assert "speedup" in report
    bat = report["reports"][1]
    assert bat["new_compilations_under_load"] in (0, None) or \
        bat["new_compilations_under_load"] <= 0
    # the telemetry run carries the loadgen events -> serving row
    import importlib.util as _ilu
    spec = _ilu.spec_from_file_location(
        "telemetry_report",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "telemetry_report.py"))
    trep = _ilu.module_from_spec(spec)
    spec.loader.exec_module(trep)
    rendered = trep.render(trep.find_runs(tdir))
    assert "Serving mode" in rendered
    capsys.readouterr()  # swallow loadgen's stdout JSON


def test_loadgen_trace_produces_chrome_trace(loadgen_model, tmp_path):
    """ISSUE 6 acceptance: a traced load run yields a Chrome
    trace-event JSON in which one request's queue -> batch -> device ->
    decode spans share a single trace id (with flow events through the
    batcher flush), and trace_report prints its critical-path
    breakdown with every phase attributed."""
    lg = _load_loadgen()
    cfg, model = loadgen_model
    from code2vec_tpu.obs import Telemetry
    tdir = str(tmp_path / "tele")
    cfg.TRACE = True
    cfg.SERVE_CACHE_SIZE = 0
    tele = Telemetry.create(tdir, config=cfg,
                            component="loadgen").make_threadsafe()
    server = PredictionServer(cfg, model, telemetry=tele)
    server.start()
    try:
        corpus = [make_raw_lines(1, seed=100 + i) for i in range(16)]
        rep = lg.run_load(server, corpus, mode="closed", concurrency=4)
        assert rep["ok"] == 16 and rep["errors"] == 0
    finally:
        server.close()
        cfg.TRACE = False
    tele.close()
    from tools.trace_report import (load_spans, request_breakdowns,
                                    write_chrome_trace)
    out = str(tmp_path / "trace.json")
    n = write_chrome_trace([tele.run_dir], out)
    assert n > 0
    with open(out, encoding="utf-8") as f:
        doc = json.load(f)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    by_trace = {}
    for e in xs:
        by_trace.setdefault(e["args"].get("trace"), set()).add(e["name"])
    chain = {"serve/request", "serve/queue_wait", "serve/batch_flush",
             "serve/device", "serve/decode"}
    assert any(chain <= names for names in by_trace.values()), \
        "no request's full chain shares one trace id"
    assert any(e["ph"] == "s" for e in doc["traceEvents"])
    (_m, spans), = load_spans([tele.run_dir])
    rows = request_breakdowns(spans)
    assert len(rows) == 16
    for r in rows:
        for phase in ("queue_wait", "parse", "encode", "device",
                      "decode"):
            assert r.get(phase, 0.0) > 0.0, (phase, r)
