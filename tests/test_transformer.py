"""Transformer path-encoder (BASELINE.json configs[4]): shape/mask
invariants, permutation equivariance (contexts are a bag), end-to-end
learning vs the bag encoder, checkpoint round-trip, and REAL context
parallelism — the train step on a ('data','ctx','model') = (2,2,2) mesh
with the context dim sharded must match single-device numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from code2vec_tpu.models.encoder import ModelDims, get_encode_fn, \
    init_params
from tests.helpers import build_tiny_dataset, example_batch

DIMS = ModelDims(token_vocab_size=40, path_vocab_size=30,
                 target_vocab_size=20, embeddings_size=16, max_contexts=8,
                 dropout_keep_rate=1.0, encoder_type="transformer",
                 xf_layers=2, xf_heads=4)


def test_init_params_has_xf_subtree():
    p = init_params(jax.random.PRNGKey(0), DIMS)
    assert "xf" in p and len(p["xf"]["layers"]) == 2
    D = DIMS.context_vector_size
    assert p["xf"]["layers"][0]["qkv"].shape == (D, 3 * D)
    # bag dims get no xf subtree
    bag = init_params(jax.random.PRNGKey(0),
                      ModelDims(40, 30, 20, 16, 8))
    assert "xf" not in bag


def test_masked_contexts_do_not_affect_code():
    p = init_params(jax.random.PRNGKey(1), DIMS)
    enc = get_encode_fn(DIMS)
    labels, src, pth, dst, mask, _w = example_batch(3, DIMS, 4)
    mask = np.ones_like(mask)
    mask[:, 5:] = 0.0
    code1, attn1 = enc(p, src, pth, dst, jnp.asarray(mask))
    # change ids ONLY in masked positions
    src2 = src.copy()
    src2[:, 5:] = (src2[:, 5:] + 7) % DIMS.token_vocab_size
    code2, attn2 = enc(p, jnp.asarray(src2), pth, dst, jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(code1), np.asarray(code2),
                               atol=1e-5)
    assert np.all(np.asarray(attn1)[:, 5:] < 1e-6)


def test_permutation_equivariance_of_code():
    """Contexts are an unordered bag: permuting them (and the mask) must
    not change the code vector."""
    p = init_params(jax.random.PRNGKey(2), DIMS)
    enc = get_encode_fn(DIMS)
    labels, src, pth, dst, mask, _w = example_batch(4, DIMS, 4)
    perm = np.random.default_rng(0).permutation(DIMS.max_contexts)
    code1, _ = enc(p, src, pth, dst, jnp.asarray(mask))
    code2, _ = enc(p, jnp.asarray(src[:, perm]), jnp.asarray(pth[:, perm]),
                   jnp.asarray(dst[:, perm]), jnp.asarray(mask[:, perm]))
    np.testing.assert_allclose(np.asarray(code1), np.asarray(code2),
                               atol=1e-4)


def test_all_pad_row_is_finite():
    p = init_params(jax.random.PRNGKey(3), DIMS)
    enc = get_encode_fn(DIMS)
    labels, src, pth, dst, mask, _w = example_batch(5, DIMS, 2)
    mask = np.zeros_like(mask)
    code, attn = enc(p, src, pth, dst, jnp.asarray(mask))
    assert np.all(np.isfinite(np.asarray(code)))
    assert np.all(np.isfinite(np.asarray(attn)))


def test_transformer_train_step_learns():
    from code2vec_tpu.training.steps import make_train_step

    p = init_params(jax.random.PRNGKey(0), DIMS)
    opt = optax.adam(3e-3)
    step = make_train_step(DIMS, opt)
    state = opt.init(p)
    batch = tuple(jnp.asarray(a) for a in example_batch(7, DIMS, 16))
    rng = jax.random.PRNGKey(0)
    losses = []
    for _ in range(30):
        rng, k = jax.random.split(rng)
        p, state, loss = step(p, state, batch, k)
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    assert np.isfinite(losses[-1])


@pytest.mark.parametrize("ring", [False, True],
                         ids=["xla-allgather", "ring-attention"])
def test_context_parallel_matches_single_device(ring):
    """(data=2, ctx=2, model=2) mesh, context dim sharded — via XLA's
    inserted collectives or explicit ring attention (K/V ppermute
    rotation): numerics must match one device either way."""
    import dataclasses

    from code2vec_tpu.parallel.mesh import make_mesh
    from code2vec_tpu.parallel.sharding import (shard_batch,
                                                shard_opt_state,
                                                shard_params)
    from code2vec_tpu.training.steps import make_train_step

    dims = ModelDims(token_vocab_size=40, path_vocab_size=30,
                     target_vocab_size=20, embeddings_size=16,
                     max_contexts=8, dropout_keep_rate=1.0,
                     encoder_type="transformer", xf_layers=2, xf_heads=4,
                     vocab_pad_multiple=2)
    params = init_params(jax.random.PRNGKey(0), dims)
    opt = optax.adam(1e-2)
    batch = tuple(jnp.asarray(a) for a in example_batch(9, dims, 8))
    rng = jax.random.PRNGKey(1)

    step = make_train_step(dims, opt)
    p1, _, loss1 = step(jax.tree_util.tree_map(jnp.copy, params),
                        opt.init(params), batch, rng)

    mesh = make_mesh(2, 2, 2)
    assert dict(mesh.shape) == {"dcn": 1, "data": 2, "ctx": 2,
                                "model": 2}
    dims2 = dataclasses.replace(dims, ring_attention=ring)
    sp = shard_params(mesh, params)
    so = shard_opt_state(mesh, opt.init(sp), sp)
    sb = shard_batch(mesh, batch, shard_contexts=True)
    # [B, C] tensors really are context-sharded
    assert "ctx" in str(sb[1].sharding.spec)
    step2 = make_train_step(dims2, opt, mesh=mesh if ring else None)
    p2, _, loss2 = step2(sp, so, sb, rng)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    flat1, tree1 = jax.tree_util.tree_flatten(p1)
    flat2, tree2 = jax.tree_util.tree_flatten(p2)
    assert tree1 == tree2
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)),
                                   atol=2e-5)


def test_transformer_model_end_to_end(tmp_path):
    """Tiny dataset: transformer encoder trains through the full model
    class, ties/beats the bag encoder's F1, and round-trips its
    checkpoint (encoder config from the manifest)."""
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.test_model import tiny_config

    prefix = build_tiny_dataset(str(tmp_path), n_train=256, n_val=32,
                                n_test=64, max_contexts=16)
    cfg = tiny_config(prefix, ENCODER_TYPE="transformer", XF_LAYERS=2,
                      XF_HEADS=4, NUM_TRAIN_EPOCHS=8, LEARNING_RATE=0.01)
    ckpt_dir = str(tmp_path / "ckpt")
    cfg.save_path = ckpt_dir
    model = Code2VecModel(cfg)
    model.train()
    xf_eval = model.evaluate()
    assert xf_eval.subtoken_f1 > 0.5
    model.save(ckpt_dir)

    cfg2 = tiny_config(prefix)   # encoder comes from the manifest
    cfg2.load_path = ckpt_dir
    model2 = Code2VecModel(cfg2)
    assert model2.dims.encoder_type == "transformer"
    loaded = model2.evaluate()
    assert loaded.topk_acc == pytest.approx(xf_eval.topk_acc)


def test_xf_remat_identical_numerics():
    """xf_remat recomputes activations in backward but must not change
    forward values or gradients (CodeBERT-depth memory knob)."""
    import dataclasses

    from code2vec_tpu.training.steps import make_train_step
    dims_r = dataclasses.replace(DIMS, xf_remat=True)
    p = init_params(jax.random.PRNGKey(5), DIMS)
    labels, src, pth, dst, mask, w = example_batch(5, DIMS, 4)
    batch = tuple(jnp.asarray(a) for a in
                  (labels, src, pth, dst, mask, w))
    opt = optax.adam(0.01)
    outs = []
    for d in (DIMS, dims_r):
        step = make_train_step(d, opt)
        p2, _, loss = step(jax.tree_util.tree_map(jnp.copy, p),
                           opt.init(p), batch, jax.random.PRNGKey(6))
        outs.append((np.asarray(p2["xf"]["layers"][0]["qkv"]),
                     float(loss)))
    np.testing.assert_allclose(outs[0][1], outs[1][1], rtol=1e-6)
    np.testing.assert_allclose(outs[0][0], outs[1][0], atol=1e-6)


def test_checkpoint_roundtrips_xf_remat(tmp_path):
    import dataclasses

    from code2vec_tpu.training import checkpoint as ckpt
    from code2vec_tpu.vocab.vocabularies import (Code2VecVocabs, Vocab,
                                                 VocabType)
    dims_r = dataclasses.replace(DIMS, xf_remat=True)
    p = init_params(jax.random.PRNGKey(7), dims_r)
    vocabs = Code2VecVocabs(Vocab(VocabType.Token, ["a"]),
                            Vocab(VocabType.Path, ["1"]),
                            Vocab(VocabType.Target, ["t"]))
    ckpt.save_checkpoint(str(tmp_path / "c"), {"params": p, "step": 0},
                         0, vocabs, dims_r)
    assert ckpt.load_dims(str(tmp_path / "c")).xf_remat is True
