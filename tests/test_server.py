"""Batched serving subsystem (ISSUE 3): micro-batcher flush/shed logic,
LRU prediction cache, admission control, predict shape bucketing, and
the CPU loadgen acceptance check (batched >= 3x sequential throughput
at concurrency 8 with zero post-warmup jit compilations). All CPU-only
tier-1 (conftest pins JAX_PLATFORMS=cpu)."""

import threading
import time

import numpy as np
import pytest

from code2vec_tpu.models.jax_model import Code2VecModel, PreparedRows
from code2vec_tpu.obs import Telemetry
from code2vec_tpu.serving.batcher import (MicroBatcher, PredictRequest,
                                          ServerOverloaded)
from code2vec_tpu.serving.server import (PredictionCache,
                                         PredictionServer, normalize_bag)
from tests.helpers import build_tiny_dataset, make_raw_lines
from tests.test_model import tiny_config


# ---------------------------------------------------------------------
# micro-batcher unit tests (no model, no jax compute)
# ---------------------------------------------------------------------

def _rows(n):
    """Opaque batcher payload standing in for PreparedRows."""
    return list(range(n))


def _echo_batch_fn(record):
    def fn(requests):
        record.append([r.n for r in requests])
        return [f"batch{len(record)}:{r.n}" for r in requests]
    return fn


def test_batcher_flushes_on_max_batch():
    batches = []
    b = MicroBatcher(_echo_batch_fn(batches), max_batch=4,
                     timeout_ms=10_000, queue_depth=16)
    b.start()
    try:
        reqs = [PredictRequest(_rows(1), 1) for _ in range(4)]
        for r in reqs:
            assert b.submit(r)
        # max_batch reached -> flush long before the 10s window
        for r in reqs:
            assert r.wait(5), "batch did not flush on max_batch"
        assert batches and sum(batches[0]) == 4
    finally:
        b.stop()


def test_batcher_flushes_on_timeout():
    batches = []
    b = MicroBatcher(_echo_batch_fn(batches), max_batch=64,
                     timeout_ms=30, queue_depth=16)
    b.start()
    try:
        req = PredictRequest(_rows(2), 2)
        t0 = time.monotonic()
        assert b.submit(req)
        assert req.wait(5), "batch did not flush on timeout"
        elapsed_ms = (time.monotonic() - t0) * 1e3
        assert elapsed_ms < 5_000
        assert batches == [[2]]
        assert req.result == "batch1:2"
    finally:
        b.stop()


def test_batcher_never_splits_a_request():
    batches = []
    b = MicroBatcher(_echo_batch_fn(batches), max_batch=4,
                     timeout_ms=0, queue_depth=16)
    # submit before start so both are queued when the thread wakes
    big, small = PredictRequest(_rows(3), 3), PredictRequest(_rows(3), 3)
    b.start()
    try:
        assert b.submit(big) and b.submit(small)
        assert big.wait(5) and small.wait(5)
        # 3 + 3 > max_batch=4: two flushes, payloads intact
        assert [sum(bt) for bt in batches] == [3, 3]
    finally:
        b.stop()


def test_batcher_queue_full_refuses_submit():
    unblock = threading.Event()

    def slow_fn(requests):
        unblock.wait(10)
        return [None] * len(requests)

    b = MicroBatcher(slow_fn, max_batch=1, timeout_ms=0, queue_depth=2)
    b.start()
    try:
        first = PredictRequest(_rows(1), 1)
        assert b.submit(first)
        time.sleep(0.05)  # batcher thread now blocked in slow_fn
        assert b.submit(PredictRequest(_rows(1), 1))
        assert b.submit(PredictRequest(_rows(1), 1))
        # queue holds queue_depth=2 -> admission control refuses
        assert not b.submit(PredictRequest(_rows(1), 1))
    finally:
        unblock.set()
        b.stop()


def test_batcher_sheds_expired_requests():
    tele = Telemetry.memory("test").make_threadsafe()
    release = threading.Event()

    def gated_fn(requests):
        release.wait(10)
        return ["served"] * len(requests)

    b = MicroBatcher(gated_fn, max_batch=8, timeout_ms=0, queue_depth=8,
                     telemetry=tele)
    b.start()
    try:
        blocker = PredictRequest(_rows(1), 1)
        assert b.submit(blocker)
        time.sleep(0.05)  # batcher blocked serving `blocker`
        expired = PredictRequest(_rows(1), 1,
                                 deadline=time.monotonic() + 0.05)
        assert b.submit(expired)
        time.sleep(0.15)  # deadline passes while queued
        release.set()
        assert expired.wait(5)
        assert isinstance(expired.error, ServerOverloaded)
        assert tele.counters.get("serve/shed") == 1
        assert blocker.wait(5) and blocker.result == "served"
    finally:
        release.set()
        b.stop()


def test_batcher_stop_fails_pending():
    b = MicroBatcher(lambda reqs: [None] * len(reqs), max_batch=8,
                     timeout_ms=0, queue_depth=8)
    # never started: queued requests must still resolve on stop
    b._running = True  # allow submit without a consumer thread
    req = PredictRequest(_rows(1), 1)
    assert b.submit(req)
    b.stop()
    assert req.wait(1)
    assert isinstance(req.error, ServerOverloaded)


# ---------------------------------------------------------------------
# prediction cache
# ---------------------------------------------------------------------

def test_normalize_bag_is_order_insensitive():
    a = normalize_bag("get|x a,1,b c,2,d")
    b = normalize_bag("get|x c,2,d a,1,b")
    assert a == b
    assert normalize_bag("get|x a,1,b ,, ") == \
        normalize_bag("get|x a,1,b")
    assert normalize_bag("set|x a,1,b") != a


def test_prediction_cache_lru_eviction():
    cache = PredictionCache(2)
    cache.put("k1", "v1")
    cache.put("k2", "v2")
    assert cache.get("k1") == "v1"  # refresh k1
    cache.put("k3", "v3")  # evicts k2 (least recent)
    assert cache.get("k2") is None
    assert cache.get("k1") == "v1" and cache.get("k3") == "v3"
    assert len(cache) == 2


def test_prediction_cache_zero_capacity_disables():
    cache = PredictionCache(0)
    cache.put("k", "v")
    assert cache.get("k") is None
    assert len(cache) == 0


# ---------------------------------------------------------------------
# server over the real model (CPU, tiny dims, untrained — latency and
# batching are shape-dependent, not value-dependent)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def served_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("serve_ds")
    prefix = build_tiny_dataset(str(d), n_train=64, n_val=8, n_test=8,
                                max_contexts=16)
    cfg = tiny_config(prefix)
    return cfg, Code2VecModel(cfg)


def _corpus(n_requests, methods=1, seed=11):
    lines = make_raw_lines(n_requests * methods, seed=seed, max_ctx=12)
    return [lines[i * methods:(i + 1) * methods]
            for i in range(n_requests)]


def test_server_matches_direct_predict(served_model):
    cfg, model = served_model
    server = PredictionServer(cfg, model)
    server.start()
    try:
        lines = make_raw_lines(3, seed=5)
        direct = model.predict(lines)
        served = server.predict_lines(lines)
        assert len(served) == len(direct) == 3
        for a, b in zip(direct, served):
            assert a.original_name == b.original_name
            assert [p["name"] for p in a.predictions] == \
                [p["name"] for p in b.predictions]
            np.testing.assert_allclose(
                [p["probability"] for p in a.predictions],
                [p["probability"] for p in b.predictions], rtol=1e-6)
    finally:
        server.close()


def test_serve_kill_failpoint_on_request_path(served_model):
    """ISSUE 13 satellite (ROADMAP item 1's hook): the `serve/kill`
    failpoint sits on the replica request path, symmetric with
    serve/extract — armed, it fires before any span opens; disarmed
    (the default), it is one None check. The real scenario arms it
    with action `kill` (replica SIGKILL); here `raise` proves the
    seam without killing the test process."""
    from code2vec_tpu.resilience import FaultInjected, faults
    cfg, model = served_model
    server = PredictionServer(cfg, model)
    server.start()
    try:
        faults.install({"seed": 0, "sites": {
            "serve/kill": {"action": "raise", "at": 1}}},
            log=lambda _m: None)
        with pytest.raises(FaultInjected):
            server.predict_lines(make_raw_lines(1, seed=3))
        assert faults.stats()["serve/kill"]["fired"] == 1
        faults.clear()
        # the seam leaked nothing: the next request serves normally
        assert len(server.predict_lines(make_raw_lines(1,
                                                       seed=3))) == 1
    finally:
        faults.clear()
        server.close()


def test_server_cache_hits_skip_device(served_model):
    cfg, model = served_model
    server = PredictionServer(cfg, model)
    server.start()
    try:
        lines = make_raw_lines(4, seed=21)
        first = server.predict_lines(lines)
        predict_calls = server.telemetry.timer("serve/predict_ms").count
        again = server.predict_lines(list(reversed(lines)))
        # all four methods hit the normalized-bag cache: no new device
        # call, no new encode
        assert server.telemetry.counters["serve/cache_hit"] == 4
        assert server.telemetry.timer("serve/predict_ms").count == \
            predict_calls
        for r, expect in zip(again, reversed(first)):
            assert r.original_name == expect.original_name
            assert [p["name"] for p in r.predictions] == \
                [p["name"] for p in expect.predictions]
    finally:
        server.close()


def test_predict_bucketing_pow2_and_mesh_divisible(served_model):
    """Satellite: padded leading dim = next power of two, rounded to a
    mesh-data-axis multiple; method counts in the same bucket reuse ONE
    compiled variant."""
    cfg, model = served_model
    dax = 1
    if model.mesh is not None:
        from code2vec_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS
        dax = model.mesh.shape[DATA_AXIS] * model.mesh.shape[DCN_AXIS]
    for n, pow2 in ((1, 1), (2, 2), (3, 4), (5, 8), (8, 8), (9, 16)):
        expect = -(-pow2 // dax) * dax
        got = model.predict_bucket_size(n)
        assert got == expect, (n, got, expect)
        assert got & (got - 1) == 0 or got % dax == 0
        assert got % dax == 0, "bucket must divide over the mesh"

    # shapes actually dispatched + compile-count reuse
    dispatched = []
    orig_step = model._predict_step

    def capture(params, batch):
        dispatched.append(int(batch[1].shape[0]))
        return orig_step(params, batch)

    model._predict_step = capture
    try:
        model.predict(make_raw_lines(3, seed=1))
        model.predict(make_raw_lines(5, seed=2))
    finally:
        model._predict_step = orig_step
    assert dispatched == [model.predict_bucket_size(3),
                          model.predict_bucket_size(5)]

    n_compiled = model.predict_compile_count()
    if n_compiled >= 0 and \
            model.predict_bucket_size(3) == model.predict_bucket_size(5):
        # same bucket -> the two calls shared one compiled variant
        before = n_compiled
        model.predict(make_raw_lines(4, seed=3))  # also same bucket
        assert model.predict_compile_count() == before


def test_oversized_request_chunks_to_warmed_buckets(served_model):
    """A request with more methods than --serve_batch_max must chunk to
    the warmed buckets (no unwarmed jit compile under load) and come
    back in input order."""
    cfg, model = served_model
    cfg.SERVE_CACHE_SIZE = 0
    cfg.SERVE_BATCH_MAX = 8
    server = PredictionServer(cfg, model)
    server.start()
    try:
        compiled = model.predict_compile_count()
        lines = make_raw_lines(20, seed=31)  # 20 > 8 -> chunks 8/8/4
        res = server.predict_lines(lines)
        assert len(res) == 20
        assert [r.original_name for r in res] == \
            [ln.split(" ", 1)[0] for ln in lines]
        if compiled >= 0:
            assert model.predict_compile_count() == compiled
    finally:
        server.close()


def test_overcap_downsample_is_position_and_order_invariant(served_model):
    """The over-MAX_CONTEXTS downsample seeds from the normalized bag:
    the same method samples the same contexts regardless of its batch
    position or context order — the property the prediction cache's
    bag key assumes."""
    import random

    from code2vec_tpu.data.reader import parse_c2v_rows
    _, model = served_model
    ctxs = [f"a{k},1,b{k}" for k in range(40)]  # 40 > MAX_CONTEXTS=16
    line = "get|value " + " ".join(ctxs)
    shuffled = "get|value " + " ".join(
        random.Random(0).sample(ctxs, len(ctxs)))
    alone = parse_c2v_rows([line], model.vocabs, 16, keep_strings=True)
    behind = parse_c2v_rows(["noise a,1,b", line], model.vocabs, 16,
                            keep_strings=True)
    np.testing.assert_array_equal(alone[1][0], behind[1][1])  # src rows
    np.testing.assert_array_equal(alone[2][0], behind[2][1])  # pth rows
    reordered = parse_c2v_rows([shuffled], model.vocabs, 16,
                               keep_strings=True)
    assert sorted(alone[6][0]) == sorted(reordered[6][0])  # same SET


def test_overloaded_queue_sheds_within_deadline():
    """Acceptance: a saturated queue returns ServerOverloaded within the
    deadline instead of queueing unboundedly. Uses a stub model whose
    device phase blocks, so saturation is deterministic."""

    class _StubModel:
        telemetry = Telemetry.disabled()
        release_batches = threading.Event()

        def prepare_predict_rows(self, lines):
            n = len([ln for ln in lines if ln.strip()])
            z = np.zeros((n, 4), np.int32)
            return PreparedRows(np.zeros((n,), np.int32), z, z, z,
                                z.astype(np.float32),
                                ["m"] * n, [[] for _ in range(n)])

        def predict_device(self, prepared):
            self.release_batches.wait(10)
            n = prepared.n
            return (np.zeros((n, 1), np.int32),
                    np.zeros((n, 1), np.float32),
                    np.zeros((n, 4), np.float32),
                    np.zeros((n, 4), np.float32))

        def decode_predictions(self, prepared, device_out):
            return ["res"] * prepared.n

        def warmup_predict(self, max_batch):
            return [1]

        def predict_compile_count(self):
            return 0

    from code2vec_tpu.config import Config
    cfg = Config(SERVE_QUEUE_DEPTH=2, SERVE_BATCH_MAX=1,
                 SERVE_BATCH_TIMEOUT_MS=0.0, SERVE_DEADLINE_MS=200.0)
    cfg.train_data_path = "unused"  # bypass verify's train-or-load rule
    model = _StubModel()
    server = PredictionServer(cfg, model)
    server.start()
    outcomes = []
    out_lock = threading.Lock()

    def client(i):
        t0 = time.monotonic()
        try:
            server.predict_lines([f"m a,{i},b"])
            with out_lock:
                outcomes.append(("ok", time.monotonic() - t0))
        except ServerOverloaded:
            with out_lock:
                outcomes.append(("shed", time.monotonic() - t0))

    try:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(10)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # all deadlines (200 ms) long expired
        model.release_batches.set()
        for t in threads:
            t.join(timeout=10)
        assert len(outcomes) == 10, "a client blocked unboundedly"
        shed = [dt for kind, dt in outcomes if kind == "shed"]
        assert shed, "saturation never shed load"
        # queue-full refusals are immediate; deadline sheds resolve
        # within deadline + one batch window + scheduling slack
        for dt in shed:
            assert dt < 0.2 + 5.5, f"shed took {dt:.2f}s"
        assert server.telemetry.counters.get("serve/shed", 0) >= len(shed)
    finally:
        model.release_batches.set()
        server.close()


def test_batched_serving_3x_sequential_throughput(served_model):
    """ISSUE 3 acceptance: at concurrency >= 8, batched serving >= 3x
    the sequential one-request-at-a-time path on the same synthetic
    corpus, with zero jit compilations after bucket warmup."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "loadgen", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    cfg, model = served_model
    corpus = _corpus(128, methods=1)
    model.warmup_predict(1)  # sequential pays no compile either

    cfg.SERVE_CACHE_SIZE = 0  # throughput, not cache, is under test
    # closed-loop with matched batch cap: batches flush on max, not on
    # the coalescing window (16 workers are all blocked while a batch
    # is in flight, so a larger cap would only add window dead-time)
    cfg.SERVE_BATCH_MAX = 16
    cfg.SERVE_BATCH_TIMEOUT_MS = 5.0
    server = PredictionServer(cfg, model)
    server.start()
    try:
        compiled = model.predict_compile_count()
        # paired trials, best-of-3: the bar is a REAL >= 3x, but this
        # box may have as few as 2 cores, and one descheduled batcher
        # thread wrecks a single sample — correctness asserts below
        # still hold on every trial
        speedups = []
        for _ in range(3):
            seq = loadgen.run_sequential(model, corpus)
            bat = loadgen.run_load(server, corpus, mode="closed",
                                   concurrency=16)
            assert bat["ok"] == 128 and bat["shed"] == 0 and \
                bat["errors"] == 0
            speedups.append(bat["throughput_rps"]
                            / seq["throughput_rps"])
            if speedups[-1] >= 3.0:
                break
        if compiled >= 0:
            assert model.predict_compile_count() == compiled, \
                "serving under load triggered a jit compilation"
        assert max(speedups) >= 3.0, (
            f"batched vs sequential speedups {speedups} — all < 3x")
        # batches actually coalesced (not 128 singleton flushes)
        n_batches = server.telemetry.counters["serve/batches"]
        assert n_batches < 64 * len(speedups)
    finally:
        server.close()
