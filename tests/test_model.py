"""Integration tests (SURVEY.md §5): tiny synthetic dataset -> short train
-> loss decreases & F1 beats naive; checkpoint -> resume continuity;
release + predict round-trip."""

import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.models.jax_model import Code2VecModel
from tests.helpers import build_tiny_dataset, make_raw_lines


def tiny_config(prefix, **kw):
    cfg = Config(
        MAX_CONTEXTS=16,
        MAX_TOKEN_VOCAB_SIZE=1000,
        MAX_PATH_VOCAB_SIZE=1000,
        MAX_TARGET_VOCAB_SIZE=1000,
        DEFAULT_EMBEDDINGS_SIZE=16,
        TRAIN_BATCH_SIZE=32,
        TEST_BATCH_SIZE=32,
        NUM_TRAIN_EPOCHS=6,
        SAVE_EVERY_EPOCHS=100,  # no mid-train saves unless asked
        NUM_BATCHES_TO_LOG_PROGRESS=1000,
        LEARNING_RATE=0.05,
        USE_BF16=False,
        MESH_MODEL_AXIS=1,
    )
    cfg.train_data_path = prefix
    cfg.test_data_path = prefix + ".test.c2v"
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("data")
    return build_tiny_dataset(str(d), n_train=256, n_val=32, n_test=64,
                              max_contexts=16)


def test_train_loss_decreases_and_f1_beats_naive(dataset, tmp_path):
    cfg = tiny_config(dataset, save_path=str(tmp_path / "ckpt"))
    model = Code2VecModel(cfg)

    # capture initial loss via one eval pass
    before = model.evaluate()
    model.train()
    after = model.evaluate()
    assert after.loss < before.loss
    # synthetic data is learnable: expect real F1, far above a naive
    # always-predict-most-frequent baseline on 8 balanced classes
    assert after.subtoken_f1 > 0.5
    assert after.topk_acc[0] > 0.3
    model.save(str(tmp_path / "ckpt"))


def test_checkpoint_resume_continuity(dataset, tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=2)
    cfg.save_path = ckpt_dir
    model = Code2VecModel(cfg)
    model.train()
    model.save(ckpt_dir)
    saved_eval = model.evaluate()
    step_before = model.step_num

    cfg2 = tiny_config(dataset)
    cfg2.load_path = ckpt_dir
    model2 = Code2VecModel(cfg2)
    assert model2.step_num == step_before
    loaded_eval = model2.evaluate()
    # same params -> metric continuity
    assert abs(loaded_eval.loss - saved_eval.loss) < 1e-4
    assert loaded_eval.topk_acc == pytest.approx(saved_eval.topk_acc)
    # vocab sidecar round-trip
    assert (model2.vocabs.target_vocab.word_to_index
            == model.vocabs.target_vocab.word_to_index)


def test_release_and_predict_roundtrip(dataset, tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=2)
    cfg.save_path = ckpt_dir
    model = Code2VecModel(cfg)
    model.train()
    model.save(ckpt_dir)

    release_dir = str(tmp_path / "released")
    cfg_rel = tiny_config(dataset)
    cfg_rel.load_path = ckpt_dir
    cfg_rel.save_path = release_dir
    model_rel = Code2VecModel(cfg_rel)
    model_rel.release()

    cfg3 = tiny_config(dataset, export_code_vectors=True)
    cfg3.train_data_path = None
    cfg3.load_path = release_dir
    model3 = Code2VecModel(cfg3)
    lines = make_raw_lines(3, seed=9, max_ctx=10)
    results = model3.predict(lines)
    assert len(results) == 3
    r = results[0]
    assert r.original_name
    assert len(r.predictions) >= 1
    assert all(0.0 <= p["probability"] <= 1.0 for p in r.predictions)
    # attention paths sorted descending, only valid contexts
    scores = [a.attention_score for a in r.attention_paths]
    assert scores == sorted(scores, reverse=True)
    assert len(scores) >= 1
    assert r.code_vector is not None and r.code_vector.shape == (48,)


def test_w2v_export(dataset, tmp_path):
    from code2vec_tpu.vocab.vocabularies import VocabType
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=1)
    model = Code2VecModel(cfg)
    dest = str(tmp_path / "tokens.w2v")
    model.save_word2vec_format(dest, VocabType.Token)
    with open(dest) as f:
        header = f.readline().split()
        n, dim = int(header[0]), int(header[1])
        assert dim == 16
        lines = f.readlines()
        assert len(lines) == n
        first = lines[0].split()
        assert first[0] == "<PAD>" and len(first) == dim + 1


def test_sampled_softmax_training_works(dataset, tmp_path):
    cfg = tiny_config(dataset, USE_SAMPLED_SOFTMAX=True,
                      NUM_SAMPLED_CLASSES=6, NUM_TRAIN_EPOCHS=6)
    model = Code2VecModel(cfg)
    before = model.evaluate()
    model.train()
    after = model.evaluate()
    assert after.loss < before.loss
    assert after.topk_acc[0] > 0.2


def test_profile_flag_writes_trace(dataset, tmp_path):
    import os
    trace_dir = str(tmp_path / "trace")
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=1,
                      PROFILE_DIR=trace_dir, PROFILE_START_STEP=1,
                      PROFILE_STEPS=3)
    model = Code2VecModel(cfg)
    model.train()
    # jax.profiler writes plugins/profile/<run>/*.xplane.pb under the dir
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found.extend(f for f in files if f.endswith(".xplane.pb"))
    assert found, f"no trace files under {trace_dir}"


def test_cosine_lr_schedule_trains_and_resumes(dataset, tmp_path):
    ckpt = str(tmp_path / "ckpt")
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=4, LR_SCHEDULE="cosine",
                      save_path=ckpt)
    model = Code2VecModel(cfg)
    before = model.evaluate()
    model.train()
    after = model.evaluate()
    assert after.loss < before.loss
    model.save(ckpt)

    # resume restores schedule structure from the manifest even though
    # the fresh config requests a DIFFERENT schedule (the manifest must
    # win or the opt_state template won't match)
    cfg2 = tiny_config(dataset, NUM_TRAIN_EPOCHS=1,
                       LR_SCHEDULE="constant")
    cfg2.load_path = ckpt
    model2 = Code2VecModel(cfg2)
    assert cfg2.LR_SCHEDULE == "cosine"
    loaded = model2.evaluate()
    assert abs(loaded.loss - after.loss) < 1e-4
    model2.train()  # one more epoch continues without structure errors

    # eval-only load (no train data): the opt_state template must still
    # carry the schedule structure or orbax restore fails
    cfg3 = tiny_config(dataset)
    cfg3.train_data_path = None
    cfg3.load_path = ckpt
    model3 = Code2VecModel(cfg3)
    eval_only = model3.evaluate()
    assert abs(eval_only.loss - after.loss) < 1e-4


def test_warmup_trust_ratio_trains_and_resumes(dataset, tmp_path):
    """The large-global-batch recipe (warmup_cosine + LAMB-style trust
    ratio; BASELINE.md round-4 study): trains, saves, and a resume gets
    BOTH structure-affecting settings back from the manifest even when
    the fresh config asks for the defaults."""
    ckpt = str(tmp_path / "ckpt")
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=4,
                      LR_SCHEDULE="warmup_cosine", LR_WARMUP_STEPS=3,
                      TRUST_RATIO=True, save_path=ckpt)
    model = Code2VecModel(cfg)
    before = model.evaluate()
    model.train()
    after = model.evaluate()
    assert after.loss < before.loss
    model.save(ckpt)

    cfg2 = tiny_config(dataset, NUM_TRAIN_EPOCHS=1,
                       LR_SCHEDULE="constant")
    cfg2.load_path = ckpt
    model2 = Code2VecModel(cfg2)
    assert cfg2.LR_SCHEDULE == "warmup_cosine"
    assert cfg2.TRUST_RATIO is True
    # warmup length is restored too — the resumed schedule must follow
    # the original trajectory, not an auto length from the new horizon
    assert cfg2.LR_WARMUP_STEPS == 3
    loaded = model2.evaluate()
    assert abs(loaded.loss - after.loss) < 1e-4
    model2.train()  # structure matches; training continues

    # eval-only load (no train data, schedule horizon 1): the
    # warmup_cosine schedule must still build — optax needs positive
    # cosine steps past the warmup (caught by /verify in round 4)
    cfg3 = tiny_config(dataset)
    cfg3.train_data_path = None
    cfg3.load_path = ckpt
    model3 = Code2VecModel(cfg3)
    eval_only = model3.evaluate()
    assert abs(eval_only.loss - after.loss) < 1e-4


def test_tensorboard_scalars_written(dataset, tmp_path):
    import os
    tb = str(tmp_path / "tb")
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=2,
                      NUM_BATCHES_TO_LOG_PROGRESS=2,
                      SAVE_EVERY_EPOCHS=1, TENSORBOARD_DIR=tb)
    model = Code2VecModel(cfg)
    model.train()
    events = []
    for root, _d, files in os.walk(tb):
        events.extend(f for f in files if "tfevents" in f)
    assert events, f"no event files under {tb}"


def test_auto_resume_via_cli_dispatch(dataset, tmp_path, monkeypatch):
    """--auto_resume turns a rerun into a resume OF ITSELF (round-15
    semantics, the supervisor's contract): the restored step counts
    toward NUM_TRAIN_EPOCHS, so a partially-finished run trains only
    the REMAINING epochs and a completed run's rerun is a no-op — not
    the old behavior of training the full epoch budget again."""
    import sys

    import code2vec as cli
    ckpt = str(tmp_path / "ckpt")

    def run(epochs):
        monkeypatch.setattr(sys, "argv", [
            "code2vec.py", "--data", dataset, "--save", ckpt,
            "--epochs", str(epochs), "--batch_size", "32",
            "--max_contexts", "16", "--auto_resume"])
        assert cli.main() == 0
        from code2vec_tpu.training.checkpoint import latest_step
        return latest_step(ckpt)

    step1 = run(1)
    assert step1 and step1 > 0
    # raise the epoch budget: the rerun resumes from the checkpoint and
    # trains exactly the ONE remaining epoch
    step2 = run(2)
    assert step2 == 2 * step1
    # rerun the COMPLETED command: nothing left to train, step unchanged
    step3 = run(2)
    assert step3 == step2


def test_auto_resume_ignores_torn_checkpoint_dir(dataset, tmp_path,
                                                 monkeypatch):
    """A step dir without a committed `state` (preemption mid-save) must
    be invisible to latest_step, so auto-resume restarts cleanly."""
    import os

    from code2vec_tpu.training.checkpoint import latest_step
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(os.path.join(ckpt, "step_7"))  # torn: no state/ inside
    assert latest_step(ckpt) is None

    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=1, SAVE_EVERY_EPOCHS=1,
                      save_path=ckpt)
    model = Code2VecModel(cfg)
    model.train()
    assert latest_step(ckpt) == model.step_num  # real save is visible
