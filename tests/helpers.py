"""Shared test fixtures: tiny synthetic extractor output + dataset build."""

from __future__ import annotations

import os
import random

from code2vec_tpu.data import binarize as binarize_mod
from code2vec_tpu.data import preprocess as preprocess_mod
from code2vec_tpu.vocab.vocabularies import Code2VecVocabs

TOKENS = ["foo", "bar", "baz", "qux", "value", "name", "index", "count"]
PATHS = [str(h) for h in (123456, -98765, 424242, 1337, -777, 31415)]
TARGETS = ["get|value", "set|value", "get|name", "set|name", "add|item",
           "remove|item", "to|string", "is|empty"]


def make_raw_lines(n: int, seed: int = 0, max_ctx: int = 12):
    """Synthetic extractor-format lines: `target tok,path,tok ...` where
    the target is (weakly) recoverable from the contexts: target class k
    biases which tokens/paths appear."""
    rng = random.Random(seed)
    lines = []
    for _ in range(n):
        t_idx = rng.randrange(len(TARGETS))
        target = TARGETS[t_idx]
        n_ctx = rng.randint(1, max_ctx)
        ctxs = []
        for _ in range(n_ctx):
            # bias token/path choice by the target class so the model can
            # actually learn the mapping
            tok_a = TOKENS[(t_idx + rng.randrange(2)) % len(TOKENS)]
            tok_b = TOKENS[(t_idx * 3 + rng.randrange(2)) % len(TOKENS)]
            path = PATHS[t_idx % len(PATHS)] if rng.random() < 0.7 \
                else rng.choice(PATHS)
            ctxs.append(f"{tok_a},{path},{tok_b}")
        lines.append(target + " " + " ".join(ctxs))
    return lines


def example_batch(seed: int, dims, batch: int):
    """Deterministic synthetic device-batch tuple in the train-step format
    (labels, src, pth, dst, mask, weights)."""
    import numpy as np
    r = np.random.default_rng(seed)
    C = dims.max_contexts
    labels = r.integers(0, dims.target_vocab_size, (batch,)).astype(np.int32)
    src = r.integers(0, dims.token_vocab_size, (batch, C)).astype(np.int32)
    pth = r.integers(0, dims.path_vocab_size, (batch, C)).astype(np.int32)
    dst = r.integers(0, dims.token_vocab_size, (batch, C)).astype(np.int32)
    mask = (r.random((batch, C)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0
    weights = np.ones((batch,), dtype=np.float32)
    return labels, src, pth, dst, mask, weights


def build_tiny_dataset(tmpdir: str, n_train: int = 256, n_val: int = 32,
                       n_test: int = 64, max_contexts: int = 16,
                       binarize: bool = False) -> str:
    """Write raw lines, run preprocess (+ optional binarize); returns the
    dataset prefix."""
    raw_train = os.path.join(tmpdir, "raw.train.txt")
    raw_val = os.path.join(tmpdir, "raw.val.txt")
    raw_test = os.path.join(tmpdir, "raw.test.txt")
    for path, n, seed in ((raw_train, n_train, 1), (raw_val, n_val, 2),
                          (raw_test, n_test, 3)):
        with open(path, "w") as f:
            f.write("\n".join(make_raw_lines(n, seed=seed)) + "\n")
    prefix = os.path.join(tmpdir, "tiny")
    preprocess_mod.main([
        "--train_data", raw_train, "--val_data", raw_val,
        "--test_data", raw_test, "--max_contexts", str(max_contexts),
        "--word_vocab_size", "1000", "--path_vocab_size", "1000",
        "--target_vocab_size", "1000", "--output_name", prefix])
    if binarize:
        binarize_mod.main(["--data", prefix,
                           "--max_contexts", str(max_contexts),
                           "--word_vocab_size", "1000",
                           "--path_vocab_size", "1000",
                           "--target_vocab_size", "1000"])
    return prefix


def load_tiny_vocabs(prefix: str) -> Code2VecVocabs:
    return Code2VecVocabs.load_from_dict_file(
        prefix + ".dict.c2v", 1000, 1000, 1000)


def sharded_eval_setup(dir_path: str):
    """The (dataset, Config) pair shared by the 2-process sharded-eval
    worker (tests/mp_worker.py) and its single-process oracle
    (tests/test_multihost.py) — one definition, so the comparison can
    never drift via config edits to only one side."""
    from code2vec_tpu.config import Config

    prefix = build_tiny_dataset(dir_path, n_train=48, n_val=8, n_test=8,
                                max_contexts=16)
    cfg = Config(MAX_CONTEXTS=16, MAX_TOKEN_VOCAB_SIZE=1000,
                 MAX_PATH_VOCAB_SIZE=1000, MAX_TARGET_VOCAB_SIZE=1000,
                 DEFAULT_EMBEDDINGS_SIZE=16, TRAIN_BATCH_SIZE=16,
                 TEST_BATCH_SIZE=8, USE_BF16=False,
                 LR_SCHEDULE="constant")
    cfg.train_data_path = prefix
    cfg.test_data_path = prefix + ".train.c2v"
    return cfg
