"""int8 quantized-table tests (ops/quant.py; VERDICT r4 item 3).

Covers: quantize/dequantize error bounds, the straight-through gather's
gradient correctness against a float-table reference, untouched-row
requantize stability, and an end-to-end quantized train step (loss
decreases, structure preserved, optimizer flat-view compatibility).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.models.encoder import ModelDims, init_params, take_rows
from code2vec_tpu.ops.quant import (dequantize_table, is_quantized,
                                    quantize_table, quantized_take,
                                    requantize)
from code2vec_tpu.training.optimizers import make_optimizer
from code2vec_tpu.training.steps import make_train_step

DIMS = ModelDims(token_vocab_size=64, path_vocab_size=32,
                 target_vocab_size=24, embeddings_size=8, max_contexts=6,
                 tables_dtype="int8")


def _batch(rng, b=16):
    r = np.random.default_rng(rng)
    return (jnp.asarray(r.integers(0, 24, b), jnp.int32),
            jnp.asarray(r.integers(0, 64, (b, 6)), jnp.int32),
            jnp.asarray(r.integers(0, 32, (b, 6)), jnp.int32),
            jnp.asarray(r.integers(0, 64, (b, 6)), jnp.int32),
            jnp.ones((b, 6), jnp.float32),
            jnp.ones((b,), jnp.float32))


def test_quantize_roundtrip_bound():
    r = np.random.default_rng(0)
    t = jnp.asarray(r.normal(size=(40, 8)) * 0.3, jnp.float32)
    qt = quantize_table(t)
    assert qt["q"].dtype == jnp.int8 and qt["s"].shape == (40, 1)
    err = np.abs(np.asarray(dequantize_table(qt)) - np.asarray(t))
    # per-row error bound: half a quantum
    assert (err <= np.asarray(qt["s"]) / 2 + 1e-7).all()
    # the absmax element of each row quantizes to exactly +-127
    assert (np.abs(np.asarray(qt["q"])).max(axis=1) == 127).all()


def test_quantized_take_grad_matches_float_reference():
    r = np.random.default_rng(1)
    t = jnp.asarray(r.normal(size=(32, 8)) * 0.2, jnp.float32)
    qt = quantize_table(t)
    deq = dequantize_table(qt)  # the exact values the int8 path sees
    ids = jnp.asarray(r.integers(0, 32, (4, 6)), jnp.int32)
    w = jnp.asarray(r.normal(size=(4, 6, 8)), jnp.float32)

    def loss_q(carrier):
        return jnp.sum(quantized_take(carrier, qt, ids) * w)

    def loss_f(table):
        return jnp.sum(jnp.take(table, ids, axis=0) * w)

    g_carrier = jax.grad(loss_q)(jnp.zeros((32, 8), jnp.float32))
    g_ref = jax.grad(loss_f)(deq)
    # qtake emits bf16 (by design — see ops/quant.py), so the gradient
    # and forward agree with the f32 reference to bf16 precision
    np.testing.assert_allclose(np.asarray(g_carrier), np.asarray(g_ref),
                               rtol=1e-2, atol=1e-3)
    np.testing.assert_allclose(
        np.asarray(quantized_take(jnp.zeros((32, 8)), qt, ids),
                   dtype=np.float32),
        np.asarray(jnp.take(deq.astype(jnp.bfloat16), ids, axis=0),
                   dtype=np.float32), rtol=1e-6)


def test_requantize_untouched_rows_stable():
    r = np.random.default_rng(2)
    t = jnp.asarray(r.normal(size=(64, 8)) * 0.5, jnp.float32)
    qt = quantize_table(t)
    upd = np.zeros((64, 8), np.float32)
    upd[3] = 0.01  # one touched row
    out = requantize(qt, jnp.asarray(upd), jax.random.PRNGKey(0))
    dq = np.asarray(qt["q"])
    dq_new = np.asarray(out["q"])
    untouched = [i for i in range(64) if i != 3]
    # scale roundtrip is exact to 1 ulp -> at most a 1-quantum dither
    # tail with ~1e-5 probability per element; on 63x8 elements expect
    # bit-equality (assert a tiny tolerance, not luck)
    flips = (dq_new[untouched] != dq[untouched]).sum()
    assert flips <= 1
    assert (np.abs(dq_new[untouched].astype(int)
                   - dq[untouched].astype(int)) <= 1).all()
    # the touched row actually moved toward the update
    row_f = np.asarray(dequantize_table(out))[3]
    target = np.asarray(dequantize_table(qt))[3] + upd[3]
    assert np.abs(row_f - target).max() <= np.asarray(out["s"])[3, 0]


def test_requantize_stochastic_rounding_unbiased():
    # an update of 0.3 quanta must survive in expectation (deterministic
    # rounding would drop it entirely)
    V, E = 1, 256
    q = jnp.full((V, E), 10, jnp.int8)
    s = jnp.full((V, 1), 0.01, jnp.float32)
    upd = jnp.full((V, E), 0.003, jnp.float32)  # 0.3 quanta
    outs = [np.asarray(dequantize_table(
        requantize({"q": q, "s": s}, upd, jax.random.PRNGKey(k)))).mean()
            for k in range(8)]
    mean_v = float(np.mean(outs))
    # expected float value 0.1 + 0.003 = 0.103; deterministic rounding
    # of a constant row would also land here via the rescale, so ALSO
    # check per-element variation exists (the dither is real): with a
    # constant row every element maps to q=127, so use the float mean
    # bound plus a non-constant row check below
    assert 0.1015 < mean_v < 0.1045, mean_v
    # non-constant row: a sub-quantum update must survive in
    # expectation where deterministic rounding would drop it
    r = np.random.default_rng(3)
    t = jnp.asarray(np.abs(r.normal(size=(1, 512))) * 0.1 + 0.01,
                    jnp.float32)
    qt = quantize_table(t)
    base = np.asarray(dequantize_table(qt))
    upd2 = jnp.full((1, 512), float(np.asarray(qt["s"])[0, 0]) * 0.3,
                    jnp.float32)  # 0.3 quanta everywhere
    deltas = [np.asarray(dequantize_table(
        requantize(qt, upd2, jax.random.PRNGKey(100 + k)))).mean()
        - base.mean() for k in range(8)]
    mean_delta = float(np.mean(deltas))
    expect = float(np.asarray(upd2).mean())
    assert 0.5 * expect < mean_delta < 1.5 * expect, (mean_delta, expect)


def test_init_params_int8_structure():
    params = init_params(jax.random.PRNGKey(0), DIMS)
    assert is_quantized(params["token_emb"])
    assert is_quantized(params["path_emb"])
    assert params["target_emb"].dtype == jnp.bfloat16
    assert params["transform"].dtype == jnp.float32


def test_take_rows_serving_path():
    params = init_params(jax.random.PRNGKey(0), DIMS)
    ids = jnp.asarray([[0, 1], [2, 3]])
    rows = take_rows(params, "token_emb", ids)
    assert rows.dtype == jnp.bfloat16  # half-width activation contract
    ref = jnp.take(dequantize_table(params["token_emb"]), ids, axis=0)
    np.testing.assert_allclose(np.asarray(rows, dtype=np.float32),
                               np.asarray(ref), rtol=1e-2, atol=1e-3)


@pytest.mark.parametrize("embedding_optimizer", ["adafactor", "adam"])
def test_quantized_train_step_learns(embedding_optimizer):
    params = init_params(jax.random.PRNGKey(3), DIMS)
    opt = make_optimizer(0.05, embedding_optimizer=embedding_optimizer)
    # the flat optimizer view (jax_model._opt_param_view contract)
    view = {k: (jnp.zeros(v["q"].shape, jnp.bfloat16)
                if is_quantized(v) else v) for k, v in params.items()}
    opt_state = opt.init(view)
    step = make_train_step(DIMS, opt, use_sampled_softmax=False)
    batch = _batch(7)
    losses = []
    rng = jax.random.PRNGKey(4)
    for i in range(60):
        rng, k = jax.random.split(rng)
        params, opt_state, loss = step(params, opt_state, batch, k)
        losses.append(float(loss))
    assert is_quantized(params["token_emb"])  # structure preserved
    assert params["token_emb"]["q"].dtype == jnp.int8
    # memorizing one small batch must drive the loss down hard
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_quantized_vs_bf16_step_close_at_start():
    """First-step loss of the int8 model sits near the bf16 model's
    (same seed): quantization noise is a small perturbation, not a
    different model."""
    dims_b = ModelDims(**{**DIMS.__dict__, "tables_dtype": "bfloat16"})
    p_q = init_params(jax.random.PRNGKey(5), DIMS)
    p_b = init_params(jax.random.PRNGKey(5), dims_b)
    opt = make_optimizer(1e-3)
    view = {k: (jnp.zeros(v["q"].shape, jnp.bfloat16)
                if is_quantized(v) else v) for k, v in p_q.items()}
    s_q = make_train_step(DIMS, opt)
    s_b = make_train_step(dims_b, opt)
    batch = _batch(11)
    _, _, l_q = s_q(p_q, opt.init(view), batch, jax.random.PRNGKey(6))
    _, _, l_b = s_b(p_b, opt.init(p_b), batch, jax.random.PRNGKey(6))
    assert abs(float(l_q) - float(l_b)) < 0.15, (float(l_q), float(l_b))


def test_int8_model_trains_and_roundtrips(tmp_path):
    """End-to-end: Code2VecModel with --tables_dtype int8 trains on the
    tiny dataset, quality lands near the bf16 run's, and the checkpoint
    round-trips the quantized structure through the manifest."""
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.helpers import build_tiny_dataset
    from tests.test_model import tiny_config

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    dataset = build_tiny_dataset(str(data_dir), n_train=256,
                                 n_val=32, n_test=64, max_contexts=16)
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = tiny_config(dataset, TABLES_DTYPE="int8",
                      EMBEDDING_OPTIMIZER="adafactor",
                      NUM_TRAIN_EPOCHS=6)
    cfg.verify()
    model = Code2VecModel(cfg)
    assert is_quantized(model.params["token_emb"])
    before = model.evaluate()
    model.train()
    after = model.evaluate()
    assert after.loss < before.loss
    assert after.subtoken_f1 > 0.5
    model.save(ckpt_dir)

    cfg2 = tiny_config(dataset)  # dtype comes from the checkpoint dims
    cfg2.load_path = ckpt_dir
    model2 = Code2VecModel(cfg2)
    assert is_quantized(model2.params["token_emb"])
    assert model2.dims.tables_dtype == "int8"
    loaded = model2.evaluate()
    assert loaded.topk_acc == pytest.approx(after.topk_acc)


def test_int8_mesh_guard_covers_manifest_load(tmp_path):
    """The multi-axis-mesh backstop must fire AFTER the checkpoint
    manifest has set the tables dtype (ADVICE r5 finding 1): a
    programmatic Config that LOADS an int8 checkpoint (so its own
    TABLES_DTYPE default says bfloat16) onto a model-sharded mesh must
    be rejected, not silently row-shard the {q, s} subtrees."""
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.helpers import build_tiny_dataset
    from tests.test_model import tiny_config

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    dataset = build_tiny_dataset(str(data_dir), n_train=64, n_val=16,
                                 n_test=16, max_contexts=16)
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = tiny_config(dataset, TABLES_DTYPE="int8")
    cfg.verify()
    Code2VecModel(cfg).save(ckpt_dir)

    cfg2 = tiny_config(dataset, MESH_MODEL_AXIS=2)
    cfg2.load_path = ckpt_dir
    # deliberately NO verify(): verify() could not catch this anyway
    # (cfg2's TABLES_DTYPE still reads bfloat16 — only the manifest
    # knows the checkpoint is int8)
    with pytest.raises(ValueError, match="data-parallel meshes"):
        Code2VecModel(cfg2)


def test_int8_config_gates():
    """verify() rejects the combinations the int8 path does not cover."""
    from code2vec_tpu.config import Config

    for bad in (dict(ENCODER_TYPE="transformer"),
                dict(HEAD="varmisuse"),
                dict(MESH_MODEL_AXIS=2),
                dict(TRUST_RATIO=True)):
        cfg = Config(TABLES_DTYPE="int8", **bad)
        cfg.train_data_path = "x"
        with pytest.raises(ValueError):
            cfg.verify()


def test_trust_ratio_scope_dense():
    """scope='dense' trust-scales only the non-table branch: table
    updates match plain adafactor exactly, dense updates differ
    (VERDICT r4 item 8 — the sane LAMB form)."""
    dims = ModelDims(token_vocab_size=32, path_vocab_size=16,
                     target_vocab_size=12, embeddings_size=8,
                     max_contexts=4, tables_dtype="float32")
    params = init_params(jax.random.PRNGKey(0), dims)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 0.01, p.dtype), params)
    plain = make_optimizer(1e-3)
    dense = make_optimizer(1e-3, trust_ratio=True,
                           trust_ratio_scope="dense")
    u1, _ = plain.update(grads, plain.init(params), params)
    u2, _ = dense.update(grads, dense.init(params), params)
    np.testing.assert_allclose(np.asarray(u1["token_emb"]),
                               np.asarray(u2["token_emb"]), rtol=1e-6)
    assert not np.allclose(np.asarray(u1["transform"]),
                           np.asarray(u2["transform"]))
    # adam branch has no table/dense split -> clean error
    with pytest.raises(ValueError):
        make_optimizer(1e-3, embedding_optimizer="adam",
                       trust_ratio=True, trust_ratio_scope="dense")


def test_dither_is_uniform_enough():
    """The counter-hash dither must behave like U(-0.5, 0.5): bounded,
    near-zero mean, ~1/12 variance, and decorrelated across salts."""
    from code2vec_tpu.ops.quant import _dither
    d1 = np.asarray(_dither(jax.random.PRNGKey(0), (512, 128)))
    d2 = np.asarray(_dither(jax.random.PRNGKey(1), (512, 128)))
    assert d1.min() >= -0.5 and d1.max() < 0.5
    assert abs(d1.mean()) < 0.005
    assert abs(d1.var() - 1.0 / 12.0) < 0.005
    # different step salts -> different streams
    assert np.abs(np.corrcoef(d1.ravel(), d2.ravel())[0, 1]) < 0.02
    # adjacent elements are not visibly correlated within one stream
    assert np.abs(np.corrcoef(d1.ravel()[:-1], d1.ravel()[1:])[0, 1]) \
        < 0.02
