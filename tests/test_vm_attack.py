"""VarMisuse-head adversarial attack tests (attacks/vm_attack.py): the
paper's second target model — renaming a candidate variable must be
able to move the pointer's localization."""

import os

import numpy as np
import pytest

from code2vec_tpu.attacks.vm_attack import VMGradientRenameAttack
from code2vec_tpu.data.varmisuse_gen import write_vm_dataset
from code2vec_tpu.data.vm_reader import parse_vm_rows
from code2vec_tpu.extractor import native
from tests.test_varmisuse import vm_config


@pytest.fixture(scope="module")
def vm_trained(tmp_path_factory):
    if not native.available():
        pytest.skip("native extractor not built")
    from code2vec_tpu.models.vm_model import VarMisuseModel
    d = tmp_path_factory.mktemp("vm_attack")
    prefix = os.path.join(str(d), "vm")
    write_vm_dataset(prefix, n_train=1200, n_val=150, n_test=100,
                     seed=11)
    cfg = vm_config(prefix)
    cfg.test_data_path = prefix + ".val.vm.c2v"
    model = VarMisuseModel(cfg)
    model.train()
    return cfg, model, prefix


def _rows(cfg, model, prefix, n):
    with open(prefix + ".val.vm.c2v", encoding="utf-8") as f:
        lines = [ln for ln in f if ln.strip()][:n]
    labels, src, pth, dst, mask, cand, cmask, valid, _ = parse_vm_rows(
        lines, model.vocabs, cfg.MAX_CONTEXTS, cfg.MAX_CANDIDATES)
    keep = [i for i in range(len(lines)) if valid[i] > 0]
    return [(src[i], pth[i], dst[i], mask[i], cand[i], cmask[i])
            for i in keep], [int(labels[i]) for i in keep]


def test_vm_untargeted_attack_moves_the_pointer(vm_trained):
    cfg, model, prefix = vm_trained
    attack = VMGradientRenameAttack(
        model.dims, model.vocabs.token_vocab, max_iters=4,
        compute_dtype=model.compute_dtype)
    rows, _ = _rows(cfg, model, prefix, 12)
    results = [attack.attack_method(model.params, r, targeted=False,
                                    max_renames=2) for r in rows]
    flips = sum(r.success for r in results)
    assert flips >= len(results) // 3, \
        f"only {flips}/{len(results)} VM attacks moved the pointer"
    for r in results:
        if r.success:
            assert r.final_slot != r.original_slot
        assert r.iterations >= 1


def test_vm_targeted_attack_points_at_chosen_slot(vm_trained):
    cfg, model, prefix = vm_trained
    attack = VMGradientRenameAttack(
        model.dims, model.vocabs.token_vocab, max_iters=5,
        top_k_candidates=48, compute_dtype=model.compute_dtype)
    rows, _ = _rows(cfg, model, prefix, 12)
    hits = tried = 0
    for r in rows:
        cmask = np.asarray(r[5])
        clean = attack.attack_method(model.params, r, targeted=False,
                                     max_renames=0)
        # aim at a DIFFERENT live slot than the clean prediction
        live = [k for k in range(len(cmask)) if cmask[k] > 0
                and k != clean.original_slot]
        if not live:
            continue
        tried += 1
        res = attack.attack_method(model.params, r, targeted=True,
                                   target_slot=live[0], max_renames=2)
        if res.success:
            hits += 1
            assert res.final_slot == live[0]
    assert tried >= 8
    assert hits >= 1, "targeted VM attack never reached its slot"


def test_vm_robustness_report(vm_trained):
    from code2vec_tpu.attacks.vm_robustness import evaluate_vm_robustness
    _, model, prefix = vm_trained
    report = evaluate_vm_robustness(
        model, prefix + ".val.vm.c2v", n_methods=10, max_renames=1,
        max_iters=3, log=lambda *_: None)
    assert report["n_methods"] > 0
    assert 0.0 <= report["attack_success_rate"] <= 1.0
    assert report["robustness"] == pytest.approx(
        1.0 - report["attack_success_rate"], abs=1e-6)
    assert 0.0 <= report["clean_localization_acc"] <= 1.0


def test_vm_attack_requires_slot_for_targeted(vm_trained):
    _, model, prefix = vm_trained
    attack = VMGradientRenameAttack(model.dims,
                                    model.vocabs.token_vocab)
    rows, _ = _rows(vm_trained[0], model, prefix, 1)
    with pytest.raises(ValueError, match="slot"):
        attack.attack_method(model.params, rows[0], targeted=True)
