package com.example;

import java.util.List;
import java.util.ArrayList;

public class Example {
    private int count;
    private List<String> names = new ArrayList<>();

    public int getCount() {
        return count;
    }

    public void addName(String name) {
        if (name != null && !name.isEmpty()) {
            names.add(name.trim());
            count++;
        }
    }

    public String findLongest(List<String> items) {
        String longest = "";
        for (String item : items) {
            if (item.length() > longest.length()) {
                longest = item;
            }
        }
        return longest;
    }

    public static int max(int a, int b) {
        return a > b ? a : b;
    }
}
