package com.example;
import java.util.*;
import java.util.function.*;

@SuppressWarnings("unchecked")
public class Hard<T extends Comparable<T>> implements Iterable<T> {
    private Map<String, List<Integer>> cache = new HashMap<>();

    public <R> List<R> transform(List<T> input, Function<T, R> fn) {
        List<R> result = new ArrayList<>(input.size());
        for (int i = 0; i < input.size(); i++) {
            result.add(fn.apply(input.get(i)));
        }
        return result;
    }

    public int sumEvens(int[] values) {
        int total = 0;
        for (int v : values) {
            if ((v & 1) == 0) { total += v; }
        }
        return total;
    }

    public Optional<T> firstMatching(Collection<T> items, Predicate<T> p) {
        return items.stream().filter(p).findFirst();
    }

    public void process() {
        Runnable r = () -> System.out.println("hello" + 42);
        Comparator<T> cmp = (a, b) -> a.compareTo(b);
        try (AutoCloseable ac = open()) {
            int x = (int) compute(3.14, 'c');
            switch (x) {
                case 1: doThing(); break;
                case 2: case 3: other(); break;
                default: fallback();
            }
        } catch (RuntimeException | Error e) {
            throw new IllegalStateException("bad", e);
        } finally {
            cleanup();
        }
        new Thread(new Runnable() {
            public void run() { loop(); }
        }).start();
        String s = x > 0 ? "pos" : "neg";
        this.cache.put(s, Arrays.asList(1, 2, 3));
        Supplier<List<T>> sup = ArrayList::new;
        int[][] grid = new int[3][4];
        var inferred = cache.keySet();
    }

    public Iterator<T> iterator() { return null; }
}
