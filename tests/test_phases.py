"""Phase-attribution plane (ISSUE 15): sampled phase-split profiler
(obs/phases.py + training/phase_probes.py), the PhaseRoofline health
monitor, per-phase bench gating, and the tool surface (obs_top phase
columns + counter-reset clamp, telemetry_report phase table,
trace_report --merge). All tier-1, CPU."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from code2vec_tpu.models.encoder import ModelDims, init_params
from code2vec_tpu.obs.phases import (PhaseProfiler, ProbeKit,
                                     derive_chain_phases)
from code2vec_tpu.obs.telemetry import Telemetry
from code2vec_tpu.training.phase_probes import (make_code2vec_probes,
                                                make_vm_probes)
from code2vec_tpu.training.steps import make_train_step
from tests.helpers import example_batch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_dims(**kw):
    base = dict(token_vocab_size=50, path_vocab_size=40,
                target_vocab_size=30, embeddings_size=8,
                max_contexts=6, tables_dtype="float32")
    base.update(kw)
    return ModelDims(**base)


def _copy_tree(tree):
    return jax.tree_util.tree_map(jnp.copy, tree)


def _assert_trees_bit_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            "tree leaves differ bit-for-bit"


def _dense_setup(dims):
    optimizer = optax.adam(1e-2)
    params = init_params(jax.random.PRNGKey(0), dims)
    opt_state = optimizer.init(params)
    step = make_train_step(dims, optimizer)
    batch = example_batch(3, dims, batch=8)
    return optimizer, params, opt_state, step, batch


# ---- tentpole: split-vs-fused parity + derivation ----

def test_dense_split_vs_fused_bit_parity():
    """The sampled step's state update IS the fused dispatch, so
    loss/params after run_split must equal the plain fused step
    bit-for-bit — sampling can never perturb the trajectory."""
    dims = tiny_dims()
    optimizer, params, opt_state, step, batch = _dense_setup(dims)
    rng = jax.random.PRNGKey(7)
    p1, s1, loss1 = step(_copy_tree(params), _copy_tree(opt_state),
                         batch, rng)

    tele = Telemetry.memory("train")
    prof = PhaseProfiler.create(
        tele, fused_step=step,
        probes_factory=lambda: make_code2vec_probes(dims, optimizer),
        enabled=True, sample_every=1)
    p2, s2, loss2 = prof.run_split(_copy_tree(params),
                                   _copy_tree(opt_state), batch, rng,
                                   step=5)
    assert float(loss1) == loss2
    _assert_trees_bit_equal(p1, p2)
    _assert_trees_bit_equal(s1, s2)


def test_sparse_split_vs_fused_bit_parity_and_timers():
    """Same parity bar on the sparse (--sparse_embeddings) path — the
    java-large go-forward config — plus the published surface: every
    chain phase + the table_apply remainder lands a train/phase/*
    timer and one `phase` event whose device phases reconcile with the
    fused dispatch (split_sum + residual == fused)."""
    from code2vec_tpu.training.sparse_steps import init_sparse_opt_state
    dims = tiny_dims()
    dense_opt = optax.adam(1e-2)
    params = init_params(jax.random.PRNGKey(1), dims)
    opt_state = init_sparse_opt_state(params, dense_opt, True)
    step = make_train_step(dims, dense_opt, use_sampled_softmax=True,
                           num_sampled=16, sparse_updates=True,
                           learning_rate=1e-2)
    batch = example_batch(11, dims, batch=8)
    rng = jax.random.PRNGKey(3)
    p1, s1, loss1 = step(_copy_tree(params), _copy_tree(opt_state),
                         batch, rng)

    events = []
    tele = Telemetry.memory("train")
    tele.sinks = [type("S", (), {"write": lambda _s, e: events.append(e),
                                 "close": lambda _s: None})()]
    prof = PhaseProfiler.create(
        tele, fused_step=step,
        probes_factory=lambda: make_code2vec_probes(
            dims, None, use_sampled_softmax=True, num_sampled=16,
            sparse_updates=True),
        enabled=True, sample_every=1)
    p2, s2, loss2 = prof.run_split(_copy_tree(params),
                                   _copy_tree(opt_state), batch, rng,
                                   step=64, infeed_wait_ms=0.5)
    assert float(loss1) == loss2
    _assert_trees_bit_equal(p1, p2)
    _assert_trees_bit_equal(s1, s2)

    for phase in ("embed_gather", "concat_dense", "forward_pool",
                  "backward", "table_apply", "infeed_wait",
                  "fused_step"):
        stat = tele.timers.get(f"train/phase/{phase}_ms")
        assert stat is not None and stat.count == 1, phase
    ev = [e for e in events if e.get("kind") == "phase"]
    assert len(ev) == 1 and ev[0]["step"] == 64
    # the accounting identity: fused == split_sum + residual always;
    # on this remainder-attributed kit table_apply = fused - chain, so
    # the residual is just clamp slack (~0)
    assert ev[0]["fused_ms"] == pytest.approx(
        ev[0]["split_sum_ms"] + ev[0]["residual_ms"], abs=0.01)
    # zero when fused >= chain; negative only by probe jitter slack
    assert ev[0]["residual_ms"] <= 0.02
    assert ev[0]["table_apply_ms"] >= 0.0


def test_run_split_beats_and_rebases_recorder():
    """The sampled step must not leak probe time into the step-time
    plane: run_split beats the recorder after EVERY probe dispatch
    (first-sample compiles can exceed a stall deadline) and rebases
    the step window right before the fused dispatch, so the sampled
    step's train/step_ms records the fused step alone."""
    dims = tiny_dims()
    optimizer, params, opt_state, step, batch = _dense_setup(dims)

    class FakeRecorder:
        ticks = 0
        rebased = 0

        def probe_tick(self):
            FakeRecorder.ticks += 1

        def rebase_step_window(self):
            # the rebase must come AFTER all probe dispatches
            FakeRecorder.rebased += 1
            FakeRecorder.ticks_at_rebase = FakeRecorder.ticks

    tele = Telemetry.memory("train")
    prof = PhaseProfiler.create(
        tele, fused_step=step,
        probes_factory=lambda: make_code2vec_probes(dims, optimizer),
        enabled=True, sample_every=1)
    prof.run_split(_copy_tree(params), _copy_tree(opt_state), batch,
                   jax.random.PRNGKey(7), recorder=FakeRecorder())
    chain_len = len(prof._kit.chain)
    # first sample: warmup pass + measured pass each beat per probe
    assert FakeRecorder.ticks == 2 * chain_len
    assert FakeRecorder.rebased == 1
    assert FakeRecorder.ticks_at_rebase == FakeRecorder.ticks
    prof.run_split(_copy_tree(params), _copy_tree(opt_state), batch,
                   jax.random.PRNGKey(8), recorder=FakeRecorder())
    assert FakeRecorder.ticks == 3 * chain_len  # no warmup this time
    assert FakeRecorder.rebased == 2


def test_derive_chain_phases_clamps_and_diffs():
    assert derive_chain_phases(["a", "b", "c"], [2.0, 5.0, 4.0]) == [
        ("a", 2.0), ("b", 3.0), ("c", 0.0)]


def test_vm_probe_kit_runs():
    """The vm head's kit: gather → forward → backward chain, with
    table_apply riding the fused remainder — all dispatchable on the
    vm batch layout."""
    from code2vec_tpu.models.varmisuse import init_vm_params
    dims = tiny_dims()
    params = init_vm_params(jax.random.PRNGKey(0), dims)
    kit = make_vm_probes(dims)
    r = np.random.default_rng(0)
    B, C, K = 4, dims.max_contexts, 3
    batch = (r.integers(0, K, (B,)).astype(np.int32),
             r.integers(0, dims.token_vocab_size, (B, C)).astype(np.int32),
             r.integers(0, dims.path_vocab_size, (B, C)).astype(np.int32),
             r.integers(0, dims.token_vocab_size, (B, C)).astype(np.int32),
             np.ones((B, C), np.float32),
             r.integers(0, dims.token_vocab_size, (B, K)).astype(np.int32),
             np.ones((B, K), np.float32),
             np.ones((B,), np.float32))
    rng = jax.random.PRNGKey(2)
    assert [n for n, _ in kit.chain] == ["embed_gather",
                                         "forward_pool", "backward"]
    out = None
    for _name, fn in kit.chain:
        out = fn(params, batch, rng)
    loss, grads = out
    assert np.isfinite(float(loss))
    assert set(grads) == set(params)
    # apply rides the fused remainder (sampling-overhead budget)
    assert kit.apply_fn is None
    assert kit.remainder_name == "table_apply"


# ---- disabled path + cadence ----

def test_disabled_profiler_is_shared_noop():
    """PR-2 discipline: off is one boolean check — create() returns
    the shared singleton for every off-shape (flag off, dead registry,
    missing step), should_sample is always False, run_split refuses."""
    dead = Telemetry.disabled()
    live = Telemetry.memory("t")
    off = PhaseProfiler.create(live, fused_step=lambda *a: None,
                               probes_factory=lambda: None,
                               enabled=False)
    assert off is PhaseProfiler.disabled()
    assert PhaseProfiler.create(dead, fused_step=lambda *a: None,
                                probes_factory=lambda: None,
                                enabled=True) is off
    assert PhaseProfiler.create(live, enabled=True) is off
    assert not off.enabled
    assert not off.should_sample(64)
    with pytest.raises(RuntimeError):
        off.run_split(None, None, None, None)
    # and the off registry carries no phase state at all
    assert not [t for t in live.timers if t.startswith("train/phase/")]


def test_sampler_cadence_fake_clock():
    """Step-count cadence with a fake-clock min-interval rate limit:
    step 0 (the compile step) is never sampled; the interval gate
    suppresses a due step until the clock catches up."""
    clock = {"t": 100.0}
    prof = PhaseProfiler(
        Telemetry.memory("t"), fused_step=lambda *a: None,
        probes_factory=lambda: None, sample_every=4,
        min_interval_s=10.0, clock=lambda: clock["t"])
    assert not prof.should_sample(0)   # compile step, never sampled
    assert not prof.should_sample(3)
    assert prof.should_sample(4)
    prof._last_sample_t = clock["t"]   # as run_split would stamp
    clock["t"] = 105.0
    assert not prof.should_sample(8)   # due by count, too soon by clock
    clock["t"] = 111.0
    assert prof.should_sample(8)
    # no min-interval: pure step cadence
    prof2 = PhaseProfiler(Telemetry.memory("t"),
                          fused_step=lambda *a: None,
                          probes_factory=lambda: None, sample_every=2)
    assert [s for s in range(9) if prof2.should_sample(s)] == [2, 4, 6, 8]


# ---- health: PhaseRoofline monitor + /metrics rendering ----

def test_phase_roofline_monitor_and_prometheus_render():
    from code2vec_tpu.obs.exposition import render_prometheus
    from code2vec_tpu.obs.health import PhaseRoofline
    tele = Telemetry.memory("train")
    mon = PhaseRoofline()
    mon.evaluate(tele, 0.0)
    assert mon.status == "unknown"  # no sampled step yet

    # a sampled step's worth of timers + the static analytic gauges
    tele.gauge("train/phase_ceiling_gbps", 100.0, emit=False,
               static=True)
    tele.gauge("train/phase_bytes/embed_gather", 4_000_000, emit=False,
               static=True)
    for name, ms in (("embed_gather", 0.2), ("concat_dense", 0.3),
                     ("forward_pool", 0.5), ("backward", 1.0),
                     ("table_apply", 1.0), ("infeed_wait", 5.0),
                     ("fused_step", 3.0)):
        tele.record_ms(f"train/phase/{name}_ms", ms)
    mon.evaluate(tele, 1.0)
    # coverage = (0.2+0.3+0.5+1.0+1.0)/3.0 — infeed_wait excluded
    assert mon.value == pytest.approx(1.0)
    assert mon.status == "ok"
    # per-phase roofline gauge: 4 MB / 0.2 ms = 20 GB/s over 100 GB/s
    assert tele.gauges["health/phase_embed_gather"] == pytest.approx(
        0.2)
    text = render_prometheus(tele)
    assert "health_phase_embed_gather" in text
    assert "health_phase_coverage" in text
    assert "train_phase_backward_ms" in text

    # a drifting split (uncovered fused time) turns the verdict bad
    for _ in range(9):
        tele.record_ms("train/phase/fused_step_ms", 9.0)
    mon.evaluate(tele, 2.0)
    assert mon.status == "bad"


# ---- acceptance: A/B trajectory parity + mid-train scrape ----

@pytest.fixture(scope="module")
def tiny_prefix(tmp_path_factory):
    from tests.helpers import build_tiny_dataset
    d = tmp_path_factory.mktemp("phase_ds")
    return build_tiny_dataset(str(d), n_train=96, n_val=8, n_test=8,
                              max_contexts=16)


def test_train_ab_trajectory_bit_identical(tiny_prefix, tmp_path):
    """--phase_profile off vs on (sampling every 2 steps): the final
    params are bit-identical — the off hot path is untouched AND the
    sampled steps' state updates are the fused dispatches. The on-run
    additionally persists `phase` events + train/phase timers."""
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.test_model import tiny_config

    cfg_off = tiny_config(tiny_prefix, NUM_TRAIN_EPOCHS=2)
    m_off = Code2VecModel(cfg_off)
    m_off.train()

    tdir = str(tmp_path / "tele")
    cfg_on = tiny_config(tiny_prefix, NUM_TRAIN_EPOCHS=2,
                         TELEMETRY_DIR=tdir, PHASE_PROFILE="on",
                         PHASE_SAMPLE_EVERY=2)
    m_on = Code2VecModel(cfg_on)
    m_on.train()

    _assert_trees_bit_equal(m_off.params, m_on.params)
    run_dir = os.path.join(tdir, os.listdir(tdir)[0])
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    phase_events = [e for e in events if e.get("kind") == "phase"]
    # 6 steps (2 epochs x 3 batches): samples at steps-into-run 2, 4
    assert len(phase_events) == 2
    summary = [e for e in events if e.get("kind") == "summary"][-1]
    assert "train/phase/fused_step_ms" in summary["timers"]
    assert summary["timers"]["train/phase/fused_step_ms"]["count"] == 2
    assert "train/phase_bytes/embed_gather" in summary["gauges"]


def test_metrics_scrape_has_health_phase_mid_train(tiny_prefix,
                                                  tmp_path):
    """Acceptance: a /metrics scrape DURING a --phase_profile run
    carries the health_phase_* roofline gauges and train_phase_*
    summaries. The run is held open by a gate after several sampled
    steps so the scrape provably happens mid-train."""
    import socket

    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.test_model import tiny_config

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cfg = tiny_config(tiny_prefix, NUM_TRAIN_EPOCHS=6,
                      TELEMETRY_DIR=str(tmp_path / "tele"),
                      PHASE_PROFILE="on", PHASE_SAMPLE_EVERY=2,
                      HEALTH_EVERY_S=0.05)
    cfg.METRICS_PORT = port
    model = Code2VecModel(cfg)

    orig_step = model._train_step
    gate = threading.Event()
    calls = []

    def gated_step(params, opt_state, batch, rng):
        calls.append(1)
        if len(calls) == 6:
            gate.wait(timeout=60)
        return orig_step(params, opt_state, batch, rng)

    model._train_step = gated_step
    err = []

    def run():
        try:
            model.train()
        except BaseException as e:
            err.append(e)

    trainer = threading.Thread(target=run, daemon=True)
    trainer.start()
    try:
        deadline = time.time() + 120
        seen = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=1.0) as resp:
                    body = resp.read().decode("utf-8")
            except (urllib.error.URLError, OSError):
                time.sleep(0.05)
                continue
            if "health_phase_embed_gather" in body \
                    and "train_phase_fused_step_ms" in body:
                seen = body
                break
            time.sleep(0.05)
        assert seen is not None, \
            "never scraped health_phase_* mid-train"
        assert "health_phase_coverage" in seen
        assert "train_phase_table_apply_ms" in seen
    finally:
        gate.set()
        trainer.join(timeout=120)
    assert not err, f"train thread failed: {err}"


def test_phase_profile_config_verify():
    from code2vec_tpu.config import Config
    with pytest.raises(ValueError, match="phase_profile"):
        Config(PHASE_PROFILE="sometimes", load_path="x").verify()
    with pytest.raises(ValueError, match="phase_sample_every"):
        Config(PHASE_SAMPLE_EVERY=0, load_path="x").verify()
    with pytest.raises(ValueError, match="live registry"):
        Config(PHASE_PROFILE="on", load_path="x").verify()
    Config(PHASE_PROFILE="on", METRICS_PORT=9100,
           load_path="x").verify()
    Config(PHASE_PROFILE="on", TELEMETRY_DIR="/tmp/t",
           load_path="x").verify()


# ---- bench gate: single-phase regression vs headline-only ----

def test_bench_regression_catches_single_phase_2x():
    """Acceptance: the injected single-phase 2x regression fixture
    exits 1 under the default (phase-gated) metric set while the
    headline-only check would have passed."""
    from tools.bench_regression import DEFAULT_METRICS, run
    fixture = os.path.join(REPO, "tests", "bench_fixtures",
                           "phase_regress")
    rc, rows = run(fixture, list(DEFAULT_METRICS), band=0.05,
                   window=5, min_history=2, strict=False)
    assert rc == 1
    by = {r["metric"]: r for r in rows}
    assert by["phase_backward_ms"]["status"] == "REGRESSION"
    assert by["phase_backward_ms"]["lower_is_better"] is True
    assert by["value"]["status"] == "ok"
    # headline-only: the regression sails through — the reason the
    # per-phase gate exists
    rc2, _ = run(fixture, ["value", "sparse_pc_per_sec"], band=0.05,
                 window=5, min_history=2, strict=False)
    assert rc2 == 0


def test_bench_regression_gates_unlisted_phase_keys(tmp_path):
    """A phase key OUTSIDE the PHASE_MS_METRICS literals (a future
    mesh capture's phase_allreduce_ms, the int8 backward_apply
    remainder) is auto-discovered from the rounds and gated
    lower-is-better — no phase escapes the gate the docs promise."""
    from tools.bench_regression import run
    base = {"metric": "path-contexts/sec/chip", "value": 6.6e6,
            "phase_backward_apply_ms": 8.0}
    for n in (1, 2, 3):
        (tmp_path / f"BENCH_r0{n}.json").write_text(json.dumps(base))
    bad = dict(base)
    bad["phase_backward_apply_ms"] = 16.0  # 2x, headline steady
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(bad))
    rc, rows = run(str(tmp_path), ["value"], band=0.05, window=5,
                   min_history=2, strict=False, auto_phases=True)
    assert rc == 1
    by = {r["metric"]: r for r in rows}
    assert by["phase_backward_apply_ms"]["status"] == "REGRESSION"
    assert by["value"]["status"] == "ok"
    # an explicit metric list is respected as given (the CLI passes
    # auto_phases only for default-set runs)
    rc2, _ = run(str(tmp_path), ["value"], band=0.05, window=5,
                 min_history=2, strict=False)
    assert rc2 == 0


def test_bench_regression_phase_direction_is_lower_better():
    from tools.bench_regression import (PHASE_MS_METRICS,
                                        _lower_is_better)
    for m in PHASE_MS_METRICS:
        assert _lower_is_better(m)
    assert _lower_is_better("recovery_seconds")
    assert not _lower_is_better("value")
    assert not _lower_is_better("phase_sum_bytes")


def test_tool_phase_order_copies_match_canonical():
    """obs_top and telemetry_report carry literal copies of
    PHASE_ORDER (+ the trailing fused_step timer) so they stay
    runnable with nothing installed — this pin is what keeps the
    copies from drifting when a phase is added."""
    from code2vec_tpu.obs.phases import DEVICE_PHASES, PHASE_ORDER
    from tools.obs_top import _PHASE_ORDER as top_order
    from tools.telemetry_report import _PHASE_ORDER as report_order
    canonical = PHASE_ORDER + ("fused_step",)
    assert tuple(top_order) == canonical
    assert tuple(report_order) == canonical
    assert set(DEVICE_PHASES) <= set(PHASE_ORDER)


# ---- obs_top: counter-reset clamp + phase columns ----

def _fake_metrics(steps, examples, phases=None):
    text = [f"train_steps {steps}", f"train_examples {examples}",
            "train_max_contexts 16"]
    for name, p50 in (phases or {}).items():
        text.append(f'train_phase_{name}_ms{{quantile="0.5"}} {p50}')
    return "\n".join(text) + "\n"


def test_obs_top_counter_reset_clamps_and_annotates(monkeypatch):
    import tools.obs_top as obs_top
    feed = [_fake_metrics(1000, 32000), _fake_metrics(5, 160)]

    def fake_scrape(endpoint, timeout_s=3.0):
        return obs_top.parse_prometheus(feed.pop(0))

    monkeypatch.setattr(obs_top, "scrape", fake_scrape)
    st = obs_top.EndpointState("h:1")
    st.poll(60.0)
    row = st.poll(60.0)
    # supervisor restart zeroed the counters: no negative rates, the
    # row says why
    assert row["steps_s"] is not None and row["steps_s"] >= 0
    assert row["ex_s"] is not None and row["ex_s"] >= 0
    assert "train_steps" in row["restarted"]
    out = obs_top.render([row])
    assert "RESTARTED" in out
    assert "-" + "1" not in out.replace("|---", "")  # no negative cell


def test_obs_top_phase_columns(monkeypatch):
    import tools.obs_top as obs_top
    phases = {"embed_gather": 4.1, "backward": 9.3, "fused_step": 30.2}
    feed = [_fake_metrics(10, 320, phases),
            _fake_metrics(20, 640, phases)]

    def fake_scrape(endpoint, timeout_s=3.0):
        return obs_top.parse_prometheus(feed.pop(0))

    monkeypatch.setattr(obs_top, "scrape", fake_scrape)
    st = obs_top.EndpointState("h:1")
    st.poll(60.0)
    row = st.poll(60.0)
    assert row["phases"] == phases
    out = obs_top.render([row])
    assert "embed_gather" in out and "backward" in out
    assert "9.300" in out
    # a host without phase summaries renders no phase table
    assert obs_top.render_phases([{"endpoint": "x", "phases": {}}]) == []


# ---- telemetry_report phase table + trace_report --merge ----

def _write_run(d, manifest, events):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    with open(os.path.join(d, "events.jsonl"), "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def test_telemetry_report_phase_table(tmp_path):
    from tools.telemetry_report import phase_rows, render
    run_dir = str(tmp_path / "run-1")
    events = [
        {"kind": "phase", "ts": 1.0, "step": 64, "fused_ms": 30.0,
         "split_sum_ms": 29.0, "residual_ms": 1.0,
         "embed_gather_ms": 4.0, "backward_ms": 9.0,
         "table_apply_ms": 7.0},
        {"kind": "phase", "ts": 2.0, "step": 128, "fused_ms": 31.0,
         "split_sum_ms": 30.0, "residual_ms": 1.0,
         "embed_gather_ms": 4.2, "backward_ms": 9.4,
         "table_apply_ms": 7.1},
        {"kind": "summary",
         "gauges": {"train/phase_bytes/embed_gather": 1_000_000_000,
                    "train/phase_ceiling_gbps": 500.0}},
    ]
    _write_run(run_dir, {"run_id": "run-1", "component": "train",
                         "process_index": 0, "process_count": 1},
               events)
    gauges = events[-1]["gauges"]
    rows = phase_rows(events, gauges)
    by = {r["phase"]: r for r in rows}
    assert by["embed_gather"]["n"] == 2
    # 1 GB / 4.0 ms (nearest-rank p50 of [4.0, 4.2]) = 250 GB/s
    assert by["embed_gather"]["gbps"] == pytest.approx(250.0, abs=1.0)
    assert by["embed_gather"]["vs_ceiling"] == pytest.approx(
        0.5, abs=0.01)
    assert "fused_step" in by and by["fused_step"]["n"] == 2
    # derived-only keys never masquerade as phases
    assert "split_sum" not in by and "residual" not in by
    out = render([run_dir])
    assert "| Phase | samples |" in out
    assert "embed_gather" in out


def test_trace_report_merge_cohort(tmp_path, capsys):
    from tools.trace_report import main, write_chrome_trace
    spans0 = [{"kind": "span", "trace": "t0", "span": "s0",
               "name": "train/step_cycle", "t0": 100.0, "dur_ms": 5.0,
               "tid": 1, "tname": "main", "attrs": {"step": 1}}]
    spans1 = [{"kind": "span", "trace": "t1", "span": "s1",
               "name": "train/step_cycle", "t0": 900.0, "dur_ms": 5.0,
               "tid": 1, "tname": "main", "attrs": {"step": 1}}]
    d0 = str(tmp_path / "r0")
    d1 = str(tmp_path / "r1")
    _write_run(d0, {"run_id": "run-p0", "component": "train",
                    "process_index": 0, "process_count": 2,
                    "created_unix": 1000.0}, spans0)
    _write_run(d1, {"run_id": "run-p1", "component": "train",
                    "process_index": 1, "process_count": 2,
                    "created_unix": 1002.5}, spans1)
    out = str(tmp_path / "merged.json")
    write_chrome_trace([d0, d1], out, merge=True)
    with open(out) as f:
        trace = json.load(f)["traceEvents"]
    names = [(e["name"], e.get("pid")) for e in trace]
    assert ("process_name", 0) in names and ("process_name", 1) in names
    # wall-clock alignment: p1's span starts ~2.5 s after p0's (each
    # run's own monotonic base is meaningless across processes)
    e0 = next(e for e in trace
              if e["name"] == "train/step_cycle" and e["pid"] == 0)
    e1 = next(e for e in trace
              if e["name"] == "train/step_cycle" and e["pid"] == 1)
    assert e1["ts"] - e0["ts"] == pytest.approx(2.5e6, abs=1.0)
    notes = [e for e in trace if e["name"] == "clock_note"]
    assert len(notes) == 2
    assert "monotonic" in notes[0]["args"]["note"]
    # unmerged export stays byte-compatible: no metadata injected
    out2 = str(tmp_path / "flat.json")
    write_chrome_trace([d0, d1], out2)
    with open(out2) as f:
        flat = json.load(f)["traceEvents"]
    assert not [e for e in flat if e["name"] in ("process_name",
                                                 "clock_note")]
    # --merge without --chrome: usage error, not a silent non-merge
    assert main(["--merge", d0, d1]) == 2
    capsys.readouterr()
