"""Model-level test of the sparse-embedding-updates config: training
converges and checkpoint resume round-trips the sparse opt state."""

import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.models.jax_model import Code2VecModel
from tests.helpers import build_tiny_dataset


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("data_sparse")
    return build_tiny_dataset(str(d), n_train=256, n_val=32, n_test=64,
                              max_contexts=16)


def _cfg(prefix, **kw):
    cfg = Config(MAX_CONTEXTS=16, MAX_TOKEN_VOCAB_SIZE=1000,
                 MAX_PATH_VOCAB_SIZE=1000, MAX_TARGET_VOCAB_SIZE=1000,
                 DEFAULT_EMBEDDINGS_SIZE=16, TRAIN_BATCH_SIZE=32,
                 TEST_BATCH_SIZE=32, NUM_TRAIN_EPOCHS=6,
                 SAVE_EVERY_EPOCHS=100, NUM_BATCHES_TO_LOG_PROGRESS=1000,
                 LEARNING_RATE=0.05, USE_BF16=False,
                 SPARSE_EMBEDDING_UPDATES=True,
                 TABLES_DTYPE="float32",  # sparse path is f32-only
                 EMBEDDING_OPTIMIZER="adam",  # ... and adam-only
                 LR_SCHEDULE="constant")  # ... at constant LR
    cfg.train_data_path = prefix
    cfg.test_data_path = prefix + ".test.c2v"
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def test_sparse_training_converges_and_resumes(dataset, tmp_path):
    ckpt = str(tmp_path / "ckpt")
    cfg = _cfg(dataset, save_path=ckpt)
    model = Code2VecModel(cfg)
    before = model.evaluate()
    model.train()
    after = model.evaluate()
    assert after.loss < before.loss
    assert after.subtoken_f1 > 0.5
    model.save(ckpt)

    cfg2 = _cfg(dataset)
    cfg2.load_path = ckpt
    model2 = Code2VecModel(cfg2)
    assert cfg2.SPARSE_EMBEDDING_UPDATES  # restored from manifest
    loaded = model2.evaluate()
    assert loaded.topk_acc == pytest.approx(after.topk_acc)


def test_sparse_with_sampled_softmax(dataset):
    cfg = _cfg(dataset, USE_SAMPLED_SOFTMAX=True, NUM_SAMPLED_CLASSES=6)
    model = Code2VecModel(cfg)
    before = model.evaluate()
    model.train()
    after = model.evaluate()
    assert after.loss < before.loss
