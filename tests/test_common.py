"""Unit tests for subtokenization / normalization / metric primitives
(SURVEY.md §5: "subtokenization/normalization parity ... F1 computation
against hand-computed cases")."""

from code2vec_tpu.common import (SpecialVocabWords, SubtokenStatistics,
                                 calculate_subtoken_tp_fp_fn,
                                 filter_impossible_names, get_subtokens,
                                 legal_method_names_checker, normalize_word,
                                 split_to_subtokens)


def test_normalize_word():
    assert normalize_word("Foo") == "foo"
    assert normalize_word("foo123") == "foo"
    assert normalize_word("123") == "123"  # all-stripped falls back to lower
    assert normalize_word("FOO_BAR") == "foobar"
    assert normalize_word("") == ""


def test_split_to_subtokens():
    assert split_to_subtokens("setFooBar") == ["set", "foo", "bar"]
    assert split_to_subtokens("set_foo_bar") == ["set", "foo", "bar"]
    assert split_to_subtokens("HTMLParser") == ["html", "parser"]
    assert split_to_subtokens("value2x") == ["value", "x"]
    assert split_to_subtokens("  trim  ") == ["trim"]


def test_get_subtokens():
    assert get_subtokens("set|name") == ["set", "name"]
    assert get_subtokens("toString") == ["toString"]
    assert get_subtokens("") == []


def test_legal_method_names():
    assert legal_method_names_checker("get|value")
    assert not legal_method_names_checker(SpecialVocabWords.OOV)
    assert not legal_method_names_checker(SpecialVocabWords.PAD)
    assert not legal_method_names_checker("")
    assert not legal_method_names_checker("|||")
    assert filter_impossible_names(
        ["<OOV>", "get|x", "<PAD>"]) == ["get|x"]


def test_subtoken_tp_fp_fn_hand_cases():
    # exact match
    assert calculate_subtoken_tp_fp_fn("get|name", "get|name") == (2, 0, 0)
    # partial: predicted {get,value}, true {get,name}
    assert calculate_subtoken_tp_fp_fn("get|name", "get|value") == (1, 1, 1)
    # empty prediction
    assert calculate_subtoken_tp_fp_fn("get|name", "") == (0, 0, 2)
    # extra subtokens
    assert calculate_subtoken_tp_fp_fn("run", "run|fast|now") == (1, 2, 0)


def test_subtoken_statistics_f1():
    st = SubtokenStatistics()
    st.update("get|name", "get|value")  # tp1 fp1 fn1
    st.update("set|x", "set|x")         # +tp2
    assert st.true_positive == 3
    assert st.false_positive == 1
    assert st.false_negative == 1
    assert abs(st.precision - 3 / 4) < 1e-9
    assert abs(st.recall - 3 / 4) < 1e-9
    assert abs(st.f1 - 0.75) < 1e-9


def test_framework_flag_is_an_alias_with_notice():
    """--framework tensorflow|keras (the reference's implementation
    selector) is accepted as an alias of the one JAX implementation,
    with a logged notice; unknown values are rejected (VERDICT r3
    item 8)."""
    import pytest

    from code2vec_tpu.config import Config

    for alias in ("tensorflow", "keras"):
        cfg = Config.load_from_args(
            ["--data", "/tmp/x", "--framework", alias])
        assert cfg.DL_FRAMEWORK == alias  # recorded, not rewritten

    cfg = Config(DL_FRAMEWORK="jax")
    cfg.train_data_path = "/tmp/x"
    cfg.verify()  # no notice needed for the native value

    cfg_bad = Config(DL_FRAMEWORK="torch")
    cfg_bad.train_data_path = "/tmp/x"
    with pytest.raises(ValueError):
        cfg_bad.verify()


def test_new_lr_flags_verified():
    import pytest

    from code2vec_tpu.config import Config

    # warmup steps demand the warmup schedule
    cfg = Config(LR_SCHEDULE="cosine", LR_WARMUP_STEPS=10)
    cfg.train_data_path = "/tmp/x"
    with pytest.raises(ValueError):
        cfg.verify()
    # trust ratio is incompatible with the sparse row-update kernel
    cfg2 = Config(SPARSE_EMBEDDING_UPDATES=True, TRUST_RATIO=True,
                  TABLES_DTYPE="float32", EMBEDDING_OPTIMIZER="adam",
                  LR_SCHEDULE="constant")
    cfg2.train_data_path = "/tmp/x"
    with pytest.raises(ValueError):
        cfg2.verify()


def test_infeed_chunk_requires_thread():
    import pytest

    from code2vec_tpu.config import Config

    cfg = Config(INFEED_CHUNK=4, INFEED_PREFETCH=0)
    cfg.train_data_path = "/tmp/x"
    with pytest.raises(ValueError, match="producer thread"):
        cfg.verify()


def test_round4_flags_plumb_through_cli():
    from code2vec_tpu.config import Config

    cfg = Config.load_from_args(
        ["--data", "/tmp/x", "--lr_schedule", "warmup_cosine",
         "--warmup_steps", "7", "--trust_ratio", "--infeed_prefetch",
         "3", "--infeed_chunk", "4", "--adv_rename_prob", "0.2",
         "--adv_rename_mode", "batch"])
    assert cfg.LR_SCHEDULE == "warmup_cosine"
    assert cfg.LR_WARMUP_STEPS == 7
    assert cfg.TRUST_RATIO is True
    assert cfg.INFEED_PREFETCH == 3
    assert cfg.INFEED_CHUNK == 4
    assert cfg.ADV_RENAME_PROB == 0.2
    assert cfg.ADV_RENAME_MODE == "batch"
