"""obs/health.py + obs/alerts.py (ISSUE 7 tentpole): monitor units
under explicit timestamps, the alert rule state machine (edge
trigger, for_s hold, multi-window burn rate), warn|raise sticky
discipline, rule-file parsing, and the end-to-end acceptance: an
injected non-finite loss during a real CPU train run fires an
edge-triggered alert event (warn) and a sticky AlertError at the
loop's next beat (raise)."""

import json
import os
import threading
import time

import pytest

from code2vec_tpu.obs import (AlertEngine, AlertError, AlertRule,
                              Telemetry, load_rules)
from code2vec_tpu.obs.alerts import (default_serving_rules,
                                     default_train_rules)
from code2vec_tpu.obs.health import (CounterRate, CounterRatio,
                                     EwmaZScore, HealthEngine,
                                     NonFiniteGauges, OptEfficiency,
                                     TimerShare, default_train_monitors)


# ---- monitors ----

def test_nonfinite_monitor_flags_nan_and_inf():
    t = Telemetry.memory("m")
    mon = NonFiniteGauges(("train/loss",), name="loss_nonfinite")
    mon.evaluate(t, 0.0)
    assert mon.status == "unknown"  # nothing published yet
    t.gauge("train/loss", 2.5, emit=False)
    mon.evaluate(t, 1.0)
    assert mon.status == "ok"
    assert t.gauges["health/loss_nonfinite"] == 0.0
    for bad in (float("nan"), float("inf"), float("-inf")):
        t.gauge("train/loss", bad, emit=False)
        mon.evaluate(t, 2.0)
        assert mon.status == "bad"
        assert t.gauges["health/loss_nonfinite"] == 1.0


def test_ewma_zscore_spike_detection():
    t = Telemetry.memory("m")
    mon = EwmaZScore("train/loss", name="loss_spike_z", warmup=5)
    # steady-ish loss: z stays small
    for i, v in enumerate([2.0, 1.9, 1.95, 1.85, 1.9, 1.88, 1.92,
                           1.86, 1.9]):
        t.gauge("train/loss", v, emit=False)
        mon.evaluate(t, float(i))
    assert t.gauges["health/loss_spike_z"] < 6.0
    # a 10x spike screams
    t.gauge("train/loss", 19.0, emit=False)
    mon.evaluate(t, 99.0)
    assert t.gauges["health/loss_spike_z"] > 6.0
    assert mon.status == "bad"
    # NaN is NOT this monitor's department (no crash, no verdict flip)
    t.gauge("train/loss", float("nan"), emit=False)
    mon.evaluate(t, 100.0)
    assert mon.status == "unknown"


def test_counter_rate_and_regression_ratio():
    t = Telemetry.memory("m")
    mon = CounterRate("train/examples", name="throughput",
                      min_history=3)
    count = 0.0
    now = 0.0
    # steady 100 ex/s for 10 ticks
    for _ in range(10):
        now += 1.0
        count += 100.0
        t.counters["train/examples"] = count
        mon.evaluate(t, now)
    assert t.gauges["health/throughput"] == pytest.approx(100.0)
    assert t.gauges["health/throughput_ratio"] == pytest.approx(
        1.0, rel=0.05)
    assert mon.status == "ok"
    # throughput collapses to 20 ex/s -> ratio vs rolling median < 0.5
    now += 1.0
    count += 20.0
    t.counters["train/examples"] = count
    mon.evaluate(t, now)
    assert t.gauges["health/throughput_ratio"] == pytest.approx(
        0.2, rel=0.05)
    assert mon.status == "bad"


def test_counter_rate_pause_is_not_a_regression():
    """A zero-progress window (epoch eval, checkpoint tail) must keep
    the last verdict — liveness is the watchdog's domain, and a
    healthy run must not fire throughput_regression at every epoch
    boundary."""
    t = Telemetry.memory("m")
    mon = CounterRate("train/examples", name="throughput",
                      min_history=3)
    count, now = 0.0, 0.0
    for _ in range(8):
        now += 1.0
        count += 100.0
        t.counters["train/examples"] = count
        mon.evaluate(t, now)
    assert mon.status == "ok"
    # 20 seconds of eval: counter flat across many sweeps
    for _ in range(20):
        now += 1.0
        mon.evaluate(t, now)
        assert mon.status == "ok", "pause misread as regression"
    # training resumes at full rate: baseline was not poisoned by 0s
    now += 1.0
    count += 100.0
    t.counters["train/examples"] = count
    mon.evaluate(t, now)
    assert mon.status == "ok"
    assert t.gauges["health/throughput_ratio"] == pytest.approx(
        1.0, rel=0.1)


def test_timer_share_infeed_starvation():
    t = Telemetry.memory("m")
    mon = TimerShare(name="infeed_starvation")
    t.record_ms("train/step_ms", 90.0)
    t.record_ms("train/infeed_wait_ms", 10.0)
    mon.evaluate(t, 0.0)  # baseline
    t.record_ms("train/step_ms", 90.0)
    t.record_ms("train/infeed_wait_ms", 10.0)
    mon.evaluate(t, 1.0)
    assert t.gauges["health/infeed_starvation"] == pytest.approx(0.1)
    assert mon.status == "ok"
    # the producer wedges: waits dominate the delta
    t.record_ms("train/step_ms", 10.0)
    t.record_ms("train/infeed_wait_ms", 400.0)
    mon.evaluate(t, 2.0)
    assert t.gauges["health/infeed_starvation"] > 0.9
    assert mon.status == "bad"
    # an idle tick keeps the last share instead of fabricating 0/0
    mon.evaluate(t, 3.0)
    assert mon.status == "bad"


def test_counter_ratio_cache_hit_and_shed():
    t = Telemetry.memory("m")
    hit = CounterRatio("serve/cache_hit",
                       ("serve/cache_hit", "serve/cache_miss"),
                       name="cache_hit_rate", min_events=4)
    shed = CounterRatio("serve/shed", ("serve/requests", "serve/shed"),
                        name="shed_rate", bad_above=0.05, min_events=4)
    t.counters.update({"serve/cache_hit": 0, "serve/cache_miss": 0,
                       "serve/requests": 0, "serve/shed": 0})
    hit.evaluate(t, 0.0)
    shed.evaluate(t, 0.0)
    t.counters.update({"serve/cache_hit": 80, "serve/cache_miss": 20,
                       "serve/requests": 95, "serve/shed": 5})
    hit.evaluate(t, 1.0)
    shed.evaluate(t, 1.0)
    assert t.gauges["health/cache_hit_rate"] == pytest.approx(0.8)
    assert t.gauges["health/shed_rate"] == pytest.approx(0.05)
    assert shed.status == "ok"
    # shed climbs past the bad_above threshold
    t.counters.update({"serve/requests": 145, "serve/shed": 55})
    shed.evaluate(t, 2.0)
    assert t.gauges["health/shed_rate"] == pytest.approx(0.5)
    assert shed.status == "bad"
    # a quiet window (below min_events) keeps the last verdict
    shed.evaluate(t, 3.0)
    assert shed.status == "bad"


def test_opt_efficiency_floor_over_observed_p50():
    """ISSUE 8 satellite: the live optimizer-efficiency gauge = the
    sparse path's static [U, E]-aware floor gauge over observed p50
    step time — unknown until BOTH exist, capped at 1, bad below the
    threshold when the step slows down mid-run."""
    t = Telemetry.memory("m")
    mon = OptEfficiency(name="opt_efficiency")
    mon.evaluate(t, 0.0)  # neither floor nor samples yet
    assert mon.status == "unknown"
    t.gauge("train/step_floor_ms", 8.0, emit=False, static=True)
    mon.evaluate(t, 1.0)  # floor but no step samples
    assert mon.status == "unknown"
    for _ in range(5):
        t.record_ms("train/step_ms", 10.0)
    mon.evaluate(t, 2.0)
    assert t.gauges["health/opt_efficiency"] == pytest.approx(0.8)
    assert mon.status == "ok"
    # step regresses 10 ms -> 40 ms: efficiency collapses below bad
    for _ in range(20):
        t.record_ms("train/step_ms", 40.0)
    mon.evaluate(t, 3.0)
    assert t.gauges["health/opt_efficiency"] < 0.25
    assert mon.status == "bad"
    # a step FASTER than the analytic floor caps at 1, never > 1
    t2 = Telemetry.memory("m2")
    t2.gauge("train/step_floor_ms", 8.0, emit=False, static=True)
    t2.record_ms("train/step_ms", 2.0)
    mon2 = OptEfficiency(name="opt_efficiency")
    mon2.evaluate(t2, 0.0)
    assert t2.gauges["health/opt_efficiency"] == 1.0
    # the default train set carries it
    assert any(m.name == "opt_efficiency"
               for m in default_train_monitors())


def test_broken_monitor_does_not_kill_sweep():
    t = Telemetry.memory("m")

    class Boom(NonFiniteGauges):
        def evaluate(self, telemetry, now):
            raise RuntimeError("boom")

    eng = HealthEngine.create(t).add(
        Boom(name="boom"),
        NonFiniteGauges(("g",), name="fine"))
    t.gauge("g", 1.0, emit=False)
    rows = eng.check_now()
    by = {r["monitor"]: r for r in rows}
    assert by["boom"]["status"] == "error"
    assert by["fine"]["status"] == "ok"


def test_health_engine_thread_and_listener():
    t = Telemetry.memory("m")
    t.gauge("g", 1.0, emit=False)
    sweeps = []
    eng = HealthEngine.create(t, interval_s=0.02)
    eng.add(NonFiniteGauges(("g",), name="g_finite"))
    eng.add_listener(sweeps.append)
    eng.start()
    deadline = time.time() + 5
    while not sweeps and time.time() < deadline:
        time.sleep(0.01)
    eng.stop()
    assert sweeps, "monitor thread never swept"
    assert t.gauges["health/g_finite"] == 0.0
    n = len(sweeps)
    time.sleep(0.1)
    assert len(sweeps) == n  # stopped means stopped


def test_disabled_engine_is_shared_noop():
    assert HealthEngine.create(None) is HealthEngine.disabled()
    assert HealthEngine.create(Telemetry.disabled()) \
        is HealthEngine.disabled()
    off = HealthEngine.disabled()
    assert off.add().start().check_now() == []
    off.stop()
    assert AlertEngine.create(Telemetry.memory("x"), mode="off") \
        is AlertEngine.disabled()
    assert AlertEngine.create(None, mode="warn") \
        is AlertEngine.disabled()


# ---- alert rules ----

def test_threshold_rule_edge_trigger_and_resolve():
    t = Telemetry.memory("m")
    eng = AlertEngine.create(
        t, mode="warn",
        rules=[AlertRule("hot", metric="g", op=">", value=10.0)])
    t.gauge("g", 5.0, emit=False)
    assert eng.evaluate(now=0.0) == []
    t.gauge("g", 11.0, emit=False)
    trans = eng.evaluate(now=1.0)
    assert [x["transition"] for x in trans] == ["firing"]
    # still bad: edge-triggered, no repeat event
    assert eng.evaluate(now=2.0) == []
    assert t.gauges["alerts/firing"] == 1
    t.gauge("g", 3.0, emit=False)
    trans = eng.evaluate(now=3.0)
    assert [x["transition"] for x in trans] == ["resolved"]
    assert t.gauges["alerts/firing"] == 0
    # a NEW episode fires again
    t.gauge("g", 12.0, emit=False)
    assert [x["transition"] for x in eng.evaluate(now=4.0)] \
        == ["firing"]
    assert t.counters["alerts/fired"] == 2


def test_threshold_rule_for_s_hold():
    t = Telemetry.memory("m")
    eng = AlertEngine.create(
        t, mode="warn",
        rules=[AlertRule("slowburn", metric="g", op=">", value=1.0,
                         for_s=10.0)])
    t.gauge("g", 2.0, emit=False)
    assert eng.evaluate(now=0.0) == []     # pending, not firing
    assert eng.evaluate(now=5.0) == []     # still inside the hold
    t.gauge("g", 0.0, emit=False)
    assert eng.evaluate(now=7.0) == []     # recovered while pending:
    assert eng.rules[0].state == "ok"      # no event at all
    t.gauge("g", 2.0, emit=False)
    assert eng.evaluate(now=8.0) == []     # hold restarts
    assert [x["transition"] for x in eng.evaluate(now=19.0)] \
        == ["firing"]


def test_timer_percentile_metric_resolution():
    t = Telemetry.memory("m")
    for ms in (10.0, 12.0, 300.0):
        t.record_ms("serve/request_ms", ms)
    eng = AlertEngine.create(
        t, mode="warn",
        rules=[AlertRule("slo", metric="serve/request_ms:p99",
                         op=">", value=250.0)])
    assert [x["transition"] for x in eng.evaluate(now=0.0)] \
        == ["firing"]
    assert eng.rules[0].last_value == 300.0


def test_burn_rate_needs_both_windows():
    t = Telemetry.memory("m")
    rule = AlertRule("burn", metric="serve/shed",
                     kind="burn_rate", denominator="serve/requests",
                     op=">", value=0.1, windows=(10.0, 50.0))
    eng = AlertEngine.create(t, mode="warn", rules=[rule])
    req = shed = 0.0
    now = 0.0
    fired_at = None
    # healthy for 60s, then a sustained 50% shed ratio
    for _ in range(12):
        now += 5.0
        req += 50.0
        t.counters.update({"serve/requests": req, "serve/shed": shed})
        assert eng.evaluate(now=now) == []
    for _ in range(20):
        now += 5.0
        req += 50.0
        shed += 25.0
        t.counters.update({"serve/requests": req, "serve/shed": shed})
        trans = eng.evaluate(now=now)
        if trans:
            fired_at = now
            break
    assert fired_at is not None, "sustained burn never fired"
    # the long (50s) window had to fill with bad minutes first: a
    # single bad short-window sample must NOT have fired it
    assert fired_at >= 60.0 + 10.0


def test_burn_rate_summed_denominator_total_outage():
    """serve/requests counts only COMPLETED requests, so the default
    shed rule divides by serve/requests+serve/shed — a 100%-shed
    outage (denominator otherwise flat) must still fire."""
    t = Telemetry.memory("m")
    eng = AlertEngine.create(t, mode="warn",
                             rules=[default_serving_rules()[1]])
    rule = eng.rules[0]
    assert rule.name == "shed_burn_rate"
    req = shed = 0.0
    now = 0.0
    for _ in range(70):  # 350s of healthy traffic fills both windows
        now += 5.0
        req += 50.0
        t.counters.update({"serve/requests": req, "serve/shed": shed})
        assert eng.evaluate(now=now) == []
    fired = False
    for _ in range(80):  # total outage: ONLY sheds move
        now += 5.0
        shed += 50.0
        t.counters.update({"serve/requests": req, "serve/shed": shed})
        if eng.evaluate(now=now):
            fired = True
            break
    assert fired, "100%-shed outage never fired the burn-rate alert"


def test_burn_rate_blip_does_not_fire():
    t = Telemetry.memory("m")
    rule = AlertRule("burn", metric="serve/shed",
                     kind="burn_rate", denominator="serve/requests",
                     op=">", value=0.1, windows=(10.0, 50.0))
    eng = AlertEngine.create(t, mode="warn", rules=[rule])
    req = shed = 0.0
    now = 0.0
    for i in range(40):
        now += 5.0
        req += 50.0
        if i == 15:  # one bad 5s sample in an otherwise clean run
            shed += 25.0
        t.counters.update({"serve/requests": req, "serve/shed": shed})
        assert eng.evaluate(now=now) == [], \
            f"blip fired the burn-rate alert at t={now}"


def test_raise_mode_sticky_polls_not_monitor_thread():
    t = Telemetry.memory("m")
    eng = AlertEngine.create(
        t, mode="raise",
        rules=[AlertRule("hot", metric="g", op=">", value=0.0)])
    t.gauge("g", 1.0, emit=False)
    # evaluate (the monitor-thread call site) must NOT raise
    trans = eng.evaluate(now=0.0)
    assert [x["transition"] for x in trans] == ["firing"]
    with pytest.raises(AlertError, match="hot"):
        eng.poll()
    eng.poll()  # sticky consumed: the next poll is clean


def test_recorder_surfaces_sticky_alert_at_next_beat():
    from code2vec_tpu.obs import TrainStepRecorder
    t = Telemetry.memory("m")
    eng = AlertEngine.create(
        t, mode="raise",
        rules=[AlertRule("hot", metric="g", op=">", value=0.0)])
    rec = TrainStepRecorder(t, alerts=eng)
    rec._t_yield = time.perf_counter()
    rec.end_step(1, 0.5, 4)  # no sticky: records normally
    t.gauge("g", 1.0, emit=False)
    eng.evaluate(now=0.0)
    rec._t_yield = time.perf_counter()
    with pytest.raises(AlertError):
        rec.end_step(2, 0.5, 4)


def test_rule_validation_and_load_rules(tmp_path):
    with pytest.raises(ValueError, match="kind"):
        AlertRule("x", metric="g", kind="nope")
    with pytest.raises(ValueError, match="op"):
        AlertRule("x", metric="g", op="!=")
    with pytest.raises(ValueError, match="denominator"):
        AlertRule("x", metric="g", kind="burn_rate")
    with pytest.raises(ValueError, match="windows"):
        AlertRule("x", metric="g", kind="burn_rate",
                  denominator="d", windows=(60.0, 60.0))
    assert load_rules(None) is None
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([
        {"name": "nan", "metric": "health/loss_nonfinite",
         "op": ">=", "value": 1},
        {"name": "burn", "metric": "serve/shed",
         "kind": "burn_rate", "denominator": "serve/requests",
         "op": ">", "value": 0.05, "windows": [30, 120],
         "severity": "page"},
    ]))
    rules = load_rules(str(p))
    assert [r.name for r in rules] == ["nan", "burn"]
    assert rules[1].windows == (30.0, 120.0)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([{"metric": "g"}]))
    with pytest.raises(ValueError, match="name and metric"):
        load_rules(str(bad))
    notalist = tmp_path / "obj.json"
    notalist.write_text(json.dumps({"name": "x"}))
    with pytest.raises(ValueError, match="JSON list"):
        load_rules(str(notalist))


def test_empty_rule_file_is_honored_not_replaced(tmp_path):
    """An explicit empty rules list means "no rules" — only the
    ABSENCE of a file falls back to the defaults (the or-fallback
    would silently re-enable what the user disabled)."""
    from code2vec_tpu.obs import Watchdog, build_live_plane
    from code2vec_tpu.obs.health import default_train_monitors
    p = tmp_path / "empty.json"
    p.write_text("[]")
    t = Telemetry.memory("m")
    plane = build_live_plane(
        t, metrics_port=0, alerts_mode="warn",
        alerts_rules=str(p), health_every_s=1.0,
        watchdog=Watchdog.disabled(),
        monitors=default_train_monitors(),
        default_rules=default_train_rules)
    assert plane.alerts.enabled and plane.alerts.rules == []
    plane_default = build_live_plane(
        t, metrics_port=0, alerts_mode="warn", alerts_rules=None,
        health_every_s=1.0, watchdog=Watchdog.disabled(),
        monitors=default_train_monitors(),
        default_rules=default_train_rules)
    assert [r.name for r in plane_default.alerts.rules] \
        == [r.name for r in default_train_rules()]


def test_static_gauges_exempt_from_staleness():
    t = Telemetry.memory("m")
    t.gauge("train/max_contexts", 16, emit=False, static=True)
    t.gauge("serve/queue_depth", 3, emit=False)
    ages = t.gauge_ages()
    assert "serve/queue_depth" in ages
    assert "train/max_contexts" not in ages  # set-once: never stale
    assert t.gauges["train/max_contexts"] == 16  # value still served


def test_default_rule_sets_construct():
    assert {r.name for r in default_train_rules()} >= {
        "loss_nonfinite", "loss_spike", "throughput_regression",
        "infeed_starvation"}
    assert {r.name for r in default_serving_rules()} == {
        "cache_hit_collapse", "shed_burn_rate"}
    for m in default_train_monitors():
        assert m.name


# ---- acceptance: injected NaN during a real CPU train run ----

def _nan_train_model(tmp_path, alerts_mode):
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.helpers import build_tiny_dataset
    from tests.test_model import tiny_config

    d = str(tmp_path / "ds")
    os.makedirs(d, exist_ok=True)
    prefix = build_tiny_dataset(d, n_train=96, n_val=8, n_test=8,
                                max_contexts=16)
    tdir = os.path.join(d, "tele")
    cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=8, TELEMETRY_DIR=tdir,
                      ALERTS_MODE=alerts_mode, HEALTH_EVERY_S=0.05)
    model = Code2VecModel(cfg)
    import jax.numpy as jnp
    orig_step = model._train_step
    calls = []

    def nan_step(params, opt_state, batch, rng):
        calls.append(1)
        params, opt_state, loss = orig_step(params, opt_state, batch,
                                            rng)
        if len(calls) >= 3:
            loss = jnp.float32(float("nan"))
        # pace the loop so the 0.05s health cadence provably sweeps
        # between steps (the injected NaN persists either way)
        time.sleep(0.03)
        return params, opt_state, loss

    model._train_step = nan_step
    return model, tdir


def _run_events(tdir):
    runs = [os.path.join(tdir, d) for d in os.listdir(tdir)]
    assert len(runs) == 1
    with open(os.path.join(runs[0], "events.jsonl")) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def test_injected_nan_fires_edge_triggered_alert_warn(tmp_path):
    model, tdir = _nan_train_model(tmp_path, "warn")
    model.train()  # warn mode: the run completes
    events = _run_events(tdir)
    alerts = [e for e in events if e["kind"] == "alert"]
    firing = [e for e in alerts if e["transition"] == "firing"
              and e["rule"] == "loss_nonfinite"]
    assert len(firing) == 1, f"expected ONE edge-triggered firing " \
                             f"event, got {alerts}"
    assert firing[0]["severity"] == "page"
    assert firing[0]["metric"] == "health/loss_nonfinite"
    # counters made it into the close()-time summary too
    summary = events[-1]
    assert summary["kind"] == "summary"
    assert summary["counters"]["alerts/fired"] == 1


def test_report_tool_renders_alerts_table(tmp_path, capsys):
    """tools/telemetry_report.py grows an alerts table (ISSUE 7
    satellite): the run's alert events come back as one row per
    edge-triggered transition."""
    model, tdir = _nan_train_model(tmp_path, "warn")
    model.train()
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(
            os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            "tools", "telemetry_report.py"))
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)
    assert report.main([tdir]) == 0
    out = capsys.readouterr().out
    assert "| Alert | transition |" in out
    assert "| loss_nonfinite | firing | threshold " \
           "| health/loss_nonfinite >=" in out


def test_injected_nan_raise_mode_sticky_at_next_beat(tmp_path):
    model, tdir = _nan_train_model(tmp_path, "raise")
    with pytest.raises(AlertError, match="loss_nonfinite"):
        model.train()
    events = _run_events(tdir)
    assert any(e["kind"] == "alert"
               and e["rule"] == "loss_nonfinite" for e in events)
    # the error surfaced from the LOOP (a beat), not the monitor
    # thread: steps kept recording after the alert fired
    steps = [e for e in events if e["kind"] == "step"]
    assert steps, "no steps recorded"
