"""Smoke-test bench.py's measurement machinery on the virtual CPU mesh
with tiny capacities: the benchmark is the driver-facing artifact run
once per round on real hardware, so API drift (encoder/step/loss
signatures, optimizer construction, JSON assembly) must be caught by CI
rather than at round end."""

import json

import numpy as np
import pytest

import bench


@pytest.fixture(autouse=True)
def tiny_bench(monkeypatch):
    monkeypatch.setattr(bench, "TOKEN_VOCAB", 128)
    monkeypatch.setattr(bench, "PATH_VOCAB", 96)
    monkeypatch.setattr(bench, "TARGET_VOCAB", 64)
    monkeypatch.setattr(bench, "BATCH", 8)
    monkeypatch.setattr(bench, "MAX_CONTEXTS", 6)
    monkeypatch.setattr(bench, "NUM_SAMPLED", 16)
    monkeypatch.setattr(bench, "WARMUP_STEPS", 1)
    monkeypatch.setattr(bench, "MEASURE_STEPS", 4)


def test_measure_encoder_and_floor_run():
    # API-drift smoke only: on a contended CI host the slope-timed
    # difference of two tiny chains can legitimately come out <= 0, so
    # assert finiteness, not positivity (bench runs on an idle chip).
    pc, ms, gbps = bench._measure_encoder("bag")
    assert all(np.isfinite(x) for x in (pc, ms, gbps))
    floor = bench._measure_fwd_bwd_floor()
    assert np.isfinite(floor)


def test_main_emits_one_valid_json_line(monkeypatch, capsys):
    # the 1-GiB ceiling copy is too slow for CI; stub it
    monkeypatch.setattr(bench, "_measure_hbm_ceiling",
                        lambda: 590e9)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    j = json.loads(out[0])
    for key in ("metric", "value", "unit", "vs_baseline", "ms_per_step",
                "hbm_gbps", "hbm_ceiling_gbps",
                "fwd_bwd_floor_pc_per_sec", "optimizer_efficiency",
                "transformer_pc_per_sec"):
        assert key in j, key
    assert j["metric"] == "path-contexts/sec/chip"
    assert np.isfinite(j["value"])


def test_graft_entry_forward_compiles():
    """entry() is the driver's single-chip compile check — keep it
    importable and jittable."""
    import jax

    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.shape[0] == 256
