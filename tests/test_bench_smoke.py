"""Smoke-test bench.py's measurement machinery on the virtual CPU mesh
with tiny capacities: the benchmark is the driver-facing artifact run
once per round on real hardware, so API drift (encoder/step/loss
signatures, optimizer construction, JSON assembly) must be caught by CI
rather than at round end."""

import json

import numpy as np
import pytest

import bench


@pytest.fixture(autouse=True)
def tiny_bench(monkeypatch):
    monkeypatch.setattr(bench, "TOKEN_VOCAB", 128)
    monkeypatch.setattr(bench, "PATH_VOCAB", 96)
    monkeypatch.setattr(bench, "TARGET_VOCAB", 64)
    monkeypatch.setattr(bench, "BATCH", 8)
    monkeypatch.setattr(bench, "MAX_CONTEXTS", 6)
    monkeypatch.setattr(bench, "NUM_SAMPLED", 16)
    monkeypatch.setattr(bench, "WARMUP_STEPS", 1)
    monkeypatch.setattr(bench, "MEASURE_STEPS", 4)


def test_measure_encoder_and_floor_run():
    # API-drift smoke only: on a contended CI host the slope-timed
    # difference of two tiny chains can legitimately come out <= 0, so
    # assert finiteness, not positivity (bench runs on an idle chip).
    pc, ms, gbps = bench._measure_encoder("bag")
    assert all(np.isfinite(x) for x in (pc, ms, gbps))
    floor = bench._measure_fwd_bwd_floor()
    assert np.isfinite(floor)


def test_main_emits_one_valid_json_line(monkeypatch, capsys):
    # the 1-GiB ceiling copy is too slow for CI; stub it
    monkeypatch.setattr(bench, "_measure_hbm_ceiling",
                        lambda: 590e9)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    j = json.loads(out[0])
    for key in ("metric", "value", "unit", "vs_baseline", "ms_per_step",
                "hbm_gbps", "hbm_ceiling_gbps",
                "fwd_bwd_floor_pc_per_sec", "optimizer_efficiency",
                "transformer_pc_per_sec",
                # int8 requantize phase attribution (round 6): the
                # acceptance contract is these fields present off-TPU
                "int8_hbm_gbps", "int8_requant_ms", "int8_requant_bytes",
                "int8_requant_gbps", "int8_requant_floor_ms",
                "int8_requant_vs_ceiling", "int8_requant_fused",
                # sparse table-update attribution (round 13): same
                # present-off-TPU contract
                "sparse_pc_per_sec", "sparse_ms_per_step",
                "sparse_hbm_gbps", "sparse_step_floor_pc_per_sec",
                "sparse_optimizer_efficiency", "sparse_update_ms",
                "sparse_update_bytes", "sparse_update_gbps",
                "sparse_update_floor_ms", "sparse_update_vs_ceiling",
                "sparse_update_unique_rows", "sparse_update_fused"):
        assert key in j, key
    assert j["metric"] == "path-contexts/sec/chip"
    assert np.isfinite(j["value"])
    assert j["int8_requant_fused"] is False  # CPU -> reference path
    assert j["int8_requant_bytes"] > 0
    assert j["sparse_update_fused"] is False  # CPU -> reference path
    assert j["sparse_update_bytes"] > 0
    # per-table uniques are bounded by each vocab (the id draws cover
    # the tiny vocabs almost fully: _device_batches' max_contexts
    # default binds the REAL 200 at import time, not the patched 6)
    assert 0 < j["sparse_update_unique_rows"] <= 128 + 96 + 64


def test_step_hbm_bytes_counts_quantized_carrier():
    """int8 subtrees: the analytic grad term must size the bf16 [V, E]
    carrier (2 B/elt), not the stored int8 (1 B/elt), and the param
    term the q/s read+write (ADVICE r5 finding 2)."""
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import ModelDims, init_params
    from code2vec_tpu.ops.quant import is_quantized

    dims = ModelDims(token_vocab_size=64, path_vocab_size=32,
                     target_vocab_size=24, embeddings_size=8,
                     max_contexts=6, tables_dtype="int8")
    params = init_params(jax.random.PRNGKey(0), dims)
    opt_state = {"nu": jnp.zeros((3, 4), jnp.float32)}
    expected = opt_state["nu"].size * 4 * 2
    for p in params.values():
        if is_quantized(p):
            expected += (p["q"].size * 2 * 2          # bf16 carrier r+w
                         + p["q"].size * 1 * 2        # int8 q r+w
                         + p["s"].size * 4 * 2)       # f32 s r+w
        else:
            expected += p.size * p.dtype.itemsize * 4
    assert bench._step_hbm_bytes(params, opt_state) == expected
    # regression guard for the original bug: the quantized accounting
    # must exceed stored-dtype sizing (1 B grads) for the same params
    naive = sum(x.size * x.dtype.itemsize * 4
                for x in jax.tree_util.tree_leaves(params)) \
        + opt_state["nu"].size * 4 * 2
    assert bench._step_hbm_bytes(params, opt_state) > naive


def test_graft_entry_forward_compiles():
    """entry() is the driver's single-chip compile check — keep it
    importable and jittable."""
    import jax

    import __graft_entry__ as g
    fn, args = g.entry()
    # one-shot compile IS the test  # graftlint: disable=retrace-hazard
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.shape[0] == 256


def test_aggregate_projection_collective_model():
    """The v4-32 projection (tools/aggregate_projection.py) must model
    DP efficiency from explicit collective traffic, not imply 1.0
    (VERDICT r3 item 5)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "aggregate_projection",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools",
            "aggregate_projection.py"))
    ap = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ap)

    m = ap.collective_model(per_chip_batch=1024, step_ms=26.0)
    dp = m["pure_dp16_replicated"]
    tp = m["data4xmodel4_rowsharded"]
    # both shipped meshes are itemized with strictly positive comm
    assert 0 < dp["dp_efficiency"] < 1 and dp["comm_ms"] > 0
    assert tp["comm_ms"] > 0
    # the TP mesh models compute replication explicitly (ADVICE r4:
    # shard_batch shards over 'data' only, so model-axis chips repeat
    # the dense work): replicated + sharded + comm adds up to the
    # modeled group step, and the aggregate counts each batch shard
    # once — NOT chips x per-chip
    assert tp["replicated_dense_ms"] > 0
    assert abs(tp["replicated_dense_ms"] + tp["sharded_table_ms"]
               + tp["comm_ms"] - tp["modeled_step_ms_per_group"]) < 0.05
    recon = m["data_ax"] * 1024 * ap.CTX \
        / tp["modeled_step_ms_per_group"] * 1e3
    assert abs(tp["aggregate_pc_per_sec"] - recon) / recon < 1e-2
    # bytes sanity: replicated allreduce carries the three bf16 tables
    expected = 2 * (ap.VT * ap.E + ap.VP * ap.E + ap.VY * ap.D3)
    assert abs(dp["allreduce_bytes_per_step"] - expected) < 1e7
    # the formula itself rides in the output (checkable prose)
    assert "2*(N-1)/N" in m["formula"]
    assert "replicate" in m["formula"]
    # a zero-comm step would be efficiency 1; the DP formula must be
    # monotone in step time (longer steps amortize the same traffic)
    m_slow = ap.collective_model(per_chip_batch=1024, step_ms=100.0)
    assert m_slow["dp_efficiency"] > m["dp_efficiency"]
