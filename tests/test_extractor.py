"""Native C++ extractor tests: golden-file comparison (SURVEY.md §5:
"C++ extractor output vs. checked-in expected output"), hash parity,
normalization parity with common.py, robustness on malformed input, and
the Python-AST frontend."""

import os
import subprocess

import pytest

from code2vec_tpu.common import split_to_subtokens
from code2vec_tpu.extractor import python_extractor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIN = os.path.join(REPO, "code2vec_tpu", "extractor", "build",
                   "c2v_extract")
GOLDEN_DIR = os.path.join(REPO, "tests", "golden")

needs_binary = pytest.mark.skipif(
    not os.path.exists(BIN),
    reason="native extractor not built (run ./build_extractor.sh)")


def run_extractor(*args) -> str:
    proc = subprocess.run([BIN, *args], capture_output=True, text=True,
                          timeout=60)
    assert proc.returncode == 0, proc.stderr
    return proc.stdout


@needs_binary
@pytest.mark.parametrize("name", ["Example.java", "Hard.java"])
def test_golden_files(name):
    out = run_extractor("--file", os.path.join(GOLDEN_DIR, name))
    with open(os.path.join(GOLDEN_DIR, name + ".expected")) as f:
        expected = f.read()
    assert out == expected


@needs_binary
def test_output_format_contract(tmp_path):
    """SURVEY.md §3.2: `name ctx1 ... ctxN`, ctx = tok,pathHash,tok."""
    src = tmp_path / "T.java"
    src.write_text(
        "class T { int addTwo(int x) { return x + 2; } }")
    out = run_extractor("--file", str(src)).strip()
    lines = out.splitlines()
    assert len(lines) == 1
    parts = lines[0].split(" ")
    assert parts[0] == "add|two"
    assert len(parts) > 1
    for ctx in parts[1:]:
        fields = ctx.split(",")
        assert len(fields) == 3
        int(fields[1])  # hashed path is an integer
    # method's own name leaf appears as the special token
    assert any("METHOD_NAME" in c for c in parts[1:])
    # parameter and literal leaves appear normalized
    joined = " ".join(parts[1:])
    assert ",2" in joined or "2," in joined  # int literal kept
    assert "x," in joined or ",x" in joined


@needs_binary
def test_java_string_hash_parity():
    from code2vec_tpu.extractor import native
    # pure-python fallback vs the C implementation
    lib = native._load()
    if lib is None:
        pytest.skip("libc2v.so not built")
    for s in ["", "a", "hello", "NameExpr^BlockStmt_ReturnStmt",
              "x" * 100]:
        c_val = lib.c2v_java_string_hash(s.encode())
        py_val = python_extractor.java_string_hash(s)
        assert c_val == py_val, s
    # known Java values: "hello".hashCode() == 99162322
    assert python_extractor.java_string_hash("hello") == 99162322
    assert python_extractor.java_string_hash("polygenelubricants") == \
        -2147483648  # the classic Integer.MIN_VALUE hash


@needs_binary
def test_normalization_parity_with_common(tmp_path):
    """C++ subtoken splitting must match common.split_to_subtokens."""
    src = tmp_path / "N.java"
    src.write_text("class N { void fooBarBaz(int someHTMLValue2x) "
                   "{ use(someHTMLValue2x); } }")
    out = run_extractor("--file", str(src))
    assert out.splitlines()[0].split(" ")[0] == \
        "|".join(split_to_subtokens("fooBarBaz"))
    assert "|".join(split_to_subtokens("someHTMLValue2x")) == \
        "some|html|value|x"
    assert "some|html|value|x," in out or ",some|html|value|x" in out


@needs_binary
def test_path_length_and_width_flags(tmp_path):
    src = tmp_path / "L.java"
    src.write_text("class L { int deep(int a) { if (a > 0) { "
                   "while (a > 1) { a = a - 1; } } return a; } }")
    wide = run_extractor("--file", str(src), "--max_path_length", "12",
                         "--max_path_width", "3")
    narrow = run_extractor("--file", str(src), "--max_path_length", "4",
                           "--max_path_width", "1")
    assert len(wide.split(" ")) > len(narrow.split(" "))


@needs_binary
def test_malformed_input_never_crashes(tmp_path):
    cases = [
        "",                               # empty
        "not java at all @@@@ %%%",       # garbage
        "class X {",                      # unbalanced
        "class X { void f( { } }",        # broken params
        "class X { void f() { if (a }",   # broken body
        "class X { void g() { return 1; } void ok() { use(x); } }",
    ]
    for i, src in enumerate(cases):
        p = tmp_path / f"M{i}.java"
        p.write_text(src)
        run_extractor("--file", str(p))  # asserts rc == 0


@needs_binary
def test_fuzz_mutated_and_random_sources(tmp_path):
    """Seeded fuzz: byte-level mutations of a real source plus random
    token soup must never crash the parser (fuel-bounded recursive
    descent; ASan build available via C2V_SANITIZE). One threaded --dir
    run over all cases keeps this fast."""
    import random
    rng = random.Random(0xC2)
    with open(os.path.join(GOLDEN_DIR, "Example.java")) as f:
        base = f.read()
    d = tmp_path / "fuzz"
    d.mkdir()
    for i in range(60):  # mutations: delete / insert / splice
        s = list(base)
        for _ in range(rng.randint(1, 8)):
            op = rng.randrange(3)
            pos = rng.randrange(max(len(s), 1))
            if op == 0 and s:
                del s[pos]
            elif op == 1:
                s.insert(pos, rng.choice("{}();,.<>[]@\"'\\\x00\xff"))
            else:
                s.insert(pos, rng.choice(["class", "((", "}}", "/*",
                                          "*/", "//", "\"", "for(",
                                          "int", "...."]))
        (d / f"Mut{i}.java").write_text("".join(s), errors="replace")
    soup = ("class interface enum void int if for while return new "
            "{ } ( ) ; , . < > [ ] = + - ! @ # $ % \" ' \\ 0x1p3 "
            "é 中").split(" ")
    for i in range(40):
        (d / f"Soup{i}.java").write_text(
            " ".join(rng.choice(soup)
                     for _ in range(rng.randint(0, 400))))
    run_extractor("--dir", str(d), "--num_threads", "4")  # rc == 0


@needs_binary
def test_dir_mode_and_threads(tmp_path):
    for i in range(8):
        (tmp_path / f"F{i}.java").write_text(
            f"class F{i} {{ int getNum{i}() {{ return {i}; }} }}")
    out = run_extractor("--dir", str(tmp_path), "--num_threads", "4")
    lines = out.strip().splitlines()
    assert len(lines) == 8
    names = sorted(ln.split(" ")[0] for ln in lines)
    assert names[0].startswith("get|num")


def test_ctypes_in_process_extraction():
    from code2vec_tpu.extractor import native
    if native._load() is None:
        pytest.skip("libc2v.so not built")
    lines = native.extract_source(
        "class C { int plusOne(int v) { return v + 1; } }")
    assert len(lines) == 1
    assert lines[0].startswith("plus|one ")


# ---- Python-AST frontend (python150k config) ----

def test_python_extractor_basic():
    lines = python_extractor.extract_source(
        "def add_two(x):\n    return x + 2\n")
    assert len(lines) == 1
    parts = lines[0].split(" ")
    assert parts[0] == "add|two"
    for ctx in parts[1:]:
        fields = ctx.split(",")
        assert len(fields) == 3
        int(fields[1])
    assert any("METHOD_NAME" in c for c in parts[1:])


def test_python_extractor_multiple_and_nested():
    src = (
        "def outer(a, b):\n"
        "    def inner(c):\n"
        "        return c * 2\n"
        "    return inner(a) + b\n"
        "\n"
        "class K:\n"
        "    def method_one(self, value):\n"
        "        if value > 0:\n"
        "            return self.cache[value]\n"
        "        return None\n")
    lines = python_extractor.extract_source(src)
    names = [ln.split(" ")[0] for ln in lines]
    assert "outer" in names and "inner" in names and "method|one" in names


def test_python_extractor_syntax_error_returns_empty():
    assert python_extractor.extract_source("def broken(:\n  pass") == []


def test_python_extractor_respects_length_limit():
    src = ("def f(a):\n"
           "    if a:\n"
           "        while a:\n"
           "            a = a - 1\n"
           "    return a\n")
    wide = python_extractor.extract_source(src, max_path_length=14)
    narrow = python_extractor.extract_source(src, max_path_length=4)
    n_wide = len(wide[0].split(" ")) if wide else 0
    n_narrow = len(narrow[0].split(" ")) if narrow else 0
    assert n_wide > n_narrow
