"""tools/epoch_overhead.py — the ISSUE 5 boundary-stall driver.

Tier-1 covers the event analysis (boundary wall, steps-during-save
window) on synthetic events; the end-to-end sync-vs-async measurement
run is `slow`-marked (it trains two models), same policy as
requant_sweep / loadgen.
"""

import json

import pytest

from tools.epoch_overhead import analyze, main


def test_analyze_boundary_and_overlap_windows():
    events = [
        {"kind": "step", "ts": 1.00, "step": 7, "step_ms": 10.0},
        {"kind": "step", "ts": 1.01, "step": 8, "step_ms": 10.0},
        {"kind": "save", "ts": 1.02, "step": 8, "blocked_ms": 5.0,
         "is_async": True},
        {"kind": "step", "ts": 1.05, "step": 9, "step_ms": 10.0},
        {"kind": "step", "ts": 1.10, "step": 10, "step_ms": 10.0},
        {"kind": "save_committed", "ts": 1.12, "step": 8,
         "total_ms": 100.0},
        {"kind": "step", "ts": 1.20, "step": 11, "step_ms": 10.0},
    ]
    rows = analyze(events)
    assert len(rows) == 1
    r = rows[0]
    assert r["step"] == 8
    assert r["blocked_ms"] == 5.0 and r["total_ms"] == 100.0
    # boundary: last step event at/below the save step -> first after
    assert r["boundary_ms"] == pytest.approx((1.05 - 1.01) * 1e3, abs=0.2)
    # steps 9 and 10 fired inside [save.ts, commit.ts]
    assert r["steps_during_save"] == 2


@pytest.mark.slow
def test_epoch_overhead_cli_end_to_end(capsys):
    """Small but real sync-vs-async comparison on the CPU harness: the
    acceptance numbers come from this driver at default scale."""
    result = main(["--epochs", "3", "--examples", "128", "--batch", "32",
                   "--emb", "16", "--warmup_boundaries", "2"])
    assert len(result["sync"]) == 3 and len(result["async"]) == 3
    s = result["summary"]
    assert s["sync_save_wall_ms_p50"] > 0
    assert s["async_blocked_ms_p50"] == s["async_blocked_ms_p50"]  # not nan
    # every boundary row is printed as a JSON line
    out = capsys.readouterr().out
    assert sum(1 for ln in out.splitlines()
               if ln.startswith("{") and "mode" in json.loads(ln)) == 6
