"""Async checkpointing (ISSUE 5): zero-stall epoch boundaries.

Covers the acceptance contract on the CPU mesh harness:
  - the train loop emits `save_blocked_ms` << `save_total_ms` with
    async on, and training steps demonstrably proceed while the writer
    drains;
  - `--async_checkpoint off` reproduces the synchronous checkpoint
    directory layout bit-for-bit (same file tree, same restored
    values);
  - crash safety: a writer killed before the `state` rename commits
    leaves auto-resume pointing at the last COMMITTED step (the
    torn-write protocol survives the async path);
  - mid-train save -> restore parity: the snapshot is the exact params
    at save time, unpolluted by the donated-buffer updates that race
    the background writer;
  - sidecar write-once semantics keep `--release` correct;
  - tools/telemetry_report.py renders the epoch-boundary table from
    the new save / save_committed / eval events.
"""

import json
import os
import time

import jax
import numpy as np
import pytest

from code2vec_tpu.models.jax_model import Code2VecModel
from code2vec_tpu.training import checkpoint as ckpt
from tests.helpers import build_tiny_dataset
from tests.test_model import tiny_config


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("data")
    return build_tiny_dataset(str(d), n_train=256, n_val=32, n_test=64,
                              max_contexts=16)


def _read_events(run_dir):
    out = []
    with open(os.path.join(run_dir, "events.jsonl"),
              encoding="utf-8") as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


def _tree_leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(jax.device_get(x)),
                                      np.asarray(jax.device_get(y)))


def test_async_blocked_far_below_total_and_steps_overlap(
        dataset, tmp_path, monkeypatch):
    """The CI acceptance assertion, deflaked (ISSUE 12): the PROOF that
    training proceeds while the writer drains is event-ordering, not a
    wall-clock ratio (the old `blocked < 0.25 * total` bar flaked
    under 2-core contention). The injected save_fn GATES the first
    commit on the train loop advancing past the save's step — if
    submit blocked the loop, no step could ever arrive and the gate's
    deadline fails the test; if it returned, the observed step advance
    is the overlap, deterministically."""
    real_save = ckpt.save_checkpoint
    model_box = []
    overlap_steps = {}

    def gated_save(ckpt_dir, state, step, *a, **k):
        # runs ON the writer thread: refuse to commit save #1 until
        # the LOOP has dispatched more training steps (epoch 2 runs
        # while this save is in flight). Bounded wait: a loop wedged
        # on submit shows up as overlap 0, not a hang.
        if not overlap_steps:
            deadline = time.monotonic() + 30.0
            while (model_box and model_box[0].step_num <= step
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            overlap_steps[step] = model_box[0].step_num - step \
                if model_box else 0
        return real_save(ckpt_dir, state, step, *a, **k)

    monkeypatch.setattr(ckpt, "save_checkpoint", gated_save)
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=2, SAVE_EVERY_EPOCHS=1,
                      save_path=str(tmp_path / "ckpt"),
                      TELEMETRY_DIR=str(tmp_path / "tele"))
    cfg.test_data_path = None  # no eval: epoch-2 steps fill the drain
    assert cfg.ASYNC_CHECKPOINT  # the default
    model = Code2VecModel(cfg)
    model_box.append(model)
    model.train()
    model.close_session()

    # the writer observed the loop training PAST the save step while
    # save #1 was still writing: submit did not block the loop
    (first_step, advanced), = overlap_steps.items()
    assert advanced >= 1, (
        "no training steps ran while the writer drained — submit "
        "blocked the loop")
    events = _read_events(model.telemetry.run_dir)
    saves = {e["step"]: e for e in events if e["kind"] == "save"}
    commits = {e["step"]: e for e in events
               if e["kind"] == "save_committed"}
    assert len(saves) == 2 and len(commits) == 2
    assert min(saves) == first_step
    assert saves[first_step]["is_async"] is True
    # the loop-side event carries blocked_ms, the writer-side event
    # carries total_ms (the deterministic-ratio assertion lives in
    # test_writer_total_ms_under_fake_clock — no wall-clock bar here)
    assert "blocked_ms" in saves[first_step]
    assert "total_ms" in commits[first_step]
    # both epochs' checkpoints committed despite the gated writer
    assert ckpt.latest_step(cfg.save_path) == model.step_num


def test_writer_total_ms_under_fake_clock(tmp_path):
    """The timing contract, sleep-free (ISSUE 12): with the writer's
    injectable clock, a save_fn that advances the fake clock 300 "ms"
    produces EXACTLY total_ms=300.0 in the save_committed event — the
    old test asserted this shape through a real sleep and a flaky
    wall-clock ratio."""
    clk = {"t": 100.0}
    recorded = {}

    class _Tele:
        def record_ms(self, name, ms):
            recorded[name] = ms

        def event(self, kind, **fields):
            recorded[kind] = fields

    def fake_disk_save(ckpt_dir, state, step, vocabs, dims, **kw):
        clk["t"] += 0.3  # the simulated disk tail, in fake seconds

    writer = ckpt.AsyncCheckpointWriter(save_fn=fake_disk_save,
                                        clock=lambda: clk["t"])
    writer.submit(str(tmp_path), {}, 7, None, None, telemetry=_Tele())
    writer.wait()
    writer.close()
    assert recorded["train/save_total_ms"] == pytest.approx(300.0)
    assert recorded["save_committed"]["step"] == 7
    assert recorded["save_committed"]["total_ms"] == pytest.approx(
        300.0)


def test_sync_flag_reproduces_checkpoint_layout(dataset, tmp_path):
    """--async_checkpoint off must be today's synchronous save — and
    the async dir must be indistinguishable from it: identical file
    tree, identical manifest, identical restored values (same seed and
    data give the same trajectory)."""
    def run(use_async, tag):
        cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=2,
                          SAVE_EVERY_EPOCHS=1,
                          ASYNC_CHECKPOINT=use_async,
                          save_path=str(tmp_path / tag))
        cfg.test_data_path = None
        model = Code2VecModel(cfg)
        model.train()
        model.close_session()
        return model

    m_async = run(True, "a")
    m_sync = run(False, "s")

    def layout(root):
        """The checkpoint-protocol layout: every file/dir relative
        path, pruned INSIDE the orbax `state` trees (ocdbt names its
        data blobs with unique ids, so their filenames legitimately
        differ run to run — the protocol contract is the step dirs,
        the committed `state` marker, and the sidecars)."""
        out = set()
        for base, dirs, files in os.walk(root):
            rel = os.path.relpath(base, root)
            if "state" in dirs:
                out.add(os.path.normpath(os.path.join(rel, "state")))
                dirs.remove("state")
            for f in files:
                out.add(os.path.normpath(os.path.join(rel, f)))
        return out

    sync_layout = layout(str(tmp_path / "s"))
    assert layout(str(tmp_path / "a")) == sync_layout
    # and that layout is exactly the documented protocol shape
    steps_per_epoch = m_sync.step_num // 2
    assert {p for p in sync_layout if "state" in p} == {
        os.path.join(f"step_{steps_per_epoch * e}", "state")
        for e in (1, 2)}
    assert {p for p in sync_layout if os.sep not in p} == {
        "manifest.json", "vocab.pkl"}
    assert (ckpt.latest_step(str(tmp_path / "a"))
            == ckpt.latest_step(str(tmp_path / "s")))
    assert (ckpt.load_manifest(str(tmp_path / "a"))
            == ckpt.load_manifest(str(tmp_path / "s")))
    _tree_leaves_equal(m_async.params, m_sync.params)
    # restored values agree too (the async snapshot wrote the same
    # bytes the sync save did)
    template = {"params": m_sync.params, "opt_state": m_sync.opt_state,
                "step": 0}
    a = ckpt.load_checkpoint(str(tmp_path / "a"), template)
    s = ckpt.load_checkpoint(str(tmp_path / "s"), template)
    _tree_leaves_equal(a, s)


def test_writer_crash_before_commit_preserves_resume(dataset, tmp_path):
    """Kill the writer before the `state` rename: the torn step dir is
    invisible to latest_step, auto-resume restores the last COMMITTED
    step, and the failure surfaces at the barrier instead of vanishing."""
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=1, SAVE_EVERY_EPOCHS=1,
                      save_path=ckpt_dir)
    cfg.test_data_path = None
    model = Code2VecModel(cfg)
    model.train()
    model.close_session()
    committed = ckpt.latest_step(ckpt_dir)
    assert committed == model.step_num

    def killed_mid_save(ckpt_dir, state, step, vocabs, dims, **kw):
        # what a preemption mid-orbax-write leaves behind: a step dir
        # with temp content but NO renamed `state`
        os.makedirs(os.path.join(ckpt_dir, f"step_{step}",
                                 "state.orbax-checkpoint-tmp"),
                    exist_ok=True)
        raise RuntimeError("writer killed before commit")

    writer = ckpt.AsyncCheckpointWriter(save_fn=killed_mid_save)
    state = {"params": model.params, "opt_state": model.opt_state,
             "step": model.step_num + 5}
    writer.submit(ckpt_dir, state, model.step_num + 5, model.vocabs,
                  model.dims)
    with pytest.raises(RuntimeError, match="killed before commit"):
        writer.wait()
    writer.close()

    # the torn dir exists but is invisible to resume
    assert os.path.isdir(os.path.join(
        ckpt_dir, f"step_{model.step_num + 5}"))
    assert ckpt.latest_step(ckpt_dir) == committed

    # auto-resume semantics: a fresh model loading this dir restores
    # the committed step
    cfg2 = tiny_config(dataset)
    cfg2.load_path = ckpt_dir
    model2 = Code2VecModel(cfg2)
    assert model2.step_num == committed
    _tree_leaves_equal(model2.params, model.params)


def test_mid_train_async_save_restore_parity(dataset, tmp_path):
    """The epoch-1 checkpoint of a 2-epoch async run must be the EXACT
    params a 1-epoch run ends with (same seed/data => same trajectory):
    the on-device snapshot is immune to the donated-buffer updates the
    next epoch makes while the writer is still draining. Constant LR:
    the cosine schedule's horizon depends on NUM_TRAIN_EPOCHS, which
    would legitimately diverge the two trajectories."""
    def run(epochs, tag):
        cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=epochs,
                          SAVE_EVERY_EPOCHS=1, LR_SCHEDULE="constant",
                          save_path=str(tmp_path / tag))
        cfg.test_data_path = None
        model = Code2VecModel(cfg)
        model.train()
        model.close_session()
        return model

    m1 = run(1, "one")
    m2 = run(2, "two")
    steps_per_epoch = m1.step_num
    assert m2.step_num == 2 * steps_per_epoch
    # both epoch checkpoints committed in the 2-epoch run
    template = {"params": m2.params, "opt_state": m2.opt_state,
                "step": 0}
    mid = ckpt.load_checkpoint(str(tmp_path / "two"), template,
                               step=steps_per_epoch)
    assert int(jax.device_get(mid["step"])) == steps_per_epoch
    _tree_leaves_equal(mid["params"], m1.params)
    # and the final checkpoint is the final params
    final = ckpt.load_checkpoint(str(tmp_path / "two"), template)
    _tree_leaves_equal(final["params"], m2.params)


def test_sidecars_written_once_and_release_step_correct(
        dataset, tmp_path, monkeypatch):
    """Satellite: epoch saves must not re-pickle vocab.pkl / rewrite an
    unchanged manifest.json, and --release must still pick the REAL
    latest step (the manifest's `step` is advisory now)."""
    from code2vec_tpu.vocab.vocabularies import Code2VecVocabs
    calls = []
    real_save = Code2VecVocabs.save

    def counting_save(self, path):
        calls.append(path)
        return real_save(self, path)

    monkeypatch.setattr(Code2VecVocabs, "save", counting_save)
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = tiny_config(dataset, NUM_TRAIN_EPOCHS=3, SAVE_EVERY_EPOCHS=1,
                      save_path=ckpt_dir)
    cfg.test_data_path = None
    model = Code2VecModel(cfg)
    model.train()
    model.close_session()
    assert len([c for c in calls if c.startswith(ckpt_dir)]) == 1, (
        f"vocab.pkl re-pickled: {calls}")
    # the ON-DISK manifest step is the FIRST save's (write-once,
    # advisory) while load_manifest corrects it to the latest
    # COMMITTED step for every consumer (release, LR resume horizon)
    steps = sorted(s for s, _ in ckpt._step_dirs(ckpt_dir))
    assert len(steps) == 3
    with open(os.path.join(ckpt_dir, "manifest.json")) as f:
        assert json.load(f)["step"] == steps[0]
    assert ckpt.load_manifest(ckpt_dir)["step"] == steps[-1]

    # release resolves the latest committed step, not the stale field
    dest = str(tmp_path / "released")
    ckpt.release_checkpoint(ckpt_dir, dest, model.params)
    rel_manifest = ckpt.load_manifest(dest)
    assert rel_manifest["step"] == steps[-1] == model.step_num
    assert rel_manifest["released"] is True
    # and a released-checkpoint load restores that step
    cfg2 = tiny_config(dataset)
    cfg2.train_data_path = None
    cfg2.load_path = dest
    model_rel = Code2VecModel(cfg2)
    assert model_rel.step_num == model.step_num


def test_second_submit_blocks_never_drops(tmp_path):
    """One-in-flight discipline: submit #2 waits for save #1's commit;
    both land."""
    order = []

    def slow_save(ckpt_dir, state, step, vocabs, dims, **kw):
        time.sleep(0.15)
        order.append(step)

    writer = ckpt.AsyncCheckpointWriter(save_fn=slow_save)
    writer.submit("d", {}, 1, None, None)
    t0 = time.perf_counter()
    writer.submit("d", {}, 2, None, None)
    waited = time.perf_counter() - t0
    writer.wait()
    writer.close()
    assert order == [1, 2]
    assert waited >= 0.05  # submit #2 really blocked on save #1


def test_telemetry_report_renders_boundary_table(tmp_path):
    """Satellite: the epoch-boundary row (save_blocked_ms /
    save_total_ms / eval_ms / overlap) renders from the new events."""
    from tools.telemetry_report import boundary_rows, render
    run_dir = tmp_path / "run-x"
    run_dir.mkdir()
    (run_dir / "manifest.json").write_text(json.dumps(
        {"run_id": "run-x", "component": "train"}))
    events = [
        {"kind": "save", "ts": 10.0, "step": 8, "blocked_ms": 5.0,
         "is_async": True},
        {"kind": "save_committed", "ts": 10.2, "step": 8,
         "total_ms": 200.0},
        {"kind": "eval", "ts": 10.15, "step": 8, "epoch": 1,
         "loss": 1.0, "eval_ms": 120.0},
    ]
    with open(run_dir / "events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    rows = boundary_rows(events)
    assert rows == [{"step": 8, "blocked_ms": 5.0, "total_ms": 200.0,
                     "eval_ms": 120.0, "overlap": 1.0 - 5.0 / 200.0,
                     "is_async": True}]
    out = render([str(run_dir)])
    assert "Epoch boundary" in out
    assert "| 8 | async | 5.00 | 200.00 | 120.00 | 0.975 |" in out
