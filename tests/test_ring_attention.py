"""Ring attention == dense masked attention (forward AND gradients) on
the virtual CPU mesh, for several ring sizes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.ops.ring_attention import ring_attention
from code2vec_tpu.parallel.mesh import make_mesh


def dense_oracle(q, k, v, log_mask):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(float(q.shape[-1])) \
        + log_mask[:, None, None, :]
    attn = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", attn, v)


def _inputs(B=4, H=2, C=8, hd=4, seed=0):
    r = np.random.default_rng(seed)
    q = jnp.asarray(r.normal(size=(B, H, C, hd)), jnp.float32)
    k = jnp.asarray(r.normal(size=(B, H, C, hd)), jnp.float32)
    v = jnp.asarray(r.normal(size=(B, H, C, hd)), jnp.float32)
    mask = np.zeros((B, C), np.float32)
    mask[:, -2:] = -1e30  # padded keys
    return q, k, v, jnp.asarray(mask)


@pytest.mark.parametrize("ctx", [2, 4])
def test_ring_matches_dense_forward_and_grad(ctx):
    q, k, v, mask = _inputs()
    mesh = make_mesh(8 // (ctx), 1, ctx)  # data x ctx
    assert mesh.shape["ctx"] == ctx

    out_ref = dense_oracle(q, k, v, mask)
    out_ring = ring_attention(q, k, v, mask, mesh)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               atol=1e-5)

    def loss_ref(q, k, v):
        return jnp.sum(dense_oracle(q, k, v, mask) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mask, mesh) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4)


def test_ring_handles_fully_padded_shard():
    """A ring shard whose keys are ALL padded must not poison the
    softmax (running max stays finite once any real key is seen)."""
    q, k, v, mask = _inputs(C=8)
    mask = np.zeros((4, 8), np.float32)
    mask[:, 4:] = -1e30  # the entire second half-shard is padding
    mask = jnp.asarray(mask)
    mesh = make_mesh(4, 1, 2)
    out_ref = dense_oracle(q, k, v, mask)
    out_ring = ring_attention(q, k, v, mask, mesh)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               atol=1e-5)
    assert np.isfinite(np.asarray(out_ring)).all()


def test_ring_on_combined_dcn_ctx_mesh():
    """Ring attention must also be exact when the batch rides the
    composite ('dcn','data') axes alongside a ctx ring."""
    q, k, v, mask = _inputs()
    mesh = make_mesh(1, 2, 2, dcn=2)
    assert dict(mesh.shape) == {"dcn": 2, "data": 1, "ctx": 2,
                                "model": 2}
    out_ref = dense_oracle(q, k, v, mask)
    out_ring = ring_attention(q, k, v, mask, mesh)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               atol=1e-5)


def test_ring_matches_dense_bf16_compute():
    """bf16 q/k/v (the TPU compute dtype): the f32 running-softmax
    accumulators must keep ring ~ dense within bf16 tolerance."""
    q, k, v, mask = _inputs()
    q = q.astype(jnp.bfloat16)
    k = k.astype(jnp.bfloat16)
    v = v.astype(jnp.bfloat16)
    mesh = make_mesh(4, 1, 2)
    out_ref = dense_oracle(q, k, v, mask)
    out_ring = ring_attention(q, k, v, mask, mesh)
    assert out_ring.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out_ring, np.float32), np.asarray(out_ref, np.float32),
        atol=2e-2)


def test_ring_compiled_memory_is_o_c_over_s():
    """The O(C/s) memory claim (tools/ring_memory.py, BASELINE.md
    long-context row): per-device temp memory of the compiled ring
    program must be several times below the all-gather path's at a
    context length where the attention matrix dominates."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from code2vec_tpu.parallel.mesh import CONTEXT_AXIS

    B, H, C, hd = 2, 2, 2048, 16
    mesh = make_mesh(1, 1, 4)
    shard = NamedSharding(mesh, P(None, None, CONTEXT_AXIS, None))
    mshard = NamedSharding(mesh, P(None, CONTEXT_AXIS))
    q, k, v, _ = _inputs(B, H, C, hd)
    args = (jax.device_put(q, shard), jax.device_put(k, shard),
            jax.device_put(v, shard),
            jax.device_put(jnp.zeros((B, C), jnp.float32), mshard))
    shardings = (shard, shard, shard, mshard)
    ring = jax.jit(lambda q, k, v, m: ring_attention(q, k, v, m, mesh),
                   in_shardings=shardings, out_shardings=shard
                   ).lower(*args).compile()
    dense = jax.jit(dense_oracle, in_shardings=shardings,
                    out_shardings=shard).lower(*args).compile()
    r = ring.memory_analysis().temp_size_in_bytes
    d = dense.memory_analysis().temp_size_in_bytes
    # 4 ctx shards -> expect ~4x; accept >2x to stay robust across
    # XLA versions' fusion choices
    assert d / max(r, 1) > 2.0, (r, d)
