"""Unified run telemetry (code2vec_tpu/obs, ISSUE 2): registry + sink
contracts, the guaranteed-cheap disabled path, the CPU smoke train run
writing manifest + per-step JSONL (step_ms / infeed_wait_ms / loss),
tools/telemetry_report.py summarizing it into the BASELINE.md table
shape, and the serving REPL's p50/p95/p99 request-latency line."""

import importlib.util
import json
import os

import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.obs import (Telemetry, TimerStat, TrainStepRecorder,
                              format_latency_line)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_report_tool():
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(REPO, "tools",
                                         "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _read_events(run_dir):
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


def _one_run_dir(telemetry_dir):
    runs = [d for d in os.listdir(telemetry_dir)
            if os.path.exists(os.path.join(telemetry_dir, d,
                                           "manifest.json"))]
    assert len(runs) == 1, runs
    return os.path.join(telemetry_dir, runs[0])


# ---- registry ----

def test_timer_stat_percentiles_and_summary():
    t = TimerStat()
    for v in range(1, 101):
        t.record(float(v))
    s = t.summary()
    assert s["count"] == 100
    assert s["max_ms"] == 100.0
    assert abs(s["mean_ms"] - 50.5) < 1e-9
    assert 49 <= s["p50_ms"] <= 51
    assert 94 <= s["p95_ms"] <= 96
    assert 98 <= s["p99_ms"] <= 100


def test_timer_stat_ring_keeps_recent_window():
    t = TimerStat(cap=8)
    for v in (1000.0,) * 8 + (1.0,) * 64:  # old outliers age out
        t.record(v)
    assert t.percentile(99) == 1.0
    assert t.max_ms == 1000.0  # exact max survives the ring
    assert t.count == 72


def test_percentile_takes_registry_lock_in_threadsafe_mode():
    """ISSUE 6 satellite: make_threadsafe() installs the registry lock
    onto every timer's percentile reads — existing AND later-created —
    so serving's cross-thread percentile reads can't sort a ring that
    a concurrent record is mutating (the lock-free path stays
    lock-free for the single-threaded train loop)."""
    import threading

    tele = Telemetry.memory("t")
    before = tele.timer("pre")          # created before the lock
    assert before._lock is None         # lock-free fast path
    tele.make_threadsafe()
    after = tele.timer("post")          # created after
    assert before._lock is tele._lock is not None
    assert after._lock is tele._lock
    # hammer record + percentile concurrently: with the lock this can
    # never raise or return junk outside the recorded range
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            after.record(float(i % 100))
            i += 1

    def reader():
        try:
            while not stop.is_set():
                p = after.percentile(99)
                assert p != p or 0.0 <= p <= 99.0
        except Exception as e:  # pragma: no cover - the failure path
            errors.append(e)

    threads = [threading.Thread(target=writer),
               threading.Thread(target=reader)]
    for t in threads:
        t.start()
    stop.wait(0.3)
    stop.set()
    for t in threads:
        t.join(5)
    assert not errors, errors


def test_disabled_is_shared_singleton_and_noop(tmp_path):
    a = Telemetry.create(None)
    assert a is Telemetry.disabled()
    assert not a.enabled
    a.count("c")
    a.gauge("g", 1.0)
    a.record_ms("t", 5.0)
    a.event("step", step=1)
    assert a.span("s").stop() == 0.0
    with a.timed("x"):
        pass
    a.close()
    assert a.counters == {} and a.timers == {}
    # the recorder's disabled path: wrap() is identity, enabled is the
    # single per-step check the loops guard on
    rec = TrainStepRecorder(a)
    infeed = [1, 2, 3]
    assert rec.wrap(infeed) is infeed
    assert rec.enabled is False


def test_memory_mode_records_without_sinks():
    tele = Telemetry.memory("serve")
    assert tele.enabled and not tele.sinks
    tele.record_ms("serve/request_ms", 7.0)
    tele.event("request", request_ms=7.0)  # no sink: must not raise
    assert tele.timer("serve/request_ms").count == 1
    tele.close()


def test_file_backed_run_manifest_and_events(tmp_path):
    cfg = Config(MAX_CONTEXTS=16, TRAIN_BATCH_SIZE=8)
    tele = Telemetry.create(str(tmp_path), config=cfg, component="unit")
    assert tele.enabled
    tele.event("step", step=1, step_ms=1.5, infeed_wait_ms=0.2,
               loss=2.25, examples=8)
    tele.record_ms("train/step_ms", 1.5)
    tele.gauge("device/bytes_in_use", 4096)
    tele.count("train/steps")
    tele.close()
    run_dir = tele.run_dir
    with open(os.path.join(run_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["run_id"] == tele.run_id
    assert manifest["component"] == "unit"
    assert "process_index" in manifest and "devices" in manifest
    assert manifest["config"]["MAX_CONTEXTS"] == 16
    events = _read_events(run_dir)
    kinds = [e["kind"] for e in events]
    assert "step" in kinds and "gauge" in kinds
    assert kinds[-1] == "summary"
    summary = events[-1]
    assert summary["timers"]["train/step_ms"]["count"] == 1
    assert summary["counters"]["train/steps"] == 1
    assert summary["gauges"]["device/bytes_in_use"] == 4096


def test_two_runs_same_process_get_distinct_run_ids(tmp_path):
    a = Telemetry.create(str(tmp_path), component="a")
    b = Telemetry.create(str(tmp_path), component="b")
    assert a.run_id != b.run_id
    a.close()
    b.close()


def test_span_sync_on_device_tree(tmp_path):
    import jax.numpy as jnp
    tele = Telemetry.memory("unit")
    sp = tele.span("dev_ms")
    out = jnp.ones((4, 4)) * 2.0
    ms = sp.stop(sync=out)  # device-sync-aware stop
    assert ms >= 0.0
    assert tele.timer("dev_ms").count == 1


# ---- train loop (acceptance: CPU smoke run) ----

@pytest.fixture(scope="module")
def tele_train(tmp_path_factory):
    """One tiny telemetry-enabled train run shared by the assertions
    below (the run itself is the expensive part)."""
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.helpers import build_tiny_dataset
    from tests.test_model import tiny_config

    d = str(tmp_path_factory.mktemp("tele_train"))
    prefix = build_tiny_dataset(d, n_train=96, n_val=16, n_test=16,
                                max_contexts=16)
    tdir = os.path.join(d, "tele")
    cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=2, TELEMETRY_DIR=tdir,
                      NUM_BATCHES_TO_LOG_PROGRESS=2)
    model = Code2VecModel(cfg)
    model.train()
    return tdir, model


def test_train_smoke_writes_manifest_and_step_events(tele_train):
    tdir, model = tele_train
    run_dir = _one_run_dir(tdir)
    with open(os.path.join(run_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["component"] == "train"
    assert manifest["config"]["MAX_CONTEXTS"] == 16
    assert manifest["devices"]["count"] >= 1
    events = _read_events(run_dir)
    steps = [e for e in events if e["kind"] == "step"]
    assert len(steps) == 6  # 96 examples / B=32 * 2 epochs
    for e in steps:
        assert {"step", "step_ms", "infeed_wait_ms", "loss",
                "examples"} <= set(e)
        assert e["step_ms"] >= 0 and e["infeed_wait_ms"] >= 0
    assert [e["step"] for e in steps] == list(range(1, 7))
    summary = events[-1]
    assert summary["kind"] == "summary"
    assert summary["timers"]["train/step_ms"]["count"] == 6
    assert summary["counters"]["train/examples"] == 192
    # train() publishes its run on the model (closed once train ends;
    # a subsequent serve phase opens its own run)
    assert model.telemetry.run_id == manifest["run_id"]
    assert model.telemetry.sinks == []  # closed


def test_report_tool_renders_baseline_table_shape(tele_train, capsys):
    tdir, _model = tele_train
    report = _load_report_tool()
    assert report.main([tdir]) == 0
    out = capsys.readouterr().out
    # the BASELINE.md shipped-table shape
    assert "| Config | ms/step | pc/s/chip | vs V100 (1.94M) " in out
    assert "bag bfloat16 B=32 C=16" in out
    # per-run detail: timer histogram table
    assert "| train/step_ms |" in out
    assert "| train/infeed_wait_ms |" in out
    assert "run-" in out  # run_id as the Source column


def test_report_tool_accepts_single_run_dir(tele_train, capsys):
    tdir, _model = tele_train
    report = _load_report_tool()
    assert report.main([_one_run_dir(tdir)]) == 0
    assert "| Config |" in capsys.readouterr().out


def test_report_tool_errors_on_empty_dir(tmp_path, capsys):
    report = _load_report_tool()
    assert report.main([str(tmp_path)]) == 2
    assert "no telemetry runs" in capsys.readouterr().err


def test_train_without_flag_is_disabled_and_writes_nothing(tmp_path):
    from code2vec_tpu.models.jax_model import Code2VecModel
    from tests.helpers import build_tiny_dataset
    from tests.test_model import tiny_config

    d = str(tmp_path / "ds")
    os.makedirs(d)
    prefix = build_tiny_dataset(d, n_train=64, n_val=8, n_test=8,
                                max_contexts=16)
    cfg = tiny_config(prefix, NUM_TRAIN_EPOCHS=1)
    assert cfg.TELEMETRY_DIR is None
    model = Code2VecModel(cfg)
    model.train()
    # the disabled singleton: no files, no registry growth
    assert model.telemetry is Telemetry.disabled()
    assert model.telemetry.timers == {}


# ---- serving latency (acceptance: p50/p95/p99 request line) ----

def _scripted_repl(tmp_path, monkeypatch, telemetry_dir=None):
    import numpy as np

    from code2vec_tpu.models.jax_model import PreparedRows
    from code2vec_tpu.serving.interactive_predict import (
        InteractivePredictor)

    class StubModel:
        """Just enough of the jax_model predict surface for the
        server's prepare -> device -> decode pipeline."""
        mesh = None

        def prepare_predict_rows(self, lines):
            n = len([ln for ln in lines if ln.strip()])
            z = np.zeros((n, 4), np.int32)
            return PreparedRows(np.zeros((n,), np.int32), z, z, z,
                                z.astype(np.float32), ["f"] * n,
                                [[] for _ in range(n)])

        def predict_device(self, prepared):
            n = prepared.n
            return (np.zeros((n, 1), np.int32),
                    np.zeros((n, 1), np.float32),
                    np.zeros((n, 4), np.float32),
                    np.zeros((n, 4), np.float32))

        def decode_predictions(self, prepared, device_out):
            from code2vec_tpu.common import MethodPredictionResults
            return [MethodPredictionResults(original_name=name)
                    for name in prepared.target_strings]

        def warmup_predict(self, max_batch):
            return []

        def predict_compile_count(self):
            return 0

    class StubPool:
        def extract_paths(self, path):
            return ("A", ["f a,1,b"])

        def close(self):
            pass

    cfg = Config(MAX_CONTEXTS=16)
    cfg.TELEMETRY_DIR = telemetry_dir
    input_file = str(tmp_path / "Input.java")
    with open(input_file, "w") as f:
        f.write("class A { int f() { return 1; } }\n")
    pred = InteractivePredictor(cfg, StubModel())
    monkeypatch.setattr(pred.server, "extractor_pool",
                        lambda **kw: StubPool())
    answers = iter(["", "", "q"])
    monkeypatch.setattr("builtins.input", lambda: next(answers))
    pred.predict(input_file=input_file)
    return pred


def test_serving_reports_latency_percentiles(tmp_path, monkeypatch,
                                             capsys):
    pred = _scripted_repl(tmp_path, monkeypatch)
    out = capsys.readouterr().out
    assert "latency: request" in out
    assert "p50" in out and "p95" in out and "p99" in out
    assert "over 2 requests" in out
    # no --telemetry_dir: memory-mode histograms, nothing persisted
    assert pred.telemetry.enabled and not pred.telemetry.sinks
    assert pred.telemetry.timer("serve/request_ms").count == 2
    assert pred.telemetry.timer("serve/extract_ms").count == 2


def test_serving_persists_request_events_with_flag(tmp_path,
                                                   monkeypatch, capsys):
    tdir = str(tmp_path / "tele")
    pred = _scripted_repl(tmp_path, monkeypatch, telemetry_dir=tdir)
    capsys.readouterr()
    run_dir = _one_run_dir(tdir)
    with open(os.path.join(run_dir, "manifest.json")) as f:
        assert json.load(f)["component"] == "serve"
    events = _read_events(run_dir)
    requests = [e for e in events if e["kind"] == "request"]
    assert len(requests) == 2
    assert all("request_ms" in e and "extract_ms" in e
               for e in requests)
    # REPL exit closed the run: summary carries the histograms
    assert events[-1]["kind"] == "summary"
    assert events[-1]["timers"]["serve/request_ms"]["count"] == 2
    assert pred.telemetry.run_dir == run_dir


def test_format_latency_line():
    t = TimerStat()
    for v in (5.0, 10.0, 20.0):
        t.record(v)
    line = format_latency_line(t, 20.0)
    assert line.startswith("latency: request 20.0 ms")
    assert "p50" in line and "p99" in line and "over 3 requests" in line


# ---- bench / profile emit the shared format ----

def test_bench_emits_telemetry_events(tmp_path, monkeypatch, capsys):
    import numpy as np

    import bench
    monkeypatch.setattr(bench, "TOKEN_VOCAB", 128)
    monkeypatch.setattr(bench, "PATH_VOCAB", 96)
    monkeypatch.setattr(bench, "TARGET_VOCAB", 64)
    monkeypatch.setattr(bench, "BATCH", 8)
    monkeypatch.setattr(bench, "MAX_CONTEXTS", 6)
    monkeypatch.setattr(bench, "NUM_SAMPLED", 16)
    monkeypatch.setattr(bench, "WARMUP_STEPS", 1)
    monkeypatch.setattr(bench, "MEASURE_STEPS", 2)
    monkeypatch.setattr(bench, "_measure_hbm_ceiling", lambda: 590e9)
    tdir = str(tmp_path / "tele")
    bench.main(["--telemetry_dir", tdir])
    out = capsys.readouterr().out.strip().splitlines()
    j = json.loads(out[-1])  # the JSON contract line is unchanged
    assert j["metric"] == "path-contexts/sec/chip"
    assert np.isfinite(j["value"])
    run_dir = _one_run_dir(tdir)
    events = _read_events(run_dir)
    bench_events = [e for e in events if e["kind"] == "bench"]
    assert len(bench_events) == 1
    assert bench_events[0]["value"] == j["value"]
    assert events[-1]["kind"] == "summary"
    assert events[-1]["gauges"]["bench/ms_per_step"] == j["ms_per_step"]


# ---- multi-process merge (--merge, ISSUE 6 satellite) ----

def _fake_process_run(root, idx, count, n_steps, step_ms, run_id):
    """One per-process run dir: manifest carrying process_index /
    process_count + step events (the shape Telemetry.create writes)."""
    d = os.path.join(root, run_id)
    os.makedirs(d)
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump({"run_id": run_id, "component": "train",
                   "process_index": idx, "process_count": count,
                   "config": {"MAX_CONTEXTS": 10, "ENCODER_TYPE": "bag",
                              "TABLES_DTYPE": "float32",
                              "TRAIN_BATCH_SIZE": 4}}, f)
    with open(os.path.join(d, "events.jsonl"), "w") as f:
        for s in range(n_steps):
            f.write(json.dumps({
                "kind": "step", "ts": 1000.0 + s, "step": s + 1,
                "step_ms": step_ms, "infeed_wait_ms": 0.0,
                "loss": 1.0, "examples": 4}) + "\n")
    return d


def test_report_merge_aggregates_per_process_runs(tmp_path, capsys):
    """`--merge <dir>...` folds one run dir per process into ONE
    multi-host table: pc/s summed across processes, step percentiles
    pooled, per-process rows kept for skew."""
    report = _load_report_tool()
    # two processes, process 1 is the 2x-slower straggler
    d0 = _fake_process_run(str(tmp_path), 0, 2, 10, 10.0, "run-p0")
    d1 = _fake_process_run(str(tmp_path), 1, 2, 10, 20.0, "run-p1")
    rc = report.main(["--merge", d0, d1])
    out = capsys.readouterr().out
    assert rc == 0
    assert "merged(2 runs)" in out
    assert "| 0/2 |" in out and "| 1/2 |" in out  # skew rows kept
    # summed throughput: p0 at 10ms/step does 400 ex/s * 10 ctx = 4000
    # pc/s, p1 half that -> merged 6,000 pc/s
    assert "6,000" in out, out
    # without --merge the same dirs render as separate headline rows
    rc = report.main([d0, d1])
    out2 = capsys.readouterr().out
    assert rc == 0 and "merged" not in out2
    assert out2.count("run-p") >= 2


def test_report_merge_warns_on_partial_run_set(tmp_path, capsys):
    report = _load_report_tool()
    d0 = _fake_process_run(str(tmp_path), 0, 4, 5, 10.0, "run-p0")
    rc = report.main(["--merge", d0])
    captured = capsys.readouterr()
    assert rc == 0
    assert "partial or mixed" in captured.out
