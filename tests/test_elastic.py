"""Elastic cohort recovery (ISSUE 13) — the tier-1 unit halves of the
slow kill_resize chaos scenario: checkpoint reshard round-trips across
topology change (row-sharded tables, optimizer state, int8 tables,
corrupt-file quarantine mid-reshard), the per-step save-time topology
record and the topology-independent resume arithmetic, and the
reader's global-permutation data order (same global stream under any
host count)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from code2vec_tpu.config import Config
from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.parallel.mesh import make_mesh
from code2vec_tpu.resilience import faults
from code2vec_tpu.training import checkpoint as ckpt
from code2vec_tpu.vocab.vocabularies import Code2VecVocabs, Vocab, \
    VocabType


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _tiny_vocabs():
    return Code2VecVocabs(Vocab(VocabType.Token, ["a", "b"]),
                          Vocab(VocabType.Path, ["1"]),
                          Vocab(VocabType.Target, ["t"]))


def _tiny_dims():
    return ModelDims(token_vocab_size=8, path_vocab_size=8,
                     target_vocab_size=8, embeddings_size=4,
                     max_contexts=4, dropout_keep_rate=1.0)


def _opt_like(table: np.ndarray) -> dict:
    """Adam-slot-shaped optimizer state over one table."""
    return {"mu": np.asarray(table) * 0.25,
            "nu": np.asarray(table) ** 2}


def _host_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, tree)


def _assert_trees_bit_equal(a, b):
    la = jax.tree_util.tree_flatten_with_path(a)[0]
    lb = jax.tree_util.tree_flatten_with_path(b)[0]
    assert len(la) == len(lb)
    for (ka, va), (kb, vb) in zip(la, lb):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb),
                                      err_msg=str(ka))


def _shard_rows(tree, mesh):
    """Row-shard every rank-2 leaf over the mesh's model axis (the
    vocab-table layout); everything else replicates."""
    def put(x):
        if not hasattr(x, "ndim"):
            return x
        spec = P("model", None) if getattr(x, "ndim", 0) == 2 else P()
        return jax.device_put(jnp.asarray(x),
                              NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, tree)


# ------------------------------------- reshard round-trips (tentpole)

def test_reshard_roundtrip_row_sharded_tables_and_opt_state(tmp_path):
    """Save at a 2-shard model axis, restore onto no mesh (N=1) and
    back onto a DIFFERENT (4-shard) mesh: params AND optimizer slots
    bit-equal to the unresharded tree in both directions — the
    checkpoint layer's cross-topology restore promise, exercised
    explicitly (the kill_resize chaos scenario rides exactly this)."""
    d = str(tmp_path)
    rng = np.random.default_rng(0)
    table = rng.standard_normal((8, 4)).astype(np.float32)
    state = {"params": {"token_emb": table},
             "opt_state": _opt_like(table), "step": 7}

    mesh2 = make_mesh(0, 2)
    sharded = dict(state)
    sharded["params"] = _shard_rows(state["params"], mesh2)
    sharded["opt_state"] = _shard_rows(state["opt_state"], mesh2)
    ckpt.save_checkpoint(d, sharded, 7, _tiny_vocabs(), _tiny_dims())

    # N=2 (row-sharded) -> N=1 (plain single-device template)
    flat = ckpt.load_checkpoint(d, state)
    _assert_trees_bit_equal(_host_tree(flat), state)

    # ... and back up onto a WIDER mesh (4-way row shards)
    mesh4 = make_mesh(0, 4)
    template = dict(state)
    template["params"] = _shard_rows(state["params"], mesh4)
    template["opt_state"] = _shard_rows(state["opt_state"], mesh4)
    wide = ckpt.load_checkpoint(d, template)
    shard_shapes = {
        s.data.shape
        for s in wide["params"]["token_emb"].addressable_shards}
    assert shard_shapes == {(2, 4)}  # genuinely redistributed
    _assert_trees_bit_equal(_host_tree(wide), state)


def test_reshard_roundtrip_int8_tables(tmp_path):
    """The quantized {q int8, s f32} table subtrees survive the same
    cross-mesh round-trip bit-exactly — requantization never happens
    on the restore path."""
    from code2vec_tpu.ops.quant import quantize_table
    d = str(tmp_path)
    rng = np.random.default_rng(1)
    qt = jax.tree_util.tree_map(
        np.asarray,
        quantize_table(jnp.asarray(
            rng.standard_normal((8, 4)).astype(np.float32))))
    state = {"params": {"token_emb": qt}, "step": 3}

    mesh = make_mesh(0, 2)
    sharded = {"params": {"token_emb": _shard_rows(qt, mesh)},
               "step": 3}
    ckpt.save_checkpoint(d, sharded, 3, _tiny_vocabs(), _tiny_dims())

    flat = ckpt.load_checkpoint(d, state)
    _assert_trees_bit_equal(_host_tree(flat), state)
    assert _host_tree(flat)["params"]["token_emb"]["q"].dtype \
        == np.int8

    mesh4 = make_mesh(0, 4)
    template = {"params": {"token_emb": _shard_rows(qt, mesh4)},
                "step": 3}
    wide = ckpt.load_checkpoint(d, template)
    _assert_trees_bit_equal(_host_tree(wide), state)


def test_corrupt_file_during_reshard_quarantines_and_falls_back(
        tmp_path):
    """PR-10 quarantine semantics hold ON the reshard path: a
    bit-flipped blob in the latest step is caught by the per-file
    checksums (they are resharding-proof by design), the step is
    quarantined, and the CROSS-TOPOLOGY restore falls back to the
    prior committed step."""
    d = str(tmp_path)
    rng = np.random.default_rng(2)
    t1 = rng.standard_normal((8, 4)).astype(np.float32)
    t2 = t1 + 1.0
    mesh = make_mesh(0, 2)
    for step, t in ((1, t1), (2, t2)):
        ckpt.save_checkpoint(
            d, {"params": {"w": _shard_rows({"w": t}, mesh)["w"]},
                "step": step}, step, _tiny_vocabs(), _tiny_dims())
    # flip a byte in step_2's largest state blob
    blobs = []
    for base, _dirs, files in os.walk(os.path.join(d, "step_2",
                                                   "state")):
        blobs += [os.path.join(base, f) for f in files]
    target = max(blobs, key=os.path.getsize)
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))

    # restore onto the NEW topology (no mesh): quarantine + fallback
    restored = ckpt.load_checkpoint(
        d, {"params": {"w": t1}, "step": 0})
    assert int(np.asarray(restored["step"])) == 1
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]), t1)
    assert os.path.isdir(os.path.join(d, "quarantine", "step_2"))


# -------------------------- save-time topology record + resume math

def test_step_topology_record_written_and_loaded(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, {"params": {"w": np.zeros((2, 2),
                                                      np.float32)},
                             "step": 4},
                         4, _tiny_vocabs(), _tiny_dims(),
                         topology={"epoch": 2})
    topo = ckpt.load_step_topology(d, 4)
    assert topo == {"step": 4, "num_processes": 1, "epoch": 2}
    # None-valued extras are dropped, num_processes always recorded
    ckpt.write_step_topology(d, 4, {"epoch": None})
    assert ckpt.load_step_topology(d, 4) == {"step": 4,
                                             "num_processes": 1}
    # pre-elastic step: no record, no crash
    assert ckpt.load_step_topology(d, 99) is None


def _resume_cfg(tmp_path, **kw):
    cfg = Config(TRAIN_BATCH_SIZE=32, NUM_TRAIN_EPOCHS=6,
                 AUTO_RESUME=True)
    cfg.train_data_path = "unused"
    cfg.load_path = str(tmp_path)
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def test_resume_epoch_offset_prefers_save_time_epoch(tmp_path):
    """The restored step's save-time record wins: a step count
    accumulated under a 2-process topology resumed by a 1-process
    cohort must NOT be divided by the 1-process steps-per-epoch (the
    arithmetic that would be wrong is never run)."""
    from code2vec_tpu.models.setup import resume_epoch_offset
    os.makedirs(tmp_path / "step_4")
    (tmp_path / "step_4" / ckpt.TOPOLOGY_NAME).write_text(json.dumps(
        {"step": 4, "num_processes": 2, "epoch": 2}))
    cfg = _resume_cfg(tmp_path)
    logs = []
    # 96 examples, B=32: spe(1 proc)=3 would give 4//3=1 — WRONG; the
    # record says the 2-proc run (spe=2) had finished epoch 2
    completed = resume_epoch_offset(cfg, 4, lambda: 96, logs.append)
    assert completed == 2
    assert any("save-time record" in m for m in logs)


def test_resume_epoch_offset_uses_saved_process_count(tmp_path):
    """No epoch field (a record written by a non-boundary save): the
    division runs under the SAVE-TIME process count, not the current
    one."""
    from code2vec_tpu.models.setup import resume_epoch_offset
    os.makedirs(tmp_path / "step_4")
    (tmp_path / "step_4" / ckpt.TOPOLOGY_NAME).write_text(json.dumps(
        {"step": 4, "num_processes": 2}))
    cfg = _resume_cfg(tmp_path)
    # spe at the SAVED 2-proc topology = ceil(ceil(96/2)/32) = 2
    assert resume_epoch_offset(cfg, 4, lambda: 96,
                               lambda _m: None) == 2


def test_resume_epoch_offset_pre_elastic_falls_back(tmp_path):
    """Pre-elastic checkpoint (no topology.json): PR-10 arithmetic
    under the current topology — exact for a never-resized history."""
    from code2vec_tpu.models.setup import resume_epoch_offset
    os.makedirs(tmp_path / "step_6")
    cfg = _resume_cfg(tmp_path)
    assert resume_epoch_offset(cfg, 6, lambda: 96,
                               lambda _m: None) == 2  # 6 // spe(1)=3


# -------------------------------- topology-independent data order

def test_global_data_order_is_topology_independent(tmp_path):
    """The per-epoch shuffle is ONE global permutation sliced per
    host: with equal GLOBAL batch, the union of examples each global
    step consumes is identical for a 1-host and a 2-host topology —
    the elastic parity bar's data-order half."""
    from code2vec_tpu.data.reader import open_reader
    from tests.helpers import build_tiny_dataset, load_tiny_vocabs
    prefix = build_tiny_dataset(str(tmp_path), n_train=48, n_val=8,
                                n_test=8, max_contexts=8)
    vocabs = load_tiny_vocabs(prefix)

    def step_multisets(num_hosts, per_host_batch):
        per_host = []
        for h in range(num_hosts):
            r = open_reader(prefix + ".train.c2v", vocabs, 8,
                            per_host_batch, shuffle=True, seed=5,
                            host_shard=h, num_host_shards=num_hosts)
            per_host.append([b.target_index[:b.num_valid_examples]
                             for b in r])
        steps = []
        for parts in zip(*per_host):
            steps.append(sorted(np.concatenate(parts).tolist()))
        return steps

    one = step_multisets(1, 16)   # global batch 16
    two = step_multisets(2, 8)    # global batch 2 x 8 = 16
    assert len(one) == 3
    assert one == two


def test_epoch_offset_replay_is_topology_independent(tmp_path):
    """A reader resumed at epoch k under a NEW host count replays the
    exact global stream an uninterrupted reader at that host count
    would produce for epoch k — shuffle state is (seed, epoch) alone,
    never topology history."""
    from code2vec_tpu.data.reader import open_reader
    from tests.helpers import build_tiny_dataset, load_tiny_vocabs
    prefix = build_tiny_dataset(str(tmp_path), n_train=48, n_val=8,
                                n_test=8, max_contexts=8)
    vocabs = load_tiny_vocabs(prefix)

    def epoch_batches(reader):
        return [b.target_index.copy() for b in reader]

    cold = open_reader(prefix + ".train.c2v", vocabs, 8, 16,
                       shuffle=True, seed=7)
    _first, second = epoch_batches(cold), epoch_batches(cold)
    resumed = open_reader(prefix + ".train.c2v", vocabs, 8, 16,
                          shuffle=True, seed=7, epoch_offset=1)
    for a, b in zip(second, epoch_batches(resumed)):
        np.testing.assert_array_equal(a, b)
