"""Stall watchdog (ISSUE 6): deadline detection under a FAKE clock (no
sleeps — tier-1 stays fast), the injected infeed-stall and
checkpoint-writer-hang scenarios, the diagnostic dump bundle, warn vs
raise modes, and the disabled path. CPU tier-1."""

import json
import os
import threading
import time

import pytest

from code2vec_tpu.obs import StallError, Telemetry, Tracer, Watchdog
from code2vec_tpu.obs.watchdog import _NULL_HEARTBEAT


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture()
def tele(tmp_path):
    t = Telemetry.create(str(tmp_path), component="wd").make_threadsafe()
    yield t
    t.close()


def _stall_events(run_dir):
    out = []
    with open(os.path.join(run_dir, "events.jsonl"),
              encoding="utf-8") as f:
        for line in f:
            if line.strip():
                e = json.loads(line)
                if e["kind"] == "stall":
                    out.append(e)
    return out


# ---------------------------------------------------------------------
# deadline mechanics (fake clock, synchronous check_now)
# ---------------------------------------------------------------------

def test_stall_fires_after_deadline_and_is_edge_triggered(tele):
    fc = FakeClock()
    wd = Watchdog(tele, stall_s=5.0, clock=fc)
    hb = wd.register("infeed_producer")
    assert wd.check_now() == []          # never beaten -> inactive
    hb.beat()
    fc.advance(4.9)
    assert wd.check_now() == []          # within deadline
    fc.advance(0.2)
    stalls = wd.check_now()
    assert [s["component"] for s in stalls] == ["infeed_producer"]
    assert stalls[0]["age_s"] > 5.0
    assert wd.check_now() == []          # same episode reported once
    # a beat BETWEEN two overdue checks still re-arms the episode
    hb.beat()
    fc.advance(6.0)
    assert wd.check_now(), "beat did not re-arm the stall episode"
    assert tele.counters["watchdog/stalls"] == 2


def test_idle_components_are_exempt(tele):
    fc = FakeClock()
    wd = Watchdog(tele, stall_s=1.0, clock=fc)
    hb = wd.register("checkpoint_writer")
    hb.busy()
    hb.idle()                            # job done, nothing in flight
    fc.advance(100.0)
    assert wd.check_now() == []
    hb.busy()                            # next job starts the clock
    fc.advance(1.5)
    assert wd.check_now()


def test_per_component_deadlines(tele):
    fc = FakeClock()
    wd = Watchdog(tele, stall_s=10.0, clock=fc)
    fast = wd.register("batcher_consumer", deadline_s=1.0)
    slow = wd.register("train_loop")     # default 10s
    fast.beat()
    slow.beat()
    fc.advance(2.0)
    assert [s["component"] for s in wd.check_now()] == \
        ["batcher_consumer"]


def test_stall_event_and_dump_bundle(tele, tmp_path):
    fc = FakeClock()
    tracer = Tracer.create(tele)
    wd = Watchdog(tele, stall_s=2.0, clock=fc, tracer=tracer)
    hb = wd.register("infeed_producer")
    hb.beat()
    live = tracer.start_trace("serve/request", n_methods=3)
    tele.gauge("serve/queue_depth", 7, emit=False)
    fc.advance(3.0)
    stalls = wd.check_now()
    assert stalls
    live.end()
    evs = _stall_events(tele.run_dir)
    assert evs and evs[0]["component"] == "infeed_producer"
    dump_path = evs[0]["dump"]
    assert dump_path and os.path.exists(dump_path)
    bundle = json.load(open(dump_path, encoding="utf-8"))
    # the bundle answers "what was in flight": live spans, every
    # thread's stack, component states, the registry snapshot
    assert bundle["stalls"][0]["component"] == "infeed_producer"
    assert [s["name"] for s in bundle["live_spans"]] == \
        ["serve/request"]
    assert bundle["threads"], "no thread stacks captured"
    assert any("test_stall_event_and_dump_bundle" in "".join(frames)
               for frames in bundle["threads"].values())
    assert bundle["telemetry"]["gauges"]["serve/queue_depth"] == 7
    assert bundle["components"]["infeed_producer"]["active"]


def test_raise_mode_sticky_at_beat_and_poll(tele):
    fc = FakeClock()
    wd = Watchdog(tele, stall_s=1.0, clock=fc, mode="raise")
    hb = wd.register("train_loop")
    hb.beat()
    fc.advance(2.0)
    assert wd.check_now()
    with pytest.raises(StallError):
        hb.beat()                        # sticky error lands here
    wd.poll()                            # cleared by the raise
    # warn mode never raises
    wd2 = Watchdog(tele, stall_s=1.0, clock=fc, mode="warn")
    hb2 = wd2.register("x")
    hb2.beat()
    fc.advance(2.0)
    assert wd2.check_now()
    hb2.beat()
    wd2.poll()
    wd2.stop()


def test_monitor_thread_runs_and_stops(tele):
    """Real clock, tiny deadline: the daemon monitor fires without an
    explicit check_now, and stop() joins it."""
    wd = Watchdog(tele, stall_s=0.05, check_interval_s=0.02)
    hb = wd.register("c")
    hb.beat()
    wd.start()
    deadline = time.monotonic() + 5.0
    while not tele.counters.get("watchdog/stalls") \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    wd.stop()
    assert tele.counters.get("watchdog/stalls", 0) >= 1


def test_disabled_watchdog_is_shared_noop():
    wd = Watchdog.disabled()
    assert wd is Watchdog.disabled() and not wd.enabled
    hb = wd.register("anything")
    assert hb is _NULL_HEARTBEAT
    hb.beat(); hb.busy(); hb.idle()
    assert wd.start() is wd
    wd.stop(); wd.poll()
    assert wd.check_now() == []
    # memory/disabled telemetry -> the disabled singleton via create()
    assert Watchdog.create(Telemetry.memory("m"), stall_s=5.0) is wd
    assert Watchdog.create(None, stall_s=5.0) is wd
    # stall_s=0 (the flag default) -> disabled too
    assert Watchdog.create(Telemetry.disabled(), stall_s=0.0) is wd


# ---------------------------------------------------------------------
# injected stalls through the REAL components
# ---------------------------------------------------------------------

def test_injected_infeed_stall_fires_watchdog(tele):
    """A producer wedged inside its parse/transfer function (put_fn
    hangs) stops beating -> stall; a producer merely blocked on a FULL
    queue keeps beating -> no stall (that indicts the consumer)."""
    from code2vec_tpu.data.prefetch import prefetch_to_device
    fc = FakeClock()
    wd = Watchdog(tele, stall_s=5.0, clock=fc)
    hb = wd.register("infeed_producer")
    wedge = threading.Event()
    produced = threading.Event()

    def put_fn(b):
        if b == 1:
            produced.set()
            wedge.wait(10)               # the injected stall
        return b

    infeed = prefetch_to_device([0, 1, 2], put_fn, depth=2)
    infeed._heartbeat = hb
    it = iter(infeed)
    assert next(it)[1] == 0
    assert produced.wait(5)              # producer entered the wedge
    fc.advance(6.0)
    stalls = wd.check_now()
    assert [s["component"] for s in stalls] == ["infeed_producer"]
    wedge.set()                          # release; drain cleanly
    assert [b for _, b in it] == [1, 2]
    fc.advance(6.0)
    assert wd.check_now() == [], \
        "finished producer must go idle, not stall"


def test_injected_writer_hang_fires_watchdog(tele, tmp_path):
    """An async checkpoint save hung in serialization (save_fn blocks)
    stops the writer's heartbeat -> stall with the writer thread's
    stack in the dump; an idle writer is exempt."""
    from code2vec_tpu.training.checkpoint import AsyncCheckpointWriter
    fc = FakeClock()
    wd = Watchdog(tele, stall_s=5.0, clock=fc)
    hb = wd.register("checkpoint_writer")
    hang = threading.Event()
    entered = threading.Event()

    def stuck_save(ckpt_dir, state, step, vocabs, dims, **kw):
        entered.set()
        hang.wait(10)

    writer = AsyncCheckpointWriter(save_fn=stuck_save, heartbeat=hb)
    writer.submit(str(tmp_path / "ckpt"), {"step": 1}, 1, None, None)
    assert entered.wait(5)
    fc.advance(6.0)
    stalls = wd.check_now()
    assert [s["component"] for s in stalls] == ["checkpoint_writer"]
    dump = json.load(open(_stall_events(tele.run_dir)[0]["dump"],
                          encoding="utf-8"))
    assert any("ckpt-writer" in label for label in dump["threads"])
    hang.set()
    writer.close()
    fc.advance(6.0)
    assert wd.check_now() == [], "idle writer must be exempt"
