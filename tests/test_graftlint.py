"""graftlint (ISSUE 4): the suite is tier-1 — the repo must lint clean
against its checked-in baseline, every rule must catch its fixture
true-positives and ignore its tricky false-positives, and the whole
thing must run fast (< 30 s) WITHOUT importing JAX or TensorFlow
(blocked-module subprocess proof, the test_obs_guard.py pattern — a
linter that drags in a backend couldn't gate commits on a CPU image).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from tools.graftlint import baseline as baseline_mod
from tools.graftlint.core import (DEFAULT_PATHS, REPO_ROOT, Finding,
                                  FileContext, all_rules, run_lint)
from tools.graftlint.rules.config_drift import check_config_drift
from tools.graftlint.rules.test_markers import (TestMarkerRule,
                                                registered_markers)

REPO = REPO_ROOT
FIXTURES = os.path.join(REPO, "tests", "graftlint_fixtures")

# every registered rule — extended by the ISSUE 12 dataflow trio; the
# no-baseline gate below runs ALL of them, so serving/obs/training/
# ops/parallel/resilience must come up clean under the new rules too
ALL_RULES = {"host-sync-in-hot-path", "retrace-hazard",
             "lock-discipline", "config-drift", "test-marker-hygiene",
             "swallowed-error", "donation-safety", "thread-handoff",
             "resource-leak"}


def _fx(name):
    return os.path.join(FIXTURES, name)


# ---- the repo itself must lint clean (the CI gate) ----

@pytest.fixture(scope="module")
def repo_scan():
    """ONE timed repo-wide scan shared by the gate tests (it dominates
    the suite's runtime; the assertions are independent views of it).
    -> (findings, elapsed_seconds)"""
    t0 = time.perf_counter()
    findings = run_lint(DEFAULT_PATHS, root=REPO)
    return findings, time.perf_counter() - t0


@pytest.fixture(scope="module")
def repo_findings(repo_scan):
    return repo_scan[0]


def test_all_nine_rules_registered():
    assert set(all_rules()) == ALL_RULES


def test_full_scan_performance(repo_scan):
    """Tier-1 guard (ISSUE 12 satellite): the full-repo scan with all
    9 rules must stay comfortably inside the pre-commit budget — the
    dataflow core's one-pass loop fixpoint is O(statements) per
    function, and this bound is how we notice if a rule change quietly
    goes quadratic. Generous: the scan measures ~2-4 s on a loaded CI
    core."""
    _findings, elapsed = repo_scan
    assert elapsed < 60.0, f"full graftlint scan took {elapsed:.1f}s"


def test_repo_lints_clean_against_baseline(repo_findings):
    entries = baseline_mod.load()
    new, old, stale = baseline_mod.split(repo_findings, entries)
    assert new == [], "\n".join(f.render() for f in new)
    assert stale == [], f"stale baseline entries (regenerate): {stale}"


def test_serving_and_obs_trees_are_finding_free(repo_findings):
    """ISSUE 4 acceptance (extended to training/ with the async
    checkpoint writer, ops/ with the fused sparse-update kernel):
    EMPTY baseline for the no-baseline trees — and not just
    baselined-away: zero findings at all."""
    dirty = [f for f in repo_findings
             if f.path.startswith(baseline_mod.NO_BASELINE_PREFIXES)]
    assert dirty == [], "\n".join(f.render() for f in dirty)
    assert not [e for e in baseline_mod.load()
                if e["path"].startswith(
                    baseline_mod.NO_BASELINE_PREFIXES)]


def test_slow_marker_registered():
    """Tier-1 deselects with -m 'not slow' (the guard the marker rule
    generalizes — keep the direct assertion too)."""
    assert "slow" in registered_markers(os.path.join(REPO, "pytest.ini"))


# ---- per-rule fixtures: true positives hit, tricky FPs don't ----

def _rule_findings(rule, paths):
    return run_lint(paths, root=REPO, rules=[rule])


def test_host_sync_fixtures():
    tp = _rule_findings("host-sync-in-hot-path", [_fx("host_sync_tp.py")])
    hits = {(f.symbol, f.line) for f in tp}
    assert len(tp) == 7, "\n".join(f.render() for f in tp)
    assert {s for s, _ in hits} == {"hot_step", "fetch_helper",
                                    "MicroBatcher._run",
                                    "loop_defined_step"}
    msgs = " ".join(f.message for f in tp)
    for needle in (".item()", "float()", "print", "block_until_ready",
                   "np.asarray", "device_get"):
        assert needle in msgs, needle
    # two-hop reachability: the asarray sits two calls below the root;
    # the root label lives in `detail`, OUTSIDE the baseline identity
    # (BFS order must not be able to invalidate baseline entries)
    two_hop = [f for f in tp if f.symbol == "fetch_helper"]
    assert two_hop and all("via hot_step" in f.detail
                           and "via" not in f.message for f in two_hop)
    fp = _rule_findings("host-sync-in-hot-path", [_fx("host_sync_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_retrace_fixtures():
    tp = _rule_findings("retrace-hazard", [_fx("retrace_tp.py")])
    msgs = [f.message for f in tp]
    for needle in ("inside a loop", "compiles on EVERY call",
                   "static_argnums must be a literal",
                   "static_argnames must be a literal",
                   "Python scalar literal", "dict literal",
                   "shape-derived branch"):
        assert any(needle in m for m in msgs), needle
    fp = _rule_findings("retrace-hazard", [_fx("retrace_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_lock_discipline_fixtures():
    tp = _rule_findings("lock-discipline", [_fx("lock_tp.py")])
    assert {f.symbol for f in tp} == {
        "RacyQueue._running", "RacyQueue._items", "RacyCond._depth",
        "RacyClassLock._size", "RacyUnpack._thread",
        "RacyUnpack._assembled"}
    assert all("(locked)" in f.message for f in tp)  # names both sites
    fp = _rule_findings("lock-discipline", [_fx("lock_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_config_drift_fixtures():
    tp_dir = os.path.join(FIXTURES, "config_drift_tp")
    tp = check_config_drift(os.path.join(tp_dir, "config.py"),
                            os.path.join(tp_dir, "README.md"))
    symbols = {f.symbol for f in tp}
    assert symbols == {"--dead_flag", "ns.phantom", "self.BTACH_SIZE",
                       "--undocumented", "--stale_flag", "ORPHAN_ATTR",
                       "WIRED_BUT_LISTED", "GHOST_CONSTANT"}, symbols
    fp_dir = os.path.join(FIXTURES, "config_drift_fp")
    fp = check_config_drift(os.path.join(fp_dir, "config.py"),
                            os.path.join(fp_dir, "README.md"))
    assert fp == [], "\n".join(f.render() for f in fp)


def test_swallowed_error_fixtures():
    tp = _rule_findings("swallowed-error", [_fx("swallowed_tp.py")])
    assert {f.symbol for f in tp} == {
        "classic_pass", "bound_but_unused", "bare_except_continue",
        "base_exception_pass", "broad_inside_tuple",
        "docstring_only_body", "closest"}
    assert all("swallows the error" in f.message for f in tp)
    fp = _rule_findings("swallowed-error", [_fx("swallowed_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_donation_safety_fixtures():
    """ISSUE 12 acceptance: a post-donation read of a make_train_step-
    style step's params must flag; the snapshot_state pattern (and the
    rebind idiom) must stay quiet."""
    tp = _rule_findings("donation-safety", [_fx("donation_tp.py")])
    assert {f.symbol for f in tp} == {
        "read_after_factory_step_donation", "return_of_donated",
        "aliased_container_read", "donate_argnames_read",
        "closure_capture_after_donation", "ModelWithStep.train_one"}
    msgs = " ".join(f.message for f in tp)
    assert "donated" in msgs and "snapshot_state" in msgs
    # the alias shape names the flow; the closure shape names capture
    assert any("through an alias" in f.message for f in tp)
    assert any("captured by a nested function" in f.message for f in tp)
    # the donation site is context, NOT baseline identity (line moves
    # must not resurrect entries)
    assert all("donated at line" in f.detail
               and "line" not in f.message for f in tp)
    fp = _rule_findings("donation-safety", [_fx("donation_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_thread_handoff_fixtures():
    tp = _rule_findings("thread-handoff", [_fx("handoff_tp.py")])
    assert {f.symbol for f in tp} == {
        "RacyBatcher.submit", "RacyBatcher.submit_batch",
        "thread_args_mutation", "executor_submit_mutation",
        "aug_extend_after_put", "SharedStore.publish",
        "raising_monitor"}
    # every escape vector is represented
    msgs = " ".join(f.message for f in tp)
    for needle in ("Thread(...)", ".put(...)", ".submit(...)",
                   "self._current = ..."):
        assert needle in msgs, needle
    # the monitor sub-check: never raise from the monitor thread
    monitor = [f for f in tp if f.symbol == "raising_monitor"]
    assert monitor and "monitor" in monitor[0].message \
        and "record the failure" in monitor[0].message
    fp = _rule_findings("thread-handoff", [_fx("handoff_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_resource_leak_fixtures():
    """ISSUE 12 acceptance: the PR-6 leaked-span shape must flag;
    try/finally, except-handler and context-manager releases must stay
    quiet."""
    tp = _rule_findings("resource-leak", [_fx("leak_tp.py")])
    syms = {f.symbol for f in tp}
    assert syms == {
        "leaked_span_on_error", "telemetry_span_error_window",
        "early_return_leaks", "thread_never_joined",
        "submit_without_barrier", "acquire_without_release"}
    msgs = " ".join(f.message for f in tp)
    assert "PR-6 leaked-span class" in msgs       # the error-path form
    assert "not released on every path" in msgs   # the exit-leak form
    # early_return_leaks exhibits BOTH hazards on one span
    assert len([f for f in tp
                if f.symbol == "early_return_leaks"]) == 2
    fp = _rule_findings("resource-leak", [_fx("leak_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_dataflow_sees_defs_in_match_and_async_with():
    """Regression (review): a def nested in a match-case arm or an
    async-with body is still a frame — a span leak there must flag."""
    import ast as ast_mod
    from tools.graftlint import dataflow as df
    src = (
        "async def outer(cm, mode, tracer, req):\n"
        "    match mode:\n"
        "        case 'a':\n"
        "            def in_match():\n"
        "                sp = tracer.start_span('x')\n"
        "                handle(req)\n"
        "                sp.end()\n"
        "    async with cm:\n"
        "        def in_async_with():\n"
        "            sp2 = tracer.start_span('y')\n"
        "            handle(req)\n"
        "            sp2.end()\n")
    names = {fn.name for fn, _cls in
             df.iter_functions(ast_mod.parse(src))}
    assert {"in_match", "in_async_with"} <= names


def test_marker_fixtures():
    rule = all_rules()["test-marker-hygiene"]
    tp = list(rule.check_ctx(FileContext(_fx("markers_tp.py"), REPO),
                             {"slow"}))
    assert {f.symbol for f in tp} == {
        "pytest.mark.slwo", "pytest.mark.sloow", "test_long_soak",
        "test_duration_cli"}
    fp = list(rule.check_ctx(FileContext(_fx("markers_fp.py"), REPO),
                             {"slow"}))
    assert fp == [], "\n".join(f.render() for f in fp)


# ---- suppressions and the baseline workflow ----

def test_inline_and_file_suppressions(tmp_path):
    bad = ("import jax\n\n\n"
           "@jax.jit\n"
           "def hot(x):\n"
           "    return x.item()\n")
    p = tmp_path / "mod.py"
    p.write_text(bad)
    assert len(run_lint([str(p)], root=str(tmp_path),
                        rules=["host-sync-in-hot-path"])) == 1
    p.write_text(bad.replace(
        "return x.item()",
        "return x.item()  # graftlint: disable=host-sync-in-hot-path"))
    assert run_lint([str(p)], root=str(tmp_path),
                    rules=["host-sync-in-hot-path"]) == []
    p.write_text("# graftlint: disable-file=all\n" + bad)
    assert run_lint([str(p)], root=str(tmp_path)) == []


def test_baseline_split_and_write(tmp_path):
    f1 = Finding("r", "a.py", 3, "m1", "s")
    f2 = Finding("r", "a.py", 9, "m2", "s")
    path = str(tmp_path / "base.json")
    baseline_mod.write([f1], path)
    new, old, stale = baseline_mod.split([f1, f2],
                                         baseline_mod.load(path))
    assert (new, old, stale) == ([f2], [f1], [])
    # line moves don't resurrect a grandfathered finding
    moved = Finding("r", "a.py", 300, "m1", "s")
    new, old, _ = baseline_mod.split([moved], baseline_mod.load(path))
    assert new == [] and old == [moved]
    # a fixed finding reports its entry as stale
    _, _, stale = baseline_mod.split([], baseline_mod.load(path))
    assert len(stale) == 1
    # a SECOND instance of a baselined finding is NEW (duplicate-aware)
    new, old, _ = baseline_mod.split([f1, moved],
                                     baseline_mod.load(path))
    assert len(new) == 1 and len(old) == 1


def test_baseline_refuses_serving_and_obs(tmp_path):
    path = str(tmp_path / "base.json")
    bad = Finding("lock-discipline", "code2vec_tpu/serving/batcher.py",
                  1, "m", "s")
    bad_training = Finding("lock-discipline",
                           "code2vec_tpu/training/checkpoint.py",
                           1, "m", "s")
    bad_ops = Finding("host-sync-in-hot-path",
                      "code2vec_tpu/ops/pallas_sparse_update.py",
                      1, "m", "s")
    bad_parallel = Finding("host-sync-in-hot-path",
                           "code2vec_tpu/parallel/distributed.py",
                           1, "m", "s")
    bad_resilience = Finding("swallowed-error",
                             "code2vec_tpu/resilience/retry.py",
                             1, "m", "s")
    ok = Finding("retrace-hazard", "tools/x.py", 1, "m", "s")
    refused = baseline_mod.write(
        [bad, bad_training, bad_ops, bad_parallel, bad_resilience, ok],
        path)
    assert refused == [bad, bad_training, bad_ops, bad_parallel,
                       bad_resilience]
    assert [e["path"] for e in baseline_mod.load(path)] == ["tools/x.py"]


def test_no_baseline_prefixes_cover_parallel():
    """ISSUE 9: the distribution layer is fenced — fetch_global is a
    sanctioned seam (rules/host_sync._SANCTIONED), not a suppression
    or a baseline entry."""
    assert "code2vec_tpu/parallel/" in baseline_mod.NO_BASELINE_PREFIXES
    from tools.graftlint.rules.host_sync import _SANCTIONED
    assert ("", "fetch_global") in _SANCTIONED


# ---- CLI: platform-free, fast, machine-readable ----

def test_cli_runs_clean_without_jax_or_tf(tmp_path):
    """The pre-commit gate (`python -m tools.graftlint`) must exit 0 on
    the current tree with BOTH jax and tensorflow import-blocked: the
    AST walk may not touch either (tier-1 runs on bare CPU images, and
    the < 30 s budget leaves no room for a backend init)."""
    blocker = tmp_path / "block"
    blocker.mkdir()
    for mod in ("jax", "tensorflow"):
        (blocker / f"{mod}.py").write_text(
            f"raise ImportError('{mod} blocked by test_graftlint')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(blocker), REPO] + ([env["PYTHONPATH"]]
                                if env.get("PYTHONPATH") else []))
    r = subprocess.run([sys.executable, "-m", "tools.graftlint"],
                       cwd=REPO, env=env, capture_output=True,
                       text=True, timeout=30)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 findings" in r.stdout
    # ALL nine rules ran under the import block — the dataflow core
    # (ISSUE 12) must hold parse-never-import like everything else
    assert f"rules: {len(ALL_RULES)})" in r.stdout


def test_cli_json_format_and_rule_selection(capsys):
    from tools.graftlint.__main__ import main
    rc = main(["--format", "json", "--rules", "config-drift",
               "code2vec_tpu"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == []
    assert main(["--rules", "no-such-rule"]) == 2
    capsys.readouterr()


def test_cli_guards_partial_baseline_and_bad_paths(tmp_path, capsys):
    from tools.graftlint.__main__ import main
    # a partial-scope --write-baseline would silently drop every
    # out-of-scope grandfathered entry — refused outright
    assert main(["--write-baseline", "--rules", "config-drift"]) == 2
    assert main(["--write-baseline", "tools"]) == 2
    # a typo'd path scanning zero files must not report "clean"
    assert main(["serving"]) == 2
    capsys.readouterr()


def test_changed_py_files_tracks_git(tmp_path):
    """--changed's file list (ISSUE 12 satellite): worktree diff +
    untracked, scan-set-scoped, fixture dirs excluded, deletions
    dropped."""
    from tools.graftlint.__main__ import changed_py_files
    repo = str(tmp_path / "r")
    os.makedirs(os.path.join(repo, "tools", "graftlint_fixtures"))
    os.makedirs(os.path.join(repo, "docs"))

    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t",
                        "-c", "user.name=t", *args], cwd=repo,
                       check=True, capture_output=True)

    def write(rel, text="x = 1\n"):
        with open(os.path.join(repo, rel), "w") as f:
            f.write(text)

    git("init", "-q")
    write("tools/clean.py")
    write("tools/gone.py")
    git("add", "-A")
    git("commit", "-qm", "seed")
    assert changed_py_files(repo) == []
    write("tools/clean.py", "x = 2\n")          # modified
    write("tools/fresh.py")                      # untracked
    write("tools/graftlint_fixtures/tp.py")      # excluded dir
    write("docs/outside.py")                     # outside the scan set
    write("tools/notes.txt")                     # not .py
    os.remove(os.path.join(repo, "tools", "gone.py"))  # deleted
    assert changed_py_files(repo) == ["tools/clean.py",
                                      "tools/fresh.py"]


def test_cli_changed_mode_gates_a_diff(tmp_path, capsys):
    """`--changed` end-to-end on a HERMETIC tmp git repo (linting the
    developer's live worktree here would fail on THEIR in-flight
    changes): a clean modified file passes, a planted finding fails,
    and the flag refuses path arguments / --write-baseline
    combinations that would silently narrow the gate."""
    from tools.graftlint.__main__ import main
    repo = str(tmp_path / "r")
    os.makedirs(os.path.join(repo, "tools"))

    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t",
                        "-c", "user.name=t", *args], cwd=repo,
                       check=True, capture_output=True)

    def write(rel, text):
        with open(os.path.join(repo, rel), "w") as f:
            f.write(text)

    git("init", "-q")
    write("tools/mod.py", "x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    write("tools/mod.py", "y = 2\n")
    assert main(["--changed", "--root", repo]) == 0
    write("tools/mod.py",
          "def f():\n"
          "    try:\n"
          "        g()\n"
          "    except Exception:\n"
          "        pass\n")
    assert main(["--changed", "--root", repo]) == 1
    out = capsys.readouterr().out
    assert "swallowed-error" in out
    assert main(["--changed", "tools"]) == 2
    assert main(["--changed", "--write-baseline"]) == 2
    capsys.readouterr()


def test_cli_scoped_scans_do_not_spam_stale_entries(capsys):
    """A rule- or path-scoped scan must neither fail on out-of-scope
    grandfathered findings nor misreport their entries as stale."""
    from tools.graftlint.__main__ import main
    for argv in (["--rules", "lock-discipline"], ["tools"]):
        rc = main(argv)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "stale" not in out, out
