"""graftlint (ISSUE 4; interprocedural since ISSUE 14): the suite is
tier-1 — the repo must lint clean against its checked-in baseline,
every rule must catch its fixture true-positives and ignore its tricky
false-positives, and the whole two-pass scan (per-file rules + the
call-summary fixpoint) must run fast (< 60 s) WITHOUT importing JAX or
TensorFlow
(blocked-module subprocess proof, the test_obs_guard.py pattern — a
linter that drags in a backend couldn't gate commits on a CPU image).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from tools.graftlint import baseline as baseline_mod
from tools.graftlint.core import (DEFAULT_PATHS, REPO_ROOT, Finding,
                                  FileContext, all_rules, run_lint)
from tools.graftlint.rules.config_drift import check_config_drift
from tools.graftlint.rules.test_markers import (TestMarkerRule,
                                                registered_markers)

REPO = REPO_ROOT
FIXTURES = os.path.join(REPO, "tests", "graftlint_fixtures")

# every registered rule — extended by the ISSUE 12 dataflow trio and
# the ISSUE 14 interprocedural pair; the no-baseline gate below runs
# ALL of them, so serving/obs/training/ops/parallel/resilience must
# come up clean under the new rules too
ALL_RULES = {"host-sync-in-hot-path", "retrace-hazard",
             "lock-discipline", "config-drift", "test-marker-hygiene",
             "swallowed-error", "donation-safety", "thread-handoff",
             "resource-leak", "spmd-divergence", "nondeterminism"}


def _fx(name):
    return os.path.join(FIXTURES, name)


# ---- the repo itself must lint clean (the CI gate) ----

@pytest.fixture(scope="module")
def repo_scan():
    """ONE timed repo-wide scan shared by the gate tests (it dominates
    the suite's runtime; the assertions are independent views of it).
    -> (findings, elapsed_seconds)"""
    t0 = time.perf_counter()
    findings = run_lint(DEFAULT_PATHS, root=REPO)
    return findings, time.perf_counter() - t0


@pytest.fixture(scope="module")
def repo_findings(repo_scan):
    return repo_scan[0]


def test_all_eleven_rules_registered():
    assert set(all_rules()) == ALL_RULES
    assert len(ALL_RULES) == 11


def test_full_scan_performance(repo_scan):
    """Tier-1 guard (ISSUE 12 satellite, re-baselined for the ISSUE 14
    TWO-PASS scan): the full-repo scan with all 11 rules — including
    the summary pass + call-graph fixpoint — must stay comfortably
    inside the pre-commit budget; this bound is how we notice if a
    rule change (or the fixpoint) quietly goes quadratic. Generous:
    the two-pass scan measures ~8-10 s on a loaded CI core."""
    _findings, elapsed = repo_scan
    assert elapsed < 60.0, f"full graftlint scan took {elapsed:.1f}s"


def test_repo_lints_clean_against_baseline(repo_findings):
    entries = baseline_mod.load()
    new, old, stale = baseline_mod.split(repo_findings, entries)
    assert new == [], "\n".join(f.render() for f in new)
    assert stale == [], f"stale baseline entries (regenerate): {stale}"


def test_serving_and_obs_trees_are_finding_free(repo_findings):
    """ISSUE 4 acceptance (extended to training/ with the async
    checkpoint writer, ops/ with the fused sparse-update kernel):
    EMPTY baseline for the no-baseline trees — and not just
    baselined-away: zero findings at all."""
    dirty = [f for f in repo_findings
             if f.path.startswith(baseline_mod.NO_BASELINE_PREFIXES)]
    assert dirty == [], "\n".join(f.render() for f in dirty)
    assert not [e for e in baseline_mod.load()
                if e["path"].startswith(
                    baseline_mod.NO_BASELINE_PREFIXES)]


def test_slow_marker_registered():
    """Tier-1 deselects with -m 'not slow' (the guard the marker rule
    generalizes — keep the direct assertion too)."""
    assert "slow" in registered_markers(os.path.join(REPO, "pytest.ini"))


# ---- per-rule fixtures: true positives hit, tricky FPs don't ----

def _rule_findings(rule, paths):
    return run_lint(paths, root=REPO, rules=[rule])


def test_host_sync_fixtures():
    tp = _rule_findings("host-sync-in-hot-path", [_fx("host_sync_tp.py")])
    hits = {(f.symbol, f.line) for f in tp}
    assert len(tp) == 7, "\n".join(f.render() for f in tp)
    assert {s for s, _ in hits} == {"hot_step", "fetch_helper",
                                    "MicroBatcher._run",
                                    "loop_defined_step"}
    msgs = " ".join(f.message for f in tp)
    for needle in (".item()", "float()", "print", "block_until_ready",
                   "np.asarray", "device_get"):
        assert needle in msgs, needle
    # two-hop reachability: the asarray sits two calls below the root;
    # the root label lives in `detail`, OUTSIDE the baseline identity
    # (BFS order must not be able to invalidate baseline entries)
    two_hop = [f for f in tp if f.symbol == "fetch_helper"]
    assert two_hop and all("via hot_step" in f.detail
                           and "via" not in f.message for f in two_hop)
    fp = _rule_findings("host-sync-in-hot-path", [_fx("host_sync_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_retrace_fixtures():
    tp = _rule_findings("retrace-hazard", [_fx("retrace_tp.py")])
    msgs = [f.message for f in tp]
    for needle in ("inside a loop", "compiles on EVERY call",
                   "static_argnums must be a literal",
                   "static_argnames must be a literal",
                   "Python scalar literal", "dict literal",
                   "shape-derived branch"):
        assert any(needle in m for m in msgs), needle
    fp = _rule_findings("retrace-hazard", [_fx("retrace_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_lock_discipline_fixtures():
    tp = _rule_findings("lock-discipline", [_fx("lock_tp.py")])
    assert {f.symbol for f in tp} == {
        "RacyQueue._running", "RacyQueue._items", "RacyCond._depth",
        "RacyClassLock._size", "RacyUnpack._thread",
        "RacyUnpack._assembled"}
    assert all("(locked)" in f.message for f in tp)  # names both sites
    fp = _rule_findings("lock-discipline", [_fx("lock_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_config_drift_fixtures():
    tp_dir = os.path.join(FIXTURES, "config_drift_tp")
    tp = check_config_drift(os.path.join(tp_dir, "config.py"),
                            os.path.join(tp_dir, "README.md"))
    symbols = {f.symbol for f in tp}
    assert symbols == {"--dead_flag", "ns.phantom", "self.BTACH_SIZE",
                       "--undocumented", "--stale_flag", "ORPHAN_ATTR",
                       "WIRED_BUT_LISTED", "GHOST_CONSTANT"}, symbols
    fp_dir = os.path.join(FIXTURES, "config_drift_fp")
    fp = check_config_drift(os.path.join(fp_dir, "config.py"),
                            os.path.join(fp_dir, "README.md"))
    assert fp == [], "\n".join(f.render() for f in fp)


def test_swallowed_error_fixtures():
    tp = _rule_findings("swallowed-error", [_fx("swallowed_tp.py")])
    assert {f.symbol for f in tp} == {
        "classic_pass", "bound_but_unused", "bare_except_continue",
        "base_exception_pass", "broad_inside_tuple",
        "docstring_only_body", "closest"}
    assert all("swallows the error" in f.message for f in tp)
    fp = _rule_findings("swallowed-error", [_fx("swallowed_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_donation_safety_fixtures():
    """ISSUE 12 acceptance: a post-donation read of a make_train_step-
    style step's params must flag; the snapshot_state pattern (and the
    rebind idiom) must stay quiet."""
    tp = _rule_findings("donation-safety", [_fx("donation_tp.py")])
    assert {f.symbol for f in tp} == {
        "read_after_factory_step_donation", "return_of_donated",
        "aliased_container_read", "donate_argnames_read",
        "closure_capture_after_donation", "ModelWithStep.train_one"}
    msgs = " ".join(f.message for f in tp)
    assert "donated" in msgs and "snapshot_state" in msgs
    # the alias shape names the flow; the closure shape names capture
    assert any("through an alias" in f.message for f in tp)
    assert any("captured by a nested function" in f.message for f in tp)
    # the donation site is context, NOT baseline identity (line moves
    # must not resurrect entries)
    assert all("donated at line" in f.detail
               and "line" not in f.message for f in tp)
    fp = _rule_findings("donation-safety", [_fx("donation_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_thread_handoff_fixtures():
    tp = _rule_findings("thread-handoff", [_fx("handoff_tp.py")])
    assert {f.symbol for f in tp} == {
        "RacyBatcher.submit", "RacyBatcher.submit_batch",
        "thread_args_mutation", "executor_submit_mutation",
        "aug_extend_after_put", "SharedStore.publish",
        "raising_monitor"}
    # every escape vector is represented
    msgs = " ".join(f.message for f in tp)
    for needle in ("Thread(...)", ".put(...)", ".submit(...)",
                   "self._current = ..."):
        assert needle in msgs, needle
    # the monitor sub-check: never raise from the monitor thread
    monitor = [f for f in tp if f.symbol == "raising_monitor"]
    assert monitor and "monitor" in monitor[0].message \
        and "record the failure" in monitor[0].message
    fp = _rule_findings("thread-handoff", [_fx("handoff_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_resource_leak_fixtures():
    """ISSUE 12 acceptance: the PR-6 leaked-span shape must flag;
    try/finally, except-handler and context-manager releases must stay
    quiet."""
    tp = _rule_findings("resource-leak", [_fx("leak_tp.py")])
    syms = {f.symbol for f in tp}
    assert syms == {
        "leaked_span_on_error", "telemetry_span_error_window",
        "early_return_leaks", "thread_never_joined",
        "submit_without_barrier", "acquire_without_release"}
    msgs = " ".join(f.message for f in tp)
    assert "PR-6 leaked-span class" in msgs       # the error-path form
    assert "not released on every path" in msgs   # the exit-leak form
    # early_return_leaks exhibits BOTH hazards on one span
    assert len([f for f in tp
                if f.symbol == "early_return_leaks"]) == 2
    fp = _rule_findings("resource-leak", [_fx("leak_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_spmd_divergence_fixtures():
    """ISSUE 14 acceptance: every collective-under-divergent-control
    shape flags (direct, assigned-rank, early exit, exception handler,
    IfExp arm, writer submit, per-host loop, and the summary-hop
    reaches); the uniform/audited shapes stay quiet."""
    tp = _rule_findings("spmd-divergence", [_fx("spmd_tp.py")])
    assert {f.symbol for f in tp} == {
        "branch_on_process_index", "branch_on_assigned_rank",
        "divergent_early_exit", "collective_in_exception_handler",
        "interprocedural_reach", "divergent_test_via_summary",
        "ternary_collective", "RankedSaver.maybe_submit",
        "loop_over_local_devices"}
    msgs = " ".join(f.message for f in tp)
    assert "cohort deadlocks" in msgs
    for needle in ("collective `psum`", "shard_map",
                   "exception handler", "early exit",
                   "async checkpoint writer"):
        assert needle in msgs, needle
    # the divergent-site line/via chain is context, NOT baseline
    # identity (line moves must not resurrect entries)
    assert all("divergent control:" in f.detail for f in tp)
    # the one-hop reach names the callee the effect came through
    via = [f for f in tp if f.symbol == "interprocedural_reach"]
    assert via and "inherited via _sync_helper" in via[0].detail
    fp = _rule_findings("spmd-divergence", [_fx("spmd_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


def test_nondeterminism_fixtures():
    """ISSUE 14 acceptance: wall-clock/global-rng/fs-order/set-order/
    id() into rng seams, tensors, seed kwargs and checkpointed state
    all flag; the sanctioned seams (step-keyed fold_in, seeded
    instance streams, sorted listings, set membership, telemetry
    timestamps, per-host row tags, dither_from_index) stay quiet."""
    tp = _rule_findings("nondeterminism", [_fx("nondet_tp.py")])
    assert {f.symbol for f in tp} == {
        "clock_seeded_key", "clock_fold_in", "global_rng_tensor",
        "set_order_tensor", "listing_order_rows",
        "glob_into_checkpoint", "loop_var_into_checkpoint",
        "seed_kwarg_from_clock", "interprocedural_source",
        "object_identity_seed"}
    msgs = " ".join(f.message for f in tp)
    for needle in ("wall clock", "global random stream",
                   "set iteration order", "filesystem listing order",
                   "rng seam", "tensor construction",
                   "checkpointed state", "resume-parity"):
        assert needle in msgs, needle
    # the source site rides in `detail` (outside baseline identity);
    # the one-hop source names the returning callee
    assert all("source:" in f.detail for f in tp)
    hop = [f for f in tp if f.symbol == "interprocedural_source"]
    assert hop and "returned by `_wall_clock_stamp`" in hop[0].detail
    fp = _rule_findings("nondeterminism", [_fx("nondet_fp.py")])
    assert fp == [], "\n".join(f.render() for f in fp)


# ---- the summary layer itself (ISSUE 14 satellite) ----

def test_nested_helper_keeps_hot_path_reach(tmp_path):
    """Review round: excluding nested defs from GLOBAL resolution must
    not cost the lexical reach — a host sync in a helper nested inside
    a jitted step still flags (nested defs resolve through the
    enclosing frame's scope chain), while a nested def can no longer
    shadow a same-named module-level def repo-wide."""
    p = tmp_path / "hot.py"
    p.write_text(
        "import jax\n"
        "import numpy as np\n\n\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    def fetch(v):\n"
        "        return float(np.asarray(v))\n"
        "    return fetch(x)\n")
    fs = run_lint([str(p)], root=str(tmp_path),
                  rules=["host-sync-in-hot-path"])
    assert {f.symbol for f in fs} == {"fetch"}, \
        "\n".join(f.render() for f in fs)


def test_summaries_two_hop_reach():
    """A hazard TWO resolved calls below the divergent/sinking site
    fires only through the propagated summaries — nothing
    intraprocedural can see it."""
    spmd = _rule_findings("spmd-divergence",
                          [_fx("summaries_twohop_tp.py")])
    assert {f.symbol for f in spmd} == {"divergent_two_hops_up"}
    assert "inherited via _middle" in spmd[0].detail
    nondet = _rule_findings("nondeterminism",
                            [_fx("summaries_twohop_tp.py")])
    assert {f.symbol for f in nondet} == {"seeded_two_hops_up"}
    assert "returned by `_stamp`" in nondet[0].detail


def test_summaries_terminate_on_cycles():
    """Recursion and mutual call cycles must converge (facts are
    monotone finite sets): summaries come back, clean cycles stay
    empty, and an effect inside a cycle propagates to every member —
    while the uniform caller produces no finding."""
    from tools.graftlint.core import Scan

    ctx = FileContext(_fx("summaries_cycle_fp.py"), REPO)
    scan = Scan([ctx], REPO)
    sums = {s.qualname: s for s in scan.summaries.values()}
    assert sums["clean_self_recursive"].collective == {}
    assert sums["ping"].collective == {} and sums["pong"].nondet == {}
    for member in ("cyc_a", "cyc_b", "uniform_cycle_user"):
        assert any("psum" in lbl for lbl in sums[member].collective), \
            member
    for rule in ("spmd-divergence", "nondeterminism"):
        fs = _rule_findings(rule, [_fx("summaries_cycle_fp.py")])
        assert fs == [], "\n".join(f.render() for f in fs)


def test_summaries_record_escaping_and_donated_params():
    """The ISSUE 14 summary spec: params that escape (thread/queue/
    attribute/closure) and params the body donates are recorded, and
    donation propagates so donation-safety sees through wrappers."""
    import textwrap

    from tools.graftlint.core import Scan

    src = textwrap.dedent("""\
        import jax, threading, queue

        step = jax.jit(lambda p, o: (p, o), donate_argnums=(0, 1))

        def wrapper(params, opt, batch):
            return step(params, opt)

        def two_hop_wrapper(params, opt, batch):
            return wrapper(params, opt, batch)

        def escapes(params, q, store):
            q.put(params)
            store.latest = params
            def closure():
                return params
            return closure

        def caller(params, opt, batch, save):
            new_p, new_o = two_hop_wrapper(params, opt, batch)
            save(params)  # read-after-donation, two wrappers deep
            return new_p, new_o
    """)
    path = os.path.join(REPO, "tests", "graftlint_fixtures")
    tmp = os.path.join(path, "_summary_params_tmp.py")
    with open(tmp, "w") as f:
        f.write(src)
    try:
        ctx = FileContext(tmp, REPO)
        scan = Scan([ctx], REPO)
        sums = {s.qualname: s for s in scan.summaries.values()}
        assert sums["wrapper"].donated_params == {0: "params", 1: "opt"}
        assert sums["two_hop_wrapper"].donated_params == {
            0: "params", 1: "opt"}
        assert sums["escapes"].escaping_params == {"params"}
        dn = run_lint([tmp], root=REPO, rules=["donation-safety"])
        assert [f.symbol for f in dn] == ["caller"], \
            "\n".join(f.render() for f in dn)
        assert "`params` is read after being donated" in dn[0].message
    finally:
        os.remove(tmp)


def test_dataflow_sees_defs_in_match_and_async_with():
    """Regression (review): a def nested in a match-case arm or an
    async-with body is still a frame — a span leak there must flag."""
    import ast as ast_mod
    from tools.graftlint import dataflow as df
    src = (
        "async def outer(cm, mode, tracer, req):\n"
        "    match mode:\n"
        "        case 'a':\n"
        "            def in_match():\n"
        "                sp = tracer.start_span('x')\n"
        "                handle(req)\n"
        "                sp.end()\n"
        "    async with cm:\n"
        "        def in_async_with():\n"
        "            sp2 = tracer.start_span('y')\n"
        "            handle(req)\n"
        "            sp2.end()\n")
    names = {fn.name for fn, _cls in
             df.iter_functions(ast_mod.parse(src))}
    assert {"in_match", "in_async_with"} <= names


def test_marker_fixtures():
    rule = all_rules()["test-marker-hygiene"]
    tp = list(rule.check_ctx(FileContext(_fx("markers_tp.py"), REPO),
                             {"slow"}))
    assert {f.symbol for f in tp} == {
        "pytest.mark.slwo", "pytest.mark.sloow", "test_long_soak",
        "test_duration_cli"}
    fp = list(rule.check_ctx(FileContext(_fx("markers_fp.py"), REPO),
                             {"slow"}))
    assert fp == [], "\n".join(f.render() for f in fp)


# ---- suppressions and the baseline workflow ----

def test_inline_and_file_suppressions(tmp_path):
    bad = ("import jax\n\n\n"
           "@jax.jit\n"
           "def hot(x):\n"
           "    return x.item()\n")
    p = tmp_path / "mod.py"
    p.write_text(bad)
    assert len(run_lint([str(p)], root=str(tmp_path),
                        rules=["host-sync-in-hot-path"])) == 1
    p.write_text(bad.replace(
        "return x.item()",
        "return x.item()  # graftlint: disable=host-sync-in-hot-path"))
    assert run_lint([str(p)], root=str(tmp_path),
                    rules=["host-sync-in-hot-path"]) == []
    p.write_text("# graftlint: disable-file=all\n" + bad)
    assert run_lint([str(p)], root=str(tmp_path)) == []


def test_baseline_split_and_write(tmp_path):
    f1 = Finding("r", "a.py", 3, "m1", "s")
    f2 = Finding("r", "a.py", 9, "m2", "s")
    path = str(tmp_path / "base.json")
    baseline_mod.write([f1], path)
    new, old, stale = baseline_mod.split([f1, f2],
                                         baseline_mod.load(path))
    assert (new, old, stale) == ([f2], [f1], [])
    # line moves don't resurrect a grandfathered finding
    moved = Finding("r", "a.py", 300, "m1", "s")
    new, old, _ = baseline_mod.split([moved], baseline_mod.load(path))
    assert new == [] and old == [moved]
    # a fixed finding reports its entry as stale
    _, _, stale = baseline_mod.split([], baseline_mod.load(path))
    assert len(stale) == 1
    # a SECOND instance of a baselined finding is NEW (duplicate-aware)
    new, old, _ = baseline_mod.split([f1, moved],
                                     baseline_mod.load(path))
    assert len(new) == 1 and len(old) == 1


def test_baseline_refuses_serving_and_obs(tmp_path):
    path = str(tmp_path / "base.json")
    bad = Finding("lock-discipline", "code2vec_tpu/serving/batcher.py",
                  1, "m", "s")
    bad_training = Finding("lock-discipline",
                           "code2vec_tpu/training/checkpoint.py",
                           1, "m", "s")
    bad_ops = Finding("host-sync-in-hot-path",
                      "code2vec_tpu/ops/pallas_sparse_update.py",
                      1, "m", "s")
    bad_parallel = Finding("host-sync-in-hot-path",
                           "code2vec_tpu/parallel/distributed.py",
                           1, "m", "s")
    bad_resilience = Finding("swallowed-error",
                             "code2vec_tpu/resilience/retry.py",
                             1, "m", "s")
    # ISSUE 14 satellite: the new interprocedural rules are refused
    # entries under training/, parallel/ and resilience/ from day one —
    # a divergent collective or a nondeterministic parity leak in
    # those trees is a bug to fix, never debt to grandfather
    bad_spmd = Finding("spmd-divergence",
                       "code2vec_tpu/training/checkpoint.py", 1, "m", "s")
    bad_spmd_par = Finding("spmd-divergence",
                           "code2vec_tpu/parallel/distributed.py",
                           1, "m", "s")
    bad_nondet = Finding("nondeterminism",
                         "code2vec_tpu/resilience/faults.py", 1, "m", "s")
    bad_nondet_tr = Finding("nondeterminism",
                            "code2vec_tpu/training/sparse_update.py",
                            1, "m", "s")
    # ISSUE 15 satellite: the phase-attribution plane joins the obs/
    # fence from day one — a finding in the module whose whole job is
    # honest measurement is a bug to fix, never debt to grandfather
    bad_phases = Finding("host-sync-in-hot-path",
                         "code2vec_tpu/obs/phases.py", 1, "m", "s")
    bad_probes = Finding("retrace-hazard",
                         "code2vec_tpu/training/phase_probes.py",
                         1, "m", "s")
    # ISSUE 17 satellite: the fleet plane joins the obs/ fence from
    # day one — a leak or swallowed error in the cohort collector
    # (the thing that watches everyone else) is a bug to fix, never
    # debt to grandfather
    bad_fleet = Finding("resource-leak",
                        "code2vec_tpu/obs/fleet.py", 1, "m", "s")
    # ISSUE 18 satellite: the external serving plane lands inside the
    # fenced serving/ tree — the front-end, replica pool, reload
    # watcher and autoscaler answer live traffic, so a lock slip or a
    # leaked thread there is a bug to fix, never debt to grandfather
    bad_frontend = Finding("thread-handoff",
                           "code2vec_tpu/serving/frontend.py",
                           1, "m", "s")
    bad_replicas = Finding("lock-discipline",
                           "code2vec_tpu/serving/replicas.py",
                           1, "m", "s")
    bad_reload = Finding("resource-leak",
                         "code2vec_tpu/serving/reload.py", 1, "m", "s")
    bad_scaler = Finding("nondeterminism",
                         "code2vec_tpu/serving/autoscale.py",
                         1, "m", "s")
    ok = Finding("retrace-hazard", "tools/x.py", 1, "m", "s")
    refused = baseline_mod.write(
        [bad, bad_training, bad_ops, bad_parallel, bad_resilience,
         bad_spmd, bad_spmd_par, bad_nondet, bad_nondet_tr,
         bad_phases, bad_probes, bad_fleet, bad_frontend,
         bad_replicas, bad_reload, bad_scaler, ok],
        path)
    assert refused == [bad, bad_training, bad_ops, bad_parallel,
                       bad_resilience, bad_spmd, bad_spmd_par,
                       bad_nondet, bad_nondet_tr, bad_phases,
                       bad_probes, bad_fleet, bad_frontend,
                       bad_replicas, bad_reload, bad_scaler]
    assert [e["path"] for e in baseline_mod.load(path)] == ["tools/x.py"]


def test_no_baseline_prefixes_cover_parallel():
    """ISSUE 9: the distribution layer is fenced — fetch_global is a
    sanctioned seam (rules/host_sync._SANCTIONED), not a suppression
    or a baseline entry."""
    assert "code2vec_tpu/parallel/" in baseline_mod.NO_BASELINE_PREFIXES
    from tools.graftlint.rules.host_sync import _SANCTIONED
    assert ("", "fetch_global") in _SANCTIONED


# ---- CLI: platform-free, fast, machine-readable ----

def test_cli_runs_clean_without_jax_or_tf(tmp_path):
    """The pre-commit gate (`python -m tools.graftlint`) must exit 0 on
    the current tree with BOTH jax and tensorflow import-blocked: the
    AST walk may not touch either (tier-1 runs on bare CPU images, and
    the scan-perf budget leaves no room for a backend init). The
    timeout tracks the two-pass (ISSUE 14) scan-perf guard's bound."""
    blocker = tmp_path / "block"
    blocker.mkdir()
    for mod in ("jax", "tensorflow"):
        (blocker / f"{mod}.py").write_text(
            f"raise ImportError('{mod} blocked by test_graftlint')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(blocker), REPO] + ([env["PYTHONPATH"]]
                                if env.get("PYTHONPATH") else []))
    r = subprocess.run([sys.executable, "-m", "tools.graftlint"],
                       cwd=REPO, env=env, capture_output=True,
                       text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 findings" in r.stdout
    # ALL eleven rules ran under the import block — the dataflow core
    # (ISSUE 12) and the two-pass summary layer (ISSUE 14) must hold
    # parse-never-import like everything else
    assert f"rules: {len(ALL_RULES)})" in r.stdout


def test_cli_sarif_format(tmp_path, capsys):
    """ISSUE 14 satellite: `--format sarif` emits valid SARIF 2.1.0 —
    all 11 rules in the driver table, one result per NEW finding with
    rule id + uri + startLine — while text/json stay untouched. Exit
    semantics match json (1 on findings)."""
    from tools.graftlint.__main__ import main

    rc = main(["--format", "sarif", "--rules", "config-drift",
               "code2vec_tpu"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == ALL_RULES
    assert run["results"] == []
    # a planted finding renders as a SARIF result
    p = tmp_path / "bad.py"
    p.write_text("def f():\n"
                 "    try:\n"
                 "        g()\n"
                 "    except Exception:\n"
                 "        pass\n")
    rc = main(["--format", "sarif", "--root", str(tmp_path),
               "--baseline", str(tmp_path / "none.json"), str(p)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    res = doc["runs"][0]["results"]
    assert len(res) == 1 and res[0]["ruleId"] == "swallowed-error"
    loc = res[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "bad.py"
    assert loc["region"]["startLine"] == 4


def test_cli_json_format_and_rule_selection(capsys):
    from tools.graftlint.__main__ import main
    rc = main(["--format", "json", "--rules", "config-drift",
               "code2vec_tpu"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == []
    assert main(["--rules", "no-such-rule"]) == 2
    capsys.readouterr()


def test_cli_guards_partial_baseline_and_bad_paths(tmp_path, capsys):
    from tools.graftlint.__main__ import main
    # a partial-scope --write-baseline would silently drop every
    # out-of-scope grandfathered entry — refused outright
    assert main(["--write-baseline", "--rules", "config-drift"]) == 2
    assert main(["--write-baseline", "tools"]) == 2
    # a typo'd path scanning zero files must not report "clean"
    assert main(["serving"]) == 2
    capsys.readouterr()


def test_changed_py_files_tracks_git(tmp_path):
    """--changed's file list (ISSUE 12 satellite): worktree diff +
    untracked, scan-set-scoped, fixture dirs excluded, deletions
    dropped."""
    from tools.graftlint.__main__ import changed_py_files
    repo = str(tmp_path / "r")
    os.makedirs(os.path.join(repo, "tools", "graftlint_fixtures"))
    os.makedirs(os.path.join(repo, "docs"))

    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t",
                        "-c", "user.name=t", *args], cwd=repo,
                       check=True, capture_output=True)

    def write(rel, text="x = 1\n"):
        with open(os.path.join(repo, rel), "w") as f:
            f.write(text)

    git("init", "-q")
    write("tools/clean.py")
    write("tools/gone.py")
    git("add", "-A")
    git("commit", "-qm", "seed")
    assert changed_py_files(repo) == []
    write("tools/clean.py", "x = 2\n")          # modified
    write("tools/fresh.py")                      # untracked
    write("tools/graftlint_fixtures/tp.py")      # excluded dir
    write("docs/outside.py")                     # outside the scan set
    write("tools/notes.txt")                     # not .py
    os.remove(os.path.join(repo, "tools", "gone.py"))  # deleted
    assert changed_py_files(repo) == ["tools/clean.py",
                                      "tools/fresh.py"]


def test_cli_changed_mode_gates_a_diff(tmp_path, capsys):
    """`--changed` end-to-end on a HERMETIC tmp git repo (linting the
    developer's live worktree here would fail on THEIR in-flight
    changes): a clean modified file passes, a planted finding fails,
    and the flag refuses path arguments / --write-baseline
    combinations that would silently narrow the gate."""
    from tools.graftlint.__main__ import main
    repo = str(tmp_path / "r")
    os.makedirs(os.path.join(repo, "tools"))

    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t",
                        "-c", "user.name=t", *args], cwd=repo,
                       check=True, capture_output=True)

    def write(rel, text):
        with open(os.path.join(repo, rel), "w") as f:
            f.write(text)

    git("init", "-q")
    write("tools/mod.py", "x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    write("tools/mod.py", "y = 2\n")
    assert main(["--changed", "--root", repo]) == 0
    write("tools/mod.py",
          "def f():\n"
          "    try:\n"
          "        g()\n"
          "    except Exception:\n"
          "        pass\n")
    assert main(["--changed", "--root", repo]) == 1
    out = capsys.readouterr().out
    assert "swallowed-error" in out
    assert main(["--changed", "tools"]) == 2
    assert main(["--changed", "--write-baseline"]) == 2
    capsys.readouterr()


def test_cli_changed_mode_is_summary_aware(tmp_path, capsys):
    """ISSUE 14 satellite, both directions of the one-hop blast
    radius: (a) a changed CALLEE body can change a CALLER's findings
    one hop up, so the gate re-lints the callers' files; (b) a changed
    CALL SITE can only be judged with its callee's summary present, so
    the gate pulls the callees' files into the scan set too — editing
    ONLY the caller of an unchanged collective helper must still flag
    the new divergent call (review round: the gate used to pass what
    the full scan then failed on)."""
    from tools.graftlint.__main__ import main, summary_scope
    repo = str(tmp_path / "r")
    os.makedirs(os.path.join(repo, "tools"))

    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t",
                        "-c", "user.name=t", *args], cwd=repo,
                       check=True, capture_output=True)

    def write(rel, text):
        with open(os.path.join(repo, rel), "w") as f:
            f.write(text)

    git("init", "-q")
    write("tools/callee.py", "def helper(x):\n    return x\n")
    write("tools/caller.py",
          "from tools.callee import helper\n\n\n"
          "def top(x):\n"
          "    try:\n"
          "        return helper(x)\n"
          "    except Exception:\n"
          "        pass\n")
    write("tools/unrelated.py", "def lonely():\n    return 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    # (a) only the callee changes; the planted finding is in caller.py
    write("tools/callee.py", "def helper(x):\n    return x + 1\n")
    assert summary_scope(repo, ["tools/callee.py"])[0] == [
        "tools/caller.py"]
    rc = main(["--changed", "--root", repo])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "caller/callee file" in out
    assert "tools/caller.py" in out and "swallowed-error" in out
    assert "unrelated" not in out  # one hop, not the whole repo
    git("add", "-A")
    git("commit", "-qm", "callee change")
    # (b) only the CALLER changes: a new process_index() branch around
    # the unchanged collective helper — resolvable only because the
    # gate pulls sync.py into the scan set
    write("tools/sync.py",
          "import jax\n\n\n"
          "def sync_helper(x):\n"
          "    return jax.lax.psum(x, 'data')\n")
    git("add", "-A")
    git("commit", "-qm", "fix finding; add helper")
    write("tools/caller.py",
          "import jax\n\nfrom tools.sync import sync_helper\n\n\n"
          "def top(x):\n"
          "    if jax.process_index() == 0:\n"
          "        return sync_helper(x)\n"
          "    return x\n")
    assert summary_scope(repo, ["tools/caller.py"])[0] == [
        "tools/sync.py"]
    rc = main(["--changed", "--root", repo])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "spmd-divergence" in out and "tools/caller.py" in out
    # per-file-rules-only runs skip the expansion (the fast path the
    # gate exists to preserve) — and therefore don't flag
    rc = main(["--changed", "--root", repo,
               "--rules", "swallowed-error"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "caller/callee" not in out
    # (b') TRANSITIVE closure (review round 3): A calls B calls C;
    # change only the LEAF C to grow the collective — the divergent
    # call in UNCHANGED A is indicted through two summary hops, so the
    # gate must pull both B's and A's files
    git("add", "-A")
    git("commit", "-qm", "divergent caller")
    write("tools/leaf.py", "def leaf(x):\n    return x\n")
    write("tools/mid.py",
          "from tools.leaf import leaf\n\n\n"
          "def middle(x):\n"
          "    return leaf(x)\n")
    write("tools/caller.py",
          "import jax\n\nfrom tools.mid import middle\n\n\n"
          "def top(x):\n"
          "    if jax.process_index() == 0:\n"
          "        return middle(x)\n"
          "    return x\n")
    os.remove(os.path.join(repo, "tools", "sync.py"))
    git("add", "-A")
    git("commit", "-qm", "clean chain")
    write("tools/leaf.py",
          "import jax\n\n\n"
          "def leaf(x):\n"
          "    return jax.lax.psum(x, 'data')\n")
    extra, _amb = summary_scope(repo, ["tools/leaf.py"])
    assert set(extra) >= {"tools/mid.py", "tools/caller.py"}
    rc = main(["--changed", "--root", repo])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "spmd-divergence" in out and "tools/caller.py" in out

    # (c) subset-resolution bias (review round): a SECOND sync_helper
    # makes the name ambiguous repo-wide — the full scan refuses to
    # resolve it, and the --changed subset (which only sees one
    # definition) must refuse too instead of emitting a phantom
    # finding tier-1 never shows
    write("tools/sync.py",
          "import jax\n\n\n"
          "def sync_helper(x):\n"
          "    return jax.lax.psum(x, 'data')\n")
    write("tools/caller.py",
          "import jax\n\nfrom tools.sync import sync_helper\n\n\n"
          "def top(x):\n"
          "    if jax.process_index() == 0:\n"
          "        return sync_helper(x)\n"
          "    return x\n")
    write("tools/leaf.py", "def leaf(x):\n    return x\n")
    write("tools/sync2.py",
          "def sync_helper(x):\n    return x\n")
    git("add", "-A")
    git("commit", "-qm", "second helper: name now ambiguous")
    write("tools/caller.py",
          "import jax\n\nfrom tools.sync import sync_helper\n\n\n"
          "def top(x):\n"
          "    if jax.process_index() == 0:\n"
          "        return sync_helper(x)\n"
          "    return x + 0\n")
    _, ambiguous = summary_scope(repo, ["tools/caller.py"])
    assert "sync_helper" in ambiguous
    rc = main(["--changed", "--root", repo])
    out = capsys.readouterr().out
    assert rc == 0, out  # matches the full scan's under-reach verdict
    assert "spmd-divergence" not in out


def test_cli_scoped_path_scans_use_the_ambiguity_fence(tmp_path,
                                                       capsys):
    """Review round 3: a path-scoped scan (`graftlint tools/sub`) is a
    subset scan too — a name defined twice repo-wide must not
    uniqueness-resolve just because the second definition's file sits
    outside the given paths (the full scan refuses, so the scoped scan
    must refuse too, or it emits phantom findings tier-1 never shows
    and the baseline can never grandfather)."""
    from tools.graftlint.__main__ import main
    repo = str(tmp_path / "r")
    os.makedirs(os.path.join(repo, "tools", "sub"))

    def write(rel, text):
        with open(os.path.join(repo, rel), "w") as f:
            f.write(text)

    write("tools/sub/helper.py",
          "import jax\n\n\n"
          "def sync_helper(x):\n"
          "    return jax.lax.psum(x, 'data')\n")
    write("tools/other.py", "def sync_helper(x):\n    return x\n")
    write("tools/sub/a.py",
          "import jax\n\nfrom tools.sub.helper import sync_helper\n\n\n"
          "def top(x):\n"
          "    if jax.process_index() == 0:\n"
          "        return sync_helper(x)\n"
          "    return x\n")
    # control: with BOTH definitions in the scan set the name is
    # natively ambiguous and nothing flags
    assert main(["--root", repo, "tools"]) == 0
    capsys.readouterr()
    rc = main(["--root", repo, "tools/sub"])    # scoped: fenced
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "spmd-divergence" not in out


def test_cli_scoped_scans_do_not_spam_stale_entries(capsys):
    """A rule- or path-scoped scan must neither fail on out-of-scope
    grandfathered findings nor misreport their entries as stale."""
    from tools.graftlint.__main__ import main
    for argv in (["--rules", "lock-discipline"], ["tools"]):
        rc = main(argv)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "stale" not in out, out
