"""Corpus generator invariants (tools/gen_java_corpus.py): determinism
across runs (the quality study's bit-identical-rebuild claim) and the
--tail_names regime's additions."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GEN = os.path.join(REPO, "tools", "gen_java_corpus.py")


def _gen(out, *extra):
    subprocess.run(
        [sys.executable, GEN, "--out", out, "--names", "50",
         "--methods", "200", "--seed", "3", *extra],
        check=True, capture_output=True, text=True, timeout=120)


def _slurp(root):
    out = {}
    for dirpath, _, files in os.walk(root):
        for f in sorted(files):
            p = os.path.join(dirpath, f)
            with open(p, encoding="utf-8") as fh:
                out[os.path.relpath(p, root)] = fh.read()
    return out


def test_generator_is_deterministic(tmp_path):
    _gen(str(tmp_path / "a"))
    _gen(str(tmp_path / "b"))
    assert _slurp(tmp_path / "a") == _slurp(tmp_path / "b")


def test_tail_mode_adds_distractors_and_keeps_default_stream(tmp_path):
    _gen(str(tmp_path / "plain"))
    _gen(str(tmp_path / "tail"), "--tail_names", "100")
    _gen(str(tmp_path / "tail2"), "--tail_names", "100")
    plain = "".join(_slurp(tmp_path / "plain").values())
    tail = "".join(_slurp(tmp_path / "tail").values())
    # tail mode is itself deterministic
    assert _slurp(tmp_path / "tail") == _slurp(tmp_path / "tail2")
    # the redundant cue and junk locals only exist in tail mode
    assert "Copy = " in tail and "Copy = " not in plain
    # default mode is byte-identical to the pre-flag generator (its rng
    # stream must not shift): spot-check that plain has no tail syllable
    # compounds while tail does
    assert any(s in tail for s in ("tmpBuf", "bufAcc", "locRef",
                                   "idxPtr", "accCur", "curAux"))


def test_tail_mode_emits_no_unreachable_statements(tmp_path):
    """Tail-mode insertions must land BEFORE a method's trailing return
    (javac rejects statements after it); scan every generated body."""
    _gen(str(tmp_path / "t"), "--tail_names", "100")
    for dirpath, _, files in os.walk(tmp_path / "t"):
        for fn in files:
            with open(os.path.join(dirpath, fn)) as f:
                lines = [ln.strip() for ln in f]
            for i, ln in enumerate(lines[:-1]):
                if ln.startswith("return"):
                    nxt = lines[i + 1]
                    assert nxt in ("}", "") or nxt.startswith("}"), \
                        f"{fn}: statement after {ln!r}: {nxt!r}"
