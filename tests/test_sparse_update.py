"""Fused sparse table-update tests (training/sparse_update.py +
ops/pallas_sparse_update.py, round 13).

Covers: the dedup + segment-sum + scatter-back property against the
dense-carrier oracle (bit-for-bit in f32, including heavy-duplicate /
all-same / all-unique extremes), interpret-mode fused-vs-reference
parity (bit-exact on f32/bf16 tables; q-exact on int8 under the shared
dither salt), the dispatch + config resolution, the mesh path's
(mesh_sparse_apply, round 14) bit-exact agreement with BOTH the
single-device compact apply and the dense-carrier reference on the
8-device virtual mesh, a fused-path train smoke through
make_train_step's sparse dispatch, the analytic traffic model, and the
vm head's rows_from_dense — all on the CPU interpreter (tier-1).

Both paths are compared UNDER JIT (the production context — the train
step jits the whole update): eager XLA contracts multiply-adds
differently than the compiled graph, so eager-vs-jit comparisons
differ in the last ulp while jit-vs-jit is bit-exact.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from code2vec_tpu.models.encoder import ModelDims, init_params
from code2vec_tpu.ops.quant import dequantize_table, quantize_table
from code2vec_tpu.training import sparse_update as su
from code2vec_tpu.training.sparse_adam import (RowAdamState,
                                               init_row_adam,
                                               row_adam_update)
from code2vec_tpu.training.sparse_steps import (init_sparse_opt_state,
                                                make_sparse_train_step)
from code2vec_tpu.training.steps import make_train_step


def _ids_cases(V, N, seed=0):
    """Random id multisets incl. the extremes the property demands."""
    r = np.random.default_rng(seed)
    return {
        "heavy_dup": r.integers(0, max(V // 4, 1), N).astype(np.int32),
        "uniform": r.integers(0, V, N).astype(np.int32),
        "all_same": np.full(N, V - 1, np.int32),
        "all_unique": r.permutation(V)[:min(N, V)].astype(np.int32),
    }


@pytest.mark.parametrize("case", ["heavy_dup", "uniform", "all_same",
                                  "all_unique"])
def test_dedup_segment_sum_matches_dense_carrier_bitwise(case):
    """The compact segment sums must equal the dense [V, E] carrier's
    scatter-add gathered at the unique ids BIT-FOR-BIT in f32: both
    scatters apply the same updates array in the same per-index order,
    so accumulation order per duplicate group is identical."""
    V, E, N = 64, 8, 256
    ids = jnp.asarray(_ids_cases(V, N)[case])
    n = ids.shape[0]
    g = jnp.asarray(np.random.default_rng(1).normal(size=(n, E)),
                    jnp.float32)

    @jax.jit
    def both(ids, g):
        dense = jnp.zeros((V, E), jnp.float32).at[ids].add(g)
        uids, seg = su.dedup_segment_sum(ids, g, V, block_rows=32)
        return dense, uids, seg

    dense, uids, seg = both(ids, g)
    uids, seg, dense = (np.asarray(uids), np.asarray(seg),
                        np.asarray(dense))
    live = uids < V
    assert live.sum() == len(set(np.asarray(ids).tolist()))
    np.testing.assert_array_equal(seg[live], dense[uids[live]])
    # padded slots carry no gradient
    np.testing.assert_array_equal(seg[~live], 0.0)


def test_scatter_back_equals_dense_carrier_path_f32():
    """Full property (ISSUE 8): dedup + segment-sum + live-row apply +
    scatter-back == the dense-carrier scatter-add path bit-for-bit in
    f32 — row_adam_update IS the dense-carrier form, kept as the
    oracle."""
    V, E, N = 48, 8, 192
    r = np.random.default_rng(2)
    oracle = jax.jit(functools.partial(row_adam_update, lr=0.01))
    compact = jax.jit(functools.partial(
        su.sparse_row_adam, lr=0.01, fused=False, block_rows=16))
    for case, ids_np in _ids_cases(V, N, seed=3).items():
        table = jnp.asarray(r.normal(size=(V, E)), jnp.float32)
        state = init_row_adam(table)
        ids = jnp.asarray(ids_np)
        g = jnp.asarray(r.normal(size=(ids.shape[0], E)), jnp.float32)
        count = jnp.asarray(5, jnp.int32)

        t_ref, s_ref = oracle(table, state, ids, g, count=count)
        t_new, s_new = compact(table, state, ids, g, count=count)
        np.testing.assert_array_equal(np.asarray(t_ref),
                                      np.asarray(t_new), err_msg=case)
        np.testing.assert_array_equal(np.asarray(s_ref.m),
                                      np.asarray(s_new.m), err_msg=case)
        np.testing.assert_array_equal(np.asarray(s_ref.v),
                                      np.asarray(s_new.v), err_msg=case)


# shapes cover: multi-block, non-multiple-of-block id counts, a
# single-block table, E > lane width, and a 1-row table
@pytest.mark.parametrize("V,E,N", [(64, 8, 100), (40, 16, 37),
                                   (300, 128, 513), (5, 8, 160),
                                   (1, 256, 7)])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_matches_reference(V, E, N, dtype):
    """The kernel IS the reference restructured around per-row DMA:
    same shared row math -> bit-exact tables AND moments."""
    r = np.random.default_rng(V + N)
    table = jnp.asarray(r.normal(size=(V, E)) * 0.3).astype(dtype)
    state = RowAdamState(
        m=jnp.asarray(r.normal(size=(V, E)) * 0.01, jnp.float32),
        v=jnp.asarray(np.abs(r.normal(size=(V, E))) * 1e-3,
                      jnp.float32))
    ids = jnp.asarray(r.integers(0, V, N), jnp.int32)
    g = jnp.asarray(r.normal(size=(N, E)) * 0.1).astype(dtype)
    count = jnp.asarray(3, jnp.int32)

    def run(fused):
        # one-shot compile IS the test  # graftlint: disable=retrace-hazard
        return jax.jit(functools.partial(
            su.sparse_row_adam, lr=0.01, fused=fused, block_rows=32))(
            table, state, ids, g, count=count)

    (t_ref, s_ref), (t_fus, s_fus) = run(False), run(True)
    np.testing.assert_array_equal(
        np.asarray(t_ref, np.float32), np.asarray(t_fus, np.float32))
    np.testing.assert_array_equal(np.asarray(s_ref.m),
                                  np.asarray(s_fus.m))
    np.testing.assert_array_equal(np.asarray(s_ref.v),
                                  np.asarray(s_fus.v))


@pytest.mark.parametrize("V,E,N", [(64, 8, 100), (40, 16, 37),
                                   (300, 128, 513)])
def test_fused_matches_reference_int8(V, E, N):
    """int8 {q, s} live-row requantize-aware update: q bit-exact under
    the shared dither salt (the ISSUE's q-parity contract); s to <= 2
    ulp (float-contraction ordering, same bound as pallas_requant)."""
    r = np.random.default_rng(V + N)
    qt = quantize_table(jnp.asarray(r.normal(size=(V, E)) * 0.3,
                                    jnp.float32))
    state = init_row_adam(qt)
    ids = jnp.asarray(r.integers(0, V, N), jnp.int32)
    g = jnp.asarray(r.normal(size=(N, E)) * 0.1, jnp.float32)
    count = jnp.asarray(2, jnp.int32)
    rng = jax.random.PRNGKey(9)

    def run(fused):
        # one-shot compile IS the test  # graftlint: disable=retrace-hazard
        return jax.jit(functools.partial(
            su.sparse_requant_adam, lr=0.01, fused=fused,
            block_rows=32))(qt, state, ids, g, rng, count=count)

    (q_ref, s_ref), (q_fus, s_fus) = run(False), run(True)
    np.testing.assert_array_equal(np.asarray(q_ref["q"]),
                                  np.asarray(q_fus["q"]))
    ulp = np.abs(np.asarray(q_ref["s"]).ravel().view(np.int32)
                 - np.asarray(q_fus["s"]).ravel().view(np.int32))
    assert ulp.max() <= 2, ulp.max()
    np.testing.assert_array_equal(np.asarray(s_ref.m),
                                  np.asarray(s_fus.m))
    np.testing.assert_array_equal(np.asarray(s_ref.v),
                                  np.asarray(s_fus.v))


def test_int8_untouched_rows_stable_and_touched_rows_move():
    """A live-row pass must leave untouched q/s rows BIT-identical (the
    dense requantize pass re-rounds every row; this path does not
    touch them at all) and move touched rows by the applied update."""
    V, E = 64, 8
    r = np.random.default_rng(4)
    qt = quantize_table(jnp.asarray(r.normal(size=(V, E)) * 0.5,
                                    jnp.float32))
    state = init_row_adam(qt)
    ids = jnp.asarray([3, 3, 17], jnp.int32)
    g = jnp.asarray(r.normal(size=(3, E)), jnp.float32)
    # one-shot compile IS the test  # graftlint: disable=retrace-hazard
    out, _ = jax.jit(functools.partial(
        su.sparse_requant_adam, lr=0.01, fused=True, block_rows=16))(
        qt, state, ids, g, jax.random.PRNGKey(0),
        count=jnp.asarray(1, jnp.int32))
    untouched = [i for i in range(V) if i not in (3, 17)]
    np.testing.assert_array_equal(np.asarray(out["q"])[untouched],
                                  np.asarray(qt["q"])[untouched])
    np.testing.assert_array_equal(np.asarray(out["s"])[untouched],
                                  np.asarray(qt["s"])[untouched])
    moved = np.asarray(dequantize_table(out))[[3, 17]]
    orig = np.asarray(dequantize_table(qt))[[3, 17]]
    assert np.abs(moved - orig).max() > 0


def test_mode_resolution_and_auto_dispatch():
    assert su.resolve_sparse_update_mode("auto") is None
    assert su.resolve_sparse_update_mode("fused") is True
    assert su.resolve_sparse_update_mode("reference") is False
    with pytest.raises(ValueError):
        su.resolve_sparse_update_mode("bogus")
    # CPU backend: auto == reference (bit-identical results)
    V, E, N = 32, 8, 50
    r = np.random.default_rng(0)
    table = jnp.asarray(r.normal(size=(V, E)), jnp.float32)
    state = init_row_adam(table)
    ids = jnp.asarray(r.integers(0, V, N), jnp.int32)
    g = jnp.asarray(r.normal(size=(N, E)), jnp.float32)

    def run(fused):
        # one-shot compile IS the test  # graftlint: disable=retrace-hazard
        return jax.jit(functools.partial(
            su.sparse_row_adam, lr=0.01, fused=fused))(
            table, state, ids, g, count=jnp.asarray(1, jnp.int32))

    (t_auto, _), (t_ref, _) = run(None), run(False)
    np.testing.assert_array_equal(np.asarray(t_auto),
                                  np.asarray(t_ref))


def test_sparse_update_pallas_config_gate():
    from code2vec_tpu.config import Config

    cfg = Config(SPARSE_UPDATE_PALLAS="bogus")
    cfg.train_data_path = "x"
    with pytest.raises(ValueError):
        cfg.verify()
    # the relaxed tables gate: bf16 + sparse now verifies
    cfg2 = Config(SPARSE_EMBEDDING_UPDATES=True,
                  EMBEDDING_OPTIMIZER="adam", LR_SCHEDULE="constant",
                  TABLES_DTYPE="bfloat16")
    cfg2.train_data_path = "x"
    cfg2.verify()
    cfg3 = Config(SPARSE_EMBEDDING_UPDATES=True,
                  EMBEDDING_OPTIMIZER="adafactor",
                  LR_SCHEDULE="constant")
    cfg3.train_data_path = "x"
    with pytest.raises(ValueError):
        cfg3.verify()


DIMS = ModelDims(token_vocab_size=64, path_vocab_size=32,
                 target_vocab_size=24, embeddings_size=8,
                 max_contexts=6, dropout_keep_rate=1.0)


def _batch(seed, dims=DIMS, b=16):
    r = np.random.default_rng(seed)
    C = dims.max_contexts
    return tuple(jnp.asarray(a) for a in (
        r.integers(0, dims.target_vocab_size, (b,)).astype(np.int32),
        r.integers(0, dims.token_vocab_size, (b, C)).astype(np.int32),
        r.integers(0, dims.path_vocab_size, (b, C)).astype(np.int32),
        r.integers(0, dims.token_vocab_size, (b, C)).astype(np.int32),
        np.ones((b, C), np.float32), np.ones((b,), np.float32)))


def _mesh_for_sparse(model=2):
    from code2vec_tpu.parallel.mesh import make_mesh
    return make_mesh(0, model)


def test_mesh_sparse_apply_bitexact_vs_carrier_f32():
    """The round-14 acceptance contract: the mesh sparse-update path
    (dedup + segment-sum + live-row apply inside shard_map on the
    8-device virtual mesh, vocab sharded over 'model') is BIT-exact vs
    BOTH the single-device compact path and the dense-carrier reference
    (row_adam_update — the [V, E] scatter-add form the mesh path no
    longer constructs). Two sharded parts + one replicated part
    exercise the all-gather + caller-order concatenation."""
    V, E, N = 48, 8, 64  # V % model == 0, N % (dcn*data) == 0
    r = np.random.default_rng(11)
    table = jnp.asarray(r.normal(size=(V, E)), jnp.float32)
    state = init_row_adam(table)
    ids_a = jnp.asarray(r.integers(0, V, N), jnp.int32)
    ids_b = jnp.asarray(r.integers(0, V, N), jnp.int32)
    ids_r = jnp.asarray(r.integers(0, V, 8), jnp.int32)  # replicated
    g_a = jnp.asarray(r.normal(size=(N, E)), jnp.float32)
    g_b = jnp.asarray(r.normal(size=(N, E)), jnp.float32)
    g_r = jnp.asarray(r.normal(size=(8, E)), jnp.float32)
    count = jnp.asarray(4, jnp.int32)
    mesh = _mesh_for_sparse(model=2)

    @jax.jit
    def run_mesh(table, m, v, ids_a, g_a, ids_b, g_b, ids_r, g_r,
                 count):
        t, s = su.mesh_sparse_apply(
            mesh, table, RowAdamState(m=m, v=v),
            [(ids_a, g_a, True), (ids_b, g_b, True),
             (ids_r, g_r, False)],
            count=count, lr=0.01, fused=False, block_rows=16)
        return t, s.m, s.v

    t_mesh, m_mesh, v_mesh = run_mesh(table, state.m, state.v, ids_a,
                                      g_a, ids_b, g_b, ids_r, g_r,
                                      count)
    s_mesh = RowAdamState(m=m_mesh, v=v_mesh)

    ids = jnp.concatenate([ids_a, ids_b, ids_r])
    g = jnp.concatenate([g_a, g_b, g_r])
    # one-shot compile IS the test  # graftlint: disable=retrace-hazard
    t_sd, s_sd = jax.jit(functools.partial(
        su.sparse_row_adam, lr=0.01, fused=False, block_rows=16))(
        table, state, ids, g, count=count)
    # one-shot compile IS the test  # graftlint: disable=retrace-hazard
    t_car, s_car = jax.jit(functools.partial(row_adam_update, lr=0.01))(
        table, state, ids, g, count=count)

    for name, (t_ref, s_ref) in {"single-device": (t_sd, s_sd),
                                 "carrier": (t_car, s_car)}.items():
        np.testing.assert_array_equal(np.asarray(t_mesh),
                                      np.asarray(t_ref), err_msg=name)
        np.testing.assert_array_equal(np.asarray(s_mesh.m),
                                      np.asarray(s_ref.m), err_msg=name)
        np.testing.assert_array_equal(np.asarray(s_mesh.v),
                                      np.asarray(s_ref.v), err_msg=name)


def test_fused_step_reproduces_reference_step_exactly():
    """--sparse_update_pallas fused vs reference: identical training
    trajectory (the flag-level A/B), through make_train_step's sparse
    dispatch — the exact entry point jax_model uses."""
    params = init_params(jax.random.PRNGKey(0), DIMS)

    def build(fused):
        return make_train_step(
            DIMS, optax.adam(0.05), use_sampled_softmax=True,
            num_sampled=8, sparse_updates=True, learning_rate=0.05,
            sparse_update_fused=fused, sparse_block_rows=32)

    ref_step, fus_step = build(False), build(True)
    o1 = init_sparse_opt_state(params, optax.adam(0.05), True)
    o2 = init_sparse_opt_state(params, optax.adam(0.05), True)
    p1 = jax.tree_util.tree_map(jnp.copy, params)
    p2 = jax.tree_util.tree_map(jnp.copy, params)
    rng = jax.random.PRNGKey(1)
    batch = _batch(11)
    for _ in range(4):
        rng, k = jax.random.split(rng)
        p1, o1, l1 = ref_step(p1, o1, batch, k)
        p2, o2, l2 = fus_step(p2, o2, batch, k)
    assert float(l1) == float(l2)
    for key in p1:
        np.testing.assert_array_equal(np.asarray(p1[key]),
                                      np.asarray(p2[key]), err_msg=key)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_mesh_sparse_apply_matches_single_device(dtype):
    """f32 AND bf16 tables run the compact path under the mesh now
    (round 14 removed the f32-only dense-carrier restriction along
    with the carrier): bit-exact vs the single-device compact apply,
    with the vocab dim sharded over 'model'."""
    V, E, N = 40, 16, 32
    r = np.random.default_rng(23)
    table = jnp.asarray(r.normal(size=(V, E)) * 0.3).astype(dtype)
    state = RowAdamState(
        m=jnp.asarray(r.normal(size=(V, E)) * 0.01, jnp.float32),
        v=jnp.asarray(np.abs(r.normal(size=(V, E))) * 1e-3,
                      jnp.float32))
    ids = jnp.asarray(r.integers(0, V, N), jnp.int32)
    g = jnp.asarray(r.normal(size=(N, E)) * 0.1).astype(dtype)
    count = jnp.asarray(3, jnp.int32)
    mesh = _mesh_for_sparse(model=2)

    @jax.jit
    def run_mesh(table, m, v, ids, g, count):
        t, s = su.mesh_sparse_apply(
            mesh, table, RowAdamState(m=m, v=v), [(ids, g, True)],
            count=count, lr=0.01, fused=False, block_rows=16)
        return t, s.m, s.v

    t_mesh, m_mesh, v_mesh = run_mesh(table, state.m, state.v, ids, g,
                                      count)
    # one-shot compile IS the test  # graftlint: disable=retrace-hazard
    t_sd, s_sd = jax.jit(functools.partial(
        su.sparse_row_adam, lr=0.01, fused=False, block_rows=16))(
        table, state, ids, g, count=count)
    np.testing.assert_array_equal(np.asarray(t_mesh, np.float32),
                                  np.asarray(t_sd, np.float32))
    np.testing.assert_array_equal(np.asarray(m_mesh),
                                  np.asarray(s_sd.m))
    np.testing.assert_array_equal(np.asarray(v_mesh),
                                  np.asarray(s_sd.v))


def test_mesh_sparse_apply_int8_q_exact():
    """int8 {q, s} tables under the mesh: the model-sharded blocks draw
    dither from the GLOBAL row index, so q is bit-exact vs the
    single-device compact pass under the same rng (s within 2 ulp —
    the pallas_requant float-contraction bound)."""
    V, E, N = 64, 8, 32
    r = np.random.default_rng(31)
    qt = quantize_table(jnp.asarray(r.normal(size=(V, E)) * 0.3,
                                    jnp.float32))
    state = init_row_adam(qt)
    ids = jnp.asarray(r.integers(0, V, N), jnp.int32)
    g = jnp.asarray(r.normal(size=(N, E)) * 0.1, jnp.float32)
    count = jnp.asarray(2, jnp.int32)
    rng = jax.random.PRNGKey(9)
    mesh = _mesh_for_sparse(model=2)

    @jax.jit
    def run_mesh(qt, m, v, ids, g, count, rng):
        t, s = su.mesh_sparse_apply(
            mesh, qt, RowAdamState(m=m, v=v), [(ids, g, True)],
            count=count, lr=0.01, fused=False, block_rows=16, rng=rng)
        return t, s.m, s.v

    q_mesh, m_mesh, v_mesh = run_mesh(qt, state.m, state.v, ids, g,
                                      count, rng)
    # one-shot compile IS the test  # graftlint: disable=retrace-hazard
    q_sd, s_sd = jax.jit(functools.partial(
        su.sparse_requant_adam, lr=0.01, fused=False, block_rows=16))(
        qt, state, ids, g, rng, count=count)
    np.testing.assert_array_equal(np.asarray(q_mesh["q"]),
                                  np.asarray(q_sd["q"]))
    ulp = np.abs(np.asarray(q_mesh["s"]).ravel().view(np.int32)
                 - np.asarray(q_sd["s"]).ravel().view(np.int32))
    assert ulp.max() <= 2, ulp.max()
    np.testing.assert_array_equal(np.asarray(m_mesh),
                                  np.asarray(s_sd.m))
    np.testing.assert_array_equal(np.asarray(v_mesh),
                                  np.asarray(s_sd.v))


def test_mesh_sparse_apply_honors_fused_flag():
    """SPARSE_UPDATE_PALLAS is honored under the mesh: fused=True runs
    the Pallas live-row kernel per device inside the manual region
    (interpret mode on CPU), bit-exact vs the mesh reference."""
    V, E, N = 32, 8, 16
    r = np.random.default_rng(7)
    table = jnp.asarray(r.normal(size=(V, E)), jnp.float32)
    state = init_row_adam(table)
    ids = jnp.asarray(r.integers(0, V, N), jnp.int32)
    g = jnp.asarray(r.normal(size=(N, E)), jnp.float32)
    count = jnp.asarray(1, jnp.int32)
    mesh = _mesh_for_sparse(model=2)

    def run(fused):
        # one-shot compile IS the test  # graftlint: disable=retrace-hazard
        @jax.jit
        def go(table, m, v, ids, g, count):
            t, s = su.mesh_sparse_apply(
                mesh, table, RowAdamState(m=m, v=v), [(ids, g, True)],
                count=count, lr=0.01, fused=fused, block_rows=16)
            return t, s.m, s.v
        return go(table, state.m, state.v, ids, g, count)

    (t_ref, m_ref, v_ref), (t_fus, m_fus, v_fus) = run(False), run(True)
    np.testing.assert_array_equal(np.asarray(t_ref), np.asarray(t_fus))
    np.testing.assert_array_equal(np.asarray(m_ref), np.asarray(m_fus))
    np.testing.assert_array_equal(np.asarray(v_ref), np.asarray(v_fus))


def test_mesh_sparse_apply_error_paths():
    """Trace-time guards: ctx-sharded meshes are refused (the bag
    encoder's batch never shards over 'ctx'), int8 requires the dither
    rng, and non-model-divisible tables are caught up front."""
    from code2vec_tpu.parallel.mesh import make_mesh
    table = jnp.zeros((8, 4), jnp.float32)
    state = init_row_adam(table)
    part = [(jnp.zeros((8,), jnp.int32),
             jnp.zeros((8, 4), jnp.float32), True)]
    count = jnp.asarray(1, jnp.int32)
    with pytest.raises(ValueError, match="ctx"):
        su.mesh_sparse_apply(make_mesh(0, 1, context=2), table, state,
                             part, count=count, lr=0.01)
    qt = quantize_table(jnp.ones((8, 4), jnp.float32))
    with pytest.raises(ValueError, match="rng"):
        su.mesh_sparse_apply(make_mesh(0, 2), qt, init_row_adam(qt),
                             part, count=count, lr=0.01)
    with pytest.raises(ValueError, match="divisible"):
        su.mesh_sparse_apply(make_mesh(0, 8),
                             jnp.zeros((12, 4), jnp.float32),
                             init_row_adam(jnp.zeros((12, 4))),
                             part, count=count, lr=0.01)


def test_int8_sparse_step_trains_through_fused_path():
    """int8 tables + sparse updates end to end through the fused
    interpret-mode kernel: loss decreases, {q, s} structure preserved,
    moments live."""
    dims = ModelDims(token_vocab_size=64, path_vocab_size=32,
                     target_vocab_size=24, embeddings_size=8,
                     max_contexts=6, tables_dtype="int8",
                     dropout_keep_rate=1.0)
    params = init_params(jax.random.PRNGKey(3), dims)
    step = make_train_step(dims, optax.adam(0.05),
                           use_sampled_softmax=False,
                           sparse_updates=True, learning_rate=0.05,
                           sparse_update_fused=True,
                           sparse_block_rows=32)
    opt_state = init_sparse_opt_state(params, optax.adam(0.05), False)
    batch = _batch(7, dims)
    losses = []
    rng = jax.random.PRNGKey(4)
    for _ in range(40):
        rng, k = jax.random.split(rng)
        params, opt_state, loss = step(params, opt_state, batch, k)
        losses.append(float(loss))
    assert set(params["token_emb"]) == {"q", "s"}
    assert params["token_emb"]["q"].dtype == jnp.int8
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_vm_rows_from_dense_matches_dense_rows():
    """The varmisuse entry: unique rows of the DENSE cotangent get one
    row-Adam step; rows outside the id set stay untouched even when
    the dense grad is nonzero there (the sparse contract)."""
    V, E = 32, 8
    r = np.random.default_rng(5)
    table = jnp.asarray(r.normal(size=(V, E)), jnp.float32)
    state = init_row_adam(table)
    dense_grad = jnp.asarray(r.normal(size=(V, E)), jnp.float32)
    ids = jnp.asarray([1, 1, 4, 9, 4], jnp.int32)
    # one-shot compile IS the test  # graftlint: disable=retrace-hazard
    out, _ = jax.jit(functools.partial(
        su.rows_from_dense, lr=0.01, fused=False, block_rows=8))(
        table, state, dense_grad, ids,
        count=jnp.asarray(1, jnp.int32))
    # oracle: one row-Adam step on exactly rows {1, 4, 9}
    oracle = jax.jit(functools.partial(row_adam_update, lr=0.01))
    t_ref, _ = oracle(table, state, jnp.asarray([1, 4, 9], jnp.int32),
                      jnp.take(dense_grad, jnp.asarray([1, 4, 9]),
                               axis=0),
                      count=jnp.asarray(1, jnp.int32))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(t_ref))
    untouched = [i for i in range(V) if i not in (1, 4, 9)]
    np.testing.assert_array_equal(np.asarray(out)[untouched],
                                  np.asarray(table)[untouched])


def test_vm_sparse_train_step_runs_and_trains():
    from code2vec_tpu.models.varmisuse import init_vm_params
    from code2vec_tpu.training.vm_steps import (init_vm_sparse_opt_state,
                                                make_vm_train_step)
    dims = ModelDims(token_vocab_size=32, path_vocab_size=16,
                     target_vocab_size=8, embeddings_size=8,
                     max_contexts=5, dropout_keep_rate=1.0)
    params = init_vm_params(jax.random.PRNGKey(0), dims)
    opt = optax.adam(0.05)
    step = make_vm_train_step(dims, opt, sparse_updates=True,
                              learning_rate=0.05,
                              sparse_update_fused=True)
    opt_state = init_vm_sparse_opt_state(params, opt)
    r = np.random.default_rng(0)
    B, C, K = 8, 5, 4
    batch = tuple(jnp.asarray(a) for a in (
        r.integers(0, K, (B,)).astype(np.int32),
        r.integers(0, 32, (B, C)).astype(np.int32),
        r.integers(0, 16, (B, C)).astype(np.int32),
        r.integers(0, 32, (B, C)).astype(np.int32),
        np.ones((B, C), np.float32),
        r.integers(0, 32, (B, K)).astype(np.int32),
        np.ones((B, K), np.float32),
        np.ones((B,), np.float32)))
    losses = []
    rng = jax.random.PRNGKey(2)
    for _ in range(30):
        rng, k = jax.random.split(rng)
        params, opt_state, loss = step(params, opt_state, batch, k)
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    assert int(opt_state["count"]) == 30
    # vm + mesh is gated (the dedup-under-GSPMD miscompile)
    with pytest.raises(ValueError):
        make_vm_train_step(dims, opt, sparse_updates=True,
                           learning_rate=0.05, mesh=object())


def test_traffic_model():
    V, E, N, U = 64, 8, 100, 40
    table = jnp.zeros((V, E), jnp.float32)
    b = su.sparse_update_traffic_bytes(table, N, U, block_rows=32)
    slots = -(-N // 32) * 32
    expect = (N * 4 + N * E * 4 + slots * E * 8
              + U * E * 4 * 2 + U * E * 16)
    assert b == expect
    qt = {"q": jnp.zeros((V, E), jnp.int8),
          "s": jnp.zeros((V, 1), jnp.float32)}
    bq = su.sparse_update_traffic_bytes(qt, N, U, grad_itemsize=2,
                                        block_rows=32)
    expect_q = (N * 4 + N * E * 2 + slots * E * 8
                + U * E * 2 + U * 8 + U * E * 16)
    assert bq == expect_q
    # E[U] is monotone, bounded by both N and V
    assert su.expected_unique_rows(10**6, 1000) <= 1000
    assert su.expected_unique_rows(10, 10**6) <= 10 + 1
    assert su.expected_unique_rows(0, 100) == 0
    # the full-step floor model runs on a real params tree, and the
    # phase-alone helper (the live gauge's model) is a strict subset
    params = init_params(jax.random.PRNGKey(0), DIMS)
    full = su.sparse_step_floor_bytes(params, 16, DIMS.max_contexts,
                                      num_sampled=8)
    phase = su.sparse_update_phase_bytes(params, 16, DIMS.max_contexts,
                                         num_sampled=8)
    assert 0 < phase < full
