"""VarMisuse head (BASELINE.json configs[3]): generator row validity,
reader shapes, above-chance bug localization after a short train, and
checkpoint round-trip via the --head varmisuse model class."""

import os
import random

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data.varmisuse_gen import (SLOT_TOKEN, make_vm_rows,
                                             make_vm_source,
                                             write_vm_dataset)
from code2vec_tpu.extractor import native


def _need_native():
    if not native.available():
        pytest.skip("native extractor not built")


def vm_config(prefix, **kw):
    cfg = Config(
        MAX_CONTEXTS=64,
        MAX_TOKEN_VOCAB_SIZE=1000,
        MAX_PATH_VOCAB_SIZE=2000,
        MAX_TARGET_VOCAB_SIZE=10,
        DEFAULT_EMBEDDINGS_SIZE=32,
        TRAIN_BATCH_SIZE=32,
        TEST_BATCH_SIZE=32,
        NUM_TRAIN_EPOCHS=8,
        SAVE_EVERY_EPOCHS=100,
        NUM_BATCHES_TO_LOG_PROGRESS=1000,
        LEARNING_RATE=0.02,
        USE_BF16=False,
        MESH_MODEL_AXIS=1,
        HEAD="varmisuse",
        MAX_CANDIDATES=6,
    )
    cfg.train_data_path = prefix
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def test_vm_source_has_exactly_one_hole():
    import re

    keywords = {"class", "int", "boolean", "void", "for", "if",
                "return", "this", "VM", SLOT_TOKEN}
    rng = random.Random(3)
    for _ in range(200):
        src, cands, label = make_vm_source(rng)
        assert src.count(SLOT_TOKEN) == 1
        assert 0 <= label < len(cands)
        assert len(set(cands)) == len(cands)
        # the hole replaced a USE of the labeled var: the var still
        # appears elsewhere (declaration at minimum)
        assert re.search(rf"\b{cands[label]}\b",
                         src.replace(SLOT_TOKEN, " "))
        # no corrupted identifiers: every identifier in the source is a
        # keyword, a method name, or one of the declared variables
        # (catches substring-boundary bugs in hole insertion)
        for ident in re.findall(r"[A-Za-z_]\w*", src):
            assert (ident in keywords or ident in cands
                    or ident.startswith("method")), (ident, src)


def test_vm_rows_parse_and_carry_slot():
    _need_native()
    rows = make_vm_rows(20, seed=5)
    assert len(rows) == 20
    for row in rows:
        parts = row.split(" ")
        label = int(parts[0])
        cands = parts[1].split(",")
        assert 0 <= label < len(cands)
        assert any(SLOT_TOKEN in ctx for ctx in parts[2:])
        for ctx in parts[2:]:
            assert len(ctx.split(",")) == 3


def test_vm_reader_shapes(tmp_path):
    _need_native()
    from code2vec_tpu.data.vm_reader import (VMTextReader,
                                             build_vm_vocabs)

    prefix = str(tmp_path / "vm")
    write_vm_dataset(prefix, n_train=40, n_val=8, n_test=8, seed=1)
    vocabs = build_vm_vocabs(prefix + ".train.vm.c2v", 1000, 2000)
    assert vocabs.token_vocab.lookup_index(SLOT_TOKEN) \
        != vocabs.token_vocab.oov_index

    reader = VMTextReader(prefix + ".train.vm.c2v", vocabs,
                          max_contexts=64, max_candidates=6,
                          batch_size=16)
    batches = list(reader)
    assert sum(b.num_valid_examples for b in batches) == 40
    b = batches[0]
    assert b.label.shape == (16,)
    assert b.cand_ids.shape == (16, 6)
    assert b.path_indices.shape == (16, 64)
    assert b.cand_mask[0].sum() == 5  # 5 role candidates per example
    assert b.context_valid_mask.max() == 1.0
    # final padded batch keeps one live candidate per padded row
    last = batches[-1]
    assert last.cand_mask.min(axis=1).max() <= 1.0
    assert last.cand_mask.sum(axis=1).min() >= 1.0


@pytest.fixture(scope="module")
def vm_dataset(tmp_path_factory):
    _need_native()
    d = tmp_path_factory.mktemp("vm")
    prefix = os.path.join(str(d), "vm")
    write_vm_dataset(prefix, n_train=1200, n_val=150, n_test=100,
                     seed=11)
    return prefix


def test_vm_training_beats_chance_and_roundtrips(vm_dataset, tmp_path):
    from code2vec_tpu.models.vm_model import VarMisuseModel

    ckpt_dir = str(tmp_path / "ckpt")
    cfg = vm_config(vm_dataset, save_path=ckpt_dir)
    cfg.test_data_path = vm_dataset + ".val.vm.c2v"
    model = VarMisuseModel(cfg)
    before = model.evaluate()
    model.train()
    after = model.evaluate()
    assert after.loss < before.loss
    # 5 live candidates -> chance = 0.2; role-consistent synthetic data
    # is fully learnable (measured 0.8-1.0 at these settings across
    # tables dtypes)
    assert after.accuracy >= 0.7, after
    model.save(ckpt_dir)

    cfg2 = vm_config(vm_dataset)
    cfg2.train_data_path = None
    cfg2.load_path = ckpt_dir
    cfg2.test_data_path = vm_dataset + ".val.vm.c2v"
    model2 = VarMisuseModel(cfg2)
    assert model2.step_num == model.step_num
    loaded = model2.evaluate()
    assert loaded.accuracy == pytest.approx(after.accuracy)

    # pointer predictions on fresh rows the model never saw
    rows = make_vm_rows(25, seed=99)
    pred = model2.predict_batch(rows)
    assert pred.shape == (25,)
    labels = [int(r.split(" ")[0]) for r in rows]
    acc = np.mean([p == l for p, l in zip(pred, labels)])
    assert acc >= 0.5  # far above the 0.2 chance level


def test_vm_cli_flag_validation(vm_dataset):
    cfg = vm_config(vm_dataset, is_predict=True)
    cfg.load_path = "whatever"
    with pytest.raises(ValueError):
        cfg.verify()


def test_vm_cosine_schedule_trains(vm_dataset, tmp_path):
    """--lr_schedule is wired through the varmisuse head too (total
    steps sized from the .vm.c2v split)."""
    from code2vec_tpu.models.vm_model import VarMisuseModel
    cfg = vm_config(vm_dataset, NUM_TRAIN_EPOCHS=3, LR_SCHEDULE="cosine")
    cfg.save_path = str(tmp_path / "vmck")
    m = VarMisuseModel(cfg)
    m.train()
    m.save()
    res = m.evaluate(m._vm_path("train"))
    assert res.accuracy > 0.3
    # eval-only load restores the schedule-bearing opt_state structure;
    # request a conflicting schedule so the assert only passes when the
    # manifest override actually runs
    cfg2 = vm_config(vm_dataset, LR_SCHEDULE="constant")
    cfg2.train_data_path = None
    cfg2.load_path = str(tmp_path / "vmck")
    cfg2.test_data_path = "unused"
    m2 = VarMisuseModel(cfg2)
    assert cfg2.LR_SCHEDULE == "cosine"
