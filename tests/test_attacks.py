"""Adversarial-attack tests (the noamyft fork delta, SURVEY.md §0
item 2): gradient-guided rename attacks against a small trained model —
untargeted flip rate, targeted reachability, trajectory consistency,
robustness sweep, and the source-level rename / dead-code drivers
through the native extractor."""

import os

import numpy as np
import pytest

from code2vec_tpu.attacks import (GradientRenameAttack, SourceAttack,
                                  evaluate_robustness, render_identifier)
from code2vec_tpu.attacks.source_attack import (identifiers_for_token,
                                                insert_dead_declaration,
                                                rename_in_source)
from code2vec_tpu.data.reader import parse_c2v_rows
from code2vec_tpu.models.jax_model import Code2VecModel
from tests.helpers import build_tiny_dataset
from tests.test_model import tiny_config

EXTRACTOR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "code2vec_tpu", "extractor", "build",
    "c2v_extract")


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    d = tmp_path_factory.mktemp("attack_data")
    prefix = build_tiny_dataset(str(d), n_train=256, n_val=32, n_test=64,
                                max_contexts=16)
    cfg = tiny_config(prefix)
    model = Code2VecModel(cfg)
    model.train()
    return cfg, model, prefix


def _attack_for(model, **kw):
    return GradientRenameAttack(
        model.dims, model.vocabs.token_vocab, model.vocabs.target_vocab,
        compute_dtype=model.compute_dtype, **kw)


def _test_methods(model, prefix, n):
    with open(prefix + ".test.c2v", encoding="utf-8") as f:
        lines = [ln for ln in f if ln.strip()][:n]
    labels, src, pth, dst, mask, _, _ = parse_c2v_rows(
        lines, model.vocabs, model.dims.max_contexts)
    return labels, [(src[i], pth[i], dst[i], mask[i])
                    for i in range(len(lines))]


def test_render_identifier():
    assert render_identifier("array|index") == "arrayIndex"
    assert render_identifier("foo") == "foo"
    assert render_identifier("get|html|body") == "getHtmlBody"
    assert render_identifier("<PAD>") is None
    assert render_identifier("a|2b") is None
    # reserved words are not identifiers — `int while;` is not Java
    assert render_identifier("while") is None
    assert render_identifier("int") is None
    assert render_identifier("string") is None


def test_untargeted_attack_flips_predictions(trained):
    _, model, prefix = trained
    attack = _attack_for(model, max_iters=4)
    _, methods = _test_methods(model, prefix, 12)
    results = [attack.attack_method(model.params, m, targeted=False,
                                    max_renames=2) for m in methods]
    flips = sum(r.success for r in results)
    # the synthetic corpus ties targets to token identity, so renaming
    # the decisive tokens must flip most predictions
    assert flips >= len(results) // 2, \
        f"only {flips}/{len(results)} untargeted attacks succeeded"
    for r in results:
        if r.success:
            assert r.final_prediction != r.original_prediction


def test_batch_attack_rejects_unattackable_method(trained):
    """A method with zero attackable tokens gets a clear ValueError from
    attack_batch, not a bare IndexError (ADVICE r3): external callers
    that skip robustness.py's filter see the precondition by name."""
    import pytest

    _, model, prefix = trained
    attack = _attack_for(model, max_iters=2)
    _, methods = _test_methods(model, prefix, 2)
    m = methods[0]
    # fully-padded method: no valid slots -> no attackable tokens
    dead = (m[0], m[1], m[2], np.zeros_like(m[3]))
    with pytest.raises(ValueError, match="no attackable tokens"):
        attack.attack_batch(model.params, [methods[1], dead])


def test_batch_attack_matches_serial(trained):
    """attack_batch is an optimization, not a different attack: same
    success flags, renames, and final predictions as the serial driver
    on the same methods."""
    _, model, prefix = trained
    attack = _attack_for(model, max_iters=4)
    _, methods = _test_methods(model, prefix, 10)
    eligible = [m for m in methods
                if attack.attackable_tokens(m[0], m[2], m[3])]
    serial = [attack.attack_method(model.params, m, targeted=False,
                                   max_renames=1) for m in eligible]
    batch = attack.attack_batch(model.params, eligible)
    assert len(batch) == len(serial)
    for s, b in zip(serial, batch):
        assert b.success == s.success
        assert b.renames == s.renames
        assert b.final_prediction == s.final_prediction
        assert b.original_prediction == s.original_prediction
        assert b.iterations == s.iterations
        np.testing.assert_array_equal(b.final_method[0],
                                      s.final_method[0])


def test_attack_trajectory_monotone_and_consistent(trained):
    _, model, prefix = trained
    attack = _attack_for(model, max_iters=4)
    _, methods = _test_methods(model, prefix, 6)
    for m in methods:
        r = attack.attack_method(model.params, m, targeted=False,
                                 max_renames=1)
        # every ACCEPTED step must strictly improve the attack loss
        for s in r.steps:
            assert s.loss_after < s.loss_before
        assert r.iterations >= 1


def test_targeted_attack_reaches_target(trained):
    _, model, prefix = trained
    attack = _attack_for(model, max_iters=6, top_k_candidates=48)
    labels, methods = _test_methods(model, prefix, 12)
    tv = model.vocabs.target_vocab
    hits = tried = 0
    for lbl, m in zip(labels, methods):
        # aim each method at a DIFFERENT class than its ground truth
        target_id = int(lbl) + 1
        if target_id >= tv.size:
            target_id = 2  # first non-special row
        target = tv.lookup_word(target_id)
        if target in ("<PAD>", "<OOV>"):
            continue
        tried += 1
        r = attack.attack_method(model.params, m, targeted=True,
                                 target_name=target, max_renames=3)
        if r.success:
            hits += 1
            assert r.final_prediction == target
    assert tried >= 8
    assert hits >= tried // 4, \
        f"targeted attack hit {hits}/{tried} — gradient guidance broken?"


def test_attack_works_on_model_sharded_params(trained):
    """The attack's jitted steps must follow the params' NamedSharding
    (TP-sharded vocab tables) — jit partitions around the spare-row
    update and the [V,E]@[E] matvec without host-side changes."""
    _, _, prefix = trained
    cfg = tiny_config(prefix, MESH_MODEL_AXIS=2, NUM_TRAIN_EPOCHS=2)
    model = Code2VecModel(cfg)
    model.train()
    attack = _attack_for(model, max_iters=3)
    _, methods = _test_methods(model, prefix, 4)
    for m in methods:
        r = attack.attack_method(model.params, m, targeted=False,
                                 max_renames=1)
        assert r.original_prediction  # ran end-to-end on sharded params
    batch = attack.attack_batch(model.params, methods)
    assert len(batch) == len(methods)


def test_robustness_report(trained):
    _, model, prefix = trained
    report = evaluate_robustness(model, prefix + ".test.c2v",
                                 n_methods=8, max_renames=1,
                                 max_iters=3, log=lambda *_: None)
    assert report["n_methods"] > 0
    assert 0.0 <= report["attack_success_rate"] <= 1.0
    assert report["robustness"] == pytest.approx(
        1.0 - report["attack_success_rate"], abs=1e-6)
    assert 0.0 <= report["clean_top1_acc"] <= 1.0


def test_source_helpers():
    src = "int foo(int barBaz) { return barBaz + quxVal.size(); }"
    assert identifiers_for_token(src, "bar|baz") == ["barBaz"]
    # quxVal is never declared here -> not a rename target
    assert identifiers_for_token(src, "qux|val") == []
    out = rename_in_source(src, "barBaz", "newName")
    assert "barBaz" not in out and out.count("newName") == 2
    dead = insert_dead_declaration(
        "class A { int go(int x) { return x; } }", "go", "deadVar")
    assert dead is not None and "int deadVar;" in dead
    assert insert_dead_declaration("class A {}", "missing", "v") is None


def test_declared_variables_heuristic():
    from code2vec_tpu.attacks.source_attack import declared_variables
    src = ("class A { int[] items; "
           "int go(int loVal, String name) { "
           "int mid = loVal + 1; for (int i = 0; i < mid; i++) "
           "{ helper(mid); } return mid; } }")
    decls = declared_variables(src)
    assert set(decls) == {"items", "loVal", "name", "mid", "i"}
    # called methods and `return x` never count as declarations
    assert "helper" not in decls and "go" not in decls


def test_declared_variables_python():
    from code2vec_tpu.attacks.source_attack import (
        declared_variables_python)
    src = ("def go(loVal, name, *rest, **opts):\n"
           "    mid = loVal + 1\n"
           "    for i in range(mid):\n"
           "        helper(mid)\n"
           "    return mid\n")
    decls = declared_variables_python(src)
    assert set(decls) == {"loVal", "name", "rest", "opts", "mid", "i"}
    assert "helper" not in decls and "range" not in decls
    assert declared_variables_python("def broken(:") == []


def test_source_level_python_rename_attack(trained, tmp_path):
    cfg, model, _ = trained
    py = tmp_path / "victim.py"
    py.write_text(
        "def foo(value, count):\n"
        "    index = value + count\n"
        "    return index * value\n")
    attack = SourceAttack(cfg, model, max_iters=3)
    res = attack.attack_file(str(py), targeted=False, max_renames=2)
    assert res.attack.original_prediction
    if res.renames:
        for old, new in res.renames.items():
            # word-boundary: the new name may CONTAIN the old one
            import re as _re
            assert _re.search(rf"\b{old}\b",
                              res.adversarial_source) is None
            assert new in res.adversarial_source
    # dead-code mode is a documented Java-only feature
    with pytest.raises(ValueError, match="Java"):
        attack.attack_file(str(py), targeted=False, deadcode=True)


def test_python_rename_rewrites_global_statements():
    from code2vec_tpu.attacks.source_attack import (
        rename_in_source_python)
    src = ("cnt = 0\n"
           "def f():\n"
           "    global cnt\n"
           "    cnt = cnt + 1\n")
    out = rename_in_source_python(src, "cnt", "qux")
    assert "global qux" in out and "cnt" not in out


def test_python_declared_excludes_unrenameable_binders():
    from code2vec_tpu.attacks.source_attack import (
        declared_variables_python)
    src = ("import os as osmod\n"
           "def f(x):\n"
           "    try:\n"
           "        y = x\n"
           "    except ValueError as err:\n"
           "        return err\n"
           "    return y\n")
    decls = declared_variables_python(src)
    assert "err" not in decls and "osmod" not in decls
    assert {"x", "y"} <= set(decls)
    # match-capture binders and dotted-import roots are bare strings in
    # the AST (no positions) -> never rename targets
    src2 = ("import os.path\n"
            "def g(v):\n"
            "    x = 0\n"
            "    match v:\n"
            "        case x:\n"
            "            return x\n"
            "    return x + len(os.path.sep)\n")
    decls2 = declared_variables_python(src2)
    assert "x" not in decls2 and "os" not in decls2
    assert "v" in decls2


def test_java_declared_keeps_python_keyword_words():
    # `match`/`value` are legal Java identifiers; the Python keyword
    # set must not leak into the Java declaration filter
    from code2vec_tpu.attacks.source_attack import declared_variables
    src = "int go(int match) { int value = match; return value; }"
    decls = declared_variables(src)
    assert {"match", "value"} <= set(decls)


def test_python_rename_preserves_kwarg_names():
    from code2vec_tpu.attacks.source_attack import (
        rename_in_source_python)
    src = ("def go(timeout):\n"
           "    return fetch(url, timeout=timeout, s='timeout')\n")
    out = rename_in_source_python(src, "timeout", "qux")
    # param + value renamed; the callee's kwarg NAME and the string stay
    assert "def go(qux):" in out
    assert "timeout=qux" in out
    assert "'timeout'" in out


def test_dead_declaration_skips_call_sites():
    # `if (check()) {` is a call followed by a block, not a declaration
    src = ("class A { void run() { if (check()) { doIt(); } } "
           "boolean check() { return true; } }")
    out = insert_dead_declaration(src, "check", "dv", ordinal=0)
    assert out.index("int dv;") > out.index("boolean check()")


def test_dead_declaration_overload_ordinal():
    src = ("class A { int f(int x) { return x; } "
           "int f(int x, int y) { return x + y; } }")
    first = insert_dead_declaration(src, "f", "dv", ordinal=0)
    second = insert_dead_declaration(src, "f", "dv", ordinal=1)
    assert first.index("int dv;") < first.index("int f(int x, int y)")
    assert second.index("int dv;") > second.index("int f(int x, int y)")


def test_rename_never_collides_with_method_tokens(trained):
    _, model, prefix = trained
    attack = _attack_for(model, max_iters=4)
    _, methods = _test_methods(model, prefix, 10)
    for m in methods:
        src, _, dst, mask = m
        present = {model.vocabs.token_vocab.lookup_word(int(t))
                   for t in np.unique(np.concatenate([src, dst]))}
        r = attack.attack_method(model.params, m, targeted=False,
                                 max_renames=1)
        for s in r.steps:
            # a new name must not merge with a token the method used
            assert s.to_token not in present


def test_rarity_detector_flags_attacks(trained):
    from code2vec_tpu.attacks.detect import (RarityDetector, auc,
                                             load_token_counts)
    _, model, prefix = trained
    counts = load_token_counts(prefix + ".dict.c2v")
    det = RarityDetector(model.dims, model.vocabs.token_vocab, counts,
                         compute_dtype=model.compute_dtype)
    report = evaluate_robustness(model, prefix + ".test.c2v",
                                 n_methods=10, max_renames=1,
                                 max_iters=3, detector=det,
                                 log=lambda *_: None)
    # attacks on this fixture corpus succeed ~always; if that stops
    # holding the test must fail loudly, not skip its assertions
    assert "detection_auc" in report, report
    assert 0.0 <= report["detection_auc"] <= 1.0
    assert 0.0 <= report["detection_tpr_at_5fpr"] <= 1.0
    # AUC helper sanity: separable score sets -> 1.0; identical -> 0.5
    assert auc(np.array([1.0, 2.0]), np.array([3.0, 4.0])) == 1.0
    assert auc(np.array([1.0]), np.array([1.0])) == 0.5
    assert auc(np.array([3.0, 4.0]), np.array([1.0, 2.0])) == 0.0


def test_freq_stats_excludes_oov_words():
    """The replacement-frequency report must not let OOV-mapped words
    contribute the OOV row's (typically zero) train count — they are
    excluded and counted separately (ADVICE r5 finding 3)."""
    from code2vec_tpu.attacks.robustness import _freq_stats
    from code2vec_tpu.vocab.vocabularies import Vocab, VocabType

    v = Vocab(VocabType.Token, ["alpha", "beta", "gamma"])
    counts = np.zeros((8,), np.int64)
    counts[v.lookup_index("alpha")] = 100
    counts[v.lookup_index("beta")] = 1
    counts[v.lookup_index("gamma")] = 50
    stats = _freq_stats(["alpha", "notInVocabXyz", "beta"], counts, v)
    assert stats["n"] == 2 and stats["n_oov_excluded"] == 1
    # without the filter the OOV word's count-0 row would have dragged
    # the median to 1 and pushed frac_singleton to 2/3
    assert stats["median_train_count"] == 50.5
    assert stats["frac_singleton"] == 0.5
    # all-OOV input: no stats rows, the exclusion count still reported
    assert _freq_stats(["q1", "q2"], counts, v) == \
        {"n": 0, "n_oov_excluded": 2}


def test_rarity_detector_scores_rare_attention_higher(trained):
    import jax.numpy as jnp
    from code2vec_tpu.attacks.detect import (RarityDetector,
                                             load_token_counts)
    _, model, prefix = trained
    counts = load_token_counts(prefix + ".dict.c2v")
    det = RarityDetector(model.dims, model.vocabs.token_vocab, counts,
                         compute_dtype=model.compute_dtype)
    tv = model.vocabs.token_vocab
    # two one-context methods differing only in token frequency
    common = max(counts, key=counts.get)
    rare = min(counts, key=counts.get)
    C = model.dims.max_contexts

    def one(tok_word):
        t = tv.lookup_index(tok_word)
        src = np.full((C,), tv.pad_index, np.int32)
        src[0] = t
        dst = src.copy()
        pth = np.zeros((C,), np.int32)
        mask = np.zeros((C,), np.float32)
        mask[0] = 1.0
        return src, pth, dst, mask

    assert counts[common] > counts[rare], "flat histogram fixture?"
    assert det.score(model.params, one(rare)) > \
        det.score(model.params, one(common))


def test_rename_augment_semantics(trained):
    import jax
    import jax.numpy as jnp
    from code2vec_tpu.attacks.defense import (legal_token_mask,
                                              make_rename_augment)
    _, model, prefix = trained
    _, methods = _test_methods(model, prefix, 4)
    src = np.stack([m[0] for m in methods])
    pth = np.stack([m[1] for m in methods])
    dst = np.stack([m[2] for m in methods])
    mask = np.stack([m[3] for m in methods])
    labels = np.zeros((len(methods),), np.int32)
    weights = np.ones((len(methods),), np.float32)
    batch = tuple(jnp.asarray(a)
                  for a in (labels, src, pth, dst, mask, weights))
    legal = legal_token_mask(model.vocabs.token_vocab, model.dims)

    # p=0: identity
    out0 = make_rename_augment(legal, 0.0)(batch, jax.random.PRNGKey(0))
    assert np.array_equal(np.asarray(out0[1]), src)
    assert np.array_equal(np.asarray(out0[3]), dst)

    # p=1: one token per example renamed; occurrences consistent
    out1 = make_rename_augment(legal, 1.0)(batch, jax.random.PRNGKey(1))
    src1, dst1 = np.asarray(out1[1]), np.asarray(out1[3])
    for i in range(len(methods)):
        changed = src[i] != src1[i]
        if not changed.any():
            continue  # renamed token can collide with itself
        old = np.unique(src[i][changed])
        new = np.unique(src1[i][changed])
        assert len(old) == 1 and len(new) == 1  # ONE variable renamed
        # every occurrence moved, on both sides
        assert not (src1[i] == old[0]).any()
        assert not (dst1[i] == old[0]).any()
        assert legal[int(new[0])]
        assert legal[int(old[0])]  # never renames OOV/PAD/literals
    # labels/paths/mask untouched
    assert np.array_equal(np.asarray(out1[2]), pth)
    assert np.array_equal(np.asarray(out1[4]), mask)

    # mode="batch": same rename semantics, but the replacement is
    # another example's variable (wrong-class cue injection) — so every
    # introduced token must already occur somewhere in the ORIGINAL
    # batch and be legal (round-4 positive-control defense)
    outb = make_rename_augment(legal, 1.0, mode="batch")(
        batch, jax.random.PRNGKey(2))
    srcb, dstb = np.asarray(outb[1]), np.asarray(outb[3])
    batch_tokens = set(np.unique(np.concatenate(
        [src[mask > 0], dst[mask > 0]])).tolist())
    from_batch = renamed = 0
    for i in range(len(methods)):
        changed = src[i] != srcb[i]
        if not changed.any():
            continue
        new = np.unique(srcb[i][changed])
        assert len(new) == 1
        assert legal[int(new[0])]
        renamed += 1
        from_batch += int(new[0]) in batch_tokens
    # donors with no legal slot fall back to a uniform legal draw
    # (defense.py), so not EVERY replacement must come from the batch —
    # but the distinguishing property of batch mode is that they
    # overwhelmingly do (a uniform draw over the full vocab would land
    # in this tiny batch's token set with negligible probability)
    assert renamed > 0 and from_batch >= max(1, renamed - 1), (
        f"batch-mode replacements not batch-sourced: "
        f"{from_batch}/{renamed}")


def test_adversarial_training_converges(trained):
    _, _, prefix = trained
    cfg = tiny_config(prefix, ADV_RENAME_PROB=0.3)
    model = Code2VecModel(cfg)
    model.train()
    res = model.evaluate()
    assert res.subtoken_f1 > 0.5  # augmented training still learns


@pytest.mark.skipif(not os.path.exists(EXTRACTOR),
                    reason="native extractor not built")
def test_source_level_rename_attack(trained, tmp_path):
    cfg, model, _ = trained
    # identifiers drawn from the synthetic vocab so the attack has
    # in-vocab variables to work with (paths will be OOV — fine)
    java = tmp_path / "Victim.java"
    java.write_text(
        "class Victim {\n"
        "    int foo(int value, int count) {\n"
        "        int index = value + count;\n"
        "        return index * value;\n"
        "    }\n"
        "}\n")
    attack = SourceAttack(cfg, model, max_iters=3)
    res = attack.attack_file(str(java), targeted=False, max_renames=2)
    assert res.attack.original_prediction
    if res.renames:
        assert res.adversarial_source != java.read_text()
        for old, new in res.renames.items():
            assert old not in res.adversarial_source
            assert new in res.adversarial_source
        # the driver re-extracted and re-predicted the rewritten source
        assert isinstance(res.verified_prediction, str)


@pytest.mark.skipif(not os.path.exists(EXTRACTOR),
                    reason="native extractor not built")
def test_source_level_deadcode_attack(trained, tmp_path):
    cfg, model, _ = trained
    java = tmp_path / "Dead.java"
    java.write_text(
        "class Dead {\n"
        "    int foo(int value, int count) {\n"
        "        int index = value + count;\n"
        "        return index;\n"
        "    }\n"
        "}\n")
    attack = SourceAttack(cfg, model, max_iters=3)
    res = attack.attack_file(str(java), targeted=False, deadcode=True)
    # dead-code mode only ever touches the inserted declaration: the
    # original program text survives in the adversarial source
    if res.adversarial_source is not None:
        for line in ("int index = value + count;", "return index;"):
            assert line in res.adversarial_source
        assert "int " in res.adversarial_source


def test_source_scans_are_comment_and_string_aware():
    """Round-4 fix for r3 weak #6: the Java source scans/rewrites must
    ignore comments and string literals (a regex over raw text renamed
    inside strings and counted commented-out declarations)."""
    from code2vec_tpu.attacks.source_attack import (
        code_char_mask, declared_variables, insert_dead_declaration,
        mask_non_code, rename_in_source)

    src = (
        'class C {\n'
        '  // int fakeDecl = 1; value in a comment\n'
        '  /* value multi\n'
        '     line int ghost = 2; */\n'
        '  String s = "value + 1; int strDecl = 3;";\n'
        '  char q = \'v\';\n'
        '  char esc = \'\\\'\';  // escaped quote then value\n'
        '  int compute(int value) {\n'
        '    return value + 1; // value\n'
        '  }\n'
        '}\n')

    mask = code_char_mask(src)
    assert len(mask) == len(src)
    masked = mask_non_code(src)
    # comment/string contents blanked, code intact, offsets preserved
    assert "fakeDecl" not in masked and "ghost" not in masked
    assert "strDecl" not in masked
    assert "int compute(int value)" in masked
    assert len(masked) == len(src)

    # declarations inside comments/strings don't exist
    decls = declared_variables(src)
    assert "value" in decls and "s" in decls and "q" in decls
    assert "fakeDecl" not in decls and "ghost" not in decls
    assert "strDecl" not in decls

    # rename rewrites code occurrences ONLY
    out = rename_in_source(src, "value", "abc")
    assert "int compute(int abc)" in out
    assert "return abc + 1;" in out
    assert '"value + 1; int strDecl = 3;"' in out  # string untouched
    assert "// int fakeDecl = 1; value in a comment" in out
    assert "/* value multi" in out
    assert out.count("abc") == 2

    # dead-code insertion: the commented-out method mention is skipped
    src2 = ('class D {\n'
            '  // compute(int x) { old impl }\n'
            '  int compute(int x) {\n'
            '    return x;\n'
            '  }\n'
            '}\n')
    mod = insert_dead_declaration(src2, "compute", "deadVar")
    assert mod is not None
    assert mod.index("deadVar") > mod.index("return") - 40
    # inserted into the REAL method body, not after the comment
    assert "// compute(int x) { old impl }\n  int compute" in mod


def test_code_mask_handles_java_text_blocks():
    """Java 15 text blocks legally contain unescaped double quotes; the
    scanner must keep their content masked and return to CODE state at
    the closing triple quote (review r4: an embedded quote previously
    flipped the state and exposed/inverted everything after)."""
    from code2vec_tpu.attacks.source_attack import (declared_variables,
                                                    rename_in_source)

    src = ('class T {\n'
           '  String t = """\n'
           '      hello "value" world\n'
           '      """;\n'
           '  int compute(int value) {\n'
           '    return value + 1;\n'
           '  }\n'
           '}\n')
    out = rename_in_source(src, "value", "abc")
    assert 'hello "value" world' in out       # text block untouched
    assert "int compute(int abc)" in out      # code renamed
    assert "return abc + 1;" in out
    # odd quote count inside the block must not invert the mask: the
    # declarations AFTER the block are still seen
    decls = declared_variables(src)
    assert "t" in decls
    assert "value" in decls  # the real parameter, after the block
