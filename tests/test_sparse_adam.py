"""Sparse-row Adam: duplicate-row accumulation correctness, and exact
agreement with the dense-Adam step when every row is touched (lazy ==
dense in that case, including the first step from zero moments)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from code2vec_tpu.models.encoder import ModelDims, init_params
from code2vec_tpu.training.sparse_adam import row_adam_update
from code2vec_tpu.training.sparse_steps import (init_sparse_opt_state,
                                                make_sparse_train_step)
from code2vec_tpu.training.steps import make_train_step

DIMS = ModelDims(token_vocab_size=12, path_vocab_size=10,
                 target_vocab_size=8, embeddings_size=4, max_contexts=5,
                 dropout_keep_rate=1.0)


def test_row_adam_duplicate_ids_accumulate():
    """Duplicate ids must contribute summed gradients, and each touched
    row must receive exactly one Adam update for that sum."""
    V, E = 10, 2
    table = jnp.zeros((V, E), jnp.float32)
    from code2vec_tpu.training.sparse_adam import init_row_adam
    state = init_row_adam(table)
    ids = jnp.asarray([3, 1, 3, 7, 1, 3], dtype=jnp.int32)
    grads = jnp.arange(6 * 2, dtype=jnp.float32).reshape(6, 2)
    out, _ = row_adam_update(table, state, ids, grads,
                             count=jnp.asarray(1, jnp.int32), lr=0.01)
    out = np.asarray(out)
    expected_sums = {1: grads[1] + grads[4],
                     3: grads[0] + grads[2] + grads[5], 7: grads[3]}
    for row in range(V):
        if row not in expected_sums:
            np.testing.assert_allclose(out[row], 0.0)
            continue
        g = np.asarray(expected_sums[row])
        # one Adam step from zero moments with summed gradient g
        m = 0.1 * g
        v = 0.001 * np.square(g)
        lr_t = 0.01 * np.sqrt(1 - 0.999) / (1 - 0.9)
        ref = -lr_t * m / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(out[row], ref, rtol=1e-5)


def test_row_adam_matches_dense_adam_when_all_rows_touched():
    rng = np.random.default_rng(0)
    V, E = 6, 3
    table = jnp.asarray(rng.normal(size=(V, E)).astype(np.float32))
    grad_dense = rng.normal(size=(V, E)).astype(np.float32)

    # dense optax adam, one step
    opt = optax.adam(0.01)
    state = opt.init(table)
    upd, _ = opt.update(jnp.asarray(grad_dense), state, table)
    dense_out = optax.apply_updates(table, upd)

    # sparse: every row appears exactly once
    from code2vec_tpu.training.sparse_adam import init_row_adam
    rstate = init_row_adam(table)
    sparse_out, _ = row_adam_update(
        table, rstate, jnp.arange(V, dtype=jnp.int32),
        jnp.asarray(grad_dense), count=jnp.asarray(1, jnp.int32), lr=0.01)
    np.testing.assert_allclose(np.asarray(sparse_out),
                               np.asarray(dense_out), atol=1e-6)


def _batch(seed, b=8):
    r = np.random.default_rng(seed)
    C = DIMS.max_contexts
    return (r.integers(0, DIMS.target_vocab_size, (b,)).astype(np.int32),
            r.integers(0, DIMS.token_vocab_size, (b, C)).astype(np.int32),
            r.integers(0, DIMS.path_vocab_size, (b, C)).astype(np.int32),
            r.integers(0, DIMS.token_vocab_size, (b, C)).astype(np.int32),
            np.ones((b, C), np.float32), np.ones((b,), np.float32))


def test_sparse_step_first_step_matches_dense_step():
    """From zero moments, untouched rows get zero updates under dense
    Adam too, so step 1 must agree exactly (full-softmax config)."""
    params = init_params(jax.random.PRNGKey(0), DIMS)
    lr = 0.02
    batch = tuple(jnp.asarray(a) for a in _batch(1))
    rng = jax.random.PRNGKey(3)

    dense_step = make_train_step(DIMS, optax.adam(lr))
    p1, _, loss1 = dense_step(jax.tree_util.tree_map(jnp.copy, params),
                              optax.adam(lr).init(params), batch, rng)

    sp_step = make_sparse_train_step(DIMS, learning_rate=lr)
    opt_state = init_sparse_opt_state(params, optax.adam(lr),
                                      use_sampled_softmax=False)
    p2, _, loss2 = sp_step(jax.tree_util.tree_map(jnp.copy, params),
                           opt_state, batch, rng)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)
    for k in p1:
        # scatter-add vs segment-sum accumulate duplicates in different
        # orders; Adam's m/(sqrt(v)+eps) amplifies those ulps for rows
        # with tiny gradients, so agreement is ~1e-4, not exact
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   atol=2e-4, err_msg=k)


def test_sparse_step_sampled_softmax_trains():
    params = init_params(jax.random.PRNGKey(0), DIMS)
    step = make_sparse_train_step(DIMS, learning_rate=0.05,
                                  use_sampled_softmax=True, num_sampled=4)
    opt_state = init_sparse_opt_state(params, optax.adam(0.05),
                                      use_sampled_softmax=True)
    rng = jax.random.PRNGKey(0)
    losses = []
    batch = tuple(jnp.asarray(a) for a in _batch(2))
    for i in range(30):
        rng, k = jax.random.split(rng)
        params, opt_state, loss = step(params, opt_state, batch, k)
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    for k_, v in params.items():
        assert np.all(np.isfinite(np.asarray(v))), k_


def test_sparse_step_on_mesh_matches_single_device():
    from code2vec_tpu.parallel.mesh import make_mesh
    from code2vec_tpu.parallel.sharding import shard_batch, shard_params
    dims = ModelDims(token_vocab_size=12, path_vocab_size=10,
                     target_vocab_size=8, embeddings_size=4,
                     max_contexts=5, dropout_keep_rate=1.0,
                     vocab_pad_multiple=2)
    params = init_params(jax.random.PRNGKey(0), dims)
    batch = tuple(jnp.asarray(a) for a in _batch(3, b=16))
    rng = jax.random.PRNGKey(1)

    step = make_sparse_train_step(dims, learning_rate=0.01)
    o1 = init_sparse_opt_state(params, optax.adam(0.01), False)
    p1, _, loss1 = step(jax.tree_util.tree_map(jnp.copy, params), o1,
                        batch, rng)

    mesh = make_mesh(0, 2)
    sp = shard_params(mesh, params)
    o2 = init_sparse_opt_state(sp, optax.adam(0.01), False)
    sb = shard_batch(mesh, batch)
    # the mesh kwarg routes the apply through mesh_sparse_apply
    # (round 14: the compact dedup/segment-sum/live-row update inside
    # shard_map's manual region — the GSPMD partitioner never sees the
    # composition it miscompiles); this is the layout-invariance check
    # that the mesh and single-device compact paths agree
    step2 = make_sparse_train_step(dims, learning_rate=0.01, mesh=mesh)
    p2, _, loss2 = step2(sp, o2, sb, rng)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   atol=1e-5, err_msg=k)
