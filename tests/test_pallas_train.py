"""The fused Pallas pool under grad (custom VJP) must match the XLA path
in both loss and gradients (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from code2vec_tpu.models.encoder import ModelDims, init_params
from code2vec_tpu.training.steps import make_train_step

DIMS = ModelDims(token_vocab_size=20, path_vocab_size=16,
                 target_vocab_size=12, embeddings_size=8, max_contexts=6,
                 dropout_keep_rate=1.0)


def _batch(b=16):
    r = np.random.default_rng(0)
    C = DIMS.max_contexts
    mask = np.ones((b, C), np.float32)
    mask[0, 3:] = 0.0
    return tuple(jnp.asarray(a) for a in (
        r.integers(0, 12, (b,)).astype(np.int32),
        r.integers(0, 20, (b, C)).astype(np.int32),
        r.integers(0, 16, (b, C)).astype(np.int32),
        r.integers(0, 20, (b, C)).astype(np.int32),
        mask, np.ones((b,), np.float32)))


def test_pallas_train_step_matches_xla_train_step():
    params = init_params(jax.random.PRNGKey(0), DIMS)
    opt = optax.adam(0.01)
    batch = _batch()
    rng = jax.random.PRNGKey(1)

    step_x = make_train_step(DIMS, opt)
    p1, _, loss1 = step_x(jax.tree_util.tree_map(jnp.copy, params),
                          opt.init(params), batch, rng)
    step_p = make_train_step(DIMS, opt, use_pallas=True)
    p2, _, loss2 = step_p(jax.tree_util.tree_map(jnp.copy, params),
                          opt.init(params), batch, rng)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   atol=1e-4, err_msg=k)
