#!/usr/bin/env bash
# Build the native path-context extractor (c2v_extract + libc2v.so).
# Usage: ./build_extractor.sh [--sanitize]
set -euo pipefail
cd "$(dirname "$0")/code2vec_tpu/extractor"
SAN=OFF
if [[ "${1:-}" == "--sanitize" ]]; then SAN=ON; fi
cmake -S . -B build -G Ninja -DC2V_SANITIZE=${SAN} >/dev/null
cmake --build build
echo "built: $(pwd)/build/c2v_extract and libc2v.so"
