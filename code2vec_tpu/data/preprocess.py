"""Offline preprocessing: raw extractor output -> `.c2v` shards + `.dict.c2v`.

Reference parity target: `preprocess.py` (SURVEY.md §3 "Offline
preprocessor", §4.1 call stack): one histogram pass over the train split
counting token / path / target frequencies, then a per-split rewrite that
truncates each method to `max_contexts` contexts (random sample when over),
pads rows to a fixed field count, and writes `<name>.<split>.c2v`; finally
the three count dicts (+ example count) are pickled sequentially into
`<name>.dict.c2v` (SURVEY.md §3.2).

Row format (SURVEY.md §3.2): space-separated; field 0 = target label
(`|`-joined subtokens), fields 1..max_contexts = `left,path,right` with
missing contexts as empty fields.

Usage (reference flag spelling):
  python -m code2vec_tpu.data.preprocess \
      --train_data raw.train.txt --val_data raw.val.txt --test_data raw.test.txt \
      --max_contexts 200 --word_vocab_size 1301136 --path_vocab_size 911417 \
      --target_vocab_size 261245 --output_name data/java-small/java-small
"""

from __future__ import annotations

import argparse
import pickle
import random
from collections import Counter
from typing import Iterable, Optional, Tuple


def parse_raw_line(line: str) -> Optional[Tuple[str, list]]:
    """One extractor output line -> (target_name, [context_str, ...])."""
    parts = line.strip().split(" ")
    if not parts or not parts[0]:
        return None
    return parts[0], [p for p in parts[1:] if p]


def count_histograms(path: str) -> Tuple[Counter, Counter, Counter, int]:
    """The histogram pass (HOT LOOP in the reference, SURVEY.md §4.1)."""
    token_counts: Counter = Counter()
    path_counts: Counter = Counter()
    target_counts: Counter = Counter()
    num_examples = 0
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            parsed = parse_raw_line(line)
            if parsed is None:
                continue
            target, contexts = parsed
            target_counts[target] += 1
            num_examples += 1
            for ctx in contexts:
                fields = ctx.split(",")
                if len(fields) != 3:
                    continue
                left, path_str, right = fields
                token_counts[left] += 1
                token_counts[right] += 1
                path_counts[path_str] += 1
    return token_counts, path_counts, target_counts, num_examples


def process_split(in_path: str, out_path: str, max_contexts: int,
                  rng: random.Random) -> int:
    """Truncate/pad each method row to exactly `max_contexts` context
    fields and write the `.c2v` shard. Returns the number of examples."""
    n = 0
    with open(in_path, "r", encoding="utf-8", errors="replace") as fin, \
            open(out_path, "w", encoding="utf-8") as fout:
        for line in fin:
            parsed = parse_raw_line(line)
            if parsed is None:
                continue
            target, contexts = parsed
            contexts = [c for c in contexts if len(c.split(",")) == 3]
            if len(contexts) > max_contexts:
                contexts = rng.sample(contexts, max_contexts)
            elif len(contexts) < max_contexts:
                contexts = contexts + [""] * (max_contexts - len(contexts))
            fout.write(target + " " + " ".join(contexts) + "\n")
            n += 1
    return n


def save_dictionaries(dict_path: str, token_counts: Counter,
                      path_counts: Counter, target_counts: Counter,
                      num_examples: int) -> None:
    """Sequential-pickle format of the reference's `.dict.c2v`."""
    with open(dict_path, "wb") as f:
        pickle.dump(dict(token_counts), f)
        pickle.dump(dict(path_counts), f)
        pickle.dump(dict(target_counts), f)
        pickle.dump(num_examples, f)


def main(argv: Optional[Iterable[str]] = None) -> None:
    p = argparse.ArgumentParser(description="code2vec-tpu preprocess")
    p.add_argument("--train_data", required=True)
    p.add_argument("--val_data", dest="val_data", default=None)
    p.add_argument("--test_data", dest="test_data", default=None)
    p.add_argument("--max_contexts", type=int, default=200)
    p.add_argument("--word_vocab_size", type=int, default=1301136)
    p.add_argument("--path_vocab_size", type=int, default=911417)
    p.add_argument("--target_vocab_size", type=int, default=261245)
    p.add_argument("--output_name", required=True)
    p.add_argument("--seed", type=int, default=239)
    args = p.parse_args(list(argv) if argv is not None else None)

    rng = random.Random(args.seed)
    token_counts, path_counts, target_counts, _ = count_histograms(
        args.train_data)

    num_train = process_split(args.train_data,
                              f"{args.output_name}.train.c2v",
                              args.max_contexts, rng)
    if args.val_data:
        process_split(args.val_data, f"{args.output_name}.val.c2v",
                      args.max_contexts, rng)
    if args.test_data:
        process_split(args.test_data, f"{args.output_name}.test.c2v",
                      args.max_contexts, rng)

    save_dictionaries(f"{args.output_name}.dict.c2v", token_counts,
                      path_counts, target_counts, num_train)
    print(f"preprocess: wrote {num_train} train examples and dictionaries "
          f"to {args.output_name}.*")


if __name__ == "__main__":
    main()
