"""Offline int-izer: `.c2v` text -> pre-tokenized int32 binary shard.

SURVEY.md §8.3 step 2: host CSV parsing is the #1 throughput risk for the
8x target, so training reads memmapped int32 shards instead of text. The
shard is a [N, 1 + 3*C] int32 matrix per example row:
  col 0                     : target label index
  cols 1        .. C        : source-token indices
  cols 1 +   C  .. 2C       : path indices
  cols 1 + 2*C  .. 3C       : target-token indices
padded positions hold the PAD index; the padding mask is recomputed at read
time as `path != PAD` (a real context always has a path).

A `<prefix>.bin.targets` sidecar stores one raw target string per example
(same order), so evaluation — which needs the ORIGINAL name for subtoken
metrics even when it is OOV in the target vocab — can also ride the
binary fast path instead of seek-per-line text reads.

Usage:
  python -m code2vec_tpu.data.binarize --data prefix  # binarizes
      prefix.{train,val,test}.c2v using prefix.dict.c2v vocabularies
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Optional

import numpy as np

from code2vec_tpu.data.reader import parse_c2v_rows
from code2vec_tpu.vocab.vocabularies import Code2VecVocabs


def binarize_file(c2v_path: str, out_prefix: str, vocabs: Code2VecVocabs,
                  max_contexts: int, chunk: int = 8192) -> int:
    """Stream-convert one `.c2v` file; returns example count."""
    C = max_contexts
    row_width = 1 + 3 * C
    n_total = 0
    tmp_path = out_prefix + ".bin.tmp"
    tgt_tmp = out_prefix + ".bin.targets.tmp"
    with open(c2v_path, "r", encoding="utf-8", errors="replace") as fin, \
            open(tmp_path, "wb") as fout, \
            open(tgt_tmp, "w", encoding="utf-8") as ftgt:
        batch = []
        for line in fin:
            if not line.strip():
                continue
            batch.append(line)
            ftgt.write(line.split(" ", 1)[0].strip() + "\n")
            if len(batch) >= chunk:
                n_total += _write_chunk(batch, fout, vocabs, C, row_width)
                batch = []
        if batch:
            n_total += _write_chunk(batch, fout, vocabs, C, row_width)
    os.replace(tmp_path, out_prefix + ".bin")
    os.replace(tgt_tmp, out_prefix + ".bin.targets")
    with open(out_prefix + ".bin.json", "w") as f:
        json.dump({"num_examples": n_total, "max_contexts": C,
                   "pad_index": vocabs.token_vocab.pad_index,
                   "layout": "label,src*C,path*C,tgt*C", "dtype": "int32"},
                  f)
    return n_total


def _write_chunk(lines, fout, vocabs, C, row_width) -> int:
    labels, src, pth, dst, _mask, _, _ = parse_c2v_rows(lines, vocabs, C)
    rows = np.empty((len(lines), row_width), dtype=np.int32)
    rows[:, 0] = labels
    rows[:, 1:1 + C] = src
    rows[:, 1 + C:1 + 2 * C] = pth
    rows[:, 1 + 2 * C:1 + 3 * C] = dst
    rows.tofile(fout)
    return len(lines)


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(description="code2vec-tpu binarize")
    p.add_argument("--data", required=True,
                   help="dataset prefix (expects <prefix>.{split}.c2v and "
                        "<prefix>.dict.c2v)")
    p.add_argument("--max_contexts", type=int, default=200)
    p.add_argument("--word_vocab_size", type=int, default=1301136)
    p.add_argument("--path_vocab_size", type=int, default=911417)
    p.add_argument("--target_vocab_size", type=int, default=261245)
    args = p.parse_args(argv)

    vocabs = Code2VecVocabs.load_from_dict_file(
        args.data + ".dict.c2v", args.word_vocab_size,
        args.path_vocab_size, args.target_vocab_size)
    for split in ("train", "val", "test"):
        c2v = f"{args.data}.{split}.c2v"
        if os.path.exists(c2v):
            n = binarize_file(c2v, f"{args.data}.{split}", vocabs,
                              args.max_contexts)
            print(f"binarize: {c2v} -> {n} examples")


if __name__ == "__main__":
    main()
