"""Double-buffered device infeed (SURVEY.md §3.3 infeed row:
"fixed-shape int32 [B,200]x3 + f32 mask, double buffered").

The reference's tf.data pipeline prefetches to the GPU; the TPU
equivalent here is a daemon thread that runs the host side of the next
`depth` batches — `.c2v`/binary parsing, padding, and the
host->device `device_put`/`make_array_from_process_local_data` calls —
while the chip executes the current step. jax transfers are themselves
asynchronous, so by the time the train loop pops batch k+1 from the
queue its bytes are already streaming into HBM; the loop never blocks
on the host between steps (VERDICT r3 item 2: the round-3 loop
transferred synchronously inside the step loop, idling the chip on
every host->device copy).

Default depth 2 = classic double buffering: one batch on the chip, one
in flight. Deeper pipelines buy nothing here (the reader's measured
27x headroom means the producer is never the bottleneck) and cost host
RAM at B=8192 shapes.

Multi-host note: each process prefetches its OWN reader shard in
deterministic reader order, and `make_array_from_process_local_data`
is per-process local work, so threading it does not reorder anything
across hosts.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Tuple

_SENTINEL = object()
_EPOCH_END = object()


class _ThreadedInfeed:
    """Shared producer-thread machinery: bounded queue, (sentinel, exc)
    completion protocol, abandoned-iteration shutdown (a consumer that
    exits early — exception in the step, generator GC'd — must release
    the thread and its device-resident batches instead of pinning them
    for the process lifetime). Subclasses implement `_produce(put)`
    (call `put(item)`; stop when it returns False) and `_emit(item)`
    (yield consumer tuples for one queue item). Each __iter__ is one
    epoch: fresh queue + thread, so one instance wraps a re-iterable
    reader across epochs."""

    def __init__(self, depth: int):
        assert depth >= 1
        self._depth = depth
        # optional obs.watchdog Heartbeat: the producer thread beats on
        # every queue-put attempt (a put blocked on a FULL queue still
        # beats — that means the CONSUMER is slow, not the producer
        # stuck) and goes idle when its passes are done, so "infeed
        # producer wedged in parse/transfer" is distinguishable from
        # "nothing left to produce"
        self._heartbeat = None

    def _produce(self, put: Callable) -> None:
        raise NotImplementedError

    def _emit(self, item) -> Iterator[Tuple]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Tuple]:
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        stop = threading.Event()
        heartbeat = self._heartbeat

        def put(item) -> bool:
            # bounded-wait put so shutdown can interrupt a full queue
            while not stop.is_set():
                if heartbeat is not None:
                    heartbeat.beat()
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def run() -> None:
            try:
                self._produce(put)
            except BaseException as e:  # propagate into the consumer
                put((_SENTINEL, e))
            else:
                put((_SENTINEL, None))
            finally:
                # idle LAST (the sentinel put itself beats): a finished
                # producer is exempt from the deadline, not stalled
                if heartbeat is not None:
                    heartbeat.idle()

        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        try:
            while True:
                item = q.get()
                if item[0] is _SENTINEL:
                    thread.join()
                    if item[1] is not None:
                        raise item[1]
                    return
                yield from self._emit(item)
        finally:
            stop.set()
            while thread.is_alive():  # drain so a blocked put returns
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                thread.join(timeout=0.05)


class DevicePrefetcher(_ThreadedInfeed):
    """Iterate `(put_fn(batch), batch)` pairs with the put_fn work done
    up to `depth` batches ahead on the producer thread.

    put_fn is the host->device transfer (e.g. jax_model._device_batch);
    the original host batch rides along because the consumers also need
    host-side fields (num_valid_examples, target_strings).

    Exceptions in the producer surface in the consumer at the position
    they occurred (not silently truncating the epoch)."""

    def __init__(self, batches: Iterable, put_fn: Callable,
                 depth: int = 2):
        super().__init__(depth)
        self._batches = batches
        self._put_fn = put_fn

    def _produce(self, put: Callable) -> None:
        for b in self._batches:
            if not put((self._put_fn(b), b)):
                return

    def _emit(self, item) -> Iterator[Tuple]:
        yield item


class ChunkedDevicePrefetcher(_ThreadedInfeed):
    """Latency-amortizing infeed: group `chunk` host batches, transfer
    them as ONE stacked device array per field, then yield on-device
    slices — N per-batch transfers per epoch become N/chunk.

    This targets HIGH-LATENCY host->device links. Measured on the
    tunneled dev platform (BASELINE.md round 4): each device_put costs
    a ~200 ms round trip regardless of size, making the train loop
    transfer-latency-bound at ~1M pc/s while the device step alone
    runs 6.6M; thread-overlap (DevicePrefetcher) cannot help because
    every dispatch serializes on the one tunnel connection. Stacking
    G batches turns G round trips into one; the per-step device-side
    slice is a ~2 ms dispatch. On a production host (local PCIe,
    sub-ms transfers) plain depth prefetch is the right tool — this
    class is opt-in via --infeed_chunk. Inherently threaded (the
    producer stacks ahead); Config.verify rejects --infeed_prefetch 0
    with chunking so the synchronous A/B control stays unconfounded.

    Single-device only (the stacked array is not mesh-sharded);
    jax_model falls back to DevicePrefetcher when a mesh is active.

    `to_arrays(batch) -> tuple[np.ndarray, ...]` converts a host batch
    to its per-field numpy arrays; `transfer` (default jnp.asarray,
    injectable for tests) moves a stacked field to the device.
    """

    def __init__(self, batches: Iterable, to_arrays: Callable,
                 chunk: int, depth: int = 2, transfer=None):
        assert chunk >= 1
        super().__init__(depth)
        self._batches = batches
        self._to_arrays = to_arrays
        self._chunk = chunk
        self._transfer = transfer

    def _produce(self, put: Callable) -> None:
        import numpy as np
        transfer = self._transfer
        if transfer is None:
            import jax.numpy as jnp
            transfer = jnp.asarray

        def ship(hosts, rows) -> bool:
            stacked = tuple(
                transfer(np.stack([r[f] for r in rows]))
                for f in range(len(rows[0])))
            return put((stacked, hosts))

        hosts, rows = [], []
        for b in self._batches:
            hosts.append(b)
            rows.append(self._to_arrays(b))
            if len(rows) == self._chunk:
                if not ship(hosts, rows):
                    return
                hosts, rows = [], []
        if rows:  # partial tail chunk
            ship(hosts, rows)

    def _emit(self, item) -> Iterator[Tuple]:
        stacked, hosts = item
        for i, host in enumerate(hosts):
            yield tuple(a[i] for a in stacked), host


class _SyncInfeed:
    """depth=0: synchronous transfer in the caller's loop (the round-3
    behavior, kept for A/B measurement via --infeed_prefetch 0).
    Re-iterable like DevicePrefetcher so epoch loops treat both alike."""

    def __init__(self, batches: Iterable, put_fn: Callable):
        self._batches = batches
        self._put_fn = put_fn

    def __iter__(self) -> Iterator[Tuple]:
        for b in self._batches:
            yield self._put_fn(b), b


def prefetch_to_device(batches: Iterable, put_fn: Callable,
                       depth: int = 2) -> Iterable[Tuple]:
    if depth <= 0:
        return _SyncInfeed(batches, put_fn)
    return DevicePrefetcher(batches, put_fn, depth)


def persistent_epochs(infeed, num_epochs: int, first_epoch: int = 1
                      ) -> Iterator[Tuple[int, Iterator[Tuple]]]:
    """Keep the infeed producer WARM across epoch boundaries.

    Yields `(epoch, epoch_batches)` pairs for epochs
    `first_epoch..num_epochs` (1-based; `first_epoch > 1` is the
    auto-resume path — a restarted run trains only the epochs its
    killed predecessor had not finished, with the reader's
    `epoch_offset` replaying the matching shuffle stream). For a threaded
    infeed, ONE producer thread runs all `num_epochs` passes over the
    reader back-to-back, separating them with an epoch-end marker in
    the shared queue — so while the consumer is doing epoch-boundary
    work (checkpoint save, eval), the producer is already parsing and
    transferring epoch k+1's first batches instead of cold-restarting a
    fresh thread and re-filling the double buffer from scratch.
    Per-epoch shuffle semantics are preserved exactly: each pass is one
    `iter(reader)`, which advances the reader's `_epoch` counter and
    draws that epoch's seeded permutation, same as the cold path.

    The synchronous A/B control (`--infeed_prefetch 0` -> _SyncInfeed)
    re-iterates cold per epoch — persistence is inherently threaded and
    must not confound the no-thread measurement.

    The consumer must drain each epoch's iterator before taking the
    next pair (a `for` over the pair's iterator does); abandoning the
    generator mid-run (exception in the step loop) releases the
    producer thread and its device-resident batches via the `finally`
    drain, exactly like `_ThreadedInfeed.__iter__`.
    """
    epochs = range(first_epoch, num_epochs + 1)
    if not isinstance(infeed, _ThreadedInfeed):
        for epoch in epochs:
            yield epoch, iter(infeed)
        return

    q: queue.Queue = queue.Queue(maxsize=infeed._depth)
    stop = threading.Event()
    heartbeat = infeed._heartbeat

    def put(item) -> bool:
        while not stop.is_set():
            if heartbeat is not None:
                heartbeat.beat()
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def run() -> None:
        try:
            for _ in epochs:
                infeed._produce(put)
                if not put((_EPOCH_END, None)):
                    return
        except BaseException as e:  # surfaces at the consumer position
            put((_SENTINEL, e))
        else:
            put((_SENTINEL, None))
        finally:
            # idle LAST (the sentinel put itself beats): the producer
            # finishing all passes is exempt, not stalled
            if heartbeat is not None:
                heartbeat.idle()

    thread = threading.Thread(target=run, daemon=True,
                              name="train-infeed")
    thread.start()
    finished = threading.Event()  # producer exhausted (error or done):
    #                               later epochs must not block on q.get

    def epoch_iter() -> Iterator[Tuple]:
        if finished.is_set():
            return
        while True:
            item = q.get()
            if item[0] is _EPOCH_END:
                return
            if item[0] is _SENTINEL:
                finished.set()
                thread.join()
                if item[1] is not None:
                    raise item[1]
                return
            yield from infeed._emit(item)

    try:
        for epoch in epochs:
            yield epoch, epoch_iter()
    finally:
        stop.set()
        while thread.is_alive():  # drain so a blocked put returns
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            thread.join(timeout=0.05)


def build_train_infeed(reader: Iterable, *, chunk: int, depth: int,
                       mesh, host_arrays_fn: Callable,
                       device_batch_fn: Callable,
                       log: Callable, instrument: Callable = None,
                       heartbeat=None) -> Iterable[Tuple]:
    """The train-loop infeed both model heads share: chunked
    (latency-amortizing, single-device only) when --infeed_chunk > 1,
    else depth-prefetched; logs instead of silently ignoring the chunk
    request when a mesh forces the fallback.

    `instrument` (ISSUE 6 tracing) wraps the per-batch producer-side
    function — it runs on the PRODUCER thread once per batch, so the
    model can emit an `infeed/produce` span and send its context down
    a SpanChannel without changing the queue's item shape. `heartbeat`
    is the producer's obs.watchdog Heartbeat (beaten on every queue
    put attempt). Both default to off and cost nothing when unset.

    The `infeed/produce` failpoint (ISSUE 10, armed via --faults)
    wraps the same seam: an injected raise happens ON the producer
    thread and surfaces at the consumer through the existing
    sentinel/exception protocol — exactly the path a real parse or
    transfer failure takes. Only the per-batch function the CHOSEN
    infeed actually calls is wrapped, so the site counts exactly one
    hit per batch (the spec's `at`/`prob` semantics). Disarmed,
    nothing is wrapped."""
    use_chunked = chunk > 1 and mesh is None
    from code2vec_tpu.resilience import faults
    fp = faults.point("infeed/produce")
    if fp.armed:
        def _faulted(fn, _fp=fp):
            def wrapped(b):
                _fp.fire()
                return fn(b)
            return wrapped
        if use_chunked:
            host_arrays_fn = _faulted(host_arrays_fn)
        else:
            device_batch_fn = _faulted(device_batch_fn)
    if instrument is not None:
        host_arrays_fn = instrument(host_arrays_fn)
        device_batch_fn = instrument(device_batch_fn)
    if use_chunked:
        infeed = ChunkedDevicePrefetcher(reader, host_arrays_fn, chunk,
                                         depth=max(1, depth))
        infeed._heartbeat = heartbeat
        return infeed
    if chunk > 1:
        log("--infeed_chunk ignored: chunked infeed is single-device "
            "only (mesh active); using depth prefetch")
    infeed = prefetch_to_device(reader, device_batch_fn, depth)
    if isinstance(infeed, _ThreadedInfeed):
        infeed._heartbeat = heartbeat
    return infeed
