"""Double-buffered device infeed (SURVEY.md §3.3 infeed row:
"fixed-shape int32 [B,200]x3 + f32 mask, double buffered").

The reference's tf.data pipeline prefetches to the GPU; the TPU
equivalent here is a daemon thread that runs the host side of the next
`depth` batches — `.c2v`/binary parsing, padding, and the
host->device `device_put`/`make_array_from_process_local_data` calls —
while the chip executes the current step. jax transfers are themselves
asynchronous, so by the time the train loop pops batch k+1 from the
queue its bytes are already streaming into HBM; the loop never blocks
on the host between steps (VERDICT r3 item 2: the round-3 loop
transferred synchronously inside the step loop, idling the chip on
every host->device copy).

Default depth 2 = classic double buffering: one batch on the chip, one
in flight. Deeper pipelines buy nothing here (the reader's measured
27x headroom means the producer is never the bottleneck) and cost host
RAM at B=8192 shapes.

Multi-host note: each process prefetches its OWN reader shard in
deterministic reader order, and `make_array_from_process_local_data`
is per-process local work, so threading it does not reorder anything
across hosts.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Tuple

_SENTINEL = object()


class DevicePrefetcher:
    """Iterate `(put_fn(batch), batch)` pairs with the put_fn work done
    up to `depth` batches ahead on a daemon thread.

    put_fn is the host->device transfer (e.g. jax_model._device_batch);
    the original host batch rides along because the consumers also need
    host-side fields (num_valid_examples, target_strings).

    Exceptions in the producer thread surface in the consumer at the
    position they occurred (not silently truncating the epoch).
    """

    def __init__(self, batches: Iterable, put_fn: Callable,
                 depth: int = 2):
        assert depth >= 1
        self._batches = batches
        self._put_fn = put_fn
        self._depth = depth

    # -- consumer (each __iter__ = one epoch: fresh queue + thread, so
    # the same prefetcher can wrap a re-iterable reader across epochs) --
    def __iter__(self) -> Iterator[Tuple]:
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        stop = threading.Event()

        def put(item) -> bool:
            # bounded-wait put so an ABANDONED iteration (consumer loop
            # exited early — exception in the train step, generator
            # GC'd) releases the thread and its device-resident batches
            # instead of pinning them for the process lifetime
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce() -> None:
            try:
                for b in self._batches:
                    if not put((self._put_fn(b), b)):
                        return
            except BaseException as e:  # propagate into the consumer
                put((_SENTINEL, e))
                return
            put((_SENTINEL, None))

        thread = threading.Thread(target=produce, daemon=True)
        thread.start()
        try:
            while True:
                dev, host = q.get()
                if dev is _SENTINEL:
                    thread.join()
                    if host is not None:
                        raise host
                    return
                yield dev, host
        finally:
            stop.set()
            while thread.is_alive():  # drain so a blocked put returns
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                thread.join(timeout=0.05)


class _SyncInfeed:
    """depth=0: synchronous transfer in the caller's loop (the round-3
    behavior, kept for A/B measurement via --infeed_prefetch 0).
    Re-iterable like DevicePrefetcher so epoch loops treat both alike."""

    def __init__(self, batches: Iterable, put_fn: Callable):
        self._batches = batches
        self._put_fn = put_fn

    def __iter__(self) -> Iterator[Tuple]:
        for b in self._batches:
            yield self._put_fn(b), b


def prefetch_to_device(batches: Iterable, put_fn: Callable,
                       depth: int = 2) -> Iterable[Tuple]:
    if depth <= 0:
        return _SyncInfeed(batches, put_fn)
    return DevicePrefetcher(batches, put_fn, depth)
