"""VarMisuse dataset generation: synthetic-bug Java methods through the
native extractor.

BASELINE.json configs[3]. Each example is a Java method in which ONE
variable use-site is replaced by the `slotvar` hole marker; the task is
to point at the variable that belongs there among the method's
candidates. Variables get role-consistent names and use-sites (counters
in loop headers, accumulators in `x = x + ...`, limits in comparisons,
flags in conditionals, results in returns), so the hole's path-contexts
genuinely determine the answer — the same signal real VarMisuse corpora
carry (role-aware usage), scaled down.

Row format (`.vm.c2v`):
    <label_idx> <cand_1,...,cand_K> <ctx> <ctx> ...
label_idx indexes the candidate list; candidates are normalized tokens;
contexts are standard `left,pathHash,right` triples from the extractor.
"""

from __future__ import annotations

import random
import re
from typing import List, Tuple

from code2vec_tpu.models.varmisuse import SLOT_TOKEN

ROLE_NAMES = {
    "counter": ["i", "j", "k", "idx", "pos", "cursor"],
    "accumulator": ["total", "sum", "acc", "agg", "tally"],
    "limit": ["limit", "bound", "size", "len", "cap"],
    "flag": ["flag", "valid", "done", "ready", "ok"],
    "result": ["result", "out", "res", "answer", "value"],
}
ROLES = list(ROLE_NAMES)


def make_vm_source(rng: random.Random
                   ) -> Tuple[str, List[str], int]:
    """One method with a hole. Returns (java_source, candidates,
    label_index): candidates are the method's variable names (shuffled),
    label_index points at the variable the hole replaces."""
    names = {role: rng.choice(opts) for role, opts in ROLE_NAMES.items()}
    counter, accum = names["counter"], names["accumulator"]
    limit, flag, result = names["limit"], names["flag"], names["result"]

    # every var has role-typical use sites; one site becomes the hole
    sites = {
        "counter_cond": f"{counter} < {limit}",
        "counter_inc": f"{counter} = {counter} + 1",
        "accum_add": f"{accum} = {accum} + {counter}",
        "flag_check": f"if ({flag} > 0) {{ {accum} = {accum} * 2; }}",
        "result_set": f"{result} = {accum} + {flag}",
    }
    hole_role, hole_site = rng.choice([
        ("counter", "counter_cond"), ("counter", "counter_inc"),
        ("accumulator", "accum_add"), ("flag", "flag_check"),
        ("limit", "counter_cond"), ("result", "result_set"),
        ("accumulator", "result_set"),
    ])
    hole_var = names[hole_role]
    # replace exactly one whole-token occurrence of the hole variable
    # (identifier-boundary regex: 'i' inside 'limit' must not match)
    parts = re.split(rf"\b{re.escape(hole_var)}\b", sites[hole_site])
    assert len(parts) >= 2, (hole_site, hole_var)
    occ = rng.randrange(len(parts) - 1)
    sites[hole_site] = (hole_var.join(parts[:occ + 1]) + SLOT_TOKEN
                        + hole_var.join(parts[occ + 1:]))

    body = [
        f"int method{rng.randrange(10_000)}(int {limit}, int {flag}) {{",
        f"  int {accum} = 0;",
        f"  int {result} = 0;",
        f"  for (int {counter} = 0; {sites['counter_cond']}; "
        f"{sites['counter_inc']}) {{",
        f"    {sites['accum_add']};",
        f"    {sites['flag_check']}",
        "  }",
        f"  {sites['result_set']};",
        f"  return {result};",
        "}",
    ]
    source = ("class VM {\n" + "\n".join("  " + ln for ln in body)
              + "\n}\n")
    candidates = [counter, accum, limit, flag, result]
    rng.shuffle(candidates)
    return source, candidates, candidates.index(hole_var)


def make_vm_rows(n: int, seed: int = 0,
                 extract=None) -> List[str]:
    """n `.vm.c2v` rows. `extract` maps java source -> extractor output
    lines (defaults to the native C++ extractor)."""
    if extract is None:
        from code2vec_tpu.extractor import native

        def extract(src: str) -> List[str]:
            return native.extract_source(src)

    rng = random.Random(seed)
    rows = []
    while len(rows) < n:
        source, candidates, label = make_vm_source(rng)
        lines = extract(source)
        if not lines:
            continue
        # one method per class -> one line; drop the method-name field
        contexts = lines[0].split(" ")[1:]
        if not any(SLOT_TOKEN in c for c in contexts):
            continue  # hole optimized away by extraction; rare
        rows.append(f"{label} {','.join(candidates)} "
                    + " ".join(contexts))
    return rows


def write_vm_dataset(out_prefix: str, n_train: int, n_val: int,
                     n_test: int, seed: int = 0,
                     extract=None) -> None:
    for split, n, s in (("train", n_train, seed),
                        ("val", n_val, seed + 1),
                        ("test", n_test, seed + 2)):
        rows = make_vm_rows(n, seed=s, extract=extract)
        with open(f"{out_prefix}.{split}.vm.c2v", "w") as f:
            f.write("\n".join(rows) + "\n")
