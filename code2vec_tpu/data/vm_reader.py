"""Reader for `.vm.c2v` VarMisuse rows (data/varmisuse_gen.py format):

    <label_idx> <cand_1,...,cand_K> <ctx> <ctx> ...

Streams via the same offset machinery as data/reader.py's C2VTextReader
(subclass: shuffle, host sharding, and multi-host aligned batch counts
come from there — VM files can be production-scale without slurping the
host's memory). Rows whose true candidate falls beyond `max_candidates`
get `row_valid = 0` so they are excluded from the loss instead of
training toward a wrong candidate.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np

from code2vec_tpu.data.reader import C2VTextReader
from code2vec_tpu.vocab.vocabularies import Code2VecVocabs


class VMBatch(NamedTuple):
    label: np.ndarray           # int32 [B] index into candidates
    path_source_token_indices: np.ndarray  # int32 [B, C]
    path_indices: np.ndarray    # int32 [B, C]
    path_target_token_indices: np.ndarray  # int32 [B, C]
    context_valid_mask: np.ndarray  # f32 [B, C]
    cand_ids: np.ndarray        # int32 [B, K] token-vocab ids
    cand_mask: np.ndarray       # f32 [B, K]
    row_valid: np.ndarray       # f32 [B]; 0 = drop from loss/metrics
    num_valid_examples: int
    cand_strings: List[List[str]]


def parse_vm_rows(lines: List[str], vocabs: Code2VecVocabs,
                  max_contexts: int, max_candidates: int):
    n = len(lines)
    tok_v, path_v = vocabs.token_vocab, vocabs.path_vocab
    labels = np.zeros((n,), np.int32)
    src = np.full((n, max_contexts), tok_v.pad_index, np.int32)
    pth = np.full((n, max_contexts), path_v.pad_index, np.int32)
    dst = np.full((n, max_contexts), tok_v.pad_index, np.int32)
    mask = np.zeros((n, max_contexts), np.float32)
    cand = np.full((n, max_candidates), tok_v.pad_index, np.int32)
    cand_mask = np.zeros((n, max_candidates), np.float32)
    row_valid = np.ones((n,), np.float32)
    cand_strings: List[List[str]] = []
    for i, line in enumerate(lines):
        parts = line.rstrip("\n").split(" ")
        labels[i] = int(parts[0])
        cands = [c for c in parts[1].split(",") if c][:max_candidates]
        cand_strings.append(cands)
        for k, c in enumerate(cands):
            cand[i, k] = tok_v.lookup_index(c)
            cand_mask[i, k] = 1.0
        if labels[i] >= len(cands):
            # true candidate truncated away: keep the label in-range for
            # jit but exclude the row from loss/metrics entirely
            labels[i] = 0
            row_valid[i] = 0.0
        for j, ctx in enumerate(parts[2:2 + max_contexts]):
            fields = ctx.split(",")
            if len(fields) != 3 or not fields[1]:
                continue
            src[i, j] = tok_v.lookup_index(fields[0])
            pth[i, j] = path_v.lookup_index(fields[1])
            dst[i, j] = tok_v.lookup_index(fields[2])
            mask[i, j] = 1.0
    return (labels, src, pth, dst, mask, cand, cand_mask, row_valid,
            cand_strings)


class VMTextReader(C2VTextReader):
    """Offset-streaming reader over a `.vm.c2v` file."""

    def __init__(self, path: str, vocabs: Code2VecVocabs,
                 max_contexts: int, max_candidates: int, batch_size: int,
                 shuffle: bool = False, seed: int = 0,
                 host_shard: int = 0, num_host_shards: int = 1,
                 epoch_offset: int = 0):
        super().__init__(path, vocabs, max_contexts, batch_size,
                         shuffle=shuffle, seed=seed,
                         host_shard=host_shard,
                         num_host_shards=num_host_shards,
                         epoch_offset=epoch_offset)
        self.max_candidates = max_candidates

    def _parse_batch(self, batch_lines: List[str]) -> VMBatch:
        (labels, src, pth, dst, mask, cand, cand_mask, row_valid,
         cand_strings) = parse_vm_rows(batch_lines, self.vocabs,
                                       self.max_contexts,
                                       self.max_candidates)
        nv = len(batch_lines)
        pad = self.batch_size - nv
        if pad:
            tokp = self.vocabs.token_vocab.pad_index
            pthp = self.vocabs.path_vocab.pad_index
            labels = np.pad(labels, (0, pad))
            src = np.pad(src, ((0, pad), (0, 0)), constant_values=tokp)
            pth = np.pad(pth, ((0, pad), (0, 0)), constant_values=pthp)
            dst = np.pad(dst, ((0, pad), (0, 0)), constant_values=tokp)
            mask = np.pad(mask, ((0, pad), (0, 0)))
            cand = np.pad(cand, ((0, pad), (0, 0)), constant_values=tokp)
            cand_mask = np.pad(cand_mask, ((0, pad), (0, 0)))
            row_valid = np.pad(row_valid, (0, pad))
            # padded rows need one unmasked candidate so softmax stays
            # finite; row_valid/weights zero them out of the loss
            cand_mask[nv:, 0] = 1.0
        return VMBatch(labels, src, pth, dst, mask, cand, cand_mask,
                       row_valid, nv, cand_strings)

    def _empty_batch(self) -> VMBatch:
        B, C, K = self.batch_size, self.max_contexts, self.max_candidates
        tokp = self.vocabs.token_vocab.pad_index
        pthp = self.vocabs.path_vocab.pad_index
        cm = np.zeros((B, K), np.float32)
        cm[:, 0] = 1.0
        return VMBatch(
            np.zeros((B,), np.int32),
            np.full((B, C), tokp, np.int32),
            np.full((B, C), pthp, np.int32),
            np.full((B, C), tokp, np.int32),
            np.zeros((B, C), np.float32),
            np.full((B, K), tokp, np.int32), cm,
            np.zeros((B,), np.float32), 0, [])


def build_vm_vocabs(train_path: str, max_token_vocab: int,
                    max_path_vocab: int) -> Code2VecVocabs:
    """VarMisuse vocabularies from the training rows themselves (tokens
    + paths; the 'target' vocab is the candidate pointer space, so the
    target table is unused — kept minimal)."""
    from collections import Counter

    from code2vec_tpu.vocab.vocabularies import Vocab, VocabType

    tok_counts: Counter = Counter()
    path_counts: Counter = Counter()
    with open(train_path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.strip().split(" ")
            if len(parts) < 3:
                continue
            for c in parts[1].split(","):
                if c:
                    tok_counts[c] += 1
            for ctx in parts[2:]:
                fields = ctx.split(",")
                if len(fields) != 3 or not fields[1]:
                    continue
                tok_counts[fields[0]] += 1
                tok_counts[fields[2]] += 1
                path_counts[fields[1]] += 1
    return Code2VecVocabs(
        Vocab.create_from_freq_dict(VocabType.Token, tok_counts,
                                    max_token_vocab),
        Vocab.create_from_freq_dict(VocabType.Path, path_counts,
                                    max_path_vocab),
        Vocab.create_from_freq_dict(VocabType.Target, {"method": 1}, 10))
