from code2vec_tpu.data.reader import (  # noqa: F401
    BatchTensors, C2VTextReader, BinaryShardReader, open_reader)
