"""Host-side input pipeline: `.c2v` text / binary shards -> fixed-shape
int32 batches + padding mask.

Reference parity target: `path_context_reader.py` (SURVEY.md §2 L3, §3):
`PathContextReader` yielding `ReaderInputTensors` (target idx, three
[B, MAX_CONTEXTS] context index tensors, `context_valid_mask`, plus string
fields for eval/predict). TPU-first differences:

- No tf.data graph; the host produces numpy arrays with STATIC shapes
  (the final short batch is padded and carries `num_valid`) so the jitted
  step never re-traces.
- The fast path is pre-binarized int32 shards (data/binarize.py) read via
  np.memmap — CSV/string parsing on the host is the #1 throughput risk for
  the 8x target (SURVEY.md §8.3 step 2).
- Shuffle is a GLOBAL index permutation per epoch, seeded for
  reproducibility; each host then takes its strided slice of the
  permuted order. Host h's batch t is rows perm[h::H][tB:(t+1)B], so
  the union across hosts at step t is the contiguous block
  perm[H·tB : H·(t+1)B] — the global data order is a function of
  (seed, epoch) ALONE, independent of the host count (ISSUE 13: an
  elastically re-formed cohort replays the same global stream a
  same-size uninterrupted run would).
- `host_shard` / `num_host_shards` slice the example space for multi-host
  feeding (each host feeds its local devices; SURVEY.md §3.3 "Infeed").
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Iterator, List, NamedTuple, Optional

import numpy as np

from code2vec_tpu.vocab.vocabularies import Code2VecVocabs


class BatchTensors(NamedTuple):
    """One host batch. Shapes are static: [B] / [B, C]."""
    target_index: np.ndarray            # int32 [B]
    path_source_token_indices: np.ndarray  # int32 [B, C]
    path_indices: np.ndarray            # int32 [B, C]
    path_target_token_indices: np.ndarray  # int32 [B, C]
    context_valid_mask: np.ndarray      # float32 [B, C]; 1.0 = real context
    num_valid_examples: int             # <= B; B unless final padded batch
    target_strings: Optional[List[str]] = None   # eval/predict only
    context_strings: Optional[List[List[str]]] = None  # predict only


def parse_c2v_rows(lines: List[str], vocabs: Code2VecVocabs,
                   max_contexts: int, keep_strings: bool = False,
                   sample_seed: int = 0):
    """Vectorized-enough parse of `.c2v` rows into index arrays.

    A context field is `left,path,right`; empty ('' or ',,') fields are
    padding (PAD index, mask 0). OOV words map to the OOV index
    (SURVEY.md §3.2). Rows with more than `max_contexts` contexts (raw
    extractor output on the predict path — preprocessed files are already
    capped) are downsampled uniformly without replacement, matching the
    reference preprocess behavior (SURVEY.md §3 preprocess row: "truncate
    each method's contexts to 200 (random sample when over)"); seeded for
    reproducible predictions.
    """
    n = len(lines)
    tok_v, path_v, tgt_v = (vocabs.token_vocab, vocabs.path_vocab,
                            vocabs.target_vocab)
    labels = np.zeros((n,), dtype=np.int32)
    src = np.full((n, max_contexts), tok_v.pad_index, dtype=np.int32)
    pth = np.full((n, max_contexts), path_v.pad_index, dtype=np.int32)
    dst = np.full((n, max_contexts), tok_v.pad_index, dtype=np.int32)
    mask = np.zeros((n, max_contexts), dtype=np.float32)
    target_strings: List[str] = []
    context_strings: List[List[str]] = []
    for i, line in enumerate(lines):
        parts = line.rstrip("\n").split(" ")
        target = parts[0]
        labels[i] = tgt_v.lookup_index(target)
        ctxs = parts[1:]
        if len(ctxs) > max_contexts:
            # drop pad fields ('' / ',,' — preprocess pads rows to a fixed
            # width) before sampling so only REAL contexts compete for
            # the max_contexts slots
            real = [c for c in ctxs if c and c != ",,"]
            if len(real) > max_contexts:
                # sample from the row's SORTED context bag with a seed
                # derived from that same bag — not from batch position
                # or context order: the same method must keep the same
                # contexts wherever (and however ordered) it appears,
                # so the serving cache — keyed by exactly this
                # normalized bag — stays deterministic. The bag encoder
                # is order-invariant, so emitting the sample in sorted
                # order loses nothing.
                canon = sorted(real)
                rng = np.random.default_rng(
                    (sample_seed,
                     zlib.crc32(" ".join(canon).encode("utf-8"))))
                pick = np.sort(rng.choice(len(canon), size=max_contexts,
                                          replace=False))
                real = [canon[k] for k in pick]
            ctxs = real
        if keep_strings:
            target_strings.append(target)
            context_strings.append(ctxs)
        for j, ctx in enumerate(ctxs):
            if not ctx or ctx == ",,":
                continue
            fields = ctx.split(",")
            if len(fields) != 3 or not fields[1]:
                continue
            src[i, j] = tok_v.lookup_index(fields[0])
            pth[i, j] = path_v.lookup_index(fields[1])
            dst[i, j] = tok_v.lookup_index(fields[2])
            mask[i, j] = 1.0
    return labels, src, pth, dst, mask, target_strings, context_strings


def _aligned_num_batches(global_examples: int, num_host_shards: int,
                         batch_size: int) -> int:
    """Number of batches EVERY host must emit per epoch.

    Round-robin sharding gives hosts shard sizes differing by at most 1,
    so the largest shard has ceil(N/H) examples. Hosts with fewer batches
    pad with empty (all-weight-zero) batches so every host joins the same
    number of collective steps — otherwise the epoch deadlocks on the
    host that runs one extra SPMD step.
    """
    largest_shard = -(-global_examples // num_host_shards)
    return -(-largest_shard // batch_size)


def steps_per_epoch(num_examples: int, batch_size: int,
                    num_host_shards: int = 1) -> int:
    """Train steps one epoch takes on every host — the public form of
    `_aligned_num_batches` (and the same ceil-div the LR-schedule
    horizon uses in training/optimizers.schedule_total_steps). The
    resume path divides a restored step count by this to recover how
    many epochs a killed run had completed."""
    return _aligned_num_batches(num_examples, num_host_shards,
                                batch_size)


def _pad_batch(arrs, batch_size: int):
    """Pad along axis 0 to `batch_size` by repeating zeros/PAD rows."""
    out = []
    for a in arrs:
        pad = batch_size - a.shape[0]
        if pad > 0:
            a = np.concatenate(
                [a, np.zeros((pad,) + a.shape[1:], dtype=a.dtype)], axis=0)
        out.append(a)
    return out


class C2VTextReader:
    """Slow-path reader over a `.c2v` text file (drop-in compatibility
    with reference-produced data)."""

    def __init__(self, path: str, vocabs: Code2VecVocabs, max_contexts: int,
                 batch_size: int, shuffle: bool = False, seed: int = 0,
                 keep_strings: bool = False,
                 host_shard: int = 0, num_host_shards: int = 1,
                 epoch_offset: int = 0):
        self.path = path
        self.vocabs = vocabs
        self.max_contexts = max_contexts
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.keep_strings = keep_strings
        self.host_shard = host_shard
        self.num_host_shards = num_host_shards
        # epoch_offset: an auto-resumed run starts its shuffle stream
        # at the epoch it was killed in, not back at epoch 0 — the
        # permutation is seeded `seed + _epoch`, so resume replays the
        # EXACT data order the uninterrupted run would have used
        self._epoch = epoch_offset
        self._offsets: Optional[np.ndarray] = None

    def _line_offsets(self) -> np.ndarray:
        """Byte offsets of non-empty lines (built once; the file itself is
        never held in memory — reference-scale .c2v files are tens of GB,
        so whole-file reads would OOM the host)."""
        if self._offsets is None:
            offsets = []
            with open(self.path, "rb") as f:
                pos = 0
                for raw in f:
                    if raw.strip():
                        offsets.append(pos)
                    pos += len(raw)
            self._offsets = np.asarray(offsets, dtype=np.int64)
        return self._offsets

    def __iter__(self) -> Iterator[BatchTensors]:
        offsets = self._line_offsets()
        # GLOBAL permutation first, host-shard slice second (ISSUE 13):
        # the epoch's data order is fixed by (seed, epoch) before any
        # host claims its rows, so a resize changes only how the one
        # global stream is dealt out — not what the stream is
        order = np.arange(len(offsets))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(order)
            self._epoch += 1
        mine = order[self.host_shard::self.num_host_shards]
        emitted = 0
        with open(self.path, "r", encoding="utf-8", errors="replace") as f:
            for start in range(0, len(mine), self.batch_size):
                idx = mine[start:start + self.batch_size]
                batch_lines = []
                for off in offsets[idx]:
                    f.seek(off)
                    batch_lines.append(f.readline())
                emitted += 1
                yield self._parse_batch(batch_lines)
        if self.num_host_shards > 1:
            target = _aligned_num_batches(len(self._line_offsets()),
                                          self.num_host_shards,
                                          self.batch_size)
            for _ in range(target - emitted):
                yield self._empty_batch()

    # Subclasses (e.g. the VarMisuse reader) override these two to reuse
    # the offset-streaming / shuffle / host-shard / aligned-batch loop
    # above with a different row format.
    def _parse_batch(self, batch_lines: List[str]) -> BatchTensors:
        labels, src, pth, dst, mask, tstr, cstr = parse_c2v_rows(
            batch_lines, self.vocabs, self.max_contexts,
            self.keep_strings)
        nv = len(batch_lines)
        labels, src, pth, dst, mask = _pad_batch(
            (labels, src, pth, dst, mask), self.batch_size)
        return BatchTensors(labels, src, pth, dst, mask, nv,
                            tstr if self.keep_strings else None,
                            cstr if self.keep_strings else None)

    def _empty_batch(self) -> BatchTensors:
        B, C = self.batch_size, self.max_contexts
        return BatchTensors(
            np.zeros((B,), np.int32),
            np.full((B, C), self.vocabs.token_vocab.pad_index, np.int32),
            np.full((B, C), self.vocabs.path_vocab.pad_index, np.int32),
            np.full((B, C), self.vocabs.token_vocab.pad_index, np.int32),
            np.zeros((B, C), np.float32), 0,
            [] if self.keep_strings else None,
            [] if self.keep_strings else None)


class BinaryShardReader:
    """Fast-path reader over the pre-tokenized int32 shard written by
    data/binarize.py: a memmapped [N, 1 + 3*C] int32 matrix
    (label, src*C, path*C, tgt*C) + a JSON manifest."""

    def __init__(self, prefix: str, batch_size: int, shuffle: bool = False,
                 seed: int = 0, host_shard: int = 0,
                 num_host_shards: int = 1,
                 expected_max_contexts: Optional[int] = None,
                 keep_strings: bool = False, epoch_offset: int = 0):
        with open(prefix + ".bin.json", "r") as f:
            self.manifest = json.load(f)
        self.target_strings: Optional[List[str]] = None
        if keep_strings:
            # sidecar written by binarize: original target names, needed
            # for subtoken metrics (OOV targets collapse in the vocab)
            with open(prefix + ".bin.targets", encoding="utf-8") as f:
                self.target_strings = [ln.rstrip("\n") for ln in f]
        self.max_contexts = int(self.manifest["max_contexts"])
        if (expected_max_contexts is not None
                and expected_max_contexts != self.max_contexts):
            raise ValueError(
                f"binary shard {prefix}.bin was built with max_contexts="
                f"{self.max_contexts} but the run requests "
                f"{expected_max_contexts}; re-binarize or match the flag")
        self.num_examples = int(self.manifest["num_examples"])
        row_width = 1 + 3 * self.max_contexts
        self.data = np.memmap(prefix + ".bin", dtype=np.int32, mode="r",
                              shape=(self.num_examples, row_width))
        self.pad_index = int(self.manifest["pad_index"])
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.host_shard = host_shard
        self.num_host_shards = num_host_shards
        # see C2VTextReader: resume replays the interrupted epoch's
        # seeded permutation instead of restarting the stream at 0
        self._epoch = epoch_offset

    def __iter__(self) -> Iterator[BatchTensors]:
        C = self.max_contexts
        # global permutation, then the host's strided slice — see
        # C2VTextReader.__iter__ (the elastic-resume data-order
        # contract is identical on the binary fast path)
        order = np.arange(self.num_examples)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(order)
            self._epoch += 1
        order = order[self.host_shard::self.num_host_shards]
        emitted = 0
        for start in range(0, len(order), self.batch_size):
            idx = order[start:start + self.batch_size]
            # Within-batch ascending order turns the memmap fancy-index
            # into a forward-only disk read (big win on cold page cache).
            # SGD-safe: batch MEMBERSHIP stays the shuffled permutation;
            # only the order of rows inside one batch changes, which the
            # batch-mean loss is invariant to (target_strings are
            # reindexed identically below).
            sorted_idx = np.sort(idx)
            rows = np.asarray(self.data[sorted_idx])
            labels = rows[:, 0].astype(np.int32)
            src = rows[:, 1:1 + C]
            pth = rows[:, 1 + C:1 + 2 * C]
            dst = rows[:, 1 + 2 * C:1 + 3 * C]
            mask = (pth != self.pad_index).astype(np.float32)
            nv = rows.shape[0]
            tstr = None
            if self.target_strings is not None:
                tstr = [self.target_strings[i] for i in sorted_idx]
            labels, src, pth, dst, mask = _pad_batch(
                (labels, src, pth, dst, mask), self.batch_size)
            emitted += 1
            yield BatchTensors(labels, np.ascontiguousarray(src),
                               np.ascontiguousarray(pth),
                               np.ascontiguousarray(dst), mask, nv,
                               tstr)
        if self.num_host_shards > 1:
            target = _aligned_num_batches(self.num_examples,
                                          self.num_host_shards,
                                          self.batch_size)
            for _ in range(target - emitted):
                B = self.batch_size
                yield BatchTensors(
                    np.zeros((B,), np.int32),
                    np.full((B, C), self.pad_index, np.int32),
                    np.full((B, C), self.pad_index, np.int32),
                    np.full((B, C), self.pad_index, np.int32),
                    np.zeros((B, C), np.float32), 0)


def count_examples(path_or_prefix: str) -> int:
    """Number of examples in a split — from the binary manifest when
    available (O(1)), else a line count. Used to size LR schedules."""
    prefix = path_or_prefix
    if prefix.endswith(".c2v"):
        prefix = prefix[:-len(".c2v")]
    if os.path.exists(prefix + ".bin.json"):
        with open(prefix + ".bin.json") as f:
            return int(json.load(f)["num_examples"])
    n = 0
    with open(path_or_prefix, "rb") as f:
        for raw in f:
            if raw.strip():
                n += 1
    return n


def open_reader(path_or_prefix: str, vocabs: Code2VecVocabs,
                max_contexts: int, batch_size: int, shuffle: bool = False,
                seed: int = 0, keep_strings: bool = False,
                host_shard: int = 0, num_host_shards: int = 1,
                epoch_offset: int = 0):
    """Pick the binary fast path when a `.bin` sibling exists, else text.
    `host_shard`/`num_host_shards` (typically jax.process_index/count)
    slice the example space so each host feeds a disjoint shard.
    `epoch_offset` starts the per-epoch shuffle stream at that epoch
    (auto-resume: replay the killed run's data order, don't restart
    it)."""
    prefix = path_or_prefix
    if prefix.endswith(".c2v"):
        prefix = prefix[:-len(".c2v")]
    have_bin = os.path.exists(prefix + ".bin.json")
    have_targets = os.path.exists(prefix + ".bin.targets")
    if have_bin and (not keep_strings or have_targets):
        return BinaryShardReader(prefix, batch_size, shuffle=shuffle,
                                 seed=seed, host_shard=host_shard,
                                 num_host_shards=num_host_shards,
                                 expected_max_contexts=max_contexts,
                                 keep_strings=keep_strings,
                                 epoch_offset=epoch_offset)
    return C2VTextReader(path_or_prefix, vocabs, max_contexts, batch_size,
                         shuffle=shuffle, seed=seed,
                         keep_strings=keep_strings, host_shard=host_shard,
                         num_host_shards=num_host_shards,
                         epoch_offset=epoch_offset)
