"""Shared string/subtoken utilities and prediction-result containers.

Reference parity target: `common.py` in noamyft/code2vec (SURVEY.md §3
"Shared utils": `normalize_word`, `get_subtokens`/`split_to_subtokens`,
`legal_method_names_checker`, `MethodPredictionResults`). These rules move
subtoken-F1 by points (SURVEY.md §8.4 item 5), so they are unit-tested
against hand cases in tests/test_common.py.

Conventions (SURVEY.md §3.2): method names and leaf tokens are stored as
lowercase subtokens joined by `|` (e.g. `set|name`); special vocabulary
words are `<PAD>` (a.k.a. NoSuchWord) and `<OOV>`.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Iterable, List, Sequence, Tuple


class SpecialVocabWords:
    PAD = "<PAD>"   # a.k.a. NoSuchWord in older code2vec versions
    OOV = "<OOV>"


_NON_ALPHA_RE = re.compile(r"[^a-zA-Z]")
_CAMEL_SPLIT_RE = re.compile(
    r"(?<=[a-z])(?=[A-Z])|_|[0-9]|(?<=[A-Z])(?=[A-Z][a-z])|\s+")


def normalize_word(word: str) -> str:
    """Lowercase; strip non-letters unless that would empty the word."""
    stripped = _NON_ALPHA_RE.sub("", word)
    if not stripped:
        return word.lower()
    return stripped.lower()


def split_to_subtokens(word: str) -> List[str]:
    """Split a raw identifier on camelCase / underscores / digits into
    normalized, non-empty subtokens: `setFooBar_2x` -> [set, foo, bar, x]."""
    return [normalize_word(s) for s in _CAMEL_SPLIT_RE.split(word.strip())
            if s]


def get_subtokens(name: str) -> List[str]:
    """Subtokens of a stored (already normalized) name: split on `|`."""
    return [s for s in name.split("|") if s]


def internal_name_from_subtokens(subtokens: Iterable[str]) -> str:
    return "|".join(subtokens)


def legal_method_names_checker(name: str) -> bool:
    """A predicted name counts toward metrics only if it is a real name:
    not OOV/PAD/empty, and contains at least one letter subtoken."""
    if not name or name in (SpecialVocabWords.OOV, SpecialVocabWords.PAD):
        return False
    return bool(re.search(r"[a-zA-Z]", name))


def filter_impossible_names(names: Sequence[str]) -> List[str]:
    return [n for n in names if legal_method_names_checker(n)]


def calculate_subtoken_tp_fp_fn(
        original_name: str, predicted_name: str) -> Tuple[int, int, int]:
    """Per-example subtoken true/false positives and false negatives
    (SURVEY.md §4.3 `_update_per_subtoken_statistics`): predicted subtokens
    present in the true set are TPs, extra predictions are FPs, missed true
    subtokens are FNs."""
    true_subtokens = get_subtokens(original_name)
    pred_subtokens = get_subtokens(predicted_name)
    tp = sum(1 for s in pred_subtokens if s in true_subtokens)
    fp = sum(1 for s in pred_subtokens if s not in true_subtokens)
    fn = sum(1 for s in true_subtokens if s not in pred_subtokens)
    return tp, fp, fn


@dataclass
class SubtokenStatistics:
    """Accumulates TP/FP/FN over an evaluation run."""
    true_positive: int = 0
    false_positive: int = 0
    false_negative: int = 0

    def update(self, original_name: str, predicted_name: str) -> None:
        tp, fp, fn = calculate_subtoken_tp_fp_fn(original_name, predicted_name)
        self.true_positive += tp
        self.false_positive += fp
        self.false_negative += fn

    @property
    def precision(self) -> float:
        denom = self.true_positive + self.false_positive
        return self.true_positive / denom if denom else 0.0

    @property
    def recall(self) -> float:
        denom = self.true_positive + self.false_negative
        return self.true_positive / denom if denom else 0.0

    @property
    def f1(self) -> float:
        p, r = self.precision, self.recall
        return 2 * p * r / (p + r) if (p + r) else 0.0


@dataclass
class EvaluationResults:
    """Return type of `evaluate()` (SURVEY.md §3 model_base row)."""
    topk_acc: Sequence[float]
    subtoken_precision: float
    subtoken_recall: float
    subtoken_f1: float
    loss: float = float("nan")

    def __str__(self) -> str:
        topk = ", ".join(f"top{k + 1}: {acc:.5f}"
                         for k, acc in enumerate(self.topk_acc))
        return (f"loss: {self.loss:.5f}, {topk}, "
                f"precision: {self.subtoken_precision:.5f}, "
                f"recall: {self.subtoken_recall:.5f}, "
                f"F1: {self.subtoken_f1:.5f}")


@dataclass
class AttentionedPathContext:
    """One path-context with its attention score, for interpretability
    output in the predict REPL (SURVEY.md §4.4)."""
    source_token: str
    path: str
    target_token: str
    attention_score: float


@dataclass
class MethodPredictionResults:
    """Top-k name predictions + attention-ranked paths for one method."""
    original_name: str
    predictions: List[dict] = field(default_factory=list)
    attention_paths: List[AttentionedPathContext] = field(default_factory=list)
    code_vector: object = None

    def append_prediction(self, name: str, probability: float) -> None:
        self.predictions.append({"name": get_subtokens(name),
                                 "probability": probability})

    def append_attention_path(self, score: float, source: str, path: str,
                              target: str) -> None:
        self.attention_paths.append(AttentionedPathContext(
            source_token=source, path=path, target_token=target,
            attention_score=score))
