from code2vec_tpu.ops.attention import attention_pool  # noqa: F401
from code2vec_tpu.ops.ring_attention import ring_attention  # noqa: F401
from code2vec_tpu.ops.sampled_softmax import (  # noqa: F401
    sampled_softmax_loss, log_uniform_sample)
