"""Fused Pallas live-row sparse table update (ROADMAP item 1).

The one pass over LIVE ROWS ONLY that training/sparse_update.py
dispatches to on a TPU backend: per block of deduped unique ids, DMA-
gather the named table / optimizer-state rows from HBM into VMEM,
apply the row update vectorized over the block (row-Adam; on int8
additionally the per-row absmax rescale + counter-hash dither of
ops/pallas_requant.py), and DMA-scatter the rows back. The [V, E]
table, moments and (int8) scales stay in HBM (`memory_space=ANY`) and
are ALIASED input->output, so the kernel's HBM traffic is proportional
to the number of unique rows U, not the vocab V — the whole point: the
dense path's optimizer/requantize walk moved table-sized traffic per
step (BENCH_r05: optimizer efficiency 0.786 at 15.7% HBM utilization),
this moves [U, E].

Contract with the facade (training/sparse_update.py):
  - `uids` is PRE-PADDED to a whole number of `block_rows` blocks with
    the out-of-range sentinel (the table's row count) and `seg` with
    zeros — the kernel must never see Pallas-introduced block padding,
    whose contents are undefined.
  - unique ids never repeat, so grid programs write disjoint rows and
    the sequential-grid in-place aliasing is race-free.
  - the row math IS the facade's `row_adam_math` / `requant_row_math`
    (imported, not copied), so fused-vs-reference parity cannot drift:
    bit-exact on float/bf16 tables, q-exact on int8 under a shared
    salt.

Follows the ops/pallas_requant.py pattern: TPU-compiled on a TPU
backend, interpret mode elsewhere (the CPU tier-1 tests run the
identical kernel), auto-selected by the facade, governed by
Config.SPARSE_UPDATE_PALLAS. Sentinel rows clamp their gather to row 0
(a wasted but harmless read) and `pl.when` skips their scatter. The
per-row DMAs are issued serially within a block — block size (the
`block_rows` knob, tools/sparse_update_sweep.py) trades grid overhead
against VMEM residency; rows are E-element vectors (E=128 = one lane
width at java-large), so each DMA is one contiguous run.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from code2vec_tpu.ops.quant import QuantTable
from code2vec_tpu.training.sparse_adam import RowAdamState
from code2vec_tpu.training.sparse_update import (requant_row_math,
                                                 row_adam_math)


def _gather_row(src_any, dst_vmem, slot, rid, sem):
    cp = pltpu.make_async_copy(src_any.at[rid], dst_vmem.at[slot], sem)
    cp.start()
    cp.wait()


def _scatter_row(src_vmem, dst_any, slot, rid, sem):
    cp = pltpu.make_async_copy(src_vmem.at[slot], dst_any.at[rid], sem)
    cp.start()
    cp.wait()


def _row_adam_kernel(ids_ref, seg_ref, count_ref, tbl_any, m_any, v_any,
                     tbl_out, m_out, v_out, p_vmem, m_vmem, v_vmem, sem,
                     *, block_rows: int, vocab: int, lr: float,
                     b1: float, b2: float, eps: float):
    # tbl_out/m_out/v_out alias tbl_any/m_any/v_any: gather from the
    # OUTPUT refs so re-reads inside one pallas_call (there are none —
    # ids are unique) and the aliasing contract stay coherent.
    def gather(i, _):
        rid = ids_ref[i, 0]
        rid = jnp.where(rid < vocab, rid, 0)
        _gather_row(tbl_out, p_vmem, i, rid, sem)
        _gather_row(m_out, m_vmem, i, rid, sem)
        _gather_row(v_out, v_vmem, i, rid, sem)
        return 0
    jax.lax.fori_loop(0, block_rows, gather, 0)

    p_new, m_new, v_new = row_adam_math(
        p_vmem[:].astype(jnp.float32), m_vmem[:], v_vmem[:],
        seg_ref[:], count_ref[0, 0], lr, b1, b2, eps)
    p_vmem[:] = p_new.astype(p_vmem.dtype)
    m_vmem[:] = m_new
    v_vmem[:] = v_new

    def scatter(i, _):
        rid = ids_ref[i, 0]

        @pl.when(rid < vocab)
        def _():
            _scatter_row(p_vmem, tbl_out, i, rid, sem)
            _scatter_row(m_vmem, m_out, i, rid, sem)
            _scatter_row(v_vmem, v_out, i, rid, sem)
        return 0
    jax.lax.fori_loop(0, block_rows, scatter, 0)


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "interpret", "lr",
                                    "b1", "b2", "eps"))
def _row_adam_impl(table, m, v, uids, seg, count, block_rows, interpret,
                   lr, b1, b2, eps):
    V, E = table.shape
    S = uids.shape[0]
    kernel = functools.partial(_row_adam_kernel, block_rows=block_rows,
                               vocab=V, lr=lr, b1=b1, b2=b2, eps=eps)
    return pl.pallas_call(
        kernel,
        grid=(S // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, E), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        out_shape=(jax.ShapeDtypeStruct((V, E), table.dtype),
                   jax.ShapeDtypeStruct((V, E), jnp.float32),
                   jax.ShapeDtypeStruct((V, E), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((block_rows, E), table.dtype),
                        pltpu.VMEM((block_rows, E), jnp.float32),
                        pltpu.VMEM((block_rows, E), jnp.float32),
                        pltpu.SemaphoreType.DMA],
        input_output_aliases={3: 0, 4: 1, 5: 2},
        interpret=interpret,
    )(uids.reshape(S, 1), seg, count.reshape(1, 1).astype(jnp.float32),
      table, m, v)


def sparse_row_adam_fused(table: jax.Array, state: RowAdamState,
                          uids: jax.Array, seg: jax.Array, *,
                          count: jax.Array, lr: float, b1: float,
                          b2: float, eps: float, block_rows: int,
                          interpret: bool | None = None):
    """Live-row Adam over pre-deduped `uids` / segment-summed `seg`
    (the facade's dedup_segment_sum output — padded, unique, f32).
    interpret=None auto-selects interpreter mode off-TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # hyperparams are host-side Python scalars normalized for the
    # static-arg cache key, never device arrays — no sync here
    # graftlint: disable=host-sync-in-hot-path
    hp = (float(lr), float(b1), float(b2), float(eps))
    new_t, new_m, new_v = _row_adam_impl(
        table, state.m, state.v, uids, seg, count, block_rows,
        interpret, *hp)
    return new_t, RowAdamState(m=new_m, v=new_v)


def _requant_adam_kernel(ids_ref, seg_ref, count_ref, salt_ref, q_any,
                         s_any, m_any, v_any, q_out, s_out, m_out,
                         v_out, q_vmem, s_vmem, m_vmem, v_vmem, sem, *,
                         block_rows: int, vocab: int, lr: float,
                         b1: float, b2: float, eps: float):
    def gather(i, _):
        rid = ids_ref[i, 0]
        rid = jnp.where(rid < vocab, rid, 0)
        _gather_row(q_out, q_vmem, i, rid, sem)
        _gather_row(s_out, s_vmem, i, rid, sem)
        _gather_row(m_out, m_vmem, i, rid, sem)
        _gather_row(v_out, v_vmem, i, rid, sem)
        return 0
    jax.lax.fori_loop(0, block_rows, gather, 0)

    q_new, s_new, m_new, v_new = requant_row_math(
        q_vmem[:], s_vmem[:], m_vmem[:], v_vmem[:], seg_ref[:],
        ids_ref[:, 0], salt_ref[0, 0], count_ref[0, 0], lr, b1, b2,
        eps)
    q_vmem[:] = q_new
    s_vmem[:] = s_new
    m_vmem[:] = m_new
    v_vmem[:] = v_new

    def scatter(i, _):
        rid = ids_ref[i, 0]

        @pl.when(rid < vocab)
        def _():
            _scatter_row(q_vmem, q_out, i, rid, sem)
            _scatter_row(s_vmem, s_out, i, rid, sem)
            _scatter_row(m_vmem, m_out, i, rid, sem)
            _scatter_row(v_vmem, v_out, i, rid, sem)
        return 0
    jax.lax.fori_loop(0, block_rows, scatter, 0)


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "interpret", "lr",
                                    "b1", "b2", "eps"))
def _requant_adam_impl(q, s, m, v, uids, seg, salt, count, block_rows,
                       interpret, lr, b1, b2, eps):
    V, E = q.shape
    S = uids.shape[0]
    kernel = functools.partial(_requant_adam_kernel,
                               block_rows=block_rows, vocab=V, lr=lr,
                               b1=b1, b2=b2, eps=eps)
    return pl.pallas_call(
        kernel,
        grid=(S // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, E), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        out_shape=(jax.ShapeDtypeStruct((V, E), jnp.int8),
                   jax.ShapeDtypeStruct((V, 1), jnp.float32),
                   jax.ShapeDtypeStruct((V, E), jnp.float32),
                   jax.ShapeDtypeStruct((V, E), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((block_rows, E), jnp.int8),
                        pltpu.VMEM((block_rows, 1), jnp.float32),
                        pltpu.VMEM((block_rows, E), jnp.float32),
                        pltpu.VMEM((block_rows, E), jnp.float32),
                        pltpu.SemaphoreType.DMA],
        input_output_aliases={4: 0, 5: 1, 6: 2, 7: 3},
        interpret=interpret,
    )(uids.reshape(S, 1), seg, count.reshape(1, 1).astype(jnp.float32),
      salt.reshape(1, 1), q, s, m, v)


def sparse_requant_adam_fused(qt: QuantTable, state: RowAdamState,
                              uids: jax.Array, seg: jax.Array,
                              salt: jax.Array, *, count: jax.Array,
                              lr: float, b1: float, b2: float,
                              eps: float, block_rows: int,
                              interpret: bool | None = None):
    """Live-row requantize-aware Adam over pre-deduped uids/seg; `salt`
    is the facade's per-call uint32 draw (shared with the reference so
    q parity is bit-exact). interpret=None auto-selects interpreter
    mode off-TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # hyperparams are host-side Python scalars normalized for the
    # static-arg cache key, never device arrays — no sync here
    # graftlint: disable=host-sync-in-hot-path
    hp = (float(lr), float(b1), float(b2), float(eps))
    q_new, s_new, m_new, v_new = _requant_adam_impl(
        qt["q"], qt["s"], state.m, state.v, uids, seg, salt, count,
        block_rows, interpret, *hp)
    return {"q": q_new, "s": s_new}, RowAdamState(m=m_new, v=v_new)
