"""Masked attention pooling over a bag of context vectors.

The reference computes (SURVEY.md §3 `tensorflow_model.py` row,
`_calculate_weighted_contexts`): transformed contexts
`ctx~ = tanh(ctx @ TRANSFORM)`, attention logits `ctx~ @ ATTENTION` with
`log(valid_mask)` added (padding positions get -inf), softmax over the
MAX_CONTEXTS axis, and the attention-weighted sum of `ctx~` as the code
vector.

TPU notes: the whole block is a pair of MXU matmuls ([B*C, D] @ [D, D] and
the [B, C] x [B, C, D] weighted reduction) plus elementwise ops that XLA
fuses; computation runs in the caller's dtype (bf16 on TPU) with the
softmax in f32 for stability.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def attention_pool(contexts: jax.Array, transform: jax.Array,
                   attention: jax.Array,
                   mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Args:
      contexts:  [B, C, D] context vectors (already concatenated + dropout).
      transform: [D, D] the TRANSFORM matrix.
      attention: [D] the ATTENTION vector.
      mask:      [B, C] 1.0 for real contexts, 0.0 for padding.

    Returns:
      code_vectors: [B, D] attention-weighted sums of transformed contexts.
      attn_weights: [B, C] f32 softmax weights (0 at padded positions).
    """
    transformed = jnp.tanh(contexts @ transform.astype(contexts.dtype))
    scores = (transformed @ attention.astype(contexts.dtype)).astype(
        jnp.float32)  # [B, C]
    neg_inf = jnp.asarray(-1e9, dtype=jnp.float32)
    scores = jnp.where(mask > 0, scores, neg_inf)
    attn = jax.nn.softmax(scores, axis=-1)  # f32 [B, C]
    # Guard the all-padding row (softmax over all -1e9 is uniform garbage):
    any_valid = (jnp.sum(mask, axis=-1, keepdims=True) > 0)
    attn = jnp.where(any_valid, attn, 0.0)
    code = jnp.einsum("bc,bcd->bd", attn.astype(contexts.dtype), transformed)
    return code, attn
