"""HBM streaming-bandwidth measurement (shared by bench.py and
tools/profile_step.py — methodology-critical, keep ONE copy).

The copy loop runs INSIDE one jit (fori_loop) so the tunneled axon
platform's ~2 ms per-call dispatch latency doesn't pollute the number,
with an i-dependent term in the body so XLA cannot fold the K copies
into one multiply (measured: a foldable bf16 body reports an impossible
9.9 TB/s). Outer chains are slope-timed (two lengths, differenced) to
cancel the fixed sync overhead. Measured ~590 GB/s on v5e-lite
(BASELINE.md round-3 methodology note).
"""

from __future__ import annotations

import time


def measure_hbm_ceiling(gib: float = 1.0, inner_loops: int = 32) -> float:
    """Returns effective streaming bandwidth in bytes/sec of a
    read+write copy over a `gib`-GiB f32 buffer."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = int(gib * 256 * 1024 * 1024)
    big = jnp.zeros((n,), jnp.float32)
    K = inner_loops

    @jax.jit
    def copyN(x):
        return lax.fori_loop(
            0, K, lambda i, x: x * jnp.float32(1.0 + 1e-7) + i * 0.0, x)

    def chain(m, x):
        t0 = time.perf_counter()
        for _ in range(m):
            x = copyN(x)
        float(x[0])  # sync via host transfer (block_until_ready can
        # return early on the tunneled platform)
        return time.perf_counter() - t0, x

    _, out = chain(1, big)  # compile + warm
    t1, out = chain(2, out)
    t2, out = chain(6, out)
    dt = (t2 - t1) / 4 / K
    return 2 * n * 4 / dt
