"""Fused multi-head self-attention as Pallas TPU kernels (forward AND
backward) for the transformer path-encoder (VERDICT r3 item 4:
`encode_transformer` previously dropped `use_pallas`).

Why a kernel at C=200: the XLA path materializes the [B, H, C, C]
attention logits in f32 (655 MB at B=1024/H=4) plus the softmax output
per layer per direction — at the measured ~590 GB/s streaming ceiling
that is multiple ms/layer of pure HBM traffic for tensors that never
need to exist: at C<=256 the whole per-(batch, head) attention block
(q, k, v [C, hd] and the [C, C] logits) fits comfortably in VMEM
(~500 KB), so one program per (b, h) computes logits -> masked softmax
-> context without writing any [C, C] intermediate to HBM. The
backward kernel RECOMPUTES the softmax in-VMEM (flash-attention's
trade: extra MXU flops, which the step has headroom for, against HBM
traffic, which it does not) and emits dq/dk/dv directly.

This is deliberately NOT a tiled flash-attention: tiling over the KV
axis only pays when C*C exceeds VMEM; at the path-context scale the
untiled fusion is strictly simpler and equally traffic-free. The ring
variant for ctx-sharded meshes lives in ops/ring_attention.py.

CPU tests run both kernels with interpret=True.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


_G = 8  # batch rows per program: B*H/G programs of G fused attention
# blocks each — at (1024, 4) and G=1 the grid is 4096 tiny programs
# whose launch overhead eats the fusion win (measured round 4); G=8
# amortizes the launch 8x. Per-program VMEM at (C=200, hd=128), G=8
# (ADVICE r4: the old "~1.5 MB" figure was wrong):
#   fwd: 4 refs [G,1,C,hd] bf16 (q/k/v/o) = 1.6 MB + per-g f32 working
#        set (~0.3 MB q/k/v rows + ~0.5 MB [C,C] logits/e/attn) ~2.6 MB
#   bwd: 8 refs (5 in + 3 out) = 3.3 MB + ~1.1 MB f32 temps     ~4.4 MB
# Both sit well inside the ~16 MB budget; they scale linearly in G and
# hd and QUADRATICALLY in C (the [C,C] temps) — check before raising
# any of the three.


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref):
    G, C, hd = q_ref.shape[0], q_ref.shape[2], q_ref.shape[3]
    for g in range(G):  # static unroll
        q = q_ref[g, 0].astype(jnp.float32)      # [C, hd]
        k = k_ref[g, 0].astype(jnp.float32)
        v = v_ref[g, 0].astype(jnp.float32)
        logits = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        logits = logits * (1.0 / (hd ** 0.5)) + mask_ref[g]  # [1,C]
        m = jnp.max(logits, axis=-1, keepdims=True)
        e = jnp.exp(logits - m)
        attn = e / jnp.sum(e, axis=-1, keepdims=True)
        o_ref[g, 0] = jnp.dot(attn, v,
                              preferred_element_type=jnp.float32
                              ).astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref,
                dq_ref, dk_ref, dv_ref):
    G, C, hd = q_ref.shape[0], q_ref.shape[2], q_ref.shape[3]
    scale = 1.0 / (hd ** 0.5)
    for g in range(G):  # static unroll
        q = q_ref[g, 0].astype(jnp.float32)
        k = k_ref[g, 0].astype(jnp.float32)
        v = v_ref[g, 0].astype(jnp.float32)
        do = do_ref[g, 0].astype(jnp.float32)
        # recompute the softmax in-VMEM (never materialized in HBM)
        logits = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        logits = logits * scale + mask_ref[g]    # [1, C] broadcast
        m = jnp.max(logits, axis=-1, keepdims=True)
        e = jnp.exp(logits - m)
        attn = e / jnp.sum(e, axis=-1, keepdims=True)      # [C, C]
        # dV = A^T dO;  dA = dO V^T;  dL = A*(dA - rowsum(dA*A));
        # dQ = dL K * s;  dK = dL^T Q * s
        dv_ref[g, 0] = jnp.dot(attn.T, do,
                               preferred_element_type=jnp.float32
                               ).astype(dv_ref.dtype)
        da = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        dl = attn * (da - jnp.sum(da * attn, axis=-1, keepdims=True))
        dq_ref[g, 0] = (jnp.dot(dl, k,
                                preferred_element_type=jnp.float32)
                        * scale).astype(dq_ref.dtype)
        dk_ref[g, 0] = (jnp.dot(dl.T, q,
                                preferred_element_type=jnp.float32)
                        * scale).astype(dk_ref.dtype)


def _specs(G, C, hd):
    # Mosaic requires each block's trailing two dims be sublane/lane
    # aligned OR equal to the full array dims. q/k/v blocks end in
    # (C, hd) == the array's (C, hd); the mask is passed as [B, 1, C]
    # so its block (G, 1, C) ends in (1, C) == the array's (1, C) —
    # a [B, C] layout would put block-size 1 against the B dim, which
    # real TPU lowering rejects (interpret mode does not check this).
    qkv = pl.BlockSpec((G, 1, C, hd), lambda b, h: (b, h, 0, 0))
    mask = pl.BlockSpec((G, 1, C), lambda b, h: (b, 0, 0))
    return qkv, mask


def _grid_g(B: int) -> int:
    g = _G
    while B % g:
        g //= 2
    return max(g, 1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mha_fwd_pallas(q, k, v, log_mask, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, C, hd = q.shape
    G = _grid_g(B)
    qkv_spec, mask_spec = _specs(G, C, hd)
    return pl.pallas_call(
        _fwd_kernel,
        grid=(B // G, H),
        in_specs=[qkv_spec, qkv_spec, qkv_spec, mask_spec],
        out_specs=qkv_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, C, hd), q.dtype),
        interpret=interpret,
    )(q, k, v, log_mask.astype(jnp.float32)[:, None, :])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mha_bwd_pallas(q, k, v, log_mask, do, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, C, hd = q.shape
    G = _grid_g(B)
    qkv_spec, mask_spec = _specs(G, C, hd)
    shape = jax.ShapeDtypeStruct((B, H, C, hd), q.dtype)
    return pl.pallas_call(
        _bwd_kernel,
        grid=(B // G, H),
        in_specs=[qkv_spec, qkv_spec, qkv_spec, mask_spec, qkv_spec],
        out_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_shape=(shape, shape, shape),
        interpret=interpret,
    )(q, k, v, log_mask.astype(jnp.float32)[:, None, :], do)


@jax.custom_vjp
def fused_mha(q: jax.Array, k: jax.Array, v: jax.Array,
              log_mask: jax.Array) -> jax.Array:
    """softmax(q k^T / sqrt(hd) + log_mask) v with q/k/v [B, H, C, hd]
    and log_mask [B, C] (additive, broadcast over queries) — identical
    math to the XLA path in transformer_encoder._mha, but no [B,H,C,C]
    tensor ever reaches HBM in either direction."""
    return _mha_fwd_pallas(q, k, v, log_mask)


def _vjp_fwd(q, k, v, log_mask):
    return _mha_fwd_pallas(q, k, v, log_mask), (q, k, v, log_mask)


def _vjp_bwd(res, do):
    q, k, v, log_mask = res
    dq, dk, dv = _mha_bwd_pallas(q, k, v, log_mask, do)
    return dq, dk, dv, jnp.zeros_like(log_mask)


fused_mha.defvjp(_vjp_fwd, _vjp_bwd)


def mha_reference(q, k, v, log_mask) -> jax.Array:
    """The XLA path (transformer_encoder._mha's core), kept here as the
    numerics oracle for the kernel tests."""
    hd = q.shape[-1]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(float(hd)) + log_mask[:, None, None, :]
    attn = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", attn, v)
