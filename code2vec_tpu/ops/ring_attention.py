"""Ring attention over the context-parallel mesh axis.

Long-context design (SURVEY.md §6 long-context row; the driver brief
lists ring/all-to-all sequence parallelism as first-class): with the
context dim of [B, C, D] activations sharded over the 'ctx' axis, plain
jit lets XLA insert an all-gather of K/V — O(C) memory per device.
Ring attention instead keeps K/V sharded and rotates each shard around
the ring with `ppermute` while accumulating the softmax in flash-style
running form (running max m, normalizer l, weighted accumulator acc),
so per-device memory stays O(C/s) and the transfers overlap compute on
ICI. The bag-of-contexts model needs no causal mask — only the key-side
padding log-mask, which rotates with its K/V shard.

Numerically exact (not an approximation): the streamed softmax
reproduces dense masked attention to float tolerance — verified against
the dense oracle in tests/test_ring_attention.py, gradients included
(autodiff goes through ppermute/scan natively).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from code2vec_tpu.parallel.compat import shard_map
from code2vec_tpu.parallel.mesh import CONTEXT_AXIS, DATA_AXIS, DCN_AXIS


def _ring_attention_local(q, k, v, log_mask, axis_name: str,
                          axis_size: int):
    """Per-device body (runs under shard_map): q,k,v [B, H, Cl, hd]
    local shards; log_mask [B, Cl] key-side additive mask for the LOCAL
    key shard. Returns attention output [B, H, Cl, hd] for the local
    queries, attending over ALL keys via s ring rotations.
    `axis_size` is static (from the mesh) — it sizes the ring table and
    the scan, which must be trace-time constants."""
    s = axis_size
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    ring = [(i, (i + 1) % s) for i in range(s)]

    def block(q, k, v, mask):
        # [B, H, Cq, Ck] logits in f32 for a stable running softmax
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
        return logits * scale + mask[:, None, None, :]

    def accumulate(m, l, acc, k, v, mask):
        logits = block(q, k, v, mask)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
        return m_new, l, acc

    def step(carry, _):
        m, l, acc, k, v, mask = carry
        # rotate FIRST, then accumulate: the local (hop-0) block is
        # consumed before the scan, so no dead final rotation is issued
        # (3 wasted ICI transfers per layer otherwise)
        k = jax.lax.ppermute(k, axis_name, ring)
        v = jax.lax.ppermute(v, axis_name, ring)
        mask = jax.lax.ppermute(mask, axis_name, ring)
        m, l, acc = accumulate(m, l, acc, k, v, mask)
        return (m, l, acc, k, v, mask), None

    B, H, Cq, hd = q.shape
    m0 = jnp.full((B, H, Cq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Cq), jnp.float32)
    acc0 = jnp.zeros((B, H, Cq, hd), jnp.float32)
    m, l, acc = accumulate(m0, l0, acc0, k, v, log_mask)  # local block
    (m, l, acc, _, _, _), _ = jax.lax.scan(
        step, (m, l, acc, k, v, log_mask), None, length=s - 1)
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention(q, k, v, log_mask, mesh, *,
                   axis_name: str = CONTEXT_AXIS):
    """Masked multi-head attention with the context dim sharded over
    `axis_name` of `mesh`. q/k/v: [B, H, C, hd] (C globally sharded over
    the ctx axis); log_mask: [B, C] additive key mask. Batch rides the
    composite ('dcn','data') axes as everywhere else."""
    qkv_spec = P((DCN_AXIS, DATA_AXIS), None, axis_name, None)
    mask_spec = P((DCN_AXIS, DATA_AXIS), axis_name)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          axis_size=int(mesh.shape[axis_name])),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec)
    return fn(q, k, v, log_mask)
