"""Sampled softmax over a large target vocabulary.

SURVEY.md §3.3 / §8.4: the java-large config (261K method-name targets)
requires a TPU-friendly sampled softmax matching
`tf.nn.sampled_softmax_loss` semantics — a log-uniform (Zipfian) candidate
sampler and the log-expected-count bias correction — or subtoken-F1 will
not match the reference.

Semantics implemented (matching TF's defaults):
- candidates ~ log-uniform over [0, V): P(k) = log((k+2)/(k+1)) / log(V+1),
  so frequency-sorted vocabularies (ours are: Vocab.create_from_freq_dict
  sorts by descending count) get Zipf-like negatives;
- one shared candidate set per step (TF shares candidates across the batch);
- bias correction subtracts log(expected_count) from each candidate's and
  the true class's logits; TF's unique-sampler expectation is
  E[count] = -expm1(S * log1p(-p));
- accidental hits (a sampled negative equal to the true label) are masked
  to -inf, as with TF's `remove_accidental_hits=True`.

All shapes are static (S = num_sampled) so the step jits once. The gather
of S + B rows from the [V, D] target table is the whole point: the dense
[B, V] logits matmul (the full-softmax path) is replaced by [B, D] @ [D, S].
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def log_uniform_sample(rng: jax.Array, num_sampled: int,
                       vocab_size: int) -> jax.Array:
    """Draw `num_sampled` class ids (with replacement) from the
    log-uniform distribution over [0, vocab_size)."""
    u = jax.random.uniform(rng, (num_sampled,), dtype=jnp.float32)
    s = jnp.exp(u * jnp.log(float(vocab_size + 1))) - 1.0
    return jnp.clip(s.astype(jnp.int32), 0, vocab_size - 1)


def _log_expected_count(ids: jax.Array, num_sampled: int,
                        vocab_size: int) -> jax.Array:
    k = ids.astype(jnp.float32)
    p = jnp.log1p(1.0 / (k + 1.0)) / jnp.log(float(vocab_size + 1))
    # TF log_uniform_candidate_sampler(unique=True) expected count:
    return jnp.log(-jnp.expm1(num_sampled * jnp.log1p(-p)))


def sampled_softmax_loss(
        target_table: jax.Array, code_vectors: jax.Array,
        labels: jax.Array, rng: jax.Array, num_sampled: int,
        example_weights: jax.Array | None = None,
        vocab_size: int | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Args:
      target_table:  [V_padded, D] target-name embedding table (the softmax
                     weights; reference TARGET_WORDS_VOCAB). May carry dead
                     padding rows for mesh divisibility.
      code_vectors:  [B, D].
      labels:        [B] int32 true class ids.
      rng:           PRNG key for candidate sampling.
      num_sampled:   S, static.
      example_weights: optional [B] 0/1 weights (padded final batch).
      vocab_size:    TRUE vocab size V <= V_padded; candidates are drawn
                     from [0, V) so padding rows are never sampled.

    Returns (mean_loss, sampled_ids).
    """
    if vocab_size is None:
        vocab_size = target_table.shape[0]
    sampled = log_uniform_sample(rng, num_sampled, vocab_size)  # [S]

    dtype = code_vectors.dtype
    true_w = target_table[labels].astype(dtype)          # [B, D]
    sampled_w = target_table[sampled].astype(dtype)      # [S, D]

    true_logits = jnp.sum(code_vectors * true_w, axis=-1).astype(jnp.float32)
    sampled_logits = (code_vectors @ sampled_w.T).astype(jnp.float32)

    true_logits = true_logits - _log_expected_count(
        labels, num_sampled, vocab_size)
    sampled_logits = sampled_logits - _log_expected_count(
        sampled, num_sampled, vocab_size)[None, :]

    accidental = sampled[None, :] == labels[:, None]     # [B, S]
    sampled_logits = jnp.where(accidental, -1e9, sampled_logits)

    logits = jnp.concatenate([true_logits[:, None], sampled_logits], axis=1)
    per_example = -jax.nn.log_softmax(logits, axis=-1)[:, 0]
    if example_weights is not None:
        denom = jnp.maximum(jnp.sum(example_weights), 1.0)
        return jnp.sum(per_example * example_weights) / denom, sampled
    return jnp.mean(per_example), sampled
