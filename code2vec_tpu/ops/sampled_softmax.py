"""Sampled softmax over a large target vocabulary.

SURVEY.md §3.3 / §8.4: the java-large config (261K method-name targets)
requires a TPU-friendly sampled softmax matching
`tf.nn.sampled_softmax_loss` semantics — a log-uniform (Zipfian) candidate
sampler and the log-expected-count bias correction — or subtoken-F1 will
not match the reference.

Semantics implemented (matching TF's defaults):
- candidates ~ log-uniform over [0, V): P(k) = log((k+2)/(k+1)) / log(V+1),
  so frequency-sorted vocabularies (ours are: Vocab.create_from_freq_dict
  sorts by descending count) get Zipf-like negatives;
- candidates are UNIQUE (TF's unique=True): drawn via the Gumbel-top-k
  trick — perturb per-class log-probabilities with Gumbel noise and take
  the top S, which is distributionally exact sampling without
  replacement. With replacement the head class (p~0.056 for java-large)
  would appear ~S*p~230 times and the unique-sampler bias correction
  would overweight it by orders of magnitude;
- one shared candidate set per step (TF shares candidates across the batch);
- bias correction subtracts log(expected_count) from each candidate's and
  the true class's logits. TF computes -expm1(num_tries * log1p(-p)) with
  the sampler's actual with-replacement draw count; we use the
  deterministic equivalent: solve sum_k(-expm1(T*log1p(-p_k))) = S for the
  effective draw count T once on the host (static per (V, S)) and use
  inclusion = -expm1(T*log1p(-p)). Verified within ~2% of the empirical
  Gumbel-top-k inclusion frequencies (tests/test_ops.py);
- accidental hits (a sampled negative equal to the true label) are masked
  to -inf, as with TF's `remove_accidental_hits=True`.

All shapes are static (S = num_sampled) so the step jits once. The gather
of S + B rows from the [V, D] target table is the whole point: the dense
[B, V] logits matmul (the full-softmax path) is replaced by [B, D] @ [D, S].
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _log_uniform_log_probs(vocab_size: int) -> jax.Array:
    """Static per-class log-pmf of the log-uniform distribution; XLA
    constant-folds this inside a jitted step."""
    k = jnp.arange(vocab_size, dtype=jnp.float32)
    return jnp.log(jnp.log1p(1.0 / (k + 1.0)) /
                   jnp.log(float(vocab_size + 1)))


def log_uniform_sample(rng: jax.Array, num_sampled: int,
                       vocab_size: int) -> jax.Array:
    """Draw `num_sampled` UNIQUE class ids from the log-uniform
    distribution over [0, vocab_size) via Gumbel-top-k (exact sampling
    without replacement, matching TF's unique=True candidate sampler)."""
    if num_sampled >= vocab_size:
        return jnp.arange(vocab_size, dtype=jnp.int32)
    gumbel = jax.random.gumbel(rng, (vocab_size,), dtype=jnp.float32)
    scores = _log_uniform_log_probs(vocab_size) + gumbel
    _, ids = jax.lax.top_k(scores, num_sampled)
    return ids.astype(jnp.int32)


@functools.lru_cache(maxsize=None)
def _effective_num_tries(num_sampled: int, vocab_size: int) -> float:
    """Deterministic stand-in for TF's stochastic num_tries: the T such
    that the expected number of distinct classes in T with-replacement
    log-uniform draws equals num_sampled. Newton's method on the host;
    cached per static (S, V)."""
    k = np.arange(vocab_size, dtype=np.float64)
    log1m_p = np.log1p(-(np.log1p(1.0 / (k + 1.0)) /
                         np.log(float(vocab_size + 1))))
    T = float(num_sampled)
    for _ in range(100):
        f = np.sum(-np.expm1(T * log1m_p)) - num_sampled
        df = np.sum(-log1m_p * np.exp(T * log1m_p))
        step = f / df
        T -= step
        if abs(step) < 1e-9:
            break
    return T


def _log_expected_count(ids: jax.Array, num_sampled: int,
                        vocab_size: int) -> jax.Array:
    k = ids.astype(jnp.float32)
    p = jnp.log1p(1.0 / (k + 1.0)) / jnp.log(float(vocab_size + 1))
    if num_sampled >= vocab_size:
        # exhaustive candidate set: every class appears exactly once
        return jnp.zeros_like(p)
    T = _effective_num_tries(num_sampled, vocab_size)
    return jnp.log(-jnp.expm1(T * jnp.log1p(-p)))


def sampled_softmax_from_gathered(
        code_vectors: jax.Array, true_w: jax.Array, samp_w: jax.Array,
        true_corr: jax.Array, samp_corr: jax.Array,
        accidental: jax.Array,
        example_weights: jax.Array | None = None) -> jax.Array:
    """The shared logit/correction/accidental-hit core, taking
    PRE-GATHERED target rows — called both by sampled_softmax_loss and by
    the sparse-embedding train step (which differentiates w.r.t. the
    gathered rows themselves).

    Args: code [B, D]; true_w [B, D]; samp_w [S, D]; log-expected-count
    corrections true_corr [B] / samp_corr [S]; accidental [B, S] mask of
    sampled==label collisions; optional [B] example weights.
    Returns the scalar mean loss.
    """
    dtype = code_vectors.dtype
    true_logits = jnp.sum(code_vectors * true_w.astype(dtype),
                          axis=-1).astype(jnp.float32) - true_corr
    sampled_logits = (code_vectors @ samp_w.astype(dtype).T).astype(
        jnp.float32) - samp_corr[None, :]
    sampled_logits = jnp.where(accidental, -1e9, sampled_logits)
    logits = jnp.concatenate([true_logits[:, None], sampled_logits],
                             axis=1)
    per_example = -jax.nn.log_softmax(logits, axis=-1)[:, 0]
    if example_weights is not None:
        denom = jnp.maximum(jnp.sum(example_weights), 1.0)
        return jnp.sum(per_example * example_weights) / denom
    return jnp.mean(per_example)


def sampled_softmax_loss(
        target_table: jax.Array, code_vectors: jax.Array,
        labels: jax.Array, rng: jax.Array, num_sampled: int,
        example_weights: jax.Array | None = None,
        vocab_size: int | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Args:
      target_table:  [V_padded, D] target-name embedding table (the softmax
                     weights; reference TARGET_WORDS_VOCAB). May carry dead
                     padding rows for mesh divisibility.
      code_vectors:  [B, D].
      labels:        [B] int32 true class ids.
      rng:           PRNG key for candidate sampling.
      num_sampled:   S, static.
      example_weights: optional [B] 0/1 weights (padded final batch).
      vocab_size:    TRUE vocab size V <= V_padded; candidates are drawn
                     from [0, V) so padding rows are never sampled.

    Returns (mean_loss, sampled_ids).
    """
    if vocab_size is None:
        vocab_size = target_table.shape[0]
    # S > V degenerates to the exhaustive candidate set (full softmax)
    num_sampled = min(num_sampled, vocab_size)
    sampled = log_uniform_sample(rng, num_sampled, vocab_size)  # [S]
    loss = sampled_softmax_from_gathered(
        code_vectors,
        true_w=target_table[labels],
        samp_w=target_table[sampled],
        true_corr=_log_expected_count(labels, num_sampled, vocab_size),
        samp_corr=_log_expected_count(sampled, num_sampled, vocab_size),
        accidental=sampled[None, :] == labels[:, None],
        example_weights=example_weights)
    return loss, sampled
