"""Fused Pallas requantize row-pass for int8 embedding tables.

The int8 tables (ops/quant.py) made the step SLOWER despite halving the
dominant HBM bytes: BASELINE.md's round-5 attribution pins +6.7 ms of
the +26% regression on the unfused requantize — XLA runs the apply as
separate dequant / absmax-reduce / quantize+dither passes over the full
[V, E] f32 table, each re-streaming it through HBM, against a ~3 ms SGD
streaming floor (VERDICT r5 weak #2: "the one bound phase with NO
kernel attempt"). This kernel is that attempt: ONE read-modify-write
sweep per row block —

    read q row-block (int8) + s (f32)   ->  dequantize
    + add the update row (the optimizer's bf16/f32 [V, E] output)
    -> row absmax -> rescale (new per-row scale)
    -> counter-hash dither (the SAME stream as ops/quant._dither: a
       salted xxhash-style finalizer over the absolute element index,
       so fused-vs-reference parity is bit-exact on q under a fixed
       rng, and dither streams stay step-independent via the salt)
    -> round, clip, write q + s back

so the f32 table never materializes in HBM and each byte of q/s/update
crosses the bus once. Analytic traffic of one sweep at java-large
(token+path tables, E=128): ~1.15 GB -> ~2 ms at the measured
~590 GB/s streaming ceiling, vs the unfused 9.7 ms phase.

Follows the ops/pallas_attention.py pattern: TPU-compiled when on a TPU
backend, interpret mode elsewhere (CPU tests run the identical kernel),
auto-selected by the caller (ops/quant.requantize dispatch, governed by
Config.REQUANT_PALLAS).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from code2vec_tpu.ops.quant import (_SCALE_FLOOR, QuantTable,
                                    dither_from_index)

# Rows per program. int8's min TPU tile is (32, 128); 256 rows x E=128
# keeps the three per-block buffers (q int8 + update + f32 temps) well
# under VMEM while giving the DMA engine long contiguous runs.
# tools/requant_sweep.py is the tuning driver for this knob.
_BLOCK_ROWS = 256


def _requant_kernel(salt_ref, q_ref, s_ref, upd_ref, qo_ref, so_ref, *,
                    block_rows: int, emb: int):
    salt = salt_ref[0, 0]
    f = (q_ref[:].astype(jnp.float32) * s_ref[:]
         + upd_ref[:].astype(jnp.float32))
    absmax = jnp.max(jnp.abs(f), axis=1, keepdims=True)
    s_new = jnp.maximum(absmax, _SCALE_FLOOR) / 127.0
    x = f / s_new
    # counter-hash dither over the ABSOLUTE flat element index
    # (row * E + col), identical to ops/quant._dither's iota-over-[V, E]
    # stream — the kernel grid must not change the random stream.
    row0 = (pl.program_id(0) * block_rows).astype(jnp.uint32)
    rows = jax.lax.broadcasted_iota(jnp.uint32, (block_rows, emb), 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, (block_rows, emb), 1)
    idx = (row0 + rows) * jnp.uint32(emb) + cols
    dither = dither_from_index(idx, salt)  # the shared counter-hash
    qo_ref[:] = jnp.clip(jnp.round(x + dither), -127, 127).astype(jnp.int8)
    so_ref[:] = s_new


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _requantize_fused_impl(q, s, update, salt, block_rows, interpret):
    # V need not divide block_rows: the grid is cdiv and Pallas pads
    # the trailing block itself (boundary loads see padding, boundary
    # stores are masked). That is safe here because every op in the
    # kernel is ROW-local — absmax reduces along E only, so padding
    # rows cannot leak into real rows — and it matters: materializing
    # padded copies via concatenate/slice instead would re-stream the
    # full q/update arrays through HBM per step (~1 GB at java-large,
    # where BOTH vocab sizes are non-multiples of 256), defeating the
    # kernel's one-sweep contract and the bench attribution built on
    # requant_traffic_bytes.
    V, E = q.shape
    kernel = functools.partial(_requant_kernel, block_rows=block_rows,
                               emb=E)
    q_new, s_new = pl.pallas_call(
        kernel,
        grid=(pl.cdiv(V, block_rows),),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, E), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, E), lambda i: (i, 0)),
        ],
        out_specs=(pl.BlockSpec((block_rows, E), lambda i: (i, 0)),
                   pl.BlockSpec((block_rows, 1), lambda i: (i, 0))),
        out_shape=(jax.ShapeDtypeStruct((V, E), jnp.int8),
                   jax.ShapeDtypeStruct((V, 1), jnp.float32)),
        interpret=interpret,
    )(salt, q, s, update)
    return q_new, s_new


def requantize_fused(qt: QuantTable, update: jax.Array, rng: jax.Array,
                     *, block_rows: int | None = None,
                     interpret: bool | None = None) -> QuantTable:
    """Drop-in for ops.quant.requantize_reference (same signature and
    semantics — q bit-exact under the same rng; s to float-contraction
    ulp), as one fused row-pass. interpret=None auto-selects
    interpreter mode off-TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_rows is None:
        block_rows = _BLOCK_ROWS
    # ONE tiny threefry draw per call — the same salt derivation as
    # _dither, so the fused and reference paths see the same stream.
    salt = jax.random.bits(rng, dtype=jnp.uint32).reshape(1, 1)
    q_new, s_new = _requantize_fused_impl(qt["q"], qt["s"], update, salt,
                                          block_rows, interpret)
    return {"q": q_new, "s": s_new}


def requant_traffic_bytes(qt: QuantTable, update: jax.Array) -> int:
    """Analytic HBM bytes of ONE fused sweep: q and s read + written
    once, the update rows read once. The streaming-floor comparator for
    bench.py's int8_requant_* attribution and tools/requant_sweep.py
    (the multi-pass XLA reference moves a multiple of this — it
    materializes the dequantized f32 table and re-reads it for the
    absmax and quantize passes)."""
    q, s = qt["q"], qt["s"]
    return (q.size * q.dtype.itemsize * 2
            + s.size * s.dtype.itemsize * 2
            + update.size * update.dtype.itemsize)
