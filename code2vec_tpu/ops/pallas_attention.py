"""Fused masked attention-pool as a Pallas TPU kernel.

One kernel fuses the whole pooling block (SURVEY.md §3.1 names this the
Pallas candidate): tanh(ctx @ TRANSFORM) -> masked softmax over contexts
-> attention-weighted sum, per batch block, with the [BB*C, D] matmul on
the MXU and softmax/weighted-sum on the VPU — no [B, C, D] `transformed`
intermediate ever hits HBM.

Measured reality on one v5e chip (java-large shapes): the XLA path is
embedding-gather-bound, and XLA already fuses this block competitively,
so the kernel is opt-in (`attention_pool_pallas` / Config.USE_PALLAS) and
exists for (a) configs with much larger C/D where the fused intermediate
matters and (b) the component inventory. Two sibling experiments are
documented here as negative results: a per-row DMA gather kernel (23 ms
vs XLA's 15.5 ms for 409k rows — scalar-core DMA issue rate bound) and a
fused dense-Adam kernel (17.9 ms vs optax's 15.8 ms — both at the chip's
~280 GB/s effective streaming bandwidth).

CPU tests run the same kernel with interpret=True.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BB = 8  # batch rows per program


def _attention_kernel(ctx_ref, tr_ref, at_ref, mask_ref, code_ref,
                      attn_ref):
    bb, C, D = ctx_ref.shape
    ctx = ctx_ref[:].reshape(bb * C, D)
    transformed = jnp.tanh(
        jnp.dot(ctx, tr_ref[:], preferred_element_type=jnp.float32))
    scores = jnp.dot(transformed, at_ref[:].reshape(D, 1),
                     preferred_element_type=jnp.float32)  # [bb*C, 1]
    scores = scores.reshape(bb, C)
    mask = mask_ref[:]
    scores = jnp.where(mask > 0, scores, -1e9)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    denom = jnp.sum(e, axis=-1, keepdims=True)
    attn = e / denom
    any_valid = jnp.sum(mask, axis=-1, keepdims=True) > 0
    attn = jnp.where(any_valid, attn, 0.0)
    attn_ref[:] = attn
    weighted = transformed.reshape(bb, C, D) * attn[:, :, None]
    code_ref[:] = jnp.sum(weighted, axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def attention_pool_pallas(contexts: jax.Array, transform: jax.Array,
                          attention: jax.Array, mask: jax.Array,
                          interpret: bool | None = None
                          ) -> Tuple[jax.Array, jax.Array]:
    """Drop-in for ops.attention.attention_pool (same signature/semantics;
    f32 outputs). The batch is padded to a multiple of 8 internally;
    interpret=None auto-selects interpreter mode off-TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, C, D = contexts.shape
    pad = (-B) % _BB
    if pad:
        contexts = jnp.concatenate(
            [contexts, jnp.zeros((pad, C, D), contexts.dtype)], axis=0)
        mask = jnp.concatenate(
            [mask, jnp.zeros((pad, C), mask.dtype)], axis=0)
    BP = B + pad
    f32 = jnp.float32
    code, attn = pl.pallas_call(
        _attention_kernel,
        grid=(BP // _BB,),
        in_specs=[
            pl.BlockSpec((_BB, C, D), lambda i: (i, 0, 0)),
            pl.BlockSpec((D, D), lambda i: (0, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((_BB, C), lambda i: (i, 0)),
        ],
        out_specs=(pl.BlockSpec((_BB, D), lambda i: (i, 0)),
                   pl.BlockSpec((_BB, C), lambda i: (i, 0))),
        out_shape=(jax.ShapeDtypeStruct((BP, D), f32),
                   jax.ShapeDtypeStruct((BP, C), f32)),
        interpret=interpret,
    )(contexts.astype(f32), transform.astype(f32), attention.astype(f32),
      mask.astype(f32))
    return code[:B], attn[:B]


# Differentiable wrapper: Pallas forward, XLA-recompute backward (the
# pooled intermediate is rematerialized — same trade jax.checkpoint
# makes; avoids hand-writing a backward kernel).
@jax.custom_vjp
def attention_pool_fused(contexts, transform, attention, mask):
    return attention_pool_pallas(contexts, transform, attention, mask)


def _fused_fwd(contexts, transform, attention, mask):
    out = attention_pool_pallas(contexts, transform, attention, mask)
    return out, (contexts, transform, attention, mask)


def _fused_bwd(residuals, cotangents):
    from code2vec_tpu.ops.attention import attention_pool
    contexts, transform, attention, mask = residuals

    def ref(c, t, a):
        code, attn = attention_pool(c, t, a, mask)
        return code.astype(jnp.float32), attn
    _, vjp = jax.vjp(ref, contexts, transform, attention)
    d_c, d_t, d_a = vjp(cotangents)
    return d_c, d_t, d_a, jnp.zeros_like(mask)


attention_pool_fused.defvjp(_fused_fwd, _fused_bwd)
