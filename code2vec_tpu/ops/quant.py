"""Sub-bf16 embedding tables: int8 storage with per-row scales.

BASELINE.md's round-4 structural-bound analysis ends: the per-chip step
is bound end to end by table *bytes* — the backward scatter and the
optimizer phase both stream the three vocab tables — so "further
per-chip gains need less work (smaller tables, lower-precision states),
not better scheduling". This module is that lever (VERDICT r4 item 3):
the two [V, E] leaf-token tables (token_emb / path_emb — 74% of table
params at java-large capacities; target_emb stays bf16 because the
sampled-softmax head matmuls against it) are stored as

    q : int8  [V, E]   (row value = q * s)
    s : f32   [V, 1]   (per-row absmax / 127)

halving their gather and optimizer-apply traffic vs bf16.

TPU-first design notes:

- **Gather-level dequantization** (`quantized_take`): rows dequantize
  AFTER the [B, C]-row gather — 1 byte/element crosses HBM instead of
  2, and the ``* s`` fuses into the gather consumer. The full table is
  never materialized in float during training.
- **Straight-through gradient via an unused carrier**: the backward
  pass needs the same dense [V, E] float cotangent the bf16 path
  scatter-adds (AD produces it; the optimizer consumes it). A
  `custom_vjp` routes the gather's cotangent to a zeros "carrier"
  argument the primal never reads — XLA dead-code-eliminates the
  carrier in the forward, so the carrier costs NO gather traffic and
  NO HBM residency (it is created as `jnp.zeros` inside the step and
  only its scatter-add materializes, exactly like the bf16 path's
  gradient buffer). The int8 `q` itself is a non-differentiable leaf
  (`allow_int=True` at the step's `value_and_grad`; its float0
  cotangent is dropped).
- **Stochastic-rounding requantize** (`requantize`): the int8 quantum
  (absmax/127 ≈ 3e-3 for unit-scale rows) is larger than a typical
  per-step update (~lr = 1e-3), so round-to-nearest would silently
  drop most updates and the tables would never train (the bf16
  freeze effect, BASELINE.md decay study, at 8x the magnitude).
  Uniform-dither rounding keeps the applied update correct in
  expectation. On TPU the whole update runs as ONE fused Pallas
  row-pass (ops/pallas_requant.py, round 6 — the multi-pass XLA form
  below re-streams the f32 table and cost +6.7 ms/step, BASELINE.md
  round 5); `requantize` dispatches between them.
  Untouched rows (update == 0) requantize stably: a
  freshly quantized row's absmax element is ±127, so the recomputed
  scale reproduces the old one to 1 ulp and round(q + eps + u) == q
  except on a ~1e-5-probability dither tail — no systematic drift
  (property-tested in tests/test_quant.py).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

QuantTable = Dict[str, jax.Array]  # {"q": int8 [V, E], "s": f32 [V, 1]}

# keys that may be stored quantized under tables_dtype == "int8"
QUANTIZED_TABLE_KEYS = ("token_emb", "path_emb")

_SCALE_FLOOR = 1e-12  # all-zero rows quantize against this, not 1/0


def is_quantized(leaf) -> bool:
    """True for a {"q", "s"} quantized-table subtree."""
    return isinstance(leaf, dict) and set(leaf) == {"q", "s"}


def quantize_table(table: jax.Array) -> QuantTable:
    """f32/bf16 [V, E] -> {"q" int8, "s" f32[V,1]} (per-row absmax)."""
    t = table.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(t), axis=1, keepdims=True)
    s = jnp.maximum(absmax, _SCALE_FLOOR) / 127.0
    q = jnp.round(t / s).astype(jnp.int8)
    return {"q": q, "s": s}


def dequantize_table(qt: QuantTable, dtype=jnp.float32) -> jax.Array:
    """Materialize the full float table (serving/attack/export paths —
    NOT the train step, which dequantizes at gather granularity)."""
    return (qt["q"].astype(jnp.float32) * qt["s"]).astype(dtype)


@functools.lru_cache(maxsize=None)
def _qtake_for(shape: Tuple[int, ...], dtype_name: str):
    """The custom_vjp gather for one carrier (shape, dtype) — cached so
    each table's primitive is defined once (shape/dtype are static
    Python values; residuals stay JAX types)."""
    dtype = jnp.dtype(dtype_name)

    @jax.custom_vjp
    def qtake(carrier, q, s, ids):
        del carrier  # shape-only: DCE'd from the forward
        # dequantize to bf16, not s's f32: q*s carries <= 8 significant
        # bits, so bf16 loses nothing that the quantization did not
        # already drop — and an f32 output would double the [B, C, E]
        # activation AND backward-cotangent traffic.
        # Measured dead end, kept for the record (round 5): gathering
        # the scales as a flat 1-D [V] array instead of [V, 1] slices
        # is 6x faster in a MICRObenchmark (0.57 vs 3.7 ms — [*, 1]
        # f32 slices can't use wide DMA) but reproducibly ~3 ms SLOWER
        # inside the full jitted step (32.8 vs 29.7 ms fwd+bwd) — the
        # in-program fusion/layout differs from the standalone op, so
        # the 2-D form stays.
        rows = jnp.take(q, ids, axis=0).astype(jnp.float32)
        deq = rows * jnp.take(s, ids, axis=0)
        return deq.astype(jnp.bfloat16)

    def fwd(carrier, q, s, ids):
        return qtake(carrier, q, s, ids), ids

    def bwd(ids, g):
        # the dense cotangent the optimizer consumes — same scatter-add
        # the bf16 path's AD emits for its table gradient
        dc = jnp.zeros(shape, dtype).at[ids].add(g.astype(dtype))
        return (dc, None, None, None)

    qtake.defvjp(fwd, bwd)
    return qtake


def quantized_take(carrier: jax.Array, qt: QuantTable,
                   ids: jax.Array) -> jax.Array:
    """Gather + dequantize rows `ids` of a quantized table; gradients
    flow (dense, scatter-added) to `carrier` only."""
    f = _qtake_for(tuple(carrier.shape), str(carrier.dtype))
    return f(carrier, qt["q"], qt["s"], ids)


def opt_param_view(params):
    """The optimizer's view of a params pytree: each quantized table
    appears as one flat [V, E] bf16 stand-in matching the flat gradient
    the quantized train step feeds it (values are never read — shapes
    and dtypes only), everything else as-is. Shared by the model
    (jax_model) and bench so opt_state structure can never drift
    between them."""
    return {k: (jnp.zeros(v["q"].shape, jnp.bfloat16)
                if is_quantized(v) else v)
            for k, v in params.items()}


def dither_from_index(idx: jax.Array, salt: jax.Array) -> jax.Array:
    """Uniform(-0.5, 0.5) dither for uint32 element indices `idx` under
    a uint32 `salt` — THE counter-hash stream (salted xxhash-style
    finalizer; see _dither for why not threefry). Single source of
    truth shared by the dense reference (_dither), the fused requantize
    kernel (ops/pallas_requant.py) and the sparse live-row update
    (training/sparse_update.py + ops/pallas_sparse_update.py): all four
    must draw the SAME value for the same absolute [V, E] element index
    and salt, or fused-vs-reference q parity breaks."""
    h = (idx ^ salt) * jnp.uint32(2654435761)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(2246822519)
    h = h ^ (h >> 13)
    # top 24 bits -> f32: exact in a 24-bit mantissa, so the result
    # stays in [-0.5, 0.5) — a full-32-bit convert would round values
    # near 2^32 up and emit dither of exactly +0.5
    return ((h >> 8).astype(jnp.float32) * jnp.float32(1.0 / 16777216.0)
            - 0.5)


def _dither(rng: jax.Array, shape) -> jax.Array:
    """Uniform(-0.5, 0.5) dither from a fused counter hash, NOT
    jax.random.uniform: threefry bits for a [V, E] table are ~283M
    ALU-bound draws per step at java-large scale — measured to blow the
    entire int8 byte saving (step 43.3 ms vs bf16's 30.7; BASELINE.md
    round 5). Rounding dither needs uniformity, not cryptographic
    quality, so a salted xxhash-style finalizer over the element index
    (2 multiplies + 2 xor-shifts, fused into the requantize pass) is
    the right tool — measured: it returns the int8 step to its byte
    advantage (BASELINE.md round-5 int8 section carries both step
    times). The salt is ONE tiny threefry draw from the step's rng, so
    different steps see independent dither streams."""
    salt = jax.random.bits(rng, dtype=jnp.uint32)
    n = 1
    for d in shape:
        n *= d
    idx = jax.lax.iota(jnp.uint32, n).reshape(shape)
    return dither_from_index(idx, salt)


def requantize_reference(qt: QuantTable, update: jax.Array,
                         rng: jax.Array) -> QuantTable:
    """Apply a dense [V, E] additive update to a quantized table with
    stochastic rounding; per-row scales track the new absmax.

    This is the multi-pass XLA form (it materializes the dequantized
    f32 table and streams it several times — BASELINE.md round-5 pins
    +6.7 ms of the int8 step regression on exactly that); it stays as
    the parity oracle for the fused Pallas row-pass and as the CPU
    default, where XLA's fusion beats the interpreted kernel."""
    f = qt["q"].astype(jnp.float32) * qt["s"] + update.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(f), axis=1, keepdims=True)
    s_new = jnp.maximum(absmax, _SCALE_FLOOR) / 127.0
    x = f / s_new
    q_new = jnp.clip(jnp.round(x + _dither(rng, f.shape)),
                     -127, 127).astype(jnp.int8)
    return {"q": q_new, "s": s_new}


def requantize(qt: QuantTable, update: jax.Array, rng: jax.Array, *,
               fused: bool = None) -> QuantTable:
    """The table-update entry point the quantized train step calls.
    `fused=None` (the default) auto-selects the fused Pallas row-pass
    (ops/pallas_requant.py) on a TPU backend and the multi-pass XLA
    reference elsewhere; True forces the kernel (interpret mode
    off-TPU — how the CPU tier-1 tests drive it), False forces the
    reference. Config.REQUANT_PALLAS maps onto this via
    resolve_requant_mode."""
    if fused is None:
        fused = jax.default_backend() == "tpu"
    if fused:
        from code2vec_tpu.ops.pallas_requant import requantize_fused
        return requantize_fused(qt, update, rng)
    return requantize_reference(qt, update, rng)


def resolve_tristate_mode(mode: str, flag: str):
    """The shared auto|fused|reference -> None|True|False mapping for
    kernel-dispatch config flags ("auto" = backend auto-select).
    Config.verify() rejects anything else; this raises for programmatic
    users bypassing verify(). `flag` names the offender in the error."""
    try:
        return {"auto": None, "fused": True, "reference": False}[mode]
    except KeyError:
        raise ValueError(
            f"{flag} must be auto|fused|reference, got {mode!r}")


def resolve_requant_mode(mode: str):
    """Config.REQUANT_PALLAS -> the `fused` argument of requantize()."""
    return resolve_tristate_mode(mode, "REQUANT_PALLAS")
