"""Live metrics plane (ISSUE 7 tentpole, layer 1): pull-based HTTP
exposition of the telemetry registry.

The PR-2 stack is record-then-analyze: JSONL on disk, summarized after
the run. Nothing answers "is this run healthy right now" without
tailing files. This module is the standard production answer — a tiny
stdlib-only HTTP server on a daemon thread, scrapable by Prometheus,
curl, or tools/obs_top.py:

  - `/metrics` — the registry snapshot in Prometheus text exposition
    format (version 0.0.4): counters as `counter`, gauges as `gauge`,
    timer histograms as `summary` (p50/p95/p99 quantiles + _sum/_count
    from the exact TimerStat fields). Gauge freshness rides along as
    a `gauge_age_seconds{gauge="..."}` family (telemetry.gauge_ages —
    a dead producer's queue-depth gauge keeps its last VALUE but its
    age grows, so scrapers can mark it stale instead of trusting it).
    Watchdog component liveness and alert states are exported too
    (`component_beat_age_seconds`, `component_stalled`,
    `alert_active`).
  - `/healthz` — component liveness fed by the watchdog's heartbeat
    table: 200 while every ACTIVE component is inside its deadline,
    503 the moment one is past it (computed from the live heartbeat
    timestamps at request time, not the edge-trigger memory — a load
    balancer probing readiness needs the current truth, not the event
    log). Serving readiness gates on this.
  - `/vars` — the raw JSON snapshot (registry + health monitor table
    + alert table + watchdog components), for humans and tools that
    want structure instead of the Prometheus grammar. ISSUE 17: it
    leads with an `identity` block (run_id, process_index, cohort
    size, the server's start wall/monotonic pair) so a fleet
    collector can label members without parsing JSONL manifests.
  - `/clock` — the fleet handshake (ISSUE 17): a paired
    monotonic+wall timestamp sampled at response-build time, plus the
    identity block. A collector brackets K of these with its own wall
    clock to estimate this host's wall-clock offset
    (round-trip-corrected midpoints, median of K), then COMMITS the
    measurement back (`/clock?commit=1&offset_s=...`): the member
    writes a `clock` block into its run manifest, which is what
    `trace_report.py --merge` consumes to align cohort traces on
    MEASURED offsets instead of the created_unix caveat.
  - `/fleet` — only when a FleetCollector is attached (the supervisor
    process): the latest cohort aggregate as JSON, or Prometheus text
    with `?format=prom`.

Snapshot-don't-lock discipline (ARCHITECTURE.md): handler threads
never take a lock the hot path contends on — they read dict snapshots
(atomic under the GIL against the single-writer fast path; the
threadsafe registries serving/training use under async flags lock
internally) and TimerStat's copy-then-sort percentile reads. A scrape
can see metric A from tick k and metric B from tick k+1; it can never
block a training step.

Lifecycle: `create()` returns the shared disabled singleton unless a
port is configured AND the telemetry registry is live, so every call
site wires unconditionally and pays one boolean check when off.
`start()` binds (port 0 = ephemeral, `bound_port` tells the truth)
and serves on a daemon thread; `stop()` shuts down cleanly. Stdlib
only — never imports jax or TensorFlow (guard:
tests/test_obs_guard.py).
"""

from __future__ import annotations

import http.server
import json
import re
import threading
import time
import urllib.parse
from typing import Any, Callable, Dict, List, NamedTuple, Optional

__all__ = ["LivePlane", "MetricsServer", "build_live_plane",
           "render_prometheus"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

# quantiles the summary blocks export — TimerStat.summary()'s exact set
_QUANTILES = ((0.5, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms"))


def _san(name: str) -> str:
    """Prometheus metric-name sanitization: `train/step_ms` ->
    `train_step_ms` (labels keep the raw name where identity
    matters)."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: Any) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if isinstance(v, float) else str(v)


def render_prometheus(telemetry, watchdog=None, health=None,
                      alerts=None) -> str:
    """The /metrics payload: one registry snapshot in text exposition
    format 0.0.4. Pure function of the snapshot so tests (and
    tools/obs_top.py's parser) can round-trip it without a socket."""
    lines: List[str] = []
    counters = dict(telemetry.counters)
    gauges = dict(telemetry.gauges)
    ages = telemetry.gauge_ages()
    timers = dict(telemetry.timers)

    for name in sorted(counters):
        n = _san(name)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_fmt(counters[name])}")
    for name in sorted(gauges):
        n = _san(name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt(gauges[name])}")
    if ages:
        lines.append("# HELP gauge_age_seconds seconds since each "
                     "gauge was last set (stale gauge = dead producer)")
        lines.append("# TYPE gauge_age_seconds gauge")
        for name in sorted(ages):
            lines.append(f'gauge_age_seconds{{gauge="{_san(name)}"}} '
                         f"{_fmt(round(ages[name], 3))}")
    for name in sorted(timers):
        stat = timers[name]
        n = _san(name)
        s = stat.summary() if stat.count else None
        lines.append(f"# TYPE {n} summary")
        for q, key in _QUANTILES:
            v = s[key] if s else float("nan")
            lines.append(f'{n}{{quantile="{q}"}} {_fmt(v)}')
        lines.append(f"{n}_sum {_fmt(round(stat.total_ms, 4))}")
        lines.append(f"{n}_count {stat.count}")

    if watchdog is not None and watchdog.enabled:
        status = watchdog.status()
        if status:
            lines.append("# TYPE component_beat_age_seconds gauge")
            for comp in sorted(status):
                row = status[comp]
                lines.append(
                    f'component_beat_age_seconds{{component='
                    f'"{_san(comp)}"}} {_fmt(round(row["age_s"], 3))}')
            lines.append("# TYPE component_stalled gauge")
            for comp in sorted(status):
                lines.append(
                    f'component_stalled{{component="{_san(comp)}"}} '
                    f"{1 if status[comp]['stalled'] else 0}")
    if alerts is not None and alerts.enabled:
        rows = alerts.status_table()
        if rows:
            lines.append("# TYPE alert_active gauge")
            for row in rows:
                lines.append(
                    f'alert_active{{rule="{_san(row["rule"])}"}} '
                    f"{1 if row['state'] == 'firing' else 0}")
    if health is not None and health.enabled:
        rows = health.status_table()
        if rows:
            # monitor VALUES are already health/* gauges; this family
            # adds the ok/bad verdicts in scrapeable form
            lines.append("# TYPE health_status gauge")
            for row in rows:
                up = {"ok": 0, "unknown": 0}.get(row["status"], 1)
                lines.append(
                    f'health_status{{monitor="{_san(row["monitor"])}"'
                    f'}} {up}')
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Daemon-thread HTTP server exposing /metrics, /healthz and
    /vars over one telemetry registry (plus the watchdog / health /
    alert tables when attached). Construct via `create()`."""

    def __init__(self, telemetry, *, port: int, host: str = "",
                 watchdog=None, health=None, alerts=None, fleet=None,
                 identity: Optional[Dict[str, Any]] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.enabled = True
        self.telemetry = telemetry
        self.watchdog = watchdog
        self.health = health
        self.alerts = alerts
        self.fleet = fleet
        self.port = port
        self.host = host
        self.bound_port: Optional[int] = None
        # identity block (ISSUE 17): who this endpoint is, stamped at
        # construction so the wall/monotonic pair anchors the process
        # start — call sites that know their cohort coordinates (the
        # train loops, via jax) override process_index/process_count;
        # this layer never imports jax to ask.
        self.identity: Dict[str, Any] = {
            "run_id": getattr(telemetry, "run_id", ""),
            "process_index": 0,
            "process_count": 1,
            "start_wall": time.time(),
            "start_mono": time.monotonic(),
        }
        self.identity.update(identity or {})
        self._log = log or (lambda _m: None)
        self._lock = threading.Lock()
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ---- construction ----
    @classmethod
    def create(cls, telemetry, *, port: int, **kw) -> "MetricsServer":
        """The wired-everywhere entry: disabled singleton unless a
        port is configured (`--metrics_port`, 0 = off) and the
        registry is live."""
        if port <= 0 or telemetry is None or not telemetry.enabled:
            return _NULL_SERVER
        return cls(telemetry, port=port, **kw)

    @classmethod
    def disabled(cls) -> "MetricsServer":
        return _NULL_SERVER

    # ---- request handling ----
    def _healthz(self) -> tuple:
        """(http_status, body_dict): 503 when any ACTIVE watchdog
        component is past its deadline RIGHT NOW, or a page-severity
        alert is firing; 200 otherwise. Liveness is recomputed from
        the heartbeat table at request time — a probe needs current
        truth, not the edge-trigger memory."""
        components: Dict[str, Any] = {}
        stalled: List[str] = []
        if self.watchdog is not None and self.watchdog.enabled:
            components = self.watchdog.status()
            stalled = [c for c, row in components.items()
                       if row["stalled"]]
        firing: List[str] = []
        if self.alerts is not None and self.alerts.enabled:
            firing = [r["rule"] for r in self.alerts.status_table()
                      if r["state"] == "firing"
                      and r.get("severity") == "page"]
        ok = not stalled and not firing
        body = {"status": "ok" if ok else "unhealthy",
                "stalled": stalled, "alerts_firing": firing,
                "components": components}
        return (200 if ok else 503), body

    def _vars(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"ts": time.time(),
                               "run_id": self.telemetry.run_id,
                               "identity": dict(self.identity),
                               **self.telemetry.summary()}
        out["gauge_age_s"] = {k: round(v, 3) for k, v in
                              self.telemetry.gauge_ages().items()}
        if self.watchdog is not None and self.watchdog.enabled:
            out["components"] = self.watchdog.status()
        if self.health is not None and self.health.enabled:
            out["health"] = self.health.status_table()
        if self.alerts is not None and self.alerts.enabled:
            out["alerts"] = self.alerts.status_table()
        return out

    def _clock(self, params: Dict[str, List[str]]) -> Dict[str, Any]:
        """The fleet handshake endpoint (ISSUE 17). Plain GET: one
        paired monotonic+wall sample (the monotonic reading shares the
        tracer's timebase, so a measured wall offset can realign span
        t0s) plus the identity block. `?commit=1&offset_s=X`: the
        collector's measured offset comes BACK — persist it, with a
        fresh anchor pair, as the run manifest's `clock` block so
        trace_report --merge can align this run's monotonic timeline
        onto the collector's wall clock. Memory registries have no
        manifest; `committed` reports the truth either way."""
        out: Dict[str, Any] = {"mono": time.monotonic(),
                               "wall": time.time(),
                               "identity": dict(self.identity)}
        if params.get("commit"):
            try:
                offset_s = float(params["offset_s"][0])
            except (KeyError, IndexError, ValueError):
                out["committed"] = False
                out["error"] = "commit needs a numeric offset_s"
                return out
            block = {"mono": out["mono"], "wall": out["wall"],
                     "wall_offset_s": offset_s}
            try:
                block["samples"] = int(params["samples"][0])
            except (KeyError, IndexError, ValueError):
                pass
            out["committed"] = bool(
                getattr(self.telemetry, "update_manifest",
                        lambda **_kw: False)(clock=block))
        return out

    def _respond(self, path: str) -> tuple:
        """(status, content_type, payload_bytes) for one GET; `path`
        may carry a query string."""
        path, _, query = path.partition("?")
        if path == "/metrics":
            text = render_prometheus(self.telemetry, self.watchdog,
                                     self.health, self.alerts)
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    text.encode("utf-8"))
        if path == "/healthz":
            status, body = self._healthz()
            return (status, "application/json",
                    json.dumps(body, default=str).encode("utf-8"))
        if path == "/vars":
            return (200, "application/json",
                    json.dumps(self._vars(), default=str,
                               indent=1).encode("utf-8"))
        if path == "/clock":
            body = self._clock(urllib.parse.parse_qs(query))
            return (200, "application/json",
                    json.dumps(body, default=str).encode("utf-8"))
        if path == "/fleet":
            fleet = self.fleet
            if fleet is None or not getattr(fleet, "enabled", False):
                return (404, "text/plain",
                        b"no fleet collector attached\n")
            params = urllib.parse.parse_qs(query)
            if params.get("format", [""])[0] == "prom":
                return (200,
                        "text/plain; version=0.0.4; charset=utf-8",
                        fleet.render_prometheus().encode("utf-8"))
            return (200, "application/json",
                    json.dumps(fleet.aggregate(), default=str,
                               indent=1).encode("utf-8"))
        return (404, "text/plain",
                b"not found (try /metrics, /healthz, /vars, /clock"
                b", /fleet)\n")

    # ---- lifecycle ----
    def start(self) -> "MetricsServer":
        with self._lock:
            if self._httpd is not None:
                return self
            server = self

            class _Handler(http.server.BaseHTTPRequestHandler):
                def do_GET(self):  # noqa: N802 — http.server API
                    try:
                        status, ctype, payload = server._respond(
                            self.path)
                    except Exception as e:  # noqa: BLE001 — a scrape
                        # must never take the run down with it
                        status, ctype = 500, "text/plain"
                        payload = repr(e).encode("utf-8")
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length",
                                     str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)

                def log_message(self, fmt, *args):
                    pass  # scrape chatter stays out of the train log

            self._httpd = http.server.ThreadingHTTPServer(
                (self.host, self.port), _Handler)
            self._httpd.daemon_threads = True
            self.bound_port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="metrics-exposition")
            self._thread.start()
        self._log(f"metrics: serving /metrics /healthz /vars /clock"
                  f"{' /fleet' if self.fleet is not None else ''} on "
                  f"port {self.bound_port}")
        return self

    def stop(self) -> None:
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)


class _NullMetricsServer(MetricsServer):
    """The `--metrics_port`-unset path: shared no-op singleton."""

    def __init__(self):
        self.enabled = False
        self.telemetry = None
        self.fleet = None
        self.identity = {}
        self.bound_port = None

    def start(self):
        return self

    def stop(self) -> None:
        pass


_NULL_SERVER = _NullMetricsServer()


class LivePlane(NamedTuple):
    """The three live-plane engines one call site wires together.
    Each is its own shared no-op singleton when its flag is off, so
    `start()`/`stop()` are unconditional."""

    health: Any
    alerts: Any
    metrics: Any

    def start(self) -> "LivePlane":
        self.health.start()
        self.metrics.start()
        return self

    def stop(self) -> None:
        self.health.stop()
        self.metrics.stop()


def build_live_plane(telemetry, *, metrics_port: int, alerts_mode: str,
                     alerts_rules: Optional[str],
                     health_every_s: float, watchdog, monitors,
                     default_rules: Callable[[], list],
                     identity: Optional[Dict[str, Any]] = None,
                     log: Optional[Callable[[str], None]] = None
                     ) -> LivePlane:
    """ONE wiring for the live metrics plane, shared by both train
    loops and the PredictionServer (the round-11
    `infeed_produce_instrument` lesson: hand-synced copies of
    cross-thread wiring drift): health monitors on a cadence thread,
    alert rules evaluated at each sweep's tail (so they always see the
    gauges that sweep just wrote), both attached to the watchdog's
    stall dump, and the /metrics //healthz //vars server over all of
    it. A user-supplied EMPTY rule file is honored as "no rules" —
    only the absence of a file falls back to `default_rules()`."""
    from code2vec_tpu.obs.alerts import AlertEngine, load_rules
    from code2vec_tpu.obs.health import HealthEngine

    live = metrics_port > 0 or alerts_mode != "off"
    health = HealthEngine.create(telemetry if live else None,
                                 interval_s=health_every_s, log=log)
    health.add(*monitors)
    rules = load_rules(alerts_rules)
    alerts = AlertEngine.create(
        telemetry, mode=alerts_mode,
        rules=rules if rules is not None else default_rules(),
        log=log)
    if alerts.enabled:
        health.add_listener(alerts.evaluate)
    watchdog.attach(health=health, alerts=alerts)
    metrics = MetricsServer.create(
        telemetry, port=metrics_port, watchdog=watchdog,
        health=health, alerts=alerts, identity=identity, log=log)
    return LivePlane(health, alerts, metrics)
