"""Training-health monitors (ISSUE 7 tentpole, layer 2): derived
signals evaluated on a cadence OFF the hot path.

The raw registry answers "what happened" (counters, gauges, timer
histograms); nothing in it answers "is this run healthy right now" —
a NaN loss trains on, a throughput regression ships silently, an
infeed stall reads as a slightly larger wait histogram. Each monitor
here turns raw series into ONE derived gauge (`health/<name>`), cheap
enough to recompute every second on a daemon thread, precise enough
for the alert engine (obs/alerts.py) to threshold on:

  - `NonFiniteGauges` — any watched gauge (train/loss; a grad-norm
    gauge if one is published) going NaN/inf. The canary for a
    diverged run: loss keeps "improving" as NaN compares false.
  - `EwmaZScore` — loss-spike detection: EWMA mean/variance of a
    gauge, publishes the current z-score. Robust to slow drift (the
    mean tracks), loud on step changes.
  - `CounterRate` — per-second rate of a counter (throughput), plus
    the ratio of the current rate to a rolling-median baseline: a
    regression shows up as ratio << 1 without anyone choosing an
    absolute threshold per config.
  - `TimerShare` — share of wall time one timer's total contributes
    against a group (infeed starvation: wait / (wait + step)).
  - `CounterRatio` — windowed numerator/denominator counter deltas
    (serving cache-hit rate, shed rate).
  - `OptEfficiency` — analytic-floor attainment of the train step:
    the sparse path's static `train/step_floor_ms` gauge (the
    [U, E]-aware traffic model, round 13) over observed p50 step
    time — bench.py's optimizer-efficiency story, live.

Monitors only READ the registry (snapshot-don't-lock: dict reads of
float values are atomic under the GIL; a torn multi-metric view skews
one evaluation by one tick, which the cadence tolerates) and WRITE
exactly one gauge each — so the hot path never sees them, and the
exposition endpoint serves their latest values for free.

`HealthEngine` owns the cadence: a daemon thread sweeps every monitor
each interval, then calls its listeners (the alert engine registers
itself) with the same `now`, so rules always evaluate the freshest
derived gauges. Fake-clock injectable (`clock=`) like the watchdog —
the tests advance time explicitly and call `check_now()`.

Disabled path (the PR 2 discipline): `HealthEngine.create(None)` (or a
disabled telemetry) returns a shared no-op singleton; instrumented
call sites cost one boolean check. Stdlib-only at import time.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["HealthEngine", "Monitor", "NonFiniteGauges", "EwmaZScore",
           "CounterRate", "TimerShare", "CounterRatio", "OptEfficiency",
           "PhaseRoofline", "default_train_monitors",
           "default_serving_monitors"]


def _is_finite(v: Any) -> bool:
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


class Monitor:
    """One derived signal. `evaluate(telemetry, now)` reads raw series,
    updates internal state, publishes `health/<name>` (emit=False — a
    gauge store, never a JSONL event per tick), and records its status
    row for the stall dump / /vars table."""

    def __init__(self, name: str):
        self.name = name
        self.value: float = float("nan")
        self.status: str = "unknown"  # "ok" | "bad" | "unknown"
        self.detail: str = ""

    def evaluate(self, telemetry, now: float) -> None:
        raise NotImplementedError

    def _publish(self, telemetry, value: float, status: str,
                 detail: str = "") -> None:
        self.value, self.status, self.detail = value, status, detail
        telemetry.gauge(f"health/{self.name}", value, emit=False)

    def row(self) -> Dict[str, Any]:
        return {"monitor": self.name, "value": self.value,
                "status": self.status, "detail": self.detail}


class NonFiniteGauges(Monitor):
    """1.0 while ANY watched gauge is non-finite, else 0.0. Watches
    gauges (not events): the recorder publishes `train/loss` every step
    for exactly this read."""

    def __init__(self, gauges: Sequence[str] = ("train/loss",),
                 name: str = "nonfinite"):
        super().__init__(name)
        self.watched = tuple(gauges)

    def evaluate(self, telemetry, now: float) -> None:
        seen = False
        bad: List[str] = []
        for g in self.watched:
            v = telemetry.gauges.get(g)
            if v is None:
                continue
            seen = True
            if not _is_finite(v):
                bad.append(g)
        if not seen:
            self._publish(telemetry, float("nan"), "unknown",
                          "no watched gauge published yet")
        elif bad:
            self._publish(telemetry, 1.0, "bad",
                          "non-finite: " + ", ".join(bad))
        else:
            self._publish(telemetry, 0.0, "ok")


class EwmaZScore(Monitor):
    """Spike detector: |z| of the newest gauge sample against an EWMA
    mean/variance of its history. Non-finite samples are skipped (the
    NonFiniteGauges monitor owns those); the variance floor keeps a
    perfectly flat warmup from dividing by zero on the first wiggle."""

    def __init__(self, gauge: str = "train/loss",
                 name: str = "loss_spike_z", alpha: float = 0.1,
                 warmup: int = 8, var_floor: float = 1e-12):
        super().__init__(name)
        self.gauge = gauge
        self.alpha = alpha
        self.warmup = warmup
        self.var_floor = var_floor
        self._mean: Optional[float] = None
        self._var = 0.0
        self._n = 0

    def evaluate(self, telemetry, now: float) -> None:
        v = telemetry.gauges.get(self.gauge)
        if v is None or not _is_finite(v):
            self._publish(telemetry, self.value,
                          self.status if v is None else "unknown",
                          "no finite sample")
            return
        v = float(v)
        if self._mean is None:
            self._mean = v
            self._n = 1
            self._publish(telemetry, 0.0, "ok", "warming up")
            return
        # z against the PRE-update stats: the spike itself must not
        # vanish into the mean it is being compared to
        sd = math.sqrt(max(self._var, self.var_floor))
        z = abs(v - self._mean) / sd if self._n >= self.warmup else 0.0
        d = v - self._mean
        self._mean += self.alpha * d
        self._var = (1 - self.alpha) * (self._var + self.alpha * d * d)
        self._n += 1
        self._publish(telemetry, z,
                      "ok" if self._n <= self.warmup else
                      ("bad" if z > 6.0 else "ok"))


class CounterRate(Monitor):
    """Per-second rate of a counter between sweeps, published as
    `health/<name>`; additionally publishes `health/<name>_ratio` —
    current rate over the rolling median of recent rates — so a
    throughput regression is a config-independent "ratio < 0.5", not
    an absolute examples/sec anyone has to tune per model size."""

    def __init__(self, counter: str = "train/examples",
                 name: str = "throughput", history: int = 30,
                 min_history: int = 5):
        super().__init__(name)
        self.counter = counter
        self._last: Optional[tuple] = None  # (now, count)
        self._rates: "collections.deque" = collections.deque(
            maxlen=history)
        self.min_history = min_history
        self.ratio: float = float("nan")

    def evaluate(self, telemetry, now: float) -> None:
        count = telemetry.counters.get(self.counter)
        if count is None:
            self._publish(telemetry, float("nan"), "unknown",
                          f"counter {self.counter} absent")
            return
        if self._last is None:
            self._last = (now, count)
            self._publish(telemetry, float("nan"), "unknown",
                          "first sample")
            return
        t0, c0 = self._last
        dt = now - t0
        if dt <= 0:
            return
        self._last = (now, count)
        rate = max(0.0, count - c0) / dt
        if rate == 0.0:
            # no progress at all this window: a legitimate pause
            # (epoch eval, checkpoint tail, first-step compile) or a
            # hang — either way NOT a throughput regression, and
            # liveness is the watchdog's domain (its busy()/idle()
            # exemption exists for exactly these gaps). Keep the last
            # verdict and don't poison the rolling baseline with 0s.
            self._publish(telemetry, self.value, self.status,
                          "no progress this window (liveness is the "
                          "watchdog's domain)")
            return
        baseline = (sorted(self._rates)[len(self._rates) // 2]
                    if len(self._rates) >= self.min_history else None)
        # the baseline excludes the current sample: a regression must
        # not drag down the very median it is judged against
        self._rates.append(rate)
        if baseline is None or baseline <= 0:
            self.ratio = float("nan")
            self._publish(telemetry, rate, "ok", "building baseline")
            return
        self.ratio = rate / baseline
        telemetry.gauge(f"health/{self.name}_ratio", self.ratio,
                        emit=False)
        self._publish(telemetry, rate,
                      "bad" if self.ratio < 0.5 else "ok",
                      f"ratio {self.ratio:.2f} vs rolling median")


class TimerShare(Monitor):
    """Share of one timer's total_ms against a group of timers, over
    the delta since the last sweep (infeed starvation: wait time as a
    fraction of wait + step — near 0 while the producer keeps up,
    toward 1 exactly when the input pipeline is the bottleneck)."""

    def __init__(self, numerator: str = "train/infeed_wait_ms",
                 denominators: Sequence[str] = ("train/infeed_wait_ms",
                                                "train/step_ms"),
                 name: str = "infeed_starvation"):
        super().__init__(name)
        self.numerator = numerator
        self.denominators = tuple(denominators)
        self._last_totals: Optional[Dict[str, float]] = None

    def evaluate(self, telemetry, now: float) -> None:
        totals = {}
        for t in set(self.denominators) | {self.numerator}:
            stat = telemetry.timers.get(t)
            totals[t] = stat.total_ms if stat is not None else 0.0
        if self._last_totals is None:
            self._last_totals = totals
            self._publish(telemetry, float("nan"), "unknown",
                          "first sample")
            return
        d_num = totals[self.numerator] - self._last_totals[self.numerator]
        d_den = sum(totals[t] - self._last_totals[t]
                    for t in self.denominators)
        self._last_totals = totals
        if d_den <= 0:
            # no step finished this tick — keep the last share instead
            # of a phantom 0/0 ("no work" is the watchdog's department)
            self._publish(telemetry, self.value, self.status, "no data")
            return
        share = min(1.0, max(0.0, d_num / d_den))
        self._publish(telemetry, share,
                      "bad" if share > 0.5 else "ok")


class CounterRatio(Monitor):
    """Windowed numerator/denominator counter-delta ratio: cache-hit
    rate (hits / (hits + misses)), shed rate (shed / submitted). The
    window is the sweep interval; ticks with no denominator traffic
    keep the previous value."""

    def __init__(self, numerator: str, denominators: Sequence[str],
                 name: str, bad_above: Optional[float] = None,
                 bad_below: Optional[float] = None,
                 min_events: int = 1):
        super().__init__(name)
        self.numerator = numerator
        self.denominators = tuple(denominators)
        self.bad_above = bad_above
        self.bad_below = bad_below
        self.min_events = min_events
        self._last: Optional[Dict[str, float]] = None

    def evaluate(self, telemetry, now: float) -> None:
        names = set(self.denominators) | {self.numerator}
        counts = {n: telemetry.counters.get(n, 0.0) for n in names}
        if self._last is None:
            self._last = counts
            self._publish(telemetry, float("nan"), "unknown",
                          "first sample")
            return
        d_num = counts[self.numerator] - self._last[self.numerator]
        d_den = sum(counts[n] - self._last[n]
                    for n in self.denominators)
        self._last = counts
        if d_den < self.min_events:
            self._publish(telemetry, self.value, self.status,
                          "no traffic this window")
            return
        ratio = d_num / d_den
        status = "ok"
        if self.bad_above is not None and ratio > self.bad_above:
            status = "bad"
        if self.bad_below is not None and ratio < self.bad_below:
            status = "bad"
        self._publish(telemetry, ratio, status)


class OptEfficiency(Monitor):
    """Analytic-floor attainment of the train step: a STATIC floor
    gauge (`train/step_floor_ms` — published once by the sparse-update
    train loop from the [U, E]-aware traffic model in
    training/sparse_update.py, over the HBM_CEILING_GBPS constant)
    divided by the observed p50 step time. Semantics mirror bench.py's
    `optimizer_efficiency` (throughput over the optimizer-free floor):
    near 1 means the step runs at its roofline, and ANY step-time
    regression — a de-fused sparse update, a new host sync, a slow
    kernel — pulls the gauge down mid-run instead of waiting for the
    next bench round. Publishes unknown while the floor gauge is
    absent (the dense path publishes none)."""

    def __init__(self, floor_gauge: str = "train/step_floor_ms",
                 timer: str = "train/step_ms",
                 name: str = "opt_efficiency",
                 bad_below: float = 0.25):
        super().__init__(name)
        self.floor_gauge = floor_gauge
        self.timer = timer
        self.bad_below = bad_below

    def evaluate(self, telemetry, now: float) -> None:
        floor = telemetry.gauges.get(self.floor_gauge)
        stat = telemetry.timers.get(self.timer)
        if floor is None or not _is_finite(floor) or float(floor) <= 0:
            self._publish(telemetry, float("nan"), "unknown",
                          "no step-floor gauge published")
            return
        if stat is None or stat.count == 0:
            self._publish(telemetry, float("nan"), "unknown",
                          "no step samples yet")
            return
        p50 = stat.percentile(50)
        if p50 <= 0:
            self._publish(telemetry, self.value, self.status,
                          "zero p50")
            return
        eff = min(1.0, float(floor) / p50)
        self._publish(telemetry, eff,
                      "bad" if eff < self.bad_below else "ok")


class PhaseRoofline(Monitor):
    """Per-phase roofline gauges + split-coverage verdict (ISSUE 15).

    Reads the sampled phase-split timers (`train/phase/<p>_ms`, written
    by obs/phases.PhaseProfiler every --phase_sample_every steps) and
    the static analytic traffic gauges (`train/phase_bytes/<p>`, from
    training/sparse_update.phase_traffic_bytes) and publishes one
    `health/phase_<p>` gauge per phase: achieved GB/s (bytes over the
    observed p50) divided by the `train/phase_ceiling_gbps` streaming
    ceiling — each phase's live roofline attainment, the per-phase
    generalization of OptEfficiency above. The monitor's own value is
    the SPLIT COVERAGE: sum of device-phase p50s over the fused sampled
    dispatch's p50 — the live form of the "phases sum to within 15% of
    the fused step" acceptance; far from 1 means the split no longer
    describes the fused step (a new unattributed stage, or fusion wins
    the probes cannot see). Unknown until the first sampled step lands
    (phase profiling off = no timers, no verdict)."""

    _PREFIX = "train/phase/"

    def __init__(self, name: str = "phase_coverage",
                 bad_beyond: float = 0.25):
        super().__init__(name)
        self.bad_beyond = bad_beyond

    def evaluate(self, telemetry, now: float) -> None:
        # the one list of phases that are device time inside the fused
        # dispatch (infeed wait is host time outside it; the allreduce
        # pair is comm the backward phase already carries) — owned by
        # the profiler, stdlib-only at import time like this module
        from code2vec_tpu.obs.phases import DEVICE_PHASES
        fused = telemetry.timers.get(self._PREFIX + "fused_step_ms")
        if fused is None or fused.count == 0:
            self._publish(telemetry, float("nan"), "unknown",
                          "no sampled phase-split step yet")
            return
        ceiling = telemetry.gauges.get("train/phase_ceiling_gbps")
        total = 0.0
        for tname, stat in list(telemetry.timers.items()):
            if not tname.startswith(self._PREFIX) \
                    or not tname.endswith("_ms") or stat.count == 0:
                continue
            phase = tname[len(self._PREFIX):-3]
            p50 = stat.percentile(50)
            if phase in DEVICE_PHASES:
                total += p50
            nbytes = telemetry.gauges.get(f"train/phase_bytes/{phase}")
            if nbytes and _is_finite(nbytes) and ceiling \
                    and _is_finite(ceiling) and p50 > 0:
                util = (float(nbytes) / (p50 / 1e3)) \
                    / (float(ceiling) * 1e9)
                telemetry.gauge(f"health/phase_{phase}",
                                min(1.0, util), emit=False)
        fused_p50 = fused.percentile(50)
        if fused_p50 <= 0:
            self._publish(telemetry, self.value, self.status,
                          "zero fused p50")
            return
        cov = total / fused_p50
        self._publish(telemetry, cov,
                      "bad" if abs(cov - 1.0) > self.bad_beyond
                      else "ok",
                      f"split phases cover {cov:.2f} of fused p50")


def default_train_monitors() -> List[Monitor]:
    """The train-loop set: non-finite loss, loss spike, throughput
    regression, infeed starvation, analytic-floor attainment. Raw
    inputs are the gauges/timers both train loops already publish
    through TrainStepRecorder (+ the sparse path's static floor
    gauge)."""
    return [
        NonFiniteGauges(("train/loss",), name="loss_nonfinite"),
        EwmaZScore("train/loss", name="loss_spike_z"),
        CounterRate("train/examples", name="throughput"),
        TimerShare(name="infeed_starvation"),
        OptEfficiency(name="opt_efficiency"),
        PhaseRoofline(name="phase_coverage"),
    ]


def default_serving_monitors() -> List[Monitor]:
    """The serving set: cache-hit collapse and shed rate over the
    PredictionServer's counters."""
    return [
        CounterRatio("serve/cache_hit",
                     ("serve/cache_hit", "serve/cache_miss"),
                     name="cache_hit_rate", min_events=8),
        CounterRatio("serve/shed",
                     ("serve/requests", "serve/shed"),
                     name="shed_rate", bad_above=0.05, min_events=8),
    ]


class HealthEngine:
    """Cadenced evaluator: one daemon thread sweeps every monitor each
    `interval_s`, then notifies listeners (the alert engine) with the
    sweep timestamp. Construct via `create()` (shared no-op singleton
    when telemetry is off) — the monitor thread exists only when
    something can read its output."""

    def __init__(self, telemetry, *, interval_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 log: Optional[Callable[[str], None]] = None):
        assert interval_s > 0
        self.enabled = True
        self.telemetry = telemetry
        self.interval_s = interval_s
        self._clock = clock
        self._log = log or (lambda _m: None)
        self._lock = threading.Lock()
        self._monitors: List[Monitor] = []
        self._listeners: List[Callable[[float], None]] = []
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- construction ----
    @classmethod
    def create(cls, telemetry, **kw) -> "HealthEngine":
        if telemetry is None or not telemetry.enabled:
            return _NULL_HEALTH
        return cls(telemetry, **kw)

    @classmethod
    def disabled(cls) -> "HealthEngine":
        return _NULL_HEALTH

    # ---- composition ----
    def add(self, *monitors: Monitor) -> "HealthEngine":
        with self._lock:
            self._monitors.extend(monitors)
        return self

    def add_listener(self, fn: Callable[[float], None]) -> None:
        """Called after every sweep with the sweep's `now` (the alert
        engine registers its evaluate here, so rules always see the
        derived gauges this sweep just wrote)."""
        with self._lock:
            self._listeners.append(fn)

    # ---- evaluation ----
    def check_now(self) -> List[Dict[str, Any]]:
        """One synchronous sweep (what the thread runs each interval;
        tests drive it directly under a fake clock). Returns the
        status table."""
        now = self._clock()
        with self._lock:
            monitors = list(self._monitors)
            listeners = list(self._listeners)
        for m in monitors:
            try:
                m.evaluate(self.telemetry, now)
            except Exception as e:  # noqa: BLE001 — a broken monitor
                # must not kill the sweep thread (or the run)
                m.status, m.detail = "error", repr(e)
                self._log(f"health: monitor {m.name} failed: {e!r}")
        for fn in listeners:
            fn(now)
        return self.status_table()

    def status_table(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [m.row() for m in self._monitors]

    # ---- lifecycle ----
    def start(self) -> "HealthEngine":
        with self._lock:
            if self._thread is None:
                self._stop_event.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="health-monitor")
                self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop_event.set()
        if thread is not None:
            thread.join(timeout=5)

    def _run(self) -> None:
        me = threading.current_thread()
        while not self._stop_event.wait(self.interval_s):
            if self._thread is not me:  # superseded by stop()+start()
                return
            self.check_now()


class _NullHealthEngine(HealthEngine):
    """The off path: every method a no-op, shared singleton."""

    def __init__(self):
        self.enabled = False
        self.telemetry = None

    def add(self, *monitors):
        return self

    def add_listener(self, fn):
        pass

    def check_now(self):
        return []

    def status_table(self):
        return []

    def start(self):
        return self

    def stop(self) -> None:
        pass


_NULL_HEALTH = _NullHealthEngine()
