"""SLO alert engine (ISSUE 7 tentpole, layer 3): declarative rules
over registry series, edge-triggered events, warn|raise discipline.

The health monitors (obs/health.py) turn raw series into derived
gauges; this layer turns gauges into DECISIONS. Two rule kinds, both
the standard production shapes:

  - `threshold` — compare one series against a constant, with an
    optional `for_s` hold (the Prometheus `for:` clause): the
    condition must stay true that long before the rule fires, so one
    noisy sample can't page anyone. The series reference resolves a
    gauge first, then a counter, and `name:p99` reads a timer
    percentile — `serve/request_ms:p99 > 250 for 30s` is a latency
    SLO in one line.
  - `burn_rate` — multi-window error-budget burn (the Google SRE
    workbook shape): the ratio of a bad-events counter to a total
    counter, computed over BOTH a short and a long window, must
    exceed the threshold in each. The short window makes the alert
    fast on a real outage; the long window keeps a brief blip from
    firing it. The engine keeps its own (t, num, den) sample ring per
    rule — counters are cumulative, so windowed rates need history
    the registry doesn't store.

Rules are data: built-in defaults cover the health monitors
(non-finite loss, loss spike, throughput regression, infeed
starvation, cache-hit collapse, shed burn-rate) and `--alerts_rules
<file.json>` replaces them with a JSON list (README "Live metrics &
alerts" documents the syntax).

Alerts are edge-triggered state machines (ok -> pending -> firing ->
resolved): ONE `alert` JSONL event + stdout line per transition, so a
condition that stays bad for an hour produces two lines, not a flood.
`mode="raise"` reuses the watchdog's sticky-error discipline: the
firing rule arms a sticky `AlertError` that re-raises at the training
loop's next beat (`poll()`, wired through TrainStepRecorder.end_step)
and at the end-of-run poll — never from the monitor thread, whose
raise nobody would catch.

Disabled path (the PR 2 discipline): `AlertEngine.create(None)` or
mode "off" returns a shared no-op singleton; the per-step hot-path
cost of an armed engine is one attribute check (`_sticky is None`).
Stdlib-only at import time.
"""

from __future__ import annotations

import collections
import json
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["AlertError", "AlertRule", "AlertEngine", "load_rules",
           "default_train_rules", "default_serving_rules",
           "serving_slo_rules"]

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


class AlertError(RuntimeError):
    """A firing alert under `mode="raise"` — surfaced at the training
    loop's next beat, never from the monitor thread."""


class AlertRule:
    """One declarative rule. Threshold form:

        AlertRule("loss_nonfinite", metric="health/loss_nonfinite",
                  op=">=", value=1)

    Burn-rate form (`kind="burn_rate"`): `metric` is the bad-events
    counter, `denominator` the total-events counter — "+"-separated
    names are summed, which matters when no single counter covers all
    outcomes (`serve/requests` counts only successes, so the shed
    burn-rate divides by `serve/requests+serve/shed`; a denominator
    that stops moving during a total outage would silence the alert
    exactly when it matters). `windows` is the (short_s, long_s)
    pair, `value` the budget-burn ratio both windows must exceed.
    """

    def __init__(self, name: str, metric: str, *,
                 kind: str = "threshold", op: str = ">",
                 value: float = 0.0, for_s: float = 0.0,
                 denominator: str = "",
                 windows: Sequence[float] = (60.0, 300.0),
                 severity: str = "page"):
        if kind not in ("threshold", "burn_rate"):
            raise ValueError(f"rule {name!r}: kind must be threshold "
                             f"or burn_rate (got {kind!r})")
        if op not in _OPS:
            raise ValueError(f"rule {name!r}: op must be one of "
                             f"{sorted(_OPS)} (got {op!r})")
        if kind == "burn_rate":
            if not denominator:
                raise ValueError(f"rule {name!r}: burn_rate needs a "
                                 "denominator counter")
            if len(windows) != 2 or windows[0] >= windows[1]:
                raise ValueError(f"rule {name!r}: windows must be "
                                 "(short_s, long_s) with short < long")
        self.name = name
        self.metric = metric
        self.kind = kind
        self.op = op
        self.value = float(value)
        self.for_s = float(for_s)
        self.denominator = denominator
        self._den_names = tuple(n.strip()
                                for n in denominator.split("+") if n)
        self.windows = tuple(float(w) for w in windows)
        self.severity = severity
        # state machine: "ok" | "pending" | "firing"
        self.state = "ok"
        self.since: Optional[float] = None   # entered current state at
        self.last_value: float = float("nan")
        # burn-rate sample ring: (t, num, den), long-window deep
        self._samples: "collections.deque" = collections.deque()

    # ---- evaluation ----
    def _resolve(self, telemetry) -> Optional[float]:
        """Threshold series lookup: gauge, else counter, else
        `name:pNN` timer percentile. None = series not published yet
        (the rule stays quiet — absence is the watchdog's domain)."""
        name, _, pct = self.metric.partition(":")
        if pct:
            stat = telemetry.timers.get(name)
            if stat is None or stat.count == 0:
                return None
            return stat.percentile(float(pct.lstrip("pP")))
        v = telemetry.gauges.get(name)
        if v is None:
            v = telemetry.counters.get(name)
        return None if v is None else float(v)

    def _condition(self, telemetry, now: float):
        """(condition_met, observed_value) — or (None, nan) when the
        series isn't there yet."""
        if self.kind == "threshold":
            v = self._resolve(telemetry)
            if v is None or not math.isfinite(v):
                # a non-finite gauge can't be compared; the
                # nonfinite health monitor exists to turn it into a
                # finite 0/1 signal rules CAN threshold on
                return None, float("nan")
            return _OPS[self.op](v, self.value), v
        # burn_rate: sample the counters, trim to the long window,
        # require both windowed ratios over the threshold
        num = float(telemetry.counters.get(self.metric, 0.0))
        den = float(sum(telemetry.counters.get(d, 0.0)
                        for d in self._den_names))
        self._samples.append((now, num, den))
        short_s, long_s = self.windows
        # keep ONE sample at/past the long cutoff so the long window
        # always has a base to difference against
        while len(self._samples) >= 2 \
                and now - self._samples[1][0] >= long_s:
            self._samples.popleft()

        def ratio(window_s: float) -> Optional[float]:
            cutoff = now - window_s
            base = None
            for t, n, d in self._samples:
                if t <= cutoff:
                    base = (n, d)
                else:
                    break
            if base is None:
                # not enough history for this window yet: no verdict —
                # a burn-rate needs its full window before it can
                # claim the budget is burning (fail-quiet beats a
                # false page on the first bad minute)
                return None
            d_num, d_den = num - base[0], den - base[1]
            return d_num / d_den if d_den > 0 else None

        r_short, r_long = ratio(short_s), ratio(long_s)
        if r_short is None or r_long is None:
            return None, float("nan")
        met = (_OPS[self.op](r_short, self.value)
               and _OPS[self.op](r_long, self.value))
        return met, r_short

    def evaluate(self, telemetry, now: float) -> Optional[str]:
        """Advance the state machine one tick. Returns "firing" or
        "resolved" on a transition worth reporting, else None."""
        met, value = self._condition(telemetry, now)
        self.last_value = value
        if met is None:
            return None
        if met:
            if self.state == "ok":
                self.state, self.since = "pending", now
            if self.state == "pending" and now - self.since >= self.for_s:
                self.state, self.since = "firing", now
                return "firing"
            return None
        was_firing = self.state == "firing"
        self.state, self.since = "ok", now
        return "resolved" if was_firing else None

    def row(self) -> Dict[str, Any]:
        # key is "rule_kind", not "kind": these rows are splatted into
        # Telemetry.event("alert", **row), whose first field is kind
        out = {"rule": self.name, "rule_kind": self.kind,
               "state": self.state, "metric": self.metric,
               "op": self.op, "threshold": self.value,
               "value": self.last_value, "severity": self.severity}
        if self.for_s:
            out["for_s"] = self.for_s
        if self.kind == "burn_rate":
            out["denominator"] = self.denominator
            out["windows"] = list(self.windows)
        return out


def default_train_rules() -> List[AlertRule]:
    """Rules over the default train health monitors. The spike/
    regression thresholds are deliberately loose — page-worthy, not
    dashboard-worthy (the monitors' gauges stay visible on /metrics
    either way)."""
    return [
        AlertRule("loss_nonfinite", metric="health/loss_nonfinite",
                  op=">=", value=1.0),
        AlertRule("loss_spike", metric="health/loss_spike_z",
                  op=">", value=8.0, severity="ticket"),
        AlertRule("throughput_regression",
                  metric="health/throughput_ratio",
                  op="<", value=0.5, for_s=10.0, severity="ticket"),
        AlertRule("infeed_starvation",
                  metric="health/infeed_starvation",
                  op=">", value=0.5, for_s=10.0, severity="ticket"),
    ]


def default_serving_rules() -> List[AlertRule]:
    return [
        AlertRule("cache_hit_collapse",
                  metric="health/cache_hit_rate",
                  op="<", value=0.1, for_s=10.0, severity="ticket"),
        # denominator = ALL submissions: serve/requests counts only
        # completed requests, so dividing by it alone would zero out
        # (and silence the alert) during a 100%-shed outage
        AlertRule("shed_burn_rate", metric="serve/shed",
                  kind="burn_rate",
                  denominator="serve/requests+serve/shed",
                  op=">", value=0.05, windows=(60.0, 300.0)),
    ]


def serving_slo_rules(slo_ms: float = 250.0, *,
                      windows: Sequence[float] = (30.0, 120.0)
                      ) -> List[AlertRule]:
    """The external-serving SLO rule set (ISSUE 18): what the
    autoscaler's policy loop scales on, and what the serving front-end
    reports. The p99 rule and the shed burn-rate are the two
    page-severity signals the pool grows on; `reload_refused` and
    `replica_dead` are ticket-severity — operator-visible facts that
    the system already self-healed (refused the corrupt step, refilled
    the dead replica), not pages."""
    return [
        AlertRule("serving_p99_slo", metric="serve/request_ms:p99",
                  op=">", value=float(slo_ms), for_s=5.0),
        # same denominator discipline as default_serving_rules: divide
        # by ALL submissions or a total-shed outage silences the alert
        AlertRule("serving_shed_burn", metric="serve/shed",
                  kind="burn_rate",
                  denominator="serve/requests+serve/shed",
                  op=">", value=0.05, windows=windows),
        AlertRule("reload_refused", metric="serve/reload_refused",
                  op=">=", value=1.0, severity="ticket"),
        AlertRule("replica_dead", metric="serve/replica_dead",
                  op=">=", value=1.0, severity="ticket"),
    ]


def load_rules(path: Optional[str]) -> Optional[List[AlertRule]]:
    """Parse a `--alerts_rules` JSON file: a list of rule objects whose
    keys mirror AlertRule's arguments (README documents the syntax).
    None path -> None (callers fall back to the built-in defaults)."""
    if not path:
        return None
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    if not isinstance(raw, list):
        raise ValueError(f"{path}: expected a JSON list of rule "
                         "objects")
    rules = []
    for i, obj in enumerate(raw):
        if not isinstance(obj, dict) or "name" not in obj \
                or "metric" not in obj:
            raise ValueError(f"{path}[{i}]: each rule needs at least "
                             "name and metric")
        kw = dict(obj)
        rules.append(AlertRule(kw.pop("name"), kw.pop("metric"), **kw))
    return rules


class AlertEngine:
    """Rule evaluator + sticky-raise plumbing. Evaluation runs as a
    HealthEngine listener (same sweep, same `now`) or directly via
    `check_now()`; transitions emit one `alert` event + stdout line
    each. Construct via `create()` — a disabled singleton when
    telemetry is off or mode is "off"."""

    def __init__(self, telemetry, rules: Sequence[AlertRule], *,
                 mode: str = "warn",
                 clock: Callable[[], float] = time.monotonic,
                 log: Optional[Callable[[str], None]] = None):
        assert mode in ("warn", "raise")
        self.enabled = True
        self.telemetry = telemetry
        self.mode = mode
        self.rules = list(rules)
        self._clock = clock
        self._log = log or (lambda _m: None)
        self._lock = threading.Lock()
        self._sticky: Optional[AlertError] = None

    # ---- construction ----
    @classmethod
    def create(cls, telemetry, *, mode: str = "off",
               rules: Optional[Sequence[AlertRule]] = None,
               **kw) -> "AlertEngine":
        if mode == "off" or telemetry is None or not telemetry.enabled:
            return _NULL_ALERTS
        return cls(telemetry, rules if rules is not None else [],
                   mode=mode, **kw)

    @classmethod
    def disabled(cls) -> "AlertEngine":
        return _NULL_ALERTS

    # ---- evaluation ----
    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """One sweep over every rule (the HealthEngine listener form —
        pass its `now` so rules and monitors agree on time). Returns
        the transitions reported this sweep."""
        t = self._clock() if now is None else now
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            rules = list(self.rules)
        for rule in rules:
            change = rule.evaluate(self.telemetry, t)
            if change is None:
                continue
            row = rule.row()
            row["transition"] = change
            transitions.append(row)
        for row in transitions:
            self.telemetry.count("alerts/transitions")
            self.telemetry.event("alert", **row)
            verb = ("ALERT firing" if row["transition"] == "firing"
                    else "alert resolved")
            self._log(
                f"alerts: {verb}: {row['rule']} "
                f"({row['metric']} {row['op']} {row['threshold']}, "
                f"observed {row['value']:.4g}, "
                f"severity {row['severity']})")
            if row["transition"] == "firing":
                self.telemetry.count("alerts/fired")
                if self.mode == "raise":
                    with self._lock:
                        if self._sticky is None:
                            self._sticky = AlertError(
                                f"alert {row['rule']} firing: "
                                f"{row['metric']} {row['op']} "
                                f"{row['threshold']} (observed "
                                f"{row['value']:.4g})")
        # live alert-state gauges: /metrics exposes firing rules
        # without parsing the event log
        firing = sum(1 for r in rules if r.state == "firing")
        self.telemetry.gauge("alerts/firing", firing, emit=False)
        return transitions

    def check_now(self) -> List[Dict]:
        return self.evaluate()

    # ---- sticky-raise (the watchdog discipline) ----
    def poll(self) -> None:
        """Re-raise a sticky firing alert (`mode="raise"`); no-op in
        warn mode. Call sites: TrainStepRecorder.end_step (the loop's
        next beat) and the end-of-run poll next to watchdog.poll()."""
        with self._lock:
            err, self._sticky = self._sticky, None
        if err is not None:
            raise err

    def status_table(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.row() for r in self.rules]


class _NullAlertEngine(AlertEngine):
    """The alerts-off path: shared no-op singleton, `_sticky` pinned
    to None so the hot-path guard is one attribute read."""

    def __init__(self):
        self.enabled = False
        self.telemetry = None
        self.mode = "warn"
        self.rules = []
        self._sticky = None

    def evaluate(self, now=None):
        return []

    def check_now(self):
        return []

    def poll(self) -> None:
        pass

    def status_table(self):
        return []


_NULL_ALERTS = _NullAlertEngine()
