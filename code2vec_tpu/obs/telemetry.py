"""Unified run telemetry: one registry, pluggable sinks.

ISSUE 2 (observability): the point tools that accreted around the train
loop — `training/profiler.py` trace windows, `training/scalars.py`
TensorBoard scalars, bench.py's hand-rolled slope timing — don't
compose, and none of them can answer the production questions in-band:
is the step device-bound or infeed-bound, what does a serving request
cost at p99, what did THIS run record. `Telemetry` is the one layer
they all feed:

  - counters / gauges / timer histograms (p50/p95/p99 + max) held
    in-process, cheap enough for per-step recording;
  - pluggable sinks (obs/sinks.py): a per-run JSONL event log under
    `--telemetry_dir` opened with a run manifest (run_id, config
    snapshot, device/mesh topology, process index), a TensorBoard
    adapter reusing `ScalarWriter`, and stdout;
  - span helpers explicit about host-vs-device time: `span()` is a
    plain monotonic host timer; `span().stop(sync=tree)` blocks on a
    device tree first (the same hard-sync trick `StepProfiler` uses),
    so step latency measures the chip, not the dispatch.

Dependency-light by contract: this module imports only the stdlib at
import time; jax is imported lazily (manifest topology, device sync)
and TensorFlow never (the TensorBoard sink reuses an externally-owned
`ScalarWriter`, which itself degrades to a no-op without TF). The
disabled path (`--telemetry_dir` unset) is a shared singleton whose
`enabled` is False — hot loops guard on that ONE boolean and allocate
nothing per step.

Not thread-safe by default: record from the loop thread that owns the
instance (the infeed producer thread never touches telemetry). The
serving subsystem is the exception — client threads, the extractor
pool, and the batcher thread all record into one registry — so
`make_threadsafe()` installs an RLock around the mutating surface;
the train loop keeps the lock-free fast path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Mapping, Optional, Sequence

__all__ = ["Telemetry", "TimerStat", "device_sync"]

# percentiles every summary reports; the serving latency line and
# tools/telemetry_report.py render exactly these
SUMMARY_PERCENTILES = (50, 95, 99)


def device_sync(tree) -> None:
    """Block until `tree`'s device computation has completed.

    Sync via a host transfer of a tiny on-device reduction:
    block_until_ready can return early on the tunneled axon platform
    (BASELINE.md timing methodology), which would time a step while its
    work is still in flight. Shared with StepProfiler._stop.
    """
    import jax
    import jax.numpy as jnp

    try:
        leaf = jax.tree_util.tree_leaves(tree)[0]
        # 0-d leaves (a bare loss scalar — the phase probes'
        # forward/backward outputs) have no axis to slice; indexing one
        # would raise and silently demote this sync to the unreliable
        # block_until_ready path. Everything else keeps the last-axis
        # sliver: the transferred probe must stay O(tiny) or the sync
        # itself would skew the timings it bounds.
        probe = leaf if getattr(leaf, "ndim", 1) == 0 else leaf[..., :1]
        float(jnp.sum(probe.astype(jnp.float32)))
    except Exception:
        jax.block_until_ready(tree)


class TimerStat:
    """Streaming timer histogram: exact count/total/max plus a bounded
    sample ring for percentiles (the last `cap` samples — recent-window
    percentiles, which is what a long run wants anyway: p99 of the
    current regime, not of compile-step outliers hours ago)."""

    __slots__ = ("count", "total_ms", "max_ms", "_ring", "_cap", "_lock")

    def __init__(self, cap: int = 2048):
        assert cap >= 1
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0
        self._cap = cap
        self._ring: list = []
        # installed by Telemetry.make_threadsafe() (the OWNING
        # registry's lock): percentile reads then snapshot under it
        self._lock: Optional[threading.RLock] = None

    def record(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms
        if len(self._ring) < self._cap:
            self._ring.append(ms)
        else:
            self._ring[self.count % self._cap] = ms

    @property
    def mean_ms(self) -> float:
        return self.total_ms / self.count if self.count else float("nan")

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the sample window.

        Threadsafe mode (the registry's `make_threadsafe()`) installs
        the registry lock here, so the ring snapshot cannot interleave
        with a concurrent `record` from another thread. WITHOUT the
        lock (the train loop's single-threaded fast path) the snapshot
        relies on CPython list-copy atomicity under the GIL — safe only
        when every `record` happens on the reading thread; concurrent
        lock-free use could sort a ring mid-mutation and return a
        value from a torn window."""
        lock = self._lock
        if lock is not None:
            with lock:
                if not self._ring:
                    return float("nan")
                s = sorted(self._ring)
        else:
            if not self._ring:
                return float("nan")
            s = sorted(list(self._ring))
        k = int(round(p / 100.0 * (len(s) - 1)))
        return s[max(0, min(len(s) - 1, k))]

    def summary(self) -> Dict[str, float]:
        out: Dict[str, float] = {"count": self.count,
                                 "mean_ms": round(self.mean_ms, 4),
                                 "max_ms": round(self.max_ms, 4)}
        for p in SUMMARY_PERCENTILES:
            out[f"p{p}_ms"] = round(self.percentile(p), 4)
        return out


class _Span:
    """One in-flight timing: host-monotonic start at construction,
    `stop()` records into the owning timer. `stop(sync=tree)` makes it
    device-sync-aware: the span ends only when the device work behind
    `tree` has completed, so it measures chip time, not dispatch time.
    """

    __slots__ = ("_tele", "_name", "_t0")

    def __init__(self, tele: "Telemetry", name: str):
        self._tele = tele
        self._name = name
        self._t0 = time.perf_counter()

    def stop(self, sync=None) -> float:
        if self._tele is None:  # cancelled: defensively closed already
            return 0.0
        if sync is not None:
            device_sync(sync)
        ms = (time.perf_counter() - self._t0) * 1e3
        self._tele.record_ms(self._name, ms)
        return ms

    def cancel(self) -> None:
        """Close WITHOUT recording — the error-path release (graftlint
        resource-leak discipline): a request that died mid-span must
        not leak the span, but its partial duration would pollute the
        latency histogram, so it is dropped instead of stopped."""
        self._tele = None


class _NullSpan:
    __slots__ = ()

    def stop(self, sync=None) -> float:
        return 0.0

    def cancel(self) -> None:
        pass


_NULL_SPAN = _NullSpan()

# run ids within one process get a monotonic suffix so two runs created
# in the same second (tests, back-to-back tools) never collide
_RUN_SEQ = [0]


def _build_run_manifest(config, mesh, component: str) -> Dict[str, Any]:
    process_index, process_count = 0, 1
    devices: Dict[str, Any] = {}
    try:  # lazy: keep obs importable (and fast) without a backend
        import jax
        process_index = jax.process_index()
        process_count = jax.process_count()
        devs = jax.devices()
        devices = {"platform": devs[0].platform, "count": len(devs),
                   "local_count": len(jax.local_devices())}
    except Exception as e:
        # record WHY topology is absent instead of swallowing it — a
        # manifest without device info should say so
        devices = {"unavailable": str(e)[:200]}
    _RUN_SEQ[0] += 1
    run_id = (f"run-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
              f"-p{process_index}-{_RUN_SEQ[0]}")
    manifest: Dict[str, Any] = {
        "run_id": run_id,
        "component": component,
        "created": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "created_unix": time.time(),
        "process_index": process_index,
        "process_count": process_count,
        "devices": devices,
    }
    if mesh is not None:
        try:
            manifest["mesh"] = dict(zip(mesh.axis_names,
                                        mesh.devices.shape))
        except Exception:
            manifest["mesh"] = str(mesh)
    if config is not None:
        try:
            manifest["config"] = dataclasses.asdict(config)
        except TypeError:
            manifest["config"] = {
                k: v for k, v in vars(config).items()
                if isinstance(v, (int, float, str, bool, type(None)))}
    return manifest


class Telemetry:
    """Registry of counters, gauges and timer histograms feeding a list
    of sinks. Construct via `create()` (file-backed run, or the shared
    disabled singleton when no directory is given) or `memory()` (live
    histograms, no persistence — the serving REPL's always-on mode)."""

    def __init__(self, sinks: Sequence = (), run_id: str = "",
                 enabled: bool = True):
        self.enabled = enabled
        self.run_id = run_id
        self.run_dir: Optional[str] = None
        self.sinks = list(sinks)
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        # last-update time (time.monotonic) per gauge: a dead producer's
        # queue-depth gauge must not read as a live value forever —
        # /metrics and the watchdog's stall dump mark stale gauges from
        # these timestamps (gauge_ages()).
        self.gauge_updated: Dict[str, float] = {}
        self.timers: Dict[str, TimerStat] = {}
        # None = lock-free fast path (the train loop); serving calls
        # make_threadsafe() because many threads share one registry
        self._lock: Optional[threading.RLock] = None

    def make_threadsafe(self) -> "Telemetry":
        """Install an RLock around the mutating surface (count / gauge /
        record_ms / event / summary / close) and onto every timer's
        percentile reads (existing and future — TimerStat.percentile).
        Returns self, so call sites can chain:
        `Telemetry.memory("serve").make_threadsafe()`."""
        if self._lock is None:
            self._lock = threading.RLock()
            for t in self.timers.values():
                t._lock = self._lock
        return self

    # shared stateless instance: the lock-free path must not allocate
    # a context manager per record
    _NO_LOCK = contextlib.nullcontext()

    def _guard(self):
        return self._lock if self._lock is not None else self._NO_LOCK

    # ---- construction ----
    @classmethod
    def create(cls, telemetry_dir: Optional[str], *, config=None,
               mesh=None, component: str = "run", scalar_writer=None,
               log: Optional[Callable[[str], None]] = None) -> "Telemetry":
        """File-backed run telemetry under `telemetry_dir/<run_id>/`:
        `manifest.json` plus an `events.jsonl` sink (and optionally the
        TensorBoard adapter over an existing ScalarWriter and a stdout
        sink over `log`). Returns the disabled singleton when
        `telemetry_dir` is falsy — the call site needs no branching."""
        if not telemetry_dir:
            return _NULL
        from code2vec_tpu.obs.sinks import (JsonlSink, ScalarSink,
                                            StdoutSink)
        manifest = _build_run_manifest(config, mesh, component)
        run_dir = os.path.join(telemetry_dir, manifest["run_id"])
        os.makedirs(run_dir, exist_ok=True)
        with open(os.path.join(run_dir, "manifest.json"), "w",
                  encoding="utf-8") as f:
            json.dump(manifest, f, indent=2, default=str)
        sinks: list = [JsonlSink(os.path.join(run_dir, "events.jsonl"))]
        if scalar_writer is not None:
            sinks.append(ScalarSink(scalar_writer))
        if log is not None:
            sinks.append(StdoutSink(log))
        tele = cls(sinks, run_id=manifest["run_id"])
        tele.run_dir = run_dir
        if log is not None:
            log(f"telemetry: run {manifest['run_id']} -> {run_dir}")
        return tele

    @classmethod
    def memory(cls, component: str = "run") -> "Telemetry":
        """Enabled registry with no sinks: histograms live in-process
        only. Serving uses this when --telemetry_dir is unset so the
        p50/p95/p99 request line still works without persistence."""
        return cls((), run_id=f"mem-{component}")

    @classmethod
    def disabled(cls) -> "Telemetry":
        return _NULL

    # ---- recording ----
    def count(self, name: str, n: float = 1) -> None:
        with self._guard():
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float, emit: bool = True,
              static: bool = False) -> None:
        """`static=True` marks a set-once constant (a config echo like
        train/max_contexts): freshness is meaningless for it, so it is
        excluded from gauge_ages() — otherwise every staleness
        consumer (/metrics ages, obs_top, stall dumps) would flag it
        forever and bury the real dead-producer signal."""
        with self._guard():
            self.gauges[name] = value
            if static:
                self.gauge_updated.pop(name, None)
            else:
                self.gauge_updated[name] = time.monotonic()
        if emit:
            self.event("gauge", name=name, value=value)

    def gauge_ages(self, now: Optional[float] = None
                   ) -> Dict[str, float]:
        """Seconds since each gauge was last set (time.monotonic
        timebase). The freshness signal for pull-based consumers: a
        queue-depth gauge whose producer died keeps its last VALUE, but
        its age keeps growing — /metrics exposes these so a scraper
        can mark the gauge stale, and the watchdog's stall dump lists
        gauges older than the stall deadline."""
        t = time.monotonic() if now is None else now
        with self._guard():
            return {name: max(0.0, t - ts)
                    for name, ts in self.gauge_updated.items()}

    def timer(self, name: str) -> TimerStat:
        with self._guard():
            t = self.timers.get(name)
            if t is None:
                t = self.timers[name] = TimerStat()
                t._lock = self._lock  # threadsafe-mode percentile reads
            return t

    def record_ms(self, name: str, ms: float) -> None:
        with self._guard():
            self.timer(name).record(ms)

    def span(self, name: str) -> _Span:
        """Start a host-monotonic span; `stop()` records it, and
        `stop(sync=tree)` waits for device work first (host-vs-device
        explicitness lives in the call, not the name)."""
        return _Span(self, name)

    def timed(self, name: str):
        """Context-manager form of `span` for plain host phases."""
        return self._timed(name)

    @contextlib.contextmanager
    def _timed(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record_ms(name, (time.perf_counter() - t0) * 1e3)

    def event(self, kind: str, **fields) -> None:
        """One structured record to every sink. Sinks see a flat dict
        with `kind` and a wall-clock `ts`."""
        if not self.sinks:
            return
        ev: Dict[str, Any] = {"kind": kind, "ts": round(time.time(), 6)}
        ev.update(fields)
        with self._guard():
            for s in self.sinks:
                s.write(ev)

    def update_manifest(self, **fields: Any) -> bool:
        """Merge `fields` into this run's `manifest.json` (tmp-write +
        rename, so readers never see a torn file). The fleet handshake
        (obs/exposition `/clock?commit=1`) persists the MEASURED
        wall-clock offset this way, which is what trace_report --merge
        aligns cohort traces with. False = nothing durable to update
        (memory registry, or the manifest is unreadable) — callers
        treat that as "this member can't be clock-committed", not an
        error."""
        if not self.run_dir:
            return False
        path = os.path.join(self.run_dir, "manifest.json")
        with self._guard():
            try:
                with open(path, encoding="utf-8") as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                return False
            manifest.update(fields)
            tmp = path + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(manifest, f, indent=2, default=str)
                os.replace(tmp, path)
            except OSError:
                return False
        return True

    # ---- lifecycle ----
    def summary(self) -> Dict[str, Any]:
        with self._guard():
            return {"counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "timers": {k: t.summary()
                               for k, t in sorted(self.timers.items())}}

    def close(self) -> None:
        if not self.enabled:
            return
        if self.sinks:
            self.event("summary", **self.summary())
        with self._guard():
            for s in self.sinks:
                s.close()
            self.sinks = []


class _NullTelemetry(Telemetry):
    """The `--telemetry_dir`-unset path: every method a no-op, shared
    singleton, `enabled=False` so hot loops skip with one check."""

    _NULL_TIMER = TimerStat(cap=1)

    def __init__(self):
        super().__init__((), run_id="disabled", enabled=False)

    def count(self, name, n=1):
        pass

    def gauge(self, name, value, emit=True, static=False):
        pass

    def timer(self, name):
        return self._NULL_TIMER

    def record_ms(self, name, ms):
        pass

    def span(self, name):
        return _NULL_SPAN

    def timed(self, name):
        return contextlib.nullcontext()

    def event(self, kind, **fields):
        pass

    def close(self):
        pass


_NULL = _NullTelemetry()


def format_latency_line(stat: TimerStat, last_ms: Optional[float] = None,
                        what: str = "request") -> str:
    """The serving REPL's one-line latency report."""
    s = stat.summary()
    head = (f"latency: {what} {last_ms:.1f} ms | "
            if last_ms is not None else "latency: ")
    return (head + f"p50 {s['p50_ms']:.1f} / p95 {s['p95_ms']:.1f} / "
            f"p99 {s['p99_ms']:.1f} / max {s['max_ms']:.1f} ms "
            f"over {s['count']} {what}s")
