"""Request-scoped tracing (ISSUE 6 tentpole): trace-id/span-id span
trees with explicit cross-thread handoff, recorded through the
existing `Telemetry` JSONL sink.

The telemetry registry (PR 2) answers "how slow"; this layer answers
"where": a serving request crosses three threads (client -> bounded
queue -> micro-batcher -> device -> client decode) and a training step
spans the infeed producer, the loop, and the async checkpoint writer —
a p99 outlier is only actionable once it decomposes into
queue_wait / parse / encode / device / decode (or
infeed_wait / step / save_blocked). Model (the Dapper shape):

  - a *trace* is one causal unit (a serving request, a training step);
    a *span* is one named interval on one thread, with a parent span
    and optional cross-trace *links* (the batcher flush serves many
    requests: it continues the FIRST request's trace and links the
    rest — the many-to-one arrows Chrome/Perfetto draw as flow events);
  - WITHIN a thread, parentage is implicit: entering a span as a
    context manager makes it the thread-local current span, so nested
    phases need no plumbing;
  - ACROSS threads, parentage is explicit: a `SpanContext` (immutable
    trace-id/span-id pair) is the handoff object that rides the work
    item — `PredictRequest.trace_ctx` through the serving queue, the
    checkpoint writer's job dict, and a `SpanChannel` alongside the
    infeed queue. The receiving thread parents (or links) its spans to
    the context it was handed; it never ends a span another thread
    owns (ARCHITECTURE.md "span handoff discipline").

Spans are recorded AT END as one `kind="span"` JSONL event each — no
in-memory trace tree to drain, and a crashed run keeps every span that
finished. `tools/trace_report.py` renders the log as Chrome
trace-event JSON (Perfetto / chrome://tracing, with flow events
stitching requests through batcher flushes) and computes the
critical-path breakdowns.

Timebase: `clock` (default `time.monotonic`, injectable for tests) is
shared by every span in a tracer, so retroactively recorded spans
(`record_span`) can be built from timestamps taken by other code — the
batcher reuses `PredictRequest.enqueued_at` (also `time.monotonic`)
as the queue-wait span's start.

Disabled path (the PR 2 discipline): `Tracer.disabled()` is a shared
singleton whose `enabled` is False and whose methods return the one
shared `_NullTraceSpan` — hot paths guard on the ONE boolean and
allocate nothing. Stdlib-only at import time; thread-safe by
construction (span creation takes the tracer lock; spans themselves
are single-owner by the handoff discipline).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Union

__all__ = ["SpanContext", "SpanChannel", "TraceSpan", "Tracer"]


class SpanContext(NamedTuple):
    """The immutable cross-thread handoff object: enough identity to
    parent or link a span on another thread, nothing else (no end(),
    no mutation — the owning thread keeps those)."""

    trace_id: str
    span_id: str


class SpanChannel:
    """FIFO side-channel carrying SpanContexts across a thread boundary
    in lockstep with a data queue: the producer `send()`s one context
    per item it enqueues, the consumer `recv()`s one per item it
    dequeues, and because both sides are sequential and the data queue
    is FIFO, position k's context describes position k's item — the
    infeed handoff (data/prefetch.py producer -> TrainStepRecorder)
    without changing the queue's item shape. deque append/popleft are
    atomic under the GIL."""

    __slots__ = ("_dq",)

    def __init__(self):
        self._dq: "collections.deque" = collections.deque()

    def send(self, ctx: Optional[SpanContext]) -> None:
        self._dq.append(ctx)

    def recv(self) -> Optional[SpanContext]:
        try:
            return self._dq.popleft()
        except IndexError:
            return None


class TraceSpan:
    """One open interval owned by the thread that started it. `end()`
    emits the span record; entering as a context manager makes it the
    thread-local current span (implicit within-thread parentage)."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "_t0", "_tid", "_tname", "links", "attrs", "_prev")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 links: Sequence[SpanContext], attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.links = list(links)
        self.attrs = attrs
        t = threading.current_thread()
        self._tid = t.ident or 0
        self._tname = t.name
        self._prev = None
        self._t0 = tracer.clock()

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def end(self, **extra) -> float:
        """Close the span and emit its record; returns the duration in
        ms. Idempotent: a second end() is a no-op returning 0.0, so
        error paths can close defensively without double-emitting (the
        ownership discipline still holds — only the OWNER may call)."""
        tracer, self._tracer = self._tracer, None
        if tracer is None:
            return 0.0
        t1 = tracer.clock()
        if extra:
            self.attrs.update(extra)
        tracer._finish(self, t1)
        return (t1 - self._t0) * 1e3

    # context-manager form: current-span bookkeeping for implicit
    # within-thread parentage
    def __enter__(self) -> "TraceSpan":
        tls = self._tracer._tls
        self._prev = getattr(tls, "current", None)
        tls.current = self
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        tracer = self._tracer
        if tracer is not None:  # not already end()ed early
            tracer._tls.current = self._prev
            self.end()


class _NullTraceSpan:
    """Shared no-op span: the disabled tracer hands out exactly one of
    these, so the off path allocates nothing per call."""

    __slots__ = ()
    name = ""
    trace_id = ""
    span_id = ""

    def context(self) -> None:
        return None

    def end(self, **extra) -> float:
        return 0.0

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


_NULL_SPAN = _NullTraceSpan()

# synthetic thread-id base for virtual tracks (retroactive spans that
# describe a queue or another non-thread location, kept off the real
# threads' rows in the Chrome view)
_VIRTUAL_TID_BASE = 1 << 20


class Tracer:
    """Span factory + live-span registry over one `Telemetry` registry.

    Construct via `create()` (returns the disabled singleton unless the
    telemetry run has sinks — spans are only useful once they persist)
    or `disabled()`. All span records flow through
    `telemetry.event("span", ...)`, so they land in the same
    `events.jsonl` the rest of the run writes and `--trace` needs no
    second output path. The live-span table (unfinished spans) feeds
    the watchdog's stall dump."""

    def __init__(self, telemetry, clock=time.monotonic):
        self.enabled = True
        self.telemetry = telemetry
        self.clock = clock
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._live: Dict[str, TraceSpan] = {}
        self._tls = threading.local()
        self._track_tids: Dict[str, int] = {}

    # ---- construction ----
    @classmethod
    def create(cls, telemetry, clock=time.monotonic) -> "Tracer":
        """A recording tracer over a sink-backed telemetry run; the
        shared disabled singleton otherwise (memory/disabled telemetry
        has nowhere durable to put spans)."""
        if telemetry is None or not telemetry.enabled \
                or not telemetry.sinks:
            return _NULL_TRACER
        return cls(telemetry, clock=clock)

    @classmethod
    def disabled(cls) -> "Tracer":
        return _NULL_TRACER

    # ---- span creation ----
    def _ids(self, parent) -> tuple:
        """(trace_id, parent_span_id) for a new span: explicit parent
        (TraceSpan or SpanContext) > thread-local current > new trace."""
        if parent is None:
            parent = getattr(self._tls, "current", None)
        if parent is None:
            return f"t{next(self._seq):x}", None
        if isinstance(parent, SpanContext):
            return parent.trace_id, parent.span_id
        return parent.trace_id, parent.span_id

    def start_trace(self, name: str, **attrs) -> TraceSpan:
        """Root span of a NEW trace (one serving request, one training
        step cycle) regardless of any current span on this thread."""
        trace_id = f"t{next(self._seq):x}"
        return self._start(name, trace_id, None, (), attrs)

    def start_span(self, name: str,
                   parent: Union[TraceSpan, SpanContext, None] = None,
                   links: Sequence[SpanContext] = (),
                   **attrs) -> TraceSpan:
        """Child span: of `parent` when given (the cross-thread case —
        pass the SpanContext that rode the work item), else of this
        thread's current span, else a fresh trace root."""
        trace_id, parent_id = self._ids(parent)
        return self._start(name, trace_id, parent_id, links, attrs)

    def _start(self, name, trace_id, parent_id, links, attrs
               ) -> TraceSpan:
        span = TraceSpan(self, name, trace_id, f"s{next(self._seq):x}",
                         parent_id, links, attrs)
        with self._lock:
            self._live[span.span_id] = span
        return span

    def record_span(self, name: str, t_start: float, t_end: float,
                    parent: Union[TraceSpan, SpanContext, None] = None,
                    links: Sequence[SpanContext] = (),
                    track: Optional[str] = None,
                    **attrs) -> SpanContext:
        """Retroactive span from two `clock` timestamps taken elsewhere
        (queue wait from `PredictRequest.enqueued_at`, a step interval
        the recorder already measured). `track` names a virtual Chrome
        row (e.g. "serve-queue") instead of the recording thread's —
        the span describes a location, not this thread's work."""
        trace_id, parent_id = self._ids(parent)
        span_id = f"s{next(self._seq):x}"
        if track is not None:
            with self._lock:
                tid = self._track_tids.setdefault(
                    track, _VIRTUAL_TID_BASE + len(self._track_tids))
            tname = track
        else:
            t = threading.current_thread()
            tid, tname = t.ident or 0, t.name
        self._emit(name, trace_id, span_id, parent_id, links, tid,
                   tname, t_start, t_end, attrs)
        return SpanContext(trace_id, span_id)

    # ---- record plumbing ----
    def _finish(self, span: TraceSpan, t1: float) -> None:
        with self._lock:
            self._live.pop(span.span_id, None)
        self._emit(span.name, span.trace_id, span.span_id,
                   span.parent_id, span.links, span._tid, span._tname,
                   span._t0, t1, span.attrs)

    def _emit(self, name, trace_id, span_id, parent_id, links, tid,
              tname, t0, t1, attrs) -> None:
        ev: Dict[str, Any] = {
            "name": name, "trace": trace_id, "span": span_id,
            "t0": round(t0, 6), "dur_ms": round((t1 - t0) * 1e3, 3),
            "tid": tid, "tname": tname,
        }
        if parent_id is not None:
            ev["parent"] = parent_id
        if links:
            ev["links"] = [[c.trace_id, c.span_id] for c in links
                           if c is not None]
        if attrs:
            ev["attrs"] = attrs
        self.telemetry.event("span", **ev)

    def live_spans(self) -> List[Dict[str, Any]]:
        """Snapshot of unfinished spans (the watchdog's stall dump:
        WHAT was in flight when a component went quiet)."""
        now = self.clock()
        with self._lock:
            spans = list(self._live.values())
        return [{"name": s.name, "trace": s.trace_id, "span": s.span_id,
                 "parent": s.parent_id, "tname": s._tname,
                 "tid": s._tid,
                 "age_ms": round((now - s._t0) * 1e3, 1),
                 "attrs": dict(s.attrs)} for s in spans]


class _NullTracer(Tracer):
    """The `--trace`-unset path: every method a no-op returning the
    shared null span; `enabled` False so hot loops skip with one
    boolean check."""

    def __init__(self):
        self.enabled = False
        self.telemetry = None
        self.clock = time.monotonic
        self._tls = threading.local()

    def start_trace(self, name, **attrs):
        return _NULL_SPAN

    def start_span(self, name, parent=None, links=(), **attrs):
        return _NULL_SPAN

    def record_span(self, name, t_start, t_end, parent=None, links=(),
                    track=None, **attrs):
        return None

    def live_spans(self):
        return []


_NULL_TRACER = _NullTracer()
