"""Train-loop instrumentation shared by both model heads.

`TrainStepRecorder` answers the question the throughput log line can't:
is the step device-bound or infeed-bound? Per step it records

  - `infeed_wait_ms` — host time blocked on the double-buffered infeed
    (data/prefetch.py). Near zero while the producer thread keeps up;
    grows exactly when the input pipeline, not the chip, is the
    bottleneck.
  - `step_ms` — wall time from infeed yield to step completion,
    device-sync-aware: the recorder syncs via the loss scalar's host
    transfer, so the figure bounds the dispatched device work (and the
    loss ride-along means per-step loss costs no extra transfer).
  - periodic device-memory gauges (`bytes_in_use`,
    `peak_bytes_in_use`) where the backend exposes them.

Cost model: telemetry is opt-in (`--telemetry_dir`), and enabling it
trades step pipelining for attribution — the per-step device sync
serializes the loop (steps no longer overlap the next host dispatch).
That is the documented price of in-band per-step numbers; the
jax.profiler trace window (`--profile`) remains the non-intrusive tool.
Disabled, the recorder costs ONE boolean check per step and `wrap()`
returns the infeed unchanged — zero per-step allocation.
"""

from __future__ import annotations

import time
from typing import Iterable

from code2vec_tpu.obs.telemetry import Telemetry


class TrainStepRecorder:
    """Per-step telemetry for a `for dev_batch, batch in infeed:` loop.

    Usage (both heads):
        rec = TrainStepRecorder(telemetry, gauge_every=N)
        for epoch ...:
            for dev_batch, batch in rec.wrap(infeed):
                ... dispatch step ...
                loss_f = rec.end_step(step_num, loss, n) \
                    if rec.enabled else None
    """

    def __init__(self, telemetry: Telemetry, gauge_every: int = 100):
        self.enabled = telemetry.enabled
        self._tele = telemetry
        self._gauge_every = max(1, gauge_every)
        self._steps = 0
        self._infeed_wait_ms = 0.0
        self._t_yield = 0.0

    def wrap(self, infeed: Iterable) -> Iterable:
        """Time the infeed pops. Disabled: returns `infeed` itself, so
        the loop iterates exactly what it iterated before."""
        if not self.enabled:
            return infeed
        return self._timed_iter(infeed)

    def _timed_iter(self, infeed: Iterable):
        it = iter(infeed)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            now = time.perf_counter()
            self._infeed_wait_ms = (now - t0) * 1e3
            self._t_yield = now
            yield item

    def end_step(self, step: int, loss, n_examples: int) -> float:
        """Close the current step: sync on the loss transfer, record the
        step/infeed timers, write the per-step event. Returns the loss
        as a float so the loop's log line reuses the one transfer."""
        loss_f = float(loss)  # device sync: bounds the dispatched step
        now = time.perf_counter()
        step_ms = (now - self._t_yield) * 1e3
        tele = self._tele
        tele.record_ms("train/step_ms", step_ms)
        tele.record_ms("train/infeed_wait_ms", self._infeed_wait_ms)
        tele.count("train/steps")
        tele.count("train/examples", int(n_examples))
        tele.event("step", step=int(step), step_ms=round(step_ms, 3),
                   infeed_wait_ms=round(self._infeed_wait_ms, 3),
                   loss=round(loss_f, 6), examples=int(n_examples))
        self._steps += 1
        if self._steps % self._gauge_every == 0:
            self._device_memory_gauges()
        return loss_f

    def _device_memory_gauges(self) -> None:
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:  # backend without memory_stats (CPU)
            return
        for key in ("bytes_in_use", "peak_bytes_in_use"):
            if key in stats:
                self._tele.gauge(f"device/{key}", int(stats[key]))
