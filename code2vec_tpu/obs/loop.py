"""Train-loop instrumentation shared by both model heads.

`TrainStepRecorder` answers the question the throughput log line can't:
is the step device-bound or infeed-bound? Per step it records

  - `infeed_wait_ms` — host time blocked on the double-buffered infeed
    (data/prefetch.py). Near zero while the producer thread keeps up;
    grows exactly when the input pipeline, not the chip, is the
    bottleneck.
  - `step_ms` — wall time from infeed yield to step completion,
    device-sync-aware: the recorder syncs via the loss scalar's host
    transfer, so the figure bounds the dispatched device work (and the
    loss ride-along means per-step loss costs no extra transfer).
  - periodic device-memory gauges (`bytes_in_use`,
    `peak_bytes_in_use`) where the backend exposes them.

With a tracer attached (`--trace`, ISSUE 6) each step additionally
becomes a trace: a `train/step_cycle` root span with `train/infeed_wait`
and `train/step` children (recorded retroactively from the timings the
recorder already took — no extra clock reads on the hot path beyond
one), LINKING the `infeed/produce` span of the batch it consumed (the
producer thread sends that span's context through a `SpanChannel` in
lockstep with the infeed queue — obs/trace.py has the handoff
discipline). `last_step_context` exposes the newest step's context so
the epoch-boundary save can link the step that triggered it. A
heartbeat (`--watchdog_stall_s`) beats once per step.

Cost model: telemetry is opt-in (`--telemetry_dir`), and enabling it
trades step pipelining for attribution — the per-step device sync
serializes the loop (steps no longer overlap the next host dispatch).
That is the documented price of in-band per-step numbers; the
jax.profiler trace window (`--profile`) remains the non-intrusive tool.
Disabled, the recorder costs ONE boolean check per step and `wrap()`
returns the infeed unchanged — zero per-step allocation. Trace and
watchdog ride the same discipline: off, they add one boolean check and
one no-op method call per step.
"""

from __future__ import annotations

import time
from typing import Iterable, Optional

from code2vec_tpu.obs.telemetry import Telemetry
from code2vec_tpu.obs.trace import SpanChannel, SpanContext, Tracer


def infeed_produce_instrument(tracer: Tracer,
                              channel: Optional[SpanChannel]):
    """Producer-side tracing hook for `build_train_infeed`: wraps the
    per-batch parse/transfer function so each batch gets an
    `infeed/produce` span ON the producer thread, whose context is
    handed to the consuming step through `channel` (FIFO-aligned with
    the infeed queue — the recorder links it from the step span).
    Returns None when tracing is off, so the infeed path stays
    byte-identical to the untraced one. ONE definition shared by both
    train loops: the FIFO handoff contract must not drift between
    them."""
    if not tracer.enabled:
        return None

    def instrument(fn):
        def produce(batch):
            t0 = tracer.clock()
            out = fn(batch)
            channel.send(tracer.record_span(
                "infeed/produce", t0, tracer.clock()))
            return out
        return produce
    return instrument


class TrainStepRecorder:
    """Per-step telemetry for a `for dev_batch, batch in infeed:` loop.

    Usage (both heads):
        rec = TrainStepRecorder(telemetry, gauge_every=N)
        for epoch ...:
            for dev_batch, batch in rec.wrap(infeed):
                ... dispatch step ...
                loss_f = rec.end_step(step_num, loss, n) \
                    if rec.enabled else None
    """

    def __init__(self, telemetry: Telemetry, gauge_every: int = 100,
                 tracer: Optional[Tracer] = None,
                 infeed_channel: Optional[SpanChannel] = None,
                 heartbeat=None, alerts=None):
        self.enabled = telemetry.enabled
        self._tele = telemetry
        self._tracer = tracer if tracer is not None else Tracer.disabled()
        self._channel = infeed_channel
        self._heartbeat = heartbeat
        # alert engine (obs/alerts.py): end_step is "the training
        # loop's next beat" where a raise-mode sticky alert surfaces
        self._alerts = alerts
        self.last_step_context: Optional[SpanContext] = None
        self._gauge_every = max(1, gauge_every)
        self._steps = 0
        self._infeed_wait_ms = 0.0
        self._t_yield = 0.0

    @property
    def infeed_wait_ms(self) -> float:
        """Host ms the loop spent waiting on the most recent infeed
        pop — the phase profiler's `infeed_wait` input (obs/phases.py)."""
        return self._infeed_wait_ms

    def probe_tick(self) -> None:
        """Beat the loop heartbeat from inside a long in-step
        measurement: the phase profiler calls this after every probe
        dispatch so its first-sample jit compiles (tens of seconds on
        TPU) never read as a train-loop stall to the watchdog."""
        if self._heartbeat is not None:
            self._heartbeat.beat()

    def rebase_step_window(self) -> None:
        """Restart the current step's timing window. The phase
        profiler calls this after its probe dispatches so a SAMPLED
        step's train/step_ms (and `step` event) records the fused
        dispatch alone — probe time belongs to the train/phase/*
        timers, probe compile time to neither; without the rebase 1/N
        of the step_ms samples would be probe-laden outliers and the
        p99 would report the profiler, not the training step."""
        self._t_yield = time.perf_counter()
        if self._heartbeat is not None:
            self._heartbeat.beat()

    def wrap(self, infeed: Iterable) -> Iterable:
        """Time the infeed pops. Disabled: returns `infeed` itself, so
        the loop iterates exactly what it iterated before."""
        if not self.enabled:
            return infeed
        return self._timed_iter(infeed)

    def _timed_iter(self, infeed: Iterable):
        it = iter(infeed)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            now = time.perf_counter()
            self._infeed_wait_ms = (now - t0) * 1e3
            self._t_yield = now
            yield item

    def end_step(self, step: int, loss, n_examples: int,
                 params=None) -> float:
        """Close the current step: sync on the loss transfer, record the
        step/infeed timers, write the per-step event. Returns the loss
        as a float so the loop's log line reuses the one transfer.

        `params` (optional, the live param pytree) feeds the fleet
        plane's divergence check: every `gauge_every` steps a sampled
        fingerprint (sum of one sliver per leaf) publishes as a gauge
        pair, step-labeled so the cohort collector compares hosts at
        MATCHING steps (obs/fleet.py)."""
        loss_f = float(loss)  # device sync: bounds the dispatched step
        now = time.perf_counter()
        step_ms = (now - self._t_yield) * 1e3
        tele = self._tele
        tele.record_ms("train/step_ms", step_ms)
        tele.record_ms("train/infeed_wait_ms", self._infeed_wait_ms)
        tele.count("train/steps")
        tele.count("train/examples", int(n_examples))
        # live-plane feed (obs/health.py): the newest loss as a gauge
        # so the non-finite / spike monitors can read it off the hot
        # path (emit=False: a dict store, never a JSONL event)
        tele.gauge("train/loss", loss_f, emit=False)
        # step label for the loss gauge: SPMD replicas publishing
        # different losses at the SAME step is runtime divergence
        tele.gauge("train/loss_step", float(step), emit=False)
        tele.event("step", step=int(step), step_ms=round(step_ms, 3),
                   infeed_wait_ms=round(self._infeed_wait_ms, 3),
                   loss=round(loss_f, 6), examples=int(n_examples))
        if self._heartbeat is not None:
            self._heartbeat.beat()
        alerts = self._alerts
        if alerts is not None and alerts._sticky is not None:
            alerts.poll()  # raise-mode alert lands at the loop's beat
        if self._tracer.enabled:
            self._trace_step(step, step_ms, n_examples)
        self._steps += 1
        if self._steps % self._gauge_every == 0:
            self._device_memory_gauges()
            if params is not None:
                self._params_digest_gauges(step, params)
        return loss_f

    def _params_digest_gauges(self, step: int, params) -> None:
        """Sampled params fingerprint for the cohort divergence check:
        one sliver (`leaf[..., :1]`) per leaf, summed in float32 — a
        few hundred elements instead of the full model, cheap enough
        for the gauge cadence while still moving when ANY layer's
        leading column drifts. Replicated-SPMD hosts must agree on it
        bit-for-bit-ish; the fleet collector compares hosts at the
        step this pair labels.

        The math MUST stay process-local: an op over a multi-process
        global array lowers to a collective, and a telemetry-path
        collective interleaving with the step's gradient all-reduce
        desyncs the cohort (Gloo aborts on the size mismatch). So
        only fully-replicated leaves contribute — every host skips
        the same sharded leaves, so digests stay comparable — and
        each is read through its LOCAL shard, never the global
        view."""
        try:
            import jax.numpy as jnp
            total = 0.0
            import jax
            for leaf in jax.tree_util.tree_leaves(params):
                if hasattr(leaf, "is_fully_replicated"):
                    if not leaf.is_fully_replicated:
                        continue
                    leaf = leaf.addressable_data(0)
                probe = leaf if getattr(leaf, "ndim", 0) == 0 \
                    else leaf[..., :1]
                total += float(jnp.sum(probe.astype(jnp.float32)))
        except Exception:  # non-array pytree / backend quirk: skip
            return
        self._tele.gauge("train/params_digest", total, emit=False)
        self._tele.gauge("train/params_digest_step", float(step),
                         emit=False)

    def _trace_step(self, step: int, step_ms: float,
                    n_examples: int) -> None:
        """One trace per step, built retroactively from the timings
        end_step already measured (the tracer clock and perf_counter
        tick at the same rate; only the interval lengths matter).
        Root `train/step_cycle` = infeed wait + step; its `train/step`
        child links the consumed batch's `infeed/produce` span via the
        producer's SpanChannel (FIFO-aligned with the infeed queue)."""
        tracer = self._tracer
        t_end = tracer.clock()
        t_yield = t_end - step_ms / 1e3
        t_wait0 = t_yield - self._infeed_wait_ms / 1e3
        produced = self._channel.recv() if self._channel is not None \
            else None
        root = tracer.record_span(
            "train/step_cycle", t_wait0, t_end, parent=None,
            step=int(step), examples=int(n_examples))
        tracer.record_span("train/infeed_wait", t_wait0, t_yield,
                           parent=root)
        tracer.record_span(
            "train/step", t_yield, t_end, parent=root,
            links=(produced,) if produced is not None else (),
            step=int(step))
        self.last_step_context = root

    def _device_memory_gauges(self) -> None:
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:  # backend without memory_stats (CPU)
            return
        for key in ("bytes_in_use", "peak_bytes_in_use"):
            if key in stats:
                self._tele.gauge(f"device/{key}", int(stats[key]))
