"""Telemetry sinks: where `Telemetry.event()` records go.

Three concrete sinks, all host-side and stdlib-only at import time:

  - `JsonlSink` — the per-run structured event log (`events.jsonl`
    next to the run's `manifest.json`), one JSON object per line.
    The durable artifact `tools/telemetry_report.py` summarizes.
  - `ScalarSink` — TensorBoard adapter: re-emits numeric fields of
    per-step events through an externally-owned
    `training/scalars.ScalarWriter` (reused, never reopened — the
    train loop already holds one for its loss/throughput scalars).
  - `StdoutSink` — forwards non-step events through a log callable
    (per-step volume would spam the console; steps stay in the JSONL).

A sink is anything with `write(event: dict)` and `close()`.
"""

from __future__ import annotations

import json
from typing import Callable, Sequence


def _json_default(o):
    try:
        return float(o)  # numpy / jax scalars
    except Exception:
        return str(o)


class JsonlSink:
    """Append-mode JSONL event log, flushed per event (step cadence is
    hundreds of Hz at worst; durability beats buffering for a log whose
    main consumer is a post-mortem)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", encoding="utf-8")

    def write(self, event: dict) -> None:
        self._f.write(json.dumps(event, default=_json_default) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class ScalarSink:
    """Re-emit per-step event fields as TensorBoard scalars under
    `telemetry/…`. Owns nothing: the ScalarWriter is the train loop's
    (a no-op writer when --tensorboard is unset, so attaching this sink
    unconditionally costs one isinstance-free call per step event)."""

    def __init__(self, writer):
        self._writer = writer

    def write(self, event: dict) -> None:
        if event.get("kind") != "step":
            return
        step = event.get("step")
        if step is None:
            return
        scalars = {f"telemetry/{k}": v for k, v in event.items()
                   if k not in ("kind", "ts", "step")
                   and isinstance(v, (int, float))
                   and not isinstance(v, bool)}
        if scalars:
            self._writer.write(int(step), scalars)

    def close(self) -> None:
        pass  # the train loop owns (and closes) the ScalarWriter


class StdoutSink:
    """Human-visible mirror of the low-volume events (run lifecycle,
    gauges, summaries) through the run's logger."""

    def __init__(self, log: Callable[[str], None],
                 skip_kinds: Sequence[str] = ("step", "span",
                                              "phase")):
        self._log = log
        self._skip = frozenset(skip_kinds)

    def write(self, event: dict) -> None:
        if event.get("kind") in self._skip:
            return
        body = {k: v for k, v in event.items()
                if k not in ("kind", "ts")}
        self._log(f"telemetry[{event.get('kind')}] "
                  + json.dumps(body, default=_json_default,
                               sort_keys=True))

    def close(self) -> None:
        pass
