"""code2vec_tpu.obs — unified run telemetry (ISSUE 2).

One registry (`Telemetry`: counters, gauges, p50/p95/p99 timer
histograms), pluggable sinks (per-run JSONL event log + manifest under
`--telemetry_dir`, TensorBoard adapter over `ScalarWriter`, stdout),
host-vs-device-explicit span helpers, and the train-loop recorder both
model heads share. Stdlib-only at import time — jax is lazy, TensorFlow
is never imported here (guard: tests/test_obs_guard.py).
"""

from code2vec_tpu.obs.alerts import (AlertEngine, AlertError,  # noqa: F401
                                     AlertRule, load_rules)
from code2vec_tpu.obs.exposition import (LivePlane,  # noqa: F401
                                         MetricsServer,
                                         build_live_plane,
                                         render_prometheus)
from code2vec_tpu.obs.fleet import (FleetCollector,  # noqa: F401
                                    fleet_alert_rules)
from code2vec_tpu.obs.health import HealthEngine  # noqa: F401
from code2vec_tpu.obs.loop import (TrainStepRecorder,  # noqa: F401
                                   infeed_produce_instrument)
from code2vec_tpu.obs.phases import (PhaseProfiler,  # noqa: F401
                                     ProbeKit)
from code2vec_tpu.obs.sinks import (JsonlSink, ScalarSink,  # noqa: F401
                                    StdoutSink)
from code2vec_tpu.obs.telemetry import (SUMMARY_PERCENTILES,  # noqa: F401
                                        Telemetry, TimerStat,
                                        device_sync,
                                        format_latency_line)
from code2vec_tpu.obs.trace import (SpanChannel, SpanContext,  # noqa: F401
                                    Tracer, TraceSpan)
from code2vec_tpu.obs.watchdog import (Heartbeat, StallError,  # noqa: F401
                                       Watchdog)
