"""Prometheus text-exposition parsing + counter-rate differencing
(ISSUE 17 satellite): the ONE implementation every scrape consumer
shares.

`tools/obs_top.py` grew the first copy of this for its live terminal
view (ISSUE 7); the fleet collector (obs/fleet.py) needs exactly the
same grammar and exactly the same counter-reset discipline. Hand-synced
copies of parsing rules drift the same way the round-11
`infeed_produce_instrument` copies did, so the parser lives here and
both import it.

  - `parse_prometheus` — text exposition format 0.0.4 ->
    `{metric: [(labels, value), ...]}` (the inverse of
    obs/exposition.render_prometheus; tests round-trip the pair).
  - `scalar` / `labeled` — sample lookup helpers.
  - `CounterRates` — consecutive-poll differencing of cumulative
    counters with the PR-15 RESTARTED semantics: a counter that went
    BACKWARD means the process restarted (supervisor relaunch /
    elastic resize zeroes its counters), so the rate clamps to what
    the NEW process accumulated this window instead of rendering
    negative steps/s, and the reset is reported so renderers can
    annotate the row.

Pure stdlib (re only) — importable on a laptop with nothing installed,
and inside the obs/ no-jax/no-TF fence (tests/test_obs_guard.py).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["CounterRates", "labeled", "parse_prometheus", "scalar"]

_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')

Metrics = Dict[str, List[Tuple[Dict[str, str], float]]]


def parse_prometheus(text: str) -> Metrics:
    """Text exposition format -> {metric: [(labels, value), ...]}."""
    out: Metrics = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if not m:
            continue
        name, labels_raw, raw = m.groups()
        try:
            value = float(raw)
        except ValueError:
            continue
        labels = (dict(_LABEL_RE.findall(labels_raw))
                  if labels_raw else {})
        out.setdefault(name, []).append((labels, value))
    return out


def scalar(metrics: Metrics, name: str) -> Optional[float]:
    """First unlabeled sample of a family (counters/gauges here carry
    no labels)."""
    for labels, value in metrics.get(name, ()):
        if not labels:
            return value
    return None


def labeled(metrics: Metrics, name: str, **want: str) -> Optional[float]:
    for labels, value in metrics.get(name, ()):
        if all(labels.get(k) == v for k, v in want.items()):
            return value
    return None


class CounterRates:
    """One endpoint's counter-differencing state: holds the previous
    (t, metrics) sample so each poll yields rates, with counter
    resets surfaced instead of rendered as negative rates."""

    def __init__(self) -> None:
        self._last: Optional[Tuple[float, Metrics]] = None
        # counters that went backward in the CURRENT window (filled by
        # the rate calls the latest advance() handed out)
        self.restarted: List[str] = []

    def reset(self) -> None:
        """Forget the previous sample — the collector calls this when
        it KNOWS the member restarted (fresh run_id at handshake), so
        the first post-restart poll starts a clean window instead of
        differencing across two processes."""
        self._last = None
        self.restarted = []

    def advance(self, t: float, metrics: Metrics
                ) -> Callable[[str], Optional[float]]:
        """Record this poll's sample; returns a `rate(counter_name)`
        lookup over the window just closed (None until two samples
        exist). Resets observed by those lookups accumulate in
        `self.restarted`."""
        prev, self._last = self._last, (t, metrics)
        self.restarted = []
        restarted = self.restarted

        def rate(counter: str) -> Optional[float]:
            cur = scalar(metrics, counter)
            if prev is None or cur is None:
                return None
            old = scalar(prev[1], counter)
            dt = t - prev[0]
            if old is None or dt <= 0:
                return None
            if cur < old:
                # per-host counter reset: a supervisor restart or
                # elastic resize replaced the process, zeroing its
                # cumulative counters — the raw difference is negative
                # garbage. Report the reset and rate what the NEW
                # process accumulated this window (cur since its
                # zero), clamped >= 0.
                restarted.append(counter)
                return max(0.0, cur) / dt
            return (cur - old) / dt
        return rate
