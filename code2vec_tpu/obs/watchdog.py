"""Stall watchdog (ISSUE 6 tentpole): liveness detection for the async
pipeline, before a hang becomes a dead pod.

After PR 5 the run is a web of cooperating threads — the train loop,
the persistent infeed producer, the async checkpoint writer, the
serving micro-batcher — and a wedged one manifests only as silence:
nothing crashes, throughput just stops. The watchdog turns silence
into a diagnosis:

  - components `register()` a `Heartbeat` and `beat()` it whenever they
    make progress (one attribute store — cheap enough for per-batch /
    per-step cadence). `busy()` / `idle()` bracket phases where a
    deadline applies at all: an idle checkpoint writer with no job is
    fine; one that went `busy()` and hasn't beaten within its deadline
    is a hang.
  - a monitor thread (or an explicit `check_now()` — the fake-clock
    test path) compares each ACTIVE component's last beat against its
    deadline. A miss emits a `stall` telemetry event and writes a
    diagnostic bundle to the run dir: live unfinished spans (from the
    tracer), every thread's current stack (`sys._current_frames`), and
    a registry snapshot (queue-depth/occupancy gauges included) —
    enough to tell a starved infeed from a wedged writer from a
    deadlocked batcher without attaching a debugger to a pod.
  - stalls are edge-triggered: one event per silence (re-armed by the
    component's next beat), so a long hang doesn't flood the log.
  - `mode="warn"` (default) logs and records; `mode="raise"` makes the
    stall sticky — it re-raises as `StallError` at the stalled
    component's next `beat()`, at `poll()`, and at `stop()` — for runs
    that prefer a loud death to a silent wedge.

Clock injection (`clock=`, default `time.monotonic` — the tracer's
timebase) keeps the tests sleep-free: a fake clock advances past the
deadline and `check_now()` fires synchronously.

Disabled path (the PR 2 discipline): `Watchdog.disabled()` is a shared
singleton; `register()` hands out the one shared no-op heartbeat, so
instrumented code paths cost one attribute store when off. Stdlib-only
at import time.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Heartbeat", "StallError", "Watchdog"]


class StallError(RuntimeError):
    """A monitored component missed its progress deadline under
    `mode="raise"`."""


class Heartbeat:
    """One monitored component's progress marker. `beat()` is the hot
    call: a clock read and an attribute store (no lock — the monitor
    tolerates a torn read of a float; a beat can never be mistaken for
    a stall, only observed one check late). Starts INACTIVE: the
    deadline applies only between `busy()`/first `beat()` and
    `idle()`."""

    __slots__ = ("name", "deadline_s", "_wd", "_last", "_active")

    def __init__(self, name: str, deadline_s: float, wd: "Watchdog"):
        self.name = name
        self.deadline_s = deadline_s
        self._wd = wd
        self._last = wd._clock()
        self._active = False

    def beat(self) -> None:
        self._last = self._wd._clock()
        self._active = True
        if self._wd._sticky is not None:  # raise-mode stall lands here
            self._wd.poll()

    def busy(self) -> None:
        """Deadline clock starts now (a writer picking up a job, a
        batcher starting a flush)."""
        self.beat()

    def idle(self) -> None:
        """No work in flight — exempt from the deadline until the next
        beat/busy."""
        self._active = False


class _NullHeartbeat:
    __slots__ = ()
    name = ""

    def beat(self) -> None:
        pass

    def busy(self) -> None:
        pass

    def idle(self) -> None:
        pass


_NULL_HEARTBEAT = _NullHeartbeat()


class Watchdog:
    """Registry of heartbeating components with per-component progress
    deadlines. Construct via `create()` (disabled singleton when the
    telemetry run has no sinks — a stall event nobody can read helps
    nobody) or `disabled()`."""

    def __init__(self, telemetry, *, stall_s: float,
                 mode: str = "warn", tracer=None,
                 clock: Callable[[], float] = time.monotonic,
                 log: Optional[Callable[[str], None]] = None,
                 check_interval_s: Optional[float] = None):
        assert stall_s > 0 and mode in ("warn", "raise")
        self.enabled = True
        self.telemetry = telemetry
        self.default_stall_s = stall_s
        self.mode = mode
        self.tracer = tracer
        self._clock = clock
        self._log = log or (lambda _m: None)
        # poll a few times per deadline, bounded so tests with tiny
        # deadlines don't spin and long deadlines still notice promptly
        self._interval = (check_interval_s if check_interval_s
                          else min(max(stall_s / 4.0, 0.05), 0.9))
        self._lock = threading.Lock()
        self._components: Dict[str, Heartbeat] = {}
        # edge-trigger memory: component -> the `_last` beat timestamp
        # its current stall episode was reported at. Keyed on the beat
        # (not a bare flag) so a beat BETWEEN two overdue checks still
        # re-arms the episode even if no check observed it healthy.
        self._stalled: Dict[str, float] = {}
        self._dump_seq = 0
        self._sticky: Optional[StallError] = None
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # attached via attach(): their state tables join the stall dump
        self._health = None
        self._alerts = None
        self._cohort = None

    # ---- construction ----
    @classmethod
    def create(cls, telemetry, *, stall_s: float, **kw) -> "Watchdog":
        if stall_s <= 0 or telemetry is None or not telemetry.enabled \
                or not telemetry.sinks:
            return _NULL_WATCHDOG
        return cls(telemetry, stall_s=stall_s, **kw)

    @classmethod
    def disabled(cls) -> "Watchdog":
        return _NULL_WATCHDOG

    # ---- components ----
    def register(self, name: str,
                 deadline_s: Optional[float] = None) -> Heartbeat:
        hb = Heartbeat(name, deadline_s or self.default_stall_s, self)
        with self._lock:
            self._components[name] = hb
        return hb

    def attach(self, health=None, alerts=None,
               cohort=None) -> "Watchdog":
        """Attach the health-monitor / alert engines (ISSUE 7) so a
        stall dump carries their state tables: one bundle answers both
        "what is stuck" and "what was already unhealthy". `cohort`
        (ISSUE 13) is a zero-arg callable returning the live cohort
        topology (the supervisor's `cohort_topology()` — live process
        set + target size), so a wedged-cohort dump also answers "who
        was in the mesh"."""
        if health is not None:
            self._health = health
        if alerts is not None:
            self._alerts = alerts
        if cohort is not None:
            self._cohort = cohort
        return self

    def status(self) -> Dict[str, Dict[str, Any]]:
        """Live per-component liveness, recomputed from the heartbeat
        table NOW (not the edge-trigger memory): what /healthz gates
        on. `stalled` = active and past its deadline at this instant.
        """
        now = self._clock()
        with self._lock:
            return {
                name: {"active": hb._active,
                       "deadline_s": hb.deadline_s,
                       "age_s": round(max(0.0, now - hb._last), 3),
                       "stalled": bool(hb._active
                                       and now - hb._last
                                       > hb.deadline_s)}
                for name, hb in self._components.items()}

    # ---- monitoring ----
    def start(self) -> "Watchdog":
        with self._lock:
            if self._thread is None:
                self._stop_event.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="stall-watchdog")
                self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the monitor thread. Deliberately does NOT re-raise a
        sticky stall (stop runs in `finally` teardown, where raising
        would mask the original error) — success paths call `poll()`
        after stopping."""
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop_event.set()
        if thread is not None:
            thread.join(timeout=5)

    def poll(self) -> None:
        """Re-raise a sticky stall (`mode="raise"`); no-op in warn
        mode. Call sites: a loop that wants to die loudly, the end of
        a successful run, and the stalled component's next `beat()`."""
        with self._lock:
            err, self._sticky = self._sticky, None
        if err is not None:
            raise err

    def _run(self) -> None:
        me = threading.current_thread()
        while not self._stop_event.wait(self._interval):
            if self._thread is not me:  # superseded by stop()+start()
                return
            self.check_now()

    def check_now(self) -> List[Dict[str, Any]]:
        """One synchronous deadline sweep (what the monitor thread runs
        each interval; tests drive it directly under a fake clock).
        Returns the NEW stalls found this sweep."""
        now = self._clock()
        stalls: List[Dict[str, Any]] = []
        with self._lock:
            for name, hb in self._components.items():
                last = hb._last
                if not hb._active:
                    self._stalled.pop(name, None)
                    continue
                age = now - last
                if age <= hb.deadline_s:
                    self._stalled.pop(name, None)
                    continue
                if self._stalled.get(name) == last:
                    continue  # edge-triggered: this silence episode
                    #            was already reported
                self._stalled[name] = last
                stalls.append({"component": name,
                               "age_s": round(age, 3),
                               "deadline_s": hb.deadline_s})
        if stalls:
            dump_path = self._dump(stalls)
            for s in stalls:
                self.telemetry.count("watchdog/stalls")
                self.telemetry.event("stall", dump=dump_path, **s)
                self._log(
                    f"watchdog: STALL {s['component']} — no progress "
                    f"for {s['age_s']:.1f}s (deadline "
                    f"{s['deadline_s']:.1f}s); diagnostics -> "
                    f"{dump_path}")
            if self.mode == "raise":
                with self._lock:
                    if self._sticky is None:
                        self._sticky = StallError(
                            "stalled components: " + ", ".join(
                                s["component"] for s in stalls)
                            + f" (diagnostics: {dump_path})")
        return stalls

    # ---- diagnostics ----
    def _thread_stacks(self) -> Dict[str, List[str]]:
        names = {t.ident: t.name for t in threading.enumerate()}
        out: Dict[str, List[str]] = {}
        for tid, frame in sys._current_frames().items():
            label = f"{names.get(tid, '?')}:{tid}"
            out[label] = [ln.rstrip("\n") for ln in
                          traceback.format_stack(frame)]
        return out

    def _dump(self, stalls: List[Dict[str, Any]]) -> Optional[str]:
        """The diagnostic bundle: live spans + thread stacks + registry
        snapshot, one JSON file per stall episode in the run dir."""
        run_dir = getattr(self.telemetry, "run_dir", None)
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
            components = {
                name: {"active": hb._active,
                       "deadline_s": hb.deadline_s,
                       "last_beat_age_s": round(
                           self._clock() - hb._last, 3)}
                for name, hb in self._components.items()}
        # stale gauges: a dead producer's gauge keeps its last VALUE;
        # age past the stall deadline marks it untrustworthy in the
        # same bundle that shows which component went quiet (ages ride
        # the registry's own monotonic timestamps, not the watchdog's
        # injectable clock)
        gauge_ages = self.telemetry.gauge_ages()
        bundle = {
            "ts": time.time(),
            "stalls": stalls,
            "components": components,
            "live_spans": (self.tracer.live_spans()
                           if self.tracer is not None else []),
            "threads": self._thread_stacks(),
            "telemetry": self.telemetry.summary(),
            "gauge_age_s": {k: round(v, 3)
                            for k, v in gauge_ages.items()},
            "stale_gauges": sorted(
                k for k, v in gauge_ages.items()
                if v > self.default_stall_s),
            # what was already unhealthy BEFORE the stall (ISSUE 7):
            # the health-monitor + alert-state tables, when attached
            "health": (self._health.status_table()
                       if self._health is not None
                       and self._health.enabled else []),
            "alerts": (self._alerts.status_table()
                       if self._alerts is not None
                       and self._alerts.enabled else []),
        }
        if self._cohort is not None:
            # cohort topology (ISSUE 13): best-effort — a dump must
            # never die on a provider racing a relaunch
            try:
                bundle["cohort"] = self._cohort()
            except Exception as e:
                bundle["cohort"] = {"error": str(e)}
        if run_dir is None:
            return None
        path = os.path.join(run_dir, f"stall_dump_{seq}.json")
        try:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(bundle, f, indent=1, default=str)
        except OSError:
            return None
        return path


class _NullWatchdog(Watchdog):
    """The watchdog-off path: `register()` hands out the shared no-op
    heartbeat; every other method is a no-op."""

    def __init__(self):
        self.enabled = False
        self.telemetry = None
        self.tracer = None
        self.mode = "warn"
        self._sticky = None
        self._health = None
        self._alerts = None
        self._cohort = None

    def register(self, name, deadline_s=None):
        return _NULL_HEARTBEAT

    def attach(self, health=None, alerts=None, cohort=None):
        return self

    def status(self):
        return {}

    def start(self):
        return self

    def stop(self) -> None:
        pass

    def poll(self) -> None:
        pass

    def check_now(self):
        return []


_NULL_WATCHDOG = _NullWatchdog()
