"""Sampled step-phase profiler (ISSUE 15 tentpole): where did the
millisecond go, continuously.

ROADMAP item 4 attacks "the remaining phases" of the train step, but
per-phase attribution existed only as offline bench.py special cases
for two phases (requant, sparse update) while the live plane published
one whole-step OptEfficiency number. This module closes that gap the
way tracing (PR 6) did for requests: every `--phase_sample_every` N
steps, ONE training step is dispatched through a phase-split path —
each phase its own synced dispatch over the measurement probes in
training/phase_probes.py (embed-gather → concat/dense →
attention-softmax-pool forward → backward [→ grad all-reduce under a
mesh] → table apply) — while every other step runs the fused path
untouched.

Sample the split, trust the fused (the design note in
ARCHITECTURE.md): on a sampled step the probes are measurement-only
prefixes whose outputs are DISCARDED; the state update still comes
from the one fused dispatch, timed and synced like any other phase.
That makes the sampled step's loss/params bit-equal to an unprofiled
run BY CONSTRUCTION (tests assert it anyway), at the price that the
split cannot see intra-step fusion wins — the signed `residual_ms`
(fused minus the split sum) is published precisely so that blind spot
is a number, not a guess.

Phase derivation: the probe chain is CUMULATIVE (each probe re-runs
its predecessors plus one more stage), so phase k's device time is the
difference of consecutive synced probe times. The apply probe (when
the head provides one) times the optimizer/table apply in isolation;
otherwise the apply phase is the remainder `fused - chain`. Under a
mesh the all-reduce probe times an isolated grads-shaped reduction —
the comm's fully-exposed cost — and `allreduce_exposed_ms` estimates
the portion actually extending the step as
`clamp(allreduce + fused - chain - apply, 0, allreduce)`: today (the
GSPMD reduce sits serially inside backward) that reads ~the full cost;
when ROADMAP item 5's bucketed overlap ships, it reads what overlap
failed to hide — the before/after instrument that change is judged by.

Publication: per-phase `train/phase/<name>_ms` timer histograms + one
`phase` JSONL event per sampled step; the analytic per-phase traffic
model (training/sparse_update.phase_traffic_bytes) is published once
as static `train/phase_bytes/<name>` / `train/phase_floor_ms/<name>`
gauges, and the health engine's PhaseRoofline monitor (obs/health.py)
turns the pair into live `health/phase_*` roofline-utilization gauges
on /metrics.

Disabled path (the PR-2/PR-7 discipline): `create()` returns a shared
no-op singleton unless phase profiling is on AND the telemetry
registry is live; the train loop pays one boolean check per step.
Probes are built (and warm-up compiled, unrecorded) lazily at the
first sampled step, so an off run never compiles them. Stdlib-only at
import time — jax enters only through the probe callables the model
hands over (guard: tests/test_obs_guard.py).
"""

from __future__ import annotations

import time
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

from code2vec_tpu.obs.telemetry import device_sync

__all__ = ["PHASE_ORDER", "PhaseProfiler", "ProbeKit"]

# canonical render order for tools (obs_top, telemetry_report); heads
# emit the subset their ProbeKit supports
PHASE_ORDER = ("infeed_wait", "embed_gather", "concat_dense",
               "forward_pool", "backward", "table_apply",
               "backward_apply", "allreduce", "allreduce_exposed")

# phases summed by the coverage/roofline monitor against the fused
# dispatch (infeed_wait is host time outside it; the allreduce pair is
# informational — today its cost already rides inside backward)
DEVICE_PHASES = ("embed_gather", "concat_dense", "forward_pool",
                 "backward", "table_apply", "backward_apply")


class ProbeKit:
    """The measurement probes one model head hands the profiler.

    `chain` is a sequence of (phase_name, fn(params, batch, rng))
    CUMULATIVE prefixes of the step's forward/backward computation —
    each fn re-runs everything before it plus one more stage, so phase
    k's time is the difference of consecutive probe times. When
    `apply_fn(params, opt_state, batch, rng, chain_out)` is given, the
    last chain fn's output must carry what it needs (the dense mesh
    head returns `(loss, grads)`). `allreduce_fn(chain_out)` (mesh
    runs) times an isolated grads-shaped reduction.

    `derive_remainder` (the default) books the fused step's time not
    covered by the probes as one more phase, `remainder_name` —
    `table_apply` when the chain ends at backward, `backward` when the
    kit stops at the forward chain (the ≤2%-overhead dense default:
    a direct backward probe costs a full fwd+bwd re-run, ~1.9% of a
    64-step window by itself). Kits that measure everything directly
    (dense mesh) set it False and publish the residual instead."""

    def __init__(self, chain: Sequence[Tuple[str, Callable]], *,
                 apply_fn: Optional[Callable] = None,
                 allreduce_fn: Optional[Callable] = None,
                 derive_remainder: bool = True,
                 remainder_name: str = "table_apply"):
        assert chain, "a ProbeKit needs at least one chain probe"
        self.chain = list(chain)
        self.apply_fn = apply_fn
        self.allreduce_fn = allreduce_fn
        self.derive_remainder = derive_remainder
        self.remainder_name = remainder_name


class PhaseProfiler:
    """Sampled phase-split dispatcher for a train loop.

    Usage (both heads):
        prof = PhaseProfiler.create(telemetry, fused_step=step,
                                    probes_factory=..., enabled=...,
                                    sample_every=cfg.PHASE_SAMPLE_EVERY)
        ... in the loop:
        if prof.enabled and prof.should_sample(step_num):
            params, opt_state, loss = prof.run_split(
                params, opt_state, batch, rng, infeed_wait_ms=...)
        else:
            params, opt_state, loss = step(params, opt_state, batch, rng)
    """

    def __init__(self, telemetry, fused_step: Callable,
                 probes_factory: Callable[[], ProbeKit], *,
                 sample_every: int = 64, min_interval_s: float = 0.0,
                 clock: Callable[[], float] = time.monotonic,
                 phase_bytes: Optional[Dict[str, int]] = None,
                 ceiling_gbps: float = 0.0,
                 log: Optional[Callable[[str], None]] = None):
        assert sample_every >= 1
        self.enabled = True
        self._tele = telemetry
        self._fused = fused_step
        self._factory = probes_factory
        self._every = sample_every
        self._min_interval_s = min_interval_s
        self._clock = clock
        self._log = log or (lambda _m: None)
        self._kit: Optional[ProbeKit] = None
        self._last_sample_t: Optional[float] = None
        self.samples = 0
        if ceiling_gbps > 0:
            telemetry.gauge("train/phase_ceiling_gbps", ceiling_gbps,
                            emit=False, static=True)
        for name, nbytes in (phase_bytes or {}).items():
            # analytic facts, set once — static keeps them out of the
            # staleness plane (they are not heartbeats)
            telemetry.gauge(f"train/phase_bytes/{name}", int(nbytes),
                            emit=False, static=True)
            if ceiling_gbps > 0:
                telemetry.gauge(
                    f"train/phase_floor_ms/{name}",
                    nbytes / (ceiling_gbps * 1e9) * 1e3,
                    emit=False, static=True)

    # ---- construction ----
    @classmethod
    def create(cls, telemetry, *, fused_step=None, probes_factory=None,
               enabled: bool = False, **kw) -> "PhaseProfiler":
        """The wired-everywhere entry: the shared no-op singleton
        unless phase profiling is on AND the registry is live AND the
        head supplied its step + probes."""
        if (not enabled or telemetry is None or not telemetry.enabled
                or fused_step is None or probes_factory is None):
            return _NULL_PHASES
        return cls(telemetry, fused_step, probes_factory, **kw)

    @classmethod
    def disabled(cls) -> "PhaseProfiler":
        return _NULL_PHASES

    # ---- cadence ----
    def should_sample(self, step: int) -> bool:
        """True every `sample_every` steps, rate-limited by
        `min_interval_s` on the injected clock (tiny fast steps must
        not turn 1/N sampling into a measurable tax). Step 0 is never
        sampled: that is the fused step's jit-compile call, and a
        compile-time "fused_ms" would poison the phase histograms for
        the whole early run."""
        if step == 0 or step % self._every != 0:
            return False
        if self._min_interval_s > 0 and self._last_sample_t is not None:
            if self._clock() - self._last_sample_t < self._min_interval_s:
                return False
        return True

    # ---- the sampled step ----
    def _build(self) -> ProbeKit:
        """First-sample lazy build: construct the probe kit and run
        every probe once UNRECORDED so jit compile time never lands in
        the phase histograms (the p50 would be poisoned for the whole
        early run)."""
        kit = self._factory()
        assert isinstance(kit, ProbeKit)
        self._kit = kit
        return kit

    @staticmethod
    def _timed(fn, *args) -> Tuple[float, Any]:
        t0 = time.perf_counter()
        out = fn(*args)
        device_sync(out)
        return (time.perf_counter() - t0) * 1e3, out

    def run_split(self, params, opt_state, batch, rng, *,
                  step: int = 0, infeed_wait_ms: Optional[float] = None,
                  recorder=None):
        """One sampled step: synced probe dispatches for attribution,
        then the fused dispatch for the state update — the returned
        (params, opt_state, loss) is the fused step's, so the sampled
        step's trajectory is bit-identical to an unprofiled run.
        Probes run BEFORE the fused dispatch (it donates params /
        opt_state; the probes only read them).

        `recorder` (the loop's TrainStepRecorder, when enabled) is
        beaten after every probe dispatch — the first sample's probe
        compiles must not read as a train-loop stall — and its step
        window is rebased before the fused dispatch, so the sampled
        step's train/step_ms records the fused step alone (probe time
        lives in the phase timers, never in the step-time plane)."""
        first = self._kit is None
        kit = self._kit if not first else self._build()
        tick = recorder.probe_tick if recorder is not None \
            else (lambda: None)
        if first:
            # compile warmup, unrecorded
            out = None
            for _name, fn in kit.chain:
                _ms, out = self._timed(fn, params, batch, rng)
                tick()
            if kit.apply_fn is not None:
                self._timed(kit.apply_fn, params, opt_state, batch,
                            rng, out)
                tick()
            if kit.allreduce_fn is not None:
                self._timed(kit.allreduce_fn, out)
                tick()

        tele = self._tele
        names: List[str] = []
        cum: List[float] = []
        prev = 0.0
        chain_ms = 0.0
        out = None
        for name, fn in kit.chain:
            prev, out = self._timed(fn, params, batch, rng)
            names.append(name)
            cum.append(prev)
            chain_ms = prev
            tick()
        phases: Dict[str, float] = dict(derive_chain_phases(names, cum))
        apply_ms = None
        if kit.apply_fn is not None:
            apply_ms, _ = self._timed(kit.apply_fn, params, opt_state,
                                      batch, rng, out)
            phases["table_apply"] = apply_ms
            tick()
        allreduce_ms = None
        if kit.allreduce_fn is not None:
            allreduce_ms, _ = self._timed(kit.allreduce_fn, out)
            phases["allreduce"] = allreduce_ms
            tick()
        # the state update: the fused step, synced via the loss scalar
        # exactly the way TrainStepRecorder.end_step bounds it. Rebase
        # the recorder first: train/step_ms must record THIS dispatch,
        # not the probe chain above it.
        if recorder is not None:
            recorder.rebase_step_window()
        t0 = time.perf_counter()
        new_params, new_opt_state, loss = self._fused(params, opt_state,
                                                      batch, rng)
        loss_f = float(loss)
        fused_ms = (time.perf_counter() - t0) * 1e3
        remainder_ms = None
        if kit.derive_remainder:
            remainder_ms = max(0.0, fused_ms - chain_ms
                               - (apply_ms or 0.0))
            phases[kit.remainder_name] = remainder_ms
        if allreduce_ms is not None and apply_ms is not None:
            # comm time actually extending the step: today the GSPMD
            # reduce is serial inside backward so this reads ~the full
            # isolated cost; with item-5 overlap it reads what overlap
            # failed to hide (see module docstring)
            phases["allreduce_exposed"] = min(
                allreduce_ms,
                max(0.0, allreduce_ms + fused_ms - chain_ms - apply_ms))
        if infeed_wait_ms is not None:
            phases["infeed_wait"] = infeed_wait_ms

        # split_sum = what the published phases claim, vs fused = what
        # the one real dispatch took. Remainder-deriving kits include
        # the derived phase, so their residual is just clamp slack
        # (≈0); direct-measurement kits (dense mesh) publish the real
        # fusion-win residual
        split_sum = (chain_ms + (apply_ms or 0.0)
                     + (remainder_ms or 0.0))
        residual_ms = fused_ms - split_sum
        for name, ms in phases.items():
            tele.record_ms(f"train/phase/{name}_ms", ms)
        tele.record_ms("train/phase/fused_step_ms", fused_ms)
        event = {f"{k}_ms": round(v, 3) for k, v in phases.items()}
        tele.event("phase", step=int(step),
                   fused_ms=round(fused_ms, 3),
                   split_sum_ms=round(split_sum, 3),
                   residual_ms=round(residual_ms, 3),
                   loss=round(loss_f, 6), **event)
        self.samples += 1
        self._last_sample_t = self._clock()
        return new_params, new_opt_state, loss_f


class _NullPhaseProfiler(PhaseProfiler):
    """The off path: `enabled` False, every method inert, shared
    singleton — the hot loop's guard short-circuits on the boolean."""

    def __init__(self):
        self.enabled = False
        self.samples = 0

    def should_sample(self, step: int) -> bool:
        return False

    def run_split(self, params, opt_state, batch, rng, *, step: int = 0,
                  infeed_wait_ms: Optional[float] = None,
                  recorder=None):
        raise RuntimeError("disabled PhaseProfiler cannot run_split")


_NULL_PHASES = _NullPhaseProfiler()


def derive_chain_phases(names: Sequence[str], cumulative_ms:
                        Sequence[float]) -> List[Tuple[str, float]]:
    """Cumulative probe times -> per-phase deltas (clamped at 0).
    Shared with bench.py's slope-timed breakdown so the offline and
    sampled attributions use one differencing rule."""
    out: List[Tuple[str, float]] = []
    prev = 0.0
    for name, t in zip(names, cumulative_ms):
        out.append((name, max(0.0, t - prev)))
        prev = t
    return out
