"""Fleet plane (ISSUE 17 tentpole): live cohort aggregation — the
signals no single host can compute.

Every observability layer so far — telemetry (PR 2), traces (PR 6),
/metrics + alerts (PR 7), the phase plane (PR 15) — is per-host; the
only cohort views are offline merges. `FleetCollector` is the pull
tier over N member `/metrics` + `/vars` endpoints (stdlib urllib, the
shared obs/promtext parser) that derives, each sweep:

  - **clock offsets** — at first contact (and again whenever a
    member's run_id changes: a supervisor relaunch is a NEW process)
    the collector runs the `/clock` handshake: K round trips, each
    bracketed by the collector's own wall clock; one offset sample is
    `member_wall - (c0 + c1) / 2` (the round-trip-corrected
    midpoint), and the member's offset is the median of K — robust to
    a tail of asymmetric round trips. The measurement is COMMITTED
    back (`/clock?commit=1&offset_s=...`) so the member persists it
    into its run manifest, which is what `trace_report.py --merge`
    aligns cohort traces with.
  - **straggler score** — per host, the p50 of each host-attributable
    series (`train/step_ms`, `train/infeed_wait_ms`, every
    `train/phase_*_ms` the host exports) over the COHORT MEDIAN of
    that series; the host's score is its worst ratio and the series
    that produced it names the attribution — a slow host whose cost
    surfaces as everyone else's exposed all-reduce shows up here as
    `phase_allreduce_exposed` skew, not as a mystery.
  - **divergence** — the runtime companion to the PR-14
    SPMD-divergence lint: members publish a per-step loss gauge and a
    sampled params fingerprint (obs/loop.py), step-labeled; the
    collector remembers recent (step -> value) pairs per host and
    compares hosts at MATCHING steps. SPMD training replicates both,
    so any disagreement past tolerance sets `fleet/divergence` and
    the `cohort_divergence` ticket fires through the alert engine.
  - **cohort throughput** — summed examples/s and path-contexts/s,
    differenced between sweeps with the shared counter-reset
    semantics (promtext.CounterRates).

Aggregates publish as `fleet/*` gauges into the HOSTING process's
registry (the supervisor: training/supervisor.py wires the collector,
its alert rules ride the existing engine, and the cohort snapshot
joins stall dumps next to `cohort_topology`), serve live on `/fleet`
(obs/exposition, JSON + Prometheus text), and persist as a bounded
JSONL ring for postmortems.

House rules: disabled path is a shared no-op singleton — no thread,
one boolean/None check per site; `clock`/`wall`/`fetch` are
injectable so every policy test runs sleep-free and socket-free;
stdlib only, jax and TensorFlow never (tests/test_obs_guard.py).
"""

from __future__ import annotations

import collections
import json
import statistics
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence

from code2vec_tpu.obs import promtext

__all__ = ["FleetCollector", "fleet_alert_rules"]

# per-host step history kept for cross-host divergence matching: deep
# enough that two hosts scraped a few steps apart still intersect
_STEP_HISTORY = 64


def fleet_alert_rules():
    """Cohort tickets over the collector's gauges — evaluated by the
    HOSTING process's alert engine (the supervisor's). Quiet until the
    fleet plane publishes (threshold rules on absent series never
    fire), so they are safe to install unconditionally."""
    from code2vec_tpu.obs.alerts import AlertRule
    return [
        # one host's p50 at 1.5x the cohort median on any attributable
        # series: capacity is degraded NOW, but training still moves —
        # ticket, not page
        AlertRule("cohort_straggler", metric="fleet/straggler_score",
                  op=">", value=1.5, severity="ticket"),
        # replicated loss / params fingerprints disagreeing at the
        # SAME step: the SPMD contract is broken at runtime
        AlertRule("cohort_divergence", metric="fleet/divergence",
                  op=">=", value=1.0, severity="ticket"),
    ]


class _Member:
    """One endpoint's collector-side state: rate window, measured
    clock offset, identity, and the recent step-labeled values the
    divergence check matches across hosts."""

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self.url = (endpoint if "://" in endpoint
                    else f"http://{endpoint}").rstrip("/")
        self.rates = promtext.CounterRates()
        self.offset_s: Optional[float] = None
        self.committed = False
        self.run_id: Optional[str] = None
        self.identity: Dict[str, Any] = {}
        self.error: Optional[str] = None
        self.loss_by_step: "collections.OrderedDict" = \
            collections.OrderedDict()
        self.digest_by_step: "collections.OrderedDict" = \
            collections.OrderedDict()

    def remember(self, table: "collections.OrderedDict",
                 step: Optional[float], value: Optional[float]) -> None:
        if step is None or value is None:
            return
        table[int(step)] = value
        while len(table) > _STEP_HISTORY:
            table.popitem(last=False)


class FleetCollector:
    """Pull-based cohort aggregator. Construct via `create()` (the
    shared disabled singleton when there are no members to scrape);
    `start()` sweeps on a daemon thread, `sample()` sweeps once
    synchronously (the fake-clock test path — and safe to call from
    other threads: sweeps serialize on one lock)."""

    def __init__(self, telemetry, *, members: Sequence[str] = (),
                 interval_s: float = 2.0, handshake_samples: int = 5,
                 history: int = 256,
                 history_path: Optional[str] = None,
                 alerts=None, divergence_rtol: float = 1e-4,
                 timeout_s: float = 3.0,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 fetch: Optional[Callable[[str], str]] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.enabled = True
        self.telemetry = telemetry
        self.interval_s = interval_s
        self.handshake_samples = max(1, handshake_samples)
        self.divergence_rtol = divergence_rtol
        self.timeout_s = timeout_s
        self._clock = clock
        self._wall = wall
        self._fetch = fetch if fetch is not None else self._http_fetch
        self._log = log or (lambda _m: None)
        self._alerts = alerts
        self._lock = threading.RLock()
        self._members: List[_Member] = [_Member(e) for e in members]
        self.history: "collections.deque" = \
            collections.deque(maxlen=max(1, history))
        self._history_path = history_path
        self._history_file = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- construction ----
    @classmethod
    def create(cls, telemetry, *, members: Sequence[str] = (),
               **kw) -> "FleetCollector":
        """The wired-everywhere entry: disabled singleton unless there
        are members to scrape and a live registry to publish into."""
        if not members or telemetry is None or not telemetry.enabled:
            return _NULL_FLEET
        return cls(telemetry, members=members, **kw)

    @classmethod
    def disabled(cls) -> "FleetCollector":
        return _NULL_FLEET

    def attach(self, alerts=None) -> "FleetCollector":
        """Ride the HOSTING process's alert engine: each sweep ends
        with a `check_now()` so straggler/divergence transitions
        escalate in the same tick that observed them."""
        if alerts is not None and getattr(alerts, "enabled", False):
            self._alerts = alerts
        return self

    def set_members(self, endpoints: Sequence[str]) -> None:
        """Re-point the collector at a (re)launched cohort — the
        supervisor calls this per attempt, so an elastic resize
        shrinks the scrape set with the mesh. Existing state is kept
        for endpoints that stay (the run_id check re-handshakes the
        relaunched ones)."""
        with self._lock:
            old = {m.endpoint: m for m in self._members}
            self._members = [old.get(e, _Member(e)) for e in endpoints]

    # ---- transport ----
    def _http_fetch(self, url: str) -> str:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return r.read().decode("utf-8")

    # ---- clock handshake ----
    def _handshake(self, member: _Member) -> None:
        """Estimate this member's wall-clock offset (median of K
        round-trip-corrected samples) and commit it back so the member
        persists the measurement into its run manifest."""
        samples = []
        last: Dict[str, Any] = {}
        for _ in range(self.handshake_samples):
            c0 = self._wall()
            last = json.loads(self._fetch(member.url + "/clock"))
            c1 = self._wall()
            samples.append(float(last["wall"]) - (c0 + c1) / 2.0)
        member.offset_s = statistics.median(samples)
        member.identity = dict(last.get("identity") or {})
        member.run_id = member.identity.get("run_id")
        commit = json.loads(self._fetch(
            f"{member.url}/clock?commit=1"
            f"&offset_s={member.offset_s:.9f}"
            f"&samples={len(samples)}"))
        member.committed = bool(commit.get("committed"))
        self._log(f"fleet: {member.endpoint} offset "
                  f"{member.offset_s * 1e3:+.3f} ms over "
                  f"{len(samples)} samples"
                  f"{' (committed to manifest)' if member.committed else ''}")

    # ---- one member, one sweep ----
    def _poll_member(self, member: _Member, t: float
                     ) -> Dict[str, Any]:
        try:
            vars_body = json.loads(self._fetch(member.url + "/vars"))
            identity = dict(vars_body.get("identity") or {})
            if member.run_id is None \
                    or identity.get("run_id") != member.run_id:
                if member.run_id is not None:
                    # relaunched process: its counters restarted from
                    # zero and its clock is a fresh measurement
                    member.rates.reset()
                self._handshake(member)
            metrics = promtext.parse_prometheus(
                self._fetch(member.url + "/metrics"))
            member.error = None
        except (urllib.error.URLError, OSError, ValueError,
                KeyError) as e:
            member.error = str(getattr(e, "reason", e))
            return {"endpoint": member.endpoint, "up": False,
                    "error": member.error}
        rate = member.rates.advance(t, metrics)
        ex_rate = rate("train_examples")
        max_ctx = promtext.scalar(metrics, "train_max_contexts")
        phases = {}
        for fam in metrics:
            if fam.startswith("train_phase_") and fam.endswith("_ms"):
                v = promtext.labeled(metrics, fam, quantile="0.5")
                if v is not None:
                    phases[fam[len("train_phase_"):-3]] = v
        row = {
            "endpoint": member.endpoint,
            "up": True,
            "run_id": member.run_id,
            "process_index": member.identity.get("process_index"),
            "clock_offset_s": member.offset_s,
            "clock_committed": member.committed,
            "steps": promtext.scalar(metrics, "train_steps"),
            "steps_s": rate("train_steps"),
            "ex_s": ex_rate,
            "pc_s": (ex_rate * max_ctx
                     if ex_rate is not None and max_ctx else None),
            "step_p50": promtext.labeled(metrics, "train_step_ms",
                                         quantile="0.5"),
            "infeed_p50": promtext.labeled(
                metrics, "train_infeed_wait_ms", quantile="0.5"),
            "loss": promtext.scalar(metrics, "train_loss"),
            "phases": phases,
            "restarted": list(member.rates.restarted),
        }
        member.remember(member.loss_by_step,
                        promtext.scalar(metrics, "train_loss_step"),
                        row["loss"])
        member.remember(member.digest_by_step,
                        promtext.scalar(metrics,
                                        "train_params_digest_step"),
                        promtext.scalar(metrics, "train_params_digest"))
        return row

    # ---- cohort derivations ----
    @staticmethod
    def _straggle(rows: List[Dict[str, Any]]) -> None:
        """Per-host skew vs cohort median, per attributable series;
        each host's straggler score is its worst ratio, labeled with
        the series that produced it (the per-phase entries are what
        attribute a slow host's cost to `allreduce_exposed` on
        everyone else)."""
        series: Dict[str, List[float]] = {}
        for r in rows:
            if r.get("step_p50") is not None:
                series.setdefault("step_ms", []).append(r["step_p50"])
            if r.get("infeed_p50") is not None:
                series.setdefault("infeed_wait_ms",
                                  []).append(r["infeed_p50"])
            for p, v in (r.get("phases") or {}).items():
                series.setdefault(f"phase_{p}", []).append(v)
        medians = {s: statistics.median(vals)
                   for s, vals in series.items()
                   if len(vals) >= 2 and statistics.median(vals) > 0}
        for r in rows:
            score, worst = None, None
            host_vals = {"step_ms": r.get("step_p50"),
                         "infeed_wait_ms": r.get("infeed_p50")}
            for p, v in (r.get("phases") or {}).items():
                host_vals[f"phase_{p}"] = v
            for s, med in medians.items():
                v = host_vals.get(s)
                if v is None:
                    continue
                ratio = v / med
                if score is None or ratio > score:
                    score, worst = ratio, s
            r["straggler_score"] = score
            r["straggler_series"] = worst

    def _diverge(self) -> Dict[str, Any]:
        """Cross-host disagreement at MATCHING steps, over the recent
        step-labeled history each member accumulated. Returns the
        worst relative spread seen per signal plus the 0/1 verdict."""
        out: Dict[str, Any] = {"divergence": 0}
        for key, attr in (("loss", "loss_by_step"),
                          ("params_digest", "digest_by_step")):
            tables = [getattr(m, attr) for m in self._members
                      if getattr(m, attr)]
            worst_rel, worst_step = 0.0, None
            if len(tables) >= 2:
                common = set(tables[0])
                for t in tables[1:]:
                    common &= set(t)
                for step in common:
                    vals = [t[step] for t in tables]
                    spread = max(vals) - min(vals)
                    scale = max(abs(statistics.median(vals)), 1e-12)
                    rel = spread / scale
                    if rel > worst_rel:
                        worst_rel, worst_step = rel, step
            out[f"{key}_divergence_rel"] = worst_rel
            out[f"{key}_divergence_step"] = worst_step
            if worst_rel > self.divergence_rtol:
                out["divergence"] = 1
        return out

    # ---- the sweep ----
    def sample(self) -> Dict[str, Any]:
        """One synchronous sweep: poll every member, derive cohort
        signals, publish `fleet/*` gauges, append history + JSONL,
        escalate through the attached alert engine. Returns the
        aggregate (what `/fleet` serves)."""
        with self._lock:
            t = self._clock()
            rows = [self._poll_member(m, t) for m in self._members]
            ok = [r for r in rows if r.get("up")]
            self._straggle(ok)

            def _sum(key: str) -> Optional[float]:
                vals = [r[key] for r in ok if r.get(key) is not None]
                return sum(vals) if vals else None

            scores = [(r["straggler_score"], r) for r in ok
                      if r.get("straggler_score") is not None]
            worst = max(scores, key=lambda s: s[0]) if scores else None
            p50s = [r["step_p50"] for r in ok
                    if r.get("step_p50") is not None]
            skew = (max(p50s) / statistics.median(p50s)
                    if len(p50s) >= 2 and statistics.median(p50s) > 0
                    else None)
            offsets = [r["clock_offset_s"] for r in ok
                       if r.get("clock_offset_s") is not None]
            div = self._diverge()
            cohort: Dict[str, Any] = {
                "hosts_up": len(ok),
                "hosts_total": len(rows),
                "ex_per_sec": _sum("ex_s"),
                "pc_per_sec": _sum("pc_s"),
                "steps_per_sec": _sum("steps_s"),
                "straggler_score": worst[0] if worst else None,
                "straggler_host": worst[1]["endpoint"] if worst
                else None,
                "straggler_series": worst[1]["straggler_series"]
                if worst else None,
                "step_p50_skew": skew,
                "clock_spread_s": (max(offsets) - min(offsets)
                                   if len(offsets) >= 2 else None),
                **div,
            }
            agg = {"ts": self._wall(), "cohort": cohort, "hosts": rows}
            self._publish(cohort)
            self.history.append(agg)
            self._persist(agg)
        alerts = self._alerts
        if alerts is not None and alerts.enabled:
            alerts.check_now()
        return agg

    def _publish(self, cohort: Dict[str, Any]) -> None:
        """Cohort signals -> the hosting registry (emit=False: gauge
        stores feeding /metrics and the alert rules, never JSONL —
        the aggregate history IS the durable record)."""
        tele = self.telemetry
        gauges = (("fleet/hosts_up", cohort["hosts_up"]),
                  ("fleet/hosts_total", cohort["hosts_total"]),
                  ("fleet/pc_per_sec", cohort["pc_per_sec"]),
                  ("fleet/ex_per_sec", cohort["ex_per_sec"]),
                  ("fleet/straggler_score", cohort["straggler_score"]),
                  ("fleet/step_p50_skew", cohort["step_p50_skew"]),
                  ("fleet/clock_spread_s", cohort["clock_spread_s"]),
                  ("fleet/divergence", cohort["divergence"]),
                  ("fleet/loss_divergence_rel",
                   cohort["loss_divergence_rel"]))
        for name, value in gauges:
            if value is not None:
                tele.gauge(name, float(value), emit=False)

    def _persist(self, agg: Dict[str, Any]) -> None:
        path = self._history_path
        if path is None and self.telemetry.run_dir:
            import os
            path = os.path.join(self.telemetry.run_dir, "fleet.jsonl")
        if path is None:
            return
        try:
            if self._history_file is None:
                self._history_file = open(path, "a", encoding="utf-8")
            self._history_file.write(
                json.dumps(agg, default=str) + "\n")
            self._history_file.flush()
        except OSError as e:
            # a full postmortem disk must not take the collector (or
            # the run it observes) down; the in-memory ring still holds
            self._log(f"fleet: history write failed: {e}")

    # ---- reads ----
    def aggregate(self) -> Dict[str, Any]:
        """The latest sweep's aggregate (what `/fleet` serves); {}
        before the first sweep."""
        with self._lock:
            return self.history[-1] if self.history else {}

    def brief(self) -> Dict[str, Any]:
        """The stall-dump attachment (training/supervisor wires this
        next to cohort_topology): the latest cohort block plus per-host
        one-liners — enough to answer "who was slow" from a dump."""
        agg = self.aggregate()
        if not agg:
            return {"sweeps": 0}
        return {"ts": agg["ts"], "cohort": agg["cohort"],
                "hosts": [{k: r.get(k) for k in
                           ("endpoint", "up", "error", "step_p50",
                            "straggler_score", "straggler_series")}
                          for r in agg["hosts"]],
                "sweeps": len(self.history)}

    def render_prometheus(self) -> str:
        """The `/fleet?format=prom` payload: cohort totals unlabeled,
        per-host series labeled by endpoint."""
        agg = self.aggregate()
        lines: List[str] = []
        cohort = agg.get("cohort") or {}
        for key in ("hosts_up", "hosts_total", "pc_per_sec",
                    "ex_per_sec", "straggler_score", "step_p50_skew",
                    "clock_spread_s", "divergence",
                    "loss_divergence_rel"):
            v = cohort.get(key)
            if v is not None:
                lines.append(f"# TYPE fleet_{key} gauge")
                lines.append(f"fleet_{key} {float(v)}")
        per_host = (("step_p50", "fleet_host_step_p50_ms"),
                    ("infeed_p50", "fleet_host_infeed_p50_ms"),
                    ("pc_s", "fleet_host_pc_per_sec"),
                    ("straggler_score", "fleet_host_straggler_score"),
                    ("clock_offset_s", "fleet_host_clock_offset_s"))
        for key, fam in per_host:
            rows = [(r["endpoint"], r[key])
                    for r in agg.get("hosts", ())
                    if r.get(key) is not None]
            if rows:
                lines.append(f"# TYPE {fam} gauge")
                for host, v in rows:
                    lines.append(f'{fam}{{host="{host}"}} {float(v)}')
        return "\n".join(lines) + "\n"

    # ---- lifecycle ----
    def start(self) -> "FleetCollector":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-collector")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample()
            except Exception as e:  # noqa: BLE001 — the collector
                # observes the run; it must never take it down (the
                # error IS surfaced: logged, and the member rows carry
                # their own per-endpoint errors)
                self._log(f"fleet: sweep failed: {e!r}")
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=max(5.0, self.timeout_s * 2))
        f, self._history_file = self._history_file, None
        if f is not None:
            f.close()


class _NullFleetCollector(FleetCollector):
    """The fleet-plane-off path: shared no-op singleton — no thread,
    no per-step work, `enabled` gates every site with one check."""

    def __init__(self):
        self.enabled = False
        self.telemetry = None
        self.history = collections.deque(maxlen=1)

    def attach(self, alerts=None):
        return self

    def set_members(self, endpoints):
        pass

    def sample(self):
        return {}

    def aggregate(self):
        return {}

    def brief(self):
        return {}

    def render_prometheus(self):
        return "\n"

    def start(self):
        return self

    def stop(self) -> None:
        pass


_NULL_FLEET = _NullFleetCollector()
