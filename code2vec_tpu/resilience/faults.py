"""Deterministic failpoint registry (ISSUE 10 tentpole, layer 1).

Crash-only software says the recovery path should be the ORDINARY
path — exercised constantly, not discovered in postmortems. This module
makes that exercise reproducible: named injection sites sit at the
repo's real failure seams, and a seeded spec decides exactly which hit
of which site fails, how. The same `--faults` JSON replays the same
failure on every run, so a chaos scenario (tools/chaos.py) is a TEST,
not a dice roll.

Sites wired through the codebase:

  ckpt/write       training/checkpoint.save_checkpoint — slow disk
                   (`sleep`), disk full (`io_error` + `partial` torn
                   marker), crash-before-rename (`kill`)
  infeed/produce   data/prefetch.build_train_infeed — producer-thread
                   exception per batch
  train/nan_loss   both train loops — poisons the step loss to NaN
                   (value substitution: the site calls `hit()` and
                   corrupts the loss itself)
  train/kill       both train loops — SIGKILL this process mid-epoch
  serve/extract    serving/extractor.Extractor.extract_paths — worker
                   crash the pool must survive
  serve/kill       serving/server.PredictionServer.predict_lines —
                   replica-process death on the request path (action
                   `kill`: the SIGKILL a replica pool must absorb;
                   ROADMAP item 1's serving-chaos hook, symmetric
                   with serve/extract)
  reload/read      serving/reload.ReloadManager — IO failure while
                   reading a VERIFIED checkpoint's weights for a hot
                   swap (`io_error`: exercises the reload retry
                   policy; exhausted retries refuse the step, the
                   pool keeps serving the weights it has)
  dist/init        parallel/distributed.maybe_initialize — transient
                   Gloo/coordination-service connect failure

Disabled path (the default): the module-level registry is None, so
`fire()` is one None check and `point()` returns a shared null handle
whose `armed` is False — hot loops guard on that one attribute read.
No thread is ever started by this module.

Spec format (`--faults <file-or-inline-json>`):

    {"seed": 0,
     "sites": {
       "train/kill":  {"action": "kill", "at": 5,
                       "marker": "/tmp/killed.once"},
       "ckpt/write":  {"action": "io_error", "errno": "ENOSPC",
                       "partial": true},
       "dist/init":   {"action": "raise", "times": 2},
       "infeed/produce": {"action": "raise", "prob": 0.01}}}

Per-site fields: `action` (raise | io_error | sleep | kill | exit |
nan), `at` (1-based hit index that triggers; default 1), `times` (max
firings, default 1, -1 = unlimited), `prob` (per-hit probability from a
per-site seeded stream — deterministic given the seed; overrides `at`),
`delay_ms` (sleep), `errno` (io_error; name or number, default ENOSPC),
`partial` (io_error/kill: first create an orbax-style torn
`state.orbax-checkpoint-tmp/` marker under the site's `path` context —
what a real mid-write death leaves behind), `marker` (a file path
created atomically at first firing; while it exists the site is
disarmed — the cross-RESTART once-latch a supervisor-relaunched process
needs, or the kill would replay forever), `process` (only fire on this
jax process index — kill one worker of a cohort), `code` (exit).
"""

from __future__ import annotations

import errno as errno_mod
import json
import os
import random
import signal
import threading
import time
from typing import Any, Callable, Dict, Optional

__all__ = ["FaultInjected", "FaultPoint", "install", "clear", "enabled",
           "fire", "point", "stats"]

_ACTIONS = ("raise", "io_error", "sleep", "kill", "exit", "nan")

_TORN_MARKER = "state.orbax-checkpoint-tmp"


class FaultInjected(RuntimeError):
    """An injected failure (action `raise`). Recovery code treats it
    like the real error it stands in for; nothing may catch it JUST
    because it is injected."""


def _process_index() -> int:
    """This process's jax process index, 0 when jax is unavailable or
    uninitialized (armed-path only — the disabled path never gets
    here)."""
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0


class _Site:
    """One armed injection site: trigger bookkeeping + the action."""

    def __init__(self, name: str, spec: Dict[str, Any], seed: int):
        unknown = set(spec) - {"action", "at", "times", "prob",
                               "delay_ms", "errno", "partial", "marker",
                               "process", "code"}
        if unknown:
            raise ValueError(f"fault site {name!r}: unknown spec "
                             f"fields {sorted(unknown)}")
        self.name = name
        self.action = spec.get("action", "raise")
        if self.action not in _ACTIONS:
            raise ValueError(f"fault site {name!r}: action must be one "
                             f"of {_ACTIONS} (got {self.action!r})")
        self.at = int(spec.get("at", 1))
        self.times = int(spec.get("times", 1))
        self.prob = spec.get("prob")
        self.delay_ms = float(spec.get("delay_ms", 100.0))
        err = spec.get("errno", "ENOSPC")
        self.errno = getattr(errno_mod, err) if isinstance(err, str) \
            else int(err)
        self.partial = bool(spec.get("partial", False))
        self.marker = spec.get("marker")
        self.process = spec.get("process")
        self.exit_code = int(spec.get("code", 17))
        # per-site seeded stream: which hits a `prob` site fails is a
        # function of (seed, site name) alone — independent of every
        # other site's draw order
        self._rng = random.Random(f"{seed}:{name}")
        self.hits = 0
        self.fired = 0
        self._lock = threading.Lock()

    def hit(self) -> bool:
        """Count one occurrence; True when THIS occurrence triggers."""
        if self.process is not None \
                and _process_index() != int(self.process):
            return False
        with self._lock:
            self.hits += 1
            if self.times >= 0 and self.fired >= self.times:
                return False
            if self.marker and os.path.exists(self.marker):
                return False  # already fired in an earlier incarnation
            if self.prob is not None:
                triggered = self._rng.random() < float(self.prob)
            else:
                triggered = self.hits >= self.at
            if not triggered:
                return False
            self.fired += 1
        if self.marker:
            # atomic cross-process once-latch: exactly one process of a
            # cohort wins the exclusive create; losers stay disarmed
            try:
                with open(self.marker, "x") as f:
                    f.write(f"{self.name} pid={os.getpid()} "
                            f"ts={time.time()}\n")
            except FileExistsError:
                return False
        return True

    def _make_partial(self, ctx: Dict[str, Any]) -> None:
        """Leave what a real mid-write death leaves: a torn orbax temp
        marker under the site's `path` context (never a committed
        `state`)."""
        path = ctx.get("path")
        if path:
            os.makedirs(os.path.join(path, _TORN_MARKER), exist_ok=True)

    def act(self, ctx: Dict[str, Any],
            log: Callable[[str], None]) -> None:
        log(f"faults: firing {self.name!r} action={self.action} "
            f"hit={self.hits} pid={os.getpid()}")
        if self.action == "sleep":
            time.sleep(self.delay_ms / 1e3)
            return
        if self.partial:
            self._make_partial(ctx)
        if self.action == "io_error":
            raise OSError(self.errno,
                          f"fault injected at {self.name}")
        if self.action == "kill":
            # SIGKILL: no handlers, no finallys — the real preemption
            os.kill(os.getpid(), signal.SIGKILL)
        if self.action == "exit":
            os._exit(self.exit_code)
        if self.action == "raise":
            raise FaultInjected(f"fault injected at {self.name}")
        # "nan" (and any future value-substitution action) has no side
        # effect here: the site consumes hit() and corrupts the value


class FaultPoint:
    """A site handle for hot paths: fetch once at loop setup, then
    `armed` is one attribute read per event when faults are off (or the
    site is not configured)."""

    __slots__ = ("armed", "_site", "_log")

    def __init__(self, site: Optional[_Site], log):
        self.armed = site is not None
        self._site = site
        self._log = log

    def hit(self) -> bool:
        """Trigger decision only — value-substitution sites (NaN loss)
        corrupt the value themselves when this returns True."""
        return self._site is not None and self._site.hit()

    def fire(self, **ctx) -> None:
        if self._site is not None and self._site.hit():
            self._site.act(ctx, self._log)


_NULL_POINT = FaultPoint(None, None)


class FaultRegistry:
    def __init__(self, spec: Dict[str, Any],
                 log: Optional[Callable[[str], None]] = None):
        self.seed = int(spec.get("seed", 0))
        sites = spec.get("sites")
        if not isinstance(sites, dict) or not sites:
            raise ValueError(
                "faults spec needs a non-empty 'sites' mapping "
                "(site name -> spec object)")
        self.log = log or (lambda m: print(m, flush=True))
        self.sites = {name: _Site(name, s, self.seed)
                      for name, s in sites.items()}

    def stats(self) -> Dict[str, Dict[str, int]]:
        return {name: {"hits": s.hits, "fired": s.fired}
                for name, s in self.sites.items()}


_REGISTRY: Optional[FaultRegistry] = None


def install(spec, *, log: Optional[Callable[[str], None]] = None
            ) -> FaultRegistry:
    """Arm the registry from a dict, an inline JSON string, or a path
    to a JSON file. Install BEFORE building models/servers — sites
    fetch their `point()` handles at setup time."""
    global _REGISTRY
    if isinstance(spec, str):
        if os.path.exists(spec):
            with open(spec, encoding="utf-8") as f:
                spec = json.load(f)
        else:
            spec = json.loads(spec)
    _REGISTRY = FaultRegistry(spec, log=log)
    _REGISTRY.log(f"faults: armed {sorted(_REGISTRY.sites)} "
                  f"(seed {_REGISTRY.seed})")
    return _REGISTRY


def clear() -> None:
    global _REGISTRY
    _REGISTRY = None


def enabled() -> bool:
    return _REGISTRY is not None


def point(name: str) -> FaultPoint:
    """Armed handle for `name`, or the shared null handle (armed=False)
    when faults are off or the site is not in the spec."""
    reg = _REGISTRY
    if reg is None:
        return _NULL_POINT
    site = reg.sites.get(name)
    if site is None:
        return _NULL_POINT
    return FaultPoint(site, reg.log)


def fire(name: str, **ctx) -> None:
    """One-shot form for non-hot sites (checkpoint write, extractor,
    distributed init): disabled cost is this None check."""
    reg = _REGISTRY
    if reg is None:
        return
    site = reg.sites.get(name)
    if site is not None and site.hit():
        site.act(ctx, reg.log)


def train_step_points() -> "tuple[FaultPoint, FaultPoint]":
    """The two per-step train-loop failpoints, `(nan_loss, kill)`,
    fetched together so the two model heads' loops cannot drift on
    site names (the round-11 infeed_produce_instrument lesson)."""
    return point("train/nan_loss"), point("train/kill")


def stats() -> Dict[str, Dict[str, int]]:
    reg = _REGISTRY
    return reg.stats() if reg is not None else {}
