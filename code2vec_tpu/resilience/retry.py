"""One retry/backoff policy for the whole repo (ISSUE 10 tentpole,
layer 2).

Before this module, transient-failure handling was re-invented per
call site: tools/multichip_bench.py hand-rolled a 3-attempt
fresh-port loop, the two_process_results fixture hand-rolled a
2-attempt copy of it, and every other seam (distributed init, the
extractor pool, checkpoint IO) either crashed on the first transient
error or could not retry at all. `RetryPolicy` is the one
implementation: jittered exponential backoff, a per-CALL attempt
budget (policies are shared, budgets are not), an optional `giveup`
predicate for errors that retrying cannot fix (ENOSPC), and
`resilience/retry` telemetry so a run that limped through on retries
says so in its event log.

Telemetry is module-global and optional: `set_telemetry()` points the
counters (`resilience/retry`, `resilience/retry_exhausted`,
`resilience/retry_giveup`) and `retry` events at a registry; without
one, `stats()` still answers "did anything retry" in-process.
Stdlib-only; sleeps/randomness are injectable so every test is
sleep-free and deterministic.
"""

from __future__ import annotations

import random
import subprocess
import threading
import time
from typing import Callable, Dict, Optional, Tuple, Type

__all__ = ["RetryPolicy", "TRANSIENT_DISTRIBUTED_ERRORS",
           "set_telemetry", "stats", "transient_distributed"]

# The distributed harness's transient failure surface: a crashed
# worker (RuntimeError from the spawner), a connect/transport error,
# or the peer outliving the crash inside a collective until the
# parent's communicate() wall hits first (TimeoutExpired).
TRANSIENT_DISTRIBUTED_ERRORS: Tuple[Type[BaseException], ...] = (
    RuntimeError, OSError, ConnectionError, subprocess.TimeoutExpired)

_TELEMETRY = None
_STATS: Dict[str, Dict[str, int]] = {}
_STATS_LOCK = threading.Lock()


def set_telemetry(telemetry) -> None:
    """Point retry counters/events at a Telemetry registry (None to
    detach). The train loops and the supervisor wire their own."""
    global _TELEMETRY
    _TELEMETRY = telemetry


def stats() -> Dict[str, Dict[str, int]]:
    """Per-policy {retries, exhausted, giveup} counts (in-process,
    telemetry or not)."""
    with _STATS_LOCK:
        return {k: dict(v) for k, v in _STATS.items()}


def _record(policy: str, outcome: str, attempt: int, error: str,
            delay_s: float) -> None:
    with _STATS_LOCK:
        row = _STATS.setdefault(policy, {"retries": 0, "exhausted": 0,
                                         "giveup": 0})
        key = {"retry": "retries", "exhausted": "exhausted",
               "giveup": "giveup"}[outcome]
        row[key] += 1
    tele = _TELEMETRY
    if tele is not None and tele.enabled:
        tele.count("resilience/retry" if outcome == "retry"
                   else f"resilience/retry_{outcome}")
        tele.event("retry", policy=policy, outcome=outcome,
                   attempt=attempt, error=error[:200],
                   delay_s=round(delay_s, 4))


class RetryPolicy:
    """Jittered exponential backoff with a per-call attempt budget.

    delay(n) = min(max_delay_s, base_delay_s * multiplier^(n-1)),
    scaled by a uniform draw in [1 - jitter, 1] from the policy's own
    stream (seed it for deterministic tests). A policy object is
    reusable and thread-safe to `call()` concurrently — all mutable
    per-call state is local; only the jitter stream is shared (guarded).

    `retry_on` bounds WHAT retries; `giveup(exc) -> bool` vetoes
    retrying an otherwise-matching error that backoff cannot fix
    (ENOSPC: the disk does not refill on a schedule — surface it now).
    `max_elapsed_s` is the wall budget across one call's attempts.
    """

    def __init__(self, name: str, *, max_attempts: int = 3,
                 base_delay_s: float = 0.1, max_delay_s: float = 30.0,
                 multiplier: float = 2.0, jitter: float = 0.5,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 giveup: Optional[Callable[[BaseException], bool]] = None,
                 max_elapsed_s: Optional[float] = None,
                 seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 log: Optional[Callable[[str], None]] = None):
        assert max_attempts >= 1 and base_delay_s >= 0 \
            and 0.0 <= jitter <= 1.0
        self.name = name
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.multiplier = multiplier
        self.jitter = jitter
        self.retry_on = retry_on
        self.giveup = giveup
        self.max_elapsed_s = max_elapsed_s
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._sleep = sleep
        self._log = log or (lambda _m: None)

    def delay_s(self, attempt: int) -> float:
        """Backoff before retry number `attempt` (1-based: after the
        attempt'th failure). Public so the supervisor's restart pacing
        is THIS math, not a reimplementation."""
        d = min(self.max_delay_s,
                self.base_delay_s * self.multiplier ** (attempt - 1))
        with self._rng_lock:
            u = self._rng.random()
        return d * (1.0 - self.jitter * u)

    def call(self, fn: Callable, *args, **kwargs):
        """Run `fn(*args, **kwargs)` under this policy's budget. The
        final failure (or a giveup) re-raises unwrapped — callers keep
        their exception contracts."""
        t0 = time.monotonic()
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                if self.giveup is not None and self.giveup(e):
                    _record(self.name, "giveup", attempt, repr(e), 0.0)
                    raise
                out_of_time = (
                    self.max_elapsed_s is not None
                    and time.monotonic() - t0 >= self.max_elapsed_s)
                if attempt >= self.max_attempts or out_of_time:
                    _record(self.name, "exhausted", attempt, repr(e),
                            0.0)
                    raise
                d = self.delay_s(attempt)
                _record(self.name, "retry", attempt, repr(e), d)
                self._log(
                    f"retry[{self.name}]: attempt {attempt}/"
                    f"{self.max_attempts} failed "
                    f"({str(e).splitlines()[0][:120]}); retrying in "
                    f"{d:.2f}s")
                self._sleep(d)
        raise AssertionError("unreachable")  # loop always returns/raises


def transient_distributed(name: str = "distributed", *,
                          max_attempts: int = 3,
                          base_delay_s: float = 0.5,
                          log: Optional[Callable[[str], None]] = None,
                          **kw) -> RetryPolicy:
    """The shared shape for distributed-runtime transients: worker
    crashes from the Gloo loopback transport race, coordination-service
    connect failures, and the peer-outlives-the-crash timeout. Used by
    tools/multichip_bench.py rep pairs, the two_process_results
    fixture, and `maybe_initialize`."""
    return RetryPolicy(name, max_attempts=max_attempts,
                       base_delay_s=base_delay_s, max_delay_s=5.0,
                       retry_on=TRANSIENT_DISTRIBUTED_ERRORS, log=log,
                       **kw)
