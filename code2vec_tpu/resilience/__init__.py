"""code2vec_tpu.resilience — deterministic fault injection and unified
retry/backoff (ISSUE 10).

Two modules, one discipline:

  - `faults`: a seeded failpoint registry with named injection sites
    wired through the real seams (checkpoint write, infeed producer,
    train step, serving extractor, distributed init). Disabled — the
    default — every site costs one attribute/None check; nothing is
    allocated, no thread starts (the obs/ pattern). Armed via
    `--faults <json>` and driven by tools/chaos.py.
  - `retry`: ONE jittered-exponential-backoff policy with per-call
    attempt budgets and `resilience/retry` telemetry, replacing the
    hand-rolled retries that had accreted in tools/multichip_bench.py
    and the two_process_results fixture, and adopted by distributed
    init, the supervisor's cohort relaunch, extractor-pool restart and
    transient checkpoint-IO errors.

Stdlib-only at import time (jax is lazy and touched only on armed
paths); `tools/graftlint` fences this tree under NO_BASELINE_PREFIXES.
"""

from code2vec_tpu.resilience.faults import (FaultInjected,  # noqa: F401
                                            FaultPoint)
from code2vec_tpu.resilience.retry import RetryPolicy  # noqa: F401
