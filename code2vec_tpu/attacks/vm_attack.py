"""Gradient rename attack on the VarMisuse head.

Reference parity target: "Adversarial Examples for Models of Code"
(Yefet, Alon & Yahav 2020 — the `noamyft/code2vec` fork delta,
SURVEY.md §0 item 2) attacks BOTH of its subject models: code2vec's
name prediction (attacks/gradient_attack.py) and a VarMisuse
localization model — renaming one variable makes the pointer miss a
real bug or flag correct code. This module is that attack against this
framework's VarMisuse head (models/varmisuse.py).

Tensor semantics: a VM row is (src, pth, dst, mask, cand_ids [K],
cand_mask [K]); "renaming candidate k's variable" replaces its token id
at every context occurrence AND at cand_ids[k] — the pointer embeds
candidates with the same token table, so the rename moves both the
syntactic environment and the candidate's own embedding. The search is
the same TPU-first recipe as the code2vec attack: one backward pass for
the loss gradient at a shared occurrence embedding (spare-row remap,
exact for this head), one [V,E] @ [E] matvec scoring every vocab token,
exact re-scoring of the top-K shortlist in one batched forward.
Success: the predicted candidate SLOT differs from the clean prediction
(untargeted) or equals an attacker-chosen slot (targeted).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu.attacks.gradient_attack import (attack_succeeded,
                                                  build_shortlist,
                                                  candidate_mask,
                                                  guard_leaked,
                                                  spare_row)
from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.models.varmisuse import vm_scores
from code2vec_tpu.vocab.vocabularies import Vocab


@dataclasses.dataclass
class VMAttackResult:
    success: bool
    targeted: bool
    original_slot: int            # clean predicted candidate slot
    final_slot: int
    target_slot: Optional[int]
    renames: List[Tuple[str, str]]  # per-variable (orig, final) tokens
    iterations: int

    def __str__(self) -> str:
        kind = "targeted" if self.targeted else "untargeted"
        status = "SUCCESS" if self.success else "failed"
        rename = (", ".join(f"{a} -> {b}" for a, b in self.renames)
                  if self.renames else "(no rename)")
        line = (f"[vm {kind} {status}] rename {rename}: predicted slot "
                f"{self.original_slot} -> {self.final_slot}")
        if self.targeted:
            line += f" (target slot {self.target_slot})"
        return line


def make_vm_attack_steps(dims: ModelDims, *, compute_dtype=jnp.float32):
    """(score_fn, eval_fn, predict_fn) for one VM row.

    `ids` = (src [C], pth [C], dst [C], mask [C], cand [K], cmask [K]);
    `occ` = (occ_src [C], occ_dst [C], occ_cand [K]) bool slots of the
    attacked variable; `label` is a candidate SLOT index."""

    def _slot_ce(params, src, pth, dst, mask, cand, cmask, label):
        scores, _ = vm_scores(params, src[None], pth[None], dst[None],
                              mask[None], cand[None], cmask[None],
                              compute_dtype=compute_dtype)
        logp = jax.nn.log_softmax(scores, axis=-1)
        return -logp[0, label]

    @jax.jit
    def score_fn(params, ids, occ, spare, label, sign):
        src, pth, dst, mask, cand, cmask = ids
        occ_src, occ_dst, occ_cand = occ
        table = params["token_emb"]
        src2 = jnp.where(occ_src, spare, src)
        dst2 = jnp.where(occ_dst, spare, dst)
        cand2 = jnp.where(occ_cand, spare, cand)
        cur_id = jnp.max(jnp.where(occ_cand, cand, -1))
        e_var = table[cur_id].astype(jnp.float32)

        def loss_of(e):
            t2 = table.at[spare].set(e.astype(table.dtype))
            p2 = dict(params, token_emb=t2)
            return sign * _slot_ce(p2, src2, pth, dst2, mask, cand2,
                                   cmask, label)

        g = jax.grad(loss_of)(e_var)
        return (table.astype(jnp.float32) @ g) - (e_var @ g)

    @jax.jit
    def eval_fn(params, ids, occ, cand_tok, label):
        src, pth, dst, mask, cand, cmask = ids
        occ_src, occ_dst, occ_cand = occ
        Kc = cand_tok.shape[0]
        srcK = jnp.where(occ_src[None, :], cand_tok[:, None],
                         src[None, :])
        dstK = jnp.where(occ_dst[None, :], cand_tok[:, None],
                         dst[None, :])
        candK = jnp.where(occ_cand[None, :], cand_tok[:, None],
                          cand[None, :])
        pthK = jnp.broadcast_to(pth[None, :], (Kc, pth.shape[0]))
        maskK = jnp.broadcast_to(mask[None, :], (Kc, mask.shape[0]))
        cmaskK = jnp.broadcast_to(cmask[None, :], (Kc, cmask.shape[0]))
        scores, _ = vm_scores(params, srcK, pthK, dstK, maskK, candK,
                              cmaskK, compute_dtype=compute_dtype)
        logp = jax.nn.log_softmax(scores, axis=-1)
        labels = jnp.full((Kc,), label, dtype=jnp.int32)
        ce = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        pred = jnp.argmax(scores, axis=-1)
        return ce, pred

    @jax.jit
    def predict_fn(params, ids):
        src, pth, dst, mask, cand, cmask = ids
        scores, _ = vm_scores(params, src[None], pth[None], dst[None],
                              mask[None], cand[None], cmask[None],
                              compute_dtype=compute_dtype)
        return jnp.argmax(scores[0])

    return score_fn, eval_fn, predict_fn


class VMGradientRenameAttack:
    """Host loop — the code2vec attack's structure over VM rows: greedy
    over candidate variables, iterative gradient-shortlist + exact
    re-score per variable."""

    def __init__(self, dims: ModelDims, token_vocab: Vocab, *,
                 top_k_candidates: int = 32, max_iters: int = 4,
                 compute_dtype=jnp.float32):
        self.dims = dims
        self.token_vocab = token_vocab
        self.top_k = min(top_k_candidates,
                         dims.padded(dims.token_vocab_size))
        self.max_iters = max_iters
        self.score_fn, self.eval_fn, self.predict_fn = \
            make_vm_attack_steps(dims, compute_dtype=compute_dtype)
        self.legal = candidate_mask(token_vocab,
                                    dims.padded(dims.token_vocab_size))

    def attackable_slots(self, cand: np.ndarray, cmask: np.ndarray
                         ) -> List[int]:
        """Candidate slots whose token is a legal rename target (the
        sweep filters rows with none — protocol parity with the
        code2vec sweep's attackable_tokens check)."""
        return [k for k in range(len(cand))
                if cmask[k] > 0 and int(cand[k]) < len(self.legal)
                and self.legal[int(cand[k])]]

    def attack_method(self, params, row, *, targeted: bool = False,
                      target_slot: Optional[int] = None,
                      max_renames: int = 1,
                      forbidden: frozenset = frozenset()
                      ) -> VMAttackResult:
        """`row` = (src, pth, dst, mask, cand_ids, cand_mask) for ONE
        VM example (numpy). Greedily renames up to `max_renames`
        candidate variables (most context occurrences first);
        `forbidden` token ids are never chosen as new names."""
        src, pth, dst, mask, cand, cmask = (np.asarray(a) for a in row)
        ids0 = tuple(jnp.asarray(a)
                     for a in (src, pth, dst, mask, cand, cmask))
        original = int(self.predict_fn(params, ids0))
        if targeted:
            if target_slot is None:
                raise ValueError("targeted VM attack needs a slot")
            if not 0 <= int(target_slot) < len(cmask) \
                    or cmask[int(target_slot)] == 0:
                raise ValueError(
                    f"target slot {target_slot} is not a live candidate "
                    f"(K={len(cmask)}, "
                    f"{int((cmask > 0).sum())} valid slots)")
            label, sign = int(target_slot), 1.0
        else:
            label, sign = original, -1.0

        # attackable slots, ordered by context-occurrence count
        slots = sorted(
            ((int((src == int(cand[k])).sum()
                  + (dst == int(cand[k])).sum()), k)
             for k in self.attackable_slots(cand, cmask)),
            reverse=True)

        cur = (src.copy(), pth, dst.copy(), mask, cand.copy(), cmask)
        renames: List[Tuple[int, int]] = []
        iters = 0
        success = False
        for _, k in slots[:max_renames]:
            ok, final_id, changed, used = self._attack_slot(
                params, cur, k, label, sign, targeted, original,
                forbidden)
            iters += used
            if changed:
                renames.append((int(cand[k]), final_id))
            if ok:
                success = True
                break

        idsF = tuple(jnp.asarray(a) for a in cur)
        final = int(self.predict_fn(params, idsF))
        look = self.token_vocab.lookup_word
        return VMAttackResult(
            success=success, targeted=targeted, original_slot=original,
            final_slot=final, target_slot=target_slot,
            renames=[(look(a), look(b)) for a, b in renames],
            iterations=iters)

    def _attack_slot(self, params, cur, k: int, label: int, sign: float,
                     targeted: bool, original: int,
                     forbidden: frozenset
                     ) -> Tuple[bool, int, bool, int]:
        """Iteratively rename candidate slot k's variable IN PLACE in
        `cur`. Returns (success, final_token_id, changed, iters)."""
        src, pth, dst, mask, cand, cmask = cur
        token_id = int(cand[k])
        occ_src, occ_dst = src == token_id, dst == token_id
        occ_cand = cand == token_id
        occ = tuple(jnp.asarray(a) for a in (occ_src, occ_dst, occ_cand))
        spare = spare_row(self.dims.padded(self.dims.token_vocab_size),
                          src, dst, cand)
        tried = ({token_id} | set(forbidden)
                 | set(np.unique(np.concatenate(
                     [src.ravel(), dst.ravel(), cand.ravel()])).tolist()))
        cur_id = token_id
        changed = False
        for it in range(1, self.max_iters + 1):
            ids = tuple(jnp.asarray(a)
                        for a in (src, pth, dst, mask, cand, cmask))
            scores = np.array(self.score_fn(
                params, ids, occ, jnp.int32(spare), jnp.int32(label),
                sign))
            shortlist = build_shortlist(scores, self.legal, tried,
                                        self.top_k, cur_id)
            ce, pred = self.eval_fn(params, ids, occ,
                                    jnp.asarray(shortlist),
                                    jnp.int32(label))
            att = guard_leaked(sign * np.asarray(ce), scores, shortlist)
            pred = np.asarray(pred)
            best = int(np.argmin(att[:-1]))
            tried.update(int(c) for c in shortlist)
            if att[best] >= float(att[-1]):
                return (attack_succeeded(targeted, int(pred[-1]), label,
                                         original), cur_id, changed, it)
            new_id = int(shortlist[best])
            for arr, o in ((src, occ_src), (dst, occ_dst),
                           (cand, occ_cand)):
                arr[o] = new_id
            cur_id = new_id
            changed = True
            if attack_succeeded(targeted, int(pred[best]), label,
                                original):
                return True, cur_id, True, it
        return False, cur_id, changed, self.max_iters
