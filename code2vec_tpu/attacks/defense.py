"""Adversarial-training defense: random variable-rename augmentation.

Reference parity target: the defense evaluated in "Adversarial Examples
for Models of Code" (Yefet, Alon & Yahav 2020 — the `noamyft/code2vec`
fork delta, SURVEY.md §0 item 2): training on rename-perturbed programs
makes the model invariant to the attack's manipulation surface. The
paper's strongest defense retrains on adversarially-perturbed examples;
the shipped, cheap approximation is its randomized form — each training
example, with probability p (`--adv_rename_prob`), has one of its
variables renamed to a random legal token, occurrences replaced
consistently. This is the same manipulation the attack performs, minus
the gradient guidance, and runs entirely inside the jitted train step
(per batch: one categorical slot draw, one uniform replacement draw,
one bernoulli gate, then masked `where`s — no host work, no extractor
in the loop).

Measured effect: tools/robustness_study.py trains matched
baseline/defended models and attacks both; results in BASELINE.md.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu.attacks.gradient_attack import candidate_mask
from code2vec_tpu.models.encoder import ModelDims
from code2vec_tpu.vocab.vocabularies import Vocab


def legal_token_mask(token_vocab: Vocab, dims: ModelDims) -> np.ndarray:
    """bool [padded_rows] — True where a vocab row is usable as a random
    replacement name (real, identifier-renderable tokens — same pool the
    attack draws from)."""
    mask = candidate_mask(token_vocab, dims.padded(dims.token_vocab_size))
    if not mask.any():
        raise ValueError("no legal rename tokens in the vocabulary")
    return mask


def make_rename_augment(legal: np.ndarray, prob: float,
                        mode: str = "uniform") -> Callable:
    """Returns jit-safe `augment(batch, rng) -> batch`.

    Per example: pick one valid context slot whose source token is a
    LEGAL identifier token (same candidate_mask pool the attack uses —
    never OOV/PAD/literal tokens, whose occurrences span many distinct
    source identifiers and would over-perturb), then with probability
    `prob` replace ALL occurrences of that token in the example's
    src/dst slots with a replacement token. Collisions with tokens the
    example already uses are allowed — augmentation is noise injection,
    not a validity-checked attack. Examples with no legal slot are left
    unchanged. `legal` is the bool [padded_rows] mask from
    legal_token_mask.

    `mode` selects the replacement distribution:

    - "uniform": one uniformly-drawn legal token (the round-3 defense).
      Matches the attack's manipulation SURFACE but not its choice: on
      a 150K vocab the draw almost never lands on a token that argues
      for a different class, so the model never trains against
      conflicting evidence.
    - "batch": the token another example in the batch selected (a
      batch-index roll) — typically a DIFFERENT class's name-bearing
      identifier. This simulates what the gradient attack actually
      does (inject a wrong-class cue) and is what teaches the model to
      weigh cues against each other instead of trusting any single one
      (round-4 defense positive control; BASELINE.md).
    """
    assert mode in ("uniform", "batch"), mode
    legal_mask = jnp.asarray(legal)
    legal = jnp.asarray(np.nonzero(legal)[0].astype(np.int32))

    def augment(batch, rng):
        labels, src, pth, dst, mask, weights = batch
        B = src.shape[0]
        r_slot, r_new, r_apply = jax.random.split(rng, 3)
        # one valid, legal-token slot per example, drawn over BOTH
        # context sides — a variable can survive only in dst slots
        # after downsampling, and the attack renames either side, so
        # the defense must too (all-padding rows have weight 0 —
        # whatever categorical returns there is never counted)
        all_tok = jnp.concatenate([src, dst], axis=1)       # [B, 2C]
        all_mask = jnp.concatenate([mask, mask], axis=1)
        eligible = (all_mask > 0) & legal_mask[all_tok]
        slot_logits = jnp.where(eligible, 0.0, -1e9)
        j = jax.random.categorical(r_slot, slot_logits, axis=-1)
        tok = jnp.take_along_axis(all_tok, j[:, None], axis=1)[:, 0]
        if mode == "batch" and B > 1:
            # another example's selected variable = usually a
            # wrong-class cue; roll avoids i->i (shift in [1, B-1]).
            # Rows whose donor token is illegal (donor had no legal
            # slot) fall back to a uniform legal draw via `where`.
            # B==1 (static shape) has no donor — roll over a length-1
            # axis is the identity, i.e. a silent self-rename no-op —
            # so it takes the uniform branch instead (ADVICE r4).
            shift = jax.random.randint(r_new, (), 1, B)
            donor = jnp.roll(tok, shift)
            fallback = legal[jax.random.randint(
                jax.random.fold_in(r_new, 1), (B,), 0, legal.shape[0])]
            new = jnp.where(legal_mask[donor], donor, fallback)
        else:
            new = legal[jax.random.randint(r_new, (B,), 0,
                                           legal.shape[0])]
        keep = (jax.random.bernoulli(r_apply, prob, (B,))
                & legal_mask[tok])  # no-legal-slot rows stay unchanged
        # a non-id sentinel disables the rename where keep is False
        tok_eff = jnp.where(keep, tok, -1)[:, None]
        src2 = jnp.where(src == tok_eff, new[:, None], src)
        dst2 = jnp.where(dst == tok_eff, new[:, None], dst)
        return labels, src2, pth, dst2, mask, weights

    return augment
