"""Adversarial-input detection via attention-weighted token rarity.

Reference parity target: the detection defense of "Adversarial Examples
for Models of Code" (Yefet, Alon & Yahav 2020 — the `noamyft/code2vec`
fork delta, SURVEY.md §0 item 2): adversarially-chosen names are
*outliers* — the gradient search draws them from the whole vocabulary,
so they are overwhelmingly rare in training data, while the attack
works precisely by making the model ATTEND to them. Both signals are
already in the predict path, so detection is nearly free:

    score(method) = sum_j  attn_j * rarity_j
    rarity_j      = max(-log p(src_j), -log p(dst_j))   (add-one
                    smoothed over the training token histogram; OOV is
                    maximally rare)

A clean method concentrates attention on common, task-bearing tokens →
low score; an attacked one pays attention to a rare renamed token →
high score. Calibrate the threshold on clean data at a chosen false-
positive rate. Measured detection quality (AUC, TPR@5%FPR) comes from
tools/robustness_study.py --detect; results in BASELINE.md.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu.models.encoder import ModelDims, get_encode_fn
from code2vec_tpu.vocab.vocabularies import Vocab, read_token_counts


def load_token_counts(dict_path: str) -> Dict[str, int]:
    """Token histogram from the dataset's `.dict.c2v` (the pickle
    layout is owned by vocabularies.py; only the token dict is
    deserialized — the ~1M-entry path/target dicts are skipped)."""
    return read_token_counts(dict_path)


class RarityDetector:
    @classmethod
    def from_model(cls, model, dict_path: str) -> "RarityDetector":
        """Build for a loaded Code2VecModel from its dataset's
        `.dict.c2v` (the one construction every caller needs)."""
        return cls(model.dims, model.vocabs.token_vocab,
                   load_token_counts(dict_path),
                   compute_dtype=model.compute_dtype)

    def __init__(self, dims: ModelDims, token_vocab: Vocab,
                 token_counts: Dict[str, int], *,
                 compute_dtype=jnp.float32):
        rows = dims.padded(dims.token_vocab_size)
        total = sum(token_counts.values()) + rows  # add-one smoothing
        rarity = np.full((rows,), -np.log(1.0 / total), np.float32)
        counts = np.zeros((rows,), np.int64)
        for idx, word in enumerate(token_vocab.to_word_list()):
            c = token_counts.get(word, 0)
            rarity[idx] = -np.log((c + 1.0) / total)
            counts[idx] = c
        rarity[token_vocab.pad_index] = 0.0  # masked out anyway
        self.rarity = rarity
        # per-row train counts, kept for the replacement-frequency
        # mechanism report (evaluate_robustness: is the attack choosing
        # rare-but-strong or common-but-weak replacements?)
        self.counts = counts
        self.token_vocab = token_vocab
        encode = get_encode_fn(dims)

        @jax.jit
        def attn_fn(params, src, pth, dst, mask):
            # batched [M, C]: one dispatch scores a whole sweep chunk
            _, attn = encode(params, src, pth, dst, mask,
                             compute_dtype=compute_dtype)
            return attn

        self._attn_fn = attn_fn

    _CHUNK = 64  # fixed batch shape: one jit compile, any M

    def score_batch(self, params, methods) -> np.ndarray:
        """Attention-weighted rarity of M tensorized methods, [M].
        Internally padded to fixed-size chunks so the jitted attention
        pass compiles once regardless of M (single-method calls get a
        batch-1 shape — the serving path must not pay 64x encode work
        per prediction)."""
        chunk = 1 if len(methods) == 1 else self._CHUNK
        out = []
        for lo in range(0, len(methods), chunk):
            part = list(methods[lo:lo + chunk])
            pad = chunk - len(part)
            part += [part[-1]] * pad
            src = np.stack([np.asarray(m[0]) for m in part])
            pth = np.stack([np.asarray(m[1]) for m in part])
            dst = np.stack([np.asarray(m[2]) for m in part])
            mask = np.stack([np.asarray(m[3]) for m in part])
            attn = np.asarray(self._attn_fn(
                params, jnp.asarray(src), jnp.asarray(pth),
                jnp.asarray(dst), jnp.asarray(mask)))
            rar = np.maximum(self.rarity[src], self.rarity[dst])
            scores = np.sum(attn * rar * (mask > 0), axis=1)
            out.extend(scores[:chunk - pad])
        return np.asarray(out)

    def score(self, params, method: Tuple[np.ndarray, np.ndarray,
                                          np.ndarray, np.ndarray]
              ) -> float:
        """Attention-weighted rarity of one tensorized method."""
        return float(self.score_batch(params, [method])[0])

    @staticmethod
    def calibrate(clean_scores: np.ndarray, fpr: float = 0.05) -> float:
        """Threshold flagging the top `fpr` fraction of CLEAN scores."""
        return float(np.quantile(np.asarray(clean_scores), 1.0 - fpr))


def auc(clean_scores: np.ndarray, attack_scores: np.ndarray) -> float:
    """Rank AUC (tie-corrected Mann-Whitney): P(attack > clean).
    O(n log n) via average ranks — no pairwise matrix."""
    c = np.asarray(clean_scores, np.float64)
    a = np.asarray(attack_scores, np.float64)
    if len(c) == 0 or len(a) == 0:
        return float("nan")
    scores = np.concatenate([c, a])
    _, inv, cnt = np.unique(scores, return_inverse=True,
                            return_counts=True)
    avg_rank = np.cumsum(cnt) - (cnt - 1) / 2.0  # 1-based, tie-averaged
    ranks = avg_rank[inv]
    u = ranks[len(c):].sum() - len(a) * (len(a) + 1) / 2.0
    return float(u / (len(a) * len(c)))
