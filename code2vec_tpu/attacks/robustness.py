"""Adversarial-robustness evaluation: untargeted rename attacks over a
test split, reported as model robustness metrics.

Reference parity target: the evaluation protocol of "Adversarial
Examples for Models of Code" (Yefet, Alon & Yahav 2020 — the
`noamyft/code2vec` fork delta, SURVEY.md §0 item 2): attack every method
in a held-out set with the untargeted one-variable rename attack and
report the attack success rate (= 1 - model robustness). Runs against
any checkpoint of this framework.

CLI (module-style, like data/preprocess and data/binarize):

  python -m code2vec_tpu.attacks.robustness \
      --load <ckpt> --test <file.c2v> [--n 200] [--max_renames 1] \
      [--iters 4] [--topk 32] [--out robustness.json]

Prints one JSON line: attack success rate, mean iterations/renames on
successes, and the clean-vs-attacked top-1-vs-ground-truth breakdown.
"""

from __future__ import annotations

import json
import time
from typing import Optional

import numpy as np

from code2vec_tpu.attacks.gradient_attack import GradientRenameAttack
from code2vec_tpu.data.reader import parse_c2v_rows


def _freq_stats(words, counts, token_vocab) -> dict:
    """Training-frequency stats of `words` under the detector vocab's
    per-row `counts`. Words the vocab maps to its OOV index would
    silently contribute the OOV row's train count (typically 0) —
    skewing frac_singleton and the rank percentile upward — so they
    are EXCLUDED and reported as n_oov_excluded instead (ADVICE r5
    finding 3)."""
    oov = token_vocab.oov_index
    idxs = [token_vocab.lookup_index(w) for w in words]
    kept = [i for i in idxs if i != oov]
    n_excluded = len(idxs) - len(kept)
    c = np.asarray([int(counts[i]) for i in kept], np.int64)
    if not len(c):
        return {"n": 0, "n_oov_excluded": n_excluded}
    counts = np.asarray(counts)
    nz = np.sort(counts[counts > 0])
    # fraction of in-vocab tokens strictly more common than each chosen
    # token: 0.0 = the most common token, ~1.0 = a deep-tail singleton
    rank_pct = 1.0 - np.searchsorted(nz, c, side="right") / len(nz)
    return {
        "n": len(c),
        "n_oov_excluded": n_excluded,
        "median_train_count": float(np.median(c)),
        "p90_train_count": float(np.quantile(c, 0.9)),
        "frac_singleton": round(float(np.mean(c <= 2)), 4),
        "median_rank_pct": round(float(np.median(rank_pct)), 4),
    }


def evaluate_robustness(model, test_path: str, *, n_methods: int = 200,
                        max_renames: int = 1, max_iters: int = 4,
                        top_k_candidates: int = 32,
                        detector=None, log=print) -> dict:
    """Attacks up to `n_methods` methods of `test_path` (untargeted,
    greedy rename of up to `max_renames` variables) and aggregates.

    With a `detector` (attacks/detect.py RarityDetector), also scores
    every clean method and every successful adversarial variant and
    reports detection AUC + TPR at a 5% FPR threshold (threshold
    calibrated on this sweep's own clean scores)."""
    attack = GradientRenameAttack(
        model.dims, model.vocabs.token_vocab, model.vocabs.target_vocab,
        top_k_candidates=top_k_candidates, max_iters=max_iters,
        compute_dtype=model.compute_dtype)
    tv = model.vocabs.target_vocab

    import itertools
    with open(test_path, encoding="utf-8") as f:
        # islice: production splits are GBs; read only what is attacked
        lines = list(itertools.islice(
            (ln for ln in f if ln.strip()), n_methods))
    labels, src, pth, dst, mask, tstr, _ = parse_c2v_rows(
        lines, model.vocabs, model.dims.max_contexts, keep_strings=True)

    eligible = [i for i in range(len(lines))
                if mask[i].sum() > 0
                and attack.attackable_tokens(src[i], dst[i], mask[i])]
    t0 = time.time()

    def attacked():
        """Yields (row_index, AttackResult). Single-rename sweeps run
        the lockstep batch path — each jit dispatch covers a whole
        chunk, which is what makes large sweeps fast on dispatch-bound
        platforms; multi-rename falls back to the serial driver."""
        if max_renames == 1:
            chunk = 64
            for lo in range(0, len(eligible), chunk):
                idxs = eligible[lo:lo + chunk]
                # pad a short tail chunk to the fixed size (repeat the
                # last method, drop its results): one compiled shape,
                # no retrace for the final partial batch
                padded = idxs + [idxs[-1]] * (chunk - len(idxs))
                methods = [(src[i], pth[i], dst[i], mask[i])
                           for i in padded]
                results = attack.attack_batch(model.params, methods)
                yield from zip(idxs, results[:len(idxs)])
        else:
            for i in eligible:
                yield i, attack.attack_method(
                    model.params, (src[i], pth[i], dst[i], mask[i]),
                    targeted=False, max_renames=max_renames)

    n = flipped = clean_correct = attacked_correct = 0
    iters_on_success, renames_on_success = [], []
    clean_methods, adv_methods = [], []
    replacement_words, original_words = [], []
    for i, res in attacked():
        if detector is not None:
            clean_methods.append((src[i], pth[i], dst[i], mask[i]))
            if res.success:
                adv_methods.append(res.final_method)
                for frm, to in res.renames:
                    original_words.append(frm)
                    replacement_words.append(to)
        n += 1
        truth = tv.lookup_word(int(labels[i])) if not tstr else tstr[i]
        clean_correct += res.original_prediction == truth
        attacked_correct += res.final_prediction == truth
        if res.success:
            flipped += 1
            iters_on_success.append(res.iterations)
            renames_on_success.append(len(res.renames))
        if n % 32 == 0:
            log(f"robustness: {n} methods, "
                f"{flipped / n:.3f} attack success rate so far")
    dt = time.time() - t0
    report = {
        "metric": "untargeted_rename_attack_success_rate",
        "n_methods": n,
        "attack_success_rate": round(flipped / max(n, 1), 4),
        "robustness": round(1.0 - flipped / max(n, 1), 4),
        "clean_top1_acc": round(clean_correct / max(n, 1), 4),
        "attacked_top1_acc": round(attacked_correct / max(n, 1), 4),
        "mean_iterations_on_success": round(
            float(np.mean(iters_on_success)), 2) if iters_on_success
        else None,
        "mean_renames_on_success": round(
            float(np.mean(renames_on_success)), 2) if renames_on_success
        else None,
        "max_renames": max_renames,
        "max_iters": max_iters,
        "top_k_candidates": top_k_candidates,
        "seconds": round(dt, 1),
    }
    if detector is not None and adv_methods:
        from code2vec_tpu.attacks.detect import auc
        clean_scores = detector.score_batch(model.params, clean_methods)
        attack_scores = detector.score_batch(model.params, adv_methods)
        thr = detector.calibrate(clean_scores, fpr=0.05)
        report["detection_auc"] = round(auc(clean_scores,
                                            attack_scores), 4)
        report["detection_tpr_at_5fpr"] = round(
            float(np.mean(attack_scores > thr)), 4)
        report["detection_threshold"] = round(thr, 3)
        # Replacement-frequency mechanism report (VERDICT r4 item 1):
        # the paper's detector presupposes the attack is forced into
        # RARE replacement names. Measure which regime this sweep is
        # actually in by looking up every successful rename's
        # replacement (and, as the baseline, the original attacked
        # token) in the training histogram — indexed through the
        # DETECTOR's vocab (detector.counts is aligned to it), with
        # OOV-mapped words excluded rather than miscounted.
        report["replacement_token_freq"] = _freq_stats(
            replacement_words, detector.counts, detector.token_vocab)
        report["original_token_freq"] = _freq_stats(
            original_words, detector.counts, detector.token_vocab)
    return report


def main(argv: Optional[list] = None) -> int:
    import argparse

    from code2vec_tpu.config import Config
    from code2vec_tpu.models.jax_model import Code2VecModel

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--load", required=True, help="checkpoint directory")
    p.add_argument("--test", required=True, help=".c2v file to attack")
    p.add_argument("--n", type=int, default=200)
    p.add_argument("--max_renames", type=int, default=1)
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--topk", type=int, default=32)
    p.add_argument("--out", default=None, help="also write JSON here")
    p.add_argument("--dict", dest="dict_path", default=None,
                   help="dataset .dict.c2v — enables rarity-outlier "
                        "detection metrics (attacks/detect.py)")
    a = p.parse_args(argv)

    cfg = Config()
    cfg.load_path = a.load
    model = Code2VecModel(cfg)
    detector = None
    if a.dict_path:
        from code2vec_tpu.attacks.detect import RarityDetector
        detector = RarityDetector.from_model(model, a.dict_path)
    report = evaluate_robustness(
        model, a.test, n_methods=a.n, max_renames=a.max_renames,
        max_iters=a.iters, top_k_candidates=a.topk, detector=detector,
        log=cfg.log)
    line = json.dumps(report)
    print(line)
    if a.out:
        with open(a.out, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
