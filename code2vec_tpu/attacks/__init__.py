"""Adversarial attacks on path-context models (the `noamyft/code2vec`
fork delta — SURVEY.md §0 item 2; "Adversarial Examples for Models of
Code", Yefet, Alon & Yahav 2020).

- gradient_attack: tensor-space gradient-guided variable renaming
  (targeted + untargeted), HotFlip-style vocab-wide candidate scoring
  on the MXU with exact batched re-scoring.
- source_attack: source-level driver (rename / dead-code insertion in
  real Java or Python source) verified end-to-end via re-extraction.
- robustness: untargeted attack sweep over a test split -> robustness
  metrics (module CLI).
- defense: randomized rename augmentation (--adv_rename_prob).
- vm_attack: the same attack against the VarMisuse head (the paper's
  second target model).
"""

from code2vec_tpu.attacks.gradient_attack import (AttackResult,
                                                  GradientRenameAttack,
                                                  candidate_mask,
                                                  render_identifier)
from code2vec_tpu.attacks.robustness import evaluate_robustness
from code2vec_tpu.attacks.source_attack import (SourceAttack,
                                                SourceAttackResult)
from code2vec_tpu.attacks.vm_attack import (VMAttackResult,
                                            VMGradientRenameAttack)
from code2vec_tpu.attacks.vm_robustness import evaluate_vm_robustness

__all__ = ["AttackResult", "GradientRenameAttack", "candidate_mask",
           "render_identifier", "SourceAttack", "SourceAttackResult",
           "evaluate_robustness", "VMAttackResult",
           "VMGradientRenameAttack", "evaluate_vm_robustness"]
